"""CockroachDB test suite — the reference's richest suite (2,441 LoC
across `cockroachdb/src/jepsen/cockroach/{runner,nemesis,client,auto,
bank,register,comments,monotonic,sequential,sets,adya}.clj`), providing:

  * auto          — cluster automation: tarball install, start/kill/
                    wipe, clock reset (auto.clj)
  * SQL client    — a thin connection boundary with the reference's
                    transaction-retry semantics (client.clj
                    with-txn-retry: retry on serialization-conflict
                    "restart transaction" errors); the connection
                    factory is injectable so the whole suite runs
                    in-process against an in-memory SQL engine
  * nemesis menu  — named nemesis maps {name during final client
                    clocks} and their composition (nemesis.clj:62-107
                    compose), with the full skew ladder: subcritical
                    200 ms, critical 250 ms, big 500 ms, huge 5 s,
                    strobe (nemesis.clj:252-266), plus parts/majring/
                    startstop/startkill/split and the slowing/
                    restarting wrappers (nemesis.clj:153-200)
  * workloads     — bank, bank-multitable, register, comments,
                    monotonic, sequential, sets, g2 — the registry of
                    runner.clj:25-34
  * runner        — CLI with test/nemesis registries and --nemesis2
                    mixing (runner.clj:42-56,70-76)
"""

from __future__ import annotations

import itertools
import random
import threading
import time
from typing import Callable

from jepsen_tpu import checker as ck
from jepsen_tpu import cli
from jepsen_tpu import client as client_mod
from jepsen_tpu import control as c
from jepsen_tpu import control_util as cu
from jepsen_tpu import db as db_mod
from jepsen_tpu import generator as gen
from jepsen_tpu import independent, models, nemesis as nem, net
from jepsen_tpu import nemesis_time as nt
from jepsen_tpu.checker import timeline
from jepsen_tpu.control import lit
from jepsen_tpu.history import History
from jepsen_tpu import txn as mop_txn
from jepsen_tpu.workloads import adya as adya_wl
from jepsen_tpu.workloads import causal as causal_wl
from jepsen_tpu.workloads import predicate as predicate_wl
from jepsen_tpu.workloads import session as session_wl
from jepsen_tpu.workloads import list_append as list_append_wl
from jepsen_tpu.workloads import rw_register as rw_register_wl
from jepsen_tpu.workloads import bank as bank_wl
from jepsen_tpu.workloads import linearizable_register as linreg_wl
from jepsen_tpu.workloads import monotonic as monotonic_wl
from jepsen_tpu.workloads import sequential as sequential_wl
from jepsen_tpu.workloads import sets as sets_wl

# ---------------------------------------------------------------------------
# auto — cluster automation (auto.clj)
# ---------------------------------------------------------------------------

VERSION = "23.1.11"
URL = (f"https://binaries.cockroachdb.com/"
       f"cockroach-v{VERSION}.linux-amd64.tgz")
DIR = "/opt/cockroach"
STORE = f"{DIR}/data"
LOGFILE = f"{DIR}/cockroach.log"
PIDFILE = f"{DIR}/cockroach.pid"
PORT = 26257
HTTP_PORT = 8080
BIN = f"{DIR}/cockroach"

nemesis_delay = 5       # seconds between interruptions (nemesis.clj:20)
nemesis_duration = 5    # seconds of an interruption (nemesis.clj:23)


def install(test, node) -> None:
    """Fetch + unpack the release tarball (auto.clj install!)."""
    cu.install_archive(URL, DIR)


def start(test, node) -> None:
    """Start the server daemon joined to every node (auto.clj start!)."""
    join = ",".join(f"{n}:{PORT}" for n in test.get("nodes") or [])
    cu.start_daemon(
        BIN, "start", "--insecure",
        "--store", STORE,
        "--listen-addr", f"{node}:{PORT}",
        "--http-addr", f"{node}:{HTTP_PORT}",
        "--join", join,
        "--background",
        chdir=DIR, logfile=LOGFILE, pidfile=PIDFILE)


def kill(test, node) -> None:
    """SIGKILL the server (auto.clj kill!)."""
    cu.grepkill("cockroach")


def wipe(test, node) -> None:
    c.execute("rm", "-rf", STORE, check=False)


def reset_clocks(test) -> None:
    """auto.clj reset-clocks! — fan a clock reset to every node."""
    c.on_nodes(test, lambda t, n: nt.reset_time())


class CockroachDB(db_mod.DB, db_mod.LogFiles):
    """DB lifecycle (auto.clj + cockroach.clj db)."""

    def setup(self, test, node):
        install(test, node)
        nt.install(test, node)
        start(test, node)
        c.execute(lit(
            "for i in $(seq 1 60); do "
            f"curl -sf http://{node}:{HTTP_PORT}/health "
            "&& exit 0; sleep 1; done; exit 1"), check=False)
        # One node initialises the cluster (auto.clj init!).
        if node == (test.get("nodes") or [node])[0]:
            c.execute(BIN, "init", "--insecure",
                      "--host", f"{node}:{PORT}", check=False)

    def teardown(self, test, node):
        kill(test, node)
        wipe(test, node)

    def log_files(self, test, node):
        return [LOGFILE]


# ---------------------------------------------------------------------------
# SQL client boundary (client.clj)
# ---------------------------------------------------------------------------

class Retryable(Exception):
    """A serialization conflict the client should retry — cockroach
    signals these with SQLSTATE 40001 / "restart transaction"
    (client.clj retryable?)."""


class Indeterminate(Exception):
    """The op may or may not have been applied (timeouts, node died
    mid-commit) — becomes an :info op."""


class Definite(Exception):
    """The op definitely did not happen — becomes a :fail op."""


class ShellConn:
    """Production connection: drives `cockroach sql` on the node over
    the control plane.  Tests inject an in-memory engine instead.

    The connection protocol the workload clients consume:
      sql(stmt, params) -> rows         one autocommitted statement
      txn([stmts])      -> rows         statements applied atomically
      atomically(body)  -> result       OPTIONAL interactive txn:
                                        body(run) issues statements via
                                        run(sql) inside one txn that
                                        rolls back on exception.
                                        One-shot conns (this one) omit
                                        it; clients fall back to
                                        single-statement SQL forms.
      ts_expr           (attr)          SQL expression for the DB's
                                        own txn timestamp
      close()
    """

    ts_expr = "cluster_logical_timestamp()::INT8"

    def __init__(self, node: str):
        self.node = node
        # Client invokes run on worker threads with no control session
        # bound; hold one open for this connection's lifetime.
        self._session = c.session(node)

    def _cmd(self, q: str) -> list:
        """The shell command executing one query — the subclass hook
        (yugabyte's ysqlsh conn overrides this and _parse)."""
        return [BIN, "sql", "--insecure",
                "--host", f"{self.node}:{PORT}",
                "--format", "tsv", "-e", q]

    def _parse(self, text: str) -> list:
        """Command output -> rows (first line is the TSV header)."""
        return [line.split("\t")
                for line in (text or "").splitlines()[1:] if line]

    def sql(self, stmt: str, params: tuple = ()) -> list:
        # Single-pass placeholder substitution: splitting first means a
        # '?' inside a parameter value can't be mistaken for a later
        # placeholder.
        parts = stmt.split("?")
        if len(parts) - 1 != len(params) and params:
            raise ValueError(
                f"{len(parts) - 1} placeholders, {len(params)} params")
        out = [parts[0]]
        for p, nxt in zip(params, parts[1:]):
            v = "NULL" if p is None else (
                str(p) if isinstance(p, (int, float))
                else "'" + str(p).replace("'", "''") + "'")
            out += [v, nxt]
        q = "".join(out) if params else stmt
        with c.with_session(self.node, self._session):
            text = c.execute(*self._cmd(q))
        return self._parse(text)

    def txn(self, stmts: list) -> list:
        """Run statements atomically; cockroach retries internally when
        possible, else surfaces a 40001 we map to Retryable."""
        try:
            return self.sql("BEGIN; " + "; ".join(stmts) + "; COMMIT")
        except c.RemoteError as e:  # pragma: no cover - needs cluster
            msg = str(e)
            if "40001" in msg or "restart transaction" in msg:
                raise Retryable(msg) from e
            raise

    def close(self):
        self._session.close()


txn_retry_delay = 0.001
txn_retry_max = 30.0


def with_txn_retry(f: Callable):
    """client.clj with-txn-retry — exponential backoff with jitter on
    serialization conflicts, bounded by txn_retry_max seconds."""
    deadline = time.monotonic() + txn_retry_max
    delay = txn_retry_delay
    while True:
        try:
            return f()
        except Retryable:
            if time.monotonic() > deadline:
                raise
            time.sleep(delay * (1 + random.random()))
            delay = min(delay * 2, 1.0)


def exception_to_op(op, e: Exception):
    """client.clj with-exception->op: map client exceptions onto the
    op-type taxonomy.  Only provably-not-applied failures may become
    :fail — a connection that dies mid-flight is indeterminate (the
    write may have committed server-side), so generic ConnectionError/
    OSError degrade to :info, matching the runner's default for unknown
    exceptions (core.clj:204-220)."""
    if isinstance(e, Indeterminate):
        return op.assoc(type="info", error=str(e))
    if isinstance(e, (Definite, Retryable)):
        return op.assoc(type="fail", error=str(e))
    if isinstance(e, ConnectionRefusedError):
        # refused: the request never reached the server
        return op.assoc(type="fail", error=str(e))
    if isinstance(e, (ConnectionError, OSError)):
        return op.assoc(type="info", error=str(e))
    raise e


_keyrange_lock = threading.Lock()


def update_keyrange(test, table: str, k) -> None:
    """Track the live key range per table so the split nemesis can aim
    (cockroach.clj update-keyrange!)."""
    with _keyrange_lock:
        kr = test.setdefault("keyrange", {})
        kr.setdefault(table, set()).add(k)


class SQLClient(client_mod.Client):
    """Base for every workload client: holds a connection built by the
    injectable factory (test["sql-factory"] or the constructor's),
    wraps invoke in the exception taxonomy."""

    def __init__(self, conn_factory=ShellConn):
        self.conn_factory = conn_factory
        self.conn = None
        self.node = None

    def open(self, test, node):
        out = type(self)(test.get("sql-factory") or self.conn_factory)
        out.node = node
        out.conn = out.conn_factory(node)
        return out

    def close(self, test):
        if self.conn is not None:
            self.conn.close()

    def invoke(self, test, op):
        try:
            return self._invoke(test, op)
        except Exception as e:           # noqa: BLE001 - taxonomy map
            return exception_to_op(op, e)

    def _invoke(self, test, op):  # pragma: no cover - abstract
        raise NotImplementedError


# ---------------------------------------------------------------------------
# Named nemesis maps + composition (nemesis.clj)
# ---------------------------------------------------------------------------

def nemesis_no_gen() -> dict:
    return {"during": gen.void, "final": gen.void}


def nemesis_single_gen() -> dict:
    """sleep delay / start / sleep duration / stop, forever
    (nemesis.clj:32-38)."""
    return {"during": gen.start_stop(nemesis_delay, nemesis_duration),
            "final": gen.once({"type": "info", "f": "stop"})}


def nemesis_double_gen() -> dict:
    """Interleaved start1/start2/stop1/stop2 ladder (nemesis.clj:40-60)."""
    half = nemesis_duration / 2

    def steps():
        while True:
            for s in ({"sleep": nemesis_delay},
                      {"type": "info", "f": "start1"}, {"sleep": half},
                      {"type": "info", "f": "start2"}, {"sleep": half},
                      {"type": "info", "f": "stop1"}, {"sleep": half},
                      {"type": "info", "f": "stop2"},
                      {"sleep": nemesis_delay},
                      {"type": "info", "f": "start2"}, {"sleep": half},
                      {"type": "info", "f": "start1"}, {"sleep": half},
                      {"type": "info", "f": "stop2"}, {"sleep": half},
                      {"type": "info", "f": "stop1"}):
                yield (gen.sleep(s["sleep"]) if "sleep" in s
                       else lambda t, p, _s=s: dict(_s))

    return {"during": gen.gseq(steps()),
            "final": gen.gseq([
                lambda t, p: {"type": "info", "f": "stop1"},
                lambda t, p: {"type": "info", "f": "stop2"}])}


# The named-map tagging/composition machinery moved to nemesis.py
# (named_nemesis / tag_f / compose_named) so non-suite nemeses — the
# disk-fault recipes in faultfs.py — can publish registry entries too;
# re-exported here because this suite is their reference home.
_tag_f = nem.tag_f
compose_named = nem.compose_named


def none() -> dict:
    """nemesis.clj none :111-115."""
    return dict(nemesis_no_gen(), name="blank", client=nem.Noop(),
                clocks=False)


def parts() -> dict:
    """Random-halves partition (nemesis.clj parts :119-124)."""
    return dict(nemesis_single_gen(), name="parts",
                client=nem.partition_random_halves(), clocks=False)


def majring() -> dict:
    """nemesis.clj majring :145-150."""
    return dict(nemesis_single_gen(), name="majring",
                client=nem.partition_majorities_ring(), clocks=False)


def _take_random(n: int):
    return lambda nodes: random.sample(list(nodes), min(n, len(nodes)))


def startstop(n: int = 1) -> dict:
    """SIGSTOP/SIGCONT n random servers (nemesis.clj startstop
    :127-133)."""
    return dict(nemesis_single_gen(),
                name="startstop" + (str(n) if n > 1 else ""),
                client=nem.hammer_time("cockroach",
                                       targeter=_take_random(n)),
                clocks=False)


def startkill(n: int = 1) -> dict:
    """Kill + restart n random servers (nemesis.clj startkill
    :135-142).  On the :start op the nemesis KILLS the targets; the
    :stop op restarts them — node_start_stopper's args are
    (targeter, fn-on-start, fn-on-stop)."""
    return dict(nemesis_single_gen(),
                name="startkill" + (str(n) if n > 1 else ""),
                client=nem.node_start_stopper(_take_random(n),
                                              kill, start),
                clocks=False)


class Slowing(nem.Nemesis):
    """Wrap a nemesis: slow the network before :start, restore after
    :stop (nemesis.clj slowing :153-175)."""

    def __init__(self, inner: nem.Nemesis, dt: float):
        self.inner = inner
        self.dt = dt

    def setup(self, test):
        net_ = test.get("net")
        if net_:
            net_.fast(test)
        self.inner = self.inner.setup(test) or self.inner
        return self

    def invoke(self, test, op):
        net_ = test.get("net")
        if op.f == "start":
            if net_:
                net_.slow(test, mean=self.dt * 1000, variance=1)
            return self.inner.invoke(test, op)
        if op.f == "stop":
            try:
                return self.inner.invoke(test, op)
            finally:
                if net_:
                    net_.fast(test)
        return self.inner.invoke(test, op)

    def teardown(self, test):
        net_ = test.get("net")
        if net_:
            net_.fast(test)
        self.inner.teardown(test)


class Restarting(nem.Nemesis):
    """Wrap a nemesis: after :stop completes, restart servers on every
    node (nemesis.clj restarting :178-200)."""

    def __init__(self, inner: nem.Nemesis):
        self.inner = inner

    def setup(self, test):
        self.inner = self.inner.setup(test) or self.inner
        return self

    def invoke(self, test, op):
        out = self.inner.invoke(test, op)
        if op.f == "stop":
            def restart(t, node):
                try:
                    start(t, node)
                    return "started"
                except Exception as e:   # noqa: BLE001
                    return str(e)
            statuses = c.on_nodes(test, restart)
            return out.assoc(value=[out.value, statuses])
        return out

    def teardown(self, test):
        self.inner.teardown(test)


class BumpTime(nem.Nemesis):
    """On :start, jump the clock by dt seconds on a random half of the
    nodes; on :stop, reset clocks (nemesis.clj bump-time :231-250)."""

    def __init__(self, dt: float):
        self.dt = dt

    def setup(self, test):
        reset_clocks(test)
        return self

    def invoke(self, test, op):
        if op.f == "start":
            def bump(t, node):
                if random.random() < 0.5:
                    nt.bump_time(self.dt * 1000)
                    return self.dt
                return 0
            return op.assoc(value=c.on_nodes(test, bump))
        if op.f == "stop":
            return op.assoc(value=c.on_nodes(
                test, lambda t, n: nt.reset_time()))
        return op

    def teardown(self, test):
        reset_clocks(test)


class StrobeTime(nem.Nemesis):
    """Strobe the clock between now and now+delta every period ms for
    duration s (nemesis.clj strobe-time :203-224)."""

    def __init__(self, delta_ms: float, period_ms: float,
                 duration_s: float):
        self.args = (delta_ms, period_ms, duration_s)

    def setup(self, test):
        reset_clocks(test)
        return self

    def invoke(self, test, op):
        if op.f == "start":
            return op.assoc(value=c.on_nodes(
                test, lambda t, n: nt.strobe_time(*self.args)))
        return op.assoc(value=None)

    def teardown(self, test):
        reset_clocks(test)


def skew(name: str, offset_s: float) -> dict:
    """nemesis.clj skew :259-264."""
    return dict(nemesis_single_gen(), name=name,
                client=Restarting(BumpTime(offset_s)), clocks=True)


def small_skews() -> dict:
    return skew("small-skews", 0.100)


def subcritical_skews() -> dict:
    return skew("subcritical-skews", 0.200)


def critical_skews() -> dict:
    """250 ms ≈ cockroach's default max clock offset (nemesis.clj)."""
    return skew("critical-skews", 0.250)


def big_skews() -> dict:
    out = skew("big-skews", 0.5)
    out["client"] = Slowing(out["client"], 0.5)
    return out


def huge_skews() -> dict:
    out = skew("huge-skews", 5.0)
    out["client"] = Slowing(out["client"], 5.0)
    return out


def strobe_skews() -> dict:
    """nemesis.clj strobe-skews :252-258 — no sleeps: the strobe itself
    takes time."""
    def steps():
        while True:
            yield lambda t, p: {"type": "info", "f": "start"}
            yield lambda t, p: {"type": "info", "f": "stop"}
    return {"name": "strobe-skews",
            "during": gen.gseq(steps()),
            "final": gen.once({"type": "info", "f": "stop"}),
            "client": Restarting(StrobeTime(200, 10, 10)),
            "clocks": True}


class SplitNemesis(nem.Nemesis):
    """Split a range just below a recently-written key, using the
    keyrange the clients report (nemesis.clj split-nemesis :268-305)."""

    def __init__(self, conn_factory=ShellConn):
        self.conn_factory = conn_factory
        self.already: dict = {}

    def setup(self, test):
        self.conn_factory = test.get("sql-factory") or self.conn_factory
        return self

    def invoke(self, test, op):
        kr = dict(test.get("keyrange") or {})
        if not kr:
            return op.assoc(value="no-keyrange")
        table, ks = random.choice(list(kr.items()))
        ks = set(ks) - self.already.get(table, set())
        if not ks:
            return op.assoc(value="nothing-to-split")
        k = next(iter(ks))
        conn = self.conn_factory(random.choice(test["nodes"]))
        try:
            split = getattr(conn, "split", None)
            if split is not None:
                split(table, k)
            else:
                conn.sql(f"ALTER TABLE {table} SPLIT AT VALUES (?)",
                         (k,))
            self.already.setdefault(table, set()).add(k)
            return op.assoc(value=["split", table, k])
        finally:
            conn.close()

    def teardown(self, test):
        pass


def split() -> dict:
    """nemesis.clj split :307-313."""
    return dict(nemesis_single_gen(), name="split",
                client=SplitNemesis(), clocks=False)


def _disk_recipes() -> dict:
    """The universal disk-fault recipes (PR 3 moved them out of this
    suite into faultfs/nemesis.py and the --nemesis plumbing never
    came back): re-published here so `--nemesis disk-eio` and the
    campaign orchestrator can target cockroach's data dir through the
    same registry currency as every other fault."""
    from jepsen_tpu import faultfs
    return dict(faultfs.nemeses)


nemeses = {
    "none": none,
    "parts": parts,
    "majority-ring": majring,
    "small-skews": small_skews,
    "subcritical-skews": subcritical_skews,
    "critical-skews": critical_skews,
    "big-skews": big_skews,
    "huge-skews": huge_skews,
    "strobe-skews": strobe_skews,
    "split": split,
    "start-stop": lambda: startstop(1),
    "start-stop-2": lambda: startstop(2),
    "start-kill": lambda: startkill(1),
    "start-kill-2": lambda: startkill(2),
    **{name: (lambda name=name: _disk_recipes()[name]())
       for name in ("disk-eio", "disk-slow", "disk-torn")},
}


# ---------------------------------------------------------------------------
# Workload clients
# ---------------------------------------------------------------------------

_table_lock = threading.Lock()


def _once(test, tag: str) -> bool:
    """True exactly once per (test run, tag) — the table-created? atom
    pattern every cockroach client uses.  State lives in the shared
    test map itself, so back-to-back runs in one process can't collide
    (an id(test)-keyed global would break when a later test dict reuses
    a garbage-collected address)."""
    done = test.setdefault("_once-tags", set())
    if tag in done:
        return False
    done.add(tag)
    return True


def ensure_table(conn, test, ddl: str, table: str) -> None:
    """Create a table exactly once per test run."""
    with _table_lock:
        if _once(test, f"table:{table}"):
            conn.sql(ddl)


class RegisterClient(SQLClient):
    """register.clj: independent keyed registers in one `test` table;
    read / write / cas with txn retry."""

    DDL = "CREATE TABLE IF NOT EXISTS test (id INT PRIMARY KEY, val INT)"

    def _invoke(self, test, op):
        ensure_table(self.conn, test, self.DDL, "test")
        k, v = op.value
        if op.f == "read":
            rows = with_txn_retry(lambda: self.conn.sql(
                "SELECT val FROM test WHERE id = ?", (k,)))
            val = int(rows[0][0]) if rows else None
            return op.assoc(type="ok", value=independent.tuple_(k, val))
        if op.f == "write":
            def w():
                self.conn.txn([
                    f"UPSERT INTO test (id, val) VALUES ({k}, {v})"])
            with_txn_retry(w)
            update_keyrange(test, "test", k)
            return op.assoc(type="ok")
        if op.f == "cas":
            old, new = v

            def do_cas():
                rows = self.conn.txn([
                    f"UPDATE test SET val = {new} "
                    f"WHERE id = {k} AND val = {old} RETURNING val"])
                return bool(rows)
            ok = with_txn_retry(do_cas)
            return op.assoc(type="ok" if ok else "fail")
        raise ValueError(f"unknown f {op.f!r}")


class BankClient(SQLClient):
    """bank.clj client: transfers move balance between account rows in
    one serializable txn.  The single-table and multitable variants
    differ only in where an account's row lives, so `_loc` is the one
    point of variation (bank.clj vs its multitable-test)."""

    def _loc(self, a) -> tuple:
        """(table, where-clause) of account a's balance row."""
        return "accounts", f"id = {a}"

    def _ddl(self, test):
        ensure_table(self.conn, test,
                     "CREATE TABLE IF NOT EXISTS accounts "
                     "(id INT PRIMARY KEY, balance INT)", "accounts")

    def _read_stmts(self, test) -> list:
        return ["SELECT id, balance FROM accounts"]

    def _seed_stmt(self, a, bal) -> str:
        return (f"INSERT INTO accounts (id, balance) VALUES ({a}, {bal}) "
                "ON CONFLICT (id) DO NOTHING")

    def _invoke(self, test, op):
        self._ddl(test)
        self._seed(test)
        if op.f == "read":
            rows = with_txn_retry(
                lambda: self.conn.txn(self._read_stmts(test)))
            return op.assoc(type="ok",
                            value={int(r[0]): int(r[1]) for r in rows})
        if op.f == "transfer":
            v = op.value
            frm, to, amt = v["from"], v["to"], v["amount"]
            neg_ok = bool(test.get("negative-balances?"))
            tf, wf = self._loc(frm)
            tt, wt = self._loc(to)

            def xfer():
                atomically = getattr(self.conn, "atomically", None)
                if atomically is not None:
                    # Interactive txn (the reference's with-txn JDBC
                    # path): read, check, debit, credit — one txn.
                    def body(run):
                        rows = run(f"SELECT balance FROM {tf} "
                                   f"WHERE {wf}")
                        bal = int(rows[0][0]) if rows else None
                        if bal is None or (bal < amt and not neg_ok):
                            raise Definite(
                                f"insufficient balance {bal}")
                        run(f"UPDATE {tf} SET balance = balance - {amt} "
                            f"WHERE {wf}")
                        run(f"UPDATE {tt} SET balance = balance + {amt} "
                            f"WHERE {wt}")
                    atomically(body)
                else:
                    # One-shot conns (cockroach sql -e): a single CTE
                    # statement where the credit applies only if the
                    # guarded debit matched.
                    guard = ("" if neg_ok
                             else f" AND balance >= {amt}")
                    rows = self.conn.txn([
                        f"WITH debit AS (UPDATE {tf} "
                        f"SET balance = balance - {amt} "
                        f"WHERE {wf}{guard} RETURNING id) "
                        f"UPDATE {tt} SET balance = balance + {amt} "
                        f"WHERE {wt} "
                        "AND EXISTS (SELECT 1 FROM debit) RETURNING id"])
                    if not rows:
                        raise Definite("insufficient balance")
            with_txn_retry(xfer)
            return op.assoc(type="ok")
        raise ValueError(f"unknown f {op.f!r}")

    def _seed(self, test):
        with _table_lock:
            if not _once(test, "bank-seed"):
                return
            accounts = test["accounts"]
            per = test["total-amount"] // len(accounts)
            rem = test["total-amount"] - per * len(accounts)
            for i, a in enumerate(accounts):
                self.conn.sql(
                    self._seed_stmt(a, per + (rem if i == 0 else 0)))


class MultiTableBankClient(BankClient):
    """bank.clj multitable variant: one table per account — transfers
    cross table boundaries (and thus shard ranges)."""

    def _loc(self, a) -> tuple:
        return f"accounts{a}", "id = 0"

    def _ddl(self, test):
        for a in test["accounts"]:
            ensure_table(
                self.conn, test,
                f"CREATE TABLE IF NOT EXISTS accounts{a} "
                "(id INT PRIMARY KEY, balance INT)", f"accounts{a}")

    def _read_stmts(self, test) -> list:
        return [f"SELECT {a}, balance FROM accounts{a}"
                for a in test["accounts"]]

    def _seed_stmt(self, a, bal) -> str:
        return (f"INSERT INTO accounts{a} (id, balance) "
                f"VALUES (0, {bal}) ON CONFLICT (id) DO NOTHING")


class SetsClient(SQLClient):
    """sets.clj: blind inserts of unique ints; one final read."""

    DDL = "CREATE TABLE IF NOT EXISTS sets (val INT PRIMARY KEY)"

    def _invoke(self, test, op):
        ensure_table(self.conn, test, self.DDL, "sets")
        if op.f == "add":
            with_txn_retry(lambda: self.conn.sql(
                f"INSERT INTO sets (val) VALUES ({op.value})"))
            update_keyrange(test, "sets", op.value)
            return op.assoc(type="ok")
        if op.f == "read":
            rows = with_txn_retry(
                lambda: self.conn.txn(["SELECT val FROM sets"]))
            return op.assoc(type="ok",
                            value=sorted(int(r[0]) for r in rows))
        raise ValueError(f"unknown f {op.f!r}")


class MonotonicClient(SQLClient):
    """monotonic.clj: inserts stamped with the DB's own transaction
    timestamp; checker verifies timestamp order matches value order."""

    DDL = ("CREATE TABLE IF NOT EXISTS mono "
           "(val INT PRIMARY KEY, ts BIGINT, node INT)")

    def _invoke(self, test, op):
        ensure_table(self.conn, test, self.DDL, "mono")
        if op.f == "add":
            node_idx = (test["nodes"].index(self.node)
                        if self.node in test["nodes"] else -1)

            # The val MUST be assigned in the same atomic statement
            # that inserts it (monotonic.clj invoke! :111-126): two
            # clients may otherwise commit in the opposite order of
            # their val acquisition and fake an inversion.  A single
            # INSERT..SELECT reads max(val) and the DB's own timestamp
            # atomically under serializable isolation.
            ts_expr = getattr(self.conn, "ts_expr",
                              "cluster_logical_timestamp()::INT8")
            with_txn_retry(lambda: self.conn.txn([
                "INSERT INTO mono (val, ts, node) "
                f"SELECT COALESCE(MAX(val), 0) + 1, {ts_expr}, "
                f"{node_idx} FROM mono"]))
            return op.assoc(type="ok")
        if op.f == "read":
            rows = with_txn_retry(lambda: self.conn.txn(
                ["SELECT val, ts, node FROM mono"]))
            return op.assoc(type="ok",
                            value=[[int(r[0]), int(r[1]), int(r[2])]
                                   for r in rows])
        raise ValueError(f"unknown f {op.f!r}")


class SequentialClient(SQLClient):
    """sequential.clj: a writer inserts chain keys k_0..k_n in order
    across `table_count` tables; readers scan in reverse — any
    non-prefix read breaks sequential consistency."""

    table_count = 5

    def _tables(self, test):
        for i in range(self.table_count):
            ensure_table(
                self.conn, test,
                f"CREATE TABLE IF NOT EXISTS seq_{i} "
                "(key VARCHAR(255) PRIMARY KEY)", f"seq_{i}")

    def _table_for(self, subkey: str) -> str:
        return f"seq_{hash(subkey) % self.table_count}"

    def _invoke(self, test, op):
        self._tables(test)
        chain, i = op.value
        if op.f == "write":
            subkey = f"{chain}_{i}"
            t = self._table_for(subkey)
            with_txn_retry(lambda: self.conn.sql(
                f"INSERT INTO {t} (key) VALUES (?)", (subkey,)))
            update_keyrange(test, t, subkey)
            return op.assoc(type="ok")
        if op.f == "read":
            # Each subkey read is its own txn, scanning high -> low
            # (sequential.clj invoke! :72-90).  '_' is a single-char
            # SQL wildcard, so escape it or chain 1 would also match
            # '10_3', '12_5', ...
            hi = -1
            for t in range(self.table_count):
                rows = self.conn.sql(
                    f"SELECT key FROM seq_{t} WHERE key LIKE ? "
                    "ESCAPE '#'", (f"{chain}#_%",))
                for (k,) in rows:
                    hi = max(hi, int(k.split("_")[1]))
            found = []
            for j in range(hi, -1, -1):
                subkey = f"{chain}_{j}"
                rows = with_txn_retry(
                    lambda sk=subkey: self.conn.sql(
                        f"SELECT key FROM {self._table_for(sk)} "
                        "WHERE key = ?", (sk,)))
                if rows:
                    found.append(j)
            return op.assoc(type="ok", value=[chain, sorted(found)])
        raise ValueError(f"unknown f {op.f!r}")


class CommentsClient(SQLClient):
    """comments.clj: blind inserts across tables + full-scan reads in a
    txn; checker hunts strict-serializability violations (T2 visible
    without an earlier completed T1)."""

    table_count = 5

    def _tables(self, test):
        for i in range(self.table_count):
            ensure_table(
                self.conn, test,
                f"CREATE TABLE IF NOT EXISTS comment_{i} "
                "(id INT PRIMARY KEY, key INT)", f"comment_{i}")

    def _invoke(self, test, op):
        self._tables(test)
        k, ident = op.value
        if op.f == "write":
            t = f"comment_{ident % self.table_count}"
            with_txn_retry(lambda: self.conn.sql(
                f"INSERT INTO {t} (id, key) VALUES ({ident}, {k})"))
            update_keyrange(test, t, ident)
            return op.assoc(type="ok")
        if op.f == "read":
            def read_all():
                stmts = [f"SELECT id FROM comment_{i} WHERE key = {k}"
                         for i in range(self.table_count)]
                return self.conn.txn(stmts)
            rows = with_txn_retry(read_all)
            ids = sorted(int(r[0]) for r in rows)
            return op.assoc(type="ok",
                            value=independent.tuple_(k, ids))
        raise ValueError(f"unknown f {op.f!r}")


class G2Client(SQLClient):
    """adya.clj G2: two tables; each txn predicate-reads both, then
    inserts into its own if both empty for its key."""

    def _invoke(self, test, op):
        for t in ("g2a", "g2b"):
            ensure_table(
                self.conn, test,
                f"CREATE TABLE IF NOT EXISTS {t} "
                "(id INT PRIMARY KEY, k INT)", t)
        k, v = op.value
        a_id, b_id = v
        ident = a_id if a_id is not None else b_id
        table = "g2a" if a_id is not None else "g2b"

        def txn():
            # Predicate-read both tables and insert in ONE atomic
            # statement — the guard and the write must share a txn or
            # two racers both see "empty" and both insert (the exact G2
            # anomaly this workload hunts, manufactured by the client).
            rows = self.conn.txn([
                f"INSERT INTO {table} (id, k) SELECT {ident}, {k} "
                f"WHERE NOT EXISTS (SELECT 1 FROM g2a WHERE k = {k}) "
                f"AND NOT EXISTS (SELECT 1 FROM g2b WHERE k = {k}) "
                "RETURNING id"])
            if not rows:
                raise Definite("predicate found a row")
        with_txn_retry(txn)
        return op.assoc(type="ok")


class ElleListAppendClient(SQLClient):
    """Elle list-append txns over SQL: one micro-op per statement, the
    whole txn in ONE conn.txn so the SUT's isolation — not the client —
    decides what interleaves.  Lists live as comma-joined text; reads
    are scalar subqueries so every read mop yields exactly one row and
    results align with mops by position."""

    DDL = ("CREATE TABLE IF NOT EXISTS elle_la "
           "(k INT PRIMARY KEY, val TEXT)")

    def _invoke(self, test, op):
        ensure_table(self.conn, test, self.DDL, "elle_la")
        txn = list(op.value or [])
        stmts = []
        for f, k, v in txn:
            if f == "append":
                stmts.append(
                    f"INSERT INTO elle_la (k, val) VALUES ({k}, '{v}') "
                    f"ON CONFLICT (k) DO UPDATE SET val = "
                    f"val || ',{v}'")
            else:
                stmts.append(f"SELECT {k}, (SELECT val FROM elle_la "
                             f"WHERE k = {k})")
        rows = with_txn_retry(lambda: self.conn.txn(stmts))
        reads = iter(rows)
        out = []
        for f, k, v in txn:
            if f != "r":
                out.append([f, k, v])
                continue
            row = next(reads, None)
            val = row[1] if row is not None and len(row) > 1 else None
            if val in (None, ""):
                out.append([f, k, None])
            else:
                out.append([f, k, [int(x) for x in
                                   str(val).split(",") if x != ""]])
        return op.assoc(type="ok", value=out)


class CausalClient(SQLClient):
    """Causal-register ops over SQL (ISSUE 20): independent keyed
    registers; write installs the session's counter value, reads
    return the current value (None while unwritten, which the causal
    register treats as the init state)."""

    DDL = ("CREATE TABLE IF NOT EXISTS causal "
           "(id INT PRIMARY KEY, val INT)")

    def _invoke(self, test, op):
        ensure_table(self.conn, test, self.DDL, "causal")
        k, v = op.value
        if op.f == "write":
            def w():
                self.conn.txn([
                    f"UPSERT INTO causal (id, val) VALUES ({k}, {v})"])
            with_txn_retry(w)
            update_keyrange(test, "causal", k)
            return op.assoc(type="ok")
        if op.f in ("read", "read-init"):
            rows = with_txn_retry(lambda: self.conn.sql(
                "SELECT val FROM causal WHERE id = ?", (k,)))
            val = int(rows[0][0]) if rows else None
            return op.assoc(type="ok", value=independent.tuple_(k, val))
        raise ValueError(f"unknown f {op.f!r}")


class PredicateClient(SQLClient):
    """Predicate-read txns over SQL (ISSUE 20): `["w", k, v]` upserts;
    `["rp", ["keys", ks], nil]` evaluates the predicate as one scalar
    subquery per matched key (one row per key, so results align with
    mops by position — the ElleListAppendClient discipline) and fills
    the observed {k: v} map."""

    DDL = ("CREATE TABLE IF NOT EXISTS pred "
           "(k INT PRIMARY KEY, val INT)")

    def _invoke(self, test, op):
        ensure_table(self.conn, test, self.DDL, "pred")
        txn = [list(m) for m in (op.value or [])]
        stmts = []
        for m in txn:
            if mop_txn.is_predicate_read(m):
                for k in mop_txn.predicate_keys(m):
                    stmts.append(
                        f"SELECT {k}, (SELECT val FROM pred "
                        f"WHERE k = {k})")
            else:
                _, k, v = m
                stmts.append(f"UPSERT INTO pred (k, val) "
                             f"VALUES ({k}, {v})")
        rows = with_txn_retry(lambda: self.conn.txn(stmts))
        reads = iter(rows)
        out = []
        for m in txn:
            if not mop_txn.is_predicate_read(m):
                out.append(m)
                continue
            observed = {}
            for k in mop_txn.predicate_keys(m):
                row = next(reads, None)
                val = row[1] if row is not None and len(row) > 1 \
                    else None
                if val is not None:
                    observed[k] = int(val)
            out.append([m[0], m[1], observed])
        return op.assoc(type="ok", value=out)


class ElleRwRegisterClient(SQLClient):
    """Elle rw-register txns over SQL (same one-txn discipline)."""

    DDL = "CREATE TABLE IF NOT EXISTS elle_rw (k INT PRIMARY KEY, v INT)"

    def _invoke(self, test, op):
        ensure_table(self.conn, test, self.DDL, "elle_rw")
        txn = list(op.value or [])
        stmts = []
        for f, k, v in txn:
            if f == "w":
                stmts.append(
                    f"INSERT INTO elle_rw (k, v) VALUES ({k}, {v}) "
                    f"ON CONFLICT (k) DO UPDATE SET v = {v}")
            else:
                stmts.append(f"SELECT {k}, (SELECT v FROM elle_rw "
                             f"WHERE k = {k})")
        rows = with_txn_retry(lambda: self.conn.txn(stmts))
        reads = iter(rows)
        out = []
        for f, k, v in txn:
            if f != "r":
                out.append([f, k, v])
                continue
            row = next(reads, None)
            val = row[1] if row is not None and len(row) > 1 else None
            out.append([f, k, int(val) if val is not None else None])
        return op.assoc(type="ok", value=out)


# ---------------------------------------------------------------------------
# Comments checker (comments.clj checker)
# ---------------------------------------------------------------------------

class CommentsChecker(ck.Checker):
    """Replay the history tracking writes completed before each write's
    invocation; a read seeing w_i but missing some completed-earlier
    w_j breaks strict serializability (comments.clj checker)."""

    def check(self, test, history, opts=None):
        completed: set = set()
        expected: dict = {}
        errors = []
        for op in History(history):
            if op.f == "write":
                if op.is_invoke:
                    expected[op.value] = set(completed)
                elif op.is_ok:
                    completed.add(op.value)
            elif op.f == "read" and op.is_ok and op.value is not None:
                seen = set(op.value)
                for w in seen:
                    missing = expected.get(w, set()) - seen
                    if missing:
                        errors.append({"op": op, "seen": w,
                                       "missing": sorted(missing)})
        return {"valid?": not errors, "errors": errors}


# ---------------------------------------------------------------------------
# Test constructors (runner.clj tests :25-34)
# ---------------------------------------------------------------------------

def base_test(opts, nemesis_map: dict, name: str) -> dict:
    from jepsen_tpu import tests as tst

    opts = dict(opts or {})
    nodes = opts.get("nodes") or ["n1", "n2", "n3", "n4", "n5"]
    return dict(tst.noop_test(), **{
        "name": f"cockroachdb {name} {nemesis_map['name']}",
        "nodes": nodes,
        "concurrency": opts.get("concurrency", len(nodes)),
        "ssh": opts.get("ssh", {}),
        "os": opts.get("os"),
        "db": CockroachDB(),
        "net": net.iptables,
        "nemesis": nemesis_map["client"],
        "sql-factory": opts.get("sql-factory"),
    })


def _with_nemesis(opts, test, workload_gen, nemesis_map: dict,
                  final_gen=None) -> None:
    """Wire the during/final split: workload under the nemesis' during
    gen, then heal + quiesce + final reads."""
    during = gen.time_limit(
        opts.get("time-limit", 60),
        gen.nemesis(nemesis_map["during"], workload_gen))
    phases = [during,
              gen.nemesis(nemesis_map["final"], gen.void)]
    if final_gen is not None:
        phases += [gen.sleep(opts.get("quiesce", 3)),
                   gen.clients(final_gen)]
    test["generator"] = gen.phases(*phases)


def _rounded_concurrency(opts, tpk: int) -> int:
    """concurrent-generator needs concurrency to be a positive multiple
    of threads-per-key; round the requested concurrency up."""
    conc = max(opts.get("concurrency", 10), tpk)
    return conc + (-conc) % tpk


def _nemesis_for(opts) -> dict:
    """--nemesis/--nemesis2 names -> ONE named map, resolved through
    the shared registry resolver (_template.resolve_named_nemeses,
    recadence=False: this registry carries bespoke cadences — the
    double-gen ladder, strobe's sleepless loop — that must not be
    flattened to start/stop intervals).  An explicit
    opts["nemesis-map"] (a campaign schedule's compiled window
    sequence) wins, which is what makes cockroach
    campaign-targetable."""
    # late import: _template imports _rounded_concurrency from here
    from jepsen_tpu.suites._template import resolve_named_nemeses
    names = list(opts.get("nemesis") or []) \
        + list(opts.get("nemesis2") or [])
    nm = resolve_named_nemeses(
        nemeses, dict(opts, nemesis=names or ["none"]),
        recadence=False)
    assert nm is not None
    return nm


def bank_test(opts) -> dict:
    opts = dict(opts or {})
    nm = _nemesis_for(opts)
    wl = bank_wl.workload(opts)
    test = base_test(opts, nm, "bank")
    test.update({k: wl[k] for k in
                 ("accounts", "total-amount", "max-transfer")})
    test["client"] = BankClient()
    test["checker"] = ck.compose({"bank": wl["checker"],
                                  "perf": ck.perf()})
    _with_nemesis(opts, test, gen.stagger(1 / 10, wl["generator"]), nm)
    return test


def multitable_bank_test(opts) -> dict:
    test = bank_test(opts)
    test["name"] = test["name"].replace(" bank ", " bank-multitable ")
    test["client"] = MultiTableBankClient()
    return test


def register_test(opts) -> dict:
    opts = dict(opts or {})
    nm = _nemesis_for(opts)
    test = base_test(opts, nm, "register")
    test["client"] = RegisterClient()
    wl = linreg_wl.suite_workload(opts)
    test["concurrency"] = _rounded_concurrency(
        opts, wl["threads-per-key"])
    test["checker"] = ck.compose({
        "linear": wl["checker"],
        "timeline": independent.checker(timeline.html_timeline()),
        "perf": ck.perf()})
    _with_nemesis(opts, test, wl["generator"], nm)
    return test


def sets_test(opts) -> dict:
    opts = dict(opts or {})
    nm = _nemesis_for(opts)
    wl = sets_wl.workload(opts)
    test = base_test(opts, nm, "sets")
    test["client"] = SetsClient()
    test["checker"] = ck.compose({"set": wl["checker"],
                                  "perf": ck.perf()})
    _with_nemesis(opts, test, gen.stagger(1 / 10, wl["generator"]), nm,
                  final_gen=wl["final-generator"])
    return test


def monotonic_test(opts) -> dict:
    opts = dict(opts or {})
    nm = _nemesis_for(opts)
    wl = monotonic_wl.workload(opts)
    test = base_test(opts, nm, "monotonic")
    test["client"] = MonotonicClient()
    test["checker"] = ck.compose({"monotonic": wl["checker"],
                                  "perf": ck.perf()})
    _with_nemesis(opts, test, gen.stagger(1 / 10, wl["generator"]), nm,
                  final_gen=gen.once(monotonic_wl.read))
    return test


def sequential_test(opts) -> dict:
    opts = dict(opts or {})
    nm = _nemesis_for(opts)
    wl = sequential_wl.workload(opts)
    test = base_test(opts, nm, "sequential")
    test["client"] = SequentialClient()
    test["checker"] = ck.compose({"sequential": wl["checker"],
                                  "perf": ck.perf()})
    _with_nemesis(opts, test, gen.stagger(1 / 10, wl["generator"]), nm)
    return test


def comments_test(opts) -> dict:
    opts = dict(opts or {})
    nm = _nemesis_for(opts)
    test = base_test(opts, nm, "comments")
    test["client"] = CommentsClient()
    ids = itertools.count(1)
    lock = threading.Lock()

    def next_id():
        with lock:
            return next(ids)

    def fgen(k):
        def w(t, p):
            return {"type": "invoke", "f": "write",
                    "value": next_id()}

        def r(t, p):
            return {"type": "invoke", "f": "read", "value": None}
        return gen.limit(opts.get("ops-per-key", 50),
                         gen.stagger(1 / 10, gen.mix([w, w, r])))

    test["checker"] = ck.compose({
        "comments": independent.checker(CommentsChecker()),
        "perf": ck.perf()})
    tpk = opts.get("threads-per-key", 2)
    test["concurrency"] = _rounded_concurrency(opts, tpk)
    _with_nemesis(opts, test,
                  independent.concurrent_generator(
                      tpk, itertools.count(), fgen), nm)
    return test


def g2_test(opts) -> dict:
    opts = dict(opts or {})
    nm = _nemesis_for(opts)
    wl = adya_wl.workload(opts)
    test = base_test(opts, nm, "g2")
    test["client"] = G2Client()
    test["checker"] = ck.compose({"g2": wl["checker"],
                                  "perf": ck.perf()})
    test["concurrency"] = max(2, opts.get("concurrency", 10) // 2 * 2)
    _with_nemesis(opts, test, wl["generator"], nm)
    return test


def list_append_test(opts) -> dict:
    """Elle list-append: the transactional-isolation hunt
    (checker/elle.py) — every anomaly class from G0 to G2-item, with
    the typed-cycle search batched on device."""
    opts = dict(opts or {})
    nm = _nemesis_for(opts)
    wl = list_append_wl.workload(opts)
    test = base_test(opts, nm, "list-append")
    test["client"] = ElleListAppendClient()
    test["checker"] = ck.compose({"elle": wl["checker"],
                                  "perf": ck.perf()})
    _with_nemesis(opts, test, gen.stagger(1 / 20, wl["generator"]), nm)
    return test


def rw_register_test(opts) -> dict:
    """Elle rw-register: isolation anomalies inferred from
    register traces (version orders recovered from evidence)."""
    opts = dict(opts or {})
    nm = _nemesis_for(opts)
    wl = rw_register_wl.workload(opts)
    test = base_test(opts, nm, "rw-register")
    test["client"] = ElleRwRegisterClient()
    test["checker"] = ck.compose({"elle": wl["checker"],
                                  "perf": ck.perf()})
    _with_nemesis(opts, test, gen.stagger(1 / 20, wl["generator"]), nm)
    return test


def session_test(opts) -> dict:
    """Session guarantees over the full consistency lattice
    (ISSUE 20): list-append sessions classified by the lattice
    checker — read-your-writes, monotonic-reads/writes,
    writes-follow-reads, PRAM, causal each surface as their own
    class with weakest-violated naming the minimal broken model."""
    opts = dict(opts or {})
    nm = _nemesis_for(opts)
    wl = session_wl.workload(opts)
    test = base_test(opts, nm, "session")
    test["client"] = ElleListAppendClient()
    test["checker"] = ck.compose({"lattice": wl["checker"],
                                  "perf": ck.perf()})
    _with_nemesis(opts, test, gen.stagger(1 / 20, wl["generator"]), nm)
    return test


def causal_test(opts) -> dict:
    """Causal registers (ISSUE 20): the lattice-backed causal checker
    (legacy causal register as pinned differential oracle) over
    independent keys."""
    opts = dict(opts or {})
    nm = _nemesis_for(opts)
    test = base_test(opts, nm, "causal")
    test["client"] = CausalClient()
    test["checker"] = ck.compose({
        "causal": independent.checker(causal_wl.check()),
        "perf": ck.perf()})
    test["concurrency"] = _rounded_concurrency(opts, 1)
    g = independent.concurrent_generator(
        1, itertools.count(),
        lambda k: gen.gseq([causal_wl.ri, causal_wl.cw1,
                            causal_wl.r, causal_wl.cw2,
                            causal_wl.r]))
    _with_nemesis(opts, test, gen.stagger(1 / 10, g), nm)
    return test


def predicate_test(opts) -> dict:
    """Predicate reads (ISSUE 20): phantom hunting — rp micro-ops
    over a keyed register table, G1/G2-predicate via the lattice
    engine's predicate evidence pass."""
    opts = dict(opts or {})
    nm = _nemesis_for(opts)
    wl = predicate_wl.workload(opts)
    test = base_test(opts, nm, "predicate")
    test["client"] = PredicateClient()
    test["checker"] = ck.compose({"lattice": wl["checker"],
                                  "perf": ck.perf()})
    _with_nemesis(opts, test, gen.stagger(1 / 20, wl["generator"]), nm)
    return test


tests = {
    "bank": bank_test,
    "causal": causal_test,
    "session": session_test,
    "predicate": predicate_test,
    "bank-multitable": multitable_bank_test,
    "comments": comments_test,
    "register": register_test,
    "monotonic": monotonic_test,
    "sets": sets_test,
    "sequential": sequential_test,
    "g2": g2_test,
    "list-append": list_append_test,
    "rw-register": rw_register_test,
}


# ---------------------------------------------------------------------------
# Runner (runner.clj)
# ---------------------------------------------------------------------------

def test_for(opts) -> dict:
    """Look up the workload by name and build its test map.  Suite
    options may come in directly or via the CLI's argv-options submap."""
    opts = dict(opts or {})
    av = opts.get("argv-options") or {}
    for key in ("workload", "nemesis", "nemesis2"):
        if key not in opts and av.get(key) is not None:
            opts[key] = av[key]
    name = opts.get("workload") or "register"
    try:
        ctor = tests[name]
    except KeyError:
        raise ValueError(
            f"unknown workload {name!r}; one of {sorted(tests)}")
    return ctor(opts)


def _opt_fn(parser):
    """runner.clj opt-spec: workload + repeatable nemesis registries
    (runner.clj:42-76) — the --nemesis flag through the shared
    cli.nemesis_opt_spec, like every registry-carrying suite."""
    parser.add_argument("--workload", default="register",
                        choices=sorted(tests),
                        help="which workload to run")
    cli.nemesis_opt_spec(parser, nemeses, default="none")
    parser.add_argument("--nemesis2", action="append", dest="nemesis2",
                        choices=sorted(nemeses), metavar="NAME",
                        help="an additional nemesis to mix in")


def main(argv=None):
    """runner.clj -main: test / analyze / serve / campaign with
    workload + nemesis registries."""
    cli.run(cli.single_test_cmd(test_for, _opt_fn,
                                nemesis_registry=nemeses), argv)


if __name__ == "__main__":
    main()
