"""RobustIRC test suite (reference: `robustirc/src/jepsen/robustirc.clj`,
217 LoC): a raft-replicated IRC network — every message posted to a
channel must be delivered exactly once, in order, to every member.
Modeled as the set workload (posted messages = unique adds; the final
read collects the channel backlog) — message loss is the anomaly the
reference hunted."""

from __future__ import annotations

from jepsen_tpu import checker as ck
from jepsen_tpu import client as client_mod
from jepsen_tpu import control as c
from jepsen_tpu import control_util as cu
from jepsen_tpu import db as db_mod
from jepsen_tpu import generator as gen
from jepsen_tpu import net
from jepsen_tpu import nemesis as nem
from jepsen_tpu.control import lit
from jepsen_tpu.suites._template import simple_main
from jepsen_tpu.workloads import sets as sets_wl

DIR = "/opt/robustirc"
PORT = 60667
CHANNEL = "#jepsen"


class RobustIrcDB(db_mod.DB, db_mod.LogFiles):
    def setup(self, test, node):
        nodes = test.get("nodes") or [node]
        first = nodes[0]
        args = [f"{DIR}/robustirc",
                "-network_name", "jepsen.test",
                "-peer_addr", f"{node}:{PORT}",
                "-tls_cert_path", f"{DIR}/cert.pem",
                "-tls_key_path", f"{DIR}/key.pem"]
        if node != first:
            args += ["-join", f"{first}:{PORT}"]
        cu.start_daemon(*args, chdir=DIR,
                        logfile=f"{DIR}/robustirc.log",
                        pidfile=f"{DIR}/robustirc.pid")
        c.execute(lit(
            "for i in $(seq 1 60); do "
            f"nc -z {node} {PORT} && exit 0; sleep 1; done; exit 1"),
            check=False)

    def teardown(self, test, node):
        cu.stop_daemon(f"{DIR}/robustirc.pid", f"{DIR}/robustirc")

    def log_files(self, test, node):
        return [f"{DIR}/robustirc.log"]


class IrcShellConn:
    """Post/backlog over the robustirc HTTP bridge."""

    def __init__(self, node: str):
        self.node = node
        self._session = c.session(node)

    def post(self, v) -> None:
        with c.with_session(self.node, self._session):
            c.execute("curl", "-skf", "-X", "POST",
                      "-d", f"PRIVMSG {CHANNEL} :{v}",
                      f"https://{self.node}:{PORT}/robustirc/v1/jepsen")

    def backlog(self) -> list:
        with c.with_session(self.node, self._session):
            out = c.execute("curl", "-skf",
                            f"https://{self.node}:{PORT}"
                            "/robustirc/v1/jepsen/messages",
                            check=False)
        vals = []
        for line in (out or "").splitlines():
            tail = line.rsplit(":", 1)[-1].strip()
            if tail.isdigit():
                vals.append(int(tail))
        return sorted(vals)

    def close(self):
        self._session.close()


class IrcClient(client_mod.Client):
    def __init__(self, conn_factory=IrcShellConn):
        self.conn_factory = conn_factory
        self.conn = None

    def open(self, test, node):
        out = IrcClient(test.get("irc-factory") or self.conn_factory)
        out.conn = out.conn_factory(node)
        return out

    def close(self, test):
        if self.conn is not None and hasattr(self.conn, "close"):
            self.conn.close()

    def invoke(self, test, op):
        try:
            if op.f == "add":
                self.conn.post(op.value)
                return op.assoc(type="ok")
            if op.f == "read":
                return op.assoc(type="ok", value=self.conn.backlog())
            raise ValueError(f"unknown f {op.f!r}")
        except TimeoutError as e:
            return op.assoc(type="info", error=str(e))
        except (ConnectionError, OSError) as e:
            return op.assoc(type="info", error=str(e))


def irc_test(opts) -> dict:
    from jepsen_tpu import tests as tst

    opts = dict(opts or {})
    nodes = opts.get("nodes") or ["n1", "n2", "n3", "n4", "n5"]
    wl = sets_wl.workload(opts)
    return dict(tst.noop_test(), **{
        "name": "robustirc",
        "nodes": nodes,
        "concurrency": opts.get("concurrency", len(nodes)),
        "ssh": opts.get("ssh", {}),
        "db": RobustIrcDB(),
        "net": net.iptables,
        "nemesis": nem.partition_random_halves(),
        "irc-factory": opts.get("irc-factory"),
        "client": IrcClient(),
        "generator": gen.phases(
            gen.time_limit(
                opts.get("time-limit", 60),
                gen.nemesis(
                    gen.start_stop(opts.get("nemesis-interval", 5),
                                   opts.get("nemesis-interval", 5)),
                    gen.stagger(1 / 10, wl["generator"]))),
            gen.nemesis(gen.once({"type": "info", "f": "stop"})),
            gen.sleep(opts.get("quiesce", 3)),
            gen.clients(wl["final-generator"])),
        "checker": ck.compose({"messages": wl["checker"],
                               "perf": ck.perf()}),
    })


main = simple_main(irc_test)

if __name__ == "__main__":
    main()
