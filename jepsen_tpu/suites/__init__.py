"""Per-database test suites (reference: the 24 suite projects, e.g.
`etcd/src/jepsen/etcd.clj`, `cockroachdb/src/jepsen/cockroach/runner.clj`).

Each suite packages DB automation + a client + workloads + a nemesis
menu + a CLI main.  `etcd` is the canonical template; `cockroach` is
the registry-driven template (workload + nemesis registries, named
nemesis composition).

`SUITES` maps suite names to the module path holding its `main`;
modules import lazily so one suite's deps never block another."""

from __future__ import annotations

import importlib

SUITES = {
    "etcd": "jepsen_tpu.suites.etcd",
    "cockroach": "jepsen_tpu.suites.cockroach",
    "yugabyte": "jepsen_tpu.suites.yugabyte",
    "aerospike": "jepsen_tpu.suites.aerospike",
    "dgraph": "jepsen_tpu.suites.dgraph",
    "zookeeper": "jepsen_tpu.suites.zookeeper",
    "consul": "jepsen_tpu.suites.consul",
    "rabbitmq": "jepsen_tpu.suites.rabbitmq",
    "chronos": "jepsen_tpu.suites.chronos",
    "galera": "jepsen_tpu.suites.galera",
    "percona": "jepsen_tpu.suites.percona",
    "tidb": "jepsen_tpu.suites.tidb",
    "mongodb": "jepsen_tpu.suites.mongodb",
    "mongodb-smartos": "jepsen_tpu.suites.mongodb_smartos",
    "postgres-rds": "jepsen_tpu.suites.postgres_rds",
    "raftis": "jepsen_tpu.suites.raftis",
    "logcabin": "jepsen_tpu.suites.logcabin",
    "disque": "jepsen_tpu.suites.disque",
    "rethinkdb": "jepsen_tpu.suites.rethinkdb",
    "mysql-cluster": "jepsen_tpu.suites.mysql_cluster",
    "hazelcast": "jepsen_tpu.suites.hazelcast",
    "elasticsearch": "jepsen_tpu.suites.elasticsearch",
    "crate": "jepsen_tpu.suites.crate",
    "robustirc": "jepsen_tpu.suites.robustirc",
}


def main_for(name: str):
    """Resolve a suite's CLI entry point by name."""
    try:
        mod = SUITES[name]
    except KeyError:
        raise ValueError(f"unknown suite {name!r}; one of {sorted(SUITES)}")
    return importlib.import_module(mod).main
