"""Per-database test suites (reference: the 24 suite projects, e.g.
`etcd/src/jepsen/etcd.clj`, `cockroachdb/src/jepsen/cockroach/runner.clj`).

Each suite packages DB automation + a client + workloads + a nemesis
menu + a CLI main.  `etcd` is the canonical template."""
