"""Per-database test suites (reference: the 24 suite projects, e.g.
`etcd/src/jepsen/etcd.clj`, `cockroachdb/src/jepsen/cockroach/runner.clj`).

Each suite packages DB automation + a client + workloads + a nemesis
menu + a CLI main.  `etcd` is the canonical template; `cockroach` is
the registry-driven template (workload + nemesis registries, named
nemesis composition).

`SUITES` maps suite names to the module path holding its `main`;
modules import lazily so one suite's deps never block another."""

from __future__ import annotations

import importlib

SUITES = {
    "etcd": "jepsen_tpu.suites.etcd",
    "cockroach": "jepsen_tpu.suites.cockroach",
    "yugabyte": "jepsen_tpu.suites.yugabyte",
    "aerospike": "jepsen_tpu.suites.aerospike",
    "dgraph": "jepsen_tpu.suites.dgraph",
}


def main_for(name: str):
    """Resolve a suite's CLI entry point by name."""
    try:
        mod = SUITES[name]
    except KeyError:
        raise ValueError(f"unknown suite {name!r}; one of {sorted(SUITES)}")
    return importlib.import_module(mod).main
