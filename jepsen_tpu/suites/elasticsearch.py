"""Elasticsearch test suite (reference: `elasticsearch/src/jepsen/
system/elasticsearch.clj`, 862 LoC): the canonical lost-documents
hunt — unique docs indexed with wait-for-active-shards, one refreshed
final read that must find every acknowledged doc (set workload /
set-full timeline accounting), plus a versioned-update CAS register
(`_version` conditional writes)."""

from __future__ import annotations

import json
from typing import Optional

from jepsen_tpu import checker as ck
from jepsen_tpu import client as client_mod
from jepsen_tpu import control as c
from jepsen_tpu import control_util as cu
from jepsen_tpu import db as db_mod
from jepsen_tpu import generator as gen
from jepsen_tpu import net
from jepsen_tpu import nemesis as nem
from jepsen_tpu.control import lit
from jepsen_tpu.suites._template import (KVRegisterClient,
                                         register_test, workload_main)
from jepsen_tpu.workloads import dirty_read as dirty_read_wl
from jepsen_tpu.workloads import sets as sets_wl

DIR = "/opt/elasticsearch"
PORT = 9200
INDEX = "jepsen"


class ElasticsearchDB(db_mod.DB, db_mod.LogFiles):
    def setup(self, test, node):
        nodes = test.get("nodes") or [node]
        cfg = {
            "cluster.name": "jepsen",
            "node.name": node,
            "network.host": node,
            "discovery.seed_hosts": nodes,
            "cluster.initial_master_nodes": nodes[:3],
        }
        c.upload_str(
            "\n".join(f"{k}: {json.dumps(v)}" for k, v in cfg.items())
            + "\n", f"{DIR}/config/elasticsearch.yml")
        cu.start_daemon(f"{DIR}/bin/elasticsearch", "-d",
                        "-p", f"{DIR}/es.pid",
                        chdir=DIR, logfile=f"{DIR}/logs/jepsen.log",
                        pidfile=f"{DIR}/es.pid")
        c.execute(lit(
            "for i in $(seq 1 120); do "
            f"curl -sf http://{node}:{PORT}/_cluster/health "
            "&& exit 0; sleep 1; done; exit 1"), check=False)

    def teardown(self, test, node):
        cu.stop_daemon(f"{DIR}/es.pid", "elasticsearch")
        c.execute("rm", "-rf", f"{DIR}/data", check=False)

    def log_files(self, test, node):
        return [f"{DIR}/logs/jepsen.log"]


class EsHttpConn:
    """Documents + versioned CAS over the HTTP API."""

    def __init__(self, node: str):
        self.node = node
        self._session = c.session(node)

    def _curl(self, *args) -> str:
        with c.with_session(self.node, self._session):
            return c.execute("curl", "-sf", *args, check=False)

    # -- set workload ------------------------------------------------------
    def add(self, v) -> None:
        out = self._curl("-X", "PUT",
                         "-H", "Content-Type: application/json",
                         "-d", json.dumps({"value": v}),
                         f"http://{self.node}:{PORT}/{INDEX}/_doc/{v}"
                         "?wait_for_active_shards=all")
        # Success needs POSITIVE evidence: curl -sf via the control
        # plane never raises, so a dropped PUT acked as ok would make
        # the set/dirty-read checkers report data loss against a
        # healthy cluster.
        if '"result":"created"' not in (out or "") and \
                '"result":"updated"' not in (out or ""):
            raise TimeoutError(f"unacked index write: {out[:120]!r}")

    def read_all(self) -> list:
        self._curl("-X", "POST",
                   f"http://{self.node}:{PORT}/{INDEX}/_refresh")
        out = self._curl(
            f"http://{self.node}:{PORT}/{INDEX}/_search"
            "?size=10000&_source=false")
        try:
            hits = json.loads(out or "{}")["hits"]["hits"]
        except (ValueError, KeyError):
            return []
        return sorted(int(h["_id"]) for h in hits)

    # -- register ----------------------------------------------------------
    def get(self, k) -> Optional[int]:
        out = self._curl(
            f"http://{self.node}:{PORT}/{INDEX}-reg/_doc/r{k}")
        try:
            return json.loads(out or "{}")["_source"]["value"]
        except (ValueError, KeyError):
            return None

    def put(self, k, v) -> None:
        self._curl("-X", "PUT",
                   "-H", "Content-Type: application/json",
                   "-d", json.dumps({"value": v}),
                   f"http://{self.node}:{PORT}/{INDEX}-reg/_doc/r{k}")

    def cas(self, k, old, new) -> bool:
        out = self._curl(
            f"http://{self.node}:{PORT}/{INDEX}-reg/_doc/r{k}")
        try:
            doc = json.loads(out or "{}")
            if doc["_source"]["value"] != old:
                return False
            seq, term = doc["_seq_no"], doc["_primary_term"]
        except (ValueError, KeyError):
            return False
        out = self._curl(
            "-X", "PUT", "-H", "Content-Type: application/json",
            "-d", json.dumps({"value": new}),
            f"http://{self.node}:{PORT}/{INDEX}-reg/_doc/r{k}"
            f"?if_seq_no={seq}&if_primary_term={term}")
        return "\"result\":\"updated\"" in (out or "")

    # -- dirty-read workload (elasticsearch/dirty_read.clj) -----------
    def add_id(self, v) -> None:
        self.add(v)

    def has_id(self, v) -> bool:
        out = self._curl(
            f"http://{self.node}:{PORT}/{INDEX}/_doc/{v}")
        return '"found":true' in (out or "")

    def refresh(self) -> None:
        self._curl("-X", "POST",
                   f"http://{self.node}:{PORT}/{INDEX}/_refresh")

    def all_ids(self) -> list:
        return self.read_all()

    def close(self):
        self._session.close()


class EsDirtyReadClient(client_mod.Client):
    """elasticsearch/dirty_read.clj client: GETs of specific ids probe
    uncommitted visibility; strong reads scan the refreshed index."""

    def __init__(self, conn_factory=EsHttpConn):
        self.conn_factory = conn_factory
        self.conn = None

    def open(self, test, node):
        out = EsDirtyReadClient(test.get("es-factory")
                                or self.conn_factory)
        out.conn = out.conn_factory(node)
        return out

    def close(self, test):
        if self.conn is not None and hasattr(self.conn, "close"):
            self.conn.close()

    def invoke(self, test, op):
        try:
            if op.f == "write":
                self.conn.add_id(op.value)
                return op.assoc(type="ok")
            if op.f == "read":
                return op.assoc(
                    type="ok" if self.conn.has_id(op.value) else "fail")
            if op.f == "refresh":
                self.conn.refresh()
                return op.assoc(type="ok")
            if op.f == "strong-read":
                return op.assoc(type="ok", value=self.conn.all_ids())
            raise ValueError(f"unknown f {op.f!r}")
        except TimeoutError as e:
            return op.assoc(type="info", error=str(e))
        except (ConnectionError, OSError) as e:
            return op.assoc(type="info", error=str(e))


def dirty_read_test(opts) -> dict:
    from jepsen_tpu import tests as tst
    from jepsen_tpu.suites._template import nemesis_schedule

    opts = dict(opts or {})
    nodes = opts.get("nodes") or ["n1", "n2", "n3", "n4", "n5"]
    wl = dirty_read_wl.workload(opts)
    test = dict(tst.noop_test(), **{
        "name": "elasticsearch dirty-read",
        "nodes": nodes,
        "concurrency": opts.get("concurrency", len(nodes)),
        "ssh": opts.get("ssh", {}),
        "db": ElasticsearchDB(),
        "net": net.iptables,
        "nemesis": nem.partition_random_halves(),
        "es-factory": opts.get("es-factory"),
        "client": EsDirtyReadClient(),
        "checker": ck.compose({"dirty-read": wl["checker"],
                               "perf": ck.perf()}),
    })
    nemesis_schedule(opts, test, gen.stagger(1 / 50, wl["generator"]),
                     final_gen=wl["final-generator"])
    return test


def set_test(opts) -> dict:
    from jepsen_tpu import client as client_mod
    from jepsen_tpu import tests as tst

    opts = dict(opts or {})
    nodes = opts.get("nodes") or ["n1", "n2", "n3", "n4", "n5"]
    wl = sets_wl.workload(opts)

    class Client(client_mod.Client):
        def __init__(self, conn_factory=EsHttpConn):
            self.conn_factory = conn_factory
            self.conn = None

        def open(self, test, node):
            out = Client(test.get("es-factory") or self.conn_factory)
            out.conn = out.conn_factory(node)
            return out

        def close(self, test):
            if self.conn is not None and hasattr(self.conn, "close"):
                self.conn.close()

        def invoke(self, test, op):
            try:
                if op.f == "add":
                    self.conn.add(op.value)
                    return op.assoc(type="ok")
                if op.f == "read":
                    return op.assoc(type="ok",
                                    value=self.conn.read_all())
                raise ValueError(f"unknown f {op.f!r}")
            except TimeoutError as e:
                return op.assoc(type="info", error=str(e))
            except (ConnectionError, OSError) as e:
                return op.assoc(type="info", error=str(e))

    return dict(tst.noop_test(), **{
        "name": "elasticsearch set",
        "nodes": nodes,
        "concurrency": opts.get("concurrency", len(nodes)),
        "ssh": opts.get("ssh", {}),
        "db": ElasticsearchDB(),
        "net": net.iptables,
        "nemesis": nem.partition_random_halves(),
        "es-factory": opts.get("es-factory"),
        "client": Client(),
        "generator": gen.phases(
            gen.time_limit(
                opts.get("time-limit", 60),
                gen.nemesis(
                    gen.start_stop(opts.get("nemesis-interval", 5),
                                   opts.get("nemesis-interval", 5)),
                    gen.stagger(1 / 10, wl["generator"]))),
            gen.nemesis(gen.once({"type": "info", "f": "stop"})),
            gen.sleep(opts.get("quiesce", 3)),
            gen.clients(wl["final-generator"])),
        "checker": ck.compose({"set": wl["checker"],
                               "perf": ck.perf()}),
    })


def reg_test(opts) -> dict:
    return register_test("elasticsearch register", ElasticsearchDB(),
                         KVRegisterClient(
                             (opts or {}).get("kv-factory")
                             or EsHttpConn), opts)


tests = {"set": set_test, "register": reg_test,
         "dirty-read": dirty_read_test}

test_for, _opt_fn, main = workload_main(tests, "set")

if __name__ == "__main__":
    main()
