"""CrateDB test suite (reference: `crate/src/jepsen/crate.clj` +
workloads, 1,060 LoC): SQL over an elasticsearch core — the
lost-updates hunt via `_version`-guarded UPDATEs (optimistic CC
register) and the sets workload over refreshed reads.  Speaks the
postgres wire protocol, so the conn reuses the cockroach shell-conn
hooks with crate's `crash` CLI."""

from __future__ import annotations

from jepsen_tpu import checker as ck
from jepsen_tpu import control as c
from jepsen_tpu import control_util as cu
from jepsen_tpu import db as db_mod
from jepsen_tpu import generator as gen
from jepsen_tpu import independent, net
from jepsen_tpu import nemesis as nem
from jepsen_tpu.control import lit
from jepsen_tpu.suites._template import (nemesis_schedule,
                                         workload_main)
from jepsen_tpu.suites.cockroach import (SQLClient, ShellConn,
                                         ensure_table, with_txn_retry,
                                         _rounded_concurrency)
from jepsen_tpu.workloads import linearizable_register as linreg_wl
from jepsen_tpu.workloads import dirty_read as dirty_read_wl
from jepsen_tpu.workloads import sets as sets_wl

DIR = "/opt/crate"
PSQL_PORT = 5432
HTTP_PORT = 4200


class CrateDB(db_mod.DB, db_mod.LogFiles):
    def setup(self, test, node):
        nodes = test.get("nodes") or [node]
        cfg = (f"cluster.name: jepsen\n"
               f"node.name: {node}\n"
               f"network.host: {node}\n"
               "discovery.seed_hosts: ["
               + ", ".join(nodes) + "]\n"
               "cluster.initial_master_nodes: ["
               + ", ".join(nodes[:3]) + "]\n")
        c.upload_str(cfg, f"{DIR}/config/crate.yml")
        cu.start_daemon(f"{DIR}/bin/crate", "-d",
                        "-p", f"{DIR}/crate.pid",
                        chdir=DIR, logfile=f"{DIR}/logs/jepsen.log",
                        pidfile=f"{DIR}/crate.pid")
        c.execute(lit(
            "for i in $(seq 1 120); do "
            f"curl -sf http://{node}:{HTTP_PORT}/ "
            "&& exit 0; sleep 1; done; exit 1"), check=False)

    def teardown(self, test, node):
        cu.stop_daemon(f"{DIR}/crate.pid", "crate")
        c.execute("rm", "-rf", f"{DIR}/data", check=False)

    def log_files(self, test, node):
        return [f"{DIR}/logs/jepsen.log"]


class CrateShellConn(ShellConn):
    """crash (crate shell) conn; crate has no multi-statement txns, so
    txn() degrades to sequential statements — the workloads used here
    (versioned register, sets) only need single statements."""

    def _cmd(self, q: str) -> list:
        return [f"{DIR}/bin/crash", "--hosts",
                f"http://{self.node}:{HTTP_PORT}", "--format", "tabular",
                "-c", q]

    def _parse(self, text: str) -> list:
        return [line.split("|")
                for line in (text or "").splitlines()
                if line and not line.startswith(("+", "SELECT",
                                                 "CREATE", "INSERT",
                                                 "UPDATE"))]

    def txn(self, stmts: list) -> list:
        rows = []
        for s in stmts:
            rows.extend(self.sql(s))
        return rows


class VersionedRegisterClient(SQLClient):
    """crate.clj lost-updates client: CAS via _version-guarded UPDATE
    (optimistic concurrency — the anomaly crate exhibited)."""

    DDL = ("CREATE TABLE IF NOT EXISTS registers "
           "(id INT PRIMARY KEY, val INT)")

    def _invoke(self, test, op):
        ensure_table(self.conn, test, self.DDL, "registers")
        k, v = op.value
        if op.f == "read":
            rows = with_txn_retry(lambda: self.conn.sql(
                "SELECT val FROM registers WHERE id = ?", (k,)))
            return op.assoc(type="ok", value=independent.tuple_(
                k, int(rows[0][0]) if rows else None))
        if op.f == "write":
            with_txn_retry(lambda: self.conn.sql(
                f"INSERT INTO registers (id, val) VALUES ({k}, {v}) "
                f"ON CONFLICT (id) DO UPDATE SET val = {v}"))
            return op.assoc(type="ok")
        if op.f == "cas":
            old, new = v
            versioned = getattr(self.conn, "cas", None)
            if versioned is not None:
                return op.assoc(
                    type="ok" if versioned(k, old, new) else "fail")
            rows = with_txn_retry(lambda: self.conn.sql(
                "SELECT _version FROM registers "
                f"WHERE id = {k} AND val = {old}"))
            if not rows:
                return op.assoc(type="fail")
            ver = rows[0][0]
            out = with_txn_retry(lambda: self.conn.sql(
                f"UPDATE registers SET val = {new} "
                f"WHERE id = {k} AND _version = {ver} "
                "RETURNING val"))
            return op.assoc(type="ok" if out else "fail")
        raise ValueError(f"unknown f {op.f!r}")


class SetsClient(SQLClient):
    DDL = "CREATE TABLE IF NOT EXISTS sets (val INT PRIMARY KEY)"

    def _invoke(self, test, op):
        ensure_table(self.conn, test, self.DDL, "sets")
        if op.f == "add":
            with_txn_retry(lambda: self.conn.sql(
                f"INSERT INTO sets (val) VALUES ({op.value})"))
            return op.assoc(type="ok")
        if op.f == "read":
            self.conn.sql("REFRESH TABLE sets")
            rows = with_txn_retry(
                lambda: self.conn.sql("SELECT val FROM sets"))
            return op.assoc(type="ok",
                            value=sorted(int(r[0]) for r in rows))
        raise ValueError(f"unknown f {op.f!r}")


def base(opts, name) -> dict:
    from jepsen_tpu import tests as tst

    nodes = opts.get("nodes") or ["n1", "n2", "n3", "n4", "n5"]
    return dict(tst.noop_test(), **{
        "name": f"crate {name}",
        "nodes": nodes,
        "concurrency": opts.get("concurrency", len(nodes)),
        "ssh": opts.get("ssh", {}),
        "db": CrateDB(),
        "net": net.iptables,
        "nemesis": nem.partition_random_halves(),
        "sql-factory": opts.get("sql-factory") or CrateShellConn,
    })


def register_test(opts) -> dict:
    opts = dict(opts or {})
    test = base(opts, "register")
    wl = linreg_wl.suite_workload(opts)
    test["concurrency"] = _rounded_concurrency(
        opts, wl["threads-per-key"])
    test["client"] = VersionedRegisterClient()
    test["checker"] = ck.compose({"linear": wl["checker"],
                                  "perf": ck.perf()})
    nemesis_schedule(opts, test, wl["generator"])
    return test


def sets_test(opts) -> dict:
    opts = dict(opts or {})
    test = base(opts, "sets")
    wl = sets_wl.workload(opts)
    test["client"] = SetsClient()
    test["checker"] = ck.compose({"set": wl["checker"],
                                  "perf": ck.perf()})
    nemesis_schedule(opts, test, gen.stagger(1 / 10, wl["generator"]),
                     final_gen=wl["final-generator"])
    return test


class LostUpdatesClient(SQLClient):
    """crate/lost_updates.clj: a map of keys -> sets of ints, updated
    by read-modify-write with a `_version` guard — the optimistic-CC
    pattern whose lost updates crate exhibited.  Ops carry independent
    [k, v] tuples."""

    DDL = ("CREATE TABLE IF NOT EXISTS lu_sets "
           "(id INT PRIMARY KEY, elements STRING)")

    def _invoke(self, test, op):
        import json as json_mod

        ensure_table(self.conn, test, self.DDL, "lu_sets")
        k, v = op.value
        if op.f == "read":
            self.conn.sql("REFRESH TABLE lu_sets")
            rows = with_txn_retry(lambda: self.conn.sql(
                f"SELECT elements FROM lu_sets WHERE id = {k}"))
            els = json_mod.loads(rows[0][0]) if rows else []
            return op.assoc(type="ok",
                            value=independent.tuple_(k, sorted(els)))
        if op.f == "add":
            rows = with_txn_retry(lambda: self.conn.sql(
                f"SELECT elements, _version FROM lu_sets WHERE id = {k}"))
            if rows:
                els = json_mod.loads(rows[0][0])
                ver = rows[0][1]
                els2 = json_mod.dumps(els + [v])
                out = with_txn_retry(lambda: self.conn.sql(
                    f"UPDATE lu_sets SET elements = '{els2}' "
                    f"WHERE id = {k} AND _version = {ver} "
                    "RETURNING id"))
                # 0 rows: someone else moved _version — the add
                # definitely did NOT happen
                return op.assoc(type="ok" if out else "fail")
            with_txn_retry(lambda: self.conn.sql(
                f"INSERT INTO lu_sets (id, elements) "
                f"VALUES ({k}, '{json_mod.dumps([v])}')"))
            return op.assoc(type="ok")
        raise ValueError(f"unknown f {op.f!r}")


def lost_updates_test(opts) -> dict:
    """Per-key adds under partitions, quiescence, then one final read
    per key; every acknowledged add must be in the final set
    (lost_updates.clj:107-148, checked by independent set checkers).

    Adds are a flat mix over keys — NOT per-key gen.phases inside a
    mix, whose Synchronize barriers would strand threads on different
    keys' barriers and run zero ops.  The final per-key reads ride
    nemesis_schedule's quiesced final phase."""
    opts = dict(opts or {})
    test = base(opts, "lost-updates")
    n_keys = int(opts.get("keys", 4))
    counter = [0]
    import random as _r
    import threading as _t
    lock = _t.Lock()

    def add(t, p):
        with lock:
            counter[0] += 1
            return {"type": "invoke", "f": "add",
                    "value": independent.tuple_(
                        _r.randrange(n_keys), counter[0])}

    final_reads = gen.gseq([
        {"type": "invoke", "f": "read",
         "value": independent.tuple_(k, None)} for k in range(n_keys)])
    test["client"] = LostUpdatesClient()
    test["checker"] = ck.compose({
        "set": independent.checker(ck.set_checker()),
        "perf": ck.perf()})
    nemesis_schedule(opts, test, gen.stagger(1 / 50, add),
                     final_gen=final_reads)
    return test


class VersionDivergenceClient(SQLClient):
    """crate/version_divergence.clj: reads return [value, _version];
    two reads at the same _version must agree on the value."""

    DDL = ("CREATE TABLE IF NOT EXISTS vd_registers "
           "(id INT PRIMARY KEY, val INT)")

    def _invoke(self, test, op):
        ensure_table(self.conn, test, self.DDL, "vd_registers")
        k, v = op.value
        if op.f == "read":
            rows = with_txn_retry(lambda: self.conn.sql(
                f"SELECT val, _version FROM vd_registers WHERE id = {k}"))
            val = ([int(rows[0][0]), int(rows[0][1])] if rows else None)
            return op.assoc(type="ok", value=independent.tuple_(k, val))
        if op.f == "write":
            with_txn_retry(lambda: self.conn.sql(
                f"INSERT INTO vd_registers (id, val) VALUES ({k}, {v}) "
                f"ON CONFLICT (id) DO UPDATE SET val = {v}"))
            return op.assoc(type="ok")
        raise ValueError(f"unknown f {op.f!r}")


class MultiVersionChecker(ck.Checker):
    """version_divergence.clj multiversion-checker: group ok reads by
    _version; every version must map to ONE value."""

    def check(self, test, history, opts=None):
        from jepsen_tpu.history import History

        by_version: dict = {}
        for o in History(history):
            if o.is_ok and o.f == "read" and o.value is not None:
                val_ver = o.value
                if isinstance(val_ver, (list, tuple)) and len(val_ver) == 2:
                    val, ver = val_ver
                    by_version.setdefault(ver, set()).add(val)
        multis = {ver: sorted(vals) for ver, vals in by_version.items()
                  if len(vals) > 1}
        return {"valid?": not multis, "multis": multis}


def version_divergence_test(opts) -> dict:
    opts = dict(opts or {})
    test = base(opts, "version-divergence")
    import random as _r

    def r(t, p):
        return {"type": "invoke", "f": "read",
                "value": independent.tuple_(_r.randrange(
                    int(opts.get("keys", 4))), None)}

    counter = [0]
    import threading as _t
    lock = _t.Lock()

    def w(t, p):
        with lock:
            counter[0] += 1
            return {"type": "invoke", "f": "write",
                    "value": independent.tuple_(
                        _r.randrange(int(opts.get("keys", 4))),
                        counter[0])}

    test["client"] = VersionDivergenceClient()
    test["checker"] = ck.compose({
        "multi": independent.checker(MultiVersionChecker()),
        "perf": ck.perf()})
    nemesis_schedule(opts, test, gen.stagger(1 / 50, gen.mix([r, w])))
    return test


class DirtyReadClient(SQLClient):
    """crate/dirty_read.clj client over the SQL conn."""

    DDL = "CREATE TABLE IF NOT EXISTS dirty_read (id INT PRIMARY KEY)"

    def _invoke(self, test, op):
        ensure_table(self.conn, test, self.DDL, "dirty_read")
        if op.f == "write":
            with_txn_retry(lambda: self.conn.sql(
                f"INSERT INTO dirty_read (id) VALUES ({op.value})"))
            return op.assoc(type="ok")
        if op.f == "read":
            rows = with_txn_retry(lambda: self.conn.sql(
                f"SELECT id FROM dirty_read WHERE id = {op.value}"))
            return op.assoc(type="ok" if rows else "fail")
        if op.f == "refresh":
            self.conn.sql("REFRESH TABLE dirty_read")
            return op.assoc(type="ok")
        if op.f == "strong-read":
            self.conn.sql("REFRESH TABLE dirty_read")
            rows = with_txn_retry(lambda: self.conn.sql(
                "SELECT id FROM dirty_read"))
            return op.assoc(type="ok",
                            value=sorted(int(r0[0]) for r0 in rows))
        raise ValueError(f"unknown f {op.f!r}")


def dirty_read_test(opts) -> dict:
    opts = dict(opts or {})
    test = base(opts, "dirty-read")
    wl = dirty_read_wl.workload(opts)
    test["client"] = DirtyReadClient()
    test["checker"] = ck.compose({"dirty-read": wl["checker"],
                                  "perf": ck.perf()})
    nemesis_schedule(opts, test, gen.stagger(1 / 50, wl["generator"]),
                     final_gen=wl["final-generator"])
    return test


tests = {"register": register_test, "sets": sets_test,
         "lost-updates": lost_updates_test,
         "version-divergence": version_divergence_test,
         "dirty-read": dirty_read_test}

test_for, _opt_fn, main = workload_main(tests, "register")

if __name__ == "__main__":
    main()
