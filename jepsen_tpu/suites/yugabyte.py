"""YugabyteDB test suite (reference: `yugabyte/src/yugabyte/` — 1,700
LoC: core.clj, nemesis.clj, auto.clj plus per-workload files), whose
distinctive features are:

  * two-daemon automation  — every node runs a yb-master (first 3
                             nodes) and a yb-tserver; killers target
                             each daemon separately (nemesis.clj:28-58)
  * string-keyed nemesis registry — each entry bundles {nemesis,
                             generator, final-generator,
                             max-clock-skew-ms} (nemesis.clj:122-166)
  * strobe-rest cadence    — bursts of 3 start/stop pairs then a long
                             pause (nemesis.clj:60-83)
  * healing + quiescence   — tests with a final client generator heal
                             the cluster, wait for quiescence, then run
                             the final reads (core.clj:33-45)
  * workloads              — bank, counter, long-fork, multi-key-acid,
                             set, single-key-acid (core.clj:1-60)

YSQL speaks the postgres wire protocol, so the SQL client machinery is
shared with the cockroach suite (suites/cockroach.py SQLClient /
with_txn_retry / the injectable conn boundary); only the shell driver
and the automation differ.
"""

from __future__ import annotations

import random

from jepsen_tpu import checker as ck
from jepsen_tpu import cli
from jepsen_tpu import control as c
from jepsen_tpu import control_util as cu
from jepsen_tpu import db as db_mod
from jepsen_tpu import generator as gen
from jepsen_tpu import nemesis as nem, net
from jepsen_tpu import nemesis_time as nt
from jepsen_tpu.control import lit
from jepsen_tpu.suites.cockroach import (BankClient, RegisterClient,
                                         SQLClient, SetsClient,
                                         ShellConn, ensure_table,
                                         with_txn_retry,
                                         _rounded_concurrency)
from jepsen_tpu.workloads import (bank as bank_wl, counter as counter_wl,
                                  linearizable_register as linreg_wl,
                                  list_append as list_append_wl,
                                  long_fork as long_fork_wl,
                                  multi_key_acid as mka_wl,
                                  sets as sets_wl)

# ---------------------------------------------------------------------------
# auto — two-daemon cluster automation (auto.clj)
# ---------------------------------------------------------------------------

VERSION = "2.20.1.0"
URL = (f"https://downloads.yugabyte.com/releases/{VERSION}/"
       f"yugabyte-{VERSION}-b97-linux-x86_64.tar.gz")
DIR = "/opt/yugabyte"
MASTER_LOG = f"{DIR}/master.log"
TSERVER_LOG = f"{DIR}/tserver.log"
MASTER_PID = f"{DIR}/master.pid"
TSERVER_PID = f"{DIR}/tserver.pid"
MASTER_RPC_PORT = 7100
TSERVER_RPC_PORT = 9100
YSQL_PORT = 5433
N_MASTERS = 3


def master_nodes(test) -> list:
    """The first three nodes host masters (auto.clj master quorum)."""
    return (test.get("nodes") or [])[:N_MASTERS]


def master_addresses(test) -> str:
    return ",".join(f"{n}:{MASTER_RPC_PORT}" for n in master_nodes(test))


def start_master(test, node) -> None:
    """auto.clj start-master!"""
    cu.start_daemon(
        f"{DIR}/bin/yb-master",
        "--master_addresses", master_addresses(test),
        "--rpc_bind_addresses", f"{node}:{MASTER_RPC_PORT}",
        "--fs_data_dirs", f"{DIR}/data/master",
        chdir=DIR, logfile=MASTER_LOG, pidfile=MASTER_PID)


def start_tserver(test, node) -> None:
    """auto.clj start-tserver!"""
    cu.start_daemon(
        f"{DIR}/bin/yb-tserver",
        "--tserver_master_addrs", master_addresses(test),
        "--rpc_bind_addresses", f"{node}:{TSERVER_RPC_PORT}",
        "--enable_ysql",
        "--pgsql_proxy_bind_address", f"{node}:{YSQL_PORT}",
        "--fs_data_dirs", f"{DIR}/data/tserver",
        chdir=DIR, logfile=TSERVER_LOG, pidfile=TSERVER_PID)


def kill_daemon(process: str, signal: str = "9") -> str:
    """nemesis.clj kill! :14-20 — pkill then verify it's gone: the
    verification must raise if the process survived (e.g. respawned by
    a supervisor), or kill-based nemeses silently inject nothing."""
    cu.grepkill(process, signal=signal)
    c.execute(lit(f"! ps -ce | grep {process}"))
    return "killed"


def stop_master(test, node) -> str:
    return kill_daemon("yb-master")


def stop_tserver(test, node) -> str:
    return kill_daemon("yb-tserver")


class YugabyteDB(db_mod.DB, db_mod.LogFiles):
    """Community-edition DB: master (first 3 nodes) + tserver per node
    (auto.clj community-edition)."""

    def setup(self, test, node):
        cu.install_archive(URL, DIR)
        nt.install(test, node)
        if node in master_nodes(test):
            start_master(test, node)
        start_tserver(test, node)
        c.execute(lit(
            "for i in $(seq 1 60); do "
            f"{DIR}/bin/ysqlsh -h {node} -p {YSQL_PORT} -c 'select 1' "
            "> /dev/null 2>&1 && exit 0; sleep 1; done; exit 1"),
            check=False)

    def teardown(self, test, node):
        kill_daemon("yb-tserver")
        kill_daemon("yb-master")
        c.execute("rm", "-rf", f"{DIR}/data", check=False)

    def log_files(self, test, node):
        return [MASTER_LOG, TSERVER_LOG]


class YsqlShellConn(ShellConn):
    """ysqlsh-over-control-plane connection: cockroach's ShellConn with
    the command + row-parsing hooks swapped.  -q -At suppresses command
    tags (BEGIN/COMMIT/UPDATE n) and headers so every output line is a
    data row."""

    ts_expr = "(EXTRACT(EPOCH FROM clock_timestamp()) * 1e6)::BIGINT"

    def _cmd(self, q: str) -> list:
        return [f"{DIR}/bin/ysqlsh", "-h", self.node,
                "-p", str(YSQL_PORT), "-q", "-At", "-c", q]

    def _parse(self, text: str) -> list:
        return [line.split("|")
                for line in (text or "").splitlines() if line]


# ---------------------------------------------------------------------------
# Workload clients beyond the shared SQL ones
# ---------------------------------------------------------------------------

class CounterClient(SQLClient):
    """counter workload: blind increments + reads of one row."""

    DDL = "CREATE TABLE IF NOT EXISTS counter (id INT PRIMARY KEY, c INT)"

    def _invoke(self, test, op):
        ensure_table(self.conn, test, self.DDL, "counter")
        if op.f == "add":
            amt = op.value if op.value is not None else 1
            with_txn_retry(lambda: self.conn.sql(
                f"INSERT INTO counter (id, c) VALUES (0, {amt}) "
                f"ON CONFLICT (id) DO UPDATE SET c = counter.c + {amt}"))
            return op.assoc(type="ok")
        if op.f == "read":
            rows = with_txn_retry(
                lambda: self.conn.sql("SELECT c FROM counter WHERE id = 0"))
            val = int(rows[0][0]) if rows else 0
            return op.assoc(type="ok", value=val)
        raise ValueError(f"unknown f {op.f!r}")


class LongForkClient(SQLClient):
    """long-fork workload: micro-op txns [["w", k, v]] /
    [["r", k, None], ...] over one table — reads of a group must agree
    on write order (long_fork.clj)."""

    DDL = "CREATE TABLE IF NOT EXISTS lf (key INT PRIMARY KEY, val INT)"

    def _invoke(self, test, op):
        ensure_table(self.conn, test, self.DDL, "lf")
        txn = op.value
        if op.f == "write":
            (_, k, v), = txn
            with_txn_retry(lambda: self.conn.txn([
                f"INSERT INTO lf (key, val) VALUES ({k}, {v}) "
                f"ON CONFLICT (key) DO UPDATE SET val = {v}"]))
            return op.assoc(type="ok")
        if op.f == "read":
            # The whole group read MUST be one atomic snapshot (the
            # point of long-fork); a single statement is atomic on any
            # conn, so never fall back to per-key transactions.
            ks = [k for _, k, _ in txn]
            in_list = ", ".join(str(k) for k in ks)
            rows = with_txn_retry(lambda: self.conn.txn(
                [f"SELECT key, val FROM lf WHERE key IN ({in_list})"]))
            got = {int(r[0]): int(r[1]) for r in rows}
            filled = [["r", k, got.get(k)] for k in ks]
            return op.assoc(type="ok", value=filled)
        raise ValueError(f"unknown f {op.f!r}")


class ElleListAppendClient(SQLClient):
    """Elle list-append txns (yugabyte speaks postgres SQL): lists as
    comma-joined text, one micro-op per statement, whole txn atomic in
    one conn.txn; scalar-subquery reads align rows with mops by
    position."""

    DDL = ("CREATE TABLE IF NOT EXISTS elle_la "
           "(k INT PRIMARY KEY, val TEXT)")

    def _invoke(self, test, op):
        ensure_table(self.conn, test, self.DDL, "elle_la")
        txn = list(op.value or [])
        stmts = []
        for f, k, v in txn:
            if f == "append":
                stmts.append(
                    f"INSERT INTO elle_la (k, val) VALUES ({k}, '{v}') "
                    f"ON CONFLICT (k) DO UPDATE SET val = "
                    f"val || ',{v}'")
            else:
                stmts.append(f"SELECT {k}, (SELECT val FROM elle_la "
                             f"WHERE k = {k})")
        rows = with_txn_retry(lambda: self.conn.txn(stmts))
        reads = iter(rows)
        out = []
        for f, k, v in txn:
            if f != "r":
                out.append([f, k, v])
                continue
            row = next(reads, None)
            val = row[1] if row is not None and len(row) > 1 else None
            if val in (None, ""):
                out.append([f, k, None])
            else:
                out.append([f, k, [int(x) for x in
                                   str(val).split(",") if x != ""]])
        return op.assoc(type="ok", value=out)


class MultiKeyAcidClient(SQLClient):
    """multi-key-acid: one txn writes BOTH keys of a pair to the same
    value; reads fetch both in one txn
    (yugabyte/src/yugabyte/multi_key_acid.clj)."""

    DDL = "CREATE TABLE IF NOT EXISTS mka (k INT PRIMARY KEY, v INT)"
    KEYS = (0, 1)

    def _invoke(self, test, op):
        ensure_table(self.conn, test, self.DDL, "mka")
        if op.f == "write":
            v = op.value
            stmts = [f"INSERT INTO mka (k, v) VALUES ({k}, {v}) "
                     f"ON CONFLICT (k) DO UPDATE SET v = {v}"
                     for k in self.KEYS]
            with_txn_retry(lambda: self.conn.txn(stmts))
            return op.assoc(type="ok")
        if op.f == "read":
            # Both keys in ONE statement — separate per-key txns would
            # let a write commit between them and fake a fractured read
            # on a healthy database.
            in_list = ", ".join(str(k) for k in self.KEYS)
            rows = with_txn_retry(lambda: self.conn.txn(
                [f"SELECT k, v FROM mka WHERE k IN ({in_list})"]))
            got = {int(r[0]): int(r[1]) for r in rows}
            return op.assoc(type="ok",
                            value=[got.get(k) for k in self.KEYS])
        raise ValueError(f"unknown f {op.f!r}")


class SingleKeyAcidClient(RegisterClient):
    """single-key-acid = independent keyed registers; the shared SQL
    register client already speaks [k, v] KV ops."""


# ---------------------------------------------------------------------------
# Nemesis registry (nemesis.clj:122-166)
# ---------------------------------------------------------------------------

nemesis_delay = 5     # scaled-down from the reference's 50s for CI
nemesis_duration = 5


def strobe_rest():
    """3 × (sleep, start, sleep, stop) then a long rest
    (nemesis.clj strobe/strobe-rest :60-75)."""
    t = nemesis_delay / 5
    while True:
        for _ in range(3):
            yield gen.sleep(t)
            yield lambda tst, p: {"type": "info", "f": "start"}
            yield gen.sleep(t)
            yield lambda tst, p: {"type": "info", "f": "stop"}
        yield gen.sleep(2 * t)


def gen_start_stop():
    """nemesis.clj gen-start-stop :77-83."""
    return gen.gseq(strobe_rest())


def _rand_node(nodes):
    return [random.choice(list(nodes))]


def tserver_killer(signal: str = "TERM"):
    """Kills a random node's tserver on start, restarts on stop
    (nemesis.clj:28-34)."""
    return nem.node_start_stopper(
        _rand_node,
        lambda test, node: kill_daemon("yb-tserver", signal),
        lambda test, node: start_tserver(test, node))


def master_killer(signal: str = "TERM"):
    """nemesis.clj:36-42 — only targets master-bearing nodes."""
    return nem.node_start_stopper(
        lambda test, nodes: _rand_node(master_nodes(test)),
        lambda test, node: kill_daemon("yb-master", signal),
        lambda test, node: start_master(test, node))


def node_killer(signal: str = "TERM"):
    """nemesis.clj:44-58 — both daemons."""
    def stop_all(test, node):
        kill_daemon("yb-tserver", signal)
        kill_daemon("yb-master", signal)
        return "killed"

    def start_all(test, node):
        if node in master_nodes(test):
            start_master(test, node)
        start_tserver(test, node)
        return "started"
    return nem.node_start_stopper(_rand_node, stop_all, start_all)


def clock_nemesis_entry(max_skew_ms: int) -> dict:
    """nemesis.clj clock-nemesis :116-127: random resets/bumps capped
    to max_skew_ms, clock nemesis client, reset on final."""
    def bump(test, process):
        o = nt.bump_gen(test, process)
        val = {n: max(-max_skew_ms, min(max_skew_ms, int(d)))
               for n, d in (o.get("value") or {}).items()}
        o = dict(o)
        o["value"] = val
        return o

    return {
        "nemesis": lambda: nt.clock_nemesis(),
        "generator": lambda: gen.delay(
            nemesis_delay, gen.mix([nt.reset_gen] + [bump] * 3)),
        "final-generator": lambda: gen.once(nt.reset_gen),
        "max-clock-skew-ms": max_skew_ms,
    }


def start_stop_entry(nemesis_fn) -> dict:
    """nemesis.clj start-stop :85-91."""
    return {
        "nemesis": nemesis_fn,
        "generator": gen_start_stop,
        "final-generator": lambda: gen.once(
            {"type": "info", "f": "stop"}),
        "max-clock-skew-ms": 0,
    }


nemeses = {
    "none": {"nemesis": lambda: nem.Noop(),
             "generator": lambda: gen.void,
             "final-generator": lambda: gen.void,
             "max-clock-skew-ms": 0},
    "start-stop-tserver": start_stop_entry(lambda: tserver_killer()),
    "start-kill-tserver": start_stop_entry(lambda: tserver_killer("9")),
    "start-stop-master": start_stop_entry(lambda: master_killer()),
    "start-kill-master": start_stop_entry(lambda: master_killer("9")),
    "start-stop-node": start_stop_entry(lambda: node_killer()),
    "start-kill-node": start_stop_entry(lambda: node_killer("9")),
    "partition-random-halves": start_stop_entry(
        nem.partition_random_halves),
    "partition-random-node": start_stop_entry(
        nem.partition_random_node),
    "partition-majorities-ring": start_stop_entry(
        nem.partition_majorities_ring),
    "small-skew": clock_nemesis_entry(100),
    "medium-skew": clock_nemesis_entry(250),
    "large-skew": clock_nemesis_entry(500),
    "xlarge-skew": clock_nemesis_entry(1000),
}


# ---------------------------------------------------------------------------
# Test construction (core.clj yugabyte-test :29-57)
# ---------------------------------------------------------------------------

def yugabyte_test(opts) -> dict:
    """Merge a workload's client generator with the nemesis schedule;
    when the workload has a final generator, append the reference's
    heal -> quiesce -> final-read phases (core.clj:33-45)."""
    from jepsen_tpu import tests as tst

    opts = dict(opts or {})
    av = opts.get("argv-options") or {}
    for key in ("workload", "nemesis"):
        if key not in opts and av.get(key) is not None:
            opts[key] = av[key]
    wname = opts.get("workload") or "single-key-acid"
    nname = opts.get("nemesis") or "none"
    if isinstance(nname, list):
        nname = nname[0]
    try:
        builder = workloads[wname]
    except KeyError:
        raise ValueError(
            f"unknown workload {wname!r}; one of {sorted(workloads)}")
    try:
        nentry = nemeses[nname]
    except KeyError:
        raise ValueError(
            f"unknown nemesis {nname!r}; one of {sorted(nemeses)}")

    nodes = opts.get("nodes") or ["n1", "n2", "n3", "n4", "n5"]
    test = dict(tst.noop_test(), **{
        "name": f"yugabyte {wname} {nname}",
        "nodes": nodes,
        "concurrency": opts.get("concurrency", len(nodes)),
        "ssh": opts.get("ssh", {}),
        "db": YugabyteDB(),
        "net": net.iptables,
        "nemesis": nentry["nemesis"](),
        "max-clock-skew-ms": nentry["max-clock-skew-ms"],
        "sql-factory": opts.get("sql-factory") or YsqlShellConn,
    })
    wl = builder(opts, test)

    during = gen.time_limit(
        opts.get("time-limit", 60),
        gen.nemesis(nentry["generator"](), wl["generator"]))
    if wl.get("final-generator") is not None:
        test["generator"] = gen.phases(
            during,
            gen.log("Healing cluster"),
            gen.nemesis(nentry["final-generator"](), gen.void),
            gen.log("Waiting for quiescence"),
            gen.sleep(opts.get("quiesce", 3)),
            gen.clients(wl["final-generator"]))
    else:
        test["generator"] = gen.phases(
            during,
            gen.nemesis(nentry["final-generator"](), gen.void))
    test["client"] = wl["client"]
    test["checker"] = wl["checker"]
    test.update(wl.get("test-keys") or {})
    return test


def _bank(opts, test) -> dict:
    wl = bank_wl.workload(opts)
    return {"client": BankClient(), "generator": wl["generator"],
            "final-generator": gen.once(bank_wl.read_gen),
            "checker": ck.compose({"bank": wl["checker"],
                                   "perf": ck.perf()}),
            "test-keys": {k: wl[k] for k in
                          ("accounts", "total-amount", "max-transfer")}}


def _counter(opts, test) -> dict:
    wl = counter_wl.workload(opts)
    return {"client": CounterClient(), "generator": wl["generator"],
            "final-generator": wl["final-generator"],
            "checker": ck.compose({"counter": wl["checker"],
                                   "perf": ck.perf()})}


def _long_fork(opts, test) -> dict:
    wl = long_fork_wl.workload(opts)
    return {"client": LongForkClient(), "generator": wl["generator"],
            "final-generator": None,
            "checker": ck.compose({"long-fork": wl["checker"],
                                   "perf": ck.perf()})}


def _multi_key_acid(opts, test) -> dict:
    wl = mka_wl.workload(opts)
    return {"client": MultiKeyAcidClient(), "generator": wl["generator"],
            "final-generator": gen.once(mka_wl.read),
            "checker": ck.compose({"mka": wl["checker"],
                                   "perf": ck.perf()})}


def _set(opts, test) -> dict:
    wl = sets_wl.workload(opts)
    return {"client": SetsClient(), "generator": wl["generator"],
            "final-generator": wl["final-generator"],
            "checker": ck.compose({"set": wl["checker"],
                                   "perf": ck.perf()})}


def _single_key_acid(opts, test) -> dict:
    wl = linreg_wl.suite_workload(opts)
    test["concurrency"] = _rounded_concurrency(
        opts, wl["threads-per-key"])
    return {"client": SingleKeyAcidClient(),
            "generator": wl["generator"],
            "final-generator": None,
            "checker": ck.compose({"linear": wl["checker"],
                                   "perf": ck.perf()})}


def _list_append(opts, test) -> dict:
    wl = list_append_wl.workload(opts)
    return {"client": ElleListAppendClient(),
            "generator": wl["generator"],
            "final-generator": None,
            "checker": ck.compose({"elle": wl["checker"],
                                   "perf": ck.perf()})}


workloads = {
    "bank": _bank,
    "counter": _counter,
    "list-append": _list_append,
    "long-fork": _long_fork,
    "multi-key-acid": _multi_key_acid,
    "set": _set,
    "single-key-acid": _single_key_acid,
}


def _opt_fn(parser):
    parser.add_argument("--workload", default="single-key-acid",
                        choices=sorted(workloads),
                        help="which workload to run")
    parser.add_argument("--nemesis", default="none",
                        choices=sorted(nemeses), metavar="NAME",
                        help="nemesis: " + ", ".join(sorted(nemeses)))


def main(argv=None):
    cli.run(cli.single_test_cmd(yugabyte_test, _opt_fn), argv)


if __name__ == "__main__":
    main()
