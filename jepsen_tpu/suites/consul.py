"""Consul test suite (reference: `consul/src/jepsen/consul.clj`,
146 LoC): single-binary agent with one bootstrap server, linearizable
register over the KV HTTP API (`?cas=<ModifyIndex>` conditional
writes), partition nemesis."""

from __future__ import annotations

import json
from typing import Optional

from jepsen_tpu import control as c
from jepsen_tpu import control_util as cu
from jepsen_tpu import db as db_mod
from jepsen_tpu.control import lit
from jepsen_tpu.suites._template import (KVRegisterClient,
                                         register_test, simple_main)

VERSION = "1.17.0"
URL = (f"https://releases.hashicorp.com/consul/{VERSION}/"
       f"consul_{VERSION}_linux_amd64.zip")
DIR = "/opt/consul"
DATA = f"{DIR}/data"
PIDFILE = f"{DIR}/consul.pid"
LOGFILE = f"{DIR}/consul.log"
HTTP_PORT = 8500


class ConsulDB(db_mod.DB, db_mod.LogFiles):
    """consul.clj db: first node bootstraps, the rest join it."""

    def setup(self, test, node):
        cu.install_archive(URL, DIR)
        first = (test.get("nodes") or [node])[0]
        args = [f"{DIR}/consul", "agent", "-server",
                "-data-dir", DATA, "-bind", node,
                "-client", "0.0.0.0", "-node", node]
        if node == first:
            args += ["-bootstrap-expect", "1"]
        else:
            args += ["-retry-join", first]
        cu.start_daemon(*args, chdir=DIR, logfile=LOGFILE,
                        pidfile=PIDFILE)
        c.execute(lit(
            "for i in $(seq 1 60); do "
            f"curl -sf http://{node}:{HTTP_PORT}/v1/status/leader "
            "| grep -q : && exit 0; sleep 1; done; exit 1"),
            check=False)

    def teardown(self, test, node):
        cu.stop_daemon(PIDFILE, f"{DIR}/consul")
        c.execute("rm", "-rf", DATA, check=False)

    def log_files(self, test, node):
        return [LOGFILE]


class ConsulHttpConn:
    """KV API over the control plane: GET /v1/kv/<k>, PUT with
    ?cas=<ModifyIndex> for the conditional write (consul.clj cas!)."""

    def __init__(self, node: str):
        self.node = node
        self._session = c.session(node)

    def _curl(self, *args) -> str:
        with c.with_session(self.node, self._session):
            return c.execute("curl", "-sf", *args, check=False)

    def _kv(self, k) -> Optional[dict]:
        out = self._curl(
            f"http://{self.node}:{HTTP_PORT}/v1/kv/jepsen-r{k}")
        try:
            rows = json.loads(out or "[]")
        except ValueError:
            return None
        return rows[0] if rows else None

    def get(self, k) -> Optional[int]:
        import base64
        kv = self._kv(k)
        if not kv or kv.get("Value") is None:
            return None
        return int(base64.b64decode(kv["Value"]).decode())

    def put(self, k, v) -> None:
        self._curl("-X", "PUT", "-d", str(v),
                   f"http://{self.node}:{HTTP_PORT}/v1/kv/jepsen-r{k}")

    def cas(self, k, old, new) -> bool:
        kv = self._kv(k)
        if kv is None:
            return False
        import base64
        cur = (int(base64.b64decode(kv["Value"]).decode())
               if kv.get("Value") is not None else None)
        if cur != old:
            return False
        out = self._curl(
            "-X", "PUT", "-d", str(new),
            f"http://{self.node}:{HTTP_PORT}/v1/kv/jepsen-r{k}"
            f"?cas={kv['ModifyIndex']}")
        return (out or "").strip() == "true"

    def close(self):
        self._session.close()


def consul_test(opts) -> dict:
    return register_test("consul", ConsulDB(), KVRegisterClient(
        (opts or {}).get("kv-factory") or ConsulHttpConn), opts)


main = simple_main(consul_test)

if __name__ == "__main__":
    main()
