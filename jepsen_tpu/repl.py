"""Interactive-session helpers (reference: `jepsen/src/jepsen/repl.clj`,
13 LoC): convenience accessors for poking at stored tests from a Python
REPL or notebook.

    >>> from jepsen_tpu import repl
    >>> t = repl.last_test()
    >>> t["results"]["valid?"]
"""

from __future__ import annotations

from typing import Optional

from jepsen_tpu import store


def last_test() -> Optional[dict]:
    """The most recently run test, loaded from the store
    (repl.clj last-test :7-12)."""
    return store.latest()


def last_history() -> Optional[list]:
    """The most recent test's history, or None."""
    t = last_test()
    return t.get("history") if t else None


def last_results() -> Optional[dict]:
    """The most recent test's checker results, or None."""
    t = last_test()
    return t.get("results") if t else None
