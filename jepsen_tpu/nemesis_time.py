"""Clock-manipulation nemesis (reference:
`jepsen/src/jepsen/nemesis/time.clj`): upload C clock tools, compile
them **on the db node** with gcc, and drive clock jumps / strobes /
resets from the nemesis, recording observed per-node clock offsets onto
ops so the clock checker can plot them.
"""

from __future__ import annotations

import logging
import random
import time
from pathlib import Path

from jepsen_tpu import control as c
from jepsen_tpu import generator as gen
from jepsen_tpu import nemesis as nem
from jepsen_tpu.control import lit

log = logging.getLogger("jepsen.nemesis.time")

RESOURCES = Path(__file__).parent / "resources"
TOOL_DIR = "/opt/jepsen"
TOOLS = ["bump_time", "strobe_time"]


def compile_tool(source_name: str) -> None:
    """Upload a .c source and build it on the node if the binary isn't
    there yet (nemesis/time.clj compile! :14-41)."""
    binary = f"{TOOL_DIR}/{source_name}"
    out = c.execute(lit(f"test -x {c.escape(binary)} && echo built"),
                    check=False)
    if out.strip() == "built":
        return
    c.execute("mkdir", "-p", TOOL_DIR)
    src = f"{binary}.c"
    c.upload(str(RESOURCES / f"{source_name}.c"), src)
    c.execute("gcc", "-O2", "-o", binary, src)


def install(test=None, node=None) -> None:
    """Compile all clock tools on the current node (time.clj install!
    :43)."""
    for t in TOOLS:
        compile_tool(t)


def bump_time(delta_ms: float) -> str:
    """One-shot wall-clock jump by delta ms (time.clj bump-time! :77)."""
    return c.execute(f"{TOOL_DIR}/bump_time", int(delta_ms))


def strobe_time(delta_ms: float, period_ms: float, duration_s: float) -> str:
    """Flip the clock between 0 and +delta every period, for duration
    (time.clj strobe-time! :83)."""
    return c.execute(f"{TOOL_DIR}/strobe_time", int(delta_ms),
                     int(period_ms), int(duration_s))


def reset_time(test=None) -> None:
    """Snap the clock back to real time (time.clj reset-time! :70):
    ntpdate against the test's ntp server when configured, else no-op
    with a warning."""
    server = (test or {}).get("ntp-server")
    if server:
        c.execute("ntpdate", "-b", server)
    else:
        c.execute("ntpdate", "-b", "pool.ntp.org", check=False)


def clock_offset_s() -> float:
    """Observed node wall clock minus control wall clock, seconds
    (time.clj current-offset)."""
    remote = float(c.execute("date", "+%s.%N"))
    # lint: wall-ok(the node-vs-control wall offset IS the measurement)
    return remote - time.time()


class ClockNemesis(nem.Nemesis):
    """Drives :reset / :bump / :strobe / :check-offsets ops
    (time.clj clock-nemesis :89-135).  Ops:

        {f: "reset",  value: [nodes...] or None}
        {f: "bump",   value: {node: delta_ms}}
        {f: "strobe", value: {"delta": ms, "period": ms, "duration": s}}
        {f: "check-offsets"}

    Every completion gets a {node: offset_s} map under
    op.extra["clock-offsets"].

    Bumps and strobes are registered in the test's fault ledger BEFORE
    injection (register-before-inject, ISSUE 15), with the reset-all
    heal as the undo: a nemesis that dies mid-skew still gets every
    clock snapped back by the run_case backstop, and a reset op (or
    teardown) resolves the entry so campaign.assert_empty stays
    clean."""

    LEDGER_KEY = "nemesis.clock"

    def setup(self, test):
        c.on_nodes(test, lambda t, n: install(t, n))
        try:
            c.on_nodes(test, lambda t, n: reset_time(t))
        except Exception as e:
            log.warning("initial clock reset failed: %s", e)
        return self

    def _reset_all(self, test):
        try:
            c.on_nodes(test, lambda t, n: reset_time(t))
        except Exception as e:
            log.warning("clock reset failed: %s", e)

    def invoke(self, test, op):
        f = op.f
        if f == "reset":
            nodes = op.value or test.get("nodes")
            c.on_nodes(test, lambda t, n: reset_time(t), nodes)
            nem.ledger(test).resolve(self.LEDGER_KEY)
        elif f == "bump":
            deltas = op.value or {}
            nem.ledger(test).register(self.LEDGER_KEY,
                                      lambda: self._reset_all(test),
                                      {"bump-ms": dict(deltas)})
            c.on_nodes(test,
                       lambda t, n: bump_time(deltas.get(n, 0)),
                       list(deltas))
        elif f == "strobe":
            v = op.value or {}
            nem.ledger(test).register(self.LEDGER_KEY,
                                      lambda: self._reset_all(test),
                                      {"strobe": dict(v)})
            c.on_nodes(test, lambda t, n: strobe_time(
                v.get("delta", 200), v.get("period", 10),
                v.get("duration", 10)))
        elif f == "check-offsets":
            pass
        else:
            raise ValueError(f"unknown clock op {f!r}")
        offsets = c.on_nodes(test, lambda t, n: _safe_offset())
        return op.assoc(**{"clock-offsets": offsets})

    def teardown(self, test):
        self._reset_all(test)
        nem.ledger(test).resolve(self.LEDGER_KEY)


def _safe_offset():
    try:
        return clock_offset_s()
    except Exception:
        return None


def clock_nemesis() -> ClockNemesis:
    return ClockNemesis()


# ---------------------------------------------------------------------------
# Generators (time.clj:137-173)
# ---------------------------------------------------------------------------

def reset_gen(test, process):
    return {"type": "info", "f": "reset", "value": None}


def bump_gen(test, process):
    nodes = test.get("nodes") or []
    deltas = {n: random.randrange(-262144, 262144)
              for n in random.sample(nodes, max(1, len(nodes) // 2))}
    return {"type": "info", "f": "bump", "value": deltas}


def strobe_gen(test, process):
    return {"type": "info", "f": "strobe",
            "value": {"delta": random.randrange(1, 262144),
                      "period": random.randrange(1, 1024),
                      "duration": random.randrange(1, 32)}}


def clock_gen():
    """Mix of resets, bumps and strobes (time.clj clock-gen :165-173)."""
    return gen.mix([reset_gen, bump_gen, strobe_gen])
