"""The baseline ratchet: findings can only go DOWN.

`store/ci/lint-baseline.json` holds the accepted finding counts keyed
`rule::path::qualname` (stable across unrelated line churn).  The
tier-1 lint test fails on any finding NOT covered by the baseline —
never on pre-existing ones — so adopting a new rule is not a flag day:
commit the found set as the baseline, then shrink it as fixes land.
Shrinking is a one-line diff; growing it is a reviewable decision.

Format:

    {"version": 1,
     "findings": {"<rule>::<path>::<qualname>": <count>, ...}}
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Optional

__all__ = ["baseline_path", "load", "counts", "new_findings", "write"]


def baseline_path(root=None) -> Path:
    from jepsen_tpu.lint.engine import default_root
    root = Path(root) if root is not None else default_root()
    return root / "store" / "ci" / "lint-baseline.json"


def load(path=None) -> dict:
    """{key: count}; a missing baseline is the empty (strictest)
    baseline, so a fresh tree starts fully ratcheted."""
    p = Path(path) if path is not None else baseline_path()
    if not p.exists():
        return {}
    with open(p) as f:
        d = json.load(f)
    out = d.get("findings", d) if isinstance(d, dict) else {}
    return {str(k): int(v) for k, v in out.items()}


def counts(findings) -> dict:
    out: dict = {}
    for f in findings:
        out[f.key] = out.get(f.key, 0) + 1
    return out


def new_findings(findings, baseline: dict) -> list:
    """Findings beyond the baseline's per-key allowance, in report
    order — the set that fails the ratchet."""
    budget = dict(baseline)
    out: list = []
    for f in findings:
        if budget.get(f.key, 0) > 0:
            budget[f.key] -= 1
        else:
            out.append(f)
    return out


def write(findings, path=None) -> Path:
    """Serialize the current finding counts as the new baseline
    (deterministic ordering, trailing newline — diff-friendly)."""
    p = Path(path) if path is not None else baseline_path()
    p.parent.mkdir(parents=True, exist_ok=True)
    out = {"version": 1,
           "findings": dict(sorted(counts(findings).items()))}
    with open(p, "w") as f:
        json.dump(out, f, indent=2)
        f.write("\n")
    return p
