"""jlint: the repo-invariant linter + jaxpr collective/dtype auditor
(ISSUE 15).

Two static analyzers behind one `python -m jepsen_tpu.cli lint`
command and one tier-1 test:

  * `lint.rules` / `lint.engine` — Python `ast` rules enforcing the
    tree's distributed-systems disciplines (monotonic-only decisions,
    fsync-before-rename publishes, register-before-inject fault
    hygiene, seeded draws, counted fallbacks, single-writer surfaces,
    thread/loop hygiene), each with an id, span, fix hint, and an
    inline-waiver grammar.
  * `lint.trace_audit` — traces every engine the planner can emit
    (via `planner.register_traceable` / `planner.traceable`) to its
    ClosedJaxpr and statically verifies the collective-uniformity,
    callback, dtype-exactness, and bucket-determinism invariants.

Findings ratchet against `store/ci/lint-baseline.json`
(`lint.baseline`): the tier-1 test fails on any finding not in the
baseline, and shrinking the baseline is a one-line commit.  See
docs/lint.md for the rule catalog and workflow.
"""

from jepsen_tpu.lint.baseline import (baseline_path, load,  # noqa: F401
                                      new_findings, write)
from jepsen_tpu.lint.engine import (Report, Waiver,  # noqa: F401
                                    discover, lint_source, run_lint)
from jepsen_tpu.lint.rules import RULES, Finding  # noqa: F401

__all__ = ["Finding", "Report", "Waiver", "RULES", "discover",
           "lint_source", "run_lint", "baseline_path", "load",
           "new_findings", "write"]
