"""Static jaxpr audit of the engines a plan can emit (ISSUE 15).

A non-uniform collective inside `shard_map` is not a test failure — it
is a silent fleet hang: one device exits the `while_loop` early, its
peers block in `ppermute`/`psum` forever, and the first symptom is a
wedged mesh in production.  The deep hypercube shard and the mesh Elle
closure avoid this by construction (every trip decision is a psum'd
frontier count, uniform across devices — the rendezvous invariant
PR 10 could only pin dynamically); this module verifies it
*statically*, on the traced ClosedJaxpr, for every engine the planner
can emit over its seeded shape sweep.

Checks per traced kernel:

  * **trace-nonuniform-collective** — every `while_loop` whose body
    contains a rendezvous collective must have a mesh-uniform trip
    condition.  Uniformity is a dataflow fixpoint over the jaxpr:
    full-axis `psum`/`pmin`/`pmax`/`all_gather` outputs are uniform;
    `axis_index`, `ppermute`, `all_to_all` and sharded inputs are
    varying; everything else propagates its inputs.
  * **trace-host-callback** — no host callbacks (implicit D2H
    round-trips) inside dispatch bodies.
  * **trace-dot-inexact** — closure matmuls must keep 0/1-exactness:
    bf16 operands require f32+ accumulation (or a contracting dim
    <= 256, bf16's exact-integer range); f16 and f64 operands are
    findings outright (f64 is a 4x VMEM bill for a boolean product).
  * **trace-dynamic-shape** — no data-dependent output shapes: every
    traced aval must be fully static.
  * **trace-bucket-collision** — every traced shape is a function of
    the plan's bucket key alone; two sweeps of the same bucket tracing
    different signatures means the executable cache key under-keys and
    a recompile storm ships as a bench regression.
  * **trace-undonated** — donated buffers must actually donate: on
    backends that implement donation, a dropped-donation warning at
    lower time is a finding (skipped — and counted as skipped — on
    cpu, where XLA ignores donation by design).

Engines are obtained through the planner's traceable-callable hook
(`planner.register_traceable` / `planner.traceable`): this module
registers builders for `elle-mesh`, `wgl_deep_hc`,
`wgl_deep`/`wgl_deep_split`/`wgl_deep_pipeline`, and `live-jit`;
builders derive every example shape from the plan BUCKET alone, which
is what makes the bucket-collision check meaningful.
"""

from __future__ import annotations

import dataclasses
import random
from typing import Optional

from jepsen_tpu.lint.rules import Finding

__all__ = ["audit_closed_jaxpr", "sweep", "seeded_shapes",
           "register_builtin_traceables", "AuditResult"]

#: Primitives that rendezvous across the mesh (a device missing one
#: hangs its peers).
COLLECTIVES = frozenset({
    "psum", "ppermute", "all_gather", "all_to_all", "pmin", "pmax",
    "reduce_scatter", "pgather", "psum2",
})
#: Full-axis reductions whose result is identical on every device —
#: the uniformity sources (gated on axis_index_groups is None).
UNIFORMIZING = frozenset({"psum", "pmin", "pmax", "all_gather",
                          "psum2"})
#: Host-callback primitives: an implicit D2H round-trip inside a
#: dispatch body ("debug_callback" is excluded — prints are not on the
#: verdict path).
CALLBACKS = frozenset({"pure_callback", "io_callback", "callback",
                       "outside_call", "host_callback_call"})


# ---------------------------------------------------------------------------
# Uniformity dataflow
# ---------------------------------------------------------------------------

def _inner_jaxpr(obj):
    """Open jaxpr from an open/closed jaxpr param."""
    return obj.jaxpr if hasattr(obj, "jaxpr") else obj


def _sub_jaxprs(eqn):
    """Every jaxpr nested in an eqn's params (while/scan/cond/pjit/
    pallas_call/custom_* alike), as open jaxprs."""
    out = []
    for v in eqn.params.values():
        if hasattr(v, "eqns") or (hasattr(v, "jaxpr")
                                  and hasattr(v.jaxpr, "eqns")):
            out.append(_inner_jaxpr(v))
        elif isinstance(v, (tuple, list)):
            for b in v:
                if hasattr(b, "eqns") or (hasattr(b, "jaxpr")
                                          and hasattr(b.jaxpr, "eqns")):
                    out.append(_inner_jaxpr(b))
    return out


def _contains_collective(jaxpr) -> bool:
    for eqn in jaxpr.eqns:
        if eqn.primitive.name in COLLECTIVES:
            return True
        for sub in _sub_jaxprs(eqn):
            if _contains_collective(sub):
                return True
    return False


def _is_uniformizing(eqn) -> bool:
    return eqn.primitive.name in UNIFORMIZING \
        and eqn.params.get("axis_index_groups") is None


class _Uniformity:
    """Dataflow over one mesh-body jaxpr: which values are provably
    identical across the mesh axis.  Conservative: anything not proven
    uniform is varying, so a false `nonuniform` is possible (waivable)
    but a false `uniform` is not — the analysis errs toward flagging.
    """

    def __init__(self, findings: list, where: str):
        self.findings = findings
        self.where = where

    def run(self, jaxpr, uniform_in) -> list:
        """Propagate through one open jaxpr; returns out-var
        uniformity.  constvars (host-baked numpy constants) are
        uniform by construction."""
        env: dict = {}

        def get(atom) -> bool:
            # Literals are uniform; unknown vars (constvars) default
            # uniform — they were closed over from the host
            return env.get(id(atom), True) \
                if type(atom).__name__ != "Literal" else True

        def put(var, val: bool) -> None:
            env[id(var)] = bool(val)

        for var, u in zip(jaxpr.invars, uniform_in):
            put(var, u)
        for eqn in jaxpr.eqns:
            name = eqn.primitive.name
            ins = [get(a) for a in eqn.invars]
            if name == "axis_index":
                outs = [False] * len(eqn.outvars)
            elif name in COLLECTIVES:
                outs = [_is_uniformizing(eqn)] * len(eqn.outvars)
            elif name == "while":
                outs = self._while(eqn, ins)
            elif name == "scan":
                outs = self._scan(eqn, ins)
            elif name == "cond":
                outs = self._cond(eqn, ins)
            elif name in ("pjit", "closed_call", "core_call",
                          "custom_jvp_call", "custom_vjp_call",
                          "remat", "checkpoint", "custom_vmap_call"):
                subs = _sub_jaxprs(eqn)
                if subs:
                    sub_out = self.run(subs[0],
                                       ins[:len(subs[0].invars)]
                                       + [True] * max(
                                           0, len(subs[0].invars)
                                           - len(ins)))
                    outs = sub_out[:len(eqn.outvars)] \
                        + [all(ins)] * max(0, len(eqn.outvars)
                                           - len(sub_out))
                else:
                    outs = [all(ins)] * len(eqn.outvars)
            else:
                # default: pointwise/structural — uniform iff every
                # input is.  Nested jaxprs (e.g. pallas_call) run on
                # one device; no mesh semantics inside.
                outs = [all(ins)] * len(eqn.outvars)
            for var, u in zip(eqn.outvars, outs):
                put(var, u)
        return [get(v) for v in jaxpr.outvars]

    def _while(self, eqn, ins) -> list:
        cond_j = _inner_jaxpr(eqn.params["cond_jaxpr"])
        body_j = _inner_jaxpr(eqn.params["body_jaxpr"])
        cn = eqn.params.get("cond_nconsts", 0)
        bn = eqn.params.get("body_nconsts", 0)
        cconst, bconst = ins[:cn], ins[cn:cn + bn]
        carry = list(ins[cn + bn:])
        # fixpoint: a carry slot is uniform only if its init is AND
        # the body preserves it (monotone meet; findings from nested
        # eqns are collected once, after convergence)
        sink = _Uniformity([], self.where)
        for _ in range(len(carry) + 2):
            out = sink.run(body_j, bconst + carry)
            nxt = [a and b for a, b in zip(carry, out)]
            if nxt == carry:
                break
            carry = nxt
        body_out = self.run(body_j, bconst + carry)
        trip = _Uniformity([], self.where).run(cond_j, cconst + carry)
        trip_uniform = all(trip) if trip else True
        if _contains_collective(body_j) and not trip_uniform:
            self.findings.append(Finding(
                "trace-nonuniform-collective", self.where, 0, 0,
                "while_loop body rendezvouses on a collective but its "
                "trip condition is not provably mesh-uniform (one "
                "device can exit while peers block — a silent fleet "
                "hang)",
                "derive the trip decision from a psum'd frontier "
                "count (shard_map_compat.frontier_settled)",
                "while"))
        return [a and b for a, b in zip(carry, body_out)]

    def _scan(self, eqn, ins) -> list:
        body_j = _sub_jaxprs(eqn)[0]
        nc = eqn.params.get("num_consts", 0)
        ncar = eqn.params.get("num_carry", 0)
        const, carry = ins[:nc], list(ins[nc:nc + ncar])
        xs = ins[nc + ncar:]
        sink = _Uniformity([], self.where)
        for _ in range(len(carry) + 2):
            out = sink.run(body_j, const + carry + xs)
            nxt = [a and b for a, b in zip(carry, out[:ncar])]
            if nxt == carry:
                break
            carry = nxt
        out = self.run(body_j, const + carry + xs)
        return carry + out[ncar:]

    def _cond(self, eqn, ins) -> list:
        branches = [_inner_jaxpr(b) for b in eqn.params["branches"]]
        idx_u, op_ins = ins[0], ins[1:]
        outs = None
        for b in branches:
            o = self.run(b, op_ins)
            outs = o if outs is None else [a and c
                                           for a, c in zip(outs, o)]
        outs = outs or []
        return [idx_u and o for o in outs] \
            + [idx_u] * max(0, len(eqn.outvars) - len(outs))


# ---------------------------------------------------------------------------
# Per-eqn audits
# ---------------------------------------------------------------------------

def _audit_dot(eqn, where: str, findings: list) -> None:
    lhs = eqn.invars[0].aval
    out = eqn.outvars[0].aval
    # name-based: bf16 is an ml_dtypes extension type that
    # np.issubdtype does not classify as floating
    name = str(lhs.dtype)
    if name not in ("float64", "float32", "float16", "bfloat16"):
        return
    dims = eqn.params.get("dimension_numbers")
    contract = 1
    if dims:
        for d in dims[0][0]:
            contract *= int(lhs.shape[d])
    if name == "float64":
        findings.append(Finding(
            "trace-dot-inexact", where, 0, 0,
            "f64 matmul in a closure kernel (4x the VMEM/HBM bill of "
            "the bf16 0/1-exact form)",
            "cast 0/1 operands to bf16 with "
            "preferred_element_type=f32", "dot_general"))
    elif name == "float16":
        findings.append(Finding(
            "trace-dot-inexact", where, 0, 0,
            "f16 matmul: 10 mantissa bits cannot carry the closure "
            "counts bf16+f32 accumulation keeps exact",
            "use bf16 operands with preferred_element_type=f32",
            "dot_general"))
    elif name == "bfloat16" and str(out.dtype) == "bfloat16" \
            and contract > 256:
        findings.append(Finding(
            "trace-dot-inexact", where, 0, 0,
            f"bf16 matmul accumulating in bf16 over a {contract}-wide "
            "contraction: 0/1 sums past 256 lose exactness",
            "preferred_element_type=jnp.float32 on the dot",
            "dot_general"))


def _audit_eqns(jaxpr, where: str, findings: list, stats: dict,
                in_mesh: bool) -> None:
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        stats["eqns"] = stats.get("eqns", 0) + 1
        if name in COLLECTIVES:
            stats["collectives"] = stats.get("collectives", 0) + 1
        if name == "while":
            stats["whiles"] = stats.get("whiles", 0) + 1
        if name in CALLBACKS:
            findings.append(Finding(
                "trace-host-callback", where, 0, 0,
                f"host callback `{name}` inside a dispatch body "
                "(implicit D2H round-trip on the verdict path)",
                "hoist host work out of the jitted dispatch", name))
        if name == "dot_general":
            _audit_dot(eqn, where, findings)
        if name == "shard_map":
            inner = _inner_jaxpr(eqn.params["jaxpr"])
            in_names = eqn.params.get("in_names") \
                or eqn.params.get("in_specs") or ()
            uniform_in = [not bool(n) for n in in_names]
            if len(uniform_in) != len(inner.invars):
                uniform_in = [False] * len(inner.invars)
            _Uniformity(findings, where).run(inner, uniform_in)
            _audit_eqns(inner, where, findings, stats, in_mesh=True)
            continue
        for var in eqn.outvars:
            aval = getattr(var, "aval", None)
            shape = getattr(aval, "shape", ())
            if any(not isinstance(d, int) for d in shape):
                findings.append(Finding(
                    "trace-dynamic-shape", where, 0, 0,
                    f"data-dependent output shape {shape} from "
                    f"`{name}`",
                    "pad to the plan bucket's static shape", name))
        for sub in _sub_jaxprs(eqn):
            _audit_eqns(sub, where, findings, stats, in_mesh)


def audit_closed_jaxpr(closed, where: str):
    """(findings, stats) for one traced ClosedJaxpr.  `where` names the
    kernel in finding paths (e.g. `<jaxpr:elle-mesh>`), and the
    enclosing bucket rides in the finding qualname via the sweep."""
    findings: list = []
    stats: dict = {}
    _audit_eqns(closed.jaxpr, where, findings, stats, in_mesh=False)
    return findings, stats


# ---------------------------------------------------------------------------
# Plan -> traceable builders (registered into the planner hook)
# ---------------------------------------------------------------------------

def _sds(shape, dtype):
    import jax
    import jax.numpy as jnp
    return jax.ShapeDtypeStruct(tuple(shape), getattr(jnp, dtype))


def _build_elle_mesh(plan, devices):
    from jepsen_tpu.ops import elle_mesh
    devs = tuple(devices)
    tile = elle_mesh.mesh_tile(len(devs))
    n_pad = tile                    # smallest legal mesh bucket
    fn, _mesh = elle_mesh._build_kernel(n_pad, devs,
                                        elle_mesh._block_for(n_pad))
    args = [_sds((n_pad, n_pad // 32), "uint32") for _ in range(4)]
    return fn, args, {"n_pad": n_pad, "devices": len(devs)}


def _build_elle_delta(plan, devices):
    """The incremental (warm-seeded) closure kernel: 4 packed direct
    planes + the previous 3-plane closure triple (ISSUE 18)."""
    from jepsen_tpu.ops import elle_mesh
    devs = tuple(devices)
    tile = elle_mesh.mesh_tile(len(devs))
    n_pad = int(plan.bucket[1]) if len(plan.bucket) > 1 else tile
    if n_pad % tile:
        n_pad = tile
    fn, _mesh = elle_mesh._build_kernel(
        n_pad, devs, elle_mesh._block_for(n_pad), warm=True)
    args = [_sds((n_pad, n_pad // 32), "uint32") for _ in range(7)]
    return fn, args, {"n_pad": n_pad, "devices": len(devs),
                      "warm": True}


def _build_lattice_mesh(plan, devices):
    """The full-lattice packed closure (ISSUE 20): eight packed
    planes through the seven-relation while_loop plus the twelve
    class masks, sharded by rows like elle-mesh."""
    from jepsen_tpu.lattice import engine as lattice_engine
    from jepsen_tpu.ops import elle_mesh
    devs = tuple(devices)
    tile = elle_mesh.mesh_tile(len(devs))
    n_pad = tile                    # smallest legal mesh bucket
    fn, _mesh = lattice_engine._build_mesh_kernel(
        n_pad, devs, elle_mesh._block_for(n_pad))
    args = [_sds((n_pad, n_pad // 32), "uint32")
            for _ in range(len(lattice_engine.LATTICE_PLANES))]
    return fn, args, {"n_pad": n_pad, "devices": len(devs),
                      "planes": len(lattice_engine.LATTICE_PLANES)}


def _build_lattice_device(plan, devices):
    """The dense single-device lattice kernel: one [8, n, n] bool
    stack in, per-class flags + defining edges out."""
    from jepsen_tpu.lattice import engine as lattice_engine
    n_pad = lattice_engine._TILE
    fn = lattice_engine._dense_kernel(n_pad)
    args = [_sds((len(lattice_engine.LATTICE_PLANES), n_pad, n_pad),
                 "bool_")]
    return fn, args, {"n_pad": n_pad}


def _build_deep_hc(plan, devices):
    from jepsen_tpu.ops import wgl_deep
    R = int(plan.bucket[1])
    Sn = int(plan.bucket[2] or 1)
    D = len(devices)
    D = 1 << max(1, D.bit_length() - 1)     # power-of-two slab
    if D < 2 or (1 << R) < 32 * D:
        return None
    devs = tuple(devices[:D])
    Wdl = (1 << R) // 32 // D
    SnP = wgl_deep._snp(min(Sn, 32))
    L2, I, UP = 64, 2, 64
    fn = wgl_deep._build_hc(L2, I, Wdl, SnP, R, UP, devs, "cfg")
    args = [_sds((L2,), "int32"), _sds((L2, I), "int32"),
            _sds((L2, I), "int32"), _sds((UP,), "uint32"),
            _sds((UP,), "uint32"), _sds((UP,), "int32")]
    return fn, args, {"R": R, "devices": D, "Wdl": Wdl}


def _build_deep(plan, devices):
    from jepsen_tpu.ops import planner, wgl_deep
    R = int(plan.bucket[1])
    Sn = int(plan.bucket[2] or 1)
    if R < 1:
        return None
    P = planner.deep_split_planes(R)
    Wd = max(1, (1 << R) // 32 // P)
    SnP = wgl_deep._snp(min(Sn, 32))
    G, I, UP = 1, 2, 64
    fn = wgl_deep._build(G, I, Wd, SnP, R, UP, P, True)
    # evbuf rides 3-D with a unit middle axis (Mosaic wants the
    # block's last two dims to equal the array's — see _build.kern)
    args = [_sds((G, 1, wgl_deep.EB * (1 + 2 * I)), "int32"),
            _sds((1, 3 * UP + 16), "uint32")]
    return fn, args, {"R": R, "split": P}


def _build_seg_pipeline(plan, devices):
    """The grouped register-delta pipeline's donated compact-wire
    kernel (wgl_seg._build_kernel_regs_many_c, donate=True): the one
    engine that promises buffer donation, so the sweep's donation
    audit has a real target."""
    from jepsen_tpu.ops import wgl_seg
    R = int(plan.bucket[1])
    Sn = min(int(plan.bucket[2] or 1), 32)
    U = min(int(plan.bucket[3] or 8), 255)
    K = min(int(plan.bucket[4] or 1), 16)
    if R < 1 or R > 8:
        return None
    L, Wd, Rp = 64, 1, 128
    fn = wgl_seg._build_kernel_regs_many_c(
        K, L, Wd, Sn, R, True, R + 1, 1, U, Rp, donate=True)
    args = [_sds((Rp * 2 + 4 * (K + 1),), "uint8"),
            _sds((3 * U,), "uint32")]
    return fn, args, {"R": R, "keys": K, "donate": True}


def audit_donation(fn, args, where: str):
    """trace-undonated: donated buffers must actually donate.  Lower +
    compile under a warning trap and flag any dropped-donation
    warning.  On backends where XLA ignores donation by design (cpu)
    the check is recorded as skipped, never passed vacuously."""
    import warnings

    import jax
    if jax.default_backend() not in ("tpu", "gpu"):
        return [], {"donation": "skipped (backend ignores donation)"}
    try:
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            fn.lower(*args).compile()
    except Exception as e:   # noqa: BLE001 - audit reports, never dies
        return [], {"donation": f"error: {type(e).__name__}: {e}"}
    dropped = [str(w.message) for w in caught
               if "donat" in str(w.message).lower()]
    findings = [Finding(
        "trace-undonated", where, 0, 0,
        f"donation dropped at compile time: {msg[:120]}",
        "align the donated argument's layout/aliasing with the "
        "output, or stop promising donation", "donation")
        for msg in dropped]
    return findings, {"donation": f"{len(dropped)} dropped"
                      if dropped else "ok"}


def _build_live(plan, devices):
    from jepsen_tpu.live import engine as live_engine
    _tag, T, E, M, Sn = plan.bucket
    B = int(M).bit_length() - 1
    if B < 1:
        return None
    T, E, M, Sn = int(T), int(E), int(M), int(Sn)
    fn = live_engine._build_bucket_kernel(T, E, M, Sn)
    args = [_sds((T, M, Sn), "bool_"), _sds((T, B, Sn), "int32"),
            _sds((T, B, Sn), "bool_"), _sds((T, B), "bool_"),
            _sds((T, E), "int32"), _sds((T, E), "int32"),
            _sds((T, E, Sn), "int32"), _sds((T, E, Sn), "bool_")]
    return fn, args, {"lanes": T, "events": E}


_REGISTERED = False


def register_builtin_traceables() -> None:
    """Install the built-in plan -> traceable builders into the
    planner hook (idempotent)."""
    global _REGISTERED
    if _REGISTERED:
        return
    from jepsen_tpu.ops import planner
    planner.register_traceable("elle-mesh", _build_elle_mesh)
    planner.register_traceable("elle-delta", _build_elle_delta)
    planner.register_traceable("lattice-mesh", _build_lattice_mesh)
    planner.register_traceable("lattice-device", _build_lattice_device)
    planner.register_traceable("wgl_deep_hc", _build_deep_hc)
    planner.register_traceable("wgl_deep", _build_deep)
    planner.register_traceable("wgl_deep_split", _build_deep)
    planner.register_traceable("wgl_deep_pipeline", _build_deep)
    planner.register_traceable("wgl_seg_pipeline", _build_seg_pipeline)
    planner.register_traceable("live-jit", _build_live)
    _REGISTERED = True


# ---------------------------------------------------------------------------
# The seeded sweep driver
# ---------------------------------------------------------------------------

def seeded_shapes(n: int = 400, seed: int = 11) -> list:
    """The planner's seeded-random shape sweep (the same generator
    family tests/test_planner.py pins routing with), widened with the
    elle/live kinds so every engine family the planner can emit shows
    up."""
    from jepsen_tpu.ops import planner
    rng = random.Random(seed)
    out = []
    for _ in range(n):
        kind = rng.choice(["linear", "linear-many", "linear-pipeline",
                           "deep-pipeline", "deep-mesh", "batch-many",
                           "elle", "live", "lattice"])
        mesh = rng.choice([None, 2, 8])
        if kind == "deep-mesh":
            mesh = mesh or 2            # a meshless mesh shape is
        out.append(planner.Shape(       # caller error, not a route
            kind=kind,
            R=rng.randrange(1, 20) if kind != "live"
            else rng.randrange(1, 8),
            crashes=rng.choice([0, 0, 0, 1, 2, 5]),
            Sn=rng.choice([None, 1, 2, 5, 8, 16, 32]),
            U=rng.choice([None, 1, 50, 4000]),
            decomposed=rng.choice([None, True]),
            batch=rng.choice([1, 3, 16, 128]),
            n_ops=rng.randrange(0, 10_000),
            mesh=mesh,
            device=True,
            max_states=rng.choice([16, 64]),
            max_open_bits=rng.choice([10, 14])))
    return out


@dataclasses.dataclass
class AuditResult:
    findings: list
    rows: list                      # per-(engine, bucket) audit rows
    plans: int = 0
    traced: int = 0
    skipped: int = 0

    def summary(self) -> dict:
        engines = sorted({r["engine"] for r in self.rows})
        return {"engines": engines, "plans": self.plans,
                "traced": self.traced, "skipped": self.skipped,
                "findings": len(self.findings)}

    def to_json(self) -> dict:
        return {**self.summary(),
                "rows": self.rows,
                "finding_list": [f.to_json() for f in self.findings]}


def sweep(n: int = 400, seed: int = 11, per_engine: int = 3,
          backend: Optional[str] = None, devices=None,
          shapes=None) -> AuditResult:
    """Drive plan_engines over the seeded sweep, dedupe plans by
    (engine, bucket), and statically audit up to `per_engine` traced
    kernels per engine (smallest buckets first — the audit is about
    program STRUCTURE, which the smallest legal bucket already
    exhibits; larger buckets of the same builder only scale dims).
    Plans whose engine has no registered traceable are counted, not
    failed — the hook is additive."""
    import jax

    from jepsen_tpu.ops import planner
    register_builtin_traceables()
    devices = list(devices) if devices is not None else \
        list(jax.devices())
    backend = backend or jax.default_backend()
    env = {"JEPSEN_TPU_DEEP_INTERPRET": "1"} if backend == "cpu" \
        else {}

    by_key: dict = {}
    shapes = shapes if shapes is not None else seeded_shapes(n, seed)
    for shape in shapes:
        try:
            plan = planner.plan_engines(shape, env=env,
                                        backend=backend)
        except ValueError:
            continue
        by_key.setdefault((plan.engine, plan.bucket), plan)

    findings: list = []
    rows: list = []
    traced = skipped = 0
    per_eng_count: dict = {}
    sigs: dict = {}          # (engine, bucket) -> traced aval signature
    for (engine, bucket), plan in sorted(
            by_key.items(), key=lambda kv: (kv[0][0], str(kv[0][1]))):
        if engine not in planner.traceable_engines():
            continue
        if per_eng_count.get(engine, 0) >= per_engine:
            skipped += 1
            continue
        where = f"<jaxpr:{engine}>"
        try:
            built = planner.traceable(plan, devices=devices)
        except Exception as e:   # noqa: BLE001 - audit must report, not die
            rows.append({"engine": engine, "bucket": list(bucket),
                         "error": f"build: {type(e).__name__}: {e}"})
            skipped += 1
            continue
        if built is None:
            skipped += 1
            continue
        fn, args, meta = built
        per_eng_count[engine] = per_eng_count.get(engine, 0) + 1
        try:
            closed = jax.make_jaxpr(fn)(*args)
        except Exception as e:   # noqa: BLE001
            rows.append({"engine": engine, "bucket": list(bucket),
                         "error": f"trace: {type(e).__name__}: {e}"})
            skipped += 1
            continue
        traced += 1
        fs, stats = audit_closed_jaxpr(closed, where)
        if meta.get("donate"):
            dfs, dstats = audit_donation(fn, args, where)
            fs += dfs
            stats.update(dstats)
        fs = [dataclasses.replace(f, qualname=repr(tuple(bucket)))
              for f in fs]
        sig = tuple(str(a.aval) for a in closed.jaxpr.invars) \
            + tuple(str(v.aval) for v in closed.jaxpr.outvars)
        prev = sigs.setdefault((engine, bucket), sig)
        if prev != sig:
            fs.append(Finding(
                "trace-bucket-collision", where, 0, 0,
                "same plan bucket traced two different shape "
                "signatures — the executable cache under-keys "
                "(recompile storm)",
                "fold the distinguishing dimension into "
                "planner._bucket_for", repr(tuple(bucket))))
        findings.extend(fs)
        row = {"engine": engine, "bucket": list(bucket),
               "meta": meta, "findings": len(fs),
               **{k: stats.get(k, 0)
                  for k in ("eqns", "collectives", "whiles")}}
        if "donation" in stats:
            row["donation"] = stats["donation"]
        rows.append(row)
    res = AuditResult(findings=findings, rows=rows,
                      plans=len(by_key), traced=traced,
                      skipped=skipped)
    try:
        from jepsen_tpu import telemetry
        for f in findings:
            telemetry.count_lint(f.rule, "finding")
        telemetry.REGISTRY.counter(
            "jepsen_lint_trace_audited_total").inc(traced)
    except Exception:   # noqa: BLE001 - telemetry is advisory
        pass
    from jepsen_tpu.lint import engine as lint_engine
    lint_engine.LAST["audit"] = res.summary()
    return res
