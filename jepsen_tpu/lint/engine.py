"""The lint driver: file discovery, waiver application, repo pass.

Discovery follows the same discipline `store.tests()` uses for run
directories: artifact trees are never parsed as source.  `store/`
(campaign ledgers, fleet sidecars, CI artifacts), `.cache/` (the JAX
compilation cache), and `__pycache__` are skipped at ANY depth, as are
symlinked directories (`store/latest` and friends are symlink cycles
waiting to happen).  Regression-pinned by tests/test_lint.py.

Waiver grammar:  `# lint: <token>-ok(<reason>)` on the flagged line or
the line directly above.  The token is the rule's short name
(rules.WAIVER_TOKENS: wall, rename, inject, rng, fallback, writer,
thread, sleep).  A waiver with an empty reason does not waive — it IS
a finding (`reasonless-waiver`): the whole point is that every
exception to a discipline carries its justification in-line.
"""

from __future__ import annotations

import ast
import dataclasses
import re
import time
from pathlib import Path
from typing import Optional

from jepsen_tpu.lint.rules import RULES, WAIVER_TOKENS, Finding, lint_tree

__all__ = ["discover", "lint_source", "run_lint", "Report", "Waiver",
           "EXCLUDE_DIRS", "LAST"]

#: Directory names never descended into — the store.tests() discipline
#: (campaign/fleet/CI artifacts are data, not source) plus the usual
#: tooling litter.
EXCLUDE_DIRS = frozenset({
    "store", ".cache", "__pycache__", ".git", ".pytest_cache",
    ".eggs", "build", "node_modules",
})

_WAIVER_MARK = re.compile(r"#\s*lint:\s*")
_WAIVER_RE = re.compile(r"([a-z0-9_]+)-ok\(([^()]*)\)")

#: The last run's report/audit, for the tier-1 CI artifact
#: (tests/conftest.py reads it without re-running the pass).
LAST: dict = {"report": None, "audit": None}


@dataclasses.dataclass(frozen=True)
class Waiver:
    rule: str
    path: str
    line: int
    reason: str

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class Report:
    findings: list
    waivers: list
    files: int = 0
    errors: list = dataclasses.field(default_factory=list)
    wall_s: float = 0.0

    def by_rule(self) -> dict:
        out: dict = {}
        for f in self.findings:
            out[f.rule] = out.get(f.rule, 0) + 1
        return out

    def to_json(self) -> dict:
        return {"findings": [f.to_json() for f in self.findings],
                "waivers": [w.to_json() for w in self.waivers],
                "files": self.files,
                "errors": list(self.errors),
                "wall_s": round(self.wall_s, 3)}


def discover(paths, root: Optional[Path] = None) -> list:
    """All lintable .py files under `paths` (files pass through),
    sorted, with EXCLUDE_DIRS and symlinked directories skipped at any
    depth — store/, .cache/ and __pycache__ hold campaign ledgers,
    fleet sidecars and compile caches that must never be parsed as
    source."""
    out: list = []
    for p in paths:
        p = Path(p)
        if p.is_file():
            if p.suffix == ".py":
                out.append(p)
            continue
        if not p.is_dir():
            continue
        stack = [p]
        while stack:
            d = stack.pop()
            try:
                entries = sorted(d.iterdir())
            except OSError:
                continue
            for e in entries:
                if e.is_dir():
                    if e.name in EXCLUDE_DIRS or e.is_symlink():
                        continue
                    stack.append(e)
                elif e.suffix == ".py" and not e.is_symlink():
                    out.append(e)
    return sorted(set(out))


def _parse_waivers(src: str) -> dict:
    """{lineno: [(token, reason), ...]} for every waiver comment."""
    out: dict = {}
    for i, line in enumerate(src.splitlines(), start=1):
        mark = _WAIVER_MARK.search(line)
        if mark is None:
            continue
        # several `<token>-ok(reason)` waivers may share one `# lint:`
        # marker (a line can trip more than one rule)
        for m in _WAIVER_RE.finditer(line[mark.end():]):
            out.setdefault(i, []).append(
                (m.group(1), m.group(2).strip()))
    return out


def lint_source(src: str, relpath: str, rules=None):
    """(findings, waivers) for one module's source.  Rule findings with
    a matching reasoned waiver on their line (or the line above) are
    converted to Waiver records; reasonless waivers surface as
    `reasonless-waiver` findings at the waiver site."""
    tree = ast.parse(src)
    raw = lint_tree(tree, relpath, rules=rules)
    waiver_lines = _parse_waivers(src)

    findings: list = []
    waivers: list = []
    for f in raw:
        token = WAIVER_TOKENS.get(f.rule)
        reason = None
        for ln in (f.line, f.line - 1):
            for tok, why in waiver_lines.get(ln, []):
                if tok == token and why:
                    reason = why
                    break
            if reason:
                break
        if reason:
            waivers.append(Waiver(f.rule, f.path, f.line, reason))
        else:
            findings.append(f)
    for ln, toks in sorted(waiver_lines.items()):
        for tok, why in toks:
            if not why:
                findings.append(Finding(
                    "reasonless-waiver", relpath, ln, 0,
                    f"waiver `{tok}-ok()` without a reason",
                    "every waiver must say WHY the discipline doesn't "
                    "apply: `# lint: " + tok + "-ok(<reason>)`"))
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings, waivers


def default_root() -> Path:
    """The repo root the CLI and baseline anchor to: the parent of the
    installed jepsen_tpu package (stable regardless of cwd)."""
    return Path(__file__).resolve().parent.parent.parent


def default_paths() -> list:
    """What `cli lint` checks with no path arguments: the package
    source tree."""
    return [Path(__file__).resolve().parent.parent]


def run_lint(paths=None, root: Optional[Path] = None, rules=None,
             counters: bool = True) -> Report:
    """The repo pass: discover, parse, rule-check, waive.  Unparseable
    files land in report.errors (a linter must degrade, not crash the
    suite).  Findings/waivers are counted into the process registry
    (`jepsen_lint_total{rule=,kind=}`) unless counters=False."""
    t0 = time.monotonic()
    root = Path(root) if root is not None else default_root()
    files = discover(paths if paths is not None else default_paths(),
                     root)
    findings: list = []
    waivers: list = []
    errors: list = []
    for p in files:
        try:
            rel = p.resolve().relative_to(root).as_posix()
        except ValueError:
            rel = p.as_posix()
        try:
            src = p.read_text(encoding="utf-8", errors="replace")
            fs, ws = lint_source(src, rel, rules=rules)
        except (SyntaxError, ValueError, OSError) as e:
            errors.append((rel, f"{type(e).__name__}: {e}"))
            continue
        findings.extend(fs)
        waivers.extend(ws)
    rep = Report(findings=findings, waivers=waivers, files=len(files),
                 errors=errors, wall_s=time.monotonic() - t0)
    if paths is None:
        # the canonical repo pass only: ad-hoc passes over explicit
        # paths (CLI on a fixture dir, tests on tmp trees) must not
        # clobber the row the tier-1 CI artifact reads
        LAST["report"] = rep
    if counters:
        try:
            from jepsen_tpu import telemetry
            for f in findings:
                telemetry.count_lint(f.rule, "finding")
            for w in waivers:
                telemetry.count_lint(w.rule, "waiver")
        except Exception:   # noqa: BLE001 - telemetry is advisory
            pass
    return rep
