"""The repo-invariant rule catalog (ISSUE 15).

Fourteen PRs of distributed-systems discipline live in this tree as
*conventions*: monotonic-only expiry decisions, fsync-before-rename
atomic publishes, register-before-inject fault hygiene, seeded draw
paths, counted fallback ladders, single-writer-under-lease surfaces.
Until now each was enforced only by whatever runtime battery happened
to exercise the violating path.  Elle's core lesson (Kingsbury &
Alvaro, PVLDB'20) is that soundness arguments should be *checkable
properties*; this module makes each convention a small `ast` visitor
with an id, a span, and a fix hint.

Every rule supports an inline waiver: `# lint: <token>-ok(<reason>)`
on the flagged line (or the line above) downgrades the finding to a
counted waiver — but only with a non-empty reason; a reasonless waiver
is itself a finding (`reasonless-waiver`).  See docs/lint.md for the
catalog with the *why* behind each discipline.
"""

from __future__ import annotations

import ast
import dataclasses

__all__ = ["Finding", "RULES", "WAIVER_TOKENS", "lint_tree"]


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule hit: id, span, fix hint, and the enclosing qualname
    (the baseline key is (rule, path, qualname) — stable across the
    line churn of unrelated edits, unlike raw line numbers)."""

    rule: str
    path: str                   # root-relative posix path
    line: int
    col: int
    msg: str
    hint: str = ""
    qualname: str = "<module>"

    @property
    def key(self) -> str:
        return f"{self.rule}::{self.path}::{self.qualname}"

    def to_json(self) -> dict:
        return dataclasses.asdict(self)

    def render(self) -> str:
        out = (f"{self.path}:{self.line}:{self.col}: {self.rule} "
               f"[{self.qualname}] {self.msg}")
        return out + (f"\n    fix: {self.hint}" if self.hint else "")


# rule id -> waiver token (the `<token>-ok(reason)` spelling)
WAIVER_TOKENS = {
    "wall-clock-in-frame": "wall",
    "unfsynced-rename": "rename",
    "inject-before-register": "inject",
    "global-rng-in-draw": "rng",
    "bare-fallback": "fallback",
    "stray-writer": "writer",
    "unjoined-thread": "thread",
    "naked-sleep-loop": "sleep",
}


# ---------------------------------------------------------------------------
# Shared AST helpers
# ---------------------------------------------------------------------------

def _attr_chain(func):
    """(base_name | None, [attr, ...]) for a Name/Attribute call target:
    `os.replace` -> ("os", ["replace"]); `__import__("x").datetime.now`
    -> (None, ["datetime", "now"]) — a non-Name base is None so rules
    can still match trailing attribute patterns."""
    attrs: list = []
    node = func
    while isinstance(node, ast.Attribute):
        attrs.append(node.attr)
        node = node.value
    attrs.reverse()
    return (node.id if isinstance(node, ast.Name) else None), attrs


def _dotted(func):
    base, attrs = _attr_chain(func)
    if base is None:
        return None
    return ".".join([base] + attrs)


def _last_name(func):
    """The final identifier of a call target (`x.y.z` -> 'z',
    `z` -> 'z')."""
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return None


def _iter_scoped(tree):
    """Yield (node, scope_stack) with scope_stack the enclosing
    FunctionDef/AsyncFunctionDef/ClassDef chain (innermost last; a def
    node's own stack includes itself)."""
    stack: list = []

    def rec(node):
        scoped = isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                   ast.ClassDef))
        if scoped:
            stack.append(node)
        yield node, tuple(stack)
        for child in ast.iter_child_nodes(node):
            yield from rec(child)
        if scoped:
            stack.pop()

    yield from rec(tree)


def _qualname(stack) -> str:
    return ".".join(n.name for n in stack) or "<module>"


def _innermost_func(stack):
    for node in reversed(stack):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return node
    return None


def _enclosing_class(stack):
    for node in reversed(stack):
        if isinstance(node, ast.ClassDef):
            return node
    return None


def _calls_in(node):
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call):
            yield sub


def _docstring_consts(tree) -> set:
    """id()s of docstring Constant nodes, so literal scans can ignore
    prose."""
    out: set = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.Module, ast.FunctionDef,
                             ast.AsyncFunctionDef, ast.ClassDef)):
            body = getattr(node, "body", [])
            if body and isinstance(body[0], ast.Expr) \
                    and isinstance(body[0].value, ast.Constant) \
                    and isinstance(body[0].value.value, str):
                out.add(id(body[0].value))
    return out


# ---------------------------------------------------------------------------
# wall-clock-in-frame
# ---------------------------------------------------------------------------
#
# WHY: crc'd frame envelopes (history WAL, telemetry EventLog,
# live.jsonl) and every lease/breaker *expiry decision* must be
# monotonic-only — wall clocks skew, and Jepsen's own clock nemeses
# exist precisely because systems that decide with time.time() lie
# under skew.  Advisory wall stamps (operator display, run ids, SUT
# workloads) are legitimate but must say so: `# lint: wall-ok(why)`.

def _rule_wall_clock(ctx) -> list:
    out = []
    for node, stack in _iter_scoped(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        base, attrs = _attr_chain(node.func)
        tail = attrs[-1] if attrs else None
        hit = False
        if tail in ("time", "time_ns"):
            prev = attrs[-2] if len(attrs) >= 2 else base
            hit = prev == "time"
        elif tail in ("now", "utcnow"):
            prev = attrs[-2] if len(attrs) >= 2 else base
            hit = prev == "datetime"
        if hit:
            out.append(Finding(
                "wall-clock-in-frame", ctx.relpath, node.lineno,
                node.col_offset,
                "wall-clock read on a frame/decision path "
                "(monotonic-only discipline)",
                "decide with time.monotonic(); an advisory wall stamp "
                "needs `# lint: wall-ok(<reason>)`",
                _qualname(stack)))
    return out


# ---------------------------------------------------------------------------
# unfsynced-rename
# ---------------------------------------------------------------------------
#
# WHY: the atomic-publish discipline (lease.json / live.json / ledger
# frames) is tmp-write -> fsync -> rename; an os.replace whose source
# was never fsynced can publish a zero-length file after power loss —
# exactly the torn-surface class the fleet's takeover path defends
# against.  The fsync may live in a local helper (e.g. `_write_tmp`);
# the rule resolves module-local helpers transitively.

def _fsyncing_functions(tree) -> set:
    """Names of module functions whose bodies (transitively, within the
    module) call os.fsync."""
    funcs = {node.name: node for node in ast.walk(tree)
             if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))}
    syncing: set = set()
    changed = True
    while changed:
        changed = False
        for name, node in funcs.items():
            if name in syncing:
                continue
            for call in _calls_in(node):
                if _dotted(call.func) == "os.fsync" \
                        or _last_name(call.func) in syncing:
                    syncing.add(name)
                    changed = True
                    break
    return syncing


def _rule_unfsynced_rename(ctx) -> list:
    out = []
    syncing = _fsyncing_functions(ctx.tree)
    for node, stack in _iter_scoped(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        if _dotted(node.func) not in ("os.rename", "os.replace"):
            continue
        scope = _innermost_func(stack) or ctx.tree
        ok = False
        for call in _calls_in(scope):
            if call.lineno > node.lineno:
                continue
            if _dotted(call.func) == "os.fsync" \
                    or _last_name(call.func) in syncing:
                ok = True
                break
        if not ok:
            out.append(Finding(
                "unfsynced-rename", ctx.relpath, node.lineno,
                node.col_offset,
                "atomic publish without a preceding fsync "
                "(rename of never-synced bytes)",
                "fsync the staged file (or a helper that does) before "
                "the rename; a non-publish rename needs "
                "`# lint: rename-ok(<reason>)`",
                _qualname(stack)))
    return out


# ---------------------------------------------------------------------------
# inject-before-register
# ---------------------------------------------------------------------------
#
# WHY: PR 4's fault hygiene — a nemesis records its undo in the
# FaultLedger BEFORE injecting, so a nemesis that dies mid-fault (or a
# run torn down with one active) still gets healed by the run_case
# backstop, and campaign.assert_empty can prove no fault leaked.  An
# unregistered injection is invisible to both.

_INJECT_FILES = ("nemesis.py", "nemesis_time.py", "faultfs.py")
_INJECT_CALLS = frozenset({
    "drop_all", "set_time", "bump_time", "strobe_time",
    "set_fault", "set_torn", "set_lost_fsync",
})


def _rule_inject_before_register(ctx) -> list:
    if ctx.basename not in _INJECT_FILES:
        return []
    out = []
    for node, stack in _iter_scoped(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        name = _last_name(node.func)
        if name not in _INJECT_CALLS:
            continue
        # the primitive's own definition is mechanism, not injection
        if any(isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef))
               and s.name == name for s in stack):
            continue
        registered = False
        for scope in stack:
            if not isinstance(scope, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                continue
            for call in _calls_in(scope):
                if call.lineno < node.lineno \
                        and _last_name(call.func) == "register":
                    registered = True
                    break
            if registered:
                break
        if not registered:
            out.append(Finding(
                "inject-before-register", ctx.relpath, node.lineno,
                node.col_offset,
                f"fault injection `{name}` without a preceding "
                "FaultLedger.register",
                "register the undo in the test's fault ledger before "
                "injecting; heal/teardown paths need "
                "`# lint: inject-ok(<reason>)`",
                _qualname(stack)))
    return out


# ---------------------------------------------------------------------------
# global-rng-in-draw
# ---------------------------------------------------------------------------
#
# WHY: campaign schedule draws and generator op draws must thread
# explicit seeds (random.Random(seed)) or campaigns stop being
# resumable and coverage stops being reproducible — the PR 11 fixup
# exists because one outcome-dependent draw silently diverged replays.

_RNG_FILES = ("campaign.py", "generator.py")
_RNG_CALLS = frozenset({
    "random", "randint", "randrange", "choice", "choices", "shuffle",
    "sample", "uniform", "gauss", "normalvariate", "expovariate",
    "betavariate", "triangular", "getrandbits", "seed",
})


def _rule_global_rng(ctx) -> list:
    if ctx.basename not in _RNG_FILES:
        return []
    out = []
    for node, stack in _iter_scoped(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        base, attrs = _attr_chain(node.func)
        if base == "random" and len(attrs) == 1 \
                and attrs[0] in _RNG_CALLS:
            out.append(Finding(
                "global-rng-in-draw", ctx.relpath, node.lineno,
                node.col_offset,
                f"process-global random.{attrs[0]}() in a draw path",
                "thread an explicit random.Random(seed) instance "
                "through the draw",
                _qualname(stack)))
    return out


# ---------------------------------------------------------------------------
# bare-fallback
# ---------------------------------------------------------------------------
#
# WHY: the engine fallback ladders degrade by design (Unsupported ->
# next tier), but a rung taken SILENTLY is how a perf cliff hides in a
# green suite — every typed-error handler must leave a telemetry trace
# (jepsen_engine_fallback_total) or re-raise, so `cli metrics` and the
# CI artifact can show the engine mix actually run.

_TYPED_ERRORS = frozenset({
    "Unsupported", "CheckError", "DeviceOOM", "DeadlineExceeded",
    "BackendUnavailable", "CorruptHistory",
})
_COUNTED_CALLS = frozenset({
    "count_fallback", "emit", "fault_window", "attach_dispatch",
    "_count_pack",
})


def _handler_types(handler) -> set:
    t = handler.type
    nodes = t.elts if isinstance(t, ast.Tuple) else ([t] if t else [])
    return {_last_name(n) for n in nodes} - {None}


def _rule_bare_fallback(ctx) -> list:
    out = []
    for node, stack in _iter_scoped(ctx.tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        if not (_handler_types(node) & _TYPED_ERRORS):
            continue
        counted = False
        for stmt in node.body:
            for sub in ast.walk(stmt):
                if isinstance(sub, ast.Raise):
                    counted = True
                elif isinstance(sub, ast.Call):
                    if _last_name(sub.func) in _COUNTED_CALLS \
                            or _last_name(sub.func) == "inc":
                        counted = True
                if counted:
                    break
            if counted:
                break
        if not counted:
            out.append(Finding(
                "bare-fallback", ctx.relpath, node.lineno,
                node.col_offset,
                "typed engine error swallowed without a telemetry "
                "count or re-raise (silent fallback rung)",
                "telemetry.count_fallback(<engine>, <reason>) in the "
                "handler, or re-raise",
                _qualname(stack)))
    return out


# ---------------------------------------------------------------------------
# stray-writer
# ---------------------------------------------------------------------------
#
# WHY: live.jsonl, lease.json and history.wal are single-writer-
# under-lease surfaces — the fleet's exactly-once and fencing
# guarantees hold only because every write goes through the
# scheduler's lease check (live.jsonl/lease.json) or the WAL class /
# the ingest tier's epoch-fenced registration (history.wal, ISSUE
# 16).  Any other module opening them for write is a fenced-bypass
# bug waiting for a fault schedule to find it.

_GUARDED_FILES = ("live.jsonl", "lease.json", "history.wal",
                  "txn-state.json", "trace-index.jsonl")
_ALLOWED_WRITERS = ("live/scheduler.py", "live/lease.py",
                    "live/ingest.py", "history.py")
_WRITE_ATTRS = frozenset({"write_text", "write_bytes"})


def _mentions_guarded(node, doc_ids) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Constant) and isinstance(sub.value, str) \
                and id(sub) not in doc_ids \
                and any(g in sub.value for g in _GUARDED_FILES):
            return True
    return False


def _is_write_call(call) -> bool:
    name = _last_name(call.func)
    if name in _WRITE_ATTRS or name == "EventLog":
        return True
    if _dotted(call.func) in ("os.replace", "os.rename", "os.link"):
        return True
    if isinstance(call.func, ast.Name) and call.func.id == "open" \
            or name == "open":
        mode = None
        if len(call.args) >= 2 and isinstance(call.args[1], ast.Constant):
            mode = call.args[1].value
        for kw in call.keywords:
            if kw.arg == "mode" and isinstance(kw.value, ast.Constant):
                mode = kw.value.value
        return isinstance(mode, str) \
            and any(c in mode for c in "wax+")
    return False


def _rule_stray_writer(ctx) -> list:
    if ctx.relpath.endswith(_ALLOWED_WRITERS):
        return []
    doc_ids = _docstring_consts(ctx.tree)
    out = []
    for node, stack in _iter_scoped(ctx.tree):
        if not isinstance(node, ast.Call) or not _is_write_call(node):
            continue
        scope = _innermost_func(stack) or ctx.tree
        # taint: the call's own subtree, or a name bound to a guarded
        # literal within the enclosing scope
        tainted_names: set = set()
        for sub in ast.walk(scope):
            if isinstance(sub, ast.Assign) \
                    and _mentions_guarded(sub.value, doc_ids):
                for tgt in sub.targets:
                    for n in ast.walk(tgt):
                        if isinstance(n, ast.Name):
                            tainted_names.add(n.id)
        hit = _mentions_guarded(node, doc_ids) or any(
            isinstance(n, ast.Name) and n.id in tainted_names
            for a in (list(node.args)
                      + [kw.value for kw in node.keywords])
            for n in ast.walk(a))
        if hit:
            out.append(Finding(
                "stray-writer", ctx.relpath, node.lineno,
                node.col_offset,
                "write to a single-writer-under-lease surface "
                "(live.jsonl / lease.json / history.wal) outside "
                "scheduler/lease/WAL/ingest code",
                "route the write through live/scheduler.py (lease-"
                "checked), live/lease.py, history.py (the WAL class) "
                "or live/ingest.py (epoch-fenced)",
                _qualname(stack)))
    return out


# ---------------------------------------------------------------------------
# unjoined-thread / naked-sleep-loop (hygiene)
# ---------------------------------------------------------------------------
#
# WHY: a non-daemon thread nobody joins outlives the test that spawned
# it and bleeds state into the next one (the CI-leak class PR 11's
# fixup chased); a `while True` that sleeps with no exit edge can only
# be killed, never drained — both are the stuff of flaky tier-1 runs.

def _rule_unjoined_thread(ctx) -> list:
    out = []
    for node, stack in _iter_scoped(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        base, attrs = _attr_chain(node.func)
        if not ((attrs and attrs[-1] == "Thread")
                or (base == "Thread" and not attrs)):
            continue
        daemon = any(kw.arg == "daemon"
                     and isinstance(kw.value, ast.Constant)
                     and kw.value.value is True
                     for kw in node.keywords)
        if daemon:
            continue
        search = [_innermost_func(stack) or ctx.tree]
        cls = _enclosing_class(stack)
        if cls is not None:
            search.append(cls)
        joined = any(_last_name(call.func) == "join"
                     for scope in search
                     for call in _calls_in(scope))
        if not joined:
            out.append(Finding(
                "unjoined-thread", ctx.relpath, node.lineno,
                node.col_offset,
                "non-daemon Thread that is never joined in its scope",
                "daemon=True for background workers, or join() on "
                "every exit path",
                _qualname(stack)))
    return out


def _rule_naked_sleep_loop(ctx) -> list:
    out = []
    for node, stack in _iter_scoped(ctx.tree):
        if not isinstance(node, ast.While):
            continue
        if not (isinstance(node.test, ast.Constant)
                and node.test.value):
            continue
        body_nodes = [n for stmt in node.body for n in ast.walk(stmt)]
        sleeps = any(isinstance(n, ast.Call)
                     and _dotted(n.func) == "time.sleep"
                     for n in body_nodes)
        exits = any(isinstance(n, (ast.Break, ast.Return, ast.Raise))
                    for n in body_nodes)
        if sleeps and not exits:
            out.append(Finding(
                "naked-sleep-loop", ctx.relpath, node.lineno,
                node.col_offset,
                "unbounded `while True` sleep loop with no exit edge",
                "poll a stop Event / deadline, or break on a "
                "condition",
                _qualname(stack)))
    return out


# ---------------------------------------------------------------------------
# Registry
# ---------------------------------------------------------------------------

RULES = {
    "wall-clock-in-frame": _rule_wall_clock,
    "unfsynced-rename": _rule_unfsynced_rename,
    "inject-before-register": _rule_inject_before_register,
    "global-rng-in-draw": _rule_global_rng,
    "bare-fallback": _rule_bare_fallback,
    "stray-writer": _rule_stray_writer,
    "unjoined-thread": _rule_unjoined_thread,
    "naked-sleep-loop": _rule_naked_sleep_loop,
}


@dataclasses.dataclass
class _Ctx:
    tree: ast.AST
    relpath: str
    basename: str


def lint_tree(tree: ast.AST, relpath: str, rules=None) -> list:
    """Run the (selected) rules over one parsed module.  Waiver
    application happens in engine.lint_source — this is the raw rule
    pass."""
    ctx = _Ctx(tree=tree, relpath=relpath.replace("\\", "/"),
               basename=relpath.replace("\\", "/").rsplit("/", 1)[-1])
    selected = RULES if rules is None else {
        r: RULES[r] for r in rules if r in RULES}
    out: list = []
    for fn in selected.values():
        out.extend(fn(ctx))
    out.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return out
