"""Auto-reconnecting client wrapper (reference:
`jepsen/src/jepsen/reconnect.clj`).

Wraps a stateful connection in a reader/writer-locked holder: normal
use shares the connection under the read lock; when an operation
throws, `with_conn` closes and reopens the connection (write lock) so
the *next* user gets a fresh one, then rethrows — the caller still sees
the failure, exactly like `with-conn` (reconnect.clj:92-129).
"""

from __future__ import annotations

import contextlib
import logging
import threading
from typing import Any, Callable, Optional

log = logging.getLogger("jepsen.reconnect")


class _RWLock:
    """Writer-preferring reader/writer lock."""

    def __init__(self):
        self._cond = threading.Condition()
        self._readers = 0
        self._writer = False
        self._writers_waiting = 0

    def acquire_read(self):
        with self._cond:
            while self._writer or self._writers_waiting:
                self._cond.wait()
            self._readers += 1

    def release_read(self):
        with self._cond:
            self._readers -= 1
            if not self._readers:
                self._cond.notify_all()

    @contextlib.contextmanager
    def read(self):
        self.acquire_read()
        try:
            yield
        finally:
            self.release_read()

    @contextlib.contextmanager
    def write(self):
        with self._cond:
            self._writers_waiting += 1
            while self._writer or self._readers:
                self._cond.wait()
            self._writers_waiting -= 1
            self._writer = True
        try:
            yield
        finally:
            with self._cond:
                self._writer = False
                self._cond.notify_all()


class Wrapper:
    """Connection holder (reconnect.clj wrapper :16-49).

    open_fn() -> conn; close_fn(conn); optional name for logs."""

    def __init__(self, open_fn: Callable[[], Any],
                 close_fn: Optional[Callable[[Any], None]] = None,
                 name: Any = None):
        self.open_fn = open_fn
        self.close_fn = close_fn
        self.name = name
        self.lock = _RWLock()
        self._conn: Any = None
        self._open = False

    @property
    def conn(self):
        return self._conn

    def open(self) -> "Wrapper":
        """Open the underlying conn (reconnect.clj open! :51)."""
        with self.lock.write():
            if not self._open:
                self._conn = self.open_fn()
                self._open = True
        return self

    def close(self) -> "Wrapper":
        with self.lock.write():
            self._close_locked()
        return self

    def _close_locked(self):
        if self._open:
            try:
                if self.close_fn:
                    self.close_fn(self._conn)
            except Exception as e:
                log.warning("error closing conn %s: %s", self.name, e)
            self._conn = None
            self._open = False

    def reopen(self) -> "Wrapper":
        """Close (ignoring errors) and open a fresh conn
        (reconnect.clj reopen! :78-90)."""
        with self.lock.write():
            self._close_locked()
            self._conn = self.open_fn()
            self._open = True
        return self

    @contextlib.contextmanager
    def with_conn(self):
        """Yield the live conn with the read lock held across the whole
        body, so reopen() (write lock) waits for in-flight users.  If
        the body throws, release the lock, reopen the conn for future
        users, and rethrow (reconnect.clj with-conn :92-129)."""
        self.lock.acquire_read()
        try:
            if not self._open:
                raise RuntimeError(f"conn {self.name!r} not open")
            conn = self._conn
        except BaseException:
            self.lock.release_read()
            raise
        try:
            yield conn
        except Exception:
            self.lock.release_read()
            try:
                self.reopen()
            except Exception as e:
                log.warning("error reopening conn %s: %s", self.name, e)
            raise
        else:
            self.lock.release_read()


def wrapper(open_fn: Callable[[], Any],
            close_fn: Optional[Callable[[Any], None]] = None,
            name: Any = None) -> Wrapper:
    return Wrapper(open_fn, close_fn, name)
