"""Auto-reconnecting client wrapper (reference:
`jepsen/src/jepsen/reconnect.clj`).

Wraps a stateful connection in a reader/writer-locked holder: normal
use shares the connection under the read lock; when an operation
throws, `with_conn` closes and reopens the connection (write lock) so
the *next* user gets a fresh one, then rethrows — the caller still sees
the failure, exactly like `with-conn` (reconnect.clj:92-129).

On top of the holder this module carries the rest of the self-healing
control plane's connection policy (wired through `control.py`):

  * `backoff_s` — exponential backoff with DETERMINISTIC jitter
    (seeded by (name, attempt), same discipline as the resilient
    checker runtime's retry shape) so transport-retry schedules replay
    identically across runs.
  * `CircuitBreaker` — a per-node closed/open/half-open breaker:
    after `threshold` consecutive transport failures the node is
    declared down and further commands fail fast with `BreakerOpen`
    (a ConnectionError, so the worker loop journals an `:info`
    completion) instead of hanging every worker for a full
    retry-backoff ladder; after `cooldown_s` one probe is let through
    (half-open) and a success re-closes the breaker.
"""

from __future__ import annotations

import contextlib
import logging
import threading
import time
import zlib
from typing import Any, Callable, Optional

log = logging.getLogger("jepsen.reconnect")


def backoff_s(attempt: int, base_s: float = 0.1, cap_s: float = 2.0,
              name: Any = None, seed: int = 0) -> float:
    """Exponential backoff with deterministic jitter in [0.5, 1.0) of
    the exponential slot — keyed by (seed, name, attempt), never by
    wall clock, so a failing run's retry schedule is reproducible."""
    slot = min(base_s * (2 ** attempt), cap_s)
    h = zlib.crc32(repr((seed, name, attempt)).encode())
    return slot * (0.5 + (h % 1000) / 2000.0)


class BreakerOpen(ConnectionError):
    """Fail-fast refusal: the node's circuit breaker is open.  Derives
    from ConnectionError so existing transport-failure handling (worker
    :info conversion, transient classification) applies unchanged."""

    def __init__(self, node, failures: int, retry_in_s: float):
        super().__init__(
            f"circuit breaker open for {node}: {failures} consecutive "
            f"transport failures; retrying in {retry_in_s:.1f}s")
        self.node = node
        self.failures = failures
        self.retry_in_s = retry_in_s


class CircuitBreaker:
    """Per-node transport circuit breaker (closed -> open -> half-open).

    closed: commands flow; consecutive transport failures are counted
        (any success resets the count).
    open: after `threshold` consecutive failures; `check()` raises
        BreakerOpen immediately until `cooldown_s` has elapsed.
    half-open: first `check()` past the cooldown lets ONE probe
        through; its success() re-closes the breaker, its failure()
        re-opens it for another cooldown.
    """

    def __init__(self, node=None, threshold: int = 5,
                 cooldown_s: float = 10.0,
                 clock: Callable[[], float] = time.monotonic):
        self.node = node
        self.threshold = max(1, int(threshold))
        self.cooldown_s = cooldown_s
        self.clock = clock
        self.lock = threading.Lock()
        self.failures = 0
        self.opened_at: Optional[float] = None
        self.probing = False

    def _transition(self, to: str, failures: int) -> None:
        """Journal a state transition into telemetry (outside the
        breaker lock — the event log takes its own lock and does file
        IO).  Must never fail a command attempt."""
        try:
            from jepsen_tpu import telemetry as telemetry_mod
            telemetry_mod.breaker_transition(self.node, to, failures)
        except Exception:   # noqa: BLE001 - telemetry never breaks IO
            pass

    @property
    def state(self) -> str:
        with self.lock:
            if self.opened_at is None:
                return "closed"
            return "half-open" if self.probing else "open"

    def check(self) -> None:
        """Gate a command attempt: no-op when closed; raises BreakerOpen
        while open; past the cooldown admits a single half-open probe
        (concurrent callers keep failing fast until it resolves)."""
        with self.lock:
            if self.opened_at is None:
                return
            elapsed = self.clock() - self.opened_at
            if elapsed >= self.cooldown_s and not self.probing:
                self.probing = True
                n = self.failures
            else:
                raise BreakerOpen(self.node, self.failures,
                                  max(self.cooldown_s - elapsed, 0.0))
        self._transition("half-open", n)

    def success(self) -> None:
        with self.lock:
            reclosed = self.opened_at is not None
            if reclosed:
                log.info("breaker for %s closed again", self.node)
                n = self.failures
            self.failures = 0
            self.opened_at = None
            self.probing = False
        if reclosed:
            self._transition("closed", n)

    def failure(self) -> None:
        opened = False
        with self.lock:
            self.failures += 1
            if self.probing or (self.opened_at is None
                                and self.failures >= self.threshold):
                if self.opened_at is None:
                    log.warning(
                        "breaker for %s OPEN after %d consecutive "
                        "transport failures", self.node, self.failures)
                # first open AND a failed half-open probe re-opening
                # are both journaled as -> open transitions
                opened = True
                self.opened_at = self.clock()
                self.probing = False
                n = self.failures
        if opened:
            self._transition("open", n)


class _RWLock:
    """Writer-preferring reader/writer lock."""

    def __init__(self):
        self._cond = threading.Condition()
        self._readers = 0
        self._writer = False
        self._writers_waiting = 0

    def acquire_read(self):
        with self._cond:
            while self._writer or self._writers_waiting:
                self._cond.wait()
            self._readers += 1

    def release_read(self):
        with self._cond:
            self._readers -= 1
            if not self._readers:
                self._cond.notify_all()

    @contextlib.contextmanager
    def read(self):
        self.acquire_read()
        try:
            yield
        finally:
            self.release_read()

    @contextlib.contextmanager
    def write(self):
        with self._cond:
            self._writers_waiting += 1
            while self._writer or self._readers:
                self._cond.wait()
            self._writers_waiting -= 1
            self._writer = True
        try:
            yield
        finally:
            with self._cond:
                self._writer = False
                self._cond.notify_all()


class Wrapper:
    """Connection holder (reconnect.clj wrapper :16-49).

    open_fn() -> conn; close_fn(conn); optional name for logs."""

    def __init__(self, open_fn: Callable[[], Any],
                 close_fn: Optional[Callable[[Any], None]] = None,
                 name: Any = None):
        self.open_fn = open_fn
        self.close_fn = close_fn
        self.name = name
        self.lock = _RWLock()
        self._conn: Any = None
        self._open = False

    @property
    def conn(self):
        return self._conn

    def open(self) -> "Wrapper":
        """Open the underlying conn (reconnect.clj open! :51)."""
        with self.lock.write():
            if not self._open:
                self._conn = self.open_fn()
                self._open = True
        return self

    def close(self) -> "Wrapper":
        with self.lock.write():
            self._close_locked()
        return self

    def _close_locked(self):
        if self._open:
            try:
                if self.close_fn:
                    self.close_fn(self._conn)
            except Exception as e:
                log.warning("error closing conn %s: %s", self.name, e)
            self._conn = None
            self._open = False

    def reopen(self) -> "Wrapper":
        """Close (ignoring errors) and open a fresh conn
        (reconnect.clj reopen! :78-90)."""
        with self.lock.write():
            self._close_locked()
            self._conn = self.open_fn()
            self._open = True
        return self

    @contextlib.contextmanager
    def with_conn(self):
        """Yield the live conn with the read lock held across the whole
        body, so reopen() (write lock) waits for in-flight users.  If
        the body throws, release the lock, reopen the conn for future
        users, and rethrow (reconnect.clj with-conn :92-129)."""
        self.lock.acquire_read()
        try:
            if not self._open:
                raise RuntimeError(f"conn {self.name!r} not open")
            conn = self._conn
        except BaseException:
            self.lock.release_read()
            raise
        try:
            yield conn
        except Exception:
            self.lock.release_read()
            try:
                self.reopen()
            except Exception as e:
                log.warning("error reopening conn %s: %s", self.name, e)
            raise
        else:
            self.lock.release_read()


def wrapper(open_fn: Callable[[], Any],
            close_fn: Optional[Callable[[Any], None]] = None,
            name: Any = None) -> Wrapper:
    return Wrapper(open_fn, close_fn, name)
