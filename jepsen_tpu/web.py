"""Web dashboard — L7 (reference: `jepsen/src/jepsen/web.clj`).

A dependency-free HTTP dashboard over the store/ directory: a test
table colored by validity (web.clj:25-34,122), a file browser rooted at
the store (web.clj app :328), zip export of a whole test run
(web.clj:336 zip handler), plus the telemetry surfaces (ISSUE 4):
`/telemetry` lists runs with a telemetry.jsonl, `/telemetry/<name>/<ts>`
renders op-rate and p95-latency sparklines with nemesis fault windows
shaded and the `cli metrics` summary inline, and `/metrics` is the
process-global Prometheus text exposition for scraping.
`/elle/<name>/<ts>` renders the transactional anomaly section (ISSUE
5): per-checker isolation verdicts plus the elle.txt report inline.
`/live` + `/live/<name>/<ts>` render the live verification surfaces
(ISSUE 6): verdict-so-far, violation flags with detection lag, and the
cross-tenant micro-batch dispatch records, from the checker daemon's
live.json / live.jsonl.  Built on http.server so it runs anywhere the
framework does.
"""

from __future__ import annotations

import html
import io
import json
import threading
import zipfile
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from pathlib import Path
from urllib.parse import quote, unquote

from jepsen_tpu import store

VALID_COLORS = {True: "#ADF6B0", False: "#F3BBBC", None: "#EAEAEA"}
UNKNOWN_COLOR = "#F3EABB"


def _color(valid):
    if valid in VALID_COLORS:
        return VALID_COLORS[valid]
    return UNKNOWN_COLOR


def _page(title: str, body: str) -> bytes:
    return (f"<!DOCTYPE html><html><head><title>{html.escape(title)}"
            "</title><style>"
            "body{font-family:sans-serif;margin:2em}"
            "table{border-collapse:collapse}"
            "td,th{padding:.3em .8em;border:1px solid #ccc;text-align:left}"
            "a{text-decoration:none}"
            "</style></head><body>" + body + "</body></html>").encode()


# fast-tests memoization (web.clj:48-69): keyed on the results file's
# mtime as well as (name, ts), so a re-analysis of a stored history
# (which rewrites results.json in place) invalidates the cached verdict
# instead of pinning the stale one for the life of the process.
_results_cache: dict = {}
_results_cache_lock = threading.Lock()


def _cached_validity(name: str, ts: str):
    try:
        mtime = store.results_path(name, ts).stat().st_mtime_ns
    except OSError:
        return None                  # analysis still running: retry later
    key = (name, ts, mtime)
    with _results_cache_lock:
        if key in _results_cache:
            return _results_cache[key]
    res = store.load_results(name, ts)
    if res is None:
        return None
    valid = res.get("valid?")
    with _results_cache_lock:
        # Drop stale entries for this run so the cache stays bounded by
        # the number of distinct runs, not rewrites.  The server is
        # threaded; iteration and mutation stay under the lock.
        for k in [k for k in _results_cache if k[:2] == (name, ts)]:
            del _results_cache[k]
        _results_cache[key] = valid
    return valid


def _test_rows() -> list:
    rows = []
    for name, stamps in sorted(store.tests().items()):
        for ts in sorted(stamps, reverse=True):
            rows.append((name, ts, _cached_validity(name, ts)))
    rows.sort(key=lambda r: r[1], reverse=True)
    return rows


def home_html() -> bytes:
    rows = []
    for name, ts, valid in _test_rows():
        base = f"/files/{quote(name)}/{quote(ts)}"
        # anomaly-section link only for runs an elle checker rendered
        # (a cheap existence probe, like the telemetry index)
        elle = ""
        if (store.BASE / store._sanitize(name) / ts
                / "elle.txt").exists():
            elle = (f"<a href='/elle/{quote(name)}/{quote(ts)}'>"
                    "anomalies</a>")
        rows.append(
            f"<tr style='background:{_color(valid)}'>"
            f"<td>{html.escape(name)}</td>"
            f"<td><a href='{base}/'>{html.escape(ts)}</a></td>"
            f"<td>{html.escape(json.dumps(valid))}</td>"
            f"<td><a href='{base}/results.json'>results</a></td>"
            f"<td><a href='{base}/history.txt'>history</a></td>"
            f"<td>{elle}</td>"
            f"<td><a href='/zip/{quote(name)}/{quote(ts)}'>zip</a></td>"
            "</tr>")
    body = ("<h1>Jepsen</h1><p><a href='/telemetry'>telemetry</a> &middot; "
            "<a href='/live'>live</a> &middot; "
            "<a href='/fleet'>fleet</a> &middot; "
            "<a href='/ingest'>ingest</a> &middot; "
            "<a href='/trace'>traces</a> &middot; "
            "<a href='/campaign'>campaigns</a> &middot; "
            "<a href='/metrics'>metrics</a></p>"
            "<table><tr><th>Test</th><th>Time</th>"
            "<th>Valid?</th><th>Results</th><th>History</th>"
            "<th>Anomalies</th><th>Zip</th>"
            "</tr>" + "".join(rows) + "</table>")
    return _page("Jepsen", body)


def _safe_path(rel: str) -> Path:
    """Resolve an already-decoded path under the store root, refusing
    traversal (containment via relative_to, not string prefix — a
    sibling like store-backup/ must not pass)."""
    base = store.BASE.resolve()
    p = (base / rel.lstrip("/")).resolve()
    try:
        p.relative_to(base)
    except ValueError:
        raise PermissionError(rel)
    return p


def dir_html(rel: str, p: Path) -> bytes:
    """rel is the decoded store-relative path; links re-encode it."""
    ents = []
    rel = rel.strip("/")
    for child in sorted(p.iterdir()):
        slash = "/" if child.is_dir() else ""
        href = "/files/" + quote(f"{rel}/{child.name}" if rel
                                 else child.name) + slash
        ents.append(f"<li><a href='{href}'>"
                    f"{html.escape(child.name)}{slash}</a></li>")
    return _page(rel or "store",
                 f"<h1>{html.escape(rel or 'store')}</h1><p>"
                 "<a href='/'>&larr; tests</a></p><ul>"
                 + "".join(ents) + "</ul>")


# ---------------------------------------------------------------------------
# Telemetry pages (ISSUE 4): /telemetry index, per-run sparklines with
# nemesis windows shaded, /metrics Prometheus exposition
# ---------------------------------------------------------------------------

def _sparkline_svg(values: list, windows: list, color: str,
                   width: int = 640, height: int = 80,
                   label: str = "") -> str:
    """Inline SVG polyline over bucketed values; fault windows shaded
    as translucent rectangles spanning the full height."""
    if not values:
        return "<p>(no data)</p>"
    vmax = max(values) or 1.0
    n = len(values)
    pts = " ".join(
        f"{i / max(n - 1, 1) * width:.1f},"
        f"{height - (v / vmax) * (height - 4):.1f}"
        for i, v in enumerate(values))
    shades = "".join(
        f"<rect x='{a * width:.1f}' y='0' "
        f"width='{max((b - a) * width, 1.0):.1f}' height='{height}' "
        "fill='#E8A4A4' fill-opacity='0.35'/>"
        for a, b in windows)
    return (f"<div><b>{html.escape(label)}</b> "
            f"(max {vmax:.3g})<br>"
            f"<svg width='{width}' height='{height}' "
            "style='border:1px solid #ccc;background:#fff'>"
            + shades +
            f"<polyline points='{pts}' fill='none' stroke='{color}' "
            "stroke-width='1.5'/></svg></div>")


def telemetry_index_html() -> bytes:
    rows = []
    for name, ts, valid in _test_rows():
        if not (store.BASE / store._sanitize(name) / ts
                / "telemetry.jsonl").exists():
            continue
        rows.append(
            f"<tr style='background:{_color(valid)}'>"
            f"<td>{html.escape(name)}</td>"
            f"<td><a href='/telemetry/{quote(name)}/{quote(ts)}'>"
            f"{html.escape(ts)}</a></td>"
            f"<td><a href='/files/{quote(name)}/{quote(ts)}/"
            "telemetry.jsonl'>raw</a></td></tr>")
    body = ("<h1>Telemetry</h1><p><a href='/'>&larr; tests</a> &middot; "
            "<a href='/metrics'>prometheus snapshot</a></p>"
            "<table><tr><th>Test</th><th>Run</th><th>Log</th></tr>"
            + "".join(rows) + "</table>")
    if not rows:
        body += "<p>(no runs with a telemetry.jsonl yet)</p>"
    return _page("Telemetry", body)


def _find_elle_results(tree, path="results") -> list:
    """Recursively collect elle verdicts (dicts carrying
    anomaly-types + txn-count) out of a results tree."""
    out = []
    if isinstance(tree, dict):
        if "anomaly-types" in tree and "txn-count" in tree:
            out.append((path, tree))
        else:
            for k, v in tree.items():
                out.extend(_find_elle_results(v, f"{path}/{k}"))
    return out


def elle_html(name: str, ts: str) -> bytes:
    """Transactional anomaly section for one run: per-checker verdict
    rows (weakest violated isolation level, anomaly types, engine)
    plus the rendered elle.txt report inline."""
    body = [f"<h1>{html.escape(name)} / {html.escape(ts)} "
            "&mdash; transactional isolation</h1>",
            "<p><a href='/'>&larr; tests</a></p>"]
    res = store.load_results(name, ts)
    rows = _find_elle_results(res) if res else []
    if rows:
        cells = []
        for path, r in rows:
            kinds = r.get("anomaly-types") or []
            color = _color(r.get("valid?"))
            cells.append(
                f"<tr style='background:{color}'>"
                f"<td>{html.escape(path)}</td>"
                f"<td>{html.escape(json.dumps(r.get('valid?')))}</td>"
                f"<td>{r.get('txn-count')}</td>"
                f"<td>{html.escape(', '.join(kinds) or '-')}</td>"
                f"<td>{html.escape(r.get('weakest-violated') or '-')}"
                "</td>"
                f"<td>{html.escape(r.get('engine') or '?')}</td></tr>")
        body.append("<table><tr><th>Checker</th><th>Valid?</th>"
                    "<th>Txns</th><th>Anomalies</th>"
                    "<th>Weakest violated</th><th>Engine</th></tr>"
                    + "".join(cells) + "</table>")
    else:
        body.append("<p>(no transactional isolation verdicts in "
                    "results.json)</p>")
    try:
        p = _safe_path(f"{name}/{ts}") / "elle.txt"
        if p.exists():
            body.append("<h2>Anomaly report</h2><pre>"
                        + html.escape(p.read_text()) + "</pre>")
    except (OSError, PermissionError):
        pass
    return _page(f"elle {name}/{ts}", "".join(body))


# ---------------------------------------------------------------------------
# Live verification pages (ISSUE 6): /live index + per-run
# verdict-so-far, detection flags, and micro-batch dispatch records —
# rendered from the checker daemon's live.json / live.jsonl surfaces
# ---------------------------------------------------------------------------

_LIVE_COLORS = {True: "#ADF6B0", False: "#F3BBBC",
                "unknown": "#F3EABB"}


def _live_color(verdict):
    return _LIVE_COLORS.get(verdict, "#EAEAEA")


def live_index_html() -> bytes:
    rows = []
    for name, stamps in sorted(store.tests().items()):
        for ts in sorted(stamps, reverse=True):
            p = store.BASE / store._sanitize(name) / ts / "live.json"
            if not p.exists():
                continue
            try:
                with open(p) as f:
                    lj = json.load(f)
            except (OSError, json.JSONDecodeError):
                continue
            v = lj.get("verdict-so-far")
            txn = lj.get("txn") or {}
            weakest = txn.get("weakest-violated")
            rows.append(
                f"<tr style='background:{_live_color(v)}'>"
                f"<td>{html.escape(name)}</td>"
                f"<td><a href='/live/{quote(name)}/{quote(ts)}'>"
                f"{html.escape(ts)}</a></td>"
                f"<td>{html.escape(json.dumps(v))}</td>"
                f"<td>{html.escape(weakest) if weakest else '&mdash;'}"
                "</td>"
                f"<td>{lj.get('ops_checked', 0)}</td>"
                f"<td>{lj.get('windows_checked', 0)}</td>"
                f"<td>{len(lj.get('flags') or [])}</td>"
                f"<td>{'yes' if lj.get('done') else 'tailing'}</td>"
                "</tr>")
    body = ("<h1>Live verification</h1>"
            "<p><a href='/'>&larr; tests</a> &middot; "
            "<a href='/metrics'>metrics</a></p>"
            "<table><tr><th>Test</th><th>Run</th>"
            "<th>Verdict so far</th><th>Weakest violated</th>"
            "<th>Ops checked</th>"
            "<th>Windows</th><th>Flags</th><th>Done?</th></tr>"
            + "".join(rows) + "</table>")
    if not rows:
        body += ("<p>(no runs under live checking — start "
                 "<code>python -m jepsen_tpu.cli serve-checker "
                 "store/</code>)</p>")
    return _page("Live verification", body)


def live_run_html(name: str, ts: str) -> bytes:
    from jepsen_tpu import telemetry
    d = _safe_path(f"{name}/{ts}")
    lj_path = d / "live.json"
    if not lj_path.exists():
        raise FileNotFoundError(lj_path)
    with open(lj_path) as f:
        lj = json.load(f)
    v = lj.get("verdict-so-far")
    body = [f"<h1>{html.escape(name)} / {html.escape(ts)} "
            "&mdash; live verification</h1>",
            "<p><a href='/live'>&larr; live</a> &middot; "
            f"<a href='/files/{quote(name)}/{quote(ts)}/live.jsonl'>"
            "raw event log</a></p>",
            f"<p style='background:{_live_color(v)};padding:.5em'>"
            f"<b>verdict so far: {html.escape(json.dumps(v))}</b> "
            f"({'run complete' if lj.get('done') else 'still tailing'}"
            ")</p>"]
    txn = lj.get("txn") or {}
    if txn:
        weakest = txn.get("weakest-violated")
        body.append(
            "<h2>Transactional (incremental Elle)</h2>"
            f"<p><b>weakest violated level so far: "
            f"{html.escape(weakest) if weakest else 'none (clean)'}"
            "</b></p><table>"
            + "".join(
                f"<tr><th>{html.escape(k)}</th>"
                f"<td>{html.escape(json.dumps(txn.get(k), default=repr))}"
                "</td></tr>"
                for k in ("workload", "txns", "keys", "anomalies",
                          "lattice_classes", "lattice_seconds",
                          "windows", "closure_rebuilds",
                          "resumed_txns", "engine", "rounds",
                          "n_pad", "flags_capped"))
            + "</table>")
    body.append(
        "<table>"
        + "".join(f"<tr><th>{html.escape(k)}</th>"
                  f"<td>{html.escape(json.dumps(lj.get(k), default=repr))}"
                  "</td></tr>"
                  for k in ("ops_ingested", "ops_checked",
                            "windows_checked", "lanes", "queue_depth",
                            "bytes", "evictions", "backend",
                            "plan_cache", "paused", "corrupt",
                            "saturated"))
        + "</table>")
    events = []
    ev_path = d / "live.jsonl"
    if ev_path.exists():
        events = telemetry.read_events(ev_path)
    flags = [e for e in events if e.get("type") == "live-flag"]
    if flags:
        body.append("<h2>Violation flags</h2>"
                    "<table><tr><th>Lane</th><th>Op index</th>"
                    "<th>f</th><th>Value</th>"
                    "<th>Detection lag (s)</th><th>Dispatch</th>"
                    "<th>Engine</th><th>Plan cache</th></tr>")
        for e in flags:
            body.append(
                "<tr style='background:#F3BBBC'>"
                f"<td>{html.escape(str(e.get('lane')))}</td>"
                f"<td>{e.get('op_index')}</td>"
                f"<td>{html.escape(str(e.get('f')))}</td>"
                f"<td>{html.escape(str(e.get('value')))}</td>"
                f"<td>{e.get('detection_lag_s')}</td>"
                f"<td>{html.escape(str(e.get('dispatch_id')))}</td>"
                f"<td>{html.escape(str(e.get('engine')))}</td>"
                f"<td>{html.escape(str(e.get('cache')))}</td></tr>")
        body.append("</table>")
    disps = [e for e in events if e.get("type") == "live-dispatch"]
    if disps:
        body.append("<h2>Micro-batch dispatches</h2>"
                    "<table><tr><th>Id</th><th>Engine</th>"
                    "<th>Plan cache</th><th>Lanes</th>"
                    "<th>Tenants</th><th>Bucket (T,E,M,Sn)</th>"
                    "<th>Seconds</th></tr>")
        for e in disps[-50:]:
            shared = len(e.get("tenants") or []) > 1
            body.append(
                f"<tr{' style=background:#D8E8F8' if shared else ''}>"
                f"<td>{html.escape(str(e.get('dispatch_id')))}</td>"
                f"<td>{html.escape(str(e.get('engine')))}</td>"
                f"<td>{html.escape(str(e.get('cache')))}</td>"
                f"<td>{e.get('lanes')}</td>"
                f"<td>{html.escape(', '.join(e.get('tenants') or []))}"
                "</td>"
                f"<td>{html.escape(str(e.get('bucket')))}</td>"
                f"<td>{e.get('seconds')}</td></tr>")
        body.append("</table>")
    windows = [e for e in events if e.get("type") == "live-window"]
    lags = sorted(e["lag_s"] for e in windows
                  if isinstance(e.get("lag_s"), (int, float)))
    if lags:
        p99 = lags[min(int(0.99 * len(lags)), len(lags) - 1)]
        body.append(f"<p>{len(windows)} windows checked; "
                    f"op-append&rarr;verdict lag p50="
                    f"{lags[len(lags) // 2]:.4f}s "
                    f"p99={p99:.4f}s max={lags[-1]:.4f}s</p>")
    return _page(f"live {name}/{ts}", "".join(body))


# ---------------------------------------------------------------------------
# Fleet page (ISSUE 14): /fleet — the serve-checker fleet aggregate:
# workers (from store/fleet/<id>.json status sidecars), lease-owned
# tenants with owner/epoch/cursor, the takeover/fencing timeline
# (lease-* events merged from tenant live.jsonl + worker fleet logs),
# and runs nobody ever owned, visibly flagged rather than absent
# ---------------------------------------------------------------------------

def _fleet_workers() -> list:
    out = []
    root = store.fleet_root()
    if not root.is_dir():
        return out
    for p in sorted(root.glob("*.json")):
        try:
            with open(p) as f:
                out.append(json.load(f))
        except (OSError, json.JSONDecodeError):
            continue
    return out


def _fleet_tenants() -> list:
    """(name, ts, lease-dict-or-None, live-dict-or-None) for every run
    dir carrying a history.wal."""
    from jepsen_tpu.live import lease as lease_mod
    rows = []
    for name, stamps in sorted(store.tests().items()):
        for ts in sorted(stamps, reverse=True):
            d = store.BASE / store._sanitize(name) / ts
            if not (d / "history.wal").exists():
                continue
            ls = lease_mod.read(d)
            lj = None
            try:
                with open(d / "live.json") as f:
                    lj = json.load(f)
            except (OSError, json.JSONDecodeError):
                pass
            rows.append((name, ts, ls, lj))
    return rows


def _fleet_events(limit: int = 50) -> list:
    """The takeover timeline: lease-* events from every tenant's
    live.jsonl merged with the workers' own fleet logs (the home of
    lease-fenced refusals), newest first."""
    from jepsen_tpu import telemetry
    evs = []
    for name, ts, ls, _lj in _fleet_tenants():
        if ls is None:
            continue
        p = store.BASE / store._sanitize(name) / ts / "live.jsonl"
        if not p.exists():
            continue
        for e in telemetry.read_events(p):
            if str(e.get("type", "")).startswith("lease-"):
                evs.append(dict(e, tenant=f"{name}/{ts}"))
    root = store.fleet_root()
    if root.is_dir():
        for p in sorted(root.glob("*.jsonl")):
            for e in telemetry.read_events(p):
                if str(e.get("type", "")).startswith("lease-"):
                    evs.append(e)
    evs.sort(key=lambda e: e.get("t") or 0.0, reverse=True)
    return evs[:limit]


def fleet_html() -> bytes:
    import time as time_mod
    now = time_mod.time()
    body = ["<h1>Checker fleet</h1>",
            "<p><a href='/'>&larr; tests</a> &middot; "
            "<a href='/live'>live</a> &middot; "
            "<a href='/metrics'>metrics</a></p>"]

    workers = _fleet_workers()
    if workers:
        body.append("<h2>Workers</h2>"
                    "<table><tr><th>Worker</th><th>Owned</th>"
                    "<th>Flags</th><th>Takeovers</th>"
                    "<th>Fenced writes</th>"
                    "<th>Max takeover lag (s)</th>"
                    "<th>Window lag p50/p99 (s)</th>"
                    "<th>Last beat</th></tr>")
        for w in workers:
            age = now - w.get("updated", 0)
            ttl = w.get("lease_ttl") or 5.0
            stale = age > 3 * ttl
            body.append(
                f"<tr{' style=background:#F3EABB' if stale else ''}>"
                f"<td>{html.escape(str(w.get('worker')))}</td>"
                f"<td>{w.get('owned')}</td>"
                f"<td>{w.get('flags_total')}</td>"
                f"<td>{w.get('takeovers')}</td>"
                f"<td>{w.get('fenced_writes')}</td>"
                f"<td>{w.get('max_takeover_lag_s')}</td>"
                f"<td>{w.get('lag_p50_s')} / {w.get('lag_p99_s')}</td>"
                f"<td>{age:.1f}s ago"
                f"{' (stale)' if stale else ''}</td></tr>")
        body.append("</table>")
    else:
        body.append("<p>(no worker status files under store/fleet/ — "
                    "start workers with <code>serve-checker store/ "
                    "--workers 2</code> or <code>--lease-ttl "
                    "5</code>)</p>")

    tenants = _fleet_tenants()
    owned_rows, never_rows = [], []
    for name, ts, ls, lj in tenants:
        v = (lj or {}).get("verdict-so-far")
        if ls is None:
            never_rows.append(
                "<tr style='background:#F3EABB'>"
                f"<td>{html.escape(name)}/"
                f"<a href='/live/{quote(name)}/{quote(ts)}'>"
                f"{html.escape(ts)}</a></td>"
                "<td colspan=4><b>never owned</b>"
                + (" &mdash; " + html.escape(str(
                    (lj or {}).get("reason")))
                   if (lj or {}).get("unowned") else "")
                + "</td>"
                f"<td>{html.escape(json.dumps(v))}</td></tr>")
            continue
        status = "done" if ls.done else \
            ("released" if ls.released else
             ("torn" if ls.corrupt else "held"))
        owned_rows.append(
            f"<tr style='background:{_live_color(v)}'>"
            f"<td>{html.escape(name)}/"
            f"<a href='/live/{quote(name)}/{quote(ts)}'>"
            f"{html.escape(ts)}</a></td>"
            f"<td>{html.escape(str(ls.owner))}</td>"
            f"<td>{ls.epoch}</td>"
            f"<td>{html.escape(status)}</td>"
            f"<td>{ls.offset}/{ls.seq}</td>"
            f"<td>{html.escape(json.dumps(v))}</td></tr>")
    if owned_rows or never_rows:
        body.append("<h2>Tenants</h2>"
                    "<table><tr><th>Run</th><th>Owner</th>"
                    "<th>Epoch</th><th>Lease</th>"
                    "<th>Safe cursor (off/seq)</th>"
                    "<th>Verdict so far</th></tr>"
                    + "".join(owned_rows) + "".join(never_rows)
                    + "</table>")

    evs = _fleet_events()
    if evs:
        body.append("<h2>Takeover / fencing timeline</h2>"
                    "<table><tr><th>When</th><th>Event</th>"
                    "<th>Tenant</th><th>Worker</th><th>Epoch</th>"
                    "<th>Detail</th></tr>")
        for e in evs:
            t = e.get("t")
            detail = []
            if e.get("from_worker"):
                detail.append(f"from {e['from_worker']}")
            if e.get("silent_s") is not None:
                detail.append(f"silent {e['silent_s']}s")
            if e.get("reason"):
                detail.append(str(e["reason"]))
            if e.get("cursor"):
                detail.append(f"cursor {e['cursor']}")
            color = {"lease-takeover": "#D8E8F8",
                     "lease-fenced": "#F3BBBC"}.get(e.get("type"), "")
            body.append(
                f"<tr{f' style=background:{color}' if color else ''}>"
                f"<td>{now - t:.1f}s ago</td>" if t else
                "<tr><td>?</td>")
            body.append(
                f"<td>{html.escape(str(e.get('type')))}</td>"
                f"<td>{html.escape(str(e.get('tenant', '-')))}</td>"
                f"<td>{html.escape(str(e.get('worker', '-')))}</td>"
                f"<td>{e.get('epoch', '')}</td>"
                f"<td>{html.escape('; '.join(detail))}</td></tr>")
        body.append("</table>")
    return _page("Checker fleet", "".join(body))


# ---------------------------------------------------------------------------
# Ingest page (ISSUE 16): /ingest — the remote-tenant network tier:
# listeners (from store/ingest/<server>.json status sidecars),
# connected tenants with writer/epoch/cursor/backlog/backpressure
# state, and the fenced-rejection + frame-fault timeline from the
# servers' journals (store/ingest/<server>.jsonl)
# ---------------------------------------------------------------------------

def _ingest_servers() -> list:
    out = []
    root = store.ingest_root()
    if not root.is_dir():
        return out
    for p in sorted(root.glob("*.json")):
        try:
            with open(p) as f:
                out.append(json.load(f))
        except (OSError, json.JSONDecodeError):
            continue
    return out


def _ingest_events(limit: int = 50) -> list:
    """The network-fault timeline: fenced registrations and torn/dup/
    reordered frames from every server's journal, newest first."""
    from jepsen_tpu import telemetry
    evs = []
    root = store.ingest_root()
    if root.is_dir():
        for p in sorted(root.glob("*.jsonl")):
            for e in telemetry.read_events(p):
                if str(e.get("type", "")).startswith("ingest-"):
                    evs.append(e)
    evs.sort(key=lambda e: e.get("t") or 0.0, reverse=True)
    return evs[:limit]


def ingest_html() -> bytes:
    import time as time_mod
    now = time_mod.time()
    body = ["<h1>Remote ingest</h1>",
            "<p><a href='/'>&larr; tests</a> &middot; "
            "<a href='/fleet'>fleet</a> &middot; "
            "<a href='/live'>live</a> &middot; "
            "<a href='/metrics'>metrics</a></p>"]

    servers = _ingest_servers()
    if servers:
        body.append("<h2>Listeners</h2>"
                    "<table><tr><th>Server</th><th>Listen</th>"
                    "<th>Tenants</th><th>Frames ok</th>"
                    "<th>Torn</th><th>Dup</th><th>Reorder</th>"
                    "<th>Fenced</th><th>Resumes</th>"
                    "<th>Last beat</th></tr>")
        for s in servers:
            age = now - (s.get("updated") or 0)
            stale = age > 10.0
            c = s.get("counts") or {}
            body.append(
                f"<tr{' style=background:#F3EABB' if stale else ''}>"
                f"<td>{html.escape(str(s.get('server')))}</td>"
                f"<td>{html.escape(str(s.get('host')))}:"
                f"{s.get('port')}</td>"
                f"<td>{len(s.get('tenants') or {})}"
                f"/{s.get('known_tenants')}</td>"
                f"<td>{c.get('ok')}</td><td>{c.get('torn')}</td>"
                f"<td>{c.get('dup')}</td><td>{c.get('reorder')}</td>"
                f"<td>{c.get('fenced')}</td>"
                f"<td>{c.get('resumes')}</td>"
                f"<td>{age:.1f}s ago"
                f"{' (stale)' if stale else ''}</td></tr>")
        body.append("</table>")
    else:
        body.append("<p>(no listener status files under store/ingest/ "
                    "— start one with <code>serve-checker store/ "
                    "--listen 127.0.0.1:7419</code>)</p>")

    tenant_rows = []
    for s in servers:
        for tenant, t in sorted((s.get("tenants") or {}).items()):
            f = t.get("frames") or {}
            paused = t.get("paused")
            tenant_rows.append(
                f"<tr{' style=background:#F3EABB' if paused else ''}>"
                f"<td>{html.escape(tenant)}</td>"
                f"<td>{html.escape(str(s.get('server')))}</td>"
                f"<td>{html.escape(str(t.get('writer')))}</td>"
                f"<td>{t.get('epoch')}</td>"
                f"<td>{t.get('offset')}/{t.get('seq')}</td>"
                f"<td>{t.get('backlog')}</td>"
                f"<td>{'<b>paused</b>' if paused else 'flowing'}</td>"
                f"<td>{f.get('torn', 0)}/{f.get('dup', 0)}"
                f"/{f.get('reorder', 0)}</td></tr>")
    if tenant_rows:
        body.append("<h2>Connected tenants</h2>"
                    "<table><tr><th>Tenant</th><th>Server</th>"
                    "<th>Writer</th><th>Epoch</th>"
                    "<th>Cursor (off/seq)</th>"
                    "<th>Backlog (bytes)</th><th>Flow</th>"
                    "<th>Torn/dup/reorder</th></tr>"
                    + "".join(tenant_rows) + "</table>")

    evs = _ingest_events()
    if evs:
        body.append("<h2>Fencing / frame-fault timeline</h2>"
                    "<table><tr><th>When</th><th>Event</th>"
                    "<th>Tenant</th><th>Server</th><th>Seq</th>"
                    "<th>Detail</th></tr>")
        for e in evs:
            t = e.get("t")
            detail = []
            if e.get("why"):
                detail.append(str(e["why"]))
            if e.get("writer"):
                detail.append(f"writer {e['writer']}")
            if e.get("epoch") is not None:
                detail.append(f"epoch {e['epoch']}")
            if e.get("resumed"):
                detail.append("resumed")
            color = {"ingest-fenced": "#F3BBBC",
                     "ingest-torn": "#F3EABB",
                     "ingest-dup": "#F3EABB",
                     "ingest-reorder": "#F3EABB",
                     "ingest-pause": "#D8E8F8"}.get(e.get("type"), "")
            body.append(
                f"<tr{f' style=background:{color}' if color else ''}>"
                f"<td>{now - t:.1f}s ago</td>" if t else
                "<tr><td>?</td>")
            body.append(
                f"<td>{html.escape(str(e.get('type')))}</td>"
                f"<td>{html.escape(str(e.get('tenant', '-')))}</td>"
                f"<td>{html.escape(str(e.get('server', '-')))}</td>"
                f"<td>{e.get('seq', '')}</td>"
                f"<td>{html.escape('; '.join(detail))}</td></tr>")
        body.append("</table>")
    return _page("Remote ingest", "".join(body))


# ---------------------------------------------------------------------------
# Campaign pages (ISSUE 13): /campaign index + per-campaign coverage
# matrix (nemesis x workload x anomaly class, gaps visible) — rendered
# from store/campaigns/<name>/{status,coverage}.json
# ---------------------------------------------------------------------------

def _campaign_safe_dir(name: str) -> Path:
    base = store.campaigns_root().resolve()
    p = (base / name).resolve()
    try:
        p.relative_to(base)
    except ValueError:
        raise PermissionError(name)
    return p


def campaign_index_html() -> bytes:
    rows = []
    root = store.campaigns_root()
    names = sorted(p.name for p in root.iterdir()
                   if p.is_dir()) if root.is_dir() else []
    for n in names:
        st = {}
        sp = root / n / "status.json"
        if sp.exists():
            try:
                with open(sp) as f:
                    st = json.load(f)
            except (OSError, json.JSONDecodeError):
                pass
        state = (f"done ({st.get('reason')})" if st.get("done")
                 else "in progress")
        rows.append(
            "<tr>"
            f"<td><a href='/campaign/{quote(n)}'>{html.escape(n)}</a>"
            "</td>"
            f"<td>{html.escape(str(st.get('sut', '?')))}</td>"
            f"<td>{st.get('seed', '?')}</td>"
            f"<td>{st.get('run', 0)}/{st.get('budget', '?')}</td>"
            f"<td>{st.get('novel', 0)}</td>"
            f"<td>{st.get('deduped', 0)}</td>"
            f"<td>{st.get('quarantined', 0)}</td>"
            f"<td>{st.get('leaks', 0)}</td>"
            f"<td>{html.escape(state)}</td></tr>")
    body = ("<h1>Nemesis campaigns</h1>"
            "<p><a href='/'>&larr; tests</a></p>"
            "<table><tr><th>Campaign</th><th>SUT</th><th>Seed</th>"
            "<th>Schedules</th><th>Novel</th><th>Deduped</th>"
            "<th>Quarantined</th><th>Leaks</th><th>State</th></tr>"
            + "".join(rows) + "</table>")
    if not rows:
        body += ("<p>(no campaigns — start one with "
                 "<code>python -m jepsen_tpu.cli campaign run</code>)"
                 "</p>")
    return _page("Campaigns", body)


def campaign_html(name: str) -> bytes:
    """The coverage matrix: one table per workload, nemesis rows x
    anomaly-class columns — EVERY registry nemesis gets a row, so a
    fault class the search never produced coverage for is a visible
    gap, not a missing line."""
    d = _campaign_safe_dir(name)
    if not d.is_dir():
        raise FileNotFoundError(name)
    st, cov = {}, {}
    for fname, box in (("status.json", st), ("coverage.json", cov)):
        p = d / fname
        if p.exists():
            try:
                with open(p) as f:
                    box.update(json.load(f))
            except (OSError, json.JSONDecodeError):
                pass
    body = [f"<h1>campaign {html.escape(name)}</h1>",
            "<p><a href='/campaign'>&larr; campaigns</a> &middot; "
            f"<a href='/files/campaigns/{quote(name)}/ledger.jsonl'>"
            "raw ledger</a></p>"]
    if st:
        body.append(
            "<p>" + " &middot; ".join(
                f"<b>{html.escape(k)}</b>: "
                f"{html.escape(json.dumps(st.get(k)))}"
                for k in ("sut", "seed", "run", "budget", "novel",
                          "deduped", "quarantined", "crashed",
                          "leaks", "signatures", "frontier", "dry",
                          "done", "reason")) + "</p>")
    nemeses = cov.get("nemeses") or []
    workloads = cov.get("workloads") or []
    cells = cov.get("cells") or {}
    classes = sorted({cls for wl in cells.values()
                      for cc in wl.values() for cls in cc})
    if not classes:
        classes = ["none"]
    for wl in workloads:
        body.append(f"<h2>workload: {html.escape(wl)}</h2>"
                    "<table><tr><th>Nemesis</th>"
                    + "".join(f"<th>{html.escape(c)}</th>"
                              for c in classes) + "</tr>")
        for n in nemeses:
            row = (cells.get(n) or {}).get(wl) or {}
            tds = []
            for c in classes:
                v = row.get(c, 0)
                # gaps (never-covered cells) stay visibly grey
                style = "" if v else " style='background:#EAEAEA'"
                tds.append(f"<td{style}>{v or ''}</td>")
            covered = bool(row)
            nm_style = "" if covered else \
                " style='background:#F3EABB'"
            body.append(f"<tr><td{nm_style}>{html.escape(n)}</td>"
                        + "".join(tds) + "</tr>")
        body.append("</table>")
    if not workloads:
        body.append("<p>(no coverage yet)</p>")
    return _page(f"campaign {name}", "".join(body))


def telemetry_run_html(name: str, ts: str) -> bytes:
    from jepsen_tpu import telemetry
    p = _safe_path(f"{name}/{ts}") / "telemetry.jsonl"
    if not p.exists():
        raise FileNotFoundError(p)
    events = telemetry.read_events(p)
    series = telemetry.op_series(events)
    body = [f"<h1>{html.escape(name)} / {html.escape(ts)}</h1>",
            "<p><a href='/telemetry'>&larr; telemetry</a></p>"]
    if series["rate"]:
        span = series["t1"] - series["t0"]
        body.append(f"<p>{span:.1f}s of ops; shaded bands are nemesis "
                    "fault windows</p>")
        body.append(_sparkline_svg(series["rate"], series["windows"],
                                   "#3B6EA5", label="op rate (ops/s)"))
        body.append(_sparkline_svg(series["p95_ms"], series["windows"],
                                   "#A5703B",
                                   label="op latency p95 (ms)"))
    body.append(_dispatch_plans_html(events))
    body.append("<h2>Summary</h2><pre>"
                + html.escape(telemetry.summarize(events)) + "</pre>")
    return _page(f"telemetry {name}/{ts}", "".join(body))


def _dispatch_plans_html(events) -> str:
    """The dispatch-plans panel (ISSUE 8): one row per distinct
    planner-emitted plan — engine, WHY it was chosen, the fallback
    chain below it, the compiled-shape bucket, and any env-knob
    prunes — rendered from the `plan` field attach_dispatch records on
    every verdict."""
    seen: dict = {}
    for e in events:
        if e.get("type") != "dispatch":
            continue
        rec = e.get("record") or {}
        key = (rec.get("engine"), rec.get("why"),
               tuple(rec.get("fallback_chain") or ()))
        if key in seen:
            seen[key]["verdicts"] += e.get("verdicts") or 1
        else:
            seen[key] = {"rec": rec,
                         "verdicts": e.get("verdicts") or 1}
    if not seen:
        return ""
    rows = []
    for (eng, why, fb), info in seen.items():
        rec = info["rec"]
        pl = rec.get("plan") or {}
        pruned = ", ".join(f"{k} &minus;{html.escape(str(e2))}"
                           for k, e2 in (pl.get("pruned") or []))
        # record-level pack fields are what actually ran; the plan's
        # are the intent (they differ when a native error degraded)
        pb = rec.get("pack_backend") or pl.get("pack_backend")
        pt = rec.get("pack_threads", pl.get("pack_threads"))
        pack = f"{pb} ×{pt}" if pb and pt else (pb or "")
        # deep mask-plane provenance (ISSUE 10): record-level fields
        # are what actually ran (e.g. a forced hypercube), the plan's
        # are the route
        dv = rec.get("deep_variant") or pl.get("deep_variant")
        dsh = rec.get("shards", pl.get("shards"))
        deep = f"{dv} ×{dsh}" if dv and dsh else (dv or "")
        rows.append(
            "<tr>"
            f"<td>{html.escape(str(eng))}</td>"
            f"<td>{html.escape(str(why or ''))}</td>"
            f"<td>{html.escape(' → '.join(fb))}</td>"
            f"<td>{html.escape(str(pl.get('bucket') or ''))}</td>"
            f"<td>{html.escape(pack)}</td>"
            f"<td>{html.escape(deep)}</td>"
            f"<td>{pruned}</td>"
            f"<td>{info['verdicts']}</td></tr>")
    return ("<h2>Dispatch plans</h2>"
            "<table><tr><th>Engine</th><th>Why</th>"
            "<th>Fallback chain</th><th>Bucket</th><th>Pack</th>"
            "<th>Deep shard</th>"
            "<th>Pruned by env</th><th>Verdicts</th></tr>"
            + "".join(rows) + "</table>")


# ---------------------------------------------------------------------------
# Causal flight recorder (ISSUE 19): /trace index, per-run flag list,
# and the /trace/<name>/<ts>/<trace_id> waterfall with the detection-
# lag decomposition and the cross-worker handoff link shaded.
# ---------------------------------------------------------------------------

_SEGMENT_COLORS = {"fsync": "#B8D4F0", "frame": "#A8E6CF",
                   "ack": "#FFE9A8", "window": "#F5C6A0",
                   "dispatch": "#E0B8F0", "flag": "#F3BBBC"}


def _trace_events(d: Path) -> list:
    from jepsen_tpu import telemetry
    p = d / "trace-index.jsonl"
    if not p.exists():
        return []
    try:
        return telemetry.read_events(p)
    except Exception:  # noqa: BLE001 - a torn index renders empty
        return []


def _ingest_span_stamps(tenant: str, seq) -> tuple:
    """(fs, recv, synced) for one streamed record, joined at render
    time from every ingest server journal under store/ingest/ — the
    copy that survives the worker that measured them being SIGKILLed
    (the takeover survivor's flag page still renders the full chain)."""
    from jepsen_tpu import telemetry
    fs = recv = synced = None
    root = store.BASE / "ingest"
    if not isinstance(seq, int) or not root.is_dir():
        return fs, recv, synced
    for p in sorted(root.glob("*.jsonl")):
        try:
            evs = telemetry.read_events(p)
        except Exception:  # noqa: BLE001 - skip torn journals
            continue
        for ev in evs:
            if ev.get("type") != "ingest-span" \
                    or ev.get("tenant") != tenant:
                continue
            for mark in ev.get("marks") or []:
                if isinstance(mark, list) and len(mark) == 2 \
                        and mark[0] == seq and fs is None:
                    fs = mark[1]
            lo, hi = ev.get("lo"), ev.get("hi")
            if isinstance(lo, int) and isinstance(hi, int) \
                    and lo <= seq < hi:
                recv = ev.get("recv") if recv is None else recv
                synced = ev.get("synced") if synced is None \
                    else synced
    return fs, recv, synced


def _resolve_segments(name: str, ts: str, rec: dict) -> dict:
    """The record's segment decomposition, re-derived after joining
    any transport stamps the emitting worker lacked (its in-memory
    stamps died with it; the ingest journal's copy did not)."""
    from jepsen_tpu import trace as trace_mod
    stamps = dict(rec.get("stamps") or {})
    if any(stamps.get(k) is None for k in ("fs", "recv", "synced")):
        fs, recv, synced = _ingest_span_stamps(f"{name}/{ts}",
                                               rec.get("seq"))
        for k, v in (("fs", fs), ("recv", recv), ("synced", synced)):
            if stamps.get(k) is None and v is not None:
                stamps[k] = v
    segs = trace_mod.lag_segments(stamps)
    return segs if segs is not None else (rec.get("segments") or {})


def trace_index_html(slowest: int = 0) -> bytes:
    rows = []
    for name, stamps in sorted(store.tests().items()):
        for ts in sorted(stamps, reverse=True):
            d = store.BASE / store._sanitize(name) / ts
            evs = _trace_events(d)
            if not evs:
                continue
            flags = [e for e in evs if e.get("type") == "trace-flag"]
            links = [e for e in evs if e.get("type") == "trace-link"]
            worst = max((e.get("lag_s") or 0.0 for e in flags),
                        default=0.0)
            rows.append((worst, name, ts, len(flags), len(links)))
    rows.sort(reverse=True)
    if slowest:
        rows = rows[:slowest]
    body = ("<h1>Traces</h1>"
            "<p><a href='/'>&larr; tests</a> &middot; "
            "<a href='/live'>live</a> &middot; "
            "<a href='/metrics'>metrics</a></p>"
            "<table><tr><th>Test</th><th>Run</th><th>Flags traced</th>"
            "<th>Handoff links</th><th>Worst lag (s)</th></tr>"
            + "".join(
                f"<tr><td>{html.escape(n)}</td>"
                f"<td><a href='/trace/{quote(n)}/{quote(t)}'>"
                f"{html.escape(t)}</a></td>"
                f"<td>{nf}</td><td>{nl}</td><td>{w:.4f}</td></tr>"
                for w, n, t, nf, nl in rows)
            + "</table>")
    if not rows:
        body += ("<p>(no traced flags yet — run with "
                 "<code>trace: true</code> under a serve-checker)</p>")
    return _page("Traces", body)


def trace_run_html(name: str, ts: str) -> bytes:
    d = _safe_path(f"{name}/{ts}")
    evs = _trace_events(d)
    flags = [e for e in evs if e.get("type") == "trace-flag"]
    links = [e for e in evs if e.get("type") == "trace-link"]
    base = f"/trace/{quote(name)}/{quote(ts)}"
    body = [f"<h1>{html.escape(name)} / {html.escape(ts)} "
            "&mdash; traces</h1>",
            "<p><a href='/trace'>&larr; traces</a> &middot; "
            f"<a href='/live/{quote(name)}/{quote(ts)}'>live</a> "
            "&middot; "
            f"<a href='/files/{quote(name)}/{quote(ts)}/"
            "trace-index.jsonl'>raw index</a></p>"]
    if links:
        body.append(
            "<h2>Cross-worker handoffs</h2><table><tr>"
            "<th>From</th><th>Epoch</th><th>To</th><th>Epoch</th>"
            "<th>Resume span</th><th>Silent (s)</th></tr>"
            + "".join(
                f"<tr style='background:{UNKNOWN_COLOR}'>"
                f"<td>{html.escape(str(lk.get('from_worker')))}</td>"
                f"<td>{html.escape(str(lk.get('from_epoch')))}</td>"
                f"<td>{html.escape(str(lk.get('to_worker')))}</td>"
                f"<td>{html.escape(str(lk.get('to_epoch')))}</td>"
                f"<td><code>{html.escape(str(lk.get('resume_span')))}"
                "</code></td>"
                f"<td>{lk.get('silent_s')}</td></tr>"
                for lk in links)
            + "</table>")
    body.append(
        "<h2>Traced flags</h2><table><tr><th>Trace</th><th>Lane</th>"
        "<th>Op</th><th>Event</th><th>Lag (s)</th>"
        "<th>Dominant segment</th><th>Worker</th></tr>"
        + "".join(
            f"<tr><td><a href='{base}/{quote(str(f.get('trace_id')))}'>"
            f"<code>{html.escape(str(f.get('trace_id'))[:16])}&hellip;"
            "</code></a></td>"
            f"<td>{html.escape(str(f.get('lane')))}</td>"
            f"<td>{html.escape(str(f.get('op_index')))}</td>"
            f"<td>{html.escape(str(f.get('event')))}</td>"
            f"<td>{f.get('lag_s')}</td>"
            f"<td>{html.escape(str(f.get('dominant')))}</td>"
            f"<td>{html.escape(str(f.get('worker')))}</td></tr>"
            for f in flags)
        + "</table>")
    if not flags:
        body.append("<p>(no traced flags in this run)</p>")
    return _page(f"traces {name}/{ts}", "".join(body))


def trace_flag_html(name: str, ts: str, trace_id: str) -> bytes:
    d = _safe_path(f"{name}/{ts}")
    evs = _trace_events(d)
    recs = [e for e in evs if e.get("type") == "trace-flag"
            and str(e.get("trace_id")) == trace_id]
    if not recs:
        raise FileNotFoundError(trace_id)
    links = [e for e in evs if e.get("type") == "trace-link"]
    body = [f"<h1>trace <code>{html.escape(trace_id[:16])}&hellip;"
            f"</code> &mdash; {html.escape(name)} / {html.escape(ts)}"
            "</h1>",
            f"<p><a href='/trace/{quote(name)}/{quote(ts)}'>"
            "&larr; run traces</a></p>"]
    for rec in recs:
        segs = _resolve_segments(name, ts, rec)
        lag = rec.get("lag_s")
        total = sum(v for v in segs.values()
                    if isinstance(v, (int, float))) if segs else 0.0
        body.append(
            f"<h2>flag: {html.escape(str(rec.get('event')))} on lane "
            f"{html.escape(str(rec.get('lane')))} (op "
            f"{html.escape(str(rec.get('op_index')))})</h2>"
            f"<p>span <code>{html.escape(str(rec.get('span')))}</code>"
            f" &middot; worker {html.escape(str(rec.get('worker')))}"
            f" (epoch {html.escape(str(rec.get('epoch')))})"
            f" &middot; context from "
            f"{html.escape(str(rec.get('ctx_source')))}"
            f" &middot; dispatch "
            f"{html.escape(str(rec.get('dispatch_id')))}</p>")
        # the handoff gap, shaded, between the dead worker's last
        # span and this record's parent (the survivor's resume span)
        for lk in links:
            if lk.get("resume_span") == rec.get("parent"):
                body.append(
                    f"<p style='background:{UNKNOWN_COLOR};"
                    "padding:.5em'>cross-worker handoff: "
                    f"<b>{html.escape(str(lk.get('from_worker')))}</b>"
                    f" (epoch {html.escape(str(lk.get('from_epoch')))}"
                    f", span <code>"
                    f"{html.escape(str(lk.get('from_span')))}</code>)"
                    " &rarr; "
                    f"<b>{html.escape(str(lk.get('to_worker')))}</b>"
                    f" resume span <code>"
                    f"{html.escape(str(lk.get('resume_span')))}</code>"
                    f" after {lk.get('silent_s')}s of silence</p>")
        if segs and total > 0:
            bars = "".join(
                f"<td style='background:"
                f"{_SEGMENT_COLORS.get(seg, '#EAEAEA')};width:"
                f"{max(int(600 * (segs.get(seg) or 0) / total), 1)}px'"
                f" title='{html.escape(seg)}: {segs.get(seg)}s'>"
                "</td>"
                for seg in _SEGMENT_COLORS)
            body.append(
                "<table><tr>" + bars + "</tr></table>"
                "<table><tr><th>Segment</th><th>Seconds</th>"
                "<th>Share</th></tr>"
                + "".join(
                    f"<tr><td style='background:"
                    f"{_SEGMENT_COLORS.get(seg, '#EAEAEA')}'>"
                    f"{html.escape(seg)}</td>"
                    f"<td>{segs.get(seg)}</td>"
                    f"<td>{100.0 * (segs.get(seg) or 0) / total:.1f}%"
                    "</td></tr>"
                    for seg in _SEGMENT_COLORS)
                + "</table>")
            if isinstance(lag, (int, float)) and lag > 0:
                pct = abs(total - lag) / lag * 100.0
                body.append(
                    f"<p>segments sum to {total:.6f}s vs measured "
                    f"flag lag {lag}s ({pct:.1f}% apart)</p>")
        stamps = rec.get("stamps") or {}
        body.append(
            "<h3>stamps</h3><table>"
            + "".join(
                f"<tr><th>{html.escape(k)}</th>"
                f"<td>{stamps.get(k)}</td></tr>"
                for k in ("w", "fs", "recv", "synced", "win",
                          "dis_s", "flag") if k in stamps)
            + "</table>")
    return _page(f"trace {trace_id[:16]}", "".join(body))


def metrics_text() -> str:
    """/metrics: the process exposition — federated with every fleet
    worker's exported snapshot (worker_id-labeled, stale-marked) when
    store/fleet/ sidecars exist.  Collisions resolve toward the
    federation: a supervisor's own registry says nothing useful about
    the workers doing the checking."""
    from jepsen_tpu import telemetry
    local = telemetry.snapshot()
    try:
        if not any((store.BASE / "fleet").glob("*.json")):
            return local
        fed = telemetry.federate(store.BASE)
    except Exception:  # noqa: BLE001 - federation must not break
        return local   # scraping the process metrics
    if not fed:
        return local
    fed_names = {ln.split()[2] for ln in fed.splitlines()
                 if ln.startswith("# TYPE ")}
    keep, skip = [], False
    for ln in local.splitlines():
        if ln.startswith("# TYPE "):
            skip = ln.split()[2] in fed_names
        if not skip:
            keep.append(ln)
    return fed + "\n".join(keep) + ("\n" if keep else "")


def zip_bytes(name: str, ts: str) -> bytes:
    d = _safe_path(f"{name}/{ts}")
    buf = io.BytesIO()
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as z:
        for f in sorted(d.rglob("*")):
            if f.is_file():
                z.write(f, f.relative_to(d.parent))
    return buf.getvalue()


_CONTENT_TYPES = {".json": "application/json", ".txt": "text/plain",
                  ".log": "text/plain", ".jsonl": "text/plain",
                  ".html": "text/html", ".png": "image/png",
                  ".svg": "image/svg+xml"}


class Handler(BaseHTTPRequestHandler):
    def log_message(self, *a):  # quiet
        pass

    def _send(self, code: int, body: bytes,
              ctype: str = "text/html; charset=utf-8",
              extra: dict = None) -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        for k, v in (extra or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):  # noqa: N802
        try:
            path = self.path.split("?", 1)[0]
            if path == "/" or path == "":
                return self._send(200, home_html())
            if path == "/metrics":
                return self._send(200, metrics_text().encode(),
                                  "text/plain; version=0.0.4; "
                                  "charset=utf-8")
            if path == "/trace" or path == "/trace/":
                return self._send(200, trace_index_html())
            if path.startswith("/trace/"):
                parts = [unquote(x) for x in
                         path[len("/trace/"):].strip("/").split("/")]
                if len(parts) == 2:
                    return self._send(200, trace_run_html(*parts))
                if len(parts) == 3:
                    return self._send(200, trace_flag_html(*parts))
                return self._send(404, b"not found", "text/plain")
            if path == "/fleet" or path == "/fleet/":
                return self._send(200, fleet_html())
            if path == "/ingest" or path == "/ingest/":
                return self._send(200, ingest_html())
            if path == "/live" or path == "/live/":
                return self._send(200, live_index_html())
            if path.startswith("/live/"):
                parts = [unquote(x) for x in
                         path[len("/live/"):].strip("/").split("/")]
                if len(parts) == 2:
                    return self._send(200, live_run_html(*parts))
                return self._send(404, b"not found", "text/plain")
            if path == "/campaign" or path == "/campaign/":
                return self._send(200, campaign_index_html())
            if path.startswith("/campaign/"):
                parts = [unquote(x) for x in
                         path[len("/campaign/"):].strip("/").split("/")]
                if len(parts) == 1:
                    return self._send(200, campaign_html(parts[0]))
                return self._send(404, b"not found", "text/plain")
            if path == "/telemetry" or path == "/telemetry/":
                return self._send(200, telemetry_index_html())
            if path.startswith("/telemetry/"):
                parts = [unquote(x) for x in
                         path[len("/telemetry/"):].strip("/").split("/")]
                if len(parts) == 2:
                    return self._send(200, telemetry_run_html(*parts))
                return self._send(404, b"not found", "text/plain")
            if path.startswith("/elle/"):
                parts = [unquote(x) for x in
                         path[len("/elle/"):].strip("/").split("/")]
                if len(parts) == 2:
                    return self._send(200, elle_html(*parts))
                return self._send(404, b"not found", "text/plain")
            if path.startswith("/files/"):
                rel = unquote(path[len("/files/"):])
                p = _safe_path(rel)
                if p.is_dir():
                    return self._send(200, dir_html(rel, p))
                if p.is_file():
                    ctype = _CONTENT_TYPES.get(p.suffix,
                                               "application/octet-stream")
                    return self._send(200, p.read_bytes(), ctype)
                return self._send(404, b"not found", "text/plain")
            if path.startswith("/zip/"):
                parts = [unquote(x) for x in
                         path[len("/zip/"):].strip("/").split("/")]
                if len(parts) == 2:
                    data = zip_bytes(*parts)
                    fname = f"{parts[0]}-{parts[1]}.zip"
                    return self._send(
                        200, data, "application/zip",
                        {"Content-Disposition":
                         f"attachment; filename=\"{fname}\""})
            return self._send(404, b"not found", "text/plain")
        except PermissionError:
            return self._send(403, b"forbidden", "text/plain")
        except (FileNotFoundError, NotADirectoryError):
            return self._send(404, b"not found", "text/plain")
        except Exception as e:  # pragma: no cover
            return self._send(500, str(e).encode(), "text/plain")


def serve(host: str = "0.0.0.0", port: int = 8080, block: bool = True):
    """Start the dashboard (web.clj serve! :336).  Non-blocking mode
    returns the server; call .shutdown() to stop."""
    srv = ThreadingHTTPServer((host, port), Handler)
    if block:
        print(f"Serving store on http://{host}:{srv.server_address[1]}/")
        try:
            srv.serve_forever()
        finally:
            srv.server_close()
        return srv
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    return srv
