"""Node scripting helpers (reference: `jepsen/src/jepsen/control/util.clj`):
file tests, temp dirs, cached downloads, archive installs, daemon
management — everything a DB impl needs to provision a node, all built
on the bound `control` session.
"""

from __future__ import annotations

import base64
import logging
from typing import Optional

from jepsen_tpu import control as c
from jepsen_tpu.control import lit

log = logging.getLogger("jepsen.control.util")

WGET_CACHE = "/tmp/jepsen/wget-cache"


def exists(path: str) -> bool:
    """Does a file/dir exist on the node? (util.clj exists? :18)"""
    out = c.execute(lit(f"test -e {c.escape(path)} && echo true "
                        "|| echo false"))
    return out.strip() == "true"


def file_mode(path: str) -> str:
    return c.execute("stat", "-c", "%a", path)


def tmp_dir() -> str:
    """Fresh temp dir on the node (util.clj tmp-dir! :42)."""
    return c.execute("mktemp", "-d", "-t", "jepsen.XXXXXXXX")


def tmp_file(suffix: str = "") -> str:
    return c.execute("mktemp", "-t", f"jepsen.XXXXXXXX{suffix}")


def _cache_path(url: str) -> str:
    key = base64.urlsafe_b64encode(url.encode()).decode().rstrip("=")
    return f"{WGET_CACHE}/{key}"


def cached_wget(url: str, force: bool = False) -> str:
    """Download url on the node into a base64-keyed cache; returns the
    cached path (util.clj cached-wget! :79)."""
    path = _cache_path(url)
    if force:
        c.execute("rm", "-f", path, check=False)
    if not exists(path):
        log.info("downloading %s", url)
        c.execute("mkdir", "-p", WGET_CACHE)
        tmp = path + ".tmp"
        c.execute("wget", "--tries", "20", "--waitretry", "60",
                  "--retry-connrefused", "-O", tmp, url)
        c.execute("mv", tmp, path)
    return path


def _archive_kind(url: str) -> str:
    u = url.split("?", 1)[0].lower()
    if u.endswith(".zip"):
        return "zip"
    return "tar"


def install_archive(url: str, dest: str, force: bool = False,
                    user: Optional[str] = None) -> str:
    """Download + extract an archive to dest, flattening a single
    top-level directory; retries once on a corrupt archive by busting
    the cache (util.clj install-archive! :106)."""
    for attempt in (0, 1):
        path = (cached_wget(url, force=force or attempt > 0)
                if url.startswith(("http://", "https://", "ftp://"))
                else url)
        c.execute("rm", "-rf", dest, check=False)
        tmp = tmp_dir()
        try:
            if _archive_kind(url) == "zip":
                rc_cmd = f"cd {c.escape(tmp)} && unzip {c.escape(path)}"
            else:
                rc_cmd = (f"cd {c.escape(tmp)} && "
                          f"tar xf {c.escape(path)}")
            try:
                c.execute(lit(rc_cmd))
            except c.RemoteError as e:
                blob = f"{e.err or ''} {e.out or ''}"
                corrupt = any(s in blob.lower() for s in
                              ("unexpected end of file", "not in gzip",
                               "corrupt", "end-of-central-directory"))
                if corrupt and attempt == 0:
                    log.warning("corrupt archive %s; re-downloading", url)
                    continue
                raise
            # Flatten: if the archive made exactly one top dir, move it;
            # else move the whole tmp dir.
            entries = c.execute(lit(f"ls -A {c.escape(tmp)}")).split()
            c.execute("mkdir", "-p", lit("$(dirname " + c.escape(dest) + ")"))
            if len(entries) == 1:
                c.execute("mv", f"{tmp}/{entries[0]}", dest)
            else:
                c.execute("mv", tmp, dest)
            if user:
                c.execute("chown", "-R", user, dest)
            return dest
        finally:
            c.execute("rm", "-rf", tmp, check=False)
    raise AssertionError("unreachable")


# ---------------------------------------------------------------------------
# Mounts (consumed by the faultfs FUSE layer; generic on purpose)
# ---------------------------------------------------------------------------

def mounted(path: str) -> bool:
    """Is anything mounted at exactly `path` on the node?"""
    out = c.execute(lit(f"awk -v m={c.escape(path)} "
                        "'$2 == m {print \"yes\"; exit}' /proc/mounts"),
                    check=False)
    return out.strip() == "yes"


def umount(path: str, lazy_fallback: bool = True) -> None:
    """Unmount `path`, escalating to a lazy detach (`umount -l`) when
    the plain umount fails — a wedged or SIGKILLed FUSE daemon keeps a
    plain umount blocked/EBUSY forever, and the lazy detach is the
    documented escape hatch.  Idempotent: nothing mounted is a no-op."""
    p = c.escape(path)
    tail = f"|| umount -l {p} 2>/dev/null " if lazy_fallback else ""
    c.execute(lit(f"umount {p} 2>/dev/null {tail}|| true"), check=False)


# ---------------------------------------------------------------------------
# Processes and daemons (util.clj:191-253)
# ---------------------------------------------------------------------------

def grepkill(pattern: str, signal: str = "9") -> None:
    """Kill processes matching a pattern (util.clj grepkill! :191)."""
    c.execute("pkill", f"-{signal}", "-f", pattern, check=False)


def signal(pattern: str, sig: str) -> None:
    grepkill(pattern, sig)


def start_daemon(bin_path: str, *args, chdir: Optional[str] = None,
                 logfile: str = "/dev/null",
                 pidfile: str = "/var/run/jepsen-daemon.pid",
                 make_pidfile: bool = True,
                 env: Optional[dict] = None) -> None:
    """Start a background daemon with a pidfile
    (util.clj start-daemon! :208: start-stop-daemon --start --background
    --make-pidfile --pidfile --chdir --exec … >> logfile)."""
    parts = []
    if env:
        parts += ["env"] + [c.escape(f"{k}={v}") for k, v in env.items()]
    parts += ["start-stop-daemon", "--start", "--background",
              "--no-close", "--oknodo"]
    if make_pidfile:
        parts += ["--make-pidfile"]
    parts += ["--pidfile", c.escape(pidfile)]
    if chdir:
        parts += ["--chdir", c.escape(chdir)]
    parts += ["--exec", c.escape(bin_path), "--"]
    parts += [c.escape(a) for a in args]
    parts += [">>", c.escape(logfile), "2>&1"]
    c.execute(lit(" ".join(parts)))


def stop_daemon(pidfile: str = "/var/run/jepsen-daemon.pid",
                bin_path: Optional[str] = None) -> None:
    """Kill a daemon by pidfile (+ optional exec match), wait for it to
    die, remove the pidfile (util.clj stop-daemon! :238)."""
    parts = ["start-stop-daemon", "--stop", "--oknodo", "--retry", "5",
             "--pidfile", c.escape(pidfile)]
    if bin_path:
        parts += ["--exec", c.escape(bin_path)]
    c.execute(lit(" ".join(parts)), check=False)
    c.execute("rm", "-f", pidfile, check=False)


def daemon_running(pidfile: str) -> Optional[bool]:
    """True/False if the pidfile's process is/isn't alive; None when
    there is no pidfile (util.clj daemon-running? :253)."""
    if not exists(pidfile):
        return None
    out = c.execute(lit(f"kill -0 $(cat {c.escape(pidfile)}) "
                        "2>/dev/null && echo live || echo dead"),
                    check=False)
    return out.strip() == "live"
