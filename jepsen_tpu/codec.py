"""Serialization codec for ops and test data (reference:
`jepsen/src/jepsen/codec.clj:9-17` — EDN bytes `encode`/`decode`).

The reference speaks EDN because it is Clojure; our canonical in-memory
form is Python dicts/lists.  This module provides:

  * `encode`/`decode`     — bytes round-trip of op/test data (EDN text,
                            matching the reference's wire format)
  * `edn_str`/`read_edn`  — a small EDN printer/reader covering the
                            subset Jepsen actually serializes: nil,
                            booleans, ints, floats, strings, keywords,
                            symbols, vectors, lists, sets, and maps
                            (store.clj:185-225 reads histories back with
                            exactly this shape)

Python-side conventions: EDN keywords `:foo` decode to strings `"foo"`;
maps with string keys encode with keyword keys (the op format
`{:process 0 :type :invoke :f :read :value nil}` from util.clj:146-165).
"""

from __future__ import annotations

from typing import Any

# ---------------------------------------------------------------------------
# Printer
# ---------------------------------------------------------------------------

_KEYWORD_SAFE = set("abcdefghijklmnopqrstuvwxyz"
                    "ABCDEFGHIJKLMNOPQRSTUVWXYZ"
                    "0123456789*+!-_?<>=./#")


def _keyword_ok(s: str) -> bool:
    return bool(s) and not s[0].isdigit() and all(c in _KEYWORD_SAFE
                                                  for c in s)


def edn_str(x: Any) -> str:
    """Print x as EDN.  Dict keys that look like keywords become
    keywords; everything else stays a string."""
    if x is None:
        return "nil"
    if x is True:
        return "true"
    if x is False:
        return "false"
    if isinstance(x, str):
        return '"' + x.replace("\\", "\\\\").replace('"', '\\"') \
                      .replace("\n", "\\n").replace("\t", "\\t") + '"'
    if isinstance(x, bool):  # pragma: no cover — caught above
        return "true" if x else "false"
    if isinstance(x, int):
        return str(x)
    if isinstance(x, float):
        return repr(x)
    if isinstance(x, (list, tuple)):
        return "[" + " ".join(edn_str(v) for v in x) + "]"
    if isinstance(x, (set, frozenset)):
        return "#{" + " ".join(sorted(edn_str(v) for v in x)) + "}"
    if isinstance(x, dict):
        parts = []
        for k, v in x.items():
            if isinstance(k, str) and _keyword_ok(k):
                ks = ":" + k
            else:
                ks = edn_str(k)
            # op maps: :type/:f values are keywords in the reference's
            # history format ({:type :ok :f :cas}, util.clj:146-165)
            if (k in ("type", "f") and isinstance(v, str)
                    and _keyword_ok(v)):
                parts.append(ks + " :" + v)
            else:
                parts.append(ks + " " + edn_str(v))
        return "{" + ", ".join(parts) + "}"
    # ops and other objects that know how to render themselves
    to_map = getattr(x, "to_map", None)
    if callable(to_map):
        return edn_str(to_map())
    return edn_str(str(x))


# ---------------------------------------------------------------------------
# Reader
# ---------------------------------------------------------------------------

class _Reader:
    _WS = set(" \t\n\r,")
    _DELIM = set(" \t\n\r,()[]{}\"")

    def __init__(self, s: str):
        self.s = s
        self.i = 0

    def _skip_ws(self) -> None:
        s, n = self.s, len(self.s)
        while self.i < n:
            c = s[self.i]
            if c in self._WS:
                self.i += 1
            elif c == ";":  # comment to EOL
                while self.i < n and s[self.i] != "\n":
                    self.i += 1
            else:
                return

    def _peek(self) -> str:
        return self.s[self.i] if self.i < len(self.s) else ""

    def read(self) -> Any:
        self._skip_ws()
        c = self._peek()
        if c == "":
            raise ValueError("unexpected EOF in EDN")
        if c == "{":
            self.i += 1
            return self._read_map()
        if c == "[":
            self.i += 1
            return self._read_seq("]")
        if c == "(":
            self.i += 1
            return self._read_seq(")")
        if c == "#":
            if self.s.startswith("#{", self.i):
                self.i += 2
                return set(self._read_seq("}"))
            # tagged literal: read and drop the tag, keep the value
            self.i += 1
            self._read_token()
            return self.read()
        if c == '"':
            return self._read_string()
        if c == ":":
            self.i += 1
            return self._read_token()  # keywords -> plain strings
        return self._read_atom()

    def _read_map(self) -> dict:
        out = {}
        while True:
            self._skip_ws()
            if self._peek() == "}":
                self.i += 1
                return out
            k = self.read()
            v = self.read()
            if isinstance(k, (list, set)):
                k = tuple(k)  # hashable
            out[k] = v

    def _read_seq(self, close: str) -> list:
        out = []
        while True:
            self._skip_ws()
            if self._peek() == close:
                self.i += 1
                return out
            out.append(self.read())

    def _read_string(self) -> str:
        assert self.s[self.i] == '"'
        self.i += 1
        out = []
        s, n = self.s, len(self.s)
        while self.i < n:
            c = s[self.i]
            if c == "\\":
                if self.i + 1 >= n:
                    raise ValueError("unterminated string in EDN")
                nxt = s[self.i + 1]
                out.append({"n": "\n", "t": "\t", "r": "\r",
                            '"': '"', "\\": "\\"}.get(nxt, nxt))
                self.i += 2
            elif c == '"':
                self.i += 1
                return "".join(out)
            else:
                out.append(c)
                self.i += 1
        raise ValueError("unterminated string in EDN")

    def _read_token(self) -> str:
        start = self.i
        s, n = self.s, len(self.s)
        while self.i < n and s[self.i] not in self._DELIM:
            self.i += 1
        return s[start:self.i]

    def _read_atom(self) -> Any:
        tok = self._read_token()
        if not tok:
            # A delimiter where an atom was expected (e.g. "[1 2)") —
            # raising here keeps malformed input from looping forever.
            raise ValueError(
                f"unexpected {self.s[self.i:self.i + 1]!r} at "
                f"position {self.i}")
        if tok == "nil":
            return None
        if tok == "true":
            return True
        if tok == "false":
            return False
        try:
            return int(tok)
        except ValueError:
            pass
        try:
            return float(tok)
        except ValueError:
            pass
        return tok  # symbol -> string


def read_edn(s: str) -> Any:
    """Parse one EDN form from s."""
    return _Reader(s).read()


def read_edn_all(s: str) -> list:
    """Parse every top-level EDN form in s (e.g. a history file of one
    op map per line, store.clj write-history!)."""
    r = _Reader(s)
    out = []
    while True:
        r._skip_ws()
        if r.i >= len(r.s):
            return out
        out.append(r.read())


# ---------------------------------------------------------------------------
# Bytes API (codec.clj:9-17)
# ---------------------------------------------------------------------------

def encode(x: Any) -> bytes:
    """Object -> EDN bytes (codec.clj encode :9-12)."""
    return edn_str(x).encode("utf-8")


def decode(b: bytes) -> Any:
    """EDN bytes -> object (codec.clj decode :14-17); b'' -> None like
    the reference's nil-on-empty behavior."""
    if not b:
        return None
    return read_edn(b.decode("utf-8"))
