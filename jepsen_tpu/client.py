"""Client protocol: applies operations to a database
(reference: `jepsen/src/jepsen/client.clj:8-35`)."""

from __future__ import annotations


class Client:
    """DB client lifecycle.  `open` binds to a node and must not affect
    logical test state; `setup` prepares DB state once; `invoke` applies
    one op and returns the completion; `close`/`teardown` mirror them."""

    def open(self, test, node) -> "Client":
        return self

    def close(self, test) -> None:
        pass

    def setup(self, test) -> None:
        pass

    def invoke(self, test, op):
        raise NotImplementedError

    def teardown(self, test) -> None:
        pass


class Noop(Client):
    """client.clj:29-35: acks everything."""

    def invoke(self, test, op):
        return op.assoc(type="ok")


noop = Noop()


def open_client(client: Client, test, node) -> Client:
    """open! + setup! (client.clj open-compat! :37-50)."""
    c = client.open(test, node)
    assert c is not None, f"client.open returned None from {client!r}"
    c.setup(test)
    return c


def close_client(client: Client, test) -> None:
    """teardown! + close! (client.clj close-compat! :60-70)."""
    client.teardown(test)
    client.close(test)
