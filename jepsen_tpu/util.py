"""Kitchen sink utilities (reference: `jepsen/src/jepsen/util.clj`)."""

from __future__ import annotations

import logging
import contextlib
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Iterable, Optional

log = logging.getLogger("jepsen")

# ---------------------------------------------------------------------------
# Relative time (util.clj:279-288): one origin per test run, shared by all
# worker threads (the reference conveys a dynamic var into futures).
# ---------------------------------------------------------------------------

_time_lock = threading.Lock()
_origins: list[int] = []


class with_relative_time:
    """Context manager establishing the time origin for relative-time."""

    def __enter__(self):
        with _time_lock:
            _origins.append(time.monotonic_ns())
        return self

    def __exit__(self, *exc):
        with _time_lock:
            _origins.pop()
        return False


def relative_time_nanos() -> int:
    """Nanoseconds since the innermost with_relative_time origin."""
    with _time_lock:
        origin = _origins[-1] if _origins else 0
    return time.monotonic_ns() - origin


def nanos_to_ms(ns) -> float:
    return ns / 1e6


def nanos_to_secs(ns) -> float:
    return ns / 1e9


def secs_to_nanos(s) -> int:
    return int(s * 1e9)


# ---------------------------------------------------------------------------
# Parallel map with exception propagation (dom-top real-pmap, used at
# core.clj:171-197 and control.clj:369)
# ---------------------------------------------------------------------------

def real_pmap(f: Callable, xs: Iterable) -> list:
    """Map f over xs with one thread per element; re-raises the first
    exception after all complete."""
    xs = list(xs)
    if not xs:
        return []
    if len(xs) == 1:
        return [f(xs[0])]
    with ThreadPoolExecutor(max_workers=len(xs)) as ex:
        futs = [ex.submit(f, x) for x in xs]
        results, first_err = [], None
        for fut in futs:
            try:
                results.append(fut.result())
            except Exception as e:
                if first_err is None:
                    first_err = e
                results.append(e)
        if first_err is not None:
            raise first_err
        return results


def bounded_pmap(f: Callable, xs: Iterable, bound: Optional[int] = None) -> list:
    """Parallel map with bounded worker count (dom-top bounded-pmap,
    used by independent/checker independent.clj:247)."""
    import os
    xs = list(xs)
    if not xs:
        return []
    bound = bound or min(32, (os.cpu_count() or 4) + 2)
    with ThreadPoolExecutor(max_workers=min(bound, len(xs))) as ex:
        return list(ex.map(f, xs))


def fcatch(f: Callable) -> Callable:
    """Returns a fn returning, rather than throwing, exceptions
    (util.clj meh/fcatch)."""

    def wrapper(*a, **kw):
        try:
            return f(*a, **kw)
        except Exception as e:
            return e

    return wrapper


class with_retry:
    """Retry decorator-ish helper: with_retry(tries)(f, *args)."""

    def __init__(self, tries: int = 3, backoff: float = 0.0):
        self.tries = tries
        self.backoff = backoff

    def __call__(self, f, *args, **kw):
        err = None
        for i in range(self.tries):
            try:
                return f(*args, **kw)
            except Exception as e:
                err = e
                if self.backoff:
                    time.sleep(self.backoff)
        raise err


# ---------------------------------------------------------------------------
# Cooperative cancellation (the watchdog / bounded-invoke story): Python
# threads cannot be killed, so every wrapper that abandons a thread on
# timeout instead installs a per-thread cancel token the abandoned body
# can poll.  Long-running clients and nemeses check `util.cancelled()`
# in their wait loops and return early, so abandoned threads retire
# promptly instead of accumulating for the rest of the run.
# ---------------------------------------------------------------------------

_cancel_local = threading.local()


@contextlib.contextmanager
def cancel_scope(token: threading.Event):
    """Bind `token` as the current thread's cancel token for the body.
    Installed by the thread-spawning timeout wrappers (util.timeout,
    core._bounded_invoke) around the abandoned-able call."""
    prev = getattr(_cancel_local, "token", None)
    _cancel_local.token = token
    try:
        yield token
    finally:
        _cancel_local.token = prev


def cancel_token() -> Optional[threading.Event]:
    """The current thread's cancel token, or None outside any bounded
    call.  Cooperative bodies wait on this instead of bare sleep."""
    return getattr(_cancel_local, "token", None)


def cancelled() -> bool:
    """True when the caller has been abandoned by its timeout wrapper
    and should return as soon as it conveniently can."""
    t = cancel_token()
    return t is not None and t.is_set()


def timeout(seconds: float, default, f: Callable, *args):
    """Run f in a thread with a wall-clock bound; yields default on
    timeout (util.clj:311 — the thread is abandoned, not killed, which
    is also true of the reference's variant).  The abandoned thread is
    a daemon and gets a cancel token set at abandonment, so an f that
    polls `util.cancelled()` retires promptly instead of running
    forever (the nemesis.Timeout thread-leak fix)."""
    result = [default]
    done = threading.Event()
    cancel = threading.Event()

    def run():
        with cancel_scope(cancel):
            try:
                result[0] = f(*args)
            finally:
                done.set()

    t = threading.Thread(target=run, daemon=True)
    t.start()
    if not done.wait(seconds):
        cancel.set()
        return default
    return result[0]


def log_op(op) -> None:
    """TSV op log line (util.clj:208-212, called from core.clj:311,337)."""
    log.info("%s", op)


def majority(n: int) -> int:
    """Smallest majority of n (util.clj)."""
    return n // 2 + 1


def chunk_vec(n: int, xs: list) -> list[list]:
    """Partition xs into chunks of size n (util.clj:117-126)."""
    return [xs[i:i + n] for i in range(0, len(xs), n)]


class NamedLocks:
    """A keyed lock table: `with locks.hold(key):` serializes on a lock
    unique to that key (util.clj named-locks :729-768 — the reference
    uses them to guard per-resource critical sections without one
    global lock)."""

    def __init__(self):
        self._guard = threading.Lock()
        self._locks: dict = {}

    def get(self, key) -> threading.RLock:
        with self._guard:
            lock = self._locks.get(key)
            if lock is None:
                lock = self._locks[key] = threading.RLock()
            return lock

    @contextlib.contextmanager
    def hold(self, key):
        lock = self.get(key)
        with lock:
            yield lock


def named_locks() -> NamedLocks:
    return NamedLocks()
