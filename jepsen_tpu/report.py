"""Report rendering helpers (reference: `jepsen/src/jepsen/report.clj`,
16 LoC — the `to` macro that tees human-readable output into a file
under the test's store directory while also printing it).

    with report.to(test, "linearizability.txt") as out:
        out.write(...)
"""

from __future__ import annotations

import contextlib
import io
import sys

from jepsen_tpu import store


@contextlib.contextmanager
def to(test, filename: str, echo: bool = True):
    """Write a report file under the test's store dir, echoing to
    stdout like the reference's `to` macro (report.clj:7-16)."""
    path = store.make_path(test, filename)
    buf = io.StringIO()
    yield buf
    text = buf.getvalue()
    with open(path, "w") as f:
        f.write(text)
    if echo:
        sys.stdout.write(text)


def write(test, filename: str, text: str, echo: bool = False) -> str:
    """One-shot report write; returns the path."""
    with to(test, filename, echo=echo) as out:
        out.write(text)
    return str(store.path(test, filename))
