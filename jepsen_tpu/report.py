"""Report rendering helpers (reference: `jepsen/src/jepsen/report.clj`,
16 LoC — the `to` macro that tees human-readable output into a file
under the test's store directory while also printing it).

    with report.to(test, "linearizability.txt") as out:
        out.write(...)
"""

from __future__ import annotations

import contextlib
import io
import sys

from jepsen_tpu import store


@contextlib.contextmanager
def to(test, filename: str, echo: bool = True):
    """Write a report file under the test's store dir, echoing to
    stdout like the reference's `to` macro (report.clj:7-16)."""
    path = store.make_path(test, filename)
    buf = io.StringIO()
    yield buf
    text = buf.getvalue()
    with open(path, "w") as f:
        f.write(text)
    if echo:
        sys.stdout.write(text)


def write(test, filename: str, text: str, echo: bool = False) -> str:
    """One-shot report write; returns the path."""
    with to(test, filename, echo=echo) as out:
        out.write(text)
    return str(store.path(test, filename))


# ---------------------------------------------------------------------------
# Transactional anomaly section (checker/elle.py verdicts)
# ---------------------------------------------------------------------------

def _fmt_op(d: dict) -> str:
    return (f"{d.get('process')}\t{d.get('type')}\t{d.get('f')}\t"
            f"{d.get('value')}")


def elle_section(result: dict) -> str:
    """Human-readable anomaly section for one elle verdict: the
    isolation damage first, then one explicit witness per anomaly."""
    lines = ["Transactional isolation (elle)",
             "=" * 30, ""]
    lines.append(f"txns analyzed:   {result.get('txn-count', 0)}"
                 f"  (workload {result.get('workload', '?')},"
                 f" engine {result.get('engine', '?')})")
    if result.get("shards"):
        lines.append(f"sharded closure: {result['shards']} device(s),"
                     f" {result.get('rounds', '?')} squaring round(s)"
                     " (bit-packed planes)")
    if result.get("valid?") == "unknown" and result.get("degraded"):
        lines += ["", f"VERDICT UNKNOWN: oracle degraded "
                      f"({result['degraded']}) — bounds disclosed, "
                      "not a pass."]
    kinds = result.get("anomaly-types") or []
    if not kinds:
        lines += ["", "No anomalies detected.",
                  "Consistent with: serializable."]
        return "\n".join(lines) + "\n"
    lines.append(f"anomalies found: {', '.join(kinds)}")
    weakest = result.get("weakest-violated")
    if weakest:
        lines.append(f"weakest violated isolation level: {weakest}")
        lines.append("ruled out: " + ", ".join(result.get("not", [])))
    anomalies = result.get("anomalies") or {}
    for kind in kinds:
        lines += ["", f"-- {kind} " + "-" * max(1, 40 - len(kind))]
        for w in anomalies.get(kind, [])[:4]:
            if "cycle" in w:
                edges = w.get("edges", [])
                for i, opd in enumerate(w["cycle"]):
                    lines.append("  " + _fmt_op(opd))
                    if i < len(edges):
                        lines.append(f"    --{edges[i]}-->")
            elif "op" in w:
                lines.append("  " + _fmt_op(w["op"])
                             + f"   mop {w.get('mop')}")
                if w.get("kind"):
                    lines.append(f"    ({w['kind']})")
            else:
                lines.append(f"  {w}")
        extra = len(anomalies.get(kind, [])) - 4
        if extra > 0:
            lines.append(f"  ... {extra} more {kind} witness(es)")
    return "\n".join(lines) + "\n"


def write_elle(test, result: dict, opts=None) -> str:
    """Render the anomaly section under the test's store dir (and the
    per-key subdirectory when the independent checker provides one)."""
    subdir = list((opts or {}).get("subdirectory") or [])
    path = store.make_path(test, *subdir, "elle.txt")
    with open(path, "w") as f:
        f.write(elle_section(result))
    return str(path)
