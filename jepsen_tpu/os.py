"""OS protocol: operating system setup/teardown on db nodes
(reference: `jepsen/src/jepsen/os.clj`)."""

from __future__ import annotations


class OS:
    def setup(self, test, node) -> None:
        pass

    def teardown(self, test, node) -> None:
        pass


class Noop(OS):
    pass


noop = Noop()
