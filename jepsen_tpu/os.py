"""OS protocol: operating system setup/teardown on db nodes
(reference: `jepsen/src/jepsen/os.clj`)."""

from __future__ import annotations


def setup_hostfile(test, node) -> None:
    """Write /etc/hosts mapping every test node — the shared contract of
    debian.clj:12-30 / smartos.clj setup-hostfile! (one implementation;
    the per-OS modules re-export it)."""
    from jepsen_tpu import control as c
    from jepsen_tpu.control import lit

    lines = ["127.0.0.1 localhost"]
    for n in test.get("nodes") or []:
        ip = c.execute(lit(f"getent hosts {c.escape(n)} | head -n1 "
                           "| cut -d' ' -f1"), check=False) or n
        lines.append(f"{ip.strip() or n} {n}")
    c.upload_str("\n".join(lines) + "\n", "/etc/hosts.jepsen")
    c.execute(lit("cp /etc/hosts.jepsen /etc/hosts"))


class OS:
    def setup(self, test, node) -> None:
        pass

    def teardown(self, test, node) -> None:
        pass


class Noop(OS):
    pass


noop = Noop()
