"""Operation histories: the core data structure of the framework.

A history is an ordered sequence of *operations*.  Each op is a small
record — equivalent to the reference's Clojure maps
(`jepsen/src/jepsen/util.clj:146-206`, `jepsen/src/jepsen/core.clj:55-59`)
— with fields:

  index    monotone position in the history (knossos.history/index)
  process  logical single-threaded actor id (int), or NEMESIS
  type     one of invoke | ok | fail | info
  f        operation function tag (e.g. 'read, 'write, 'cas) — any hashable
  value    op payload; for reads the invoke carries None and the completion
           carries the observed value
  time     relative nanoseconds since test start
  error    optional error payload on non-ok completions

On the device side a history becomes a *columnar* struct-of-arrays
(`pack()`), replacing the map-per-op vectors: int32/int64 arrays that JAX
kernels consume directly.  See SURVEY.md §7 (history core + op codec).
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import threading
import time
import zlib
from pathlib import Path
from typing import Any, Callable, Iterable, Iterator, Optional

import numpy as np

log = logging.getLogger("jepsen")

# Sentinel process id for the nemesis (the reference uses the keyword
# :nemesis; we reserve a negative int so process columns stay integral).
NEMESIS = -1

INVOKE, OK, FAIL, INFO = "invoke", "ok", "fail", "info"
TYPES = (INVOKE, OK, FAIL, INFO)
TYPE_CODE = {t: i for i, t in enumerate(TYPES)}
CODE_TYPE = {i: t for t, i in TYPE_CODE.items()}


@dataclasses.dataclass
class Op:
    """One operation record.  Mutable by design: the worker loop assigns
    :index/:time/:process as ops flow through it, like the reference's
    `assoc` chain (`core.clj:306-308`)."""

    process: Any = None
    type: str = INVOKE
    f: Any = None
    value: Any = None
    time: Optional[int] = None
    index: Optional[int] = None
    error: Any = None
    extra: dict = dataclasses.field(default_factory=dict)

    # -- dict-ish ergonomics -------------------------------------------------
    def __getitem__(self, k):
        if k in self.__dataclass_fields__ and k != "extra":
            return getattr(self, k)
        return self.extra[k]

    def get(self, k, default=None):
        try:
            return self[k]
        except KeyError:
            return default

    def __contains__(self, k):
        if k in ("process", "type", "f", "value", "time", "index", "error"):
            return getattr(self, k) is not None
        return k in self.extra

    def assoc(self, **kw) -> "Op":
        """Functional update: returns a copy with fields replaced.
        Hand-rolled rather than dataclasses.replace — this runs for
        every op in the worker loop and again per-op in history prep,
        and replace()'s re-init costs ~10x a plain copy."""
        out = object.__new__(Op)
        d = out.__dict__
        d.update(self.__dict__)
        extra = None
        for k, v in kw.items():
            if k in _OP_FIELDS:
                d[k] = v
            else:
                if extra is None:
                    extra = dict(self.extra)
                extra[k] = v
        d["extra"] = extra if extra is not None else dict(self.extra)
        return out

    # -- predicates (knossos.op parity: invoke? ok? fail? info?) -------------
    @property
    def is_invoke(self):
        return self.type == INVOKE

    @property
    def is_ok(self):
        return self.type == OK

    @property
    def is_fail(self):
        return self.type == FAIL

    @property
    def is_info(self):
        return self.type == INFO

    def to_dict(self) -> dict:
        v = self.value
        if type(v).__name__ == "KV" and isinstance(v, tuple):
            # Tag independent-key tuples so they survive the JSON
            # round-trip — the reference registers a custom Fressian
            # handler for MapEntry for exactly this (store.clj:28-123);
            # without it, `analyze` on a stored keyed history finds no
            # keys and trivially passes.
            v = {"__kv__": [v[0], v[1]]}
        d = {"index": self.index, "process": self.process, "type": self.type,
             "f": self.f, "value": v, "time": self.time}
        if self.error is not None:
            d["error"] = self.error
        d.update(self.extra)
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "Op":
        d = dict(d)
        kw = {k: d.pop(k) for k in
              ("process", "type", "f", "value", "time", "index", "error")
              if k in d}
        v = kw.get("value")
        if isinstance(v, dict) and set(v) == {"__kv__"}:
            from jepsen_tpu.independent import KV
            kw["value"] = KV(*v["__kv__"])
        return cls(extra=d, **kw)

    def __str__(self):
        err = f"\t{self.error}" if self.error is not None else ""
        return f"{self.process}\t{self.type}\t{self.f}\t{self.value}{err}"


_OP_FIELDS = frozenset(f for f in Op.__dataclass_fields__
                       if f != "extra")


# Convenience constructors (knossos.core/{invoke-op, ok-op, fail-op} parity —
# used heavily by the reference's checker tests, checker_test.clj:5-7).
def invoke_op(process, f, value, **kw):
    return Op(process=process, type=INVOKE, f=f, value=value, **kw)


def ok_op(process, f, value, **kw):
    return Op(process=process, type=OK, f=f, value=value, **kw)


def fail_op(process, f, value, **kw):
    return Op(process=process, type=FAIL, f=f, value=value, **kw)


def info_op(process, f, value, **kw):
    return Op(process=process, type=INFO, f=f, value=value, **kw)


def op(like: Any) -> Op:
    """Coerce a dict or Op to an Op."""
    if isinstance(like, Op):
        return like
    return Op.from_dict(like)


class History:
    """An indexed list of Ops with the analysis passes the reference gets
    from knossos.history: `index`, `complete`, `pairs`, `processes`."""

    def __init__(self, ops: Iterable[Any] = (), journal: bool = False,
                 wal: Optional["HistoryWAL"] = None):
        self.ops: list[Op] = [op(o) for o in ops]
        self._packed: Optional["PackedHistory"] = None
        # With journal=True (the run loop, core.py run_case), every
        # append also lands in an incremental ColumnJournal, so the
        # columnar representation exists the moment the run ends and
        # analysis never walks the Op objects (SURVEY.md §7).
        self._journal: Optional["ColumnJournal"] = None
        # With a wal, every append is also written through to the
        # fsynced on-disk write-ahead log, so a SIGKILLed run leaves a
        # recoverable op record (see HistoryWAL / recover).
        self.wal = wal
        if journal:
            self._journal = ColumnJournal()
            for o in self.ops:
                self._journal.append(o)
        if wal is not None:
            for o in self.ops:
                wal.append(o)

    def __len__(self):
        return len(self.ops)

    def __iter__(self) -> Iterator[Op]:
        return iter(self.ops)

    def __getitem__(self, i):
        if isinstance(i, slice):
            return History(self.ops[i])
        return self.ops[i]

    def append(self, o: Any) -> "Op":
        o = op(o)
        self.ops.append(o)
        self._packed = None          # columnar cache is positional
        if self._journal is not None:
            self._journal.append(o)
        if self.wal is not None:
            self.wal.append(o)
        return o

    def invalidate_packed(self) -> None:
        """Drop the cached columnar representation.  MUST be called (or
        attach_packed(pack_history(h)) re-run) after mutating an op IN
        PLACE: append() invalidates automatically, but in-place edits
        (bench corruption planters, test fixtures) would otherwise
        feed stale columns to the native scanners while the Python
        oracle sees the new values — a verdict-divergence footgun
        (ADVICE r3).

        Also bumps the attached PackedHistory's `version` counter, so
        any alias still holding that instance (e.g. a scanner that
        cached its contiguous casts in `_scan_cols`) recomputes
        instead of reading stale derived arrays — see the
        PackedHistory docstring."""
        if self._packed is not None:
            self._packed.version += 1
        self._packed = None

    def packed_columns(self) -> Optional["PackedHistory"]:
        """The columnar representation if one already exists (attached
        or journal-built) — WITHOUT walking the ops.  None otherwise;
        callers that need columns unconditionally use pack().

        CONTRACT: the cache is invalidated by append() but NOT by
        in-place op mutation — mutators call invalidate_packed() or
        re-attach fresh columns (see its docstring)."""
        if self._packed is not None:
            return self._packed
        if self._journal is not None:
            return self._journal.packed()
        return None

    def attach_packed(self, packed: "PackedHistory") -> "History":
        """Attach a pre-built columnar representation (from a
        ColumnJournal maintained during the run).  pack() then returns
        it without walking the ops, and the native columnar scan path
        engages in the checkers."""
        assert len(packed) == len(self.ops), \
            (len(packed), len(self.ops))
        self._packed = packed
        return self

    # -- passes --------------------------------------------------------------
    def index(self) -> "History":
        """Assign sequential :index to every op (knossos.history/index,
        called at jepsen.core/analyze! core.clj:441)."""
        for i, o in enumerate(self.ops):
            o.index = i
        return self

    def processes(self) -> list:
        return sorted({o.process for o in self.ops},
                      key=lambda p: (isinstance(p, str), p))

    def pairs(self) -> list[tuple[Op, Optional[Op]]]:
        """Pair each invocation with its completion (timeline.clj:33-56).
        Completion is None for ops that never completed.  Nemesis ops
        (non-invoke-first) pair (op, None)."""
        out = []
        open_by_process: dict[Any, Op] = {}
        for o in self.ops:
            if o.is_invoke:
                if o.process in open_by_process:
                    raise ValueError(
                        f"process {o.process} invoked twice without completing: {o}")
                open_by_process[o.process] = o
            else:
                inv = open_by_process.pop(o.process, None)
                if inv is not None:
                    out.append((inv, o))
                else:
                    out.append((o, None))
        for inv in open_by_process.values():
            out.append((inv, None))
        out.sort(key=lambda p: (p[0].index if p[0].index is not None else 0))
        return out

    def complete(self) -> "History":
        """Fill in invocation values from completions (knossos.history/complete,
        used by checker/counter checker.clj:696): an ok completion of a read
        back-fills the invocation's observed value; invocations whose op
        crashed are marked info."""
        out = []
        open_by_process: dict[Any, Op] = {}
        for o in self.ops:
            o = dataclasses.replace(o, extra=dict(o.extra))
            if o.is_invoke:
                open_by_process[o.process] = o
            elif o.process in open_by_process:
                inv = open_by_process.pop(o.process)
                if o.is_ok and inv.value is None:
                    inv.value = o.value
                if o.is_info:
                    inv.type = INFO
            out.append(o)
        return History(out)

    # -- persistence ---------------------------------------------------------
    def to_jsonl(self) -> str:
        return "\n".join(json.dumps(o.to_dict(), default=repr)
                         for o in self.ops) + ("\n" if self.ops else "")

    @classmethod
    def from_jsonl(cls, text: str) -> "History":
        return cls(json.loads(line) for line in text.splitlines() if line.strip())

    def to_dicts(self) -> list[dict]:
        return [o.to_dict() for o in self.ops]

    # -- device packing ------------------------------------------------------
    def pack(self, f_codes: Optional[dict] = None,
             value_encoder: Optional[Callable[[Op], tuple[int, int]]] = None,
             ) -> "PackedHistory":
        if f_codes is None and value_encoder is None:
            cols = self.packed_columns()
            if cols is not None:
                return cols
        return pack_history(self, f_codes, value_encoder)


@dataclasses.dataclass
class PackedHistory:
    """Columnar device representation of a history (SURVEY.md §2.5:
    "history transport to device").  Two int64 value slots cover every
    built-in workload (cas carries [old, new]); richer payloads stay
    host-side.  value_ok marks slots that held encodable (integer) values.

    Derived-cast caching: the native scanners cache their contiguous
    int32/uint8 casts of these columns on the instance (the
    `_scan_cols` attribute, built by `ops.wgl_seg._cols_args`), keyed
    by `(version, len)`.  Code that mutates the column arrays IN PLACE
    must bump `version` (History.invalidate_packed() does this for the
    attached instance) or the cached casts go stale while the Python
    oracle sees the new values — a verdict-divergence footgun.  A
    length change invalidates on its own.
    """

    index: np.ndarray       # int32 [n]
    process: np.ndarray     # int32 [n]  (NEMESIS == -1)
    type: np.ndarray        # uint8 [n]  TYPE_CODE
    f: np.ndarray           # int32 [n]  per-test f-code table
    value: np.ndarray       # int64 [n, 2]
    value_ok: np.ndarray    # bool  [n, 2]
    time: np.ndarray        # int64 [n]
    f_codes: dict           # f tag -> code
    # Value-shape discriminator for the native columnar scan: 0 = None,
    # 1 = int32-range int, 2 = int32-range [a, b] pair, 3 = other
    # (unencodable), 4 = int/pair outside int32.  None when the history
    # was packed with a custom value_encoder (the scan then falls back
    # to the Op-object walk, which sees the real values).
    vkind: Optional[np.ndarray] = None  # uint8 [n]
    # Mutation counter guarding derived-cast caches (see class
    # docstring): bump after any in-place column edit.
    version: int = 0

    def __len__(self):
        return len(self.index)

    def unpack_op(self, i: int) -> Op:
        codes_f = {v: k for k, v in self.f_codes.items()}
        val: Any = None
        if self.value_ok[i, 0] and self.value_ok[i, 1]:
            val = [int(self.value[i, 0]), int(self.value[i, 1])]
        elif self.value_ok[i, 0]:
            val = int(self.value[i, 0])
        proc = int(self.process[i])
        return Op(index=int(self.index[i]), process=proc,
                  type=CODE_TYPE[int(self.type[i])],
                  f=codes_f.get(int(self.f[i])), value=val,
                  time=int(self.time[i]))


_I64_MIN, _I64_MAX = -(1 << 63), (1 << 63) - 1


_I32 = 2 ** 31

# Process-column sentinel for CLIENT ids outside int32 range (e.g.
# uuid-derived worker ids): the object-path scanners and the oracle see
# the real id and treat the op as a client call, so the columnar pack
# must NOT silently fold it into NEMESIS — that would drop the op from
# the columnar scan and break the pinned "columnar and object paths
# classify identically" invariant (ADVICE r4).  The native columnar
# ingests treat this sentinel as out-of-scope (whole history falls back
# to the object walk, which sees the true ids).
P_OUT_OF_RANGE = -2


def _i32_process(p) -> int:
    """Process column value: exact non-negative int in int32 range as
    itself; an exact int OUTSIDE int32 range -> P_OUT_OF_RANGE (the
    columnar scans then defer to the object paths); anything else
    (nemesis tags, bools, IntEnums, strings) -> NEMESIS.  Must never
    raise inside the run-loop journal append (ADVICE r3)."""
    if type(p) is int:
        return p if 0 <= p < _I32 else \
            (P_OUT_OF_RANGE if p >= _I32 else NEMESIS)
    return NEMESIS


def _i32_index(idx, fallback: int) -> int:
    """Index column value: positional fallback when the op's own index
    is missing OR outside int32 (the column is positional anyway for
    journaled runs)."""
    return idx if isinstance(idx, int) and not isinstance(idx, bool) \
        and -_I32 <= idx < _I32 else fallback


def _fits_i64(x: int) -> bool:
    return _I64_MIN <= x <= _I64_MAX


def default_value_encoder(o: Op) -> tuple[list[int], list[bool]]:
    """Encode an op value into two int64 slots.  ints -> slot 0;
    [a, b] pairs (cas) -> both slots; None/other -> marked not-ok.
    Ints beyond int64 are marked not-ok instead of overflowing the
    column store — the run loop journals every op through here
    (ColumnJournal), so this must never raise."""
    v = o.value
    if isinstance(v, bool):  # bool is an int subclass; keep it encodable
        return [int(v), 0], [True, False]
    if isinstance(v, int):
        if not _fits_i64(v):
            return [0, 0], [False, False]
        return [v, 0], [True, False]
    if (isinstance(v, (list, tuple)) and len(v) == 2
            and all(isinstance(x, int) and not isinstance(x, bool) for x in v)):
        if not (_fits_i64(v[0]) and _fits_i64(v[1])):
            return [0, 0], [False, False]
        return [v[0], v[1]], [True, True]
    return [0, 0], [False, False]


_I32_MIN, _I32_MAX = -(1 << 31), (1 << 31) - 1


def _value_kind(v) -> int:
    """vkind discriminator (see PackedHistory.vkind)."""
    if v is None:
        return 0
    if isinstance(v, bool):
        return 1
    if isinstance(v, int):
        return 1 if _I32_MIN <= v <= _I32_MAX else 4
    if (isinstance(v, (list, tuple)) and len(v) == 2
            and all(isinstance(x, int) and not isinstance(x, bool)
                    for x in v)):
        return (2 if all(_I32_MIN <= x <= _I32_MAX for x in v) else 4)
    return 3


def pack_history(h: History, f_codes: Optional[dict] = None,
                 value_encoder=None) -> PackedHistory:
    custom_encoder = value_encoder is not None
    value_encoder = value_encoder or default_value_encoder
    if f_codes is None:
        f_codes = {}
        for o in h:
            if o.f not in f_codes:
                f_codes[o.f] = len(f_codes)
    n = len(h)
    index = np.zeros(n, np.int32)
    process = np.zeros(n, np.int32)
    typ = np.zeros(n, np.uint8)
    f = np.zeros(n, np.int32)
    value = np.zeros((n, 2), np.int64)
    value_ok = np.zeros((n, 2), bool)
    time = np.zeros(n, np.int64)
    vkind = None if custom_encoder else np.zeros(n, np.uint8)
    for i, o in enumerate(h):
        index[i] = _i32_index(o.index, i)
        p = o.process
        # `type(p) is int` (not isinstance): bools and int subclasses
        # (IntEnum, numpy ints) are NOT client processes, exactly as
        # the scan engines' PyLong_CheckExact treats them — the
        # columnar and object paths must classify identically; ints
        # past int32 are not batchable processes either (range guard)
        process[i] = _i32_process(p)
        typ[i] = TYPE_CODE[o.type]
        f[i] = f_codes.get(o.f, -1)
        (value[i, 0], value[i, 1]), (value_ok[i, 0], value_ok[i, 1]) = \
            value_encoder(o)
        time[i] = o.time if o.time is not None else 0
        if vkind is not None:
            vkind[i] = _value_kind(o.value)
    return PackedHistory(index, process, typ, f, value, value_ok, time,
                         dict(f_codes), vkind=vkind)


class ColumnJournal:
    """Incremental columnar journal: the run loop appends each op as it
    is journaled (the conj-op! point, core.clj:334-336), so by analysis
    time the SURVEY.md §7 struct-of-arrays representation already
    exists and checkers never pay a per-op Python traversal.  Attach
    the result to a History with `attach_packed` (History.pack() then
    returns it for free and the native columnar scan engages)."""

    def __init__(self, cap: int = 1024):
        self._n = 0
        self._cap = cap
        self.f_codes: dict = {}
        self._alloc(cap)

    def _alloc(self, cap):
        self.index = np.zeros(cap, np.int32)
        self.process = np.zeros(cap, np.int32)
        self.type = np.zeros(cap, np.uint8)
        self.f = np.zeros(cap, np.int32)
        self.value = np.zeros((cap, 2), np.int64)
        self.value_ok = np.zeros((cap, 2), bool)
        self.time = np.zeros(cap, np.int64)
        self.vkind = np.zeros(cap, np.uint8)

    def _grow(self):
        old = (self.index, self.process, self.type, self.f, self.value,
               self.value_ok, self.time, self.vkind)
        self._cap *= 2
        self._alloc(self._cap)
        for o, name in zip(old, ("index", "process", "type", "f",
                                 "value", "value_ok", "time", "vkind")):
            getattr(self, name)[:len(o)] = o

    def append(self, o: Op) -> None:
        i = self._n
        if i == self._cap:
            self._grow()
        self.index[i] = _i32_index(o.index, i)
        p = o.process
        # match pack_history / the scanners: exact int only, int32
        # range-guarded — journal append must never raise (ADVICE r3)
        self.process[i] = _i32_process(p)
        self.type[i] = TYPE_CODE[o.type]
        fc = self.f_codes.get(o.f)
        if fc is None:
            fc = self.f_codes[o.f] = len(self.f_codes)
        self.f[i] = fc
        (self.value[i, 0], self.value[i, 1]), \
            (self.value_ok[i, 0], self.value_ok[i, 1]) = \
            default_value_encoder(o)
        self.time[i] = o.time if o.time is not None else 0
        self.vkind[i] = _value_kind(o.value)
        self._n = i + 1

    def packed(self) -> PackedHistory:
        n = self._n
        return PackedHistory(self.index[:n], self.process[:n],
                             self.type[:n], self.f[:n], self.value[:n],
                             self.value_ok[:n], self.time[:n],
                             dict(self.f_codes), vkind=self.vkind[:n])


# ---------------------------------------------------------------------------
# Crash-safe history WAL (ISSUE 2 tentpole; same framing discipline as
# the resilient runner's verdicts.jsonl checkpoints, store.py:223-273):
# one JSON record per journaled op, appended + flushed + fsynced as it
# lands, each record guarded by a crc32 digest of its canonical op
# payload.  A SIGKILLed run leaves at worst one torn trailing line;
# `recover` rebuilds a well-formed history from the intact prefix,
# closing open invocations as :info (indeterminate — exactly what the
# reference's checkers assume about ops whose process crashed).
#
# Record framing (history.wal):
#     {"i": <seq>, "w": <append wall-clock s>, "crc": "<crc32 of
#      canonical op json>", "op": {...}}
#
# The canonical payload is json.dumps(op_dict, sort_keys=True,
# separators=(",", ":"), default=repr) — deterministic across the
# write/read round trip, so a reader can re-derive and verify the crc
# from the parsed record alone.  The `w` append stamp rides OUTSIDE the
# crc-guarded payload (old readers ignore it; old WALs lack it): it is
# what lets the live checker service measure true op-append→flag
# detection latency (docs/live-checker.md).
# ---------------------------------------------------------------------------

def _wal_payload(op_dict: dict) -> str:
    return json.dumps(op_dict, sort_keys=True, separators=(",", ":"),
                      default=repr)


def frame_line(payload_dict: dict, seq: int,
               wall: Optional[float] = None, key: str = "op",
               ctx: Optional[str] = None) -> bytes:
    """Encode ONE frame line — the unit both the WAL and the ingest
    wire protocol (docs/remote-ingest.md) are made of.  With `wall`
    the bytes are exactly what HistoryWAL.append writes; without it,
    the no-stamp variant (campaign ledgers).  The `w` stamp rides
    outside the crc-guarded payload, as always; `ctx` is the trace
    context envelope field `c` (ISSUE 19) — uncrc'd beside `w`/`e`,
    so old readers skip it and a garbled context can never invalidate
    the record it annotates."""
    body = _wal_payload(payload_dict)
    crc = zlib.crc32(body.encode())
    w = "" if wall is None else f'"w":{wall:.6f},'
    c = "" if ctx is None else f'"c":{json.dumps(str(ctx))},'
    return f'{{"i":{seq},{w}{c}"crc":"{crc:08x}","{key}":{body}}}\n' \
        .encode()


def parse_frame_line(line, key: str = "op",
                     seq: Optional[int] = None):
    """Validate ONE complete frame line; `(record, None)` when it
    holds, `(None, reason)` when it doesn't.  The single definition of
    frame validity: `follow_frames` applies it per line with the
    running sequence, the ingest tier (live/ingest.py) applies it per
    wire frame with `seq=None` and classifies the sequence number
    itself (dup vs reorder).  Guard order is parse → envelope → seq →
    crc, matching the historical stop_reason strings byte-for-byte."""
    if isinstance(line, (bytes, bytearray)):
        line = bytes(line).decode("utf-8", errors="replace")
    line = line.strip()
    try:
        rec = json.loads(line)
    except json.JSONDecodeError:
        return None, "unparseable complete record"
    if not isinstance(rec, dict) or key not in rec:
        return None, f"not a {key!r} frame"
    if seq is not None and rec.get("i") != seq:
        return None, (f"sequence break (expected {seq}, got "
                      f"{rec.get('i')})")
    payload = _wal_payload(rec[key])
    if f"{zlib.crc32(payload.encode()):08x}" != rec.get("crc"):
        return None, "crc mismatch"
    return rec, None


@dataclasses.dataclass
class FrameSegment:
    """One `follow_frames` read: the validated records, plus the cursor
    state to resume from.  `offset` always points at the first byte NOT
    consumed (the start of the first incomplete or invalid line), so a
    torn tail is re-read — and picked up whole — on the next call."""

    records: list                       # validated envelope dicts
    offset: int                         # byte offset to resume from
    seq: int                            # next expected record seq
    corrupt: bool = False               # a COMPLETE line failed a guard
    stop_reason: Optional[str] = None
    tail_bytes: int = 0                 # unconsumed bytes past `offset`
    epoch: int = 0                      # highest writer epoch accepted


def follow_frames(path, offset: int = 0, seq: int = 0,
                  key: str = "op",
                  max_records: Optional[int] = None,
                  epoch_key: Optional[str] = None,
                  epoch: int = 0) -> FrameSegment:
    """Tail a crc/seq-framed JSONL log (history.wal, telemetry.jsonl —
    both use the same framing discipline) from a byte offset.

    Intact-prefix semantics, incrementally: every COMPLETE line from
    `offset` is validated (parses, is a dict carrying `key`, sequence
    number equals `seq`+position, crc re-derived from the canonical
    payload matches); validation failure of a complete line marks the
    stream `corrupt` — everything past it is unattributable, exactly as
    in `recover`.  An INCOMPLETE trailing line (no newline yet: the
    writer is mid-append, or died mid-write) is NOT consumed: `offset`
    stays at its first byte and the next call re-reads it, so a
    follower survives torn tails and resumes by offset alone.

    `max_records` bounds one read (backpressure: a tailer ingesting
    into bounded memory reads in slices); the returned offset/seq
    resume exactly after the last consumed record.

    **Epoch fencing** (`epoch_key`, fleet tenant logs): records may
    carry their writer's lease epoch in that envelope field.  A
    paused-then-resumed stale worker can finish an in-flight append
    into a log a successor already owns — no writer-side fence can
    close that window (the pause may land between the fence check and
    the write syscall), so the READER fences, Raft-style: a valid
    record whose epoch is BELOW the highest epoch seen is a stale
    intrusion — skipped, never a sequence break; a record RAISING the
    epoch is a takeover — it supersedes any lower-epoch records at or
    after its own sequence number (the new owner resumed there before
    the stale line landed) and the expected sequence continues from
    it; within one epoch the log is single-writer and a sequence
    break still means a real tear.  Records without the field are
    epoch 0 (legacy / non-fleet logs: behavior is unchanged)."""
    with open(path, "rb") as f:
        f.seek(offset)
        buf = f.read()
    records: list = []
    pos = 0
    corrupt, reason = False, None
    while max_records is None or len(records) < max_records:
        nl = buf.find(b"\n", pos)
        if nl < 0:
            break
        line = buf[pos:nl].decode("utf-8", errors="replace").strip()
        if not line:
            pos = nl + 1
            continue
        if epoch_key is None:
            rec, err = parse_frame_line(line, key=key, seq=seq)
            if err is not None:
                corrupt, reason = True, f"record {seq}: {err}"
                break
            records.append(rec)
            seq += 1
            pos = nl + 1
            continue
        rec, err = parse_frame_line(line, key=key, seq=None)
        if err is not None:
            corrupt, reason = True, f"record {seq}: {err}"
            break
        e = rec.get(epoch_key)
        e = e if isinstance(e, int) else 0
        if e < epoch:
            pos = nl + 1                # fenced stale writer: skip
            continue
        i = rec.get("i")
        if not isinstance(i, int):
            corrupt, reason = True, (f"record {seq}: sequence break "
                                     f"(expected {seq}, got {i})")
            break
        if e > epoch:
            # takeover: the new owner's timeline supersedes any
            # lower-epoch records at/after its resume point
            while records and records[-1].get("i", -1) >= i:
                records.pop()
            epoch = e
        elif i != seq:
            corrupt, reason = True, (f"record {seq}: sequence break "
                                     f"(expected {seq}, got {i})")
            break
        records.append(rec)
        seq = i + 1
        pos = nl + 1
    return FrameSegment(records, offset + pos, seq, corrupt, reason,
                        len(buf) - pos, epoch)


@dataclasses.dataclass
class WalSegment:
    """One `follow` read of a history WAL: new ops (in append order)
    with their append wall-clock stamps, plus resume cursor state."""

    ops: list                           # Op per intact new record
    walls: list                         # parallel wall s (None if old)
    offset: int
    seq: int
    corrupt: bool = False
    stop_reason: Optional[str] = None
    tail_bytes: int = 0
    ctxs: list = dataclasses.field(default_factory=list)
    # parallel trace contexts (`c` envelope field, None if untraced)
    seqs: list = dataclasses.field(default_factory=list)
    # parallel record sequence numbers (`i`) — the join key between a
    # surfaced op and the ingest tier's transport stamps (ISSUE 19)


def follow(path, offset: int = 0, seq: int = 0,
           max_records: Optional[int] = None) -> WalSegment:
    """Resumable cursor over a (possibly still-being-written) history
    WAL: the documented streaming alternative to `recover`'s full
    re-read.  Returns the ops appended since `offset` whose records are
    intact, and the (`offset`, `seq`) pair to pass to the next call.

    Contract (the live checker service is built on it):
      * records are validated exactly like `recover` — parse, seq,
        crc — and only the intact prefix of the new bytes is returned;
      * an incomplete trailing line is left unconsumed (`tail_bytes`),
        so a follower polls through torn tails and loses nothing;
      * a COMPLETE line failing validation sets `corrupt`: the stream
        is permanently damaged past `offset` and following further
        cannot be attributed (callers should fall back to `recover`
        semantics for the final verdict);
      * `walls[i]` is the writer's append wall-clock stamp (the `w`
        envelope field) when present — detection-latency measurements
        anchor on it — or None for WALs written before the field
        existed."""
    seg = follow_frames(path, offset, seq, key="op",
                        max_records=max_records)
    ops, walls, ctxs, seqs = [], [], [], []
    for rec in seg.records:
        ops.append(Op.from_dict(rec["op"]))
        w = rec.get("w")
        walls.append(float(w) if isinstance(w, (int, float)) else None)
        c = rec.get("c")
        ctxs.append(c if isinstance(c, str) else None)
        seqs.append(rec.get("i"))
    return WalSegment(ops, walls, seg.offset, seg.seq, seg.corrupt,
                      seg.stop_reason, seg.tail_bytes, ctxs, seqs)


class HistoryWAL:
    """Append-only, fsynced, digest-guarded op log.

    Thread-safe: the run loop appends from every worker (via
    History.append under the history lock) AND from the nemesis
    journal; the internal lock keeps records whole regardless.  Append
    failures (disk full, fs gone) are logged once and disable the WAL
    rather than crashing the run — a run without crash-safety beats no
    run."""

    def __init__(self, path, fsync: bool = True, telemetry=None):
        self.path = Path(path)
        self.path.parent.mkdir(parents=True, exist_ok=True)
        self.fsync = fsync
        # jepsen_tpu.telemetry.Telemetry (or None): fsync latency is
        # recorded per append into jepsen_wal_fsync_seconds — the WAL
        # is the run loop's one mandatory disk wait, so its latency
        # distribution is the first thing to check when op latencies
        # drift (docs/observability.md)
        self.telemetry = telemetry
        self.lock = threading.Lock()
        self._n = 0
        self._dead = False
        self._f = open(self.path, "ab")

    def _write_line(self, line: bytes) -> None:
        """The single write point for a framed line.  Subclass hook:
        the streaming client (live/client.py StreamingWAL) overrides
        this to tee the exact bytes onto the ingest wire — byte
        identity between the local WAL and the remote copy is the
        robustness contract, so there is exactly one encoder."""
        self._f.write(line)

    def append(self, o: "Op") -> None:
        # the appending thread's open span (core.run's client/invoke
        # wraps the completion append) becomes the record's `c`
        # envelope field — resolved OUTSIDE the WAL lock, it belongs
        # to this thread alone
        from jepsen_tpu import trace as trace_mod
        ctx = trace_mod.current_ctx()
        with self.lock:
            if self._dead:
                return
            try:
                # frame_line embeds the canonical payload verbatim (it
                # is itself JSON) — the reader re-derives the crc from
                # it alone.  `w` (append wall clock) rides outside the
                # guarded payload: follow()-based consumers measure
                # detection lag from it; recover() ignores it.
                self._write_line(frame_line(
                    o.to_dict(), self._n,
                    # lint: wall-ok(advisory envelope stamp; recovery orders by i/crc, never w)
                    wall=time.time(), ctx=ctx))
                self._f.flush()
                if self.fsync:
                    t0 = time.monotonic()
                    os.fsync(self._f.fileno())
                    if self.telemetry is not None:
                        self.telemetry.observe_wal_fsync(
                            time.monotonic() - t0)
                seq = self._n
                self._n += 1
            except Exception:
                self._dead = True
                log.warning("history WAL write failed; continuing "
                            "without crash-safety", exc_info=True)
                return
        self._post_sync(seq, ctx)

    def _post_sync(self, seq: int, ctx: Optional[str]) -> None:
        """Post-durability hook, called (outside the lock) after a
        record is flushed — and fsynced when fsync is on.  Default:
        nothing.  StreamingWAL overrides it to ship a `mark` control
        frame stamping when record `seq` became durable, the fsync
        segment of the detection-lag decomposition (ISSUE 19)."""

    def close(self) -> None:
        with self.lock:
            self._dead = True
            try:
                self._f.close()
            except Exception:
                pass

    # Resumable read cursor over a WAL file (typically someone ELSE's
    # WAL — the live checker tails runs it did not write).  Static:
    # the follower needs no handle on the writer.
    follow = staticmethod(follow)


def recover(path) -> History:
    """Rebuild a well-formed History from a (possibly truncated) WAL.

    Reads records in order, stopping at the first line that fails to
    parse, fails its crc check, or breaks the sequence — everything
    past a tear is unattributable, so recovery trusts exactly the
    intact prefix.  Invocations without a completion in that prefix are
    closed with synthesized `:info` completions (indeterminate: the op
    may or may not have taken effect), so `core.analyze` and the
    checkpointed checkers can verify the result directly.

    The returned History carries a `recovery` attribute:
        {"ops": <recovered op count>, "closed": <synthesized :info>,
         "torn": <True when the file ended mid-record or failed a
                  guard>, "stop_reason": <str or None>}
    """
    p = Path(path)
    seg = follow(p)                      # one full-file cursor read
    ops: list[Op] = list(seg.ops)
    stop_reason = seg.stop_reason
    if stop_reason is None and seg.tail_bytes:
        stop_reason = (f"incomplete trailing record "
                       f"({seg.tail_bytes} bytes)")

    # Close open invocations as :info (knossos treats such processes as
    # crashed; the invocation stays concurrent to everything after it).
    open_by_process: dict[Any, Op] = {}
    for o in ops:
        if o.is_invoke:
            open_by_process[o.process] = o
        else:
            open_by_process.pop(o.process, None)
    last_time = max((o.time for o in ops if o.time is not None), default=0)
    closed = 0
    for inv in sorted(open_by_process.values(),
                      key=lambda o: o.index if o.index is not None else 0):
        ops.append(inv.assoc(type=INFO, time=last_time,
                             error="wal-recover: open at crash"))
        closed += 1

    h = History(ops).index()
    h.recovery = {"ops": len(ops) - closed, "closed": closed,
                  "torn": stop_reason is not None,
                  "stop_reason": stop_reason}
    if stop_reason or closed:
        log.warning("WAL recovery %s: %d ops, %d open invocations "
                    "closed as :info%s", p, len(ops) - closed, closed,
                    f" ({stop_reason})" if stop_reason else "")
    return h


def history_latencies(h: History) -> list[tuple[Op, float]]:
    """(completed-invocation, latency-ns) pairs for client ops;
    util.clj:598-632."""
    out = []
    for inv, comp in History(h).pairs():
        if (comp is not None and inv.time is not None
                and comp.time is not None and isinstance(inv.process, int)
                and inv.process >= 0):
            out.append((inv.assoc(completion=comp), comp.time - inv.time))
    return out


def nemesis_intervals(h: History) -> list[tuple[Optional[Op], Optional[Op]]]:
    """Start/stop op pairs for nemesis activity windows (util.clj:634)."""
    out = []
    start = None
    for o in h:
        if o.process != NEMESIS and o.process != "nemesis":
            continue
        if o.f == "start" and o.is_invoke and start is None:
            start = o
        elif o.f == "stop" and not o.is_invoke and start is not None:
            out.append((start, o))
            start = None
    if start is not None:
        out.append((start, None))
    return out
