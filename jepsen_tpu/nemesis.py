"""Fault injection: the nemesis layer
(reference: `jepsen/src/jepsen/nemesis.clj`)."""

from __future__ import annotations

import random
import threading
import time
from typing import Callable, Optional

from jepsen_tpu import control as c
from jepsen_tpu import net as net_mod
from jepsen_tpu import util
from jepsen_tpu.history import Op


class FaultLedger:
    """Registry of outstanding injected faults (partitions, slow/flaky
    links, process kills) with their undo actions.

    Nemeses register a fault BEFORE injecting it and resolve it when
    they reverse it themselves; teardown — every teardown, including
    the ones reached via the watchdog, the run deadline, or an
    exception after the nemesis worker died mid-fault — calls
    `heal_all`, which reverses whatever is still outstanding in
    reverse registration order.  Undo actions must therefore be
    idempotent (healing an already-healed network is a no-op).

    Thread-safe: the nemesis worker registers while client workers
    (via net helpers) may too, and heal_all can race a late resolve."""

    def __init__(self):
        self.lock = threading.Lock()
        self._seq = 0
        self._faults: dict = {}   # key -> (seq, undo fn, description)
        # jepsen_tpu.telemetry.Telemetry (wired by core.run / ledger()):
        # every register/resolve edge becomes a fault-start/fault-stop
        # event pair in telemetry.jsonl, so checker timelines and the
        # /telemetry dashboard can overlay fault windows on the op
        # stream without parsing nemesis op values.
        self.telemetry = None

    def _window(self, phase: str, key, desc=None,
                healed: bool = False) -> None:
        try:
            from jepsen_tpu import telemetry as telemetry_mod
            telemetry_mod.fault_window(phase, key, desc, healed=healed,
                                       tele=self.telemetry)
        except Exception:   # noqa: BLE001 - telemetry never fails a run
            pass

    def register(self, key, undo: Callable[[], object],
                 description=None) -> None:
        """Record an outstanding fault.  Re-registering a key replaces
        its undo (e.g. a second partition before the first healed —
        one heal reverses both for iptables -F semantics)."""
        with self.lock:
            self._faults[key] = (self._seq, undo, description)
            self._seq += 1
        self._window("start", key, description)

    def resolve(self, key) -> bool:
        """The fault was reversed by its owner; drop it."""
        with self.lock:
            dropped = self._faults.pop(key, None) is not None
        if dropped:
            self._window("stop", key)
        return dropped

    def outstanding(self) -> list:
        """[(key, description)] of unreversed faults, registration
        order."""
        with self.lock:
            items = sorted(self._faults.items(), key=lambda kv: kv[1][0])
        return [(k, d) for k, (_, _, d) in items]

    def heal_all(self, test=None) -> dict:
        """Reverse every outstanding fault, newest first (faults can
        stack: un-kill before un-partition).  Each undo runs even if
        earlier ones raise; failures are returned, not thrown.  The
        ledger is emptied regardless — a failed heal is logged by the
        caller, and retrying it forever would wedge teardown."""
        with self.lock:
            items = sorted(self._faults.items(), key=lambda kv: kv[1][0],
                           reverse=True)
            self._faults.clear()
        results = {}
        for key, (_, undo, _desc) in items:
            try:
                results[key] = undo()
            except Exception as e:   # noqa: BLE001 - reported, not raised
                results[key] = e
            self._window("stop", key, healed=True)
        return results

    def assert_empty(self, context=None) -> list:
        """Inter-schedule backstop for loops that run many cases
        against one process (campaign.py): the ledger MUST be empty
        between schedules — run_case's teardown heal already reversed
        everything on every exit path, so anything still outstanding
        here means a prior schedule's faults survived into the gap.

        Never silent: leaked faults are journaled as a durable
        `campaign-leak` telemetry event (through self.telemetry when
        wired, else the active run's log), counted in
        `jepsen_campaign_leaks_total`, logged, and THEN healed.
        Returns the leaked keys' descriptions (empty when clean)."""
        out = self.outstanding()
        if not out:
            return []
        keys = [repr(k) for k, _ in out]
        import logging
        logging.getLogger("jepsen").error(
            "campaign-leak: %d fault(s) survived a schedule (%s)%s",
            len(keys), keys, f" [{context}]" if context else "")
        try:
            from jepsen_tpu import telemetry as telemetry_mod
            telemetry_mod.REGISTRY.counter(
                "jepsen_campaign_leaks_total").inc(len(keys))
            ev = {"keys": keys}
            if context is not None:
                ev["context"] = str(context)
            t = self.telemetry if (self.telemetry is not None
                                   and self.telemetry.enabled) else None
            if t is not None:
                t.event("campaign-leak", durable=True, **ev)
            else:
                telemetry_mod.emit("campaign-leak", durable=True, **ev)
        except Exception:   # noqa: BLE001 - telemetry never fails a run
            pass
        self.heal_all()
        return keys


def ledger(test) -> FaultLedger:
    """The test's fault ledger (created by core.run; tests driving
    nemeses directly get one on demand).  Wires the test's telemetry
    into the ledger so fault-window events flow even for nemeses
    driven outside core.run."""
    led = test.get("fault_ledger")
    if led is None:
        led = test["fault_ledger"] = FaultLedger()
    if led.telemetry is None:
        from jepsen_tpu import telemetry as telemetry_mod
        t = telemetry_mod.of(test)
        if t.enabled:
            led.telemetry = t
    return led


class Nemesis:
    """nemesis.clj:9-14."""

    def setup(self, test) -> "Nemesis":
        return self

    def invoke(self, test, op: Op) -> Op:
        raise NotImplementedError

    def teardown(self, test) -> None:
        pass


class Noop(Nemesis):
    def invoke(self, test, op):
        return op


noop = Noop()


def setup(nemesis: Optional[Nemesis], test) -> Nemesis:
    if nemesis is None:
        return noop
    return nemesis.setup(test) or nemesis


def teardown(nemesis: Optional[Nemesis], test) -> None:
    if nemesis is not None:
        nemesis.teardown(test)


class Timeout(Nemesis):
    """Bound unreliable nemesis ops; timed-out ops get value 'timeout'
    (nemesis.clj:56-70).

    Thread hygiene: util.timeout runs the inner invoke on a daemon
    thread and, on timeout, abandons it with its cancel token set —
    inner nemeses that poll `util.cancelled()` in their wait loops
    retire promptly, so a long run with a flaky nemesis does not
    accumulate live threads (one timed-out op used to leak one thread
    for as long as its invoke blocked)."""

    def __init__(self, timeout_ms: float, nemesis: Nemesis):
        self.timeout_ms = timeout_ms
        self.nemesis = nemesis

    def setup(self, test):
        self.nemesis = self.nemesis.setup(test) or self.nemesis
        return self

    def invoke(self, test, op):
        return util.timeout(self.timeout_ms / 1000,
                            op.assoc(value="timeout"),
                            lambda: self.nemesis.invoke(test, op))

    def teardown(self, test):
        self.nemesis.teardown(test)


def timeout(timeout_ms, nemesis):
    return Timeout(timeout_ms, nemesis)


# ---------------------------------------------------------------------------
# Grudge topologies (pure; nemesis_test.clj:19-48 covers these)
# ---------------------------------------------------------------------------

def bisect(coll):
    """Cut a sequence in half; smaller half first (nemesis.clj:72-75)."""
    coll = list(coll)
    mid = len(coll) // 2
    return [coll[:mid], coll[mid:]]


def split_one(coll, loner=None):
    """Split one node off from the rest (nemesis.clj:77-82)."""
    coll = list(coll)
    if loner is None:
        loner = random.choice(coll)
    return [[loner], [x for x in coll if x != loner]]


def complete_grudge(components) -> dict:
    """No node may talk to any node outside its component
    (nemesis.clj:84-96)."""
    components = [set(comp) for comp in components]
    universe = set().union(*components) if components else set()
    grudge = {}
    for comp in components:
        for node in comp:
            grudge[node] = universe - comp
    return grudge


def bridge(nodes) -> dict:
    """Cut the network in half, preserving one bidirectional bridge node
    (nemesis.clj:98-109)."""
    components = bisect(nodes)
    bridge_node = components[1][0]
    grudge = complete_grudge(components)
    grudge.pop(bridge_node, None)
    return {node: others - {bridge_node}
            for node, others in grudge.items()}


def majorities_ring(nodes) -> dict:
    """Every node sees a majority, but no two nodes see the same majority
    (nemesis.clj:151-168)."""
    nodes = list(nodes)
    universe = set(nodes)
    n = len(nodes)
    m = util.majority(n)
    shuffled = list(nodes)
    random.shuffle(shuffled)
    ring = shuffled * 2  # cycle
    grudge = {}
    for i in range(n):
        maj = ring[i:i + m]
        center = maj[len(maj) // 2]
        grudge[center] = universe - set(maj)
    return grudge


# ---------------------------------------------------------------------------
# Partitioner (nemesis.clj:111-172)
# ---------------------------------------------------------------------------

class Partitioner(Nemesis):
    """:start cuts links per (grudge nodes); :stop heals.

    Outstanding partitions are registered in the test's fault ledger
    BEFORE the links are cut, so a nemesis that dies mid-partition (or
    a run torn down while one is active) still gets its network healed
    by the ledger backstop in core.run_case."""

    LEDGER_KEY = "nemesis.partition"

    def __init__(self, grudge: Optional[Callable] = None):
        self.grudge = grudge

    def setup(self, test):
        test["net"].heal(test)
        return self

    def _heal(self, test):
        test["net"].heal(test)
        ledger(test).resolve(self.LEDGER_KEY)

    def invoke(self, test, op):
        if op.f == "start":
            grudge = op.value or self.grudge(test["nodes"])
            ledger(test).register(self.LEDGER_KEY,
                                  lambda: test["net"].heal(test),
                                  {k: sorted(v)
                                   for k, v in grudge.items()})
            net_mod.drop_all(test, grudge)
            return op.assoc(value=["isolated", {k: sorted(v) for k, v in
                                                grudge.items()}])
        if op.f == "stop":
            self._heal(test)
            return op.assoc(value="network-healed")
        raise ValueError(f"partitioner can't handle {op.f!r}")

    def teardown(self, test):
        self._heal(test)


def partitioner(grudge=None):
    return Partitioner(grudge)


def partition_halves():
    """First half vs second half (nemesis.clj:134-139)."""
    return Partitioner(lambda nodes: complete_grudge(bisect(nodes)))


def partition_random_halves():
    """Randomly chosen halves (nemesis.clj:141-144)."""
    def grudge(nodes):
        nodes = list(nodes)
        random.shuffle(nodes)
        return complete_grudge(bisect(nodes))
    return Partitioner(grudge)


def partition_random_node():
    """Isolate a single random node (nemesis.clj:146-149)."""
    return Partitioner(lambda nodes: complete_grudge(split_one(nodes)))


def partition_majorities_ring():
    """nemesis.clj:170-172."""
    return Partitioner(majorities_ring)


# ---------------------------------------------------------------------------
# Compose (nemesis.clj:174-212)
# ---------------------------------------------------------------------------

class Compose(Nemesis):
    """Route ops to child nemeses by :f.  Keys are either sets of fs
    (routed unchanged) or dicts rewriting outer f -> inner f."""

    def __init__(self, nemeses: dict):
        self.nemeses = dict(nemeses)

    def _route(self, fs, f):
        if isinstance(fs, dict):
            return fs.get(f)
        if callable(fs) and not isinstance(fs, (set, frozenset)):
            return fs(f)
        return f if f in fs else None

    def setup(self, test):
        self.nemeses = {fs: n.setup(test) or n
                        for fs, n in self.nemeses.items()}
        return self

    def invoke(self, test, op):
        for fs, nemesis in self.nemeses.items():
            f2 = self._route(fs, op.f)
            if f2 is not None:
                return nemesis.invoke(test, op.assoc(f=f2)).assoc(f=op.f)
        raise ValueError(f"no nemesis can handle {op.f!r}")

    def teardown(self, test):
        for n in self.nemeses.values():
            n.teardown(test)


def compose(nemeses: dict):
    return Compose(nemeses)


# ---------------------------------------------------------------------------
# Named nemesis maps (cockroach nemesis.clj:32-107) — the registry
# currency every suite's --nemesis flag deals in: {name client during
# final clocks}.  Lived in suites/cockroach.py until the disk-fault
# nemeses needed them from outside a suite module.
# ---------------------------------------------------------------------------

def named_nemesis(name: str, client: "Nemesis", *, clocks: bool = False,
                  delay: float = 5, duration: float = 5) -> dict:
    """A named nemesis map on the standard single-gen cadence: sleep
    delay / start / sleep duration / stop, forever; final stop
    (nemesis.clj:32-38)."""
    from jepsen_tpu import generator as gen
    return {"name": name, "client": client, "clocks": clocks,
            "during": gen.start_stop(delay, duration),
            "final": gen.once({"type": "info", "f": "stop"})}


def tag_f(name: str, source):
    """Wrap a generator so emitted ops carry f=(name, inner-f) — the
    namespacing compose_named uses for routing (nemesis.clj:80-103)."""
    from jepsen_tpu import generator as gen

    def retag(op):
        if op is None:
            return None
        if isinstance(op, dict):
            out = dict(op)
            out["f"] = (name, out.get("f"))
            return out
        return op.assoc(f=(name, op.f))
    return gen.gmap(retag, source)


def compose_named(nemeses) -> dict:
    """nemesis.clj compose :62-107: merge named nemesis maps into one
    {name clocks client during final}, ops tagged (name, f) and routed
    back to their owners."""
    from jepsen_tpu import generator as gen
    nemeses = [n for n in nemeses if n]
    names = [n["name"] for n in nemeses]
    assert len(set(names)) == len(names), f"duplicate nemeses: {names}"
    routes = {}
    for nm in nemeses:
        def route(f, _name=nm["name"]):
            if isinstance(f, tuple) and len(f) == 2 and f[0] == _name:
                return f[1]
            return None
        routes[route] = nm["client"]
    return {
        "name": "+".join(names),
        "clocks": any(n.get("clocks") for n in nemeses),
        "client": compose(routes),
        "during": gen.mix([tag_f(n["name"], n["during"])
                           for n in nemeses]),
        "final": gen.concat(*[tag_f(n["name"], n["final"])
                              for n in nemeses]),
    }


class fdict(dict):
    """A hashable f-routing map for compose() keys: outer f -> inner f
    (plain dicts can't be dict keys; identity hashing is fine since
    each routing map is unique)."""

    def __hash__(self):
        return id(self)


# ---------------------------------------------------------------------------
# Clock, process, and file nemeses (nemesis.clj:214-323)
# ---------------------------------------------------------------------------

def set_time(t: float) -> None:
    """Set the local node time in POSIX seconds (nemesis.clj:214-217)."""
    with c.su():
        c.execute("date", "+%s", "-s", f"@{int(t)}")


class ClockScrambler(Nemesis):
    """Randomizes node clocks within a ±dt-second window
    (nemesis.clj:219-234).

    Skews are registered in the fault ledger BEFORE injection
    (register-before-inject, ISSUE 15): a scrambler that dies
    mid-skew still gets every clock snapped back by the run_case
    backstop, and campaign.assert_empty can prove no skew leaked."""

    LEDGER_KEY = "nemesis.clock-scrambler"

    def __init__(self, dt: float):
        self.dt = dt

    def _heal(self, test):
        # lint: wall-ok(restoring TRUE wall time IS the heal) inject-ok(heal path, not an injection)
        c.on_nodes(test, lambda tst, node: set_time(time.time()))

    def invoke(self, test, op):
        ledger(test).register(self.LEDGER_KEY,
                              lambda: self._heal(test),
                              {"dt": self.dt})

        def f(tst, node):
            # lint: wall-ok(the injected skew is relative to wall time)
            set_time(time.time() + random.randint(-self.dt, self.dt))
        return op.assoc(value=c.on_nodes(test, f))

    def teardown(self, test):
        self._heal(test)
        ledger(test).resolve(self.LEDGER_KEY)


def clock_scrambler(dt):
    return ClockScrambler(dt)


class NodeStartStopper(Nemesis):
    """Generic start!/stop! on targeted nodes (nemesis.clj:236-279).

    Started disruptions (kills, pauses) register in the fault ledger
    keyed by this nemesis instance; stop — or the teardown backstop —
    runs the stop fn on whatever nodes are still disrupted."""

    def __init__(self, targeter, start, stop):
        self.targeter = targeter
        self.start = start
        self.stop = stop
        self.nodes = None
        self.lock = threading.Lock()

    @property
    def _ledger_key(self):
        return ("nemesis.node-start-stopper", id(self))

    def _stop_all(self, test):
        """Undo: stop the disruption on every still-started node.  Used
        by :stop and, via the ledger, by the teardown backstop."""
        with self.lock:
            ns, self.nodes = self.nodes, None
        if not ns:
            return "not-started"
        return {node: c.on(node, lambda n=node: self.stop(test, n), test)
                for node in ns}

    def invoke(self, test, op):
        with self.lock:
            if op.f == "start":
                try:
                    ns = self.targeter(test, test["nodes"])
                except TypeError:
                    ns = self.targeter(test["nodes"])
                if ns is None:
                    return op.assoc(type="info", value="no-target")
                if not isinstance(ns, (list, tuple, set)):
                    ns = [ns]
                ns = list(ns)
                if self.nodes is not None:
                    return op.assoc(
                        type="info",
                        value=f"nemesis already disrupting {self.nodes}")
                ledger(test).register(self._ledger_key,
                                      lambda: self._stop_all(test), ns)
                self.nodes = ns
                value = {node: c.on(node,
                                    lambda n=node: self.start(test, n),
                                    test)
                         for node in ns}
                return op.assoc(type="info", value=value)
            if op.f == "stop":
                if self.nodes is None:
                    return op.assoc(type="info", value="not-started")
        if op.f == "stop":
            value = self._stop_all(test)
            ledger(test).resolve(self._ledger_key)
            return op.assoc(type="info", value=value)
        raise ValueError(f"node-start-stopper can't handle {op.f!r}")

    def teardown(self, test):
        if self.nodes is not None:
            self._stop_all(test)
            ledger(test).resolve(self._ledger_key)


def node_start_stopper(targeter, start, stop):
    return NodeStartStopper(targeter, start, stop)


def hammer_time(process: str, targeter=None):
    """SIGSTOP/SIGCONT a process on targeted nodes (nemesis.clj:281-295)."""
    targeter = targeter or (lambda nodes: random.choice(list(nodes)))

    def start(test, node):
        with c.su():
            c.execute("killall", "-s", "STOP", process)
        return ["paused", process]

    def stop(test, node):
        with c.su():
            c.execute("killall", "-s", "CONT", process)
        return ["resumed", process]

    return NodeStartStopper(targeter, start, stop)


class TruncateFile(Nemesis):
    """Drop the last :drop bytes of :file per node (nemesis.clj:297-321)."""

    def invoke(self, test, op):
        assert op.f == "truncate"
        plan = op.value or {}

        def f(tst, node):
            spec = plan[node]
            with c.su():
                c.execute("truncate", "-c", "-s", f"-{spec['drop']}",
                          spec["file"])
        c.on_nodes(test, f, list(plan.keys()))
        return op


def truncate_file():
    return TruncateFile()
