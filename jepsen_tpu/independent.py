"""Key-independent scaling: lift a single-key test to a map of keys
(reference: `jepsen/src/jepsen/independent.clj`).

Linearizability is expensive to check, so histories must be short — but
short histories can't reveal enough concurrency errors.  This layer
splits a test into independent per-key components: generators shard
threads into groups (one key per group), and the checker splits the
history into per-key subhistories.

This is the framework's **data-parallel axis**: `checker()` fans
per-key subhistories out host-side (bounded_pmap, like the reference's
independent.clj:247-298), and `batch_checker()` packs every per-key
history into one columnar device program — `vmap` of the WGL kernel over
keys, shardable over a TPU mesh (SURVEY.md §2.4, BASELINE config 3).
"""

from __future__ import annotations

import json
import logging
from typing import Callable, Iterable, Optional

from jepsen_tpu import checker as ck
from jepsen_tpu import generator as gen
from jepsen_tpu.history import History, Op
from jepsen_tpu.util import bounded_pmap

log = logging.getLogger("jepsen")

DIR = "independent"


class KV(tuple):
    """A key/value tuple marking an op value as belonging to an
    independent key (independent.clj tuple :21-29)."""

    def __new__(cls, k, v):
        return super().__new__(cls, (k, v))

    @property
    def key(self):
        return self[0]

    @property
    def value(self):
        return self[1]

    def __repr__(self):
        return f"[{self[0]!r} {self[1]!r}]"


def tuple_(k, v) -> KV:
    return KV(k, v)


def is_tuple(v) -> bool:
    return isinstance(v, KV)


class SequentialGenerator(gen.Generator):
    """One key at a time: exhaust fgen(k1), move to k2, ...
    (independent.clj:31-64).  Op values are wrapped in KV tuples."""

    def __init__(self, keys: Iterable, fgen: Callable):
        import threading
        self.lock = threading.Lock()
        self.keys = list(keys)
        self.i = 0
        self.gen = fgen(self.keys[0]) if self.keys else None
        self.fgen = fgen

    def op(self, test, process):
        while True:
            with self.lock:
                i, g = self.i, self.gen
            if i >= len(self.keys):
                return None
            o = gen.op(g, test, process)
            if o is not None:
                k = self.keys[i]
                v = o.get("value") if isinstance(o, dict) else o.value
                return gen._op_assoc(o, value=KV(k, v))
            with self.lock:
                if self.i == i:  # we advance
                    self.i += 1
                    self.gen = (self.fgen(self.keys[self.i])
                                if self.i < len(self.keys) else None)


def sequential_generator(keys, fgen):
    return SequentialGenerator(keys, fgen)


class ConcurrentGenerator(gen.Generator):
    """n threads per key, running keys concurrently in disjoint thread
    groups; each group moves to a fresh key when its generator is
    exhausted (independent.clj:66-220).  The nemesis does not enter
    subgenerators."""

    def __init__(self, n: int, keys: Iterable, fgen: Callable):
        import threading
        assert isinstance(n, int) and n > 0
        self.n = n
        self.keys = iter(keys)
        self.fgen = fgen
        self.lock = threading.Lock()
        self.state: Optional[dict] = None

    def _init_state(self, test):
        threads = [t for t in gen.current_threads()
                   if isinstance(t, int) and not isinstance(t, bool)]
        thread_count = len(threads)
        assert sorted(threads) == list(range(thread_count)), \
            "concurrent-generator expects integer threads 0..n"
        assert test["concurrency"] == thread_count, (
            f"Expected test concurrency ({test['concurrency']}) to equal "
            f"the number of integer threads ({thread_count})")
        group_size = self.n
        group_count = thread_count // group_size
        assert group_size <= thread_count, (
            f"With {thread_count} worker threads, this concurrent-generator"
            f" cannot run a key with {group_size} threads concurrently."
            f" Consider raising your test's concurrency to at least"
            f" {group_size}.")
        assert thread_count == group_size * group_count, (
            f"This concurrent-generator has {thread_count} threads but can"
            f" only use {group_size * group_count} of them to run"
            f" {group_count} concurrent keys with {group_size} threads"
            f" apiece. Consider a concurrency that is a multiple of"
            f" {group_size}.")
        active = []
        for _ in range(group_count):
            k = next(self.keys, _DONE)
            active.append(None if k is _DONE else (k, self.fgen(k)))
        self.state = {
            "active": active,
            "group_threads": [tuple(threads[g * group_size:
                                            (g + 1) * group_size])
                              for g in range(group_count)],
            "group_size": group_size,
        }

    def op(self, test, process):
        with self.lock:
            if self.state is None:
                self._init_state(test)
            s = self.state
        thread = gen.process_to_thread(test, process)
        assert isinstance(thread, int), (
            f"Only worker threads with numeric ids can ask for operations"
            f" from concurrent-generator; got {thread!r}")
        group = thread // s["group_size"]
        while True:
            # An enclosing time-limit may expire while we rotate keys;
            # with an infinite key iterator every fresh subgenerator
            # then yields None immediately and this loop would spin
            # forever.  Re-check the deadline each turn.
            d = gen._deadline()
            if d is not None and gen._now() > d:
                return None
            with self.lock:
                pair = s["active"][group]
            if pair is None:
                return None
            k, g = pair
            threads2 = s["group_threads"][group]
            assert thread in threads2, (
                f"Probably a bug: thread {thread} in group {group} isn't in"
                f" that group's thread list {threads2}")
            with gen.with_threads(threads2):
                o = gen.op(g, test, process)
            if o is not None:
                v = o.get("value") if isinstance(o, dict) else o.value
                return gen._op_assoc(o, value=KV(k, v))
            with self.lock:
                if self.state["active"][group] is pair:
                    k2 = next(self.keys, _DONE)
                    self.state["active"][group] = \
                        None if k2 is _DONE else (k2, self.fgen(k2))


_DONE = object()


def concurrent_generator(n, keys, fgen):
    return ConcurrentGenerator(n, keys, fgen)


# ---------------------------------------------------------------------------
# History splitting (independent.clj:222-245)
# ---------------------------------------------------------------------------

def history_keys(history) -> set:
    return {o.value.key for o in History(history) if is_tuple(o.value)}


def subhistory(k, history) -> History:
    """All ops without a differing key; KV values unwrapped.  Un-keyed
    ops (nemesis, info) appear in every subhistory."""
    out = []
    for o in History(history):
        v = o.value
        if not is_tuple(v):
            out.append(o)
        elif v.key == k:
            out.append(o.assoc(value=v.value))
    return History(out)


# ---------------------------------------------------------------------------
# Checkers
# ---------------------------------------------------------------------------

class IndependentChecker(ck.Checker):
    """Host-parallel per-key checking (independent.clj:247-298): valid
    iff the underlying checker is valid for every subhistory; writes
    per-key artifacts under independent/<k>/."""

    def __init__(self, checker: ck.Checker):
        self.checker = checker

    def _check_key(self, test, history, opts, k):
        h = subhistory(k, history)
        subdir = list((opts or {}).get("subdirectory") or []) + [DIR, str(k)]
        results = ck.check_safe(self.checker, test, h,
                                {"subdirectory": subdir, "history-key": k})
        if test and test.get("name") and test.get("start-time"):
            from jepsen_tpu import store
            try:
                with open(store.make_path(test, *subdir, "results.json"),
                          "w") as f:
                    json.dump(store._jsonable_tree(results), f, indent=2,
                              default=repr)
                with open(store.make_path(test, *subdir, "history.jsonl"),
                          "w") as f:
                    f.write(h.to_jsonl())
            except OSError:
                log.warning("could not write independent results for %r", k)
        return k, results

    def check(self, test, history, opts=None):
        ks = sorted(history_keys(history), key=repr)
        results = dict(bounded_pmap(
            lambda k: self._check_key(test, history, opts, k), ks))
        failures = [k for k, r in results.items() if r["valid?"] is not True]
        return {"valid?": ck.merge_valid(r["valid?"]
                                         for r in results.values()),
                "results": results,
                "failures": failures}


def checker(sub_checker: ck.Checker) -> IndependentChecker:
    return IndependentChecker(sub_checker)


class BatchedLinearizableChecker(ck.Checker):
    """The TPU-native independent checker: every per-key subhistory is
    one lane of a single device program, shardable over a mesh.

    Engine order mirrors checker.Linearizable: the bitmap batch kernel
    first (ops/wgl_seg.check_many — dense configuration space, no
    sorting, exact; crash-free keys with small state spaces), whose
    per-key fallback escalates out-of-scope keys to the sorted frontier
    kernel (ops/wgl) and then the CPU oracle.  The batch dispatch runs
    through ops.runner.ResilientRunner, so a device OOM on a wide key
    axis bisects instead of aborting, one poisoned key is quarantined
    with a structured verdict, and — when the analysis phase provides
    opts['checkpoint_dir'] — completed per-key verdicts checkpoint to
    the store and a killed analysis resumes.  A model with no device
    spec at all degrades to the CPU oracle, key by key (the runner's
    BackendUnavailable path)."""

    def __init__(self, model, frontier_size: int = 256, mesh=None,
                 deadline_s: Optional[float] = None,
                 max_retries: int = 2):
        self.model = model
        self.frontier_size = frontier_size  # advisory; kept for API compat
        self.mesh = mesh
        self.deadline_s = deadline_s
        self.max_retries = max_retries

    def check(self, test, history, opts=None):
        import os as _os

        from jepsen_tpu.ops import runner as runner_mod

        ks = sorted(history_keys(history), key=repr)
        if not ks:
            return {"valid?": True, "results": {}, "failures": []}
        subs = [subhistory(k, history) for k in ks]
        ckdir = (opts or {}).get("checkpoint_dir")
        per_key = runner_mod.ResilientRunner(
            engine="seg_many",
            engine_kwargs=dict(
                mesh=self.mesh,
                mesh_axis=(self.mesh.axis_names[0]
                           if self.mesh else None)),
            deadline_s=self.deadline_s,
            max_retries=self.max_retries,
            checkpoint_dir=(_os.path.join(str(ckdir), DIR)
                            if ckdir else None),
        ).check(self.model, subs)
        results = dict(zip(ks, per_key))
        failures = [k for k, r in results.items() if r["valid?"] is not True]
        # Failing-window SVGs under independent/<k>/, matching the
        # host-parallel IndependentChecker path (checker.clj:147-154).
        for k, sub in zip(ks, subs):
            r = results[k]
            if r.get("valid?") is False and r.get("op_index") is not None:
                try:
                    from jepsen_tpu.checker import linear_report
                    subdir = (list((opts or {}).get("subdirectory")
                                   or []) + [DIR, str(k)])
                    p = linear_report.write_to_store(
                        test, sub, r, {"subdirectory": subdir})
                    if p:
                        r["linear-svg"] = p
                except Exception as e:          # noqa: BLE001
                    r["linear-svg-error"] = str(e)
        return {"valid?": ck.merge_valid(r["valid?"]
                                         for r in results.values()),
                "results": results,
                "failures": failures}


def batch_checker(model_or_checker, frontier_size: int = 256, mesh=None):
    """The TPU-native independent checker.  Handed a *model*, every
    per-key subhistory rides one lane of the batched WGL program
    (BatchedLinearizableChecker).  Handed a *Checker* that knows how
    to `check_many` (e.g. `checker.elle.Elle`), the same key-splitting
    shell batches through that checker's own device engine instead —
    txn isolation planes get the same one-program treatment as
    linearizability lanes."""
    if isinstance(model_or_checker, ck.Checker) \
            and callable(getattr(model_or_checker, "check_many", None)):
        from jepsen_tpu.checker.elle import BatchedElleChecker
        return BatchedElleChecker(model_or_checker)
    return BatchedLinearizableChecker(model_or_checker, frontier_size,
                                      mesh)
