"""SmartOS provisioning (reference: `jepsen/src/jepsen/os/smartos.clj`):
pkgin package management and the node baseline, the illumos sibling of
the debian/centos OSes.  Used by the mongodb-smartos suite.
"""

from __future__ import annotations

import logging
from typing import Iterable

from jepsen_tpu import os as os_mod
from jepsen_tpu import control as c
from jepsen_tpu.control import lit

log = logging.getLogger("jepsen.os.smartos")

# smartos.clj setup! package baseline (:88-106): the same tool envelope
# the nemeses and control utils need, under pkgsrc names.
BASE_PACKAGES = ["wget", "curl", "unzip", "gtar", "bzip2", "rsyslog",
                 "logrotate", "gcc13"]


# Write /etc/hosts mapping every test node (smartos.clj setup-hostfile!
# — same contract as debian.clj:12-30); shared implementation in
# jepsen_tpu.os.
from jepsen_tpu.os import setup_hostfile  # noqa: F401,E402


def installed(pkgs: Iterable[str]) -> set:
    """Subset of pkgs already installed (smartos.clj installed? :29-38,
    via `pkgin list`)."""
    out = c.execute(lit("pkgin list 2>/dev/null | awk '{print $1}'"),
                    check=False)
    have = set()
    for line in out.splitlines():
        # pkgin lists name-version; strip only the trailing -version so
        # curl-ca-bundle-1.2 -> curl-ca-bundle, never a bare curl
        name = line.rsplit("-", 1)[0] if "-" in line else line
        have.add(name)
    return {p for p in pkgs if p in have}


def update() -> None:
    """Refresh the pkgin database (smartos.clj update! :41-43)."""
    c.execute(lit("pkgin -y update"))


def install(pkgs: Iterable[str], force: bool = False) -> None:
    """pkgin install missing packages (smartos.clj install :45-55)."""
    pkgs = list(pkgs)
    have = set() if force else installed(pkgs)
    missing = [p for p in pkgs if p not in have]
    if not missing:
        return
    c.execute(lit("pkgin -y install "
                  + " ".join(c.escape(p) for p in missing)))


class SmartOS(os_mod.OS):
    """The stock SmartOS (smartos.clj os :109-130): hostfile, baseline
    packages, network heal."""

    def setup(self, test, node):
        log.info("%s setting up smartos", node)
        setup_hostfile(test, node)
        install(BASE_PACKAGES)
        net = test.get("net")
        if net is not None:
            net.heal(test)

    def teardown(self, test, node):
        pass


os = SmartOS()
