"""Typed error taxonomy for the checker runtime.

The batch entry points (`ops.wgl_seg.check_pipeline` / `check_many`,
`ops.wgl_deep.check_pipeline` / `check_mesh`, `ops.wgl_batch.check_many`)
historically raised ad-hoc `ValueError`s; a production checking service
needs to tell "the device ran out of memory" from "this history is
malformed" from "there is no device at all", because each demands a
different recovery (bisect-and-retry, quarantine, CPU fallback — see
`ops.runner.ResilientRunner`).

Every class subclasses `ValueError` (via `CheckError`) so pre-taxonomy
`except ValueError` fallback chains keep working unchanged.

    CheckError            base; carries history_index / seed / backend
    ├── DeviceOOM         device RESOURCE_EXHAUSTED / allocation failure
    ├── DeadlineExceeded  the runner's wall-clock budget expired
    ├── BackendUnavailable no usable device path (no DeviceSpec, no
    │                     kernel lowering for this backend, whole-batch
    │                     out of engine scope)
    └── CorruptHistory    a single history the engines cannot check
                          (malformed pairing, unencodable ops) — the
                          runner quarantines it with a structured
                          verdict instead of aborting the batch

`classify()` maps arbitrary exceptions escaping a batch engine onto the
taxonomy; `is_oom()` recognizes XLA out-of-memory failures across JAX
versions by type name + message markers (the `XlaRuntimeError` type
lives in a private jaxlib module whose path has moved repeatedly, so no
import of it is attempted).
"""

from __future__ import annotations

from typing import Any, Optional


class CheckError(ValueError):
    """Base of the checker-runtime error taxonomy.

    history_index: index (within the batch that raised) of the history
        that reproduces the failure, when known.
    seed: the generator seed that reproduces the history, when the
        caller tracked one (`ResilientRunner.check(seeds=...)`).
    backend: the jax backend the failing path ran on.
    batch_size: size of the batch that was being dispatched.
    """

    def __init__(self, message: str, *,
                 history_index: Optional[int] = None,
                 seed: Optional[Any] = None,
                 backend: Optional[str] = None,
                 batch_size: Optional[int] = None):
        super().__init__(message)
        self.history_index = history_index
        self.seed = seed
        self.backend = backend
        self.batch_size = batch_size

    def to_dict(self) -> dict:
        """Structured form for quarantine verdicts / checkpoints."""
        out: dict = {"error": type(self).__name__,
                     "message": str(self)}
        for k in ("history_index", "seed", "backend", "batch_size"):
            v = getattr(self, k)
            if v is not None:
                out[k] = v
        return out


class DeviceOOM(CheckError):
    """Device memory exhaustion (XLA RESOURCE_EXHAUSTED / allocation
    failure).  Recoverable by bisecting the batch."""


class DeadlineExceeded(CheckError):
    """The runner's wall-clock deadline budget expired before the
    device path finished."""


class BackendUnavailable(CheckError):
    """No usable device path: the model has no DeviceSpec, the backend
    has no kernel lowering, or the whole batch is outside every device
    engine's scope.  Recoverable by the CPU oracle."""


class CorruptHistory(CheckError):
    """A single history the engines cannot check at all (malformed
    invoke/return pairing, unencodable values).  The runner quarantines
    it; it is never retried."""


# Message markers of an XLA device-memory failure.  Matched
# case-insensitively against the stringified exception.
_OOM_MARKERS = (
    "resource_exhausted",
    "resource exhausted",
    "out of memory",
    "failed to allocate",
    "allocation failure",
    "oom",
)


def is_oom(exc: BaseException) -> bool:
    """True when `exc` looks like a device out-of-memory failure.

    Matches by type name (`XlaRuntimeError` lives in a private jaxlib
    module whose import path has moved across releases, so it is never
    imported) plus message markers; a plain `MemoryError` and an
    explicit `DeviceOOM` also qualify."""
    if isinstance(exc, (DeviceOOM, MemoryError)):
        return True
    msg = str(exc).lower()
    return any(m in msg for m in _OOM_MARKERS)


def classify(exc: BaseException, *,
             history_index: Optional[int] = None,
             seed: Optional[Any] = None,
             backend: Optional[str] = None,
             batch_size: Optional[int] = None) -> CheckError:
    """Map an exception escaping a batch engine onto the taxonomy.

    Already-typed errors pass through (with the reproducing context
    filled in if they lacked it); `wgl_seg.Unsupported` — whole-engine
    out of scope — becomes BackendUnavailable; OOM-shaped failures
    become DeviceOOM; other ValueError/Key/Index/AssertionErrors (the
    shapes prepare()/scan raise on malformed histories) become
    CorruptHistory; anything else is a bare CheckError."""
    if isinstance(exc, CheckError) and type(exc).__name__ != "Unsupported":
        if exc.history_index is None:
            exc.history_index = history_index
        if exc.seed is None:
            exc.seed = seed
        if exc.backend is None:
            exc.backend = backend
        if exc.batch_size is None:
            exc.batch_size = batch_size
        return exc
    ctx = dict(history_index=history_index, seed=seed, backend=backend,
               batch_size=batch_size)
    if type(exc).__name__ == "Unsupported":
        err: CheckError = BackendUnavailable(str(exc), **ctx)
    elif is_oom(exc):
        err = DeviceOOM(str(exc), **ctx)
    elif isinstance(exc, (ValueError, KeyError, IndexError, TypeError,
                          AssertionError)):
        err = CorruptHistory(f"{type(exc).__name__}: {exc}", **ctx)
    else:
        err = CheckError(f"{type(exc).__name__}: {exc}", **ctx)
    err.__cause__ = exc
    return err
