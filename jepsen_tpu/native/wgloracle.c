/* Native CPU linearizability oracle (CPython extension).
 *
 * The C twin of jepsen_tpu/ops/wgl_cpu.check's hot loop — Lowe-style
 * just-in-time linearization with memoization, the same algorithm
 * knossos :linear implements on the JVM (checker.clj:141-145).  It
 * exists to BOUND THE BASELINE CONSTANT: bench.py reports device
 * speedups against both the Python oracle (the knossos-equivalent
 * reference implementation) and this native one, so no ratio hides an
 * interpreter constant (VERDICT r2 #5).
 *
 * Works on the integer encoding (uop transition tables) the device
 * kernels use; rich host-side models stay on the Python oracle.
 *
 * run(ev_kind u8[nev] bytes, ev_cid i32[nev] bytes,
 *     call_uop i32[ncalls] bytes, legal u8[U*Sn] bytes,
 *     next u32[U*Sn] bytes, Sn, init_state, max_configs,
 *     time_limit_ms)
 * -> (code, events_done, fail_event, fail_cid, seen_total,
 *     survivors bytes [(u64 mask, u64 state) pairs, <= 16],
 *     pend_cid bytes i32[64])
 * code: 1 valid, 0 invalid, 2 config-explosion, 3 timeout,
 *       4 out-of-scope (> 64 simultaneously pending calls).
 */
#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <stdint.h>
#include <string.h>
#include <time.h>

#include "scancommon.h"

typedef struct {
    uint64_t mask;
    uint32_t state;
    uint32_t used;               /* epoch stamp; 0 = never used */
} centry;

typedef struct {
    centry *e;
    long cap, n;
} cset;

static int cset_init(cset *s, long cap) {
    long c = 64;
    while (c < cap * 2) c <<= 1;
    s->e = PyMem_Malloc(c * sizeof(centry));
    if (!s->e) return -1;
    memset(s->e, 0, c * sizeof(centry));
    s->cap = c;
    s->n = 0;
    return 0;
}

static uint64_t chash(uint64_t mask, uint32_t state) {
    uint64_t h = mask * 0x9E3779B97F4A7C15ULL;
    h ^= (uint64_t)state * 0xC2B2AE3D27D4EB4FULL;
    h ^= h >> 29;
    return h;
}

static int cset_grow(cset *s, uint32_t epoch);

/* insert; returns 1 if new, 0 if present, -1 OOM.  Entries from older
 * epochs read as empty, so clearing the set between returns is one
 * epoch increment instead of a memset. */
static int cset_add(cset *s, uint64_t mask, uint32_t state,
                    uint32_t epoch) {
    if (s->n * 2 >= s->cap && cset_grow(s, epoch) < 0) return -1;
    uint64_t m = (uint64_t)s->cap - 1;
    uint64_t i = chash(mask, state) & m;
    for (;;) {
        centry *e = &s->e[i];
        if (e->used != epoch) {
            e->mask = mask;
            e->state = state;
            e->used = epoch;
            s->n++;
            return 1;
        }
        if (e->mask == mask && e->state == state) return 0;
        i = (i + 1) & m;
    }
}

static int cset_grow(cset *s, uint32_t epoch) {
    centry *old = s->e;
    long ocap = s->cap;
    s->e = PyMem_Malloc(2 * ocap * sizeof(centry));
    if (!s->e) { s->e = old; return -1; }
    memset(s->e, 0, 2 * ocap * sizeof(centry));
    s->cap = 2 * ocap;
    s->n = 0;
    for (long i = 0; i < ocap; i++)
        if (old[i].used == epoch)
            cset_add(s, old[i].mask, old[i].state, epoch);
    PyMem_Free(old);
    return 0;
}

typedef struct {
    uint64_t *mask;
    uint32_t *state;
    long len, cap;
} clist;

static int clist_push(clist *l, uint64_t mask, uint32_t state) {
    if (l->len == l->cap) {
        long nc = l->cap ? l->cap * 2 : 64;
        uint64_t *nm = PyMem_Realloc(l->mask, nc * sizeof(uint64_t));
        if (!nm) return -1;
        l->mask = nm;
        uint32_t *ns = PyMem_Realloc(l->state, nc * sizeof(uint32_t));
        if (!ns) return -1;
        l->state = ns;
        l->cap = nc;
    }
    l->mask[l->len] = mask;
    l->state[l->len] = state;
    l->len++;
    return 0;
}

static double now_ms(void) {
    struct timespec ts;
    clock_gettime(CLOCK_MONOTONIC, &ts);
    return ts.tv_sec * 1000.0 + ts.tv_nsec / 1e6;
}

static PyObject *run(PyObject *self, PyObject *args) {
    Py_buffer bkind = {0}, bcid = {0}, buop = {0}, blegal = {0},
              bnext = {0};
    long Sn, init_state, max_configs;
    double time_limit_ms;
    if (!PyArg_ParseTuple(args, "y*y*y*y*y*llld",
                          &bkind, &bcid, &buop, &blegal, &bnext,
                          &Sn, &init_state, &max_configs,
                          &time_limit_ms))
        return NULL;
    Py_ssize_t nev = bkind.len;
    const uint8_t *kind = bkind.buf;
    const int32_t *cid = bcid.buf;
    const int32_t *uop = buop.buf;
    const uint8_t *legal = blegal.buf;
    const uint32_t *next = bnext.buf;
    Py_ssize_t ncalls = buop.len / 4;

    PyObject *result = NULL;
    int code = 1;
    long events_done = 0, fail_event = -1, fail_cid = -1;
    long seen_total_max = 0;
    double t0 = now_ms();

    /* live pending calls: bit -> cid (-1 free) + summary bitmask */
    int32_t pend_cid[64];
    uint64_t pend_mask = 0;
    uint32_t epoch = 0;
    for (int i = 0; i < 64; i++) pend_cid[i] = -1;
    /* bit index per call id (only valid while pending) */
    int8_t *call_bit = PyMem_Malloc((ncalls ? ncalls : 1));
    clist configs = {0}, done = {0}, frontier = {0}, nxt = {0};
    cset seen = {0};
    if (!call_bit) { PyErr_NoMemory(); goto fail; }
    memset(call_bit, -1, ncalls ? ncalls : 1);
    if (cset_init(&seen, 64) < 0) goto nomem;

    if (clist_push(&configs, 0, (uint32_t)init_state) < 0)
        goto nomem;

    for (Py_ssize_t e = 0; e < nev; e++) {
        events_done++;
        int32_t c = cid[e];
        if (kind[e] == 0) {                    /* invoke */
            if (pend_mask == ~0ULL) { code = 4; goto out; }
            int b = __builtin_ctzll(~pend_mask);
            pend_mask |= 1ULL << b;
            pend_cid[b] = c;
            call_bit[c] = (int8_t)b;
            continue;
        }
        /* return of call c: BFS closure until every config has c */
        uint64_t cbit = 1ULL << call_bit[c];
        done.len = 0;
        frontier.len = 0;
        epoch++;
        seen.n = 0;
        if (epoch == 0) {            /* u32 wrap: hard reset */
            memset(seen.e, 0, seen.cap * sizeof(centry));
            epoch = 1;
        }
        for (long i = 0; i < configs.len; i++) {
            if (cset_add(&seen, configs.mask[i], configs.state[i],
                         epoch) < 0)
                goto nomem;
            if (clist_push(&frontier, configs.mask[i],
                           configs.state[i]) < 0)
                goto nomem;
        }
        while (frontier.len) {
            if (time_limit_ms > 0 && now_ms() - t0 > time_limit_ms) {
                code = 3;
                goto out;
            }
            nxt.len = 0;
            for (long i = 0; i < frontier.len; i++) {
                uint64_t mask = frontier.mask[i];
                uint32_t st = frontier.state[i];
                if (mask & cbit) {
                    if (clist_push(&done, mask, st) < 0) goto nomem;
                    continue;
                }
                uint64_t todo = pend_mask & ~mask;
                while (todo) {
                    int b = __builtin_ctzll(todo);
                    todo &= todo - 1;
                    int32_t j = pend_cid[b];
                    int32_t u = uop[j];
                    if (!legal[(int64_t)u * Sn + st]) continue;
                    uint32_t st2 = next[(int64_t)u * Sn + st];
                    int r = cset_add(&seen, mask | (1ULL << b), st2,
                                     epoch);
                    if (r < 0) goto nomem;
                    if (r == 1 && clist_push(&nxt, mask | (1ULL << b),
                                             st2) < 0)
                        goto nomem;
                }
            }
            if (seen.n > max_configs) { code = 2; goto out; }
            /* swap frontier <- nxt */
            {
                clist tmp = frontier;
                frontier = nxt;
                nxt = tmp;
            }
        }
        if (seen.n > seen_total_max) seen_total_max = seen.n;
        if (done.len == 0) {
            code = 0;
            fail_event = (long)e;
            fail_cid = c;
            goto out;
        }
        /* retire c's bit: dedupe (mask & ~cbit, state) */
        epoch++;
        seen.n = 0;
        if (epoch == 0) {
            memset(seen.e, 0, seen.cap * sizeof(centry));
            epoch = 1;
        }
        configs.len = 0;
        for (long i = 0; i < done.len; i++) {
            uint64_t m2 = done.mask[i] & ~cbit;
            int r = cset_add(&seen, m2, done.state[i], epoch);
            if (r < 0) goto nomem;
            if (r == 1 && clist_push(&configs, m2,
                                     done.state[i]) < 0)
                goto nomem;
        }
        pend_mask &= ~cbit;
        pend_cid[call_bit[c]] = -1;
        call_bit[c] = -1;
    }

out:
    {
        /* survivors: up to 16 configs (knossos truncates to 10 anyway,
         * checker.clj:155-158) */
        long ns = configs.len < 16 ? configs.len : 16;
        uint64_t surv[32];
        for (long i = 0; i < ns; i++) {
            surv[2 * i] = configs.mask[i];
            surv[2 * i + 1] = configs.state[i];
        }
        result = Py_BuildValue(
            "(llllly#y#)", (long)code, events_done, fail_event,
            fail_cid, seen_total_max,
            (char *)surv, ns * 2 * (Py_ssize_t)sizeof(uint64_t),
            (char *)pend_cid, (Py_ssize_t)sizeof(pend_cid));
    }
    goto cleanup;

nomem:
    PyErr_NoMemory();
fail:
cleanup:
    PyMem_Free(call_bit);
    PyMem_Free(configs.mask);
    PyMem_Free(configs.state);
    PyMem_Free(done.mask);
    PyMem_Free(done.state);
    PyMem_Free(frontier.mask);
    PyMem_Free(frontier.state);
    PyMem_Free(nxt.mask);
    PyMem_Free(nxt.state);
    PyMem_Free(seen.e);
    if (bkind.obj) PyBuffer_Release(&bkind);
    if (bcid.obj) PyBuffer_Release(&bcid);
    if (buop.obj) PyBuffer_Release(&buop);
    if (blegal.obj) PyBuffer_Release(&blegal);
    if (bnext.obj) PyBuffer_Release(&bnext);
    return result;
}

/* Columnar ingest: build (ev_kind, ev_cid, call_uop) event streams
 * straight from the history's struct-of-arrays columns — the native
 * twin of ops/prep.prepare() + the per-call encode loop, so the
 * native oracle is end-to-end native exactly like the device path.
 *
 * prep_cols(proc i32[n], typ u8[n], fmap i32[n], va i32[n],
 *           vb i32[n], vkind u8[n], seen dict, rows list)
 * -> None (out of scope: double invoke / missing f / vkind 4) or
 *    (n_calls, ev_kind bytes u8[nev], ev_cid bytes i32[nev],
 *     call_uop bytes i32[n_calls], crashed long)
 * Pairing semantics identical to prepare(): fail pairs dropped,
 * ok pairs invoke+return events, info/unpaired invokes crash (invoke
 * event only).  Invoke value None resolves from the completion. */
static PyObject *prep_cols(PyObject *self, PyObject *args) {
    Py_buffer bproc = {0}, btyp = {0}, bfmap = {0}, bva = {0},
              bvb = {0}, bvk = {0};
    PyObject *seen, *rows;
    if (!PyArg_ParseTuple(args, "y*y*y*y*y*y*O!O!",
                          &bproc, &btyp, &bfmap, &bva, &bvb, &bvk,
                          &PyDict_Type, &seen, &PyList_Type, &rows))
        return NULL;
    Py_ssize_t n = (Py_ssize_t)(bproc.len / 4);
    const int32_t *proc = bproc.buf;
    const uint8_t *typ = btyp.buf;
    const int32_t *fmap = bfmap.buf;
    const int32_t *va = bva.buf;
    const int32_t *vb = bvb.buf;
    const uint8_t *vk = bvk.buf;

    PyObject *result = NULL;
    PyObject *new_rows = NULL;
    Py_ssize_t *fate = PyMem_Malloc((n ? n : 1) * sizeof(Py_ssize_t));
    uint8_t *evk = PyMem_Malloc((n ? n : 1));
    int32_t *evc = PyMem_Malloc((n ? n : 1) * sizeof(int32_t));
    int32_t *cuop = PyMem_Malloc((n ? n : 1) * sizeof(int32_t));
    int32_t *cid_of_pos = PyMem_Malloc((n ? n : 1) * sizeof(int32_t));
    utab ut = {0};
    long nev = 0, ncalls = 0, crashed = 0;
    if (!fate || !evk || !evc || !cuop || !cid_of_pos) {
        PyErr_NoMemory();
        goto done;
    }

    /* pass 1: pairing (open (proc,pos) array) */
    {
        int32_t open_p[MAX_OPEN_HARD];
        Py_ssize_t open_i[MAX_OPEN_HARD];
        long n_open = 0;
        for (Py_ssize_t i = 0; i < n; i++) fate[i] = -1;
        for (Py_ssize_t i = 0; i < n; i++) {
            int32_t p = proc[i];
            if (p == -2) goto fallback;  /* out-of-int32 client id:
                * the object paths see the real id (history.py
                * P_OUT_OF_RANGE) — whole history out of columnar
                * scope so classifications cannot diverge */
            if (p < 0) continue;
            uint8_t t = typ[i];
            long j = -1;
            for (long k = 0; k < n_open; k++)
                if (open_p[k] == p) { j = k; break; }
            if (t == 0) {
                if (j >= 0 || n_open >= MAX_OPEN_HARD) goto fallback;
                open_p[n_open] = p;
                open_i[n_open] = i;
                n_open++;
            } else if (j >= 0) {
                fate[open_i[j]] = i;
                open_p[j] = open_p[n_open - 1];
                open_i[j] = open_i[n_open - 1];
                n_open--;
            }
        }
    }

    /* pass 2: events + call uops (interning shared with the scanners).
     * Invokes precede their completions, so one sweep suffices:
     * at an invoke, assign the call id + uop and tag the paired ok
     * completion's position; at a tagged ok completion, emit the
     * return event. */
    new_rows = PyList_New(0);
    if (!new_rows || utab_init(&ut, 256) < 0) {
        PyErr_NoMemory();
        goto done;
    }
    {
        Py_ssize_t base_rows = PyList_GET_SIZE(rows);
        int seen_nonempty = PyDict_GET_SIZE(seen) > 0;
        for (Py_ssize_t i = 0; i < n; i++) cid_of_pos[i] = -1;
        for (Py_ssize_t i = 0; i < n; i++) {
            int32_t p = proc[i];
            if (p < 0) continue;
            uint8_t t = typ[i];
            if (t == 0) {
                Py_ssize_t ci = fate[i];
                int is_crash = (ci < 0 || typ[ci] == 3);
                if (!is_crash && typ[ci] == 2) continue;  /* fail */
                long a, b, okv;
                uint8_t k = vk[i];
                Py_ssize_t vi = i;
                if (k == 0 && !is_crash) { k = vk[ci]; vi = ci; }
                if (k == 4) goto fallback;
                if (k == 0 || k == 3) { a = 0; b = 0; okv = 0; }
                else {
                    a = va[vi];
                    b = (k == 2) ? vb[vi] : 0;
                    okv = 1;
                }
                long fc = fmap[i];
                if (fc < 0) goto fallback;
                long u = intern_uop(&ut, seen, seen_nonempty, rows,
                                    new_rows, fc, a, b, okv);
                if (u < 0) goto done;
                cuop[ncalls] = (int32_t)u;
                evk[nev] = 0;
                evc[nev] = (int32_t)ncalls;
                nev++;
                if (is_crash) crashed++;
                else cid_of_pos[ci] = (int32_t)ncalls;
                ncalls++;
            } else if (t == 1 && cid_of_pos[i] >= 0) {
                evk[nev] = 1;
                evc[nev] = cid_of_pos[i];
                nev++;
            }
        }
        if (publish_interning(seen, rows, new_rows, base_rows) < 0)
            goto done;
        result = Py_BuildValue(
            "(ly#y#y#l)", ncalls,
            (char *)evk, (Py_ssize_t)nev,
            (char *)evc, nev * (Py_ssize_t)sizeof(int32_t),
            (char *)cuop, ncalls * (Py_ssize_t)sizeof(int32_t),
            crashed);
    }
    goto done;

fallback:
    result = Py_None;
    Py_INCREF(Py_None);
done:
    Py_XDECREF(new_rows);
    PyMem_Free(fate);
    PyMem_Free(evk);
    PyMem_Free(evc);
    PyMem_Free(cuop);
    PyMem_Free(cid_of_pos);
    PyMem_Free(ut.e);
    if (bproc.obj) PyBuffer_Release(&bproc);
    if (btyp.obj) PyBuffer_Release(&btyp);
    if (bfmap.obj) PyBuffer_Release(&bfmap);
    if (bva.obj) PyBuffer_Release(&bva);
    if (bvb.obj) PyBuffer_Release(&bvb);
    if (bvk.obj) PyBuffer_Release(&bvk);
    return result;
}

static PyMethodDef methods[] = {
    {"run", run, METH_VARARGS,
     "JIT-linearization oracle over integer uop tables."},
    {"prep_cols", prep_cols, METH_VARARGS,
     "Columnar event-stream ingest for the native oracle."},
    {NULL, NULL, 0, NULL},
};

static struct PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT, "_wgloracle", NULL, -1, methods,
};

PyMODINIT_FUNC PyInit__wgloracle(void) {
    return PyModule_Create(&moduledef);
}
