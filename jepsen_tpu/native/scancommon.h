/* Shared helpers for the native history scanners/oracle:
 * growable int32 vector, open-addressing uop-interning hash, and the
 * hard bound on simultaneously-open calls.  Included by histscan.c,
 * wgloracle.c and packext.c so the interning semantics live in ONE
 * place (static inline: packext builds -Wall -Werror and must not
 * trip unused-function on the helpers it doesn't call).  The PyMem-
 * based containers here require the GIL; packext's thread workers use
 * their own malloc-based twins and only touch these from the serial
 * merge phase. */
#ifndef JEPSEN_TPU_SCANCOMMON_H
#define JEPSEN_TPU_SCANCOMMON_H

#include <Python.h>
#include <stdint.h>

#define MAX_OPEN_HARD 64

typedef struct {
    int32_t *data;
    Py_ssize_t len, cap;
} vec;

static inline int vec_push(vec *v, int32_t x) {
    if (v->len == v->cap) {
        Py_ssize_t ncap = v->cap ? v->cap * 2 : 256;
        int32_t *nd = PyMem_Realloc(v->data, ncap * sizeof(int32_t));
        if (!nd) return -1;
        v->data = nd;
        v->cap = ncap;
    }
    v->data[v->len++] = x;
    return 0;
}

/* uop interning table: key (f, a, b, ok) -> dense uop id */
typedef struct { int64_t f, a, b, ok; long u; } uent;
typedef struct { uent *e; long cap, n; } utab;

static inline int utab_init(utab *t, long cap) {
    long c = 64;
    while (c < cap) c <<= 1;
    t->e = PyMem_Malloc(c * sizeof(uent));
    if (!t->e) return -1;
    for (long i = 0; i < c; i++) t->e[i].u = -1;
    t->cap = c;
    t->n = 0;
    return 0;
}

static inline uint64_t utab_hash(int64_t f, int64_t a, int64_t b, int64_t ok) {
    uint64_t h = 1469598103934665603ULL;
    h = (h ^ (uint64_t)f) * 1099511628211ULL;
    h = (h ^ (uint64_t)a) * 1099511628211ULL;
    h = (h ^ (uint64_t)b) * 1099511628211ULL;
    h = (h ^ (uint64_t)ok) * 1099511628211ULL;
    return h;
}

/* find slot for key; returns index into t->e (occupied or empty) */
static inline long utab_slot(utab *t, int64_t f, int64_t a, int64_t b,
                      int64_t ok) {
    uint64_t m = (uint64_t)t->cap - 1;
    uint64_t i = utab_hash(f, a, b, ok) & m;
    for (;;) {
        uent *e = &t->e[i];
        if (e->u < 0 || (e->f == f && e->a == a && e->b == b
                         && e->ok == ok))
            return (long)i;
        i = (i + 1) & m;
    }
}

static inline int utab_grow(utab *t) {
    uent *old = t->e;
    long ocap = t->cap;
    t->e = PyMem_Malloc(2 * ocap * sizeof(uent));
    if (!t->e) { t->e = old; return -1; }
    t->cap = 2 * ocap;
    for (long i = 0; i < t->cap; i++) t->e[i].u = -1;
    for (long i = 0; i < ocap; i++)
        if (old[i].u >= 0) {
            long s = utab_slot(t, old[i].f, old[i].a, old[i].b,
                               old[i].ok);
            t->e[s] = old[i];
        }
    PyMem_Free(old);
    return 0;
}

/* Intern (f, a, b, ok) against the shared Python `seen`/staged
 * `new_rows`, with the C hash as the fast path.  Returns the uop id,
 * or -1 on error (Python exception set). */
static inline long intern_uop(utab *ut, PyObject *seen, int seen_nonempty,
                       PyObject *rows, PyObject *new_rows,
                       long fc, long a, long b, long okv) {
    long s2 = utab_slot(ut, fc, a, b, okv);
    if (ut->e[s2].u >= 0) return ut->e[s2].u;
    long u = -1;
    if (seen_nonempty) {
        PyObject *key = Py_BuildValue("(llll)", fc, a, b, okv);
        if (!key) return -1;
        PyObject *uo = PyDict_GetItem(seen, key);
        Py_DECREF(key);
        if (uo) u = PyLong_AsLong(uo);
    }
    if (u < 0) {
        u = PyList_GET_SIZE(rows) + PyList_GET_SIZE(new_rows);
        PyObject *key = Py_BuildValue("(llll)", fc, a, b, okv);
        if (!key) return -1;
        int r = PyList_Append(new_rows, key);
        Py_DECREF(key);
        if (r < 0) return -1;
    }
    ut->e[s2].f = fc;
    ut->e[s2].a = a;
    ut->e[s2].b = b;
    ut->e[s2].ok = okv;
    ut->e[s2].u = u;
    if (++ut->n * 2 > ut->cap && utab_grow(ut) < 0) {
        PyErr_NoMemory();
        return -1;
    }
    return u;
}

/* publish staged interning rows into the shared seen/rows */
static inline int publish_interning(PyObject *seen, PyObject *rows,
                             PyObject *new_rows, Py_ssize_t base_rows) {
    Py_ssize_t m = PyList_GET_SIZE(new_rows);
    for (Py_ssize_t i = 0; i < m; i++) {
        PyObject *key = PyList_GET_ITEM(new_rows, i);
        PyObject *uu = PyLong_FromSsize_t(base_rows + i);
        int r = uu ? PyDict_SetItem(seen, key, uu) : -1;
        Py_XDECREF(uu);
        if (r < 0) return -1;
        if (PyList_Append(rows, key) < 0) return -1;
    }
    return 0;
}

#endif /* JEPSEN_TPU_SCANCOMMON_H */
