/* Native parallel ingest: GIL-released, work-stealing scan-and-pack
 * (CPython extension; ISSUE 9).
 *
 * The device kernels left the host behind: on the 3400-key north-star
 * row the WGL kernel runs 0.285s inside 1.25s of warm wall, and the
 * single-threaded numpy pack (planner._pack_regs +
 * _compact_many_block) is most of the difference — PR 8's overlap
 * executor can only HIDE host work behind device compute, not shrink
 * it.  This module shrinks it: the per-key work (columnar scan,
 * snapshot-delta derivation, compact row-stream packing) is perfectly
 * parallel across the key axis, so a small thread pool does it with
 * the GIL released, writing straight into one arena laid out exactly
 * as the compact wire block the device kernel consumes — results go
 * zero-copy (np.frombuffer -> jax.device_put) into the overlap
 * executor with no Python-side reassembly.
 *
 * Scheduling is work-stealing: each thread owns a contiguous key
 * range with an atomic claim cursor; a thread whose range drains
 * claims from the next live range with the same atomic op, so a few
 * expensive keys cannot serialize the batch and the schedule never
 * affects output bytes (each key writes only its own arena segment).
 *
 * Every entry point is a bit-identical twin of existing Python/numpy
 * code and degrades to it on any error (tests/test_packext.py pins
 * the equivalence; the planner records pack_backend/pack_threads so
 * no degradation is silent):
 *
 *   pack_compact_many(keys, Kp, R, U, n_threads)
 *       keys: list of (ret_slots, cand_counts, cand_slots, cand_uops)
 *       int32 buffers — the planner._fk_arrays form, one per scanned
 *       key.  Derives each key's per-return invoke deltas from its
 *       candidate snapshots in SLOT order (exactly np.nonzero's order
 *       inside planner._pack_regs) and packs the chunk into the
 *       compact wire block _compact_many_block emits: rows u8[Rp]
 *       (low nibble ret+1, high nibble islot+1) ++ iuop u8|u16[Rp] ++
 *       cum i32[Kp+1].  Returns (arena bytes, Rp, lp_min).
 *
 *   scan_cols_many(cols_list, seen, rows, max_open_bits, n_threads)
 *       Parallel twin of histscan.fast_scan_cols over MANY keys.
 *       Threads intern uops into key-local tables; a serial merge in
 *       key order assigns global ids in exactly the order the serial
 *       per-key scan would have (first encounter across key order,
 *       stream order within a key), then a second parallel pass
 *       remaps the uop columns.  Out-of-scope keys yield None and
 *       stage nothing.  Returns a list parallel to cols_list of
 *       fast_scan_cols-shaped tuples (or None per key).
 *
 *   or_words(plane, words, masks)
 *       plane.ravel()[words[i]] |= masks[i] over a writable uint32
 *       buffer, GIL released — the batch set_bits word-insertion the
 *       Elle packed planes (ops/elle_mesh) ride.
 *
 *   route_ops(ops, start_index)
 *       One attribute-access pass over Op objects for the live
 *       scheduler's pairing/demux loop (live/windows.Tenant.ingest):
 *       kind/process/index classification + KV key split in C.
 */
#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <pthread.h>
#include <stdint.h>
#include <stdlib.h>
#include <string.h>

#include "scancommon.h"

/* ---------------------------------------------------------------- */
/* Work-stealing pool: per-thread ranges with atomic claim cursors.  */

typedef struct {
    void (*fn)(void *ctx, long i);
    void *ctx;
    long *lo;          /* atomic claim cursor per range */
    long *hi;          /* fixed range ends */
    int nr;
} pk_pool;

typedef struct { pk_pool *p; int self; } pk_arg;

static void pk_drain(pk_pool *p, int self) {
    for (int off = 0; off < p->nr; off++) {
        int r = (self + off) % p->nr;       /* own range, then steal */
        for (;;) {
            long i = __atomic_fetch_add(&p->lo[r], 1, __ATOMIC_RELAXED);
            if (i >= p->hi[r]) break;
            p->fn(p->ctx, i);
        }
    }
}

static void *pk_thread(void *a) {
    pk_arg *pa = a;
    pk_drain(pa->p, pa->self);
    return NULL;
}

/* Run fn(ctx, i) for i in [0, n).  Caller must NOT hold the GIL and
 * fn must not touch Python state.  Claiming is atomic, so any subset
 * of successfully-spawned threads (plus the calling thread, which
 * always participates) completes ALL work — spawn failure degrades
 * to fewer workers, never to lost keys. */
static void pk_parallel(long n, int n_threads,
                        void (*fn)(void *, long), void *ctx) {
    if (n <= 0) return;
    if (n_threads < 1) n_threads = 1;
    if (n_threads > 64) n_threads = 64;
    if ((long)n_threads > n) n_threads = (int)n;
    long lo[64], hi[64];
    pk_pool p = {fn, ctx, lo, hi, n_threads};
    for (int r = 0; r < n_threads; r++) {
        lo[r] = n * r / n_threads;
        hi[r] = n * (r + 1) / n_threads;
    }
    pthread_t tid[64];
    pk_arg args[64];
    int spawned = 0;
    for (int t = 0; t + 1 < n_threads; t++) {
        args[t].p = &p;
        args[t].self = t;
        if (pthread_create(&tid[spawned], NULL, pk_thread, &args[t]))
            break;
        spawned++;
    }
    pk_drain(&p, n_threads - 1);
    for (int t = 0; t < spawned; t++)
        pthread_join(tid[t], NULL);
}

/* ---------------------------------------------------------------- */
/* malloc-based containers for thread workers (PyMem needs the GIL). */

typedef struct { int32_t *d; long len, cap; } mvec;

static int mvec_push(mvec *v, int32_t x) {
    if (v->len == v->cap) {
        long nc = v->cap ? v->cap * 2 : 256;
        int32_t *nd = realloc(v->d, (size_t)nc * sizeof(int32_t));
        if (!nd) return -1;
        v->d = nd;
        v->cap = nc;
    }
    v->d[v->len++] = x;
    return 0;
}

/* local intern table: (f, a, b, ok) -> key-local dense id */
typedef struct { int64_t f, a, b, ok; long u; } pent;
typedef struct { pent *e; long cap, n; } ptab;

static int ptab_init(ptab *t, long cap) {
    long c = 64;
    while (c < cap) c <<= 1;
    t->e = malloc((size_t)c * sizeof(pent));
    if (!t->e) return -1;
    for (long i = 0; i < c; i++) t->e[i].u = -1;
    t->cap = c;
    t->n = 0;
    return 0;
}

static long ptab_slot(ptab *t, int64_t f, int64_t a, int64_t b,
                      int64_t ok) {
    uint64_t m = (uint64_t)t->cap - 1;
    uint64_t i = utab_hash(f, a, b, ok) & m;   /* the ONE shared hash */
    for (;;) {
        pent *e = &t->e[i];
        if (e->u < 0 || (e->f == f && e->a == a && e->b == b
                         && e->ok == ok))
            return (long)i;
        i = (i + 1) & m;
    }
}

static int ptab_grow(ptab *t) {
    pent *old = t->e;
    long ocap = t->cap;
    t->e = malloc((size_t)(2 * ocap) * sizeof(pent));
    if (!t->e) { t->e = old; return -1; }
    t->cap = 2 * ocap;
    for (long i = 0; i < t->cap; i++) t->e[i].u = -1;
    for (long i = 0; i < ocap; i++)
        if (old[i].u >= 0) {
            long s = ptab_slot(t, old[i].f, old[i].a, old[i].b,
                               old[i].ok);
            t->e[s] = old[i];
        }
    free(old);
    return 0;
}

/* ---------------------------------------------------------------- */
/* pack_compact_many                                                 */

typedef struct {
    const int32_t *rs, *cnt, *cs, *cu;
    long nr, tc;
    uint8_t *rows8;     /* per-key scratch stream, malloc'd */
    uint8_t *iu;
    long rows_k;
    int err;            /* 0 ok, 1 nomem, 2 malformed input */
} pk_key;

typedef struct {
    pk_key *keys;
    long R;
    int ud;
} pk_scan_ctx;

/* Phase 1: one key's snapshot-delta scan + local row-stream pack.
 * Bit-identical to planner._pack_regs at I = 1: per return, the slots
 * whose occupant changed since the previous snapshot (with the
 * previous return's slot freed first), ascending slot order; the
 * last delta rides the return's own row, earlier ones are spill rows
 * (ret nibble 0); a delta-less return is a lone row. */
static void pk_scan_key(void *vctx, long i) {
    pk_scan_ctx *ctx = vctx;
    pk_key *K = &ctx->keys[i];
    long R = ctx->R;
    int ud = ctx->ud;
    int32_t prev[16], cur[16], dslot[16], duop[16];
    for (long s = 0; s < R; s++) prev[s] = -1;
    long cap = K->nr + K->tc;
    K->rows8 = malloc(cap ? (size_t)cap : 1);
    K->iu = malloc((cap ? (size_t)cap : 1) * (size_t)ud);
    if (!K->rows8 || !K->iu) { K->err = 1; return; }
    long coff = 0, w = 0;
    for (long r = 0; r < K->nr; r++) {
        long c = K->cnt[r];
        long ret = K->rs[r];
        if (ret < 0 || ret >= R || c < 0 || coff + c > K->tc) {
            K->err = 2;
            return;
        }
        for (long s = 0; s < R; s++) cur[s] = -1;
        for (long j = 0; j < c; j++) {
            long sl = K->cs[coff + j];
            if (sl < 0 || sl >= R) { K->err = 2; return; }
            cur[sl] = K->cu[coff + j];
        }
        coff += c;
        long nd = 0;
        for (long s = 0; s < R; s++)
            if (cur[s] != -1 && cur[s] != prev[s]) {
                dslot[nd] = (int32_t)s;
                duop[nd] = cur[s];
                nd++;
            }
        if (nd == 0) {
            K->rows8[w] = (uint8_t)(ret + 1);
            if (ud == 1) K->iu[w] = 0;
            else { K->iu[2 * w] = 0; K->iu[2 * w + 1] = 0; }
            w++;
        } else {
            for (long j = 0; j < nd; j++) {
                uint8_t low = (j == nd - 1) ? (uint8_t)(ret + 1) : 0;
                K->rows8[w] = (uint8_t)(low
                                        | (uint8_t)((dslot[j] + 1) << 4));
                if (ud == 1) K->iu[w] = (uint8_t)duop[j];
                else {
                    uint16_t u16 = (uint16_t)duop[j];
                    memcpy(K->iu + 2 * w, &u16, 2);
                }
                w++;
            }
        }
        for (long s = 0; s < R; s++) prev[s] = cur[s];
        prev[ret] = -1;
    }
    K->rows_k = w;
}

typedef struct {
    pk_key *keys;
    uint8_t *rows_out;  /* arena: rows stream */
    uint8_t *iu_out;    /* arena: iuop stream */
    const int32_t *cum;
    int ud;
} pk_copy_ctx;

/* Phase 3: copy each key's local stream into its arena segment. */
static void pk_copy_key(void *vctx, long i) {
    pk_copy_ctx *ctx = vctx;
    pk_key *K = &ctx->keys[i];
    long base = ctx->cum[i];
    if (K->rows_k) {
        memcpy(ctx->rows_out + base, K->rows8, (size_t)K->rows_k);
        memcpy(ctx->iu_out + (size_t)base * (size_t)ctx->ud, K->iu,
               (size_t)K->rows_k * (size_t)ctx->ud);
    }
}

static void pk_free_keys(pk_key *keys, Py_ssize_t nk) {
    if (!keys) return;
    for (Py_ssize_t i = 0; i < nk; i++) {
        free(keys[i].rows8);
        free(keys[i].iu);
    }
    free(keys);
}

static PyObject *pack_compact_many(PyObject *self, PyObject *args) {
    PyObject *key_list;
    long Kp, R, U, n_threads;
    if (!PyArg_ParseTuple(args, "O!llll", &PyList_Type, &key_list,
                          &Kp, &R, &U, &n_threads))
        return NULL;
    if (R < 1 || R > 15) {
        PyErr_SetString(PyExc_ValueError,
                        "pack_compact_many needs 1 <= R <= 15 (slot "
                        "ids ride 4-bit nibbles)");
        return NULL;
    }
    Py_ssize_t nk = PyList_GET_SIZE(key_list);
    if (nk > Kp) {
        PyErr_SetString(PyExc_ValueError, "len(keys) > Kp");
        return NULL;
    }
    int ud = (U <= 255) ? 1 : 2;

    Py_buffer *bufs = PyMem_Calloc((size_t)(nk ? nk : 1) * 4,
                                   sizeof(Py_buffer));
    pk_key *keys = calloc(nk ? (size_t)nk : 1, sizeof(pk_key));
    int32_t *cum = NULL;
    PyObject *out = NULL, *arena = NULL;
    Py_ssize_t acquired = 0;
    if (!bufs || !keys) {
        PyErr_NoMemory();
        goto done;
    }
    for (Py_ssize_t i = 0; i < nk; i++) {
        PyObject *t = PyList_GET_ITEM(key_list, i);
        if (!PyTuple_Check(t) || PyTuple_GET_SIZE(t) != 4) {
            PyErr_SetString(PyExc_TypeError,
                            "keys must be 4-tuples of int32 buffers");
            goto done;
        }
        for (int j = 0; j < 4; j++) {
            if (PyObject_GetBuffer(PyTuple_GET_ITEM(t, j),
                                   &bufs[4 * i + j], PyBUF_SIMPLE) < 0)
                goto done;
            acquired++;
        }
        pk_key *K = &keys[i];
        K->rs = bufs[4 * i].buf;
        K->cnt = bufs[4 * i + 1].buf;
        K->cs = bufs[4 * i + 2].buf;
        K->cu = bufs[4 * i + 3].buf;
        K->nr = (long)(bufs[4 * i].len / 4);
        K->tc = (long)(bufs[4 * i + 2].len / 4);
        if (bufs[4 * i + 1].len / 4 != bufs[4 * i].len / 4
            || bufs[4 * i + 3].len != bufs[4 * i + 2].len) {
            PyErr_SetString(PyExc_ValueError,
                            "key buffer length mismatch");
            goto done;
        }
    }

    {
        pk_scan_ctx ctx = {keys, R, ud};
        Py_BEGIN_ALLOW_THREADS
        pk_parallel((long)nk, (int)n_threads, pk_scan_key, &ctx);
        Py_END_ALLOW_THREADS
    }
    for (Py_ssize_t i = 0; i < nk; i++) {
        if (keys[i].err == 1) { PyErr_NoMemory(); goto done; }
        if (keys[i].err == 2) {
            PyErr_SetString(PyExc_ValueError,
                            "malformed key arrays (slot out of range)");
            goto done;
        }
    }

    cum = malloc((size_t)(Kp + 1) * sizeof(int32_t));
    if (!cum) { PyErr_NoMemory(); goto done; }
    cum[0] = 0;
    long lp_min = 0;
    for (long k = 0; k < Kp; k++) {
        long rk = (k < nk) ? keys[k].rows_k : 0;
        if (rk > lp_min) lp_min = rk;
        cum[k + 1] = cum[k] + (int32_t)rk;
    }
    long total = cum[Kp];
    /* exactly _compact_many_block's rounding (0 rows -> Rp 0, an
     * arena of just the cum table — bit-identical twins even there) */
    long Rp = ((total + 8191) / 8192) * 8192;
    Py_ssize_t nbytes = (Py_ssize_t)Rp * (1 + ud)
        + (Py_ssize_t)(Kp + 1) * 4;
    arena = PyBytes_FromStringAndSize(NULL, nbytes);
    if (!arena) goto done;
    {
        uint8_t *base = (uint8_t *)PyBytes_AS_STRING(arena);
        pk_copy_ctx cctx = {keys, base, base + Rp, cum, ud};
        Py_BEGIN_ALLOW_THREADS
        /* zero the stream padding, then parallel-copy the live rows */
        memset(base + total, 0, (size_t)(Rp - total));
        memset(base + Rp + (size_t)total * ud, 0,
               (size_t)(Rp - total) * (size_t)ud);
        memcpy(base + (size_t)Rp * (1 + ud), cum,
               (size_t)(Kp + 1) * 4);
        pk_parallel((long)nk, (int)n_threads, pk_copy_key, &cctx);
        Py_END_ALLOW_THREADS
    }
    out = Py_BuildValue("(Oll)", arena, Rp, lp_min);

done:
    Py_XDECREF(arena);
    free(cum);
    pk_free_keys(keys, nk);
    if (bufs) {
        for (Py_ssize_t i = 0; i < acquired; i++)
            PyBuffer_Release(&bufs[i]);
        PyMem_Free(bufs);
    }
    return out;
}

/* ---------------------------------------------------------------- */
/* scan_cols_many                                                    */

typedef struct {
    /* inputs (borrowed buffer pointers, valid while GIL released) */
    const int32_t *proc, *fmap, *va, *vb;
    const uint8_t *typ, *vk;
    long n;
    /* outputs */
    int status;         /* 0 ok, 1 out-of-scope, 2 nomem */
    long n_calls, max_open;
    mvec ret_slots, cand_counts, cand_slots, cand_uops, cut_flags,
         d_counts, d_slots, d_uops, ret_pos;
    int64_t *uops;      /* distinct (f,a,b,ok) quads, encounter order */
    long n_uops, cap_uops;
    ptab tab;
    long *remap;        /* local id -> global id (merge phase) */
} sc_key;

typedef struct {
    sc_key *keys;
    long max_open_bits;
    int remap_pass;     /* 0 = scan, 1 = remap cand/d uop columns */
} sc_ctx;

static long sc_intern(sc_key *K, long fc, long a, long b, long okv) {
    long s = ptab_slot(&K->tab, fc, a, b, okv);
    if (K->tab.e[s].u >= 0) return K->tab.e[s].u;
    if (K->n_uops == K->cap_uops) {
        long nc = K->cap_uops ? K->cap_uops * 2 : 64;
        int64_t *nd = realloc(K->uops, (size_t)nc * 4 * sizeof(int64_t));
        if (!nd) return -2;
        K->uops = nd;
        K->cap_uops = nc;
    }
    long u = K->n_uops++;
    int64_t *q = K->uops + 4 * u;
    q[0] = fc; q[1] = a; q[2] = b; q[3] = okv;
    pent e = {fc, a, b, okv, u};
    K->tab.e[s] = e;
    if (++K->tab.n * 2 > K->tab.cap && ptab_grow(&K->tab) < 0)
        return -2;
    return u;
}

/* One key's columnar scan — the logic of histscan.fast_scan_cols with
 * key-LOCAL interning (no Python calls; bit-identical outputs after
 * the merge remaps local ids to the serial scan's global order). */
static void sc_scan_key(void *vctx, long ki) {
    sc_ctx *ctx = vctx;
    sc_key *K = &ctx->keys[ki];
    if (ctx->remap_pass) {
        if (K->status == 0 && K->remap) {
            for (long i = 0; i < K->cand_uops.len; i++)
                K->cand_uops.d[i] =
                    (int32_t)K->remap[K->cand_uops.d[i]];
            for (long i = 0; i < K->d_uops.len; i++)
                K->d_uops.d[i] = (int32_t)K->remap[K->d_uops.d[i]];
        }
        return;
    }
    long n = K->n;
    long max_open_bits = ctx->max_open_bits;
    if (max_open_bits > MAX_OPEN_HARD) max_open_bits = MAX_OPEN_HARD;
    Py_ssize_t *fate = malloc((n ? (size_t)n : 1) * sizeof(Py_ssize_t));
    if (!fate || ptab_init(&K->tab, 256) < 0) {
        free(fate);
        K->status = 2;
        return;
    }

    /* pass 1: pair completions with invokes */
    {
        int32_t open_p[MAX_OPEN_HARD];
        long open_i[MAX_OPEN_HARD];
        long n_open1 = 0;
        for (long i = 0; i < n; i++) fate[i] = -1;
        for (long i = 0; i < n; i++) {
            int32_t p = K->proc[i];
            if (p == -2) goto out_of_scope;  /* out-of-int32 client id */
            if (p < 0) continue;
            uint8_t t = K->typ[i];
            long j = -1;
            for (long k = 0; k < n_open1; k++)
                if (open_p[k] == p) { j = k; break; }
            if (t == 0) {
                if (j >= 0) goto out_of_scope;      /* double invoke */
                if (n_open1 >= MAX_OPEN_HARD) goto out_of_scope;
                open_p[n_open1] = p;
                open_i[n_open1] = i;
                n_open1++;
            } else if (j >= 0) {
                fate[open_i[j]] = i;
                open_p[j] = open_p[n_open1 - 1];
                open_i[j] = open_i[n_open1 - 1];
                n_open1--;
            }
        }
        if (n_open1 > 0) goto out_of_scope;         /* crashed calls */
    }

    /* pass 2: slots + local interning + returns */
    {
        long slot_of[MAX_OPEN_HARD], uop_of[MAX_OPEN_HARD];
        int32_t open_procs[MAX_OPEN_HARD];
        long free_slots[MAX_OPEN_HARD];
        long n_free = 0, next_slot = 0, n_open = 0;
        long max_open = 0, n_calls = 0;
        long d_emitted = 0;

        for (long i = 0; i < n; i++) {
            int32_t p = K->proc[i];
            if (p < 0) continue;
            uint8_t t = K->typ[i];
            if (t == 0) {
                Py_ssize_t ci = fate[i];
                if (ci < 0 || K->typ[ci] == 3) goto out_of_scope;
                if (K->typ[ci] == 2) continue;      /* fail pair */
                long a, b, okv;
                uint8_t k = K->vk[i];
                long vi = i;
                if (k == 0) { k = K->vk[ci]; vi = ci; }
                if (k == 4) goto out_of_scope;      /* out of int32 */
                if (k == 0 || k == 3) { a = 0; b = 0; okv = 0; }
                else {
                    a = K->va[vi];
                    b = (k == 2) ? K->vb[vi] : 0;
                    okv = 1;
                }
                long fc = K->fmap[i];
                if (fc < 0) goto out_of_scope;      /* f not in spec */
                long u = sc_intern(K, fc, a, b, okv);
                if (u == -2) goto nomem;
                long s = n_free ? free_slots[--n_free] : next_slot++;
                if (n_open >= MAX_OPEN_HARD) goto out_of_scope;
                open_procs[n_open] = p;
                slot_of[n_open] = s;
                uop_of[n_open] = u;
                n_open++;
                if (n_open > max_open) {
                    max_open = n_open;
                    if (max_open > max_open_bits) goto out_of_scope;
                }
                n_calls++;
                if (mvec_push(&K->d_slots, (int32_t)s) < 0 ||
                    mvec_push(&K->d_uops, (int32_t)u) < 0)
                    goto nomem;
            } else if (t == 1) {
                long idx = -1;
                for (long j = 0; j < n_open; j++)
                    if (open_procs[j] == p) { idx = j; break; }
                if (idx < 0) continue;
                if (mvec_push(&K->d_counts,
                              (int32_t)(K->d_slots.len - d_emitted)) < 0)
                    goto nomem;
                d_emitted = K->d_slots.len;
                if (mvec_push(&K->ret_slots,
                              (int32_t)slot_of[idx]) < 0 ||
                    mvec_push(&K->cand_counts, (int32_t)n_open) < 0 ||
                    mvec_push(&K->ret_pos, (int32_t)i) < 0)
                    goto nomem;
                for (long j = 0; j < n_open; j++) {
                    if (mvec_push(&K->cand_slots,
                                  (int32_t)slot_of[j]) < 0 ||
                        mvec_push(&K->cand_uops,
                                  (int32_t)uop_of[j]) < 0)
                        goto nomem;
                }
                free_slots[n_free++] = slot_of[idx];
                for (long j = idx; j < n_open - 1; j++) {
                    open_procs[j] = open_procs[j + 1];
                    slot_of[j] = slot_of[j + 1];
                    uop_of[j] = uop_of[j + 1];
                }
                n_open--;
                if (mvec_push(&K->cut_flags, n_open == 0 ? 1 : 0) < 0)
                    goto nomem;
            }
        }
        K->n_calls = n_calls;
        K->max_open = max_open;
        K->status = 0;
    }
    free(fate);
    return;

out_of_scope:
    free(fate);
    K->status = 1;
    return;

nomem:
    free(fate);
    K->status = 2;
}

static void sc_free_key(sc_key *K) {
    free(K->ret_slots.d);
    free(K->cand_counts.d);
    free(K->cand_slots.d);
    free(K->cand_uops.d);
    free(K->cut_flags.d);
    free(K->d_counts.d);
    free(K->d_slots.d);
    free(K->d_uops.d);
    free(K->ret_pos.d);
    free(K->uops);
    free(K->tab.e);
    PyMem_Free(K->remap);
}

static PyObject *scan_cols_many(PyObject *self, PyObject *args) {
    PyObject *cols_list, *seen, *rows;
    long max_open_bits, n_threads;
    if (!PyArg_ParseTuple(args, "O!O!O!ll", &PyList_Type, &cols_list,
                          &PyDict_Type, &seen, &PyList_Type, &rows,
                          &max_open_bits, &n_threads))
        return NULL;
    Py_ssize_t nk = PyList_GET_SIZE(cols_list);

    Py_buffer *bufs = PyMem_Calloc((size_t)(nk ? nk : 1) * 6,
                                   sizeof(Py_buffer));
    sc_key *keys = calloc(nk ? (size_t)nk : 1, sizeof(sc_key));
    PyObject *result = NULL, *new_rows = NULL, *out_list = NULL;
    utab g = {0};
    Py_ssize_t acquired = 0;
    if (!bufs || !keys) {
        PyErr_NoMemory();
        goto done;
    }
    for (Py_ssize_t i = 0; i < nk; i++) {
        PyObject *t = PyList_GET_ITEM(cols_list, i);
        if (!PyTuple_Check(t) || PyTuple_GET_SIZE(t) != 6) {
            PyErr_SetString(PyExc_TypeError,
                            "cols_list items must be 6-tuples of "
                            "column buffers");
            goto done;
        }
        for (int j = 0; j < 6; j++) {
            if (PyObject_GetBuffer(PyTuple_GET_ITEM(t, j),
                                   &bufs[6 * i + j], PyBUF_SIMPLE) < 0)
                goto done;
            acquired++;
        }
        sc_key *K = &keys[i];
        long n = (long)(bufs[6 * i].len / 4);
        K->proc = bufs[6 * i].buf;
        K->typ = bufs[6 * i + 1].buf;
        K->fmap = bufs[6 * i + 2].buf;
        K->va = bufs[6 * i + 3].buf;
        K->vb = bufs[6 * i + 4].buf;
        K->vk = bufs[6 * i + 5].buf;
        K->n = n;
        if ((long)bufs[6 * i + 1].len != n
            || (long)(bufs[6 * i + 2].len / 4) != n
            || (long)(bufs[6 * i + 3].len / 4) != n
            || (long)(bufs[6 * i + 4].len / 4) != n
            || (long)bufs[6 * i + 5].len != n) {
            PyErr_SetString(PyExc_ValueError,
                            "column length mismatch");
            goto done;
        }
    }

    {
        sc_ctx ctx = {keys, max_open_bits, 0};
        Py_BEGIN_ALLOW_THREADS
        pk_parallel((long)nk, (int)n_threads, sc_scan_key, &ctx);
        Py_END_ALLOW_THREADS
    }
    for (Py_ssize_t i = 0; i < nk; i++)
        if (keys[i].status == 2) { PyErr_NoMemory(); goto done; }

    /* serial merge, key order: global ids land in exactly the order
     * the serial per-key scan would have assigned them */
    new_rows = PyList_New(0);
    if (!new_rows || utab_init(&g, 1024) < 0) {
        PyErr_NoMemory();
        goto done;
    }
    {
        Py_ssize_t base_rows = PyList_GET_SIZE(rows);
        int seen_nonempty = PyDict_GET_SIZE(seen) > 0;
        for (Py_ssize_t i = 0; i < nk; i++) {
            sc_key *K = &keys[i];
            if (K->status != 0 || K->n_uops == 0) continue;
            K->remap = PyMem_Malloc((size_t)K->n_uops * sizeof(long));
            if (!K->remap) { PyErr_NoMemory(); goto done; }
            for (long li = 0; li < K->n_uops; li++) {
                const int64_t *q = K->uops + 4 * li;
                long u = intern_uop(&g, seen, seen_nonempty, rows,
                                    new_rows, (long)q[0], (long)q[1],
                                    (long)q[2], (long)q[3]);
                if (u < 0) goto done;
                K->remap[li] = u;
            }
        }
        {
            sc_ctx ctx = {keys, max_open_bits, 1};
            Py_BEGIN_ALLOW_THREADS
            pk_parallel((long)nk, (int)n_threads, sc_scan_key, &ctx);
            Py_END_ALLOW_THREADS
        }
        if (publish_interning(seen, rows, new_rows, base_rows) < 0)
            goto done;
    }

    out_list = PyList_New(nk);
    if (!out_list) goto done;
    for (Py_ssize_t i = 0; i < nk; i++) {
        sc_key *K = &keys[i];
        PyObject *item;
        if (K->status != 0) {
            item = Py_None;
            Py_INCREF(item);
        } else {
            item = Py_BuildValue(
                "(lly#y#y#y#y#y#y#y#y#)", K->n_calls, K->max_open,
                (char *)K->ret_slots.d,
                K->ret_slots.len * (Py_ssize_t)sizeof(int32_t),
                (char *)K->cand_counts.d,
                K->cand_counts.len * (Py_ssize_t)sizeof(int32_t),
                (char *)K->cand_slots.d,
                K->cand_slots.len * (Py_ssize_t)sizeof(int32_t),
                (char *)K->cand_uops.d,
                K->cand_uops.len * (Py_ssize_t)sizeof(int32_t),
                (char *)K->cut_flags.d,
                K->cut_flags.len * (Py_ssize_t)sizeof(int32_t),
                (char *)K->d_counts.d,
                K->d_counts.len * (Py_ssize_t)sizeof(int32_t),
                (char *)K->d_slots.d,
                K->d_slots.len * (Py_ssize_t)sizeof(int32_t),
                (char *)K->d_uops.d,
                K->d_uops.len * (Py_ssize_t)sizeof(int32_t),
                (char *)K->ret_pos.d,
                K->ret_pos.len * (Py_ssize_t)sizeof(int32_t));
            if (!item) goto done;
        }
        PyList_SET_ITEM(out_list, i, item);
    }
    result = out_list;
    out_list = NULL;

done:
    Py_XDECREF(out_list);
    Py_XDECREF(new_rows);
    PyMem_Free(g.e);
    if (keys) {
        for (Py_ssize_t i = 0; i < nk; i++)
            sc_free_key(&keys[i]);
        free(keys);
    }
    if (bufs) {
        for (Py_ssize_t i = 0; i < acquired; i++)
            PyBuffer_Release(&bufs[i]);
        PyMem_Free(bufs);
    }
    return result;
}

/* ---------------------------------------------------------------- */
/* or_words: plane.ravel()[words[i]] |= masks[i], GIL released.      */

static PyObject *or_words(PyObject *self, PyObject *args) {
    Py_buffer plane = {0}, words = {0}, masks = {0};
    if (!PyArg_ParseTuple(args, "w*y*y*", &plane, &words, &masks))
        return NULL;
    PyObject *result = NULL;
    Py_ssize_t m = words.len / 8;
    Py_ssize_t nw = plane.len / 4;
    if (masks.len / 4 != m) {
        PyErr_SetString(PyExc_ValueError, "words/masks length mismatch");
        goto done;
    }
    {
        uint32_t *p = plane.buf;
        const int64_t *w = words.buf;
        const uint32_t *mk = masks.buf;
        int bad = 0;
        Py_BEGIN_ALLOW_THREADS
        for (Py_ssize_t i = 0; i < m; i++) {
            int64_t idx = w[i];
            if (idx < 0 || idx >= (int64_t)nw) { bad = 1; break; }
            p[idx] |= mk[i];
        }
        Py_END_ALLOW_THREADS
        if (bad) {
            PyErr_SetString(PyExc_IndexError,
                            "word index outside the plane");
            goto done;
        }
    }
    result = Py_None;
    Py_INCREF(result);

done:
    PyBuffer_Release(&plane);
    PyBuffer_Release(&words);
    PyBuffer_Release(&masks);
    return result;
}

/* ---------------------------------------------------------------- */
/* route_ops: the live scheduler's pairing/demux attribute pass.     */

static PyObject *s_process, *s_type, *s_f, *s_value, *s_index;
static PyObject *t_invoke, *t_ok, *t_fail, *t_info;

static int ro_type(PyObject *op) {      /* 0..3, 4 other, -2 error */
    PyObject *t = PyObject_GetAttr(op, s_type);
    if (!t) return -2;
    int out = 4;
    if (t == t_invoke) out = 0;
    else if (t == t_ok) out = 1;
    else if (t == t_fail) out = 2;
    else if (t == t_info) out = 3;
    else {
        int r;
        if ((r = PyObject_RichCompareBool(t, t_invoke, Py_EQ)) != 0)
            out = r < 0 ? -2 : 0;
        else if ((r = PyObject_RichCompareBool(t, t_ok, Py_EQ)) != 0)
            out = r < 0 ? -2 : 1;
        else if ((r = PyObject_RichCompareBool(t, t_fail, Py_EQ)) != 0)
            out = r < 0 ? -2 : 2;
        else if ((r = PyObject_RichCompareBool(t, t_info, Py_EQ)) != 0)
            out = r < 0 ? -2 : 3;
    }
    Py_DECREF(t);
    return out;
}

static PyObject *route_ops(PyObject *self, PyObject *args) {
    PyObject *ops;
    long start_index;
    if (!PyArg_ParseTuple(args, "O!l", &PyList_Type, &ops,
                          &start_index))
        return NULL;
    Py_ssize_t n = PyList_GET_SIZE(ops);
    uint8_t *kinds = PyMem_Malloc(n ? (size_t)n : 1);
    int64_t *procs = PyMem_Malloc((n ? (size_t)n : 1) * sizeof(int64_t));
    int64_t *idxs = PyMem_Malloc((n ? (size_t)n : 1) * sizeof(int64_t));
    PyObject *fs = PyList_New(n);
    PyObject *keys = PyList_New(n);
    PyObject *vals = PyList_New(n);
    PyObject *result = NULL;
    if (!kinds || !procs || !idxs || !fs || !keys || !vals) {
        PyErr_NoMemory();
        goto done;
    }
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *op = PyList_GET_ITEM(ops, i);
        /* index: synthesize the WAL position when unset (the same
         * order History.index() will stamp) */
        PyObject *ix = PyObject_GetAttr(op, s_index);
        if (!ix) goto done;
        if (ix == Py_None) {
            Py_DECREF(ix);
            ix = PyLong_FromLong(start_index + (long)i);
            if (!ix || PyObject_SetAttr(op, s_index, ix) < 0) {
                Py_XDECREF(ix);
                goto done;
            }
        }
        idxs[i] = (int64_t)PyLong_AsLongLong(ix);
        Py_DECREF(ix);
        if (idxs[i] == -1 && PyErr_Occurred()) goto done;
        /* process: exact int >= 0 is a client actor */
        PyObject *p = PyObject_GetAttr(op, s_process);
        if (!p) goto done;
        long long pv = -1;
        int client = 0;
        if (PyLong_CheckExact(p)) {
            pv = PyLong_AsLongLong(p);
            if (pv == -1 && PyErr_Occurred()) { Py_DECREF(p); goto done; }
            client = pv >= 0;
        }
        Py_DECREF(p);
        procs[i] = client ? (int64_t)pv : -1;
        if (!client) {
            kinds[i] = 5;            /* non-client actor */
            PyList_SET_ITEM(fs, i, Py_None);
            Py_INCREF(Py_None);
            PyList_SET_ITEM(keys, i, Py_None);
            Py_INCREF(Py_None);
            PyList_SET_ITEM(vals, i, Py_None);
            Py_INCREF(Py_None);
            continue;
        }
        int t = ro_type(op);
        if (t == -2) goto done;
        kinds[i] = (uint8_t)t;
        PyObject *f = PyObject_GetAttr(op, s_f);
        if (!f) goto done;
        PyList_SET_ITEM(fs, i, f);
        /* KV split: type(value).__name__ == "KV" tuples demux per
         * key, everything else rides the single None lane */
        PyObject *v = PyObject_GetAttr(op, s_value);
        if (!v) goto done;
        PyObject *key = Py_None, *val = v;
        if (PyTuple_Check(v) && PyTuple_GET_SIZE(v) == 2
            && strcmp(Py_TYPE(v)->tp_name, "KV") == 0) {
            key = PyTuple_GET_ITEM(v, 0);
            val = PyTuple_GET_ITEM(v, 1);
        }
        Py_INCREF(key);
        PyList_SET_ITEM(keys, i, key);
        Py_INCREF(val);
        PyList_SET_ITEM(vals, i, val);
        Py_DECREF(v);
    }
    result = Py_BuildValue(
        "(y#y#y#OOO)", (char *)kinds, n,
        (char *)procs, n * (Py_ssize_t)sizeof(int64_t),
        (char *)idxs, n * (Py_ssize_t)sizeof(int64_t),
        fs, keys, vals);

done:
    PyMem_Free(kinds);
    PyMem_Free(procs);
    PyMem_Free(idxs);
    Py_XDECREF(fs);
    Py_XDECREF(keys);
    Py_XDECREF(vals);
    return result;
}

/* ---------------------------------------------------------------- */

static PyMethodDef methods[] = {
    {"pack_compact_many", pack_compact_many, METH_VARARGS,
     "Parallel snapshot-delta pack of one key chunk into the compact "
     "wire block (bit-identical to _pack_regs + _compact_many_block)."},
    {"scan_cols_many", scan_cols_many, METH_VARARGS,
     "Parallel columnar scan over many keys with two-phase interning "
     "(bit-identical to serial fast_scan_cols per key)."},
    {"or_words", or_words, METH_VARARGS,
     "plane.ravel()[words] |= masks over a writable uint32 buffer."},
    {"route_ops", route_ops, METH_VARARGS,
     "Pairing/demux attribute pass for the live scheduler's ingest."},
    {NULL, NULL, 0, NULL},
};

static struct PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT, "_packext", NULL, -1, methods,
    NULL, NULL, NULL, NULL,
};

PyMODINIT_FUNC PyInit__packext(void) {
    s_process = PyUnicode_InternFromString("process");
    s_type = PyUnicode_InternFromString("type");
    s_f = PyUnicode_InternFromString("f");
    s_value = PyUnicode_InternFromString("value");
    s_index = PyUnicode_InternFromString("index");
    t_invoke = PyUnicode_InternFromString("invoke");
    t_ok = PyUnicode_InternFromString("ok");
    t_fail = PyUnicode_InternFromString("fail");
    t_info = PyUnicode_InternFromString("info");
    if (!s_process || !s_type || !s_f || !s_value || !s_index
        || !t_invoke || !t_ok || !t_fail || !t_info)
        return NULL;
    return PyModule_Create(&moduledef);
}
