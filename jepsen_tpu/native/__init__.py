"""Native (C) runtime accelerators, compiled lazily on first use.

The reference keeps its hot paths on the JVM and its fault injectors in
C (SURVEY.md §2.2); here the compute path is JAX/XLA and the native
layer accelerates the *host* runtime around it — currently `_histscan`,
the fused history scan feeding the batched device kernels
(ops/wgl_seg).  Everything degrades gracefully: if no compiler is
available the pure-Python twin runs instead, bit-identically.
"""

from __future__ import annotations

import importlib.util
import os
import subprocess
import sysconfig
import threading
from typing import Optional

_DIR = os.path.dirname(os.path.abspath(__file__))
_BUILD = os.path.join(_DIR, "build")
_lock = threading.Lock()
_cache: dict = {}


def _so_path(name: str) -> str:
    suffix = sysconfig.get_config_var("EXT_SUFFIX") or ".so"
    return os.path.join(_BUILD, name + suffix)


def _build(name: str, source: str) -> Optional[str]:
    """cc -shared -fPIC — rebuilt whenever the source is newer."""
    out = _so_path(name)
    src = os.path.join(_DIR, source)
    hdr = os.path.join(_DIR, "scancommon.h")
    try:
        newest = max([os.path.getmtime(src)]
                     + ([os.path.getmtime(hdr)]
                        if os.path.exists(hdr) else []))
        if os.path.exists(out) and os.path.getmtime(out) >= newest:
            return out
        os.makedirs(_BUILD, exist_ok=True)
        include = sysconfig.get_paths()["include"]
        cc = os.environ.get("CC", "cc")
        cmd = [cc, "-shared", "-fPIC", "-O2", f"-I{include}",
               src, "-o", out]
        r = subprocess.run(cmd, capture_output=True, timeout=120)
        if r.returncode != 0:
            return None
        return out
    except (OSError, subprocess.SubprocessError):
        return None


def _load(name: str, source: str):
    with _lock:
        if name in _cache:
            return _cache[name]
        mod = None
        path = _build(name, source)
        if path is not None:
            try:
                spec = importlib.util.spec_from_file_location(name, path)
                mod = importlib.util.module_from_spec(spec)
                spec.loader.exec_module(mod)
            except Exception:       # noqa: BLE001 - fall back to Python
                mod = None
        _cache[name] = mod
        return mod


def histscan():
    """The _histscan extension module, or None (Python fallback)."""
    if os.environ.get("JEPSEN_TPU_NO_NATIVE"):
        return None
    return _load("_histscan", "histscan.c")


def wgloracle():
    """The _wgloracle extension module, or None (Python fallback)."""
    if os.environ.get("JEPSEN_TPU_NO_NATIVE"):
        return None
    return _load("_wgloracle", "wgloracle.c")
