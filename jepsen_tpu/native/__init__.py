"""Native (C) runtime accelerators, compiled lazily on first use.

The reference keeps its hot paths on the JVM and its fault injectors in
C (SURVEY.md §2.2); here the compute path is JAX/XLA and the native
layer accelerates the *host* runtime around it — `_histscan` (the fused
history scan feeding the batched device kernels, ops/wgl_seg),
`_wgloracle` (the C twin of the CPU oracle's hot loop), and `_packext`
(the GIL-released parallel ingest layer: work-stealing scan-and-pack
for the key axis, batch word-OR for the Elle packed planes, and the
live scheduler's routing pass — ISSUE 9).  Everything degrades
gracefully: if no compiler is available the pure-Python twin runs
instead, bit-identically.

Rebuilds are md5-staleness-gated (the faultfs.py install discipline):
a stamp file beside the .so records the source+header digest, so a
source edit rebuilds exactly once and an unchanged tree never pays the
compiler, regardless of checkout mtimes.  `_packext` builds with
`-Wall -Werror` — a warning in the parallel ingest layer is a bug.
"""

from __future__ import annotations

import hashlib
import importlib.util
import os
import subprocess
import sysconfig
import threading
from typing import Optional

_DIR = os.path.dirname(os.path.abspath(__file__))
_BUILD = os.path.join(_DIR, "build")
_lock = threading.Lock()
_cache: dict = {}


def _so_path(name: str) -> str:
    suffix = sysconfig.get_config_var("EXT_SUFFIX") or ".so"
    return os.path.join(_BUILD, name + suffix)


def _src_digest(paths) -> str:
    h = hashlib.md5()
    for p in paths:
        with open(p, "rb") as f:
            h.update(f.read())
    return h.hexdigest()


def _build(name: str, source: str, flags: tuple = ()) -> Optional[str]:
    """cc -shared -fPIC — rebuilt whenever the source md5 changes
    (stamp file beside the .so; the faultfs.py staleness discipline —
    mtimes lie across checkouts, digests don't)."""
    out = _so_path(name)
    src = os.path.join(_DIR, source)
    hdr = os.path.join(_DIR, "scancommon.h")
    stamp = out + ".md5"
    try:
        digest = _src_digest([src] + ([hdr] if os.path.exists(hdr)
                                      else [])) \
            + ("+" + " ".join(flags) if flags else "")
        if os.path.exists(out) and os.path.exists(stamp):
            with open(stamp) as f:
                if f.read().strip() == digest:
                    return out
        os.makedirs(_BUILD, exist_ok=True)
        include = sysconfig.get_paths()["include"]
        cc = os.environ.get("CC", "cc")
        cmd = [cc, "-shared", "-fPIC", "-O2", *flags, f"-I{include}",
               src, "-o", out]
        r = subprocess.run(cmd, capture_output=True, timeout=120)
        if r.returncode != 0:
            return None
        with open(stamp, "w") as f:
            f.write(digest)
        return out
    except (OSError, subprocess.SubprocessError):
        return None


def _load(name: str, source: str, flags: tuple = ()):
    with _lock:
        if name in _cache:
            return _cache[name]
        mod = None
        path = _build(name, source, flags)
        if path is not None:
            try:
                spec = importlib.util.spec_from_file_location(name, path)
                mod = importlib.util.module_from_spec(spec)
                spec.loader.exec_module(mod)
            except Exception:       # noqa: BLE001 - fall back to Python
                mod = None
        _cache[name] = mod
        return mod


def histscan():
    """The _histscan extension module, or None (Python fallback)."""
    if os.environ.get("JEPSEN_TPU_NO_NATIVE"):
        return None
    return _load("_histscan", "histscan.c")


def wgloracle():
    """The _wgloracle extension module, or None (Python fallback)."""
    if os.environ.get("JEPSEN_TPU_NO_NATIVE"):
        return None
    return _load("_wgloracle", "wgloracle.c")


def _build_bin(name: str, source: str,
               flags: tuple = ()) -> Optional[str]:
    """Standalone executable variant of `_build` — same md5-staleness
    stamp discipline, no -shared/-fPIC, no Python headers.  For
    helpers that must run where Python doesn't (walsend on
    static-binary SUT hosts)."""
    out = os.path.join(_BUILD, name)
    src = os.path.join(_DIR, source)
    stamp = out + ".md5"
    try:
        digest = _src_digest([src]) \
            + ("+" + " ".join(flags) if flags else "")
        if os.path.exists(out) and os.path.exists(stamp):
            with open(stamp) as f:
                if f.read().strip() == digest:
                    return out
        os.makedirs(_BUILD, exist_ok=True)
        cc = os.environ.get("CC", "cc")
        cmd = [cc, "-O2", *flags, src, "-o", out]
        r = subprocess.run(cmd, capture_output=True, timeout=120)
        if r.returncode != 0:
            return None
        with open(stamp, "w") as f:
            f.write(digest)
        return out
    except (OSError, subprocess.SubprocessError):
        return None


def walsend() -> Optional[str]:
    """Path to the standalone `walsend` WAL-streaming binary (ingest
    wire client for hosts without Python, ISSUE 16), or None when no
    compiler is available.  Strict build, like packext: -Wall -Werror."""
    if os.environ.get("JEPSEN_TPU_NO_NATIVE"):
        return None
    with _lock:
        key = "bin:walsend"
        if key not in _cache:
            _cache[key] = _build_bin("walsend", "walsend.c",
                                     flags=("-Wall", "-Werror"))
        return _cache[key]


def packext():
    """The _packext parallel-ingest extension, or None (Python
    fallback).  Strict build: -Wall -Werror (plus -pthread for the
    work-stealing pool) — any warning fails the build and the pure
    Python/numpy twins take over, never a questionable native pack."""
    if os.environ.get("JEPSEN_TPU_NO_NATIVE"):
        return None
    return _load("_packext", "packext.c",
                 flags=("-Wall", "-Werror", "-pthread"))
