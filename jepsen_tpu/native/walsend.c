/* walsend: stream an existing history.wal to a serve-checker
 * --listen daemon (ISSUE 16) from hosts that have a C compiler and
 * nothing else — the static-binary SUT story.
 *
 *   walsend HOST PORT NAME TS WAL_PATH [WRITER]
 *
 * Wire protocol (docs/remote-ingest.md): newline-framed JSON.  Data
 * lines are shipped VERBATIM from the WAL file — the framing (crc +
 * seq) was written by history.HistoryWAL and the server re-validates
 * it, so this sender never parses op payloads at all.  Control lines:
 * we send {"ctl":{"t":"hello",...}} and {"ctl":{"t":"bye"}}, and
 * honor ack (resume cursor: skip the first `seq` lines), pause/resume
 * (flow control), and fenced (terminal).
 *
 * Exit codes: 0 streamed + fully acked; 2 fenced (a newer writer owns
 * the tenant); 1 anything else.  Rerunning after a partial send is
 * safe and cheap: the registration ack carries the server's durable
 * cursor and the sender skips exactly that many lines.
 */

#include <errno.h>
#include <netdb.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/socket.h>
#include <sys/time.h>
#include <unistd.h>

#define CTLBUF 65536

static int sock_fd = -1;

/* -- tiny field scanners (good enough for our own compact ctl json) -- */

static long json_long(const char *line, const char *key, long dflt)
{
    const char *p = strstr(line, key);
    if (!p)
        return dflt;
    p += strlen(key);
    return strtol(p, NULL, 10);
}

static int json_is(const char *line, const char *needle)
{
    return strstr(line, needle) != NULL;
}

/* -- socket helpers -------------------------------------------------- */

static int dial(const char *host, const char *port)
{
    struct addrinfo hints, *res, *rp;
    int fd = -1;
    memset(&hints, 0, sizeof(hints));
    hints.ai_family = AF_UNSPEC;
    hints.ai_socktype = SOCK_STREAM;
    if (getaddrinfo(host, port, &hints, &res) != 0)
        return -1;
    for (rp = res; rp; rp = rp->ai_next) {
        fd = socket(rp->ai_family, rp->ai_socktype, rp->ai_protocol);
        if (fd < 0)
            continue;
        if (connect(fd, rp->ai_addr, rp->ai_addrlen) == 0)
            break;
        close(fd);
        fd = -1;
    }
    freeaddrinfo(res);
    return fd;
}

static int send_all(const char *buf, size_t n)
{
    while (n > 0) {
        ssize_t w = send(sock_fd, buf, n, 0);
        if (w < 0) {
            if (errno == EINTR)
                continue;
            return -1;
        }
        buf += w;
        n -= (size_t)w;
    }
    return 0;
}

/* Shared ctl-line state: recv bytes accumulate here and are handed
 * out one line at a time.  paused/fenced/acked are updated as lines
 * arrive. */
static char ctl[CTLBUF];
static size_t ctl_n = 0;
static int paused = 0, fenced = 0;
static long acked_seq = 0;

static void ctl_handle(const char *line)
{
    if (json_is(line, "\"t\":\"fenced\"")) {
        fenced = 1;
    } else if (json_is(line, "\"t\":\"pause\"")) {
        paused = 1;
    } else if (json_is(line, "\"t\":\"resume\"")) {
        paused = 0;
    } else if (json_is(line, "\"t\":\"ack\"")) {
        long s = json_long(line, "\"seq\":", -1);
        if (s > acked_seq)
            acked_seq = s;
    }
}

/* Pump inbound ctl frames; waits up to wait_ms for the first byte.
 * Returns -1 on socket death. */
static int ctl_pump(int wait_ms)
{
    struct timeval tv;
    fd_set rd;
    tv.tv_sec = wait_ms / 1000;
    tv.tv_usec = (wait_ms % 1000) * 1000;
    FD_ZERO(&rd);
    FD_SET(sock_fd, &rd);
    if (select(sock_fd + 1, &rd, NULL, NULL, &tv) <= 0)
        return 0;
    ssize_t r = recv(sock_fd, ctl + ctl_n, sizeof(ctl) - ctl_n - 1, 0);
    if (r <= 0)
        return -1;
    ctl_n += (size_t)r;
    ctl[ctl_n] = '\0';
    char *start = ctl, *nl;
    while ((nl = memchr(start, '\n', ctl_n - (size_t)(start - ctl)))) {
        *nl = '\0';
        ctl_handle(start);
        start = nl + 1;
    }
    ctl_n -= (size_t)(start - ctl);
    memmove(ctl, start, ctl_n);
    return 0;
}

int main(int argc, char **argv)
{
    if (argc < 6) {
        fprintf(stderr, "usage: walsend HOST PORT NAME TS WAL_PATH "
                        "[WRITER]\n");
        return 1;
    }
    const char *host = argv[1], *port = argv[2];
    const char *name = argv[3], *ts = argv[4], *path = argv[5];
    const char *writer = argc > 6 ? argv[6] : "walsend";

    FILE *wal = fopen(path, "rb");
    if (!wal) {
        perror(path);
        return 1;
    }
    sock_fd = dial(host, port);
    if (sock_fd < 0) {
        fprintf(stderr, "walsend: cannot reach %s:%s\n", host, port);
        fclose(wal);
        return 1;
    }

    char hello[1024];
    int n = snprintf(hello, sizeof(hello),
                     "{\"ctl\":{\"epoch\":0,\"name\":\"%s\","
                     "\"t\":\"hello\",\"ts\":\"%s\","
                     "\"writer\":\"%s\"}}\n",
                     name, ts, writer);
    if (n <= 0 || n >= (int)sizeof(hello) || send_all(hello, (size_t)n))
        goto dead;

    /* registration ack: the server's durable cursor */
    acked_seq = -1;
    for (int spins = 0; acked_seq < 0 && !fenced && spins < 100;
         spins++)
        if (ctl_pump(100) < 0)
            goto dead;
    if (fenced)
        goto fenced_out;
    if (acked_seq < 0)
        goto dead;

    /* stream: skip the acked prefix, ship the rest verbatim */
    char *line = NULL;
    size_t cap = 0;
    ssize_t len;
    long lineno = 0, sent = 0;
    while ((len = getline(&line, &cap, wal)) > 0) {
        if (lineno++ < acked_seq)
            continue;
        while (paused && !fenced)
            if (ctl_pump(50) < 0)
                goto dead_line;
        if (fenced)
            break;
        if (send_all(line, (size_t)len))
            goto dead_line;
        sent++;
        if ((sent & 63) == 0 && ctl_pump(0) < 0)
            goto dead_line;
    }
    free(line);
    line = NULL;
    if (fenced)
        goto fenced_out;

    /* wait until everything we shipped is acked, then say bye */
    long total = lineno;
    for (int spins = 0; acked_seq < total && !fenced && spins < 600;
         spins++)
        if (ctl_pump(100) < 0)
            goto dead;
    if (fenced)
        goto fenced_out;
    if (acked_seq < total)
        goto dead;
    if (send_all("{\"ctl\":{\"t\":\"bye\"}}\n", 20))
        goto dead;
    close(sock_fd);
    fclose(wal);
    return 0;

dead_line:
    free(line);
dead:
    fprintf(stderr, "walsend: connection lost (acked %ld)\n",
            acked_seq);
    close(sock_fd);
    fclose(wal);
    return 1;

fenced_out:
    fprintf(stderr, "walsend: fenced — a newer writer owns %s/%s\n",
            name, ts);
    close(sock_fd);
    fclose(wal);
    return 2;
}
