/* Native history-scan accelerator (CPython extension).
 *
 * One fused pass over a history's ops doing invoke/completion pairing,
 * slot assignment, and op interning — the C twin of
 * jepsen_tpu/ops/wgl_seg._fast_scan, which is the host-side hot path
 * when batching thousands of independent keys for the device kernel
 * (SURVEY.md §2.5: "history transport to device").  ~8x the Python
 * scan; results are bit-identical (differential tests enforce it).
 *
 * fast_scan(ops, f_codes, seen, rows, max_open_bits)
 *   ops           list of Op objects (attrs: process/type/f/value)
 *   f_codes       dict: f -> int code
 *   seen          dict: (f, a, b, ok) -> uop id   (shared, updated)
 *   rows          list of (f, a, b, ok) rows       (shared, updated)
 *   max_open_bits max simultaneously-open calls
 * returns None when the key is outside the batch engine's scope
 * (crashed calls, deep concurrency, non-int32 values, double-invoke),
 * else a tuple:
 *   (n_calls, max_open,
 *    ret_slots  bytes of int32[n_rets],
 *    cand_counts bytes of int32[n_rets],
 *    cand_slots bytes of int32[total],
 *    cand_uops  bytes of int32[total])
 * Shared seen/rows are only mutated on success.
 */
#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <stdint.h>
#include <string.h>

#include "scancommon.h"

static PyObject *s_process, *s_type, *s_f, *s_value;
static PyObject *t_invoke, *t_ok, *t_fail, *t_info;

/* -1 error, 0 not-a-client, 1 client (proc written) */
static int client_process(PyObject *op, long *proc) {
    PyObject *p = PyObject_GetAttr(op, s_process);
    if (!p) return -1;
    if (!PyLong_CheckExact(p)) {        /* bool is not exact long */
        Py_DECREF(p);
        return 0;
    }
    long v = PyLong_AsLong(p);
    Py_DECREF(p);
    if (v == -1 && PyErr_Occurred()) return -1;
    if (v < 0) return 0;
    *proc = v;
    return 1;
}

/* op type as 0=invoke 1=ok 2=fail 3=info, -1 other, -2 error */
static int op_type(PyObject *op) {
    PyObject *t = PyObject_GetAttr(op, s_type);
    if (!t) return -2;
    int out = -1;
    if (t == t_invoke) out = 0;
    else if (t == t_ok) out = 1;
    else if (t == t_fail) out = 2;
    else if (t == t_info) out = 3;
    else {
        int r;
        if ((r = PyObject_RichCompareBool(t, t_invoke, Py_EQ)) != 0)
            out = r < 0 ? -2 : 0;
        else if ((r = PyObject_RichCompareBool(t, t_ok, Py_EQ)) != 0)
            out = r < 0 ? -2 : 1;
        else if ((r = PyObject_RichCompareBool(t, t_fail, Py_EQ)) != 0)
            out = r < 0 ? -2 : 2;
        else if ((r = PyObject_RichCompareBool(t, t_info, Py_EQ)) != 0)
            out = r < 0 ? -2 : 3;
    }
    Py_DECREF(t);
    return out;
}

/* encode value like _generic_encode_op; 1 ok, 0 out-of-scope, -1 err */
static int encode_value(PyObject *v, long *a, long *b, int *ok) {
    *a = 0; *b = 0; *ok = 0;
    if (v == Py_None) return 1;                  /* unencodable: ok=0 */
    if (PyBool_Check(v)) {
        *a = (v == Py_True);
        *ok = 1;
        return 1;
    }
    if (PyLong_Check(v)) {          /* subclasses too (IntEnum ...) */
        int overflow = 0;
        long long x = PyLong_AsLongLongAndOverflow(v, &overflow);
        if (x == -1 && PyErr_Occurred()) return -1;
        if (overflow || x < -2147483648LL || x >= 2147483648LL)
            return 0;                            /* outside int32 */
        *a = (long)x;
        *ok = 1;
        return 1;
    }
    /* subclass-inclusive (PyList_Check, not CheckExact): namedtuples
     * and list subclasses must encode as pairs exactly like the
     * Python twin's isinstance() and history._value_kind, or the
     * columnar and object paths would intern different uops for the
     * same history */
    if (PyList_Check(v) || PyTuple_Check(v)) {
        Py_ssize_t n = PySequence_Fast_GET_SIZE(v);
        if (n != 2) return 1;                    /* unencodable: ok=0 */
        PyObject *x0 = PySequence_Fast_GET_ITEM(v, 0);
        PyObject *x1 = PySequence_Fast_GET_ITEM(v, 1);
        if (!PyLong_Check(x0) || !PyLong_Check(x1)
            || PyBool_Check(x0) || PyBool_Check(x1))
            return 1;                            /* unencodable: ok=0 */
        int ov0 = 0, ov1 = 0;
        long long a0 = PyLong_AsLongLongAndOverflow(x0, &ov0);
        long long b0 = PyLong_AsLongLongAndOverflow(x1, &ov1);
        if ((a0 == -1 || b0 == -1) && PyErr_Occurred()) return -1;
        if (ov0 || ov1 || a0 < -2147483648LL || a0 >= 2147483648LL
            || b0 < -2147483648LL || b0 >= 2147483648LL)
            return 0;
        *a = (long)a0;
        *b = (long)b0;
        *ok = 1;
        return 1;
    }
    return 1;                                    /* unencodable: ok=0 */
}

static PyObject *fast_scan(PyObject *self, PyObject *args) {
    PyObject *ops, *f_codes, *seen, *rows;
    long max_open_bits;
    if (!PyArg_ParseTuple(args, "O!O!O!O!l", &PyList_Type, &ops,
                          &PyDict_Type, &f_codes, &PyDict_Type, &seen,
                          &PyList_Type, &rows, &max_open_bits))
        return NULL;
    if (max_open_bits > MAX_OPEN_HARD) max_open_bits = MAX_OPEN_HARD;

    Py_ssize_t n = PyList_GET_SIZE(ops);
    /* fate[i] = completion index for the invoke at position i, or -1 */
    Py_ssize_t *fate = PyMem_Malloc((n ? n : 1) * sizeof(Py_ssize_t));
    int8_t *kinds = PyMem_Malloc((n ? n : 1) * sizeof(int8_t));
    if (!fate || !kinds) {
        PyMem_Free(fate); PyMem_Free(kinds);
        return PyErr_NoMemory();
    }
    for (Py_ssize_t i = 0; i < n; i++) { fate[i] = -1; kinds[i] = -1; }

    /* pass 1: pair completions with invokes (open dict: proc -> pos) */
    PyObject *open_by_proc = PyDict_New();
    PyObject *result = NULL;         /* set to None for fallback */
    PyObject *new_seen = NULL, *new_rows = NULL;
    vec ret_slots = {0}, cand_counts = {0}, cand_slots = {0},
        cand_uops = {0}, cut_flags = {0}, ret_pos = {0};
    long *slot_of = NULL, *uop_of = NULL, *open_procs = NULL;
    if (!open_by_proc) goto fail;

    long n_client = 0;
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *op = PyList_GET_ITEM(ops, i);
        long proc;
        int c = client_process(op, &proc);
        if (c < 0) goto fail;
        if (c == 0) continue;
        n_client++;
        int t = op_type(op);
        if (t == -2) goto fail;
        kinds[i] = (int8_t)t;
        PyObject *pk = PyLong_FromLong(proc);
        if (!pk) goto fail;
        if (t == 0) {
            if (PyDict_GetItem(open_by_proc, pk)) {   /* double invoke */
                Py_DECREF(pk);
                goto fallback;
            }
            PyObject *pos = PyLong_FromSsize_t(i);
            int r = pos ? PyDict_SetItem(open_by_proc, pk, pos) : -1;
            Py_XDECREF(pos);
            Py_DECREF(pk);
            if (r < 0) goto fail;
        } else {
            PyObject *ip = PyDict_GetItem(open_by_proc, pk);
            if (ip) {
                fate[PyLong_AsSsize_t(ip)] = i;
                if (PyDict_DelItem(open_by_proc, pk) < 0) {
                    Py_DECREF(pk);
                    goto fail;
                }
            }
            Py_DECREF(pk);
        }
    }
    if (PyDict_GET_SIZE(open_by_proc) > 0)
        goto fallback;               /* unpaired invokes: crashed */

    /* pass 2: slots + interning + returns */
    new_seen = PyDict_New();
    new_rows = PyList_New(0);
    if (!new_seen || !new_rows) goto fail;
    slot_of = PyMem_Malloc(MAX_OPEN_HARD * sizeof(long));
    uop_of = PyMem_Malloc(MAX_OPEN_HARD * sizeof(long));
    open_procs = PyMem_Malloc(MAX_OPEN_HARD * sizeof(long));
    long free_slots[MAX_OPEN_HARD];
    long n_free = 0, next_slot = 0, n_open = 0;
    long max_open = 0, n_calls = 0;
    if (!slot_of || !uop_of || !open_procs) goto fail;

    for (Py_ssize_t i = 0; i < n; i++) {
        int8_t t = kinds[i];
        if (t < 0) continue;
        PyObject *op = PyList_GET_ITEM(ops, i);
        long proc;
        int c2 = client_process(op, &proc);
        if (c2 < 0) goto fail;
        if (c2 == 0) continue;
        if (t == 0) {
            Py_ssize_t ci = fate[i];
            if (ci < 0 || kinds[ci] == 3) goto fallback; /* crashed */
            if (kinds[ci] == 2) continue;                /* fail pair */
            PyObject *v = PyObject_GetAttr(op, s_value);
            if (!v) goto fail;
            if (v == Py_None) {
                Py_DECREF(v);
                v = PyObject_GetAttr(PyList_GET_ITEM(ops, ci), s_value);
                if (!v) goto fail;
            }
            PyObject *f = PyObject_GetAttr(op, s_f);
            if (!f) { Py_DECREF(v); goto fail; }
            PyObject *fco = PyDict_GetItem(f_codes, f);
            Py_DECREF(f);
            if (!fco) { Py_DECREF(v); goto fallback; }   /* no f-code */
            long fc = PyLong_AsLong(fco);
            long a, b; int okv;
            int e = encode_value(v, &a, &b, &okv);
            Py_DECREF(v);
            if (e < 0) goto fail;
            if (e == 0) goto fallback;                   /* non-int32 */
            PyObject *key = Py_BuildValue("(llli)", fc, a, b, okv);
            if (!key) goto fail;
            PyObject *uo = PyDict_GetItem(seen, key);
            if (!uo) uo = PyDict_GetItem(new_seen, key);
            long u;
            if (uo) {
                u = PyLong_AsLong(uo);
                Py_DECREF(key);
            } else {
                u = PyList_GET_SIZE(rows) + PyList_GET_SIZE(new_rows);
                PyObject *uu = PyLong_FromLong(u);
                int r = uu ? PyDict_SetItem(new_seen, key, uu) : -1;
                if (r == 0) r = PyList_Append(new_rows, key);
                Py_XDECREF(uu);
                Py_DECREF(key);
                if (r < 0) goto fail;
            }
            long s = n_free ? free_slots[--n_free] : next_slot++;
            if (n_open >= MAX_OPEN_HARD) goto fallback;
            open_procs[n_open] = proc;
            slot_of[n_open] = s;
            uop_of[n_open] = u;
            n_open++;
            if (n_open > max_open) {
                max_open = n_open;
                if (max_open > max_open_bits) goto fallback;
            }
            n_calls++;
        } else if (t == 1) {
            long idx = -1;
            for (long j = 0; j < n_open; j++)
                if (open_procs[j] == proc) { idx = j; break; }
            if (idx < 0) continue;
            if (vec_push(&ret_slots, (int32_t)slot_of[idx]) < 0 ||
                vec_push(&cand_counts, (int32_t)n_open) < 0 ||
                vec_push(&ret_pos, (int32_t)i) < 0)
                goto fail;
            for (long j = 0; j < n_open; j++) {
                if (vec_push(&cand_slots, (int32_t)slot_of[j]) < 0 ||
                    vec_push(&cand_uops, (int32_t)uop_of[j]) < 0)
                    goto fail;
            }
            free_slots[n_free++] = slot_of[idx];
            for (long j = idx; j < n_open - 1; j++) {
                open_procs[j] = open_procs[j + 1];
                slot_of[j] = slot_of[j + 1];
                uop_of[j] = uop_of[j + 1];
            }
            n_open--;
            if (vec_push(&cut_flags, n_open == 0 ? 1 : 0) < 0)
                goto fail;
        }
        /* t==2/3 completions: nothing to do (handled via fate) */
    }

    /* success: merge staged interning into the shared tables */
    if (PyDict_Update(seen, new_seen) < 0) goto fail;
    {
        Py_ssize_t m = PyList_GET_SIZE(new_rows);
        for (Py_ssize_t i2 = 0; i2 < m; i2++) {
            if (PyList_Append(rows, PyList_GET_ITEM(new_rows, i2)) < 0)
                goto fail;
        }
    }
    result = Py_BuildValue(
        "(lly#y#y#y#y#y#)", n_calls, max_open,
        (char *)ret_slots.data, ret_slots.len * sizeof(int32_t),
        (char *)cand_counts.data, cand_counts.len * sizeof(int32_t),
        (char *)cand_slots.data, cand_slots.len * sizeof(int32_t),
        (char *)cand_uops.data, cand_uops.len * sizeof(int32_t),
        (char *)cut_flags.data, cut_flags.len * sizeof(int32_t),
        (char *)ret_pos.data, ret_pos.len * sizeof(int32_t));
    goto done;

fallback:
    result = Py_None;
    Py_INCREF(Py_None);
    goto done;

fail:
    /* result stays NULL: propagate the Python error */
done:
    Py_XDECREF(open_by_proc);
    Py_XDECREF(new_seen);
    Py_XDECREF(new_rows);
    PyMem_Free(fate);
    PyMem_Free(kinds);
    PyMem_Free(slot_of);
    PyMem_Free(uop_of);
    PyMem_Free(open_procs);
    PyMem_Free(ret_slots.data);
    PyMem_Free(cand_counts.data);
    PyMem_Free(cand_slots.data);
    PyMem_Free(cand_uops.data);
    PyMem_Free(cut_flags.data);
    PyMem_Free(ret_pos.data);
    return result;
}

/* ---------------------------------------------------------------- */
/* Columnar scan: same fused pass, but over the history's native
 * struct-of-arrays representation (SURVEY.md §7) instead of Op
 * objects — no attribute lookups, no PyObject allocation per op.
 * ~20-30x the object walk; feeds the same _FastKey consumer.
 *
 * fast_scan_cols(proc i32[n], typ u8[n], fmap i32[n], va i32[n],
 *                vb i32[n], vkind u8[n], seen, rows, max_open_bits)
 *   fmap   per-op SPEC f-code (host maps history f-ids -> spec codes,
 *          -1 = f unknown to the spec)
 *   vkind  0 None / 1 int / 2 pair / 3 other / 4 out-of-int32
 * Returns the same tuple as fast_scan, or None when out of scope
 * (crashed calls, double invoke, vkind 4, missing f-code, deep
 * concurrency) — callers fall through to the object paths.           */

static PyObject *fast_scan_cols(PyObject *self, PyObject *args) {
    Py_buffer bproc = {0}, btyp = {0}, bfmap = {0}, bva = {0},
              bvb = {0}, bvk = {0};
    PyObject *seen, *rows;
    long max_open_bits;
    int want_snaps = 1;  /* 0: skip cand_slots/cand_uops emission —
                          * delta-stream consumers (_RegsLayout /
                          * _pack_regs_single) never read the
                          * snapshots, and emitting them is ~1/3 of
                          * the scan's work on long histories */
    if (!PyArg_ParseTuple(args, "y*y*y*y*y*y*O!O!l|i",
                          &bproc, &btyp, &bfmap, &bva, &bvb, &bvk,
                          &PyDict_Type, &seen, &PyList_Type, &rows,
                          &max_open_bits, &want_snaps))
        return NULL;
    if (max_open_bits > MAX_OPEN_HARD) max_open_bits = MAX_OPEN_HARD;
    Py_ssize_t n = (Py_ssize_t)(bproc.len / 4);
    const int32_t *proc = bproc.buf;
    const uint8_t *typ = btyp.buf;
    const int32_t *fmap = bfmap.buf;
    const int32_t *va = bva.buf;
    const int32_t *vb = bvb.buf;
    const uint8_t *vk = bvk.buf;

    PyObject *result = NULL;
    PyObject *new_rows = NULL;
    vec ret_slots = {0}, cand_counts = {0}, cand_slots = {0},
        cand_uops = {0}, cut_flags = {0}, ret_pos = {0};
    vec d_counts = {0}, d_slots = {0}, d_uops = {0};
    Py_ssize_t *fate = NULL;
    utab ut = {0};
    if ((Py_ssize_t)(btyp.len) != n || (Py_ssize_t)(bfmap.len / 4) != n
        || (Py_ssize_t)(bva.len / 4) != n
        || (Py_ssize_t)(bvb.len / 4) != n
        || (Py_ssize_t)(bvk.len) != n) {
        PyErr_SetString(PyExc_ValueError, "column length mismatch");
        goto done;
    }
    fate = PyMem_Malloc((n ? n : 1) * sizeof(Py_ssize_t));
    if (!fate) { PyErr_NoMemory(); goto done; }

    /* pass 1: pair completions with invokes (open (proc,pos) array —
     * live entries are bounded by the concurrent-open depth, which the
     * scan caps at MAX_OPEN_HARD anyway) */
    {
        int32_t open_p[MAX_OPEN_HARD];
        Py_ssize_t open_i[MAX_OPEN_HARD];
        long n_open1 = 0;
        for (Py_ssize_t i = 0; i < n; i++) fate[i] = -1;
        for (Py_ssize_t i = 0; i < n; i++) {
            int32_t p = proc[i];
            if (p == -2) goto fallback;  /* out-of-int32 client id:
                * the object paths see the real id (history.py
                * P_OUT_OF_RANGE) — whole history out of columnar
                * scope so classifications cannot diverge */
            if (p < 0) continue;
            uint8_t t = typ[i];
            long j = -1;
            for (long k = 0; k < n_open1; k++)
                if (open_p[k] == p) { j = k; break; }
            if (t == 0) {
                if (j >= 0) goto fallback;        /* double invoke */
                if (n_open1 >= MAX_OPEN_HARD) goto fallback;
                open_p[n_open1] = p;
                open_i[n_open1] = i;
                n_open1++;
            } else if (j >= 0) {
                fate[open_i[j]] = i;
                open_p[j] = open_p[n_open1 - 1];
                open_i[j] = open_i[n_open1 - 1];
                n_open1--;
            }
        }
        if (n_open1 > 0) goto fallback;           /* crashed calls */
    }

    /* pass 2: slots + interning + returns */
    new_rows = PyList_New(0);
    if (!new_rows || utab_init(&ut, 256) < 0) goto fail_nomem;
    {
        long slot_of[MAX_OPEN_HARD], uop_of[MAX_OPEN_HARD];
        int32_t open_procs[MAX_OPEN_HARD];
        long free_slots[MAX_OPEN_HARD];
        long n_free = 0, next_slot = 0, n_open = 0;
        long max_open = 0, n_calls = 0;
        Py_ssize_t d_emitted = 0;
        Py_ssize_t base_rows = PyList_GET_SIZE(rows);
        int seen_nonempty = PyDict_GET_SIZE(seen) > 0;

        for (Py_ssize_t i = 0; i < n; i++) {
            int32_t p = proc[i];
            if (p < 0) continue;
            uint8_t t = typ[i];
            if (t == 0) {
                Py_ssize_t ci = fate[i];
                if (ci < 0 || typ[ci] == 3) goto fallback;
                if (typ[ci] == 2) continue;       /* fail pair */
                long a, b, okv;
                uint8_t k = vk[i];
                Py_ssize_t vi = i;
                if (k == 0) { k = vk[ci]; vi = ci; }  /* None: completion */
                if (k == 4) goto fallback;        /* out of int32 */
                if (k == 0 || k == 3) { a = 0; b = 0; okv = 0; }
                else {
                    a = va[vi];
                    b = (k == 2) ? vb[vi] : 0;
                    okv = 1;
                }
                long fc = fmap[i];
                if (fc < 0) goto fallback;        /* f not in spec */
                long u = intern_uop(&ut, seen, seen_nonempty,
                                    rows, new_rows, fc, a, b, okv);
                if (u < 0) goto fail;
                long s = n_free ? free_slots[--n_free] : next_slot++;
                if (n_open >= MAX_OPEN_HARD) goto fallback;
                open_procs[n_open] = p;
                slot_of[n_open] = s;
                uop_of[n_open] = u;
                n_open++;
                if (n_open > max_open) {
                    max_open = n_open;
                    if (max_open > max_open_bits) goto fallback;
                }
                n_calls++;
                /* delta stream: this call registers before the NEXT
                 * return's closure (invoke order = stream order) */
                if (vec_push(&d_slots, (int32_t)s) < 0 ||
                    vec_push(&d_uops, (int32_t)u) < 0)
                    goto fail_nomem;
            } else if (t == 1) {
                long idx = -1;
                for (long j = 0; j < n_open; j++)
                    if (open_procs[j] == p) { idx = j; break; }
                if (idx < 0) continue;
                if (vec_push(&d_counts,
                             (int32_t)(d_slots.len - d_emitted)) < 0)
                    goto fail_nomem;
                d_emitted = d_slots.len;
                if (vec_push(&ret_slots, (int32_t)slot_of[idx]) < 0 ||
                    vec_push(&cand_counts, (int32_t)n_open) < 0 ||
                    vec_push(&ret_pos, (int32_t)i) < 0)
                    goto fail_nomem;
                if (want_snaps)
                    for (long j = 0; j < n_open; j++) {
                        if (vec_push(&cand_slots,
                                     (int32_t)slot_of[j]) < 0 ||
                            vec_push(&cand_uops,
                                     (int32_t)uop_of[j]) < 0)
                            goto fail_nomem;
                    }
                free_slots[n_free++] = slot_of[idx];
                for (long j = idx; j < n_open - 1; j++) {
                    open_procs[j] = open_procs[j + 1];
                    slot_of[j] = slot_of[j + 1];
                    uop_of[j] = uop_of[j + 1];
                }
                n_open--;
                if (vec_push(&cut_flags, n_open == 0 ? 1 : 0) < 0)
                    goto fail_nomem;
            }
        }

        /* success: publish the staged interning */
        if (publish_interning(seen, rows, new_rows, base_rows) < 0)
            goto fail;
        result = Py_BuildValue(
            "(lly#y#y#y#y#y#y#y#y#)", n_calls, max_open,
            (char *)ret_slots.data, ret_slots.len * sizeof(int32_t),
            (char *)cand_counts.data, cand_counts.len * sizeof(int32_t),
            (char *)cand_slots.data, cand_slots.len * sizeof(int32_t),
            (char *)cand_uops.data, cand_uops.len * sizeof(int32_t),
            (char *)cut_flags.data, cut_flags.len * sizeof(int32_t),
            (char *)d_counts.data, d_counts.len * sizeof(int32_t),
            (char *)d_slots.data, d_slots.len * sizeof(int32_t),
            (char *)d_uops.data, d_uops.len * sizeof(int32_t),
            (char *)ret_pos.data, ret_pos.len * sizeof(int32_t));
    }
    goto done;

fallback:
    result = Py_None;
    Py_INCREF(Py_None);
    goto done;

fail_nomem:
    PyErr_NoMemory();
fail:
done:
    Py_XDECREF(new_rows);
    PyMem_Free(fate);
    PyMem_Free(ut.e);
    PyMem_Free(ret_slots.data);
    PyMem_Free(cand_counts.data);
    PyMem_Free(cand_slots.data);
    PyMem_Free(cand_uops.data);
    PyMem_Free(cut_flags.data);
    PyMem_Free(d_counts.data);
    PyMem_Free(d_slots.data);
    PyMem_Free(d_uops.data);
    PyMem_Free(ret_pos.data);
    if (bproc.obj) PyBuffer_Release(&bproc);
    if (btyp.obj) PyBuffer_Release(&btyp);
    if (bfmap.obj) PyBuffer_Release(&bfmap);
    if (bva.obj) PyBuffer_Release(&bva);
    if (bvb.obj) PyBuffer_Release(&bvb);
    if (bvk.obj) PyBuffer_Release(&bvk);
    return result;
}

/* ---------------------------------------------------------------- */
/* Stream scan: the columnar scan fused with quiescent-cut
 * segmentation (wgl_seg._segment_ends' greedy policy) and I=1
 * register-delta row-stream emission — ONE pass from packed columns
 * to the exact wire layout wgl_seg._regs_fill_compact ships, so the
 * pipeline's per-history host cost is the scan alone (the separate
 * numpy segment/layout/fill stages measured ~11 ms per 100k-op
 * history on the 1-core bench host, BENCH_r05 decomposition).
 *
 * Row model (wgl_seg._RegsLayout with I = 1): each return emits the
 * calls invoked since the previous return, one row per invoke, in
 * invoke order; the LAST of them rides the return's own row, earlier
 * ones are spill rows (ret = -1); a return with no new invokes is a
 * lone row (islot = -1).
 *
 * fast_scan_streams(proc, typ, fmap, va, vb, vk, seen, rows,
 *                   max_open_bits, target)
 * returns None when out of scope (same conditions as fast_scan_cols),
 * else (n_calls, max_open, n_rets, lp_min,
 *       ret_s i32[rtot], islot_s i32[rtot], iuop_s i32[rtot],
 *       cum i32[K+1], seg_ends i32[K], positions i32[n_rets])       */

static PyObject *fast_scan_streams(PyObject *self, PyObject *args) {
    Py_buffer bproc = {0}, btyp = {0}, bfmap = {0}, bva = {0},
              bvb = {0}, bvk = {0};
    PyObject *seen, *rows;
    long max_open_bits, target;
    if (!PyArg_ParseTuple(args, "y*y*y*y*y*y*O!O!ll",
                          &bproc, &btyp, &bfmap, &bva, &bvb, &bvk,
                          &PyDict_Type, &seen, &PyList_Type, &rows,
                          &max_open_bits, &target))
        return NULL;
    if (max_open_bits > MAX_OPEN_HARD) max_open_bits = MAX_OPEN_HARD;
    if (target < 1) target = 1;
    Py_ssize_t n = (Py_ssize_t)(bproc.len / 4);
    const int32_t *proc = bproc.buf;
    const uint8_t *typ = btyp.buf;
    const int32_t *fmap = bfmap.buf;
    const int32_t *va = bva.buf;
    const int32_t *vb = bvb.buf;
    const uint8_t *vk = bvk.buf;

    PyObject *result = NULL;
    PyObject *new_rows = NULL;
    vec ret_s = {0}, islot_s = {0}, iuop_s = {0}, cum = {0},
        seg_ends = {0}, ret_pos = {0};
    Py_ssize_t *fate = NULL;
    utab ut = {0};
    if ((Py_ssize_t)(btyp.len) != n || (Py_ssize_t)(bfmap.len / 4) != n
        || (Py_ssize_t)(bva.len / 4) != n
        || (Py_ssize_t)(bvb.len / 4) != n
        || (Py_ssize_t)(bvk.len) != n) {
        PyErr_SetString(PyExc_ValueError, "column length mismatch");
        goto done;
    }
    fate = PyMem_Malloc((n ? n : 1) * sizeof(Py_ssize_t));
    if (!fate) { PyErr_NoMemory(); goto done; }

    /* pass 1: pair completions with invokes */
    {
        int32_t open_p[MAX_OPEN_HARD];
        Py_ssize_t open_i[MAX_OPEN_HARD];
        long n_open1 = 0;
        for (Py_ssize_t i = 0; i < n; i++) fate[i] = -1;
        for (Py_ssize_t i = 0; i < n; i++) {
            int32_t p = proc[i];
            if (p == -2) goto fallback;  /* out-of-int32 client id:
                * the object paths see the real id (history.py
                * P_OUT_OF_RANGE) — whole history out of columnar
                * scope so classifications cannot diverge */
            if (p < 0) continue;
            uint8_t t = typ[i];
            long j = -1;
            for (long k = 0; k < n_open1; k++)
                if (open_p[k] == p) { j = k; break; }
            if (t == 0) {
                if (j >= 0) goto fallback;        /* double invoke */
                if (n_open1 >= MAX_OPEN_HARD) goto fallback;
                open_p[n_open1] = p;
                open_i[n_open1] = i;
                n_open1++;
            } else if (j >= 0) {
                fate[open_i[j]] = i;
                open_p[j] = open_p[n_open1 - 1];
                open_i[j] = open_i[n_open1 - 1];
                n_open1--;
            }
        }
        if (n_open1 > 0) goto fallback;           /* crashed calls */
    }

    /* pass 2: slots + interning + row-stream emission */
    new_rows = PyList_New(0);
    if (!new_rows || utab_init(&ut, 256) < 0) goto fail_nomem;
    {
        long slot_of[MAX_OPEN_HARD], uop_of[MAX_OPEN_HARD];
        int32_t open_procs[MAX_OPEN_HARD];
        long free_slots[MAX_OPEN_HARD];
        long pend_slot[MAX_OPEN_HARD], pend_uop[MAX_OPEN_HARD];
        long n_pend = 0;
        long n_free = 0, next_slot = 0, n_open = 0;
        long max_open = 0, n_calls = 0, n_rets = 0;
        long nret_seg = 0, seg_row0 = 0, lp_min = 0;
        Py_ssize_t base_rows = PyList_GET_SIZE(rows);
        int seen_nonempty = PyDict_GET_SIZE(seen) > 0;
        if (vec_push(&cum, 0) < 0) goto fail_nomem;

        for (Py_ssize_t i = 0; i < n; i++) {
            int32_t p = proc[i];
            if (p < 0) continue;
            uint8_t t = typ[i];
            if (t == 0) {
                Py_ssize_t ci = fate[i];
                if (ci < 0 || typ[ci] == 3) goto fallback;
                if (typ[ci] == 2) continue;       /* fail pair */
                long a, b, okv;
                uint8_t k = vk[i];
                Py_ssize_t vi = i;
                if (k == 0) { k = vk[ci]; vi = ci; }
                if (k == 4) goto fallback;        /* out of int32 */
                if (k == 0 || k == 3) { a = 0; b = 0; okv = 0; }
                else {
                    a = va[vi];
                    b = (k == 2) ? vb[vi] : 0;
                    okv = 1;
                }
                long fc = fmap[i];
                if (fc < 0) goto fallback;        /* f not in spec */
                long u = intern_uop(&ut, seen, seen_nonempty,
                                    rows, new_rows, fc, a, b, okv);
                if (u < 0) goto fail;
                long s = n_free ? free_slots[--n_free] : next_slot++;
                if (n_open >= MAX_OPEN_HARD) goto fallback;
                open_procs[n_open] = p;
                slot_of[n_open] = s;
                uop_of[n_open] = u;
                n_open++;
                if (n_open > max_open) {
                    max_open = n_open;
                    if (max_open > max_open_bits) goto fallback;
                }
                n_calls++;
                /* n_pend < n_open <= MAX_OPEN_HARD always holds: the
                 * pending calls are all still open at the next return */
                pend_slot[n_pend] = s;
                pend_uop[n_pend] = u;
                n_pend++;
            } else if (t == 1) {
                long idx = -1;
                for (long j = 0; j < n_open; j++)
                    if (open_procs[j] == p) { idx = j; break; }
                if (idx < 0) continue;
                /* spill rows: all but the last pending invoke */
                for (long j = 0; j + 1 < n_pend; j++) {
                    if (vec_push(&ret_s, -1) < 0 ||
                        vec_push(&islot_s, (int32_t)pend_slot[j]) < 0 ||
                        vec_push(&iuop_s, (int32_t)pend_uop[j]) < 0)
                        goto fail_nomem;
                }
                /* the return row carries the last pending invoke */
                if (vec_push(&ret_s, (int32_t)slot_of[idx]) < 0 ||
                    vec_push(&islot_s, n_pend
                             ? (int32_t)pend_slot[n_pend - 1]
                             : (int32_t)-1) < 0 ||
                    vec_push(&iuop_s, n_pend
                             ? (int32_t)pend_uop[n_pend - 1]
                             : (int32_t)0) < 0 ||
                    vec_push(&ret_pos, (int32_t)i) < 0)
                    goto fail_nomem;
                n_pend = 0;
                n_rets++;
                nret_seg++;
                free_slots[n_free++] = slot_of[idx];
                for (long j = idx; j < n_open - 1; j++) {
                    open_procs[j] = open_procs[j + 1];
                    slot_of[j] = slot_of[j + 1];
                    uop_of[j] = uop_of[j + 1];
                }
                n_open--;
                if (n_open == 0 && nret_seg >= target) {
                    /* close the segment at this quiescent return */
                    long seg_rows = ret_s.len - seg_row0;
                    if (seg_rows > lp_min) lp_min = seg_rows;
                    if (vec_push(&cum, (int32_t)ret_s.len) < 0 ||
                        vec_push(&seg_ends, (int32_t)n_rets) < 0)
                        goto fail_nomem;
                    seg_row0 = ret_s.len;
                    nret_seg = 0;
                }
            }
        }
        if (nret_seg > 0) {
            /* tail segment (< target returns); the history's last
             * return is always quiescent for crash-free histories */
            long seg_rows = ret_s.len - seg_row0;
            if (seg_rows > lp_min) lp_min = seg_rows;
            if (vec_push(&cum, (int32_t)ret_s.len) < 0 ||
                vec_push(&seg_ends, (int32_t)n_rets) < 0)
                goto fail_nomem;
        }

        if (publish_interning(seen, rows, new_rows, base_rows) < 0)
            goto fail;
        result = Py_BuildValue(
            "(lllly#y#y#y#y#y#)", n_calls, max_open, n_rets, lp_min,
            (char *)ret_s.data, ret_s.len * sizeof(int32_t),
            (char *)islot_s.data, islot_s.len * sizeof(int32_t),
            (char *)iuop_s.data, iuop_s.len * sizeof(int32_t),
            (char *)cum.data, cum.len * sizeof(int32_t),
            (char *)seg_ends.data, seg_ends.len * sizeof(int32_t),
            (char *)ret_pos.data, ret_pos.len * sizeof(int32_t));
    }
    goto done;

fallback:
    result = Py_None;
    Py_INCREF(Py_None);
    goto done;

fail_nomem:
    PyErr_NoMemory();
fail:
done:
    Py_XDECREF(new_rows);
    PyMem_Free(fate);
    PyMem_Free(ut.e);
    PyMem_Free(ret_s.data);
    PyMem_Free(islot_s.data);
    PyMem_Free(iuop_s.data);
    PyMem_Free(cum.data);
    PyMem_Free(seg_ends.data);
    PyMem_Free(ret_pos.data);
    if (bproc.obj) PyBuffer_Release(&bproc);
    if (btyp.obj) PyBuffer_Release(&btyp);
    if (bfmap.obj) PyBuffer_Release(&bfmap);
    if (bva.obj) PyBuffer_Release(&bva);
    if (bvb.obj) PyBuffer_Release(&bvb);
    if (bvk.obj) PyBuffer_Release(&bvk);
    return result;
}

static PyMethodDef methods[] = {
    {"fast_scan", fast_scan, METH_VARARGS,
     "Fused pairing/slotting/interning scan over one history."},
    {"fast_scan_cols", fast_scan_cols, METH_VARARGS,
     "Columnar twin of fast_scan over struct-of-arrays histories."},
    {"fast_scan_streams", fast_scan_streams, METH_VARARGS,
     "Columnar scan fused with segmentation and I=1 row-stream "
     "emission (the grouped pipeline's wire layout)."},
    {NULL, NULL, 0, NULL},
};

static struct PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT, "_histscan", NULL, -1, methods,
};

PyMODINIT_FUNC PyInit__histscan(void) {
    s_process = PyUnicode_InternFromString("process");
    s_type = PyUnicode_InternFromString("type");
    s_f = PyUnicode_InternFromString("f");
    s_value = PyUnicode_InternFromString("value");
    t_invoke = PyUnicode_InternFromString("invoke");
    t_ok = PyUnicode_InternFromString("ok");
    t_fail = PyUnicode_InternFromString("fail");
    t_info = PyUnicode_InternFromString("info");
    if (!s_process || !s_type || !s_f || !s_value || !t_invoke ||
        !t_ok || !t_fail || !t_info)
        return NULL;
    return PyModule_Create(&moduledef);
}
