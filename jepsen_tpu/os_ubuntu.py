"""Ubuntu provisioning (reference: `jepsen/src/jepsen/os/ubuntu.clj`,
registered alongside debian in the cockroach runner's OS registry,
`cockroachdb/src/jepsen/cockroach/runner.clj:36-40`): apt-based like
debian with Ubuntu's package set differences."""

from __future__ import annotations

from jepsen_tpu import os_debian
from jepsen_tpu.os import setup_hostfile  # noqa: F401


class Ubuntu(os_debian.Debian):
    """ubuntu.clj os — the debian flow over Ubuntu images (same apt
    machinery; Ubuntu ships ntpdate/faketime from universe)."""


os = Ubuntu()
