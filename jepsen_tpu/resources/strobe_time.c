/* strobe_time: flip the wall clock between its true value and
 * (true + delta ms) every period ms, for duration seconds, then restore.
 *
 * Usage: strobe_time <delta-ms> <period-ms> <duration-s>
 *
 * TPU-framework equivalent of the reference's clock strobe tool
 * (jepsen/resources/strobe-time.c); independent implementation.  The
 * schedule is anchored on CLOCK_MONOTONIC so the strobing cadence is
 * immune to the very jumps it creates: on each tick we compute which
 * phase we *should* be in from monotonic time and apply the difference
 * between the desired and currently-applied offset to CLOCK_REALTIME.
 */
#include <errno.h>
#include <stdio.h>
#include <stdlib.h>
#include <time.h>

#define NS_PER_MS 1000000LL
#define NS_PER_S  1000000000LL

static long long ts_ns(struct timespec t) {
  return (long long)t.tv_sec * NS_PER_S + t.tv_nsec;
}

static struct timespec ns_ts(long long ns) {
  struct timespec t;
  t.tv_sec = ns / NS_PER_S;
  t.tv_nsec = ns % NS_PER_S;
  if (t.tv_nsec < 0) {
    t.tv_nsec += NS_PER_S;
    t.tv_sec -= 1;
  }
  return t;
}

static int shift_wall_clock(long long delta_ns) {
  struct timespec now;
  if (clock_gettime(CLOCK_REALTIME, &now) != 0) return -1;
  struct timespec target = ns_ts(ts_ns(now) + delta_ns);
  return clock_settime(CLOCK_REALTIME, &target);
}

int main(int argc, char **argv) {
  if (argc != 4) {
    fprintf(stderr, "usage: %s <delta-ms> <period-ms> <duration-s>\n",
            argv[0]);
    return 2;
  }
  long long delta_ns = strtoll(argv[1], NULL, 10) * NS_PER_MS;
  long long period_ns = strtoll(argv[2], NULL, 10) * NS_PER_MS;
  long long duration_ns = strtoll(argv[3], NULL, 10) * NS_PER_S;
  if (period_ns <= 0 || duration_ns < 0) {
    fprintf(stderr, "period must be > 0, duration >= 0\n");
    return 2;
  }

  struct timespec start;
  if (clock_gettime(CLOCK_MONOTONIC, &start) != 0) {
    perror("clock_gettime");
    return 1;
  }
  long long start_ns = ts_ns(start);
  long long applied = 0; /* offset currently added to the wall clock */

  for (;;) {
    struct timespec mono;
    clock_gettime(CLOCK_MONOTONIC, &mono);
    long long elapsed = ts_ns(mono) - start_ns;
    if (elapsed >= duration_ns) break;

    long long phase = (elapsed / period_ns) % 2;
    long long desired = phase ? delta_ns : 0;
    if (desired != applied) {
      if (shift_wall_clock(desired - applied) != 0) {
        perror("clock_settime");
        return 1;
      }
      applied = desired;
    }

    /* sleep until the next phase boundary (monotonic, absolute) */
    long long next = start_ns + ((elapsed / period_ns) + 1) * period_ns;
    struct timespec until = ns_ts(next);
    while (clock_nanosleep(CLOCK_MONOTONIC, TIMER_ABSTIME, &until, NULL)
           == EINTR) {
    }
  }

  /* restore the true clock */
  if (applied != 0 && shift_wall_clock(-applied) != 0) {
    perror("clock_settime");
    return 1;
  }
  return 0;
}
