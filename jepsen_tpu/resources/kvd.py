#!/usr/bin/env python3
"""kvd — a deliberately tiny single-file TCP key-value daemon.

The integration-tier system-under-test for environments with no real
database binaries: the kvd suite uploads THIS file to the node,
launches it under start-stop-daemon, talks a line protocol over real
TCP sockets, SIGSTOPs it mid-run, and snarfs its log — exercising the
whole control plane with real side effects (the reference's equivalent
tier runs a real etcd under docker, core_test.clj:54-108).

Line protocol (one request per line, one reply line):
    GET k            -> VAL v | NIL
    SET k v          -> OK | ERR disk <errno>
    CAS k old new    -> OK | FAIL | NIL | ERR disk <errno>
Every mutation is logged to the --log file (the harness downloads it).

Fault control verbs (the campaign nemeses' in-SUT fault surface —
REAL faults at the daemon's own network/clock layer, injectable on a
shared host where iptables or `date -s` would be destructive):
    PART 1|0         -> OK      partition: while set, every data
                                request is HELD (no reply) until the
                                partition heals or the client hangs
                                up — clients see exactly what a
                                dropped link looks like; healing
                                releases held requests (late
                                delivery), like a real network
    SKEW ms          -> OK      clock skew: the daemon's wall clock
                                (its only use of time: mutation-log
                                timestamps) runs offset by ms
Control verbs are processed BEFORE the partition hold, so the nemesis
can always heal what it broke.

With --data-dir the daemon is DURABLE: every mutation is appended to
<data-dir>/kvd.data with write+fsync BEFORE it is applied in memory,
and the file is replayed at startup.  That data dir is the surface the
faultfs disk-fault layer mounts over: an injected EIO surfaces to the
client as `ERR disk`, with the mutation provably not applied.
"""

import argparse
import os
import socket
import socketserver
import sys
import threading
import time


class Store:
    def __init__(self, log_path, unsafe_cas=False, data_dir=None):
        self.kv = {}
        self.lock = threading.Lock()
        self.unsafe_cas = unsafe_cas
        self.partitioned = False     # PART: hold data requests
        self.skew_ms = 0.0           # SKEW: logical wall-clock offset
        self.log = open(log_path, "a", buffering=1)
        self.data_path = None
        self.data = None
        if data_dir:
            os.makedirs(data_dir, exist_ok=True)
            self.data_path = os.path.join(data_dir, "kvd.data")
            try:
                with open(self.data_path, "rb") as f:
                    for ln in f:
                        parts = ln.decode("utf-8", "replace").split()
                        if len(parts) == 2:
                            self.kv[parts[0]] = parts[1]
            except OSError:
                pass

    def persist(self, k, v):
        """Durably append k v (unbuffered write + fsync) BEFORE the
        in-memory apply; OSError propagates so the handler replies
        `ERR disk` with the mutation NOT applied.  The handle is
        dropped after a failure so no half-buffered line survives to
        leak into a later append.  (A torn append that does reach the
        disk may be replayed at next startup — within a run there is no
        restart, so histories stay honest.)"""
        if self.data_path is None:
            return
        try:
            if self.data is None:
                self.data = open(self.data_path, "ab", buffering=0)
            self.data.write(("%s %s\n" % (k, v)).encode())
            os.fsync(self.data.fileno())
        except OSError:
            try:
                if self.data is not None:
                    self.data.close()
            except OSError:
                pass
            self.data = None
            raise

    def logline(self, msg):
        # the daemon's ONLY clock use — SKEW shifts it, so a clock
        # nemesis has a real, observable (and harmless) effect
        # lint: wall-ok(the SUT's own skewed wall clock is the thing under test)
        self.log.write("%.6f %s\n" % (time.time() + self.skew_ms / 1e3,
                                      msg))


class Handler(socketserver.StreamRequestHandler):
    def handle(self):
        store = self.server.store
        for raw in self.rfile:
            parts = raw.decode("utf-8", "replace").split()
            if not parts:
                continue
            cmd, args = parts[0].upper(), parts[1:]
            # control verbs first: the nemesis must be able to heal a
            # partition even while data requests are being held
            if cmd == "PART" and len(args) == 1:
                store.partitioned = args[0] not in ("0", "off")
                store.logline(f"PART {int(store.partitioned)}")
                self.wfile.write(b"OK\n")
                continue
            if cmd == "SKEW" and len(args) == 1:
                try:
                    store.skew_ms = float(args[0])
                    out = "OK"
                except ValueError:
                    out = "ERR"
                self.wfile.write((out + "\n").encode())
                continue
            # partition hold: no reply until healed or the client
            # hangs up — a healed partition delivers late, like a
            # real network (the client may have abandoned by then)
            while store.partitioned:
                time.sleep(0.02)
            if cmd == "GET" and len(args) == 1:
                v = store.kv.get(args[0])
                out = "NIL" if v is None else f"VAL {v}"
            elif cmd == "SET" and len(args) == 2:
                with store.lock:
                    try:
                        store.persist(args[0], args[1])
                    except OSError as e:
                        out = "ERR disk %s" % (e.errno or "")
                    else:
                        store.kv[args[0]] = args[1]
                        out = "OK"
                if out == "OK":
                    store.logline(f"SET {args[0]}={args[1]}")
            elif cmd == "CAS" and len(args) == 3:
                if store.unsafe_cas:
                    # deliberately racy check-then-set (no lock, widened
                    # window): the harness's negative test proves the
                    # checker catches THIS real bug over real TCP
                    cur = store.kv.get(args[0])
                    time.sleep(0.002)
                    ok = cur is not None and cur == args[1]
                    if ok:
                        try:
                            store.persist(args[0], args[2])
                        except OSError:
                            ok = None       # disk refused; not applied
                        else:
                            store.kv[args[0]] = args[2]
                    out = ("ERR disk" if ok is None else "OK" if ok
                           else "NIL" if cur is None else "FAIL")
                else:
                    with store.lock:
                        cur = store.kv.get(args[0])
                        ok = cur is not None and cur == args[1]
                        if ok:
                            try:
                                store.persist(args[0], args[2])
                            except OSError:
                                ok = None   # disk refused; not applied
                            else:
                                store.kv[args[0]] = args[2]
                    out = ("ERR disk" if ok is None else "OK" if ok
                           else "NIL" if cur is None else "FAIL")
                if ok:
                    store.logline(
                        f"CAS {args[0]}:{args[1]}->{args[2]}")
            elif cmd == "PING":
                out = "PONG"
            else:
                out = "ERR"
            self.wfile.write((out + "\n").encode())


class Server(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--port", type=int, default=17711)
    ap.add_argument("--log", default="/tmp/kvd.log")
    ap.add_argument("--unsafe-cas", action="store_true")
    ap.add_argument("--data-dir", default=None,
                    help="persist mutations here (write+fsync each), "
                         "replayed at startup; the faultfs mount point")
    a = ap.parse_args()
    srv = Server(("0.0.0.0", a.port), Handler)
    srv.store = Store(a.log, unsafe_cas=a.unsafe_cas,
                      data_dir=a.data_dir)
    srv.store.logline(f"kvd listening on {a.port}")
    try:
        srv.serve_forever()
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
