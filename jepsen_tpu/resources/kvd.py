#!/usr/bin/env python3
"""kvd — a deliberately tiny single-file TCP key-value daemon.

The integration-tier system-under-test for environments with no real
database binaries: the kvd suite uploads THIS file to the node,
launches it under start-stop-daemon, talks a line protocol over real
TCP sockets, SIGSTOPs it mid-run, and snarfs its log — exercising the
whole control plane with real side effects (the reference's equivalent
tier runs a real etcd under docker, core_test.clj:54-108).

Line protocol (one request per line, one reply line):
    GET k            -> VAL v | NIL
    SET k v          -> OK
    CAS k old new    -> OK | FAIL | NIL
Every mutation is logged to the --log file (the harness downloads it).
"""

import argparse
import socket
import socketserver
import sys
import threading
import time


class Store:
    def __init__(self, log_path, unsafe_cas=False):
        self.kv = {}
        self.lock = threading.Lock()
        self.unsafe_cas = unsafe_cas
        self.log = open(log_path, "a", buffering=1)

    def logline(self, msg):
        self.log.write("%.6f %s\n" % (time.time(), msg))


class Handler(socketserver.StreamRequestHandler):
    def handle(self):
        store = self.server.store
        for raw in self.rfile:
            parts = raw.decode("utf-8", "replace").split()
            if not parts:
                continue
            cmd, args = parts[0].upper(), parts[1:]
            if cmd == "GET" and len(args) == 1:
                v = store.kv.get(args[0])
                out = "NIL" if v is None else f"VAL {v}"
            elif cmd == "SET" and len(args) == 2:
                with store.lock:
                    store.kv[args[0]] = args[1]
                store.logline(f"SET {args[0]}={args[1]}")
                out = "OK"
            elif cmd == "CAS" and len(args) == 3:
                if store.unsafe_cas:
                    # deliberately racy check-then-set (no lock, widened
                    # window): the harness's negative test proves the
                    # checker catches THIS real bug over real TCP
                    cur = store.kv.get(args[0])
                    time.sleep(0.002)
                    ok = cur is not None and cur == args[1]
                    if ok:
                        store.kv[args[0]] = args[2]
                    out = ("OK" if ok
                           else "NIL" if cur is None else "FAIL")
                else:
                    with store.lock:
                        cur = store.kv.get(args[0])
                        ok = cur is not None and cur == args[1]
                        if ok:
                            store.kv[args[0]] = args[2]
                    out = ("OK" if ok
                           else "NIL" if cur is None else "FAIL")
                if ok:
                    store.logline(
                        f"CAS {args[0]}:{args[1]}->{args[2]}")
            elif cmd == "PING":
                out = "PONG"
            else:
                out = "ERR"
            self.wfile.write((out + "\n").encode())


class Server(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--port", type=int, default=17711)
    ap.add_argument("--log", default="/tmp/kvd.log")
    ap.add_argument("--unsafe-cas", action="store_true")
    a = ap.parse_args()
    srv = Server(("0.0.0.0", a.port), Handler)
    srv.store = Store(a.log, unsafe_cas=a.unsafe_cas)
    srv.store.logline(f"kvd listening on {a.port}")
    try:
        srv.serve_forever()
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
