// fault_inject: disk-fault injection for DYNAMICALLY-LINKED processes
// under test.
//
// SCOPE — read this before trusting a green run: this is an LD_PRELOAD
// interposer.  It fires only when the faulted process resolves libc's
// open/read/write/fsync through the dynamic linker.  It does NOT fire
// for
//   * statically-linked binaries (musl-static, Go's default linkage —
//     etcd, consul, cockroach, dgraph, tidb...): there is no dynamic
//     linker in the process, so LD_PRELOAD is inert;
//   * raw syscalls that bypass libc (syscall(2), io_uring, direct
//     SYSCALL instructions from a runtime's own wrappers);
//   * mmap'd I/O (faults are injected per libc call, not per page).
// For those SUTs use resources/faultfs_fuse.cpp: a FUSE passthrough
// filesystem mounted OVER the data dir, where the kernel routes every
// file op of every process through the fault layer — the mechanism of
// the reference's CharybdeFS (charybdefs/src/jepsen/charybdefs.clj)
// and the crash-consistency literature (ALICE OSDI '14, CrashMonkey
// OSDI '18).  faultfs.py prefers the FUSE backend and falls back to
// this interposer — with a logged warning — only where FUSE is
// unavailable; both speak the same TCP control protocol.
//
// What this interposer IS for: glibc-linked SUTs on hosts where FUSE
// mounts are impossible (no /dev/fuse, no CAP_SYS_ADMIN) — it needs no
// kernel support at all and injects at the libc boundary.
//
// Usage:
//   FAULTFS_PATH=/var/lib/db FAULTFS_PORT=7678 \
//     LD_PRELOAD=/opt/jepsen/libfaultinject.so db-server ...
//
// Control protocol (line-oriented over TCP, one command per line):
//   set <errno> <prob_per_100k> <delay_us> <ops-csv>   e.g.
//       set 5 100000 0 read,write,fsync     (all reads/writes/fsyncs EIO)
//       set 5 1000 500000 read,write        (1% EIO + 500ms delay)
//   clear                                   (stop injecting)
//   get                                     (report current config)
//
// Interposed symbols cover both the 32-bit and LFS ABIs
// (open/open64/openat/openat64/creat/creat64, read/pread/pread64,
// write/pwrite/pwrite64, fsync/fdatasync): binaries built with
// -D_FILE_OFFSET_BITS=64 — virtually every Linux DB — resolve to the
// *64 names.  dirfd-relative openat paths are resolved through
// /proc/self/fd so directory-anchored opens are tracked too.

#include <arpa/inet.h>
#include <dlfcn.h>
#include <errno.h>
#include <fcntl.h>
#include <netinet/in.h>
#include <pthread.h>
#include <stdarg.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/socket.h>
#include <sys/stat.h>
#include <sys/types.h>
#include <time.h>
#include <unistd.h>

#include <atomic>

namespace {

// Fault classes, bitmask.
enum OpClass : unsigned {
  OP_READ = 1u << 0,
  OP_WRITE = 1u << 1,
  OP_FSYNC = 1u << 2,
  OP_OPEN = 1u << 3,
};

std::atomic<int> g_errno{0};
std::atomic<unsigned> g_prob{0};      // per 100,000 calls
std::atomic<unsigned> g_delay_us{0};
std::atomic<unsigned> g_ops{0};
std::atomic<unsigned long> g_seed{88172645463325252ull};

// fd -> is the fd under the faulted subtree?  Fixed-size table; fds
// above the cap are never faulted (servers keep few data-dir fds).
constexpr int kMaxFd = 4096;
std::atomic<bool> g_tracked[kMaxFd];

char g_prefix[4096];
size_t g_prefix_len = 0;

typedef int (*open_fn)(const char *, int, ...);
typedef int (*openat_fn)(int, const char *, int, ...);
typedef int (*creat_fn)(const char *, mode_t);
typedef ssize_t (*read_fn)(int, void *, size_t);
typedef ssize_t (*write_fn)(int, const void *, size_t);
typedef ssize_t (*pread_fn)(int, void *, size_t, off_t);
typedef ssize_t (*pwrite_fn)(int, const void *, size_t, off_t);
typedef ssize_t (*pread64_fn)(int, void *, size_t, off64_t);
typedef ssize_t (*pwrite64_fn)(int, const void *, size_t, off64_t);
typedef int (*fsync_fn)(int);
typedef int (*close_fn)(int);

// Lazy resolution: other preloaded/linked libraries' ELF constructors
// can call into these wrappers before our own constructor has run, so
// every wrapper resolves its real symbol on first use.
#define RESOLVE(slot, type, name)                        \
  do {                                                   \
    if (!(slot)) (slot) = (type)dlsym(RTLD_NEXT, name);  \
  } while (0)

open_fn real_open = nullptr;
open_fn real_open64 = nullptr;
openat_fn real_openat = nullptr;
openat_fn real_openat64 = nullptr;
creat_fn real_creat = nullptr;
creat_fn real_creat64 = nullptr;
read_fn real_read = nullptr;
write_fn real_write = nullptr;
pread_fn real_pread = nullptr;
pwrite_fn real_pwrite = nullptr;
pread64_fn real_pread64 = nullptr;
pwrite64_fn real_pwrite64 = nullptr;
fsync_fn real_fsync = nullptr;
fsync_fn real_fdatasync = nullptr;
close_fn real_close = nullptr;

unsigned long xorshift() {
  // xorshift64star; racy updates are fine for fault dice.
  unsigned long x = g_seed.load(std::memory_order_relaxed);
  x ^= x >> 12;
  x ^= x << 25;
  x ^= x >> 27;
  g_seed.store(x, std::memory_order_relaxed);
  return x * 2685821657736338717ull;
}

bool should_fault(unsigned op) {
  if (!(g_ops.load(std::memory_order_relaxed) & op)) return false;
  unsigned prob = g_prob.load(std::memory_order_relaxed);
  if (prob == 0) return false;
  unsigned delay = g_delay_us.load(std::memory_order_relaxed);
  bool hit = (xorshift() % 100000) < prob;
  if (hit && delay) {
    struct timespec ts;
    ts.tv_sec = delay / 1000000;
    ts.tv_nsec = (delay % 1000000) * 1000L;
    nanosleep(&ts, nullptr);
  }
  return hit;
}

bool tracked(int fd) {
  return fd >= 0 && fd < kMaxFd &&
         g_tracked[fd].load(std::memory_order_relaxed);
}

// After should_fault() hit (and slept): errno 0 means latency-only —
// the op proceeds; nonzero means fail it with that errno.
bool fail_with_errno() {
  int e = g_errno.load(std::memory_order_relaxed);
  if (e) errno = e;
  return e != 0;
}

// Component-boundary prefix match: /var/lib/db matches /var/lib/db and
// /var/lib/db/x but NOT /var/lib/db-backup/x.
bool prefix_match(const char *abs_path) {
  if (g_prefix_len == 0) return false;
  if (strncmp(abs_path, g_prefix, g_prefix_len) != 0) return false;
  char next = abs_path[g_prefix_len];
  return next == '\0' || next == '/';
}

// Resolve `path` (absolute, cwd-relative, or dirfd-relative) into
// `out`; returns false when it can't be resolved or doesn't fit.
bool resolve_path(int dirfd, const char *path, char *out, size_t cap) {
  if (path[0] == '/') {
    if (strlen(path) + 1 > cap) return false;
    strcpy(out, path);
    return true;
  }
  char base[4096];
  if (dirfd == AT_FDCWD) {
    if (!getcwd(base, sizeof base)) return false;
  } else {
    char link[64];
    snprintf(link, sizeof link, "/proc/self/fd/%d", dirfd);
    ssize_t n = readlink(link, base, sizeof(base) - 1);
    if (n <= 0) return false;
    base[n] = '\0';
  }
  size_t blen = strlen(base), plen = strlen(path);
  if (blen + 1 + plen + 1 > cap) return false;
  memcpy(out, base, blen);
  out[blen] = '/';
  memcpy(out + blen + 1, path, plen + 1);
  return true;
}

bool path_in_prefix(int dirfd, const char *path) {
  if (g_prefix_len == 0) return false;
  char full[8192];
  if (!resolve_path(dirfd, path, full, sizeof full)) return false;
  return prefix_match(full);
}

void track(int fd, int dirfd, const char *path) {
  if (fd < 0 || fd >= kMaxFd || g_prefix_len == 0) return;
  g_tracked[fd].store(path_in_prefix(dirfd, path),
                      std::memory_order_relaxed);
}

// ---------------------------------------------------------------- control

unsigned parse_ops(const char *csv) {
  unsigned ops = 0;
  if (strstr(csv, "read")) ops |= OP_READ;
  if (strstr(csv, "write")) ops |= OP_WRITE;
  if (strstr(csv, "fsync")) ops |= OP_FSYNC;
  if (strstr(csv, "open")) ops |= OP_OPEN;
  return ops;
}

void handle_line(char *line, int conn) {
  char buf[256];
  int e, n = 0;
  unsigned prob, delay;
  char opscsv[128];
  if (sscanf(line, "set %d %u %u %127s%n", &e, &prob, &delay, opscsv,
             &n) == 4) {
    g_errno.store(e);
    g_prob.store(prob > 100000 ? 100000 : prob);
    g_delay_us.store(delay);
    g_ops.store(parse_ops(opscsv));
    dprintf(conn, "ok\n");
  } else if (strncmp(line, "clear", 5) == 0) {
    g_prob.store(0);
    g_ops.store(0);
    g_errno.store(0);
    g_delay_us.store(0);
    dprintf(conn, "ok\n");
  } else if (strncmp(line, "get", 3) == 0) {
    snprintf(buf, sizeof buf, "errno=%d prob=%u delay_us=%u ops=%u\n",
             g_errno.load(), g_prob.load(), g_delay_us.load(),
             g_ops.load());
    dprintf(conn, "%s", buf);
  } else {
    dprintf(conn, "err unknown command\n");
  }
}

void *control_loop(void *) {
  const char *port_s = getenv("FAULTFS_PORT");
  int port = port_s ? atoi(port_s) : 7678;
  if (port <= 0) return nullptr;
  RESOLVE(real_close, close_fn, "close");

  int srv = socket(AF_INET, SOCK_STREAM, 0);
  if (srv < 0) return nullptr;
  int one = 1;
  setsockopt(srv, SOL_SOCKET, SO_REUSEADDR, &one, sizeof one);
  struct sockaddr_in addr;
  memset(&addr, 0, sizeof addr);
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = htons((uint16_t)port);
  if (bind(srv, (struct sockaddr *)&addr, sizeof addr) != 0 ||
      listen(srv, 4) != 0) {
    real_close(srv);
    return nullptr;
  }
  for (;;) {
    int conn = accept(srv, nullptr, nullptr);
    if (conn < 0) continue;
    char line[512];
    size_t off = 0;
    for (;;) {
      ssize_t r = recv(conn, line + off, sizeof(line) - 1 - off, 0);
      if (r <= 0) break;
      off += (size_t)r;
      line[off] = 0;
      char *nl;
      char *start = line;
      while ((nl = strchr(start, '\n')) != nullptr) {
        *nl = 0;
        handle_line(start, conn);
        start = nl + 1;
      }
      off = strlen(start);
      memmove(line, start, off + 1);
    }
    real_close(conn);
  }
  return nullptr;
}

__attribute__((constructor)) void init() {
  const char *prefix = getenv("FAULTFS_PATH");
  if (prefix) {
    strncpy(g_prefix, prefix, sizeof(g_prefix) - 1);
    g_prefix_len = strlen(g_prefix);
    // Strip trailing slashes so boundary matching works.
    while (g_prefix_len > 1 && g_prefix[g_prefix_len - 1] == '/')
      g_prefix[--g_prefix_len] = '\0';
  }
  pthread_t tid;
  pthread_create(&tid, nullptr, control_loop, nullptr);
  pthread_detach(tid);
}

mode_t va_mode(int flags, va_list ap) {
  return (flags & O_CREAT) ? va_arg(ap, mode_t) : 0;
}

int do_open(open_fn &slot, const char *name, const char *path, int flags,
            mode_t mode) {
  RESOLVE(slot, open_fn, name);
  if (path_in_prefix(AT_FDCWD, path) && should_fault(OP_OPEN) &&
      fail_with_errno())
    return -1;
  int fd = slot(path, flags, mode);
  if (fd >= 0) track(fd, AT_FDCWD, path);
  return fd;
}

int do_openat(openat_fn &slot, const char *name, int dirfd,
              const char *path, int flags, mode_t mode) {
  RESOLVE(slot, openat_fn, name);
  if (path_in_prefix(dirfd, path) && should_fault(OP_OPEN) &&
      fail_with_errno())
    return -1;
  int fd = slot(dirfd, path, flags, mode);
  if (fd >= 0) track(fd, dirfd, path);
  return fd;
}

}  // namespace

extern "C" {

int open(const char *path, int flags, ...) {
  va_list ap;
  va_start(ap, flags);
  mode_t mode = va_mode(flags, ap);
  va_end(ap);
  return do_open(real_open, "open", path, flags, mode);
}

int open64(const char *path, int flags, ...) {
  va_list ap;
  va_start(ap, flags);
  mode_t mode = va_mode(flags, ap);
  va_end(ap);
  return do_open(real_open64, "open64", path, flags, mode);
}

int openat(int dirfd, const char *path, int flags, ...) {
  va_list ap;
  va_start(ap, flags);
  mode_t mode = va_mode(flags, ap);
  va_end(ap);
  return do_openat(real_openat, "openat", dirfd, path, flags, mode);
}

int openat64(int dirfd, const char *path, int flags, ...) {
  va_list ap;
  va_start(ap, flags);
  mode_t mode = va_mode(flags, ap);
  va_end(ap);
  return do_openat(real_openat64, "openat64", dirfd, path, flags, mode);
}

int creat(const char *path, mode_t mode) {
  RESOLVE(real_creat, creat_fn, "creat");
  if (path_in_prefix(AT_FDCWD, path) && should_fault(OP_OPEN) &&
      fail_with_errno())
    return -1;
  int fd = real_creat(path, mode);
  if (fd >= 0) track(fd, AT_FDCWD, path);
  return fd;
}

int creat64(const char *path, mode_t mode) {
  RESOLVE(real_creat64, creat_fn, "creat64");
  if (path_in_prefix(AT_FDCWD, path) && should_fault(OP_OPEN) &&
      fail_with_errno())
    return -1;
  int fd = real_creat64(path, mode);
  if (fd >= 0) track(fd, AT_FDCWD, path);
  return fd;
}

ssize_t read(int fd, void *buf, size_t n) {
  RESOLVE(real_read, read_fn, "read");
  if (tracked(fd) && should_fault(OP_READ) &&
      fail_with_errno())
    return -1;
  return real_read(fd, buf, n);
}

ssize_t pread(int fd, void *buf, size_t n, off_t off) {
  RESOLVE(real_pread, pread_fn, "pread");
  if (tracked(fd) && should_fault(OP_READ) &&
      fail_with_errno())
    return -1;
  return real_pread(fd, buf, n, off);
}

ssize_t pread64(int fd, void *buf, size_t n, off64_t off) {
  RESOLVE(real_pread64, pread64_fn, "pread64");
  if (tracked(fd) && should_fault(OP_READ) &&
      fail_with_errno())
    return -1;
  return real_pread64(fd, buf, n, off);
}

ssize_t write(int fd, const void *buf, size_t n) {
  RESOLVE(real_write, write_fn, "write");
  if (tracked(fd) && should_fault(OP_WRITE) &&
      fail_with_errno())
    return -1;
  return real_write(fd, buf, n);
}

ssize_t pwrite(int fd, const void *buf, size_t n, off_t off) {
  RESOLVE(real_pwrite, pwrite_fn, "pwrite");
  if (tracked(fd) && should_fault(OP_WRITE) &&
      fail_with_errno())
    return -1;
  return real_pwrite(fd, buf, n, off);
}

ssize_t pwrite64(int fd, const void *buf, size_t n, off64_t off) {
  RESOLVE(real_pwrite64, pwrite64_fn, "pwrite64");
  if (tracked(fd) && should_fault(OP_WRITE) &&
      fail_with_errno())
    return -1;
  return real_pwrite64(fd, buf, n, off);
}

int fsync(int fd) {
  RESOLVE(real_fsync, fsync_fn, "fsync");
  if (tracked(fd) && should_fault(OP_FSYNC) &&
      fail_with_errno())
    return -1;
  return real_fsync(fd);
}

int fdatasync(int fd) {
  RESOLVE(real_fdatasync, fsync_fn, "fdatasync");
  if (tracked(fd) && should_fault(OP_FSYNC) &&
      fail_with_errno())
    return -1;
  return real_fdatasync(fd);
}

int close(int fd) {
  RESOLVE(real_close, close_fn, "close");
  if (fd >= 0 && fd < kMaxFd)
    g_tracked[fd].store(false, std::memory_order_relaxed);
  return real_close(fd);
}

}  // extern "C"
