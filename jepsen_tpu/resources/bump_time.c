/* bump_time: jump the system wall clock by a signed number of
 * milliseconds, once, and print the resulting epoch time in ms.
 *
 * Usage: bump_time <delta-ms>
 *
 * TPU-framework equivalent of the reference's one-shot clock-jump tool
 * (jepsen/resources/bump-time.c); independent implementation using
 * clock_gettime/clock_settime on CLOCK_REALTIME.  Compiled on the db
 * node by jepsen_tpu/nemesis_time.py, mirroring nemesis/time.clj:14-41.
 */
#include <errno.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <time.h>

#define NS_PER_MS 1000000L
#define NS_PER_S  1000000000L

int main(int argc, char **argv) {
  if (argc != 2) {
    fprintf(stderr, "usage: %s <delta-ms>\n", argv[0]);
    return 2;
  }

  char *end = NULL;
  long long delta_ms = strtoll(argv[1], &end, 10);
  if (end == argv[1] || *end != '\0') {
    fprintf(stderr, "bad delta: %s\n", argv[1]);
    return 2;
  }

  struct timespec now;
  if (clock_gettime(CLOCK_REALTIME, &now) != 0) {
    perror("clock_gettime");
    return 1;
  }

  long long total_ns = (long long)now.tv_sec * NS_PER_S + now.tv_nsec
      + delta_ms * NS_PER_MS;
  struct timespec target;
  target.tv_sec = total_ns / NS_PER_S;
  target.tv_nsec = total_ns % NS_PER_S;
  if (target.tv_nsec < 0) {
    target.tv_nsec += NS_PER_S;
    target.tv_sec -= 1;
  }

  if (clock_settime(CLOCK_REALTIME, &target) != 0) {
    perror("clock_settime");
    return 1;
  }

  printf("%lld\n", (long long)target.tv_sec * 1000LL
         + target.tv_nsec / NS_PER_MS);
  return 0;
}
