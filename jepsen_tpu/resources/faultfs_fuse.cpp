// faultfs_fuse: universal disk-fault injection via a FUSE passthrough
// filesystem (reference: CharybdeFS, charybdefs/src/jepsen/charybdefs.clj
// — a C++ FUSE passthrough whose fault behavior is flipped over RPC).
//
// Unlike the LD_PRELOAD interposer (fault_inject.cpp), which fires only
// at the libc boundary of dynamically-linked processes, this daemon
// sits UNDER the kernel VFS: the kernel routes every file operation of
// every process — statically-linked Go binaries making raw syscalls
// included — through this process.  That is the property
// crash-consistency work (ALICE OSDI '14, CrashMonkey OSDI '18)
// shows is needed to reach real durability bugs.
//
// Implementation note: this speaks the RAW FUSE kernel protocol over
// /dev/fuse and mounts with mount(2) directly — no libfuse dependency
// at all, so it builds with nothing but g++ and libc on any node
// (the deploy images ship libfuse2 runtime but no dev headers, and no
// fusermount3).  It therefore needs root (CAP_SYS_ADMIN) to mount,
// which the test harness has on its DB nodes.
//
// Usage:
//   faultfs_fuse BACKING_DIR MOUNTPOINT [--port N]   serve (foreground)
//   faultfs_fuse --probe                             can this host mount
//                                                    FUSE? exit 0/1
//
// Control protocol (line-oriented TCP, one command per line — a strict
// superset of fault_inject.cpp's, so faultfs.py recipes work unchanged
// against either backend):
//   set <errno> <prob_per_100k> <delay_us> <ops-csv>
//       probabilistic errno faults + latency on read/write/fsync/open.
//       errno 0 = latency only (the op still succeeds after the delay).
//   torn <prob_per_100k> <first_k_bytes>
//       a faulted write persists only its first k bytes, then fails EIO
//       — the partial-write crash image fsck/recovery code must survive.
//   lostsync <prob_per_100k>
//       a faulted fsync/fdatasync is ACKed without touching the disk;
//       the fd is remembered and the sync is REPLAYED on `clear` (heal
//       = power came back before the cache died).  An fd closed while
//       a sync is pending loses that durability window for good.
//   clear
//       stop injecting and replay pending fsyncs.
//   get
//       report config: errno= prob= delay_us= ops= torn= torn_bytes=
//       lostsync= pending=
//
// Ops are served with FOPEN_DIRECT_IO so every read/write of the SUT
// reaches this layer (no page-cache bypass); mmap-heavy SUTs are out
// of scope for this mechanism (see docs/disk-faults.md).

#include <arpa/inet.h>
#include <dirent.h>
#include <errno.h>
#include <fcntl.h>
#include <netinet/in.h>
#include <pthread.h>
#include <signal.h>
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/mount.h>
#include <sys/socket.h>
#include <sys/stat.h>
#include <sys/statvfs.h>
#include <sys/types.h>
#include <sys/uio.h>
#include <time.h>
#include <unistd.h>

#include <atomic>
#include <mutex>
#include <set>
#include <string>
#include <unordered_map>
#include <vector>

// ---------------------------------------------------------------- FUSE ABI
// The stable uapi subset of <linux/fuse.h> this daemon needs, declared
// locally so the build needs no kernel/libfuse headers.

namespace {

enum {
  FUSE_LOOKUP = 1, FUSE_FORGET = 2, FUSE_GETATTR = 3, FUSE_SETATTR = 4,
  FUSE_READLINK = 5, FUSE_SYMLINK = 6, FUSE_MKNOD = 8, FUSE_MKDIR = 9,
  FUSE_UNLINK = 10, FUSE_RMDIR = 11, FUSE_RENAME = 12, FUSE_LINK = 13,
  FUSE_OPEN = 14, FUSE_READ = 15, FUSE_WRITE = 16, FUSE_STATFS = 17,
  FUSE_RELEASE = 18, FUSE_FSYNC = 20, FUSE_SETXATTR = 21,
  FUSE_GETXATTR = 22, FUSE_LISTXATTR = 23, FUSE_REMOVEXATTR = 24,
  FUSE_FLUSH = 25, FUSE_INIT = 26, FUSE_OPENDIR = 27, FUSE_READDIR = 28,
  FUSE_RELEASEDIR = 29, FUSE_FSYNCDIR = 30, FUSE_ACCESS = 34,
  FUSE_CREATE = 35, FUSE_INTERRUPT = 36, FUSE_DESTROY = 38,
  FUSE_BATCH_FORGET = 42, FUSE_FALLOCATE = 43, FUSE_RENAME2 = 45,
  FUSE_LSEEK = 46,
};

struct fuse_in_header {
  uint32_t len, opcode;
  uint64_t unique, nodeid;
  uint32_t uid, gid, pid, padding;
};

struct fuse_out_header {
  uint32_t len;
  int32_t error;
  uint64_t unique;
};

struct fuse_attr {
  uint64_t ino, size, blocks, atime, mtime, ctime;
  uint32_t atimensec, mtimensec, ctimensec;
  uint32_t mode, nlink, uid, gid, rdev, blksize, flags;
};

struct fuse_entry_out {
  uint64_t nodeid, generation, entry_valid, attr_valid;
  uint32_t entry_valid_nsec, attr_valid_nsec;
  struct fuse_attr attr;
};

struct fuse_attr_out {
  uint64_t attr_valid;
  uint32_t attr_valid_nsec, dummy;
  struct fuse_attr attr;
};

struct fuse_getattr_in { uint32_t getattr_flags, dummy; uint64_t fh; };
struct fuse_open_in { uint32_t flags, open_flags; };
struct fuse_create_in { uint32_t flags, mode, umask, open_flags; };
struct fuse_open_out { uint64_t fh; uint32_t open_flags, padding; };
struct fuse_release_in {
  uint64_t fh;
  uint32_t flags, release_flags;
  uint64_t lock_owner;
};
struct fuse_flush_in { uint64_t fh; uint32_t unused, padding; uint64_t lock_owner; };
struct fuse_read_in {
  uint64_t fh, offset;
  uint32_t size, read_flags;
  uint64_t lock_owner;
  uint32_t flags, padding;
};
struct fuse_write_in {
  uint64_t fh, offset;
  uint32_t size, write_flags;
  uint64_t lock_owner;
  uint32_t flags, padding;
};
struct fuse_write_out { uint32_t size, padding; };
struct fuse_fsync_in { uint64_t fh; uint32_t fsync_flags, padding; };
struct fuse_mknod_in { uint32_t mode, rdev, umask, padding; };
struct fuse_mkdir_in { uint32_t mode, umask; };
struct fuse_rename_in { uint64_t newdir; };
struct fuse_rename2_in { uint64_t newdir; uint32_t flags, padding; };
struct fuse_link_in { uint64_t oldnodeid; };
struct fuse_setattr_in {
  uint32_t valid, padding;
  uint64_t fh, size, lock_owner, atime, mtime, ctime;
  uint32_t atimensec, mtimensec, ctimensec;
  uint32_t mode, unused4, uid, gid, unused5;
};
struct fuse_init_in { uint32_t major, minor, max_readahead, flags; };
struct fuse_init_out {
  uint32_t major, minor, max_readahead, flags;
  uint16_t max_background, congestion_threshold;
  uint32_t max_write, time_gran;
  uint16_t max_pages, map_alignment;
  uint32_t flags2, max_stack_depth;
  uint32_t unused[6];
};
struct fuse_access_in { uint32_t mask, padding; };
struct fuse_forget_in { uint64_t nlookup; };
struct fuse_batch_forget_in { uint32_t count, dummy; };
struct fuse_forget_one { uint64_t nodeid, nlookup; };
struct fuse_interrupt_in { uint64_t unique; };
struct fuse_kstatfs {
  uint64_t blocks, bfree, bavail, files, ffree;
  uint32_t bsize, namelen, frsize, padding;
  uint32_t spare[6];
};
struct fuse_getxattr_in { uint32_t size, padding; };
struct fuse_lseek_in { uint64_t fh, offset; uint32_t whence, padding; };
struct fuse_lseek_out { uint64_t offset; };
struct fuse_fallocate_in {
  uint64_t fh, offset, length;
  uint32_t mode, padding;
};
struct fuse_dirent { uint64_t ino, off; uint32_t namelen, type; };

constexpr uint32_t FOPEN_DIRECT_IO = 1u << 0;
constexpr uint32_t FUSE_FSYNC_FDATASYNC = 1u << 0;
constexpr uint32_t FUSE_GETATTR_FH = 1u << 0;
constexpr uint32_t FATTR_MODE = 1u << 0, FATTR_UID = 1u << 1,
    FATTR_GID = 1u << 2, FATTR_SIZE = 1u << 3, FATTR_ATIME = 1u << 4,
    FATTR_MTIME = 1u << 5, FATTR_FH = 1u << 6, FATTR_ATIME_NOW = 1u << 7,
    FATTR_MTIME_NOW = 1u << 8, FATTR_CTIME = 1u << 10;

// ---------------------------------------------------------------- fault state

enum OpClass : unsigned {
  OP_READ = 1u << 0,
  OP_WRITE = 1u << 1,
  OP_FSYNC = 1u << 2,
  OP_OPEN = 1u << 3,
};

std::atomic<int> g_errno{0};
std::atomic<unsigned> g_prob{0};          // per 100,000 calls
std::atomic<unsigned> g_delay_us{0};
std::atomic<unsigned> g_ops{0};
std::atomic<unsigned> g_torn_prob{0};     // per 100,000 writes
std::atomic<unsigned> g_torn_bytes{512};
std::atomic<unsigned> g_lost_prob{0};     // per 100,000 fsyncs
std::atomic<unsigned long> g_seed{88172645463325252ull};

std::mutex g_pending_mu;
std::set<int> g_pending;                  // fds with a dropped fsync

unsigned long xorshift() {
  unsigned long x = g_seed.load(std::memory_order_relaxed);
  x ^= x >> 12;
  x ^= x << 25;
  x ^= x >> 27;
  g_seed.store(x, std::memory_order_relaxed);
  return x * 2685821657736338717ull;
}

bool dice(unsigned prob) {
  return prob != 0 && (xorshift() % 100000) < prob;
}

// Returns the errno to inject (0 = proceed), applying latency on a hit.
int fault_for(unsigned op) {
  if (!(g_ops.load(std::memory_order_relaxed) & op)) return 0;
  if (!dice(g_prob.load(std::memory_order_relaxed))) return 0;
  unsigned delay = g_delay_us.load(std::memory_order_relaxed);
  if (delay) {
    struct timespec ts;
    ts.tv_sec = delay / 1000000;
    ts.tv_nsec = (delay % 1000000) * 1000L;
    nanosleep(&ts, nullptr);
  }
  return g_errno.load(std::memory_order_relaxed);
}

void replay_pending_fsyncs() {
  std::lock_guard<std::mutex> lk(g_pending_mu);
  for (int fd : g_pending) fsync(fd);
  g_pending.clear();
}

size_t pending_count() {
  std::lock_guard<std::mutex> lk(g_pending_mu);
  return g_pending.size();
}

// ---------------------------------------------------------------- control TCP

unsigned parse_ops(const char *csv) {
  unsigned ops = 0;
  if (strstr(csv, "read")) ops |= OP_READ;
  if (strstr(csv, "write")) ops |= OP_WRITE;
  if (strstr(csv, "fsync")) ops |= OP_FSYNC;
  if (strstr(csv, "open")) ops |= OP_OPEN;
  return ops;
}

void handle_line(char *line, int conn) {
  int e;
  unsigned prob, delay, bytes;
  char opscsv[128];
  if (sscanf(line, "set %d %u %u %127s", &e, &prob, &delay, opscsv) == 4) {
    g_errno.store(e);
    g_prob.store(prob > 100000 ? 100000 : prob);
    g_delay_us.store(delay);
    g_ops.store(parse_ops(opscsv));
    dprintf(conn, "ok\n");
  } else if (sscanf(line, "torn %u %u", &prob, &bytes) == 2) {
    g_torn_prob.store(prob > 100000 ? 100000 : prob);
    g_torn_bytes.store(bytes);
    dprintf(conn, "ok\n");
  } else if (sscanf(line, "lostsync %u", &prob) == 1) {
    g_lost_prob.store(prob > 100000 ? 100000 : prob);
    dprintf(conn, "ok\n");
  } else if (strncmp(line, "clear", 5) == 0) {
    g_prob.store(0);
    g_ops.store(0);
    g_errno.store(0);
    g_delay_us.store(0);
    g_torn_prob.store(0);
    g_lost_prob.store(0);
    replay_pending_fsyncs();
    dprintf(conn, "ok\n");
  } else if (strncmp(line, "get", 3) == 0) {
    dprintf(conn,
            "errno=%d prob=%u delay_us=%u ops=%u torn=%u torn_bytes=%u "
            "lostsync=%u pending=%zu\n",
            g_errno.load(), g_prob.load(), g_delay_us.load(),
            g_ops.load(), g_torn_prob.load(), g_torn_bytes.load(),
            g_lost_prob.load(), pending_count());
  } else {
    dprintf(conn, "err unknown command\n");
  }
}

int g_port = 7678;

void *control_loop(void *) {
  if (g_port <= 0) return nullptr;
  int srv = socket(AF_INET, SOCK_STREAM, 0);
  if (srv < 0) return nullptr;
  int one = 1;
  setsockopt(srv, SOL_SOCKET, SO_REUSEADDR, &one, sizeof one);
  struct sockaddr_in addr;
  memset(&addr, 0, sizeof addr);
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = htons((uint16_t)g_port);
  if (bind(srv, (struct sockaddr *)&addr, sizeof addr) != 0 ||
      listen(srv, 4) != 0) {
    close(srv);
    return nullptr;
  }
  for (;;) {
    int conn = accept(srv, nullptr, nullptr);
    if (conn < 0) continue;
    char line[512];
    size_t off = 0;
    for (;;) {
      ssize_t r = recv(conn, line + off, sizeof(line) - 1 - off, 0);
      if (r <= 0) break;
      off += (size_t)r;
      line[off] = 0;
      char *nl, *start = line;
      while ((nl = strchr(start, '\n')) != nullptr) {
        *nl = 0;
        handle_line(start, conn);
        start = nl + 1;
      }
      off = strlen(start);
      memmove(line, start, off + 1);
    }
    close(conn);
  }
  return nullptr;
}

// ---------------------------------------------------------------- inode table

std::string g_backing;
char g_mnt[4096];

struct NodeTable {
  std::mutex mu;
  std::unordered_map<uint64_t, std::string> path;  // nodeid -> rel path
  std::unordered_map<std::string, uint64_t> id;    // rel path -> nodeid
  std::unordered_map<uint64_t, uint64_t> nlookup;
  uint64_t next = 2;

  std::string abs(uint64_t nodeid) {
    if (nodeid == 1) return g_backing;
    std::lock_guard<std::mutex> lk(mu);
    auto it = path.find(nodeid);
    return it == path.end() ? std::string() : g_backing + "/" + it->second;
  }

  std::string rel(uint64_t nodeid) {
    if (nodeid == 1) return "";
    std::lock_guard<std::mutex> lk(mu);
    auto it = path.find(nodeid);
    return it == path.end() ? std::string() : it->second;
  }

  uint64_t lookup(const std::string &rel_path) {
    std::lock_guard<std::mutex> lk(mu);
    auto it = id.find(rel_path);
    uint64_t n;
    if (it != id.end()) {
      n = it->second;
    } else {
      n = next++;
      id[rel_path] = n;
      path[n] = rel_path;
    }
    nlookup[n]++;
    return n;
  }

  void forget(uint64_t nodeid, uint64_t n) {
    if (nodeid == 1) return;
    std::lock_guard<std::mutex> lk(mu);
    auto it = nlookup.find(nodeid);
    if (it == nlookup.end()) return;
    if (it->second <= n) {
      auto pit = path.find(nodeid);
      if (pit != path.end()) {
        id.erase(pit->second);
        path.erase(pit);
      }
      nlookup.erase(it);
    } else {
      it->second -= n;
    }
  }

  void rename(const std::string &from, const std::string &to) {
    // Re-point the moved node and any children at their new paths.
    std::lock_guard<std::mutex> lk(mu);
    std::vector<std::pair<uint64_t, std::string>> moves;
    for (auto &kv : path) {
      const std::string &p = kv.second;
      if (p == from) {
        moves.emplace_back(kv.first, to);
      } else if (p.size() > from.size() &&
                 p.compare(0, from.size(), from) == 0 &&
                 p[from.size()] == '/') {
        moves.emplace_back(kv.first, to + p.substr(from.size()));
      }
    }
    for (auto &mv : moves) {
      id.erase(path[mv.first]);
      path[mv.first] = mv.second;
      id[mv.second] = mv.first;
    }
  }
};

NodeTable g_nodes;

std::string child_rel(uint64_t parent, const char *name) {
  std::string p = g_nodes.rel(parent);
  if (parent != 1 && p.empty()) return std::string();  // stale parent
  return p.empty() ? std::string(name) : p + "/" + name;
}

// ---------------------------------------------------------------- replies

int g_dev = -1;

void reply(uint64_t unique, int error, const void *body, size_t body_len) {
  fuse_out_header out;
  out.len = (uint32_t)(sizeof out + (error == 0 ? body_len : 0));
  out.error = error == 0 ? 0 : -error;   // negated errno on the wire
  out.unique = unique;
  struct iovec iov[2] = {{&out, sizeof out},
                         {const_cast<void *>(body), body_len}};
  int cnt = (error == 0 && body_len) ? 2 : 1;
  ssize_t r = writev(g_dev, iov, cnt);
  (void)r;  // ENOENT here means the request was interrupted; ignore
}

void reply_err(uint64_t unique, int error) { reply(unique, error, nullptr, 0); }
void reply_ok(uint64_t unique) { reply(unique, 0, nullptr, 0); }

void fill_attr(const struct stat &st, fuse_attr *a) {
  memset(a, 0, sizeof *a);
  a->ino = st.st_ino;
  a->size = (uint64_t)st.st_size;
  a->blocks = (uint64_t)st.st_blocks;
  a->atime = (uint64_t)st.st_atim.tv_sec;
  a->mtime = (uint64_t)st.st_mtim.tv_sec;
  a->ctime = (uint64_t)st.st_ctim.tv_sec;
  a->atimensec = (uint32_t)st.st_atim.tv_nsec;
  a->mtimensec = (uint32_t)st.st_mtim.tv_nsec;
  a->ctimensec = (uint32_t)st.st_ctim.tv_nsec;
  a->mode = st.st_mode;
  a->nlink = (uint32_t)st.st_nlink;
  a->uid = st.st_uid;
  a->gid = st.st_gid;
  a->rdev = (uint32_t)st.st_rdev;
  a->blksize = (uint32_t)st.st_blksize;
}

// Attr/entry validity 0: faults change visible file state out of band,
// so the kernel must re-ask every time rather than trust its cache.
void reply_entry(uint64_t unique, uint64_t nodeid, const struct stat &st) {
  fuse_entry_out e;
  memset(&e, 0, sizeof e);
  e.nodeid = nodeid;
  fill_attr(st, &e.attr);
  reply(unique, 0, &e, sizeof e);
}

void reply_attr(uint64_t unique, const struct stat &st) {
  fuse_attr_out a;
  memset(&a, 0, sizeof a);
  fill_attr(st, &a.attr);
  reply(unique, 0, &a, sizeof a);
}

// ---------------------------------------------------------------- dir handles

struct DirSnap {
  struct Ent { std::string name; uint64_t ino; uint32_t type; };
  std::vector<Ent> ents;
};

// ---------------------------------------------------------------- dispatch

void do_lookup(const fuse_in_header *in, const char *name) {
  std::string rel = child_rel(in->nodeid, name);
  if (in->nodeid != 1 && rel.empty()) return reply_err(in->unique, ENOENT);
  std::string abs = g_backing + "/" + rel;
  struct stat st;
  if (lstat(abs.c_str(), &st) != 0) return reply_err(in->unique, errno);
  reply_entry(in->unique, g_nodes.lookup(rel), st);
}

void do_getattr(const fuse_in_header *in, const fuse_getattr_in *gi) {
  struct stat st;
  int rc;
  if (gi && (gi->getattr_flags & FUSE_GETATTR_FH)) {
    rc = fstat((int)gi->fh, &st);
  } else {
    std::string abs = g_nodes.abs(in->nodeid);
    if (abs.empty()) return reply_err(in->unique, ENOENT);
    rc = lstat(abs.c_str(), &st);
  }
  if (rc != 0) return reply_err(in->unique, errno);
  reply_attr(in->unique, st);
}

void do_setattr(const fuse_in_header *in, const fuse_setattr_in *si) {
  std::string abs = g_nodes.abs(in->nodeid);
  bool have_fh = si->valid & FATTR_FH;
  int fd = have_fh ? (int)si->fh : -1;
  if (!have_fh && abs.empty()) return reply_err(in->unique, ENOENT);
  if (si->valid & FATTR_SIZE) {
    int rc = have_fh ? ftruncate(fd, (off_t)si->size)
                     : truncate(abs.c_str(), (off_t)si->size);
    if (rc != 0) return reply_err(in->unique, errno);
  }
  if (si->valid & FATTR_MODE) {
    int rc = have_fh ? fchmod(fd, si->mode) : chmod(abs.c_str(), si->mode);
    if (rc != 0) return reply_err(in->unique, errno);
  }
  if (si->valid & (FATTR_UID | FATTR_GID)) {
    uid_t u = (si->valid & FATTR_UID) ? si->uid : (uid_t)-1;
    gid_t g = (si->valid & FATTR_GID) ? si->gid : (gid_t)-1;
    int rc = have_fh ? fchown(fd, u, g) : lchown(abs.c_str(), u, g);
    if (rc != 0) return reply_err(in->unique, errno);
  }
  if (si->valid & (FATTR_ATIME | FATTR_MTIME | FATTR_ATIME_NOW |
                   FATTR_MTIME_NOW)) {
    struct timespec ts[2];
    ts[0].tv_nsec = UTIME_OMIT;
    ts[1].tv_nsec = UTIME_OMIT;
    if (si->valid & FATTR_ATIME_NOW) ts[0].tv_nsec = UTIME_NOW;
    else if (si->valid & FATTR_ATIME) {
      ts[0].tv_sec = (time_t)si->atime;
      ts[0].tv_nsec = si->atimensec;
    }
    if (si->valid & FATTR_MTIME_NOW) ts[1].tv_nsec = UTIME_NOW;
    else if (si->valid & FATTR_MTIME) {
      ts[1].tv_sec = (time_t)si->mtime;
      ts[1].tv_nsec = si->mtimensec;
    }
    int rc = have_fh ? futimens(fd, ts)
                     : utimensat(AT_FDCWD, abs.c_str(), ts,
                                 AT_SYMLINK_NOFOLLOW);
    if (rc != 0) return reply_err(in->unique, errno);
  }
  struct stat st;
  int rc = have_fh ? fstat(fd, &st) : lstat(abs.c_str(), &st);
  if (rc != 0) return reply_err(in->unique, errno);
  reply_attr(in->unique, st);
}

void do_open(const fuse_in_header *in, const fuse_open_in *oi) {
  int e = fault_for(OP_OPEN);
  if (e) return reply_err(in->unique, e);
  std::string abs = g_nodes.abs(in->nodeid);
  if (abs.empty()) return reply_err(in->unique, ENOENT);
  int fd = open(abs.c_str(), (int)(oi->flags & ~O_NOFOLLOW));
  if (fd < 0) return reply_err(in->unique, errno);
  fuse_open_out oo;
  memset(&oo, 0, sizeof oo);
  oo.fh = (uint64_t)fd;
  oo.open_flags = FOPEN_DIRECT_IO;
  reply(in->unique, 0, &oo, sizeof oo);
}

void do_create(const fuse_in_header *in, const fuse_create_in *ci,
               const char *name) {
  int e = fault_for(OP_OPEN);
  if (e) return reply_err(in->unique, e);
  std::string rel = child_rel(in->nodeid, name);
  if (in->nodeid != 1 && rel.empty()) return reply_err(in->unique, ENOENT);
  std::string abs = g_backing + "/" + rel;
  int fd = open(abs.c_str(), (int)ci->flags | O_CREAT, ci->mode);
  if (fd < 0) return reply_err(in->unique, errno);
  struct stat st;
  if (fstat(fd, &st) != 0) {
    int err = errno;
    close(fd);
    return reply_err(in->unique, err);
  }
  struct {
    fuse_entry_out e;
    fuse_open_out o;
  } out;
  memset(&out, 0, sizeof out);
  out.e.nodeid = g_nodes.lookup(rel);
  fill_attr(st, &out.e.attr);
  out.o.fh = (uint64_t)fd;
  out.o.open_flags = FOPEN_DIRECT_IO;
  reply(in->unique, 0, &out, sizeof out);
}

void do_read(const fuse_in_header *in, const fuse_read_in *ri) {
  int e = fault_for(OP_READ);
  if (e) return reply_err(in->unique, e);
  std::vector<char> buf(ri->size);
  ssize_t n = pread((int)ri->fh, buf.data(), ri->size, (off_t)ri->offset);
  if (n < 0) return reply_err(in->unique, errno);
  reply(in->unique, 0, buf.data(), (size_t)n);
}

void do_write(const fuse_in_header *in, const fuse_write_in *wi,
              const char *data) {
  if (dice(g_torn_prob.load(std::memory_order_relaxed))) {
    // Torn write: persist the first k bytes, then fail — the caller
    // sees EIO but a partial image reached the backing file.
    unsigned k = g_torn_bytes.load(std::memory_order_relaxed);
    if (k > wi->size) k = wi->size;
    if (k) {
      ssize_t r = pwrite((int)wi->fh, data, k, (off_t)wi->offset);
      (void)r;
    }
    return reply_err(in->unique, EIO);
  }
  int e = fault_for(OP_WRITE);
  if (e) return reply_err(in->unique, e);
  ssize_t n = pwrite((int)wi->fh, data, wi->size, (off_t)wi->offset);
  if (n < 0) return reply_err(in->unique, errno);
  fuse_write_out wo;
  memset(&wo, 0, sizeof wo);
  wo.size = (uint32_t)n;
  reply(in->unique, 0, &wo, sizeof wo);
}

void do_fsync(const fuse_in_header *in, const fuse_fsync_in *fi) {
  if (dice(g_lost_prob.load(std::memory_order_relaxed))) {
    // Lost fsync: ACK without durability; remember the fd so `clear`
    // can replay the sync (heal = the cache survived after all).
    std::lock_guard<std::mutex> lk(g_pending_mu);
    g_pending.insert((int)fi->fh);
    return reply_ok(in->unique);
  }
  int e = fault_for(OP_FSYNC);
  if (e) return reply_err(in->unique, e);
  int rc = (fi->fsync_flags & FUSE_FSYNC_FDATASYNC)
               ? fdatasync((int)fi->fh)
               : fsync((int)fi->fh);
  if (rc != 0) return reply_err(in->unique, errno);
  reply_ok(in->unique);
}

void do_release(const fuse_in_header *in, const fuse_release_in *ri) {
  {
    std::lock_guard<std::mutex> lk(g_pending_mu);
    g_pending.erase((int)ri->fh);  // a pending sync dies with the fd
  }
  close((int)ri->fh);
  reply_ok(in->unique);
}

void do_opendir(const fuse_in_header *in) {
  std::string abs = g_nodes.abs(in->nodeid);
  if (abs.empty()) return reply_err(in->unique, ENOENT);
  DIR *d = opendir(abs.c_str());
  if (!d) return reply_err(in->unique, errno);
  DirSnap *snap = new DirSnap();
  struct dirent *de;
  while ((de = readdir(d)) != nullptr)
    snap->ents.push_back({de->d_name, (uint64_t)de->d_ino,
                          (uint32_t)de->d_type});
  closedir(d);
  fuse_open_out oo;
  memset(&oo, 0, sizeof oo);
  oo.fh = (uint64_t)(uintptr_t)snap;
  reply(in->unique, 0, &oo, sizeof oo);
}

void do_readdir(const fuse_in_header *in, const fuse_read_in *ri) {
  DirSnap *snap = (DirSnap *)(uintptr_t)ri->fh;
  if (!snap) return reply_err(in->unique, EBADF);
  std::vector<char> buf;
  buf.reserve(ri->size);
  size_t i = (size_t)ri->offset;
  while (i < snap->ents.size()) {
    const auto &ent = snap->ents[i];
    size_t entlen = sizeof(fuse_dirent) + ent.name.size();
    size_t padded = (entlen + 7) & ~size_t(7);
    if (buf.size() + padded > ri->size) break;
    fuse_dirent de;
    de.ino = ent.ino;
    de.off = (uint64_t)(i + 1);   // next offset cookie
    de.namelen = (uint32_t)ent.name.size();
    de.type = ent.type;
    size_t base = buf.size();
    buf.resize(base + padded, 0);
    memcpy(&buf[base], &de, sizeof de);
    memcpy(&buf[base + sizeof de], ent.name.data(), ent.name.size());
    i++;
  }
  reply(in->unique, 0, buf.data(), buf.size());
}

void do_releasedir(const fuse_in_header *in, const fuse_release_in *ri) {
  delete (DirSnap *)(uintptr_t)ri->fh;
  reply_ok(in->unique);
}

void do_mkdir(const fuse_in_header *in, const fuse_mkdir_in *mi,
              const char *name) {
  std::string rel = child_rel(in->nodeid, name);
  if (in->nodeid != 1 && rel.empty()) return reply_err(in->unique, ENOENT);
  std::string abs = g_backing + "/" + rel;
  if (mkdir(abs.c_str(), mi->mode) != 0)
    return reply_err(in->unique, errno);
  struct stat st;
  if (lstat(abs.c_str(), &st) != 0) return reply_err(in->unique, errno);
  reply_entry(in->unique, g_nodes.lookup(rel), st);
}

void do_mknod(const fuse_in_header *in, const fuse_mknod_in *mi,
              const char *name) {
  std::string rel = child_rel(in->nodeid, name);
  if (in->nodeid != 1 && rel.empty()) return reply_err(in->unique, ENOENT);
  std::string abs = g_backing + "/" + rel;
  if (mknod(abs.c_str(), mi->mode, mi->rdev) != 0)
    return reply_err(in->unique, errno);
  struct stat st;
  if (lstat(abs.c_str(), &st) != 0) return reply_err(in->unique, errno);
  reply_entry(in->unique, g_nodes.lookup(rel), st);
}

void do_unlink(const fuse_in_header *in, const char *name, bool isdir) {
  std::string rel = child_rel(in->nodeid, name);
  if (in->nodeid != 1 && rel.empty()) return reply_err(in->unique, ENOENT);
  std::string abs = g_backing + "/" + rel;
  int rc = isdir ? rmdir(abs.c_str()) : unlink(abs.c_str());
  if (rc != 0) return reply_err(in->unique, errno);
  reply_ok(in->unique);
}

void do_rename(const fuse_in_header *in, uint64_t newdir,
               const char *oldname, const char *newname) {
  std::string from = child_rel(in->nodeid, oldname);
  std::string to = child_rel(newdir, newname);
  if ((in->nodeid != 1 && from.empty()) || (newdir != 1 && to.empty()))
    return reply_err(in->unique, ENOENT);
  if (rename((g_backing + "/" + from).c_str(),
             (g_backing + "/" + to).c_str()) != 0)
    return reply_err(in->unique, errno);
  g_nodes.rename(from, to);
  reply_ok(in->unique);
}

void do_statfs(const fuse_in_header *in) {
  struct statvfs sv;
  if (statvfs(g_backing.c_str(), &sv) != 0)
    return reply_err(in->unique, errno);
  fuse_kstatfs st;
  memset(&st, 0, sizeof st);
  st.blocks = sv.f_blocks;
  st.bfree = sv.f_bfree;
  st.bavail = sv.f_bavail;
  st.files = sv.f_files;
  st.ffree = sv.f_ffree;
  st.bsize = (uint32_t)sv.f_bsize;
  st.namelen = (uint32_t)sv.f_namemax;
  st.frsize = (uint32_t)sv.f_frsize;
  reply(in->unique, 0, &st, sizeof st);
}

void do_init(const fuse_in_header *in, const fuse_init_in *ii) {
  fuse_init_out out;
  memset(&out, 0, sizeof out);
  out.major = 7;
  out.minor = ii->minor < 31 ? ii->minor : 31;
  out.max_readahead = 0;          // direct_io: no readahead cache
  out.flags = 0;                  // no optional kernel features
  out.max_background = 12;
  out.congestion_threshold = 9;
  out.max_write = 128 * 1024;
  out.time_gran = 1;
  // Pre-7.23 kernels expect a 24-byte init_out; everything current
  // (>= 4.x) takes the full 64.
  size_t len = ii->minor < 23 ? 24 : sizeof out;
  reply(in->unique, 0, &out, len);
}

// ---------------------------------------------------------------- main loop

void on_term(int) {
  umount2(g_mnt, MNT_DETACH);
  _exit(0);
}

int serve() {
  std::vector<char> buf(1 << 20);
  for (;;) {
    ssize_t n = read(g_dev, buf.data(), buf.size());
    if (n < 0) {
      if (errno == EINTR || errno == EAGAIN) continue;
      if (errno == ENODEV) return 0;      // unmounted externally
      perror("faultfs: /dev/fuse read");
      return 1;
    }
    if ((size_t)n < sizeof(fuse_in_header)) continue;
    const fuse_in_header *in = (const fuse_in_header *)buf.data();
    const char *arg = buf.data() + sizeof(fuse_in_header);
    switch (in->opcode) {
      case FUSE_INIT:
        do_init(in, (const fuse_init_in *)arg);
        break;
      case FUSE_LOOKUP:
        do_lookup(in, arg);
        break;
      case FUSE_FORGET:
        g_nodes.forget(in->nodeid, ((const fuse_forget_in *)arg)->nlookup);
        break;                              // no reply
      case FUSE_BATCH_FORGET: {
        const fuse_batch_forget_in *bi = (const fuse_batch_forget_in *)arg;
        const fuse_forget_one *one =
            (const fuse_forget_one *)(arg + sizeof *bi);
        for (uint32_t i = 0; i < bi->count; i++)
          g_nodes.forget(one[i].nodeid, one[i].nlookup);
        break;                              // no reply
      }
      case FUSE_GETATTR:
        do_getattr(in, (const fuse_getattr_in *)arg);
        break;
      case FUSE_SETATTR:
        do_setattr(in, (const fuse_setattr_in *)arg);
        break;
      case FUSE_OPEN:
        do_open(in, (const fuse_open_in *)arg);
        break;
      case FUSE_CREATE:
        do_create(in, (const fuse_create_in *)arg,
                  arg + sizeof(fuse_create_in));
        break;
      case FUSE_READ:
        do_read(in, (const fuse_read_in *)arg);
        break;
      case FUSE_WRITE:
        do_write(in, (const fuse_write_in *)arg,
                 arg + sizeof(fuse_write_in));
        break;
      case FUSE_FSYNC:
      case FUSE_FSYNCDIR:
        do_fsync(in, (const fuse_fsync_in *)arg);
        break;
      case FUSE_FLUSH:
        reply_ok(in->unique);
        break;
      case FUSE_RELEASE:
        do_release(in, (const fuse_release_in *)arg);
        break;
      case FUSE_OPENDIR:
        do_opendir(in);
        break;
      case FUSE_READDIR:
        do_readdir(in, (const fuse_read_in *)arg);
        break;
      case FUSE_RELEASEDIR:
        do_releasedir(in, (const fuse_release_in *)arg);
        break;
      case FUSE_MKDIR:
        do_mkdir(in, (const fuse_mkdir_in *)arg,
                 arg + sizeof(fuse_mkdir_in));
        break;
      case FUSE_MKNOD:
        do_mknod(in, (const fuse_mknod_in *)arg,
                 arg + sizeof(fuse_mknod_in));
        break;
      case FUSE_UNLINK:
        do_unlink(in, arg, false);
        break;
      case FUSE_RMDIR:
        do_unlink(in, arg, true);
        break;
      case FUSE_RENAME: {
        const fuse_rename_in *ri = (const fuse_rename_in *)arg;
        const char *oldname = arg + sizeof *ri;
        do_rename(in, ri->newdir, oldname, oldname + strlen(oldname) + 1);
        break;
      }
      case FUSE_RENAME2: {
        const fuse_rename2_in *ri = (const fuse_rename2_in *)arg;
        if (ri->flags != 0) {               // RENAME_EXCHANGE etc.
          reply_err(in->unique, EINVAL);
          break;
        }
        const char *oldname = arg + sizeof *ri;
        do_rename(in, ri->newdir, oldname, oldname + strlen(oldname) + 1);
        break;
      }
      case FUSE_STATFS:
        do_statfs(in);
        break;
      case FUSE_ACCESS: {
        std::string abs = g_nodes.abs(in->nodeid);
        if (abs.empty()) reply_err(in->unique, ENOENT);
        else if (access(abs.c_str(),
                        (int)((const fuse_access_in *)arg)->mask) != 0)
          reply_err(in->unique, errno);
        else reply_ok(in->unique);
        break;
      }
      case FUSE_FALLOCATE: {
        const fuse_fallocate_in *fi = (const fuse_fallocate_in *)arg;
        int e = fault_for(OP_WRITE);
        if (e) { reply_err(in->unique, e); break; }
        if (fallocate((int)fi->fh, (int)fi->mode, (off_t)fi->offset,
                      (off_t)fi->length) != 0)
          reply_err(in->unique, errno);
        else reply_ok(in->unique);
        break;
      }
      case FUSE_LSEEK: {
        const fuse_lseek_in *li = (const fuse_lseek_in *)arg;
        off_t off = lseek((int)li->fh, (off_t)li->offset, (int)li->whence);
        if (off < 0) reply_err(in->unique, errno);
        else {
          fuse_lseek_out lo = {(uint64_t)off};
          reply(in->unique, 0, &lo, sizeof lo);
        }
        break;
      }
      case FUSE_INTERRUPT:
        break;                              // no reply, ever
      case FUSE_DESTROY:
        reply_ok(in->unique);
        return 0;
      default:
        reply_err(in->unique, ENOSYS);
        break;
    }
  }
}

int mount_fuse(const char *mnt) {
  int fd = open("/dev/fuse", O_RDWR);
  if (fd < 0) return -1;
  struct stat st;
  if (stat(g_backing.c_str(), &st) != 0) {
    close(fd);
    return -1;
  }
  char opts[256];
  snprintf(opts, sizeof opts,
           "fd=%d,rootmode=%o,user_id=%u,group_id=%u,allow_other,"
           "default_permissions",
           fd, st.st_mode & S_IFMT, getuid(), getgid());
  if (mount("faultfs", mnt, "fuse.faultfs", MS_NOSUID | MS_NODEV,
            opts) != 0) {
    close(fd);
    return -1;
  }
  return fd;
}

int probe() {
  // Can this host create FUSE mounts at all?  Mount an empty fs over a
  // temp dir and immediately detach it — no requests are ever served.
  char tmpl[] = "/tmp/faultfs-probe-XXXXXX";
  char *dir = mkdtemp(tmpl);
  if (!dir) return 1;
  g_backing = "/tmp";
  int fd = mount_fuse(dir);
  int ok = fd >= 0;
  if (ok) {
    umount2(dir, MNT_DETACH);
    close(fd);
  }
  rmdir(dir);
  printf(ok ? "ok\n" : "unsupported\n");
  return ok ? 0 : 1;
}

}  // namespace

int main(int argc, char **argv) {
  if (argc >= 2 && strcmp(argv[1], "--probe") == 0) return probe();
  if (argc < 3) {
    fprintf(stderr,
            "usage: %s BACKING_DIR MOUNTPOINT [--port N] | --probe\n",
            argv[0]);
    return 2;
  }
  char backing_real[4096];
  if (!realpath(argv[1], backing_real)) {
    perror("faultfs: backing dir");
    return 1;
  }
  g_backing = backing_real;
  if (!realpath(argv[2], g_mnt)) {
    perror("faultfs: mountpoint");
    return 1;
  }
  for (int i = 3; i + 1 < argc; i++)
    if (strcmp(argv[i], "--port") == 0) g_port = atoi(argv[i + 1]);

  int fd = mount_fuse(g_mnt);
  if (fd < 0) {
    perror("faultfs: mount");
    return 1;
  }
  g_dev = fd;
  struct sigaction sa;
  memset(&sa, 0, sizeof sa);
  sa.sa_handler = on_term;
  sigaction(SIGTERM, &sa, nullptr);
  sigaction(SIGINT, &sa, nullptr);

  pthread_t tid;
  pthread_create(&tid, nullptr, control_loop, nullptr);
  pthread_detach(tid);

  int rc = serve();
  umount2(g_mnt, MNT_DETACH);
  return rc;
}
