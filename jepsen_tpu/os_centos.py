"""CentOS provisioning (reference: `jepsen/src/jepsen/os/centos.clj`):
yum equivalents of the debian layer.
"""

from __future__ import annotations

import logging
from typing import Iterable

from jepsen_tpu import os as os_mod
from jepsen_tpu import control as c
from jepsen_tpu.control import lit
from jepsen_tpu.os_debian import setup_hostfile

log = logging.getLogger("jepsen.os.centos")

BASE_PACKAGES = ["wget", "curl", "unzip", "iptables", "psmisc", "tar",
                 "bzip2", "iputils", "iproute", "rsyslog", "logrotate",
                 "ntpdate",
                 # the clock nemesis compiles its tools on the node
                 "gcc"]


def installed(pkgs: Iterable[str]) -> set:
    pkgs = list(pkgs)
    out = c.execute(lit("rpm -q --qf '%{NAME}\\n' "
                        + " ".join(c.escape(p) for p in pkgs)
                        + " 2>/dev/null"), check=False)
    return {line.strip() for line in out.splitlines()
            if line.strip() in pkgs}


def install(pkgs: Iterable[str], force: bool = False) -> None:
    pkgs = list(pkgs)
    have = set() if force else installed(pkgs)
    missing = [p for p in pkgs if p not in have]
    if not missing:
        return
    c.execute(lit("yum install -y "
                  + " ".join(c.escape(p) for p in missing)))


class CentOS(os_mod.OS):
    """centos.clj CentOS deftype :133-161."""

    def setup(self, test, node):
        log.info("%s setting up centos", node)
        setup_hostfile(test, node)
        install(BASE_PACKAGES)
        net = test.get("net")
        if net is not None:
            net.heal(test)

    def teardown(self, test, node):
        pass


os = CentOS()
