"""Test orchestration — L5, the core runtime.

Port of `jepsen/src/jepsen/core.clj`: `run()` coordinates SSH sessions,
OS/DB setup, worker threads (one logically-single-threaded *process* per
concurrency slot plus a *nemesis*), history collection, analysis, and
teardown.  The analysis phase (`analyze`) hands the history to the
checker — where this framework swaps knossos for the TPU kernels.
"""

from __future__ import annotations

import contextlib
import logging
import threading
import time as time_mod
from typing import Any, Optional

from jepsen_tpu import checker as checker_mod
from jepsen_tpu import client as client_mod
from jepsen_tpu import control, db as db_mod
from jepsen_tpu import generator as gen
from jepsen_tpu.history import History, Op, op as to_op
from jepsen_tpu.util import (fcatch, log_op, real_pmap, relative_time_nanos,
                             with_relative_time)

log = logging.getLogger("jepsen")

NO_BARRIER = "::no-barrier"


class WorkerAbort(Exception):
    pass


def synchronize(test, timeout_s: float = 60) -> None:
    """Block until all nodes arrive (core.clj:40-53); used by IO-heavy DB
    setup code."""
    b = test.get("barrier")
    if b is None or b == NO_BARRIER:
        return
    b.wait(timeout=timeout_s)


def conj_op(test, op: Op) -> Op:
    """Append an op to the test's history (core.clj:55-59)."""
    history, lock = test["history"], test["history_lock"]
    with lock:
        history.append(op)
    return op


def primary(test):
    """core.clj:61-64."""
    return test["nodes"][0]


# ---------------------------------------------------------------------------
# Workers (core.clj:161-401)
# ---------------------------------------------------------------------------

class Worker:
    """Synchronized setup/run/teardown lifecycle (core.clj:161-169)."""

    name = "worker"

    def __init__(self):
        self.abort = threading.Event()

    def abort_worker(self):
        self.abort.set()

    def setup_worker(self):
        pass

    def run_worker(self):
        pass

    def teardown_worker(self):
        pass


class InvokeTimeout(Exception):
    """A client.invoke exceeded the test's :invoke-timeout bound."""


class InvokeStalled(Exception):
    """The worker watchdog cancelled an in-flight invoke: the worker
    had not journaled progress within the stall budget (or the run
    deadline expired mid-drain).  Indeterminate, like InvokeTimeout —
    the op may or may not have taken effect — so the completion is
    :info and the process id retires."""


class InvokeNeverRan(Exception):
    """The abandoned-invoker cap rejected an op BEFORE its invoke thread
    was spawned: the op definitively did not take effect, so the sound
    completion is :fail (history unchanged) — not :info, which would
    flood the checker with phantom crashed calls that stay concurrent
    forever and blow up the WGL config space."""


_MAX_ABANDONED = 128
_abandoned: list = []               # done-events of abandoned invokers
_abandoned_lock = threading.Lock()


def _bounded_invoke(client, test, op: Op, seconds: Optional[float],
                    cancel: Optional[threading.Event] = None):
    """client.invoke with a wall-clock bound and/or a watchdog cancel
    hook.  On timeout the invoking thread is abandoned (exactly like
    util.timeout and the reference's interrupt-based worker deadline,
    generator.clj:415-530) and InvokeTimeout is raised — the caller
    converts it to an :info completion and the worker recycles the
    process, so one hung client can no longer overrun a generator
    time_limit indefinitely.  A late result from the abandoned thread
    is discarded, which is sound: the op is already journaled :info
    (indeterminate, may or may not have taken effect).

    With `cancel` (the worker watchdog's per-op stall event, or the
    run-deadline drain), `seconds` may be None: the wait then has no
    fixed bound but wakes the moment the watchdog fires, abandoning the
    thread and raising InvokeStalled.  Either way the abandoned
    thread's cancel token (util.cancel_scope) is set, so cooperative
    clients that poll util.cancelled() retire promptly instead of
    running forever.

    Leak bound: each timeout abandons one daemon thread, which lives
    until its client call returns.  Against a fully wedged cluster the
    process-wide count of live abandoned threads is capped at
    _MAX_ABANDONED.  At the cap a new invoke first waits a BOUNDED
    slice (min(seconds, 1) — not the full invoke timeout, which would
    stall the worker for up to 2x the configured budget before even
    attempting the op; ADVICE r3) for the oldest abandoned thread to
    retire, then — if still saturated — raises InvokeNeverRan WITHOUT
    spawning a thread, which the caller journals as :fail
    (definitely-no-effect)."""
    with _abandoned_lock:
        _abandoned[:] = [d for d in _abandoned if not d.is_set()]
        oldest = _abandoned[0] if len(_abandoned) >= _MAX_ABANDONED \
            else None
    if oldest is not None:
        oldest.wait(min(seconds, 1.0) if seconds else 1.0)
        with _abandoned_lock:
            _abandoned[:] = [d for d in _abandoned if not d.is_set()]
            if len(_abandoned) >= _MAX_ABANDONED:
                raise InvokeNeverRan(
                    f"{len(_abandoned)} abandoned invokers still live "
                    f"(cluster wedged?); op not attempted")
    box: list = [None]
    err: list = [None]
    done = threading.Event()
    thread_cancel = threading.Event()

    def run():
        from jepsen_tpu.util import cancel_scope
        with cancel_scope(thread_cancel):
            try:
                box[0] = client.invoke(test, op)
            except BaseException as e:  # noqa: BLE001 - re-raised in caller
                err[0] = e
            finally:
                done.set()

    t = threading.Thread(target=run, daemon=True,
                         name=f"invoke-{op.process}")
    t.start()

    def abandon(exc):
        thread_cancel.set()           # cooperative clients retire early
        with _abandoned_lock:
            _abandoned.append(done)
        raise exc

    if cancel is None:
        finished = done.wait(seconds)
    else:
        # Wake on completion, watchdog cancel, or deadline — whichever
        # first.  Python has no multi-event wait, so slice the wait.
        deadline = (time_mod.monotonic() + seconds) if seconds else None
        while True:
            if done.wait(0.05):
                finished = True
                break
            if cancel.is_set():
                abandon(InvokeStalled(
                    "invoke cancelled by worker watchdog"))
            if deadline is not None and time_mod.monotonic() > deadline:
                finished = False
                break
    if not finished:
        abandon(InvokeTimeout(f"invoke timed out after {seconds}s"))
    if err[0] is not None:
        raise err[0]
    return box[0]


def _bounded_close(client, test, seconds: float):
    """Bounded client.close whose abandoned closer thread counts toward
    the same _MAX_ABANDONED registry as timed-out invokers — otherwise
    each recycled process would leak an uncapped closer thread and the
    invoke cap's process-wide bound would be fiction.  At the cap the
    close is skipped outright: the connection is already presumed dead
    and the client object is being discarded either way."""
    with _abandoned_lock:
        _abandoned[:] = [d for d in _abandoned if not d.is_set()]
        if len(_abandoned) >= _MAX_ABANDONED:
            return
    done = threading.Event()

    def run():
        try:
            client.close(test)
        except Exception:
            pass
        finally:
            done.set()

    t = threading.Thread(target=run, daemon=True, name="close-bounded")
    t.start()
    if not done.wait(seconds):
        with _abandoned_lock:
            _abandoned.append(done)


def invoke_op(op: Op, test, client, abort,
              cancel: Optional[threading.Event] = None) -> Op:
    """Apply an op to a client, converting exceptions to :info completions
    — 'indeterminate' (core.clj:199-232).  With test[:invoke-timeout]
    (seconds) set, each invoke is wall-clock bounded via
    _bounded_invoke; with `cancel` (the watchdog's per-op stall event)
    the invoke additionally wakes and journals :info the moment the
    watchdog retires the worker's in-flight op."""
    try:
        timeout_s = test.get("invoke_timeout")
        if timeout_s or cancel is not None:
            completion = _bounded_invoke(client, test, op, timeout_s,
                                         cancel)
        else:
            completion = client.invoke(test, op)
        completion = to_op(completion).assoc(time=relative_time_nanos())
    except InvokeNeverRan as e:
        completion = op.assoc(type="fail", time=relative_time_nanos(),
                              error=str(e))
    except BaseException as e:
        if abort.is_set():
            raise
        log.warning("Process %s crashed", op.process, exc_info=True)
        completion = op.assoc(type="info", time=relative_time_nanos(),
                              error=f"indeterminate: {e}")
    assert completion.type in ("ok", "fail", "info"), \
        (f"Expected client.invoke to return an op with type ok, fail or "
         f"info, but received {completion!r} instead")
    assert completion.process == op.process
    assert completion.f == op.f
    return completion


class ClientWorker(Worker):
    """The op loop (core.clj ClientWorker :280-358): draw op, journal
    invocation, invoke client, journal completion; on an indeterminate
    (:info) completion the process is hung — renumber it by +concurrency
    and reopen the client."""

    def __init__(self, test, process_id: int, node):
        super().__init__()
        self.test = test
        self.worker_number = process_id
        self.process = process_id
        self.node = node
        self.client: Optional[client_mod.Client] = None
        self.name = f"worker {process_id}"
        from jepsen_tpu import telemetry as telemetry_mod
        self.tele = telemetry_mod.of(test)
        # Watchdog bookkeeping: the monitor thread reads (inflight,
        # last_journal) under progress_lock and fires stall_cancel to
        # retire a wedged in-flight op (see Watchdog).
        self.progress_lock = threading.Lock()
        self.inflight: Optional[Op] = None
        self.last_journal = time_mod.monotonic()
        self.stall_cancel: Optional[threading.Event] = None

    def setup_worker(self):
        self.client = client_mod.open_client(
            self.test["client"], self.test, self.node)

    def _mark_inflight(self, op: Optional[Op]):
        with self.progress_lock:
            self.inflight = op
            self.last_journal = time_mod.monotonic()
            self.stall_cancel = threading.Event() if op is not None \
                else None
            return self.stall_cancel

    def run_worker(self):
        test = self.test
        g = test["generator"]
        drain = test.get("drain_event")
        watched = drain is not None
        with gen.with_threads(test["threads"]):
            while True:
                if self.abort.is_set():
                    raise WorkerAbort()
                if drain is not None and drain.is_set():
                    return          # run deadline: stop drawing ops
                op = gen.op_and_validate(g, test, self.process)
                if op is None:
                    return
                op = to_op(op).assoc(process=self.process,
                                     time=relative_time_nanos())
                log_op(op)
                if self.client is None:
                    try:
                        self.client = test["client"].open(test, self.node)
                    except Exception as e:
                        log.warning("Error opening client", exc_info=True)
                        fail = op.assoc(type="fail",
                                        error=["no-client", str(e)],
                                        time=relative_time_nanos())
                        conj_op(test, op)
                        conj_op(test, fail)
                        log_op(fail)
                        self.client = None
                        continue
                tr = test.get("tracer")
                traced = tr is not None and tr.enabled
                # dgraph trace.clj:52-63 wraps client ops in spans.
                # The span covers BOTH WAL appends (invoke and
                # completion), not just the client call: the open
                # span's context is what HistoryWAL.append stamps
                # into the record's `c` envelope field — the root of
                # the causal flight-recorder chain (ISSUE 19).
                with (tr.span("client/invoke", f=str(op.f),
                              process=op.process) if traced
                      else contextlib.nullcontext()):
                    conj_op(test, op)
                    cancel = self._mark_inflight(op) if watched \
                        else None
                    try:
                        completion = invoke_op(op, test, self.client,
                                               self.abort, cancel)
                        if traced:
                            tr.attribute("type", str(completion.type))
                    finally:
                        if watched:
                            self._mark_inflight(None)
                    conj_op(test, completion)
                log_op(completion)
                # per-op latency histogram keyed (f, node, outcome) +
                # one event — the telemetry.jsonl attribution stream
                self.tele.record_op(op.f, self.node, completion.type,
                                    op.time, completion.time,
                                    process=op.process)
                if completion.is_info:
                    # This process is hung: it cannot initiate another op
                    # without violating the single-threaded process
                    # constraint.  Cycle to a new process id; the
                    # invocation stays concurrent forever
                    # (core.clj:338-355).
                    self.process += test["concurrency"]
                    try:
                        # close() on a hung client can block on the same
                        # dead connection the invoke did — bound it too,
                        # abandoning the closer thread on timeout.
                        timeout_s = test.get("invoke_timeout")
                        if timeout_s:
                            _bounded_close(self.client, test, timeout_s)
                        else:
                            self.client.close(test)
                    except Exception:
                        pass
                    self.client = None

    def teardown_worker(self):
        if self.client is not None:
            client_mod.close_client(self.client, self.test)
            self.client = None


class NemesisWorker(Worker):
    """core.clj NemesisWorker :370-396: runs the generator as process
    :nemesis, journaling ops into every active history."""

    name = "nemesis"

    def __init__(self, test):
        super().__init__()
        self.test = test
        self.nemesis = None
        from jepsen_tpu import telemetry as telemetry_mod
        self.tele = telemetry_mod.of(test)

    def setup_worker(self):
        from jepsen_tpu import nemesis as nemesis_mod
        self.nemesis = nemesis_mod.setup(self.test.get("nemesis"), self.test)

    def _journal(self, op: Op):
        log_op(op)
        with self.test["active_histories_lock"]:
            entries = list(self.test["active_histories"])
        for history, lock in entries:
            with lock:
                history.append(op)

    def run_worker(self):
        from jepsen_tpu import nemesis as nemesis_mod
        test = self.test
        g = test["generator"]
        drain = test.get("drain_event")
        with gen.with_threads(test["threads"]):
            while True:
                if self.abort.is_set():
                    raise WorkerAbort()
                if drain is not None and drain.is_set():
                    return          # run deadline: drain into teardown
                op = gen.op_and_validate(g, test, gen.NEMESIS)
                if op is None:
                    return
                op = to_op(op).assoc(process=gen.NEMESIS,
                                     time=relative_time_nanos())
                self._journal(op)
                tr = test.get("tracer")
                try:
                    if tr is not None and tr.enabled:
                        # same span discipline as the client workers
                        # (the trace.py docstring's "workers + nemesis")
                        with tr.span("nemesis/invoke", f=str(op.f)):
                            completion = self.nemesis.invoke(test, op)
                    else:
                        completion = self.nemesis.invoke(test, op)
                    completion = to_op(completion).assoc(
                        time=relative_time_nanos())
                except Exception as e:
                    if self.abort.is_set():
                        raise
                    log.warning("Nemesis crashed", exc_info=True)
                    completion = op.assoc(
                        type="info", time=relative_time_nanos(),
                        error=f"indeterminate: {e}")
                self._journal(completion)
                self.tele.event("nemesis", f=str(completion.f),
                                outcome=str(completion.type))

    def teardown_worker(self):
        if self.nemesis is not None:
            from jepsen_tpu import nemesis as nemesis_mod
            nemesis_mod.teardown(self.nemesis, self.test)


class Watchdog:
    """Worker watchdog + whole-run deadline (the tentpole's part 3).

    A monitor thread polls the client workers' journaling progress:

      * **Stall detection** — a worker whose in-flight op has not
        journaled a completion within `stall_budget_s` gets its
        per-op stall event fired; the worker's `_bounded_invoke` wait
        wakes, abandons the wedged invoker thread (cancel token set so
        cooperative clients retire), and journals the op `:info` — the
        standard indeterminate path then retires the wedged logical
        process id (+concurrency) and opens a fresh client, which is
        exactly Jepsen's process-crash semantics: a fresh logical
        process takes the slot, the old one stays crashed forever.
      * **Run deadline** — past `deadline_s` (measured from watchdog
        start) the drain event is set: workers stop drawing ops and
        fall through to teardown gracefully.  In-flight ops are given
        `drain_grace_s` to finish, then stall-cancelled so a wedged
        node cannot hold the run past its deadline.

    The watchdog itself journals nothing — the woken worker does — so
    there is no completion-race between monitor and worker."""

    def __init__(self, test, workers: list["ClientWorker"],
                 stall_budget_s: Optional[float] = None,
                 deadline_s: Optional[float] = None,
                 drain_grace_s: Optional[float] = None,
                 poll_s: float = 0.05):
        self.test = test
        self.workers = workers
        self.stall_budget_s = stall_budget_s
        self.deadline_s = deadline_s
        self.drain_grace_s = drain_grace_s if drain_grace_s is not None \
            else (stall_budget_s if stall_budget_s else 1.0)
        self.poll_s = poll_s
        self.stop_event = threading.Event()
        self.stalls = 0
        self.thread = threading.Thread(target=self._run, daemon=True,
                                       name="watchdog")

    def start(self):
        self.t0 = time_mod.monotonic()
        self.drained_at: Optional[float] = None
        self.thread.start()
        return self

    def stop(self):
        self.stop_event.set()
        self.thread.join(timeout=5)

    def _cancel(self, w: "ClientWorker", why: str):
        with w.progress_lock:
            op, cancel = w.inflight, w.stall_cancel
        if op is None or cancel is None or cancel.is_set():
            return
        log.warning("watchdog: retiring process %s (%s; op %s)",
                    op.process, why, op.f)
        self.stalls += 1
        from jepsen_tpu import telemetry as telemetry_mod
        telemetry_mod.of(self.test).event(
            "watchdog-stall", durable=True, process=op.process,
            f=str(op.f), why=why)
        cancel.set()

    def _run(self):
        drain = self.test.get("drain_event")
        while not self.stop_event.wait(self.poll_s):
            now = time_mod.monotonic()
            if (self.deadline_s is not None and drain is not None
                    and not drain.is_set()
                    and now - self.t0 > self.deadline_s):
                log.warning("watchdog: run deadline %.1fs reached; "
                            "draining workers into teardown",
                            self.deadline_s)
                drain.set()
                self.drained_at = now
            for w in self.workers:
                with w.progress_lock:
                    inflight = w.inflight
                    last = w.last_journal
                if inflight is None:
                    continue
                if (self.stall_budget_s is not None
                        and now - last > self.stall_budget_s):
                    self._cancel(w, f"stalled > {self.stall_budget_s}s")
                elif (self.drained_at is not None
                        and now - self.drained_at > self.drain_grace_s):
                    self._cancel(w, "run deadline drain")


def run_workers(workers: list[Worker], test=None) -> None:
    """Setup ∥, run ∥, teardown ∥ (core.clj run-workers! :171-197).  A
    worker failure aborts its peers (and breaks generator barriers), like
    the reference's real-pmap interrupt cascade."""

    def phase(fn_name):
        def call(w):
            try:
                getattr(w, fn_name)()
            except (WorkerAbort, gen.Aborted):
                pass
            except BaseException:
                if test is not None and "abort_event" in test:
                    test["abort_event"].set()
                for other in workers:
                    other.abort_worker()
                gen.abort_barriers()
                raise
        try:
            real_pmap(call, workers)
        except threading.BrokenBarrierError:
            # secondary casualty of an abort cascade; the primary error
            # already propagated from its own worker
            pass

    try:
        phase("setup_worker")
        phase("run_worker")
    except BaseException:
        # best-effort teardown that can't mask the original error
        real_pmap(fcatch(lambda w: w.teardown_worker()), workers)
        raise
    else:
        # teardown errors propagate (core.clj:190-196)
        real_pmap(lambda w: w.teardown_worker(), workers)


# ---------------------------------------------------------------------------
# Cases + analysis (core.clj:403-465)
# ---------------------------------------------------------------------------

def run_case(test) -> History:
    """Spawn nemesis + clients, run one case, return its history
    (core.clj:403-432).

    Crash-safety wiring: named tests journal every op write-through to
    the fsynced history WAL (store/<name>/<ts>/history.wal), so a
    SIGKILLed run can be rebuilt with history.recover; a watchdog
    monitors worker progress when stall_budget_s / deadline_s are set;
    and whatever faults the nemesis left outstanding (its worker may
    have died mid-fault) are reversed from the fault ledger on EVERY
    exit path — normal, deadline drain, watchdog, or exception."""
    from jepsen_tpu import telemetry as telemetry_mod
    wal = None
    if test.get("name") and test.get("start-time"):
        from jepsen_tpu import store
        from jepsen_tpu.history import HistoryWAL
        stream = test.get("live-stream")
        if stream:
            # one test-map key turns the WAL into a remote tenant:
            # every journaled frame also streams to a serve-checker
            # --listen daemon (live/client.py, docs/remote-ingest.md)
            from jepsen_tpu.live.client import StreamingWAL
            wal = StreamingWAL(store.make_path(test, "history.wal"),
                               stream, store._sanitize(test["name"]),
                               test["start-time"],
                               writer=test.get("live-stream-writer"),
                               telemetry=telemetry_mod.of(test))
        else:
            wal = HistoryWAL(store.make_path(test, "history.wal"),
                             telemetry=telemetry_mod.of(test))
    history = History(journal=True, wal=wal)  # columns build as ops
    lock = threading.RLock()                  # land, so analysis
    test["history"] = history                 # starts from arrays
    test["history_lock"] = lock
    with test["active_histories_lock"]:
        test["active_histories"].add((history, lock))
    watchdog = None
    if test.get("stall_budget_s") or test.get("deadline_s"):
        # setdefault: an orchestrator driving many runs (campaign.py)
        # may pre-seed the drain event so it can force a graceful
        # drain from OUTSIDE after core.run copied the test map
        test.setdefault("drain_event", threading.Event())
    try:
        nodes = test.get("nodes") or []
        n = test["concurrency"]
        client_nodes = [nodes[i % len(nodes)] if nodes else None
                        for i in range(n)]
        clients = [ClientWorker(test, i, node)
                   for i, node in enumerate(client_nodes)]
        workers = [NemesisWorker(test)] + clients
        if test.get("drain_event") is not None:
            watchdog = Watchdog(
                test, clients,
                stall_budget_s=test.get("stall_budget_s"),
                deadline_s=test.get("deadline_s"),
                drain_grace_s=test.get("drain_grace_s")).start()
        run_workers(workers, test)
    finally:
        if watchdog is not None:
            watchdog.stop()
        with test["active_histories_lock"]:
            test["active_histories"].discard((history, lock))
        _heal_outstanding_faults(test)
        if wal is not None:
            wal.close()
    return history


def _heal_outstanding_faults(test) -> None:
    """Reverse every fault still registered in the test's ledger
    (nemesis.FaultLedger) — the guaranteed-heal backstop for teardown
    paths where the nemesis worker itself died mid-fault.  Never
    raises: teardown must proceed, and a heal failure cannot be
    allowed to mask the run's primary error."""
    ledger = test.get("fault_ledger")
    if ledger is None or not ledger.outstanding():
        return
    log.warning("healing %d outstanding nemesis fault(s) from the "
                "ledger: %s", len(ledger.outstanding()),
                [k for k, _ in ledger.outstanding()])
    try:
        results = ledger.heal_all(test)
        for key, res in results.items():
            if isinstance(res, Exception):
                log.error("ledger heal of %r failed: %s", key, res)
    except Exception:
        log.error("fault-ledger heal failed", exc_info=True)


def analyze(test) -> dict:
    """Index the history, run the checker, write results
    (core.clj:434-451).

    Named tests get a verdict-checkpoint directory under their store
    dir passed through checker opts: runner-backed checkers
    (independent.batch_checker, Linearizable.check_many) append
    completed per-history verdicts there as they land, so re-running a
    killed analysis resumes instead of re-checking everything (see
    ops/runner.py and store.read_checkpoint)."""
    log.info("Analyzing...")
    history = test["history"]
    if not isinstance(history, History):   # keep the run's journal
        history = History(history)
    history = history.index()
    test["history"] = history
    opts: dict = {}
    if test.get("name") and test.get("start-time"):
        from jepsen_tpu import store
        opts["checkpoint_dir"] = str(store.path(test, "checkpoints"))
    t0 = time_mod.monotonic()
    test["results"] = checker_mod.check_safe(
        test["checker"], test, history, opts)
    # one durable marker per analysis: wall seconds + validity, so a
    # telemetry log alone anchors op-append -> verdict lag (the
    # campaign orchestrator's detection-lag buckets read this)
    from jepsen_tpu import telemetry as telemetry_mod
    telemetry_mod.of(test).event(
        "analyze", durable=True,
        seconds=round(time_mod.monotonic() - t0, 6),
        valid=(test["results"] or {}).get("valid?"))
    log.info("Analysis complete")
    if test.get("name"):
        from jepsen_tpu import store
        store.save_2(test)
    return test


def log_results(test) -> dict:
    """core.clj:453-465."""
    r = test.get("results") or {}
    ok = r.get("valid?") is True
    log.info("%s\n\n%s", r,
             "Everything looks good! ヽ(‘ー`)ノ" if ok
             else "Analysis invalid! (ﾉಥ益ಥ）ﾉ ┻━┻")
    return test


def run(test: dict) -> dict:
    """Run a complete test (core.clj run! :467-570): provision OS + DB
    over SSH, drive the generator through workers, collect the history,
    analyze, tear down.  Returns the test map with :history and
    :results."""
    test = dict(test)
    # lint: wall-ok(run id / store-dir stamp, operator-facing; ordering is per-op monotonic ns)
    test["start-time"] = __import__("datetime").datetime.now().isoformat()
    test.setdefault("concurrency", len(test.get("nodes") or []))
    nodes = test.get("nodes") or []
    test["barrier"] = threading.Barrier(len(nodes)) if nodes else NO_BARRIER
    test["active_histories"] = set()
    test["active_histories_lock"] = threading.Lock()
    # setdefault: a caller that keeps a handle on the event (the
    # campaign orchestrator's per-schedule quarantine) can abort a
    # wedged run from outside even though run() copied the test map
    test.setdefault("abort_event", threading.Event())
    from jepsen_tpu import nemesis as nemesis_mod
    test.setdefault("fault_ledger", nemesis_mod.FaultLedger())
    test["threads"] = gen.sort_processes(
        [gen.NEMESIS] + list(range(test["concurrency"])))

    if test.get("name"):
        from jepsen_tpu import store
        store.start_logging(test)
        # Write the test map BEFORE the run: a SIGKILLed run then
        # leaves test.json + history.wal behind, which is everything
        # `cli recover` needs to rebuild and re-analyze it.
        fcatch(store.write_test)(test)
    # Telemetry: always-on for named tests (test["telemetry"] = False
    # opts out).  The active scope lets code with no test in reach
    # (breakers, engine dispatch, the resilient runner) emit into this
    # run's event log for the duration of run + analysis.
    from jepsen_tpu import telemetry as telemetry_mod
    tele = telemetry_mod.for_test(test)
    test["telemetry"] = tele
    telemetry_mod.set_active(tele)
    test["fault_ledger"].telemetry = tele
    tele.event("run-start", durable=True, name=test.get("name"),
               start_time=test.get("start-time"),
               nodes=list(nodes), concurrency=test["concurrency"])
    from jepsen_tpu import trace as trace_mod
    tr = test["tracer"] = trace_mod.tracer(test)
    if tr.enabled and tele.enabled:
        # bridge spans into the telemetry event log, so ONE file tells
        # the whole story (trace.jsonl remains the standalone export)
        tr.set_sink(lambda m: tele.event("span", span=m))
    log.info("Running test: %s", test.get("name"))
    try:
        with control.with_ssh(test.get("ssh")):
            sessions = dict(zip(nodes, real_pmap(control.session, nodes)))
            test["sessions"] = sessions
            try:
                _with_os_db_run(test)
            finally:
                for s in sessions.values():
                    fcatch(s.close)()
                test.pop("sessions", None)
        log_results(test)
        return test
    finally:
        fcatch(tele.metrics_event)()
        fcatch(tele.event)("run-end", durable=True)
        fcatch(tele.close)()
        telemetry_mod.clear_active(tele)
        if test.get("name"):
            from jepsen_tpu import store
            store.stop_logging()


def _snarf_logs(test) -> None:
    """Download DB log files into the store (core.clj snarf-logs! :98)."""
    db = test.get("db")
    if not isinstance(db, db_mod.LogFiles) or not test.get("name"):
        return
    from jepsen_tpu import store

    def snarf(tst, node):
        for remote in db.log_files(tst, node):
            local = store.path(tst, node, remote.lstrip("/"))
            try:
                control.download(remote, str(local))
            except Exception:
                log.info("could not download %s from %s", remote, node)

    control.on_nodes(test, snarf)


def _with_os_db_run(test) -> None:
    os_obj = test.get("os")
    db_obj = test.get("db")
    try:
        if os_obj is not None:
            control.on_nodes(test, lambda t, n: os_obj.setup(t, n))
        try:
            if db_obj is not None:
                db_mod.cycle(test)
            _run_case_and_analyze(test)
        finally:
            _snarf_logs(test)
            if db_obj is not None:
                control.on_nodes(
                    test, fcatch(lambda t, n: db_obj.teardown(t, n)))
    finally:
        if os_obj is not None:
            control.on_nodes(test, fcatch(lambda t, n: os_obj.teardown(t, n)))


def _run_case_and_analyze(test) -> None:
    with with_relative_time():
        try:
            history = run_case(test)
            test["history"] = history
            for k in ("barrier",):
                test.pop(k, None)
            log.info("Run complete, writing")
            if test.get("name"):
                from jepsen_tpu import store
                store.save_1(test)
            analyze(test)
        finally:
            # span export rides the TEARDOWN path: a run that dies in
            # analysis still leaves trace.jsonl behind (and the export
            # itself must never mask the primary error)
            tr = test.get("tracer")
            if tr is not None:
                if test.get("name"):  # file export needs a store dir
                    fcatch(tr.write)(test)
                fcatch(tr.flush_http)()  # only needs an endpoint
