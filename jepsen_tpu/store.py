"""Persistence: tests, histories, and results on disk
(reference: `jepsen/src/jepsen/store.clj`).

Layout mirrors the reference's `store/<test-name>/<timestamp>/`
(store.clj:125-154) with JSON/JSONL in place of Fressian/EDN:

    store/<name>/<date>/
        test.json       serializable test map (save_1, store.clj:367)
        history.txt     TSV op log       (write-history! store.clj:346)
        history.jsonl   op records
        results.json    checker results  (save_2, store.clj:380)
        jepsen.log      per-test log     (start-logging! store.clj:398)
    store/<name>/latest -> <date>
    store/latest        -> <name>/<date>
    store/current       -> the running test's dir
"""

from __future__ import annotations

import datetime
import json
import logging
import os
import shutil
import threading
from pathlib import Path
from typing import Any, Optional

from jepsen_tpu.history import History

log = logging.getLogger("jepsen")

BASE = Path("store")

# Live, non-serializable runtime state stripped before writing
# (store.clj nonserializable-keys :167-175).
NONSERIALIZABLE_KEYS = {
    "db", "os", "net", "client", "checker", "nemesis", "generator", "model",
    "barrier", "active_histories", "active_histories_lock", "history_lock",
    "sessions", "remote", "store", "abort_event", "tracer",
    "fault_ledger", "drain_event", "telemetry",
}


def _sanitize(name: str) -> str:
    return name.replace("/", "_")


def test_dir(test) -> Path:
    return BASE / _sanitize(test["name"]) / test["start-time"]


def path(test, *components) -> Path:
    """Path inside the test's store directory (store.clj path :125)."""
    return test_dir(test).joinpath(*[str(c) for c in components])


def make_path(test, *components) -> Path:
    """path!: ensures parent directories exist (store.clj:149-154)."""
    p = path(test, *components)
    p.parent.mkdir(parents=True, exist_ok=True)
    return p


def _jsonable(x: Any):
    try:
        json.dumps(x)
        return x
    except (TypeError, ValueError):
        return repr(x)


def serializable_test(test) -> dict:
    out = {}
    for k, v in test.items():
        if k in NONSERIALIZABLE_KEYS or k == "history" or k == "results":
            continue
        out[k] = _jsonable(v)
    return out


# ---------------------------------------------------------------------------
# Writes (store.clj:340-392)
# ---------------------------------------------------------------------------

def write_results(test) -> None:
    p = make_path(test, "results.json")
    with open(p, "w") as f:
        json.dump(_jsonable_tree(test.get("results")), f, indent=2,
                  default=repr)


def _jsonable_tree(x):
    if isinstance(x, dict):
        return {str(k): _jsonable_tree(v) for k, v in x.items()}
    if isinstance(x, (list, tuple, set, frozenset)):
        return [_jsonable_tree(v) for v in x]
    return _jsonable(x)


def write_history(test) -> None:
    """Parallel txt + jsonl history writes (store.clj:346-357; the
    reference parallelizes chunks above 16384 ops, util.clj:184-206 —
    here both files stream in one pass each)."""
    h = History(test.get("history") or [])
    with open(make_path(test, "history.txt"), "w") as f:
        for op in h:
            f.write(str(op) + "\n")
    with open(make_path(test, "history.jsonl"), "w") as f:
        f.write(h.to_jsonl())


def write_test(test) -> None:
    with open(make_path(test, "test.json"), "w") as f:
        json.dump(serializable_test(test), f, indent=2, default=repr)


def save_1(test) -> dict:
    """Post-run phase 1: history + test (store.clj:367-378)."""
    write_test(test)
    write_history(test)
    update_symlinks(test)
    return test


def save_2(test) -> dict:
    """Post-analysis phase 2: results (store.clj:380-392)."""
    write_results(test)
    write_test(test)
    update_symlinks(test)
    return test


# ---------------------------------------------------------------------------
# Reads (store.clj:177-300)
# ---------------------------------------------------------------------------

def load(name: str, timestamp: str) -> dict:
    """Load a stored test map + history (store.clj load :177)."""
    d = BASE / _sanitize(name) / timestamp
    with open(d / "test.json") as f:
        test = json.load(f)
    hist_file = d / "history.jsonl"
    if hist_file.exists():
        test["history"] = History.from_jsonl(hist_file.read_text())
    results_file = d / "results.json"
    if results_file.exists():
        with open(results_file) as f:
            test["results"] = json.load(f)
    return test


def results_path(name: str, timestamp: str) -> Path:
    """Canonical location of a run's results.json (shared with web.py's
    cache key so layout changes stay in one place)."""
    return BASE / _sanitize(name) / timestamp / "results.json"


def load_results(name: str, timestamp: str) -> Optional[dict]:
    p = results_path(name, timestamp)
    if not p.exists():
        return None
    with open(p) as f:
        return json.load(f)


def tests(name: Optional[str] = None) -> dict:
    """Map of test-name -> {timestamp: loader} (store.clj tests :270)."""
    out: dict = {}
    if not BASE.exists():
        return out
    names = [name] if name else [p.name for p in BASE.iterdir()
                                 if p.is_dir() and p.name not in
                                 ("latest", "current", "campaigns",
                                  "ci", "plan-cache", "fleet",
                                  "ingest")]
    for n in names:
        d = BASE / _sanitize(n)
        if not d.is_dir():
            continue
        stamps = {}
        for ts in sorted(p.name for p in d.iterdir()
                         if p.is_dir() and p.name != "latest"):
            stamps[ts] = (lambda n=n, ts=ts: load(n, ts))
        out[n] = stamps
    return out


def latest() -> Optional[dict]:
    """Loads the latest test (store.clj latest :291-300)."""
    link = BASE / "latest"
    if link.is_symlink() or link.exists():
        d = link.resolve()
        return load(d.parent.name, d.name)
    best = None
    for n, stamps in tests().items():
        for ts in stamps:
            if best is None or ts > best[1]:
                best = (n, ts)
    return load(*best) if best else None


def update_symlinks(test) -> None:
    """current/latest symlinks (store.clj:302-328)."""
    d = test_dir(test)
    if not d.exists():
        return
    _relink(BASE / _sanitize(test["name"]) / "latest", Path(d.name))
    _relink(BASE / "latest", Path(_sanitize(test["name"])) / d.name)
    _relink(BASE / "current", Path(_sanitize(test["name"])) / d.name)


def _relink(link: Path, target: Path) -> None:
    link.parent.mkdir(parents=True, exist_ok=True)
    try:
        if link.is_symlink() or link.exists():
            if link.is_dir() and not link.is_symlink():
                shutil.rmtree(link)
            else:
                link.unlink()
        link.symlink_to(target)
    except OSError as e:  # filesystems without symlinks
        log.debug("could not update symlink %s: %s", link, e)


# ---------------------------------------------------------------------------
# Resumable verdict checkpoints (ops/runner.py)
# ---------------------------------------------------------------------------
#
# Layout: <checkpoint_dir>/verdicts.jsonl, one record per COMPLETED
# per-history verdict, appended (and flushed + fsynced) as each lands:
#
#     {"i": <batch index>, "digest": <history fingerprint>,
#      "verdict": {...}}
#
# A killed run leaves at worst one truncated trailing line, which
# read_checkpoint skips — every fully-written verdict survives and the
# re-run checks only the remainder.  For named tests the runner's
# checkpoint_dir defaults to store/<name>/<timestamp>/checkpoints/
# (core.analyze wires it through checker opts).

def checkpoint_path(checkpoint_dir) -> Path:
    """Canonical verdict-checkpoint file inside a checkpoint dir — one
    definition shared by the runner and anything inspecting store/."""
    return Path(checkpoint_dir) / "verdicts.jsonl"


def wal_path(test) -> Path:
    """Canonical location of a run's history WAL (history.HistoryWAL):
    store/<name>/<ts>/history.wal — one definition shared by the run
    loop, `history.recover`, and the CLI `recover` subcommand."""
    return path(test, "history.wal")


# ---------------------------------------------------------------------------
# Campaign ledgers (campaign.py)
# ---------------------------------------------------------------------------
#
# Layout: store/campaigns/<name>/{ledger.jsonl, coverage.json,
# status.json} — the crash-safe search-loop ledger (crc+seq frames,
# resumable), the canonical coverage matrix, and the operator status
# sidecar.  One definition shared by campaign.py, web.py's /campaign
# pages, and the CLI `campaign status` subcommand.

def campaigns_root() -> Path:
    return BASE / "campaigns"


# ---------------------------------------------------------------------------
# Fleet bookkeeping (live/lease.py, ISSUE 14)
# ---------------------------------------------------------------------------
#
# Layout: store/fleet/<worker-id>.json (atomic per-worker status
# sidecar) + store/fleet/<worker-id>.jsonl (the worker's own event
# log: lease-fenced refusals and other events about the WORKER rather
# than a tenant it may no longer own).  Excluded from tests() and run
# discovery like campaigns/ and ci/ — bookkeeping, never a test name.

def fleet_root() -> Path:
    return BASE / "fleet"


# ---------------------------------------------------------------------------
# Ingest-tier bookkeeping (live/ingest.py, ISSUE 16)
# ---------------------------------------------------------------------------
#
# Layout: store/ingest/<server-id>.json (atomic status sidecar, carries
# the bound port) + store/ingest/<server-id>.jsonl (the server's event
# journal: fenced registrations, torn/dup/reordered frames, pause/
# resume) + store/ingest/<name>/<ts>/lease.json (WRITER registration
# leases — distinct from the checker's run-dir lease).  Excluded from
# tests() and run discovery like fleet/ and campaigns/.

def ingest_root() -> Path:
    return BASE / "ingest"


def campaign_dir(name: str) -> Path:
    d = campaigns_root() / _sanitize(name)
    d.mkdir(parents=True, exist_ok=True)
    return d


def append_checkpoint(path, record: dict) -> None:
    """Append one JSON record and force it to disk: a verdict is only
    a checkpoint if it survives a kill -9 mid-batch."""
    p = Path(path)
    p.parent.mkdir(parents=True, exist_ok=True)
    line = json.dumps(_jsonable_tree(record), default=repr)
    with open(p, "a") as f:
        f.write(line + "\n")
        f.flush()
        os.fsync(f.fileno())


def read_checkpoint(path) -> list[dict]:
    """All parseable records; a truncated final line (killed mid-write)
    is skipped rather than poisoning the resume."""
    p = Path(path)
    if not p.exists():
        return []
    out = []
    with open(p) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except json.JSONDecodeError:
                continue
    return out


# ---------------------------------------------------------------------------
# Logging (store.clj:394-422)
# ---------------------------------------------------------------------------

_log_lock = threading.Lock()
_handlers: list[logging.Handler] = []


def start_logging(test) -> None:
    """Per-test jepsen.log file + console (store.clj start-logging!)."""
    with _log_lock:
        stop_logging_unlocked()
        test.setdefault("start-time",
                        # lint: wall-ok(store-dir name, operator-facing)
                        datetime.datetime.now().strftime("%Y%m%dT%H%M%S"))
        logfile = make_path(test, "jepsen.log")
        fh = logging.FileHandler(logfile)
        fh.setFormatter(logging.Formatter(
            "%(asctime)s{%(threadName)s} %(levelname)s %(name)s - "
            "%(message)s"))
        root = logging.getLogger("jepsen")
        root.setLevel(
            getattr(logging, (test.get("logging") or {}).get(
                "level", "INFO").upper(), logging.INFO))
        root.addHandler(fh)
        _handlers.append(fh)


def stop_logging_unlocked() -> None:
    root = logging.getLogger("jepsen")
    while _handlers:
        h = _handlers.pop()
        root.removeHandler(h)
        h.close()


def stop_logging() -> None:
    with _log_lock:
        stop_logging_unlocked()
