"""Deep-overlap linearizability megakernel — one Pallas program walks
the whole history with the frontier resident in VMEM.

Scope: the regime the reference's own tutorial names as THE cost cliff
— many simultaneously-open calls ("the search is exponential in the
number of concurrent operations", `doc/tutorial/06-refining.md:7-10`;
"difficulty goes like ~n!", `doc/tutorial/07-parameters.md:148-152`).
The segment engine (`ops.wgl_seg`) covers shallow overlap (R <= 6 on
the register-delta kernel); beyond that its candidate-table fallback
walks a dense 2^R config plane as *hundreds of XLA ops per event*, and
on a latency-bound chip the per-op dispatch overhead — not FLOPs —
made one C core 20-118x faster at R = 8-10 (BENCH_r03).

This module removes the dispatch overhead instead of the plane: the
frontier is a bit-packed boolean tensor `fr[Sn, 2^R / 32]` uint32
(state rows x mask words — a few KB even at R = 14), held in VMEM
scratch for the entire event walk.  One `pl.pallas_call` processes the
whole history: the grid streams fixed-size event blocks into SMEM, and
each event is ~a hundred vector instructions on 1-8 vregs, with no
XLA op boundaries, no scan carry round-trips, and a closure
`while_loop` whose early exit costs one on-core reduction instead of a
host-visible sync.

Semantics are just-in-time linearization, identical to `ops.wgl` /
`ops.wgl_seg` (Lowe / knossos :linear, `checker.clj:141-145`):

  * at the return of call t, configurations lacking t are closed under
    linearizing any currently-open call (to fixpoint — expansion
    sources are restricted to configs still lacking t, exact by the
    deferral argument in `ops.wgl._build_kernel`), then pruned to
    those containing t, and t's slot is retired;
  * a *pure* returning op (never changes state, e.g. a read) that is
    directly legal on every config still lacking it short-circuits the
    closure entirely — the same fast path as `ops.wgl`, and the common
    case for register workloads;
  * fixpoint in <= R rounds (round k unions every config reachable by
    <= k linearizations; at most R calls are open — the exactness
    argument of `wgl_seg._build_kernel_bits`).

Crashed (:info) calls cost NOTHING structurally here: a crashed call
is an open slot that never returns (registered, never retired), and
the 2^R plane *is* the powerset of open calls — so any history with
`max_open_normal + n_crashed <= R_MAX` is checked exactly, where the
reference's knossos "can make the difference between seconds and days"
on a couple of crashed processes (`doc/tutorial/06-refining.md:12-19`).

Verdicts are exact in both directions (the plane has no capacity to
overflow).  On invalid, the kernel reports the exact failing event; the
host maps it to the returning op — the same witness `ops.wgl_cpu`
reports (differentially tested).

Transition model: the diagonal + rank-1 decomposition of
`wgl_seg._decompose` (each op either keeps the state or sends every
legal state to ONE target) with Sn <= 32 states — the whole register /
cas / mutex family.  Out-of-scope models keep their existing engines.
"""

from __future__ import annotations

import functools
import os
import time
from typing import Any

import numpy as np

from jepsen_tpu.errors import BackendUnavailable, CheckError

# Intra-word "lacks bit b" patterns: bit i set iff mask-index i has
# bit b clear (shared constant with ops.frontier._INTRA).
_INTRA = (0x55555555, 0x33333333, 0x0F0F0F0F, 0x00FF00FF, 0x0000FFFF)
_FULL = 0xFFFFFFFF

from jepsen_tpu.ops import planner

R_MAX = planner.DEEP_R_MAX   # 2^14-mask plane = [Sn, 512] words; past
                             # this the plane outgrows the VPU's appetite
EB = 512            # event rows per grid step (SMEM block budget)


def supported(R: int, Sn: int, U: int, decomposed: bool,
              backend: str) -> bool:
    """Gate shared with the wgl_seg dispatcher — now owned by the one
    engine planner (`planner.deep_supported`, ISSUE 8) so the routing
    decision and this kernel's self-description cannot drift; kept as
    a thin delegate for the long-standing callers.  See
    planner.deep_supported for the scope and the
    JEPSEN_TPU_DEEP_INTERPRET backend-capability semantics."""
    return planner.deep_supported(R, Sn, U, decomposed, backend)


def _snp(Sn: int) -> int:
    return 8 if Sn <= 8 else 16 if Sn <= 16 else 32


@functools.lru_cache(maxsize=32)
def _build(G: int, I: int, Wd: int, SnP: int, R: int, UP: int,
           interpret: bool):
    """kern(evbuf i32[G, EB*(1+2I)], auxbuf u32[1, 3*UP+16])
    -> i32[1, 2] (alive, first-dead-row | -1).

    evbuf row layout per event row r of a block:
      [r]                      return slot (-1 = registration-only row)
      [EB + r*I + i]           newly-invoked slot i (-1 = none)
      [EB + EB*I + r*I + i]    its uop index
    auxbuf: diag-mask[UP] ++ const-mask[UP] ++ t0[UP] ++ intra[16]
    (intra[b] = lacks-bit-b pattern for b < 5, FULL above — so the
    dynamic-slot target pattern needs no per-bit dispatch)."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    u32 = jnp.uint32
    EBW = EB * (1 + 2 * I)

    def popsum(x):
        return jax.lax.population_count(x).astype(jnp.int32).sum()

    def msk(cond):
        return jnp.where(cond, jnp.asarray(np.uint32(_FULL), u32),
                         jnp.asarray(np.uint32(0), u32))

    # static per-slot patterns over [SnP, Wd]
    def lackpat(b, l_iota):
        """FULL where the mask index lacks slot bit b."""
        if b < 5:
            return jnp.full((SnP, Wd), np.uint32(_INTRA[b]), u32)
        return msk(((l_iota >> (b - 5)) & 1) == 0)

    def shift_set(x, b):
        """Move configs (already masked to bit-b-clear) to mask|bit."""
        if b < 5:
            return x << (1 << b)
        d = 1 << (b - 5)
        return jnp.concatenate(
            [jnp.zeros((SnP, d), u32), x[:, :Wd - d]], axis=1)

    def shift_unset(x, b):
        """Move configs (already masked to bit-b-set) to mask&~bit."""
        if b < 5:
            return x >> (1 << b)
        d = 1 << (b - 5)
        return jnp.concatenate(
            [x[:, d:], jnp.zeros((SnP, d), u32)], axis=1)

    def or_rows(x):
        """OR-fold over the state (sublane) axis, broadcast back."""
        sh = 1
        while sh < SnP:
            x = x | jnp.roll(x, sh, axis=0)
            sh *= 2
        return x

    # LAZY BIT RETIREMENT: retiring a slot never shifts the plane.  A
    # vacant slot's bit carries no information, so the prune at a
    # return keeps the linearized (bit-set) configs AND LEAVES THE BIT
    # SET — one AND, no cross-lane shift (the hardware only supports
    # those at static amounts).  The obligation moves to registration:
    # when a slot is (re)occupied, the two bit-halves are merged onto
    # the bit-clear side (exact — configs differing only in a
    # meaningless bit are the same config), so every occupant starts
    # from uniform bit 0.  First occupancy merges an all-zero half
    # (identity); crashed slots are registered once and never retired.

    def kernel(ev_ref, aux_ref, out_ref, fr,
               a1r, a2r, t0r, openr, flags):
        g = pl.program_id(0)
        s_iota = jax.lax.broadcasted_iota(jnp.int32, (SnP, Wd), 0)
        l_iota = jax.lax.broadcasted_iota(jnp.int32, (SnP, Wd), 1)

        @pl.when(g == 0)
        def _init():
            # initial state is index 0 (interned first) at mask 0
            fr[...] = jnp.where((s_iota == 0) & (l_iota == 0),
                                jnp.asarray(np.uint32(1), u32),
                                jnp.asarray(np.uint32(0), u32))
            for b in range(R):
                a1r[b] = jnp.uint32(0)
                a2r[b] = jnp.uint32(0)
                t0r[b] = 0
                openr[b] = 0
            flags[0] = 0
            flags[1] = -1

        def slot_pattern(sl):
            """Lacks-bit-sl pattern for a DYNAMIC slot: intra-word part
            from the aux table tail, word part from the lane index."""
            ipat = aux_ref[0, 3 * UP + sl]
            sh = jnp.maximum(sl - 5, 0)
            wsel = (sl < 5) | (((l_iota >> sh) & 1) == 0)
            return jnp.where(wsel, ipat, jnp.asarray(np.uint32(0), u32))

        def expand_round(ltpv):
            """One Gauss-Seidel closure round: per open slot, linearize
            it on every config still lacking the target, accumulating
            straight into fr — later slots see earlier slots' children
            within the same round, so chains resolve in fewer rounds
            (monotone union either way; same fixpoint)."""
            for b in range(R):
                @pl.when(openr[b] == 1)
                def _(b=b):
                    f0 = fr[...]
                    src = (f0 & ltpv) & lackpat(b, l_iota)
                    a1b = a1r[b]
                    a2b = a2r[b]
                    dsel = msk(((a1b >> s_iota.astype(u32))
                                & jnp.uint32(1)) == 1)
                    moved = src & dsel
                    csel = msk(((a2b >> s_iota.astype(u32))
                                & jnp.uint32(1)) == 1)
                    red = or_rows(src & csel)
                    moved = moved | (red & msk(s_iota == t0r[b]))
                    fr[...] = f0 | shift_set(moved, b)

        def event(r, carry):
            @pl.when(flags[0] == 0)
            def _ev():
                # --- register the row's new invokes -------------------
                for i in range(I):
                    sl = ev_ref[0, 0, EB + r * I + i]

                    @pl.when(sl >= 0)
                    def _reg():
                        u = ev_ref[0, 0, EB + EB * I + r * I + i]
                        a1r[sl] = aux_ref[0, u]
                        a2r[sl] = aux_ref[0, UP + u]
                        t0r[sl] = aux_ref[0, 2 * UP + u].astype(jnp.int32)
                        openr[sl] = 1
                        # lazy-retirement merge: normalize the slot's
                        # (meaningless) bit to 0 across the plane
                        lp = slot_pattern(sl)
                        frv_i = fr[...]
                        low = frv_i & lp
                        high = frv_i & ~lp

                        @pl.when(sl < 5)
                        def _intra():
                            fr[...] = low | (
                                high >> (jnp.uint32(1)
                                         << jnp.minimum(sl, 4)
                                         .astype(u32)))

                        for b in range(5, R):
                            @pl.when(sl == b)
                            def _(b=b):
                                fr[...] = low | shift_unset(high, b)

                rs = ev_ref[0, 0, r]

                @pl.when(rs >= 0)
                def _ret():
                    # closure to fixpoint with early exit; a pure op
                    # directly legal on every lacking config is the
                    # identity on the plane (set-then-lazy-retire moves
                    # nothing) and cannot empty the frontier.
                    ltpv = slot_pattern(rs)
                    a2t = a2r[rs]
                    frv = fr[...]
                    lt = frv & ltpv
                    a1t = a1r[rs]
                    dselt = msk(((a1t >> s_iota.astype(u32))
                                 & jnp.uint32(1)) == 1)
                    n_lt = popsum(lt)
                    n_ill = popsum(lt & ~dselt)
                    fast = (a2t == jnp.uint32(0)) & (n_ill == 0)

                    @pl.when(jnp.logical_not(fast))
                    def _slow():
                        def cond(c):
                            prog, _, lack = c
                            return prog & (lack > 0)

                        def body(c):
                            _, prev, _ = c
                            expand_round(ltpv)
                            f1 = fr[...]
                            cnt = popsum(f1)
                            lack = popsum(f1 & ltpv)
                            return cnt > prev, cnt, lack

                        _, cnt, lack = jax.lax.while_loop(
                            cond, body,
                            (jnp.bool_(True), jnp.int32(-1), n_lt))
                        # prune configs that never linearized rs (bit
                        # stays set -- lazy retirement); the death test
                        # is FREE: the pruned count is cnt - lack from
                        # the last closure round
                        fr[...] = fr[...] & ~ltpv

                        @pl.when((cnt >= 0) & (cnt == lack))
                        def _dead():
                            flags[0] = 1
                            flags[1] = g * EB + r

                    openr[rs] = 0

            return carry

        jax.lax.fori_loop(0, EB, event, 0)
        out_ref[0, 0] = 1 - flags[0]
        out_ref[0, 1] = flags[1]

    # Version-drift shim (same class, renamed across Pallas releases:
    # TPUCompilerParams on older jax, CompilerParams on newer) — the
    # kernel must degrade across the drift, not AttributeError
    # (ADVICE r5's check_vma lesson, applied to the whole build path).
    _params_cls = getattr(pltpu, "CompilerParams", None) \
        or getattr(pltpu, "TPUCompilerParams")

    def kern(evbuf, auxbuf):
        return pl.pallas_call(
            kernel,
            grid=(G,),
            in_specs=[
                # 3D with a unit middle axis: Mosaic requires the
                # block's last two dims to equal the array's
                pl.BlockSpec((1, 1, EBW), lambda g: (g, 0, 0),
                             memory_space=pltpu.SMEM),
                pl.BlockSpec((1, 3 * UP + 16), lambda g: (0, 0),
                             memory_space=pltpu.SMEM),
            ],
            out_specs=pl.BlockSpec((1, 2), lambda g: (0, 0),
                                   memory_space=pltpu.SMEM),
            out_shape=jax.ShapeDtypeStruct((1, 2), np.int32),
            scratch_shapes=[
                pltpu.VMEM((SnP, Wd), np.uint32),   # fr
                pltpu.SMEM((R,), np.uint32),        # a1r
                pltpu.SMEM((R,), np.uint32),        # a2r
                pltpu.SMEM((R,), np.int32),         # t0r
                pltpu.SMEM((R,), np.int32),         # openr
                pltpu.SMEM((2,), np.int32),         # flags
            ],
            compiler_params=_params_cls(
                dimension_semantics=("arbitrary",)),
            interpret=interpret,
        )(evbuf, auxbuf)

    return jax.jit(kern)


def _pad_g(g: int) -> int:
    """Grid-size bucketing (compiled-shape control): pow2 to 16, then
    8-multiples."""
    if g <= 1:
        return 1
    b = 1
    while b < g and b < 16:
        b *= 2
    return b if g <= 16 else ((g + 7) // 8) * 8


def pack_events(ret_t: np.ndarray, islot_t: np.ndarray,
                iuop_t: np.ndarray) -> tuple[np.ndarray, int]:
    """[Lp, 1] + [Lp, 1, I] register-delta tables (wgl_seg._pack_regs
    with K=1) -> (evbuf i32[G, EB*(1+2I)], G)."""
    Lp = ret_t.shape[0]
    I = islot_t.shape[2]
    G = _pad_g((Lp + EB - 1) // EB)
    L2 = G * EB
    ret = np.full(L2, -1, np.int32)
    ret[:Lp] = ret_t[:, 0]
    islot = np.full((L2, I), -1, np.int32)
    islot[:Lp] = islot_t[:, 0, :]
    iuop = np.zeros((L2, I), np.int32)
    iuop[:Lp] = iuop_t[:, 0, :]
    evbuf = np.concatenate(
        [ret.reshape(G, EB),
         islot.reshape(G, EB * I),
         iuop.reshape(G, EB * I)], axis=1)
    return np.ascontiguousarray(evbuf[:, None, :]), G


def pack_events_compact(ret_t: np.ndarray, islot_t: np.ndarray,
                        iuop_t: np.ndarray,
                        g_min: int = 1) -> tuple[np.ndarray, int]:
    """Compact wire twin of pack_events: the same event stream as a
    uint8 buffer — ret+1 u8[L2] (0 = the -1 sentinel; slot+1 <= R_MAX
    +1 = 15) ++ islot+1 u8[L2*I] ++ iuop u16-LE bytes[2*L2*I] — ~3.6x
    fewer bytes than the int32 form at I=2, rebuilt into the kernel's
    evbuf on device by _build_c's unpack prologue.  Padding iuops are
    clamped to 0: the kernel reads a row's uop only where its islot
    >= 0 (registration gate), so the clamp is unobservable.  `g_min`
    lets check_mesh pack a whole batch at one common grid size (the
    sentinel rows are exact no-ops)."""
    Lp = ret_t.shape[0]
    I = islot_t.shape[2]
    G = max(_pad_g((Lp + EB - 1) // EB), g_min)
    L2 = G * EB
    ret = np.zeros(L2, np.uint8)
    ret[:Lp] = (ret_t[:, 0].astype(np.int32) + 1).astype(np.uint8)
    islot = np.zeros((L2, I), np.uint8)
    islot[:Lp] = (islot_t[:, 0, :].astype(np.int32) + 1).astype(
        np.uint8)
    iuop = np.zeros((L2, I), np.uint16)
    iuop[:Lp] = np.maximum(
        iuop_t[:, 0, :].astype(np.int32), 0).astype(np.uint16)
    return np.concatenate([ret, islot.ravel(),
                           iuop.ravel().view(np.uint8)]), G


@functools.lru_cache(maxsize=32)
def _build_c(G: int, I: int, Wd: int, SnP: int, R: int, UP: int,
             interpret: bool):
    """Compact-wire wrapper around _build: jit-unpacks the uint8 event
    buffer of pack_events_compact back into the int32 evbuf on device
    (a few fused casts/reshapes, free next to the event walk) and runs
    the megakernel — the tunnel carries the compact form."""
    import jax
    import jax.numpy as jnp

    kern = _build(G, I, Wd, SnP, R, UP, interpret)
    L2 = G * EB

    def fn(cbuf, auxbuf):
        ret = cbuf[:L2].astype(jnp.int32) - 1
        isl = cbuf[L2:L2 * (1 + I)].astype(jnp.int32) - 1
        pairs = cbuf[L2 * (1 + I):].reshape(L2 * I, 2)
        iu = (pairs[:, 0].astype(jnp.int32)
              | (pairs[:, 1].astype(jnp.int32) << 8))
        evbuf = jnp.concatenate(
            [ret.reshape(G, EB),
             isl.reshape(G, EB * I),
             iu.reshape(G, EB * I)], axis=1)[:, None, :]
        return kern(evbuf, auxbuf)

    return jax.jit(fn)


def pack_aux(a1t: np.ndarray, a2t: np.ndarray, t0t: np.ndarray,
             UP: int) -> np.ndarray:
    """[U] uop tables (wgl_seg._pack_uop_tables) -> u32[1, 3*UP+16]."""
    U = a1t.shape[0]
    aux = np.zeros((1, 3 * UP + 16), np.uint32)
    aux[0, :U] = a1t
    aux[0, UP:UP + U] = a2t
    aux[0, 2 * UP:2 * UP + U] = t0t.astype(np.uint32)
    for b in range(16):
        aux[0, 3 * UP + b] = _INTRA[b] if b < 5 else _FULL
    return aux


def _pad_u(u: int) -> int:
    b = 8
    while b < u:
        b *= 2
    return b


def dispatch_tables(ret_t, islot_t, iuop_t, a1t, a2t, t0t,
                    R: int, Sn: int, stats=None):
    """Asynchronously dispatch the deep kernel on pre-packed
    register-delta tables; returns the UN-FETCHED i32[1, 2] device
    verdict (alive, first-dead-row | -1).  On the tunneled chip a
    result fetch costs a fixed round trip that bounds any single-shot
    check from below (bench.py's north-star decomposition), so
    steady-state callers dispatch many histories back-to-back and
    fetch once — the same pipelined formulation wgl_seg.check_pipeline
    uses."""
    import jax

    backend = jax.default_backend()
    if backend not in ("tpu", "cpu"):
        raise BackendUnavailable(
            f"no deep-kernel lowering for {backend}", backend=backend)
    I = islot_t.shape[2]
    UP = _pad_u(a1t.shape[0])
    cbuf, G = pack_events_compact(ret_t, islot_t, iuop_t)
    auxbuf = pack_aux(a1t, a2t, t0t, UP)
    if stats is not None:           # measured wire traffic (telemetry)
        stats["wire_bytes"] = (stats.get("wire_bytes", 0)
                               + cbuf.nbytes + auxbuf.nbytes)
    Wd = max(1, (1 << R) // 32)
    kern = planner.compiled(
        "wgl_deep", (G, I, Wd, _snp(Sn), R, UP, backend),
        _build_c, G, I, Wd, _snp(Sn), R, UP,
        interpret=(backend == "cpu"))
    return kern(cbuf, auxbuf), G


def check_tables(ret_t, islot_t, iuop_t, a1t, a2t, t0t,
                 R: int, Sn: int) -> dict[str, Any]:
    """Run the deep kernel on pre-packed register-delta tables and
    fetch the verdict.  Returns {"valid?": bool, "failed_row":
    int | None, ...}; failed_row indexes ret_t's rows (callers map it
    to the returning op)."""
    t1 = time.monotonic()
    dev, G = dispatch_tables(ret_t, islot_t, iuop_t, a1t, a2t, t0t,
                             R, Sn)
    out = np.asarray(dev)
    alive = bool(out[0, 0])
    return {"valid?": alive,
            "failed_row": None if alive else int(out[0, 1]),
            "time_kernel_s": time.monotonic() - t1,
            "grid": G}


def map_witness(ret_t, fk, ops, failed_row):
    """Map a kernel-reported failing event row to the failing call's
    INVOKE op — the witness the oracle names (differentially pinned).
    Returns (op, op_index, return_position) or None when the scan
    carried no positions (pure-Python crash scans).  The ONE
    definition, shared by wgl_seg._check_deep and check_pipeline so
    the padded-row -> return-ordinal -> op arithmetic cannot drift."""
    if failed_row is None or fk.positions is None \
            or not len(fk.positions):
        return None
    ordinal = int((ret_t[:failed_row + 1, 0] >= 0).sum()) - 1
    if not (0 <= ordinal < len(fk.positions)):
        return None
    pos = int(fk.positions[ordinal])
    p = ops[pos].process
    inv = pos
    while inv >= 0 and not (ops[inv].process == p
                            and ops[inv].type == "invoke"):
        inv -= 1
    op = ops[max(inv, 0)]
    return op, (op.index if op.index is not None else max(inv, 0)), pos


def check_pipeline(model, histories, *, max_open_bits: int = 14,
                   max_states: int = 64, stats=None) -> list:
    """Steady-state deep-overlap checking: scan + pack every history on
    host, dispatch ALL kernels asynchronously, stack the [1, 2]
    verdicts ON DEVICE and fetch them in ONE round trip — the tunnel's
    fixed D2H latency bounds any single-shot check from below
    (bench.py's north-star decomposition), and this amortizes it over
    the batch exactly like wgl_seg.check_pipeline does for the shallow
    regime.  Verdict-identical to wgl_seg.check per history
    (differential battery).

    Histories OUTSIDE the deep kernel's scope (R > R_MAX, crashed
    scans, undecomposable growth) do not poison the batch: they ride
    as stragglers through wgl_seg.check's own fallback chain after the
    in-scope verdicts are fetched — the same pattern as
    wgl_seg.check_pipeline's straggler path, so a mixed-depth batch
    (e.g. one R = 15 history among R <= 14 ones) still returns one
    correct verdict per history.

    `stats`, when given a dict, receives the per-stage host-time
    decomposition (scan / tables / pack / dispatch / fetch / assemble
    seconds), mirroring wgl_seg.check_pipeline's."""
    import jax

    from jepsen_tpu.ops import wgl_seg

    spec = model.device_spec()
    if spec is None:
        raise BackendUnavailable(f"model {model!r} has no device spec")
    stats = {} if stats is None else stats   # always collected now
    _mt, _acc = wgl_seg._stats_clock(stats)
    backend = jax.default_backend()
    pend = []
    strag = []
    results: list = [None] * len(histories)
    # shared interning across the batch: state enumeration, the
    # decomposition, and the uop tables are (re)built only when a
    # history grows the alphabet — not once per history
    seen: dict = {}
    rows: list = []
    U_at = -1
    Sn = 0
    tables = None            # (a1t, a2t, t0t)
    init = np.asarray(spec.encode(model), np.int32)
    for i, h in enumerate(histories):
        ops = h.ops
        t0 = _mt()
        fk = wgl_seg._scan_history(h, ops, spec, seen, rows,
                                   max_open_bits, want_snaps=False)
        t0 = _acc("scan", t0)
        if not fk:
            strag.append(i)
            continue
        R = int(fk.max_open)
        if len(rows) != U_at:
            uops = np.asarray(rows, np.int32).reshape(len(rows), 4)
            try:
                states, legal, next_state = wgl_seg._enumerate_states(
                    spec, init, uops, max_states)
            except wgl_seg.Unsupported:
                # the alphabet (and with it the state space) only
                # grows: everything from here on is a straggler —
                # already-dispatched in-scope verdicts stay valid
                strag.extend(range(i, len(histories)))
                break
            Sn = states.shape[0]
            dw, cw, t0c = wgl_seg._decompose(legal, next_state)
            if dw is None:
                # undecomposable models only grow less decomposable
                strag.extend(range(i, len(histories)))
                break
            tables = wgl_seg._pack_uop_tables(legal, next_state,
                                              dw, cw, t0c)
            U_at = len(rows)
        t0 = _acc("tables", t0)
        if not supported(R, Sn, len(rows), True, backend):
            strag.append(i)           # e.g. R > R_MAX: serial fallback
            continue
        I = min(2, R) if R else 1
        if fk.deltas is not None:
            ret_t, islot_t, iuop_t, _ = wgl_seg._pack_regs_single(
                fk, [fk.n_rets], R, len(rows), I)
        else:
            ret_t, islot_t, iuop_t, _ = wgl_seg._pack_regs(
                [(0, fk)], 1, R, len(rows), I)
        a1t, a2t, t0t = tables
        t0 = _acc("pack", t0)
        dev, G = dispatch_tables(ret_t, islot_t, iuop_t, a1t, a2t,
                                 t0t, R, Sn, stats=stats)
        _acc("dispatch", t0)
        pend.append((dev, i, fk, ret_t, ops, R, Sn, G))

    if pend:
        t0 = _mt()
        stacked = wgl_seg._build_stack(len(pend))(
            *[d for d, *_ in pend])
        outs = np.asarray(stacked)                    # ONE fetch
        t0 = _acc("fetch", t0)
        for j, (dev, i, fk, ret_t, ops, R, Sn_i, G) in enumerate(pend):
            alive = bool(outs[j, 0, 0])
            res = {"valid?": alive, "op_count": fk.n_calls,
                   "backend": backend, "engine": "wgl_deep",
                   "max_open": R, "states": Sn_i, "pipelined": True}
            if not alive:
                res["anomaly"] = "nonlinearizable"
                w = map_witness(ret_t, fk, ops, int(outs[j, 0, 1]))
                if w is not None:
                    res["op"] = w[0].to_dict()
                    res["op_index"] = w[1]
            results[i] = res
        _acc("assemble", t0)
    # in-scope verdicts carry the deep pipeline's plan + stage
    # decomposition BEFORE the stragglers run, so the serial chain's
    # verdicts keep their own engines' records
    from jepsen_tpu import telemetry as telemetry_mod
    R_pend = max(p[5] for p in pend) if pend else 0
    pipe_plan = planner.plan_engines(
        planner.Shape(kind="deep-pipeline", R=R_pend,
                      Sn=Sn or None, U=len(rows) or None,
                      decomposed=True, batch=len(histories),
                      max_states=max_states,
                      max_open_bits=max_open_bits),
        backend=backend)
    telemetry_mod.attach_dispatch(
        results,
        pipe_plan.record(engine="wgl_deep",
                         R=R_pend or None,
                         batch=len(histories),
                         stragglers=len(strag) or None),
        stages=stats)
    for i in strag:
        try:
            results[i] = wgl_seg.check(model, histories[i],
                                       max_states=max_states,
                                       max_open_bits=max_open_bits)
        except wgl_seg.Unsupported:
            # beyond every batched gate (e.g. R > R_MAX): the serial
            # frontier engine has no overlap-depth limit
            from jepsen_tpu.ops import wgl
            results[i] = wgl.check(model, histories[i])
            telemetry_mod.attach_dispatch(
                [results[i]],
                telemetry_mod.dispatch_record(
                    results[i].get("engine", "wgl"),
                    why="deep straggler beyond every batched gate "
                        "(serial frontier engine)",
                    fallback_chain=["wgl_cpu"], batch=1))
    return results


def check_mesh(model, histories, mesh, *, mesh_axis: str = "hists",
               max_open_bits: int = R_MAX,
               max_states: int = 64) -> list:
    """Deep-overlap scale-out over a jax.sharding.Mesh: one history
    per device (SURVEY.md §2.5).  The megakernel is a single device
    program per history, so the mesh strategy is the embarrassingly
    parallel one — every history's packed event buffer is padded to
    one common grid shape, stacked on a leading axis sharded over
    `mesh_axis`, and shard_map runs the kernel once per device with NO
    collectives (verdicts are independent; the [D, 2] output gathers
    on fetch).  Grid-padding rows are ret = -1 / islot = -1 no-op rows
    — exact, as in the pipelined path.  Verdict-identical to
    check_pipeline per history; histories must all be in deep scope
    (callers route stragglers through check_pipeline instead)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec

    from jepsen_tpu.ops import shard_map_compat, wgl_seg

    spec = model.device_spec()
    if spec is None:
        raise BackendUnavailable(f"model {model!r} has no device spec")
    backend = jax.default_backend()
    n_dev = int(np.prod(mesh.devices.shape))
    if len(histories) != n_dev:
        raise CheckError(f"one history per device: got "
                         f"{len(histories)} histories, {n_dev} devices",
                         batch_size=len(histories), backend=backend)
    seen: dict = {}
    rows: list = []
    init = np.asarray(spec.encode(model), np.int32)
    fks = []
    for d, h in enumerate(histories):
        fk = wgl_seg._scan_history(h, h.ops, spec, seen, rows,
                                   max_open_bits, want_snaps=False)
        if not fk:
            raise CheckError("history out of deep-kernel scope (scan)",
                             history_index=d, backend=backend)
        fks.append(fk)
    uops = np.asarray(rows, np.int32).reshape(len(rows), 4)
    states, legal, next_state = wgl_seg._enumerate_states(
        spec, init, uops, max_states)
    Sn = states.shape[0]
    dw, cw, t0c = wgl_seg._decompose(legal, next_state)
    if dw is None:
        raise CheckError("model not decomposable", backend=backend)
    a1t, a2t, t0t = wgl_seg._pack_uop_tables(legal, next_state,
                                             dw, cw, t0c)
    R = max(int(fk.max_open) for fk in fks)
    if not supported(R, Sn, len(rows), True, backend):
        raise CheckError(
            f"batch out of deep-kernel scope (R={R}, Sn={Sn})",
            backend=backend)
    I = min(2, R) if R else 1
    UP = _pad_u(a1t.shape[0])
    auxbuf = pack_aux(a1t, a2t, t0t, UP)
    tabs, rets = [], []
    for fk in fks:
        if fk.deltas is not None:
            ret_t, islot_t, iuop_t, _ = wgl_seg._pack_regs_single(
                fk, [fk.n_rets], R, len(rows), I)
        else:
            ret_t, islot_t, iuop_t, _ = wgl_seg._pack_regs(
                [(0, fk)], 1, R, len(rows), I)
        tabs.append((ret_t, islot_t, iuop_t))
        rets.append(ret_t)
    # one common grid size, then the COMPACT wire form per history
    # (sentinel rows are exact no-ops) — the mesh path ships the same
    # ~3.6x-smaller buffers as the pipelined path
    G_max = max(_pad_g((rt.shape[0] + EB - 1) // EB)
                for rt, _, _ in tabs)
    cbufs = [pack_events_compact(rt, it, ut, g_min=G_max)[0]
             for rt, it, ut in tabs]
    ev_all = np.stack(cbufs)                     # [D, nbytes] u8
    Wd = max(1, (1 << R) // 32)
    kern = _build_c(G_max, I, Wd, _snp(Sn), R, UP,
                    interpret=(backend == "cpu"))
    pspec = PartitionSpec(mesh_axis)
    _body = lambda ev, aux: kern(ev[0], aux)[None]  # noqa: E731
    # pallas_call's out_shape carries no varying-mesh-axes info and the
    # per-device program is trivially independent (no collectives), so
    # the vma/rep check must be skipped rather than threaded through
    # the kernel builder — shard_map_compat degrades through the
    # version-sensitive kwarg spellings (ADVICE r5).
    fn = shard_map_compat(_body, mesh=mesh,
                          in_specs=(pspec, PartitionSpec()),
                          out_specs=pspec)
    ev_sharded = jax.device_put(
        ev_all, NamedSharding(mesh, pspec))
    outs = np.asarray(fn(ev_sharded, jnp.asarray(auxbuf)))  # [D, 1, 2]
    results = []
    for d, fk in enumerate(fks):
        alive = bool(outs[d, 0, 0])
        res = {"valid?": alive, "op_count": fk.n_calls,
               "backend": backend, "engine": "wgl_deep",
               "max_open": int(fk.max_open), "states": int(Sn),
               "sharded": True}
        if not alive:
            res["anomaly"] = "nonlinearizable"
            w = map_witness(rets[d], fk, histories[d].ops,
                            int(outs[d, 0, 1]))
            if w is not None:
                res["op"] = w[0].to_dict()
                res["op_index"] = w[1]
        results.append(res)
    from jepsen_tpu import telemetry as telemetry_mod
    mesh_plan = planner.plan_engines(
        planner.Shape(kind="deep-mesh", R=R, Sn=int(Sn),
                      U=len(rows), decomposed=True,
                      batch=len(histories), mesh=n_dev,
                      max_states=max_states),
        backend=backend)
    telemetry_mod.attach_dispatch(
        results,
        mesh_plan.record(
            engine="wgl_deep",
            R=R, batch=len(histories),
            mesh=dict(zip(mesh.axis_names, mesh.devices.shape))))
    return results
