"""Deep-overlap linearizability megakernel — one Pallas program walks
the whole history with the frontier resident in VMEM.

Scope: the regime the reference's own tutorial names as THE cost cliff
— many simultaneously-open calls ("the search is exponential in the
number of concurrent operations", `doc/tutorial/06-refining.md:7-10`;
"difficulty goes like ~n!", `doc/tutorial/07-parameters.md:148-152`).
The segment engine (`ops.wgl_seg`) covers shallow overlap (R <= 6 on
the register-delta kernel); beyond that its candidate-table fallback
walks a dense 2^R config plane as *hundreds of XLA ops per event*, and
on a latency-bound chip the per-op dispatch overhead — not FLOPs —
made one C core 20-118x faster at R = 8-10 (BENCH_r03).

This module removes the dispatch overhead instead of the plane: the
frontier is a bit-packed boolean tensor `fr[Sn, 2^R / 32]` uint32
(state rows x mask words — a few KB even at R = 14), held in VMEM
scratch for the entire event walk.  One `pl.pallas_call` processes the
whole history: the grid streams fixed-size event blocks into SMEM, and
each event is ~a hundred vector instructions on 1-8 vregs, with no
XLA op boundaries, no scan carry round-trips, and a closure
`while_loop` whose early exit costs one on-core reduction instead of a
host-visible sync.

Semantics are just-in-time linearization, identical to `ops.wgl` /
`ops.wgl_seg` (Lowe / knossos :linear, `checker.clj:141-145`):

  * at the return of call t, configurations lacking t are closed under
    linearizing any currently-open call (to fixpoint — expansion
    sources are restricted to configs still lacking t, exact by the
    deferral argument in `ops.wgl._build_kernel`), then pruned to
    those containing t, and t's slot is retired;
  * a *pure* returning op (never changes state, e.g. a read) that is
    directly legal on every config still lacking it short-circuits the
    closure entirely — the same fast path as `ops.wgl`, and the common
    case for register workloads;
  * fixpoint in <= R rounds (round k unions every config reachable by
    <= k linearizations; at most R calls are open — the exactness
    argument of `wgl_seg._build_kernel_bits`).

Crashed (:info) calls cost NOTHING structurally here: a crashed call
is an open slot that never returns (registered, never retired), and
the 2^R plane *is* the powerset of open calls — so any history with
`max_open_normal + n_crashed <= deep_r_max(...)` is checked exactly —
word-split/hypercube included (ISSUE 10) — where the
reference's knossos "can make the difference between seconds and days"
on a couple of crashed processes (`doc/tutorial/06-refining.md:12-19`).

Verdicts are exact in both directions (the plane has no capacity to
overflow).  On invalid, the kernel reports the exact failing event; the
host maps it to the returning op — the same witness `ops.wgl_cpu`
reports (differentially tested).

Transition model: the diagonal + rank-1 decomposition of
`wgl_seg._decompose` (each op either keeps the state or sends every
legal state to ONE target) with Sn <= 32 states — the whole register /
cas / mutex family.  Out-of-scope models keep their existing engines.
"""

from __future__ import annotations

import functools
import os
import time
from typing import Any

import numpy as np

from jepsen_tpu.errors import BackendUnavailable, CheckError

# Intra-word "lacks bit b" patterns: bit i set iff mask-index i has
# bit b clear (shared constant with ops.frontier._INTRA).
_INTRA = (0x55555555, 0x33333333, 0x0F0F0F0F, 0x00FF00FF, 0x0000FFFF)
_FULL = 0xFFFFFFFF

from jepsen_tpu.ops import planner

R_BASE = planner.DEEP_R_BASE   # depth ONE resident [Sn, 512]-word
                               # plane covers; the full envelope is
                               # planner.deep_r_max(backend, n_devices)
                               # — word-split sub-plane stacks to 16 on
                               # one device, the hypercube mask shard
                               # to 14 + log2(D) on a mesh (ISSUE 10)
EB = 512            # event rows per grid step (SMEM block budget)


def supported(R: int, Sn: int, U: int, decomposed: bool,
              backend: str, n_devices: int | None = None) -> bool:
    """Gate shared with the wgl_seg dispatcher — now owned by the one
    engine planner (`planner.deep_supported`, ISSUE 8) so the routing
    decision and this kernel's self-description cannot drift; kept as
    a thin delegate for the long-standing callers.  See
    planner.deep_supported for the scope and the
    JEPSEN_TPU_DEEP_INTERPRET backend-capability semantics;
    `n_devices` widens the boundary to the hypercube-mesh envelope."""
    return planner.deep_supported(R, Sn, U, decomposed, backend,
                                  n_devices=n_devices)


def _snp(Sn: int) -> int:
    return 8 if Sn <= 8 else 16 if Sn <= 16 else 32


@functools.lru_cache(maxsize=32)
def _build(G: int, I: int, Wd: int, SnP: int, R: int, UP: int,
           P: int, interpret: bool):
    """kern(evbuf i32[G, EB*(1+2I)], auxbuf u32[1, 3*UP+16])
    -> i32[1, 2] (alive, first-dead-row | -1).

    `P` is the WORD-SPLIT factor (ISSUE 10): the 2^R-mask plane lives
    as a stack of P sub-planes of Wd words each, laid out contiguously
    along the sublane axis ([P*SnP, Wd] VMEM scratch) — sub-plane s
    holds full-plane words [s*Wd, (s+1)*Wd).  P = 1 is the classic
    single resident plane (bit-identical to the pre-split kernel: all
    the split arms below are unreachable).  Slot-bit geography:
    bits < 5 are intra-word, [5, 5+log2(Wd)) shift along the word
    (lane) axis, and [5+log2(Wd), R) — the split bits — move WHOLE
    sub-planes along the sublane axis.  Every per-op tile the VPU sees
    stays [<=32, Wd]-shaped regardless of R; only the stack height
    grows, which is what buys R = 15/16 on one device with no semantic
    change.

    evbuf row layout per event row r of a block:
      [r]                      return slot (-1 = registration-only row)
      [EB + r*I + i]           newly-invoked slot i (-1 = none)
      [EB + EB*I + r*I + i]    its uop index
    auxbuf: diag-mask[UP] ++ const-mask[UP] ++ t0[UP] ++ intra[16]
    (intra[b] = lacks-bit-b pattern for b < 5, FULL above — so the
    dynamic-slot target pattern needs no per-bit dispatch)."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    u32 = jnp.uint32
    EBW = EB * (1 + 2 * I)
    H = P * SnP                  # stacked sub-plane rows
    LOG_SNP = SnP.bit_length() - 1
    LW = Wd.bit_length() - 1     # log2 words per sub-plane
    assert P == 1 or (Wd * P) << 5 == (1 << R), (P, Wd, R)

    def popsum(x):
        return jax.lax.population_count(x).astype(jnp.int32).sum()

    def msk(cond):
        return jnp.where(cond, jnp.asarray(np.uint32(_FULL), u32),
                         jnp.asarray(np.uint32(0), u32))

    # static per-slot patterns over the [H, Wd] stack
    def lackpat(b, w_iota):
        """FULL where the mask index lacks slot bit b (w_iota is the
        FULL-plane word index, so one test covers word and split
        bits)."""
        if b < 5:
            return jnp.full((H, Wd), np.uint32(_INTRA[b]), u32)
        return msk(((w_iota >> (b - 5)) & 1) == 0)

    def shift_set(x, b):
        """Move configs (already masked to bit-b-clear) to mask|bit."""
        if b < 5:
            return x << (1 << b)
        d = 1 << (b - 5)
        if d < Wd:
            return jnp.concatenate(
                [jnp.zeros((H, d), u32), x[:, :Wd - d]], axis=1)
        rs = (d // Wd) * SnP     # whole-sub-plane move down the stack
        return jnp.concatenate(
            [jnp.zeros((rs, Wd), u32), x[:H - rs, :]], axis=0)

    def shift_unset(x, b):
        """Move configs (already masked to bit-b-set) to mask&~bit."""
        if b < 5:
            return x >> (1 << b)
        d = 1 << (b - 5)
        if d < Wd:
            return jnp.concatenate(
                [x[:, d:], jnp.zeros((H, d), u32)], axis=1)
        rs = (d // Wd) * SnP
        return jnp.concatenate(
            [x[rs:, :], jnp.zeros((rs, Wd), u32)], axis=0)

    def or_rows(x):
        """OR-fold over the state (sublane) axis WITHIN each sub-plane,
        broadcast back."""
        sh = 1
        while sh < SnP:
            if P == 1:
                x = x | jnp.roll(x, sh, axis=0)
            else:
                x = x | jnp.concatenate(
                    [jnp.roll(x[p * SnP:(p + 1) * SnP], sh, axis=0)
                     for p in range(P)], axis=0)
            sh *= 2
        return x

    # LAZY BIT RETIREMENT: retiring a slot never shifts the plane.  A
    # vacant slot's bit carries no information, so the prune at a
    # return keeps the linearized (bit-set) configs AND LEAVES THE BIT
    # SET — one AND, no cross-lane shift (the hardware only supports
    # those at static amounts).  The obligation moves to registration:
    # when a slot is (re)occupied, the two bit-halves are merged onto
    # the bit-clear side (exact — configs differing only in a
    # meaningless bit are the same config), so every occupant starts
    # from uniform bit 0.  First occupancy merges an all-zero half
    # (identity); crashed slots are registered once and never retired.

    def kernel(ev_ref, aux_ref, out_ref, fr,
               a1r, a2r, t0r, openr, flags):
        g = pl.program_id(0)
        g_iota = jax.lax.broadcasted_iota(jnp.int32, (H, Wd), 0)
        l_iota = jax.lax.broadcasted_iota(jnp.int32, (H, Wd), 1)
        # state row within a sub-plane, and the FULL-plane word index
        # (sub-plane offset folded in) — for P = 1 these reduce to the
        # classic s_iota / l_iota exactly
        s_iota = g_iota & (SnP - 1)
        w_iota = ((g_iota >> LOG_SNP) << LW) | l_iota

        @pl.when(g == 0)
        def _init():
            # initial state is index 0 (interned first) at mask 0
            fr[...] = jnp.where((g_iota == 0) & (l_iota == 0),
                                jnp.asarray(np.uint32(1), u32),
                                jnp.asarray(np.uint32(0), u32))
            for b in range(R):
                a1r[b] = jnp.uint32(0)
                a2r[b] = jnp.uint32(0)
                t0r[b] = 0
                openr[b] = 0
            flags[0] = 0
            flags[1] = -1

        def slot_pattern(sl):
            """Lacks-bit-sl pattern for a DYNAMIC slot: intra-word part
            from the aux table tail, word/split part from the
            full-plane word index."""
            ipat = aux_ref[0, 3 * UP + sl]
            sh = jnp.maximum(sl - 5, 0)
            wsel = (sl < 5) | (((w_iota >> sh) & 1) == 0)
            return jnp.where(wsel, ipat, jnp.asarray(np.uint32(0), u32))

        def expand_round(ltpv):
            """One Gauss-Seidel closure round: per open slot, linearize
            it on every config still lacking the target, accumulating
            straight into fr — later slots see earlier slots' children
            within the same round, so chains resolve in fewer rounds
            (monotone union either way; same fixpoint)."""
            for b in range(R):
                @pl.when(openr[b] == 1)
                def _(b=b):
                    f0 = fr[...]
                    src = (f0 & ltpv) & lackpat(b, w_iota)
                    a1b = a1r[b]
                    a2b = a2r[b]
                    dsel = msk(((a1b >> s_iota.astype(u32))
                                & jnp.uint32(1)) == 1)
                    moved = src & dsel
                    csel = msk(((a2b >> s_iota.astype(u32))
                                & jnp.uint32(1)) == 1)
                    red = or_rows(src & csel)
                    moved = moved | (red & msk(s_iota == t0r[b]))
                    fr[...] = f0 | shift_set(moved, b)

        def event(r, carry):
            @pl.when(flags[0] == 0)
            def _ev():
                # --- register the row's new invokes -------------------
                for i in range(I):
                    sl = ev_ref[0, 0, EB + r * I + i]

                    @pl.when(sl >= 0)
                    def _reg():
                        u = ev_ref[0, 0, EB + EB * I + r * I + i]
                        a1r[sl] = aux_ref[0, u]
                        a2r[sl] = aux_ref[0, UP + u]
                        t0r[sl] = aux_ref[0, 2 * UP + u].astype(jnp.int32)
                        openr[sl] = 1
                        # lazy-retirement merge: normalize the slot's
                        # (meaningless) bit to 0 across the plane
                        lp = slot_pattern(sl)
                        frv_i = fr[...]
                        low = frv_i & lp
                        high = frv_i & ~lp

                        @pl.when(sl < 5)
                        def _intra():
                            fr[...] = low | (
                                high >> (jnp.uint32(1)
                                         << jnp.minimum(sl, 4)
                                         .astype(u32)))

                        for b in range(5, R):
                            @pl.when(sl == b)
                            def _(b=b):
                                fr[...] = low | shift_unset(high, b)

                rs = ev_ref[0, 0, r]

                @pl.when(rs >= 0)
                def _ret():
                    # closure to fixpoint with early exit; a pure op
                    # directly legal on every lacking config is the
                    # identity on the plane (set-then-lazy-retire moves
                    # nothing) and cannot empty the frontier.
                    ltpv = slot_pattern(rs)
                    a2t = a2r[rs]
                    frv = fr[...]
                    lt = frv & ltpv
                    a1t = a1r[rs]
                    dselt = msk(((a1t >> s_iota.astype(u32))
                                 & jnp.uint32(1)) == 1)
                    n_lt = popsum(lt)
                    n_ill = popsum(lt & ~dselt)
                    fast = (a2t == jnp.uint32(0)) & (n_ill == 0)

                    @pl.when(jnp.logical_not(fast))
                    def _slow():
                        def cond(c):
                            prog, _, lack = c
                            return prog & (lack > 0)

                        def body(c):
                            _, prev, _ = c
                            expand_round(ltpv)
                            f1 = fr[...]
                            cnt = popsum(f1)
                            lack = popsum(f1 & ltpv)
                            return cnt > prev, cnt, lack

                        _, cnt, lack = jax.lax.while_loop(
                            cond, body,
                            (jnp.bool_(True), jnp.int32(-1), n_lt))
                        # prune configs that never linearized rs (bit
                        # stays set -- lazy retirement); the death test
                        # is FREE: the pruned count is cnt - lack from
                        # the last closure round
                        fr[...] = fr[...] & ~ltpv

                        @pl.when((cnt >= 0) & (cnt == lack))
                        def _dead():
                            flags[0] = 1
                            flags[1] = g * EB + r

                    openr[rs] = 0

            return carry

        jax.lax.fori_loop(0, EB, event, 0)
        out_ref[0, 0] = 1 - flags[0]
        out_ref[0, 1] = flags[1]

    # Version-drift shim (same class, renamed across Pallas releases:
    # TPUCompilerParams on older jax, CompilerParams on newer) — the
    # kernel must degrade across the drift, not AttributeError
    # (ADVICE r5's check_vma lesson, applied to the whole build path).
    _params_cls = getattr(pltpu, "CompilerParams", None) \
        or getattr(pltpu, "TPUCompilerParams")

    def kern(evbuf, auxbuf):
        return pl.pallas_call(
            kernel,
            grid=(G,),
            in_specs=[
                # 3D with a unit middle axis: Mosaic requires the
                # block's last two dims to equal the array's
                pl.BlockSpec((1, 1, EBW), lambda g: (g, 0, 0),
                             memory_space=pltpu.SMEM),
                pl.BlockSpec((1, 3 * UP + 16), lambda g: (0, 0),
                             memory_space=pltpu.SMEM),
            ],
            out_specs=pl.BlockSpec((1, 2), lambda g: (0, 0),
                                   memory_space=pltpu.SMEM),
            out_shape=jax.ShapeDtypeStruct((1, 2), np.int32),
            scratch_shapes=[
                pltpu.VMEM((H, Wd), np.uint32),     # fr (P sub-planes)
                pltpu.SMEM((R,), np.uint32),        # a1r
                pltpu.SMEM((R,), np.uint32),        # a2r
                pltpu.SMEM((R,), np.int32),         # t0r
                pltpu.SMEM((R,), np.int32),         # openr
                pltpu.SMEM((2,), np.int32),         # flags
            ],
            compiler_params=_params_cls(
                dimension_semantics=("arbitrary",)),
            interpret=interpret,
        )(evbuf, auxbuf)

    return jax.jit(kern)


def _pad_g(g: int) -> int:
    """Grid-size bucketing (compiled-shape control): pow2 to 16, then
    8-multiples."""
    if g <= 1:
        return 1
    b = 1
    while b < g and b < 16:
        b *= 2
    return b if g <= 16 else ((g + 7) // 8) * 8


def pack_events(ret_t: np.ndarray, islot_t: np.ndarray,
                iuop_t: np.ndarray) -> tuple[np.ndarray, int]:
    """[Lp, 1] + [Lp, 1, I] register-delta tables (wgl_seg._pack_regs
    with K=1) -> (evbuf i32[G, EB*(1+2I)], G)."""
    Lp = ret_t.shape[0]
    I = islot_t.shape[2]
    G = _pad_g((Lp + EB - 1) // EB)
    L2 = G * EB
    ret = np.full(L2, -1, np.int32)
    ret[:Lp] = ret_t[:, 0]
    islot = np.full((L2, I), -1, np.int32)
    islot[:Lp] = islot_t[:, 0, :]
    iuop = np.zeros((L2, I), np.int32)
    iuop[:Lp] = iuop_t[:, 0, :]
    evbuf = np.concatenate(
        [ret.reshape(G, EB),
         islot.reshape(G, EB * I),
         iuop.reshape(G, EB * I)], axis=1)
    return np.ascontiguousarray(evbuf[:, None, :]), G


def pack_events_compact(ret_t: np.ndarray, islot_t: np.ndarray,
                        iuop_t: np.ndarray,
                        g_min: int = 1) -> tuple[np.ndarray, int]:
    """Compact wire twin of pack_events: the same event stream as a
    uint8 buffer — ret+1 u8[L2] (0 = the -1 sentinel; slot+1 <=
    deep_r_max+1 = 18, comfortably u8) ++ islot+1 u8[L2*I] ++ iuop u16-LE bytes[2*L2*I] — ~3.6x
    fewer bytes than the int32 form at I=2, rebuilt into the kernel's
    evbuf on device by _build_c's unpack prologue.  Padding iuops are
    clamped to 0: the kernel reads a row's uop only where its islot
    >= 0 (registration gate), so the clamp is unobservable.  `g_min`
    lets check_mesh pack a whole batch at one common grid size (the
    sentinel rows are exact no-ops)."""
    Lp = ret_t.shape[0]
    I = islot_t.shape[2]
    G = max(_pad_g((Lp + EB - 1) // EB), g_min)
    L2 = G * EB
    ret = np.zeros(L2, np.uint8)
    ret[:Lp] = (ret_t[:, 0].astype(np.int32) + 1).astype(np.uint8)
    islot = np.zeros((L2, I), np.uint8)
    islot[:Lp] = (islot_t[:, 0, :].astype(np.int32) + 1).astype(
        np.uint8)
    iuop = np.zeros((L2, I), np.uint16)
    iuop[:Lp] = np.maximum(
        iuop_t[:, 0, :].astype(np.int32), 0).astype(np.uint16)
    return np.concatenate([ret, islot.ravel(),
                           iuop.ravel().view(np.uint8)]), G


@functools.lru_cache(maxsize=32)
def _build_c(G: int, I: int, Wd: int, SnP: int, R: int, UP: int,
             P: int, interpret: bool):
    """Compact-wire wrapper around _build: jit-unpacks the uint8 event
    buffer of pack_events_compact back into the int32 evbuf on device
    (a few fused casts/reshapes, free next to the event walk) and runs
    the megakernel — the tunnel carries the compact form.  `P` is the
    word-split sub-plane count (_build)."""
    import jax
    import jax.numpy as jnp

    kern = _build(G, I, Wd, SnP, R, UP, P, interpret)
    L2 = G * EB

    def fn(cbuf, auxbuf):
        ret = cbuf[:L2].astype(jnp.int32) - 1
        isl = cbuf[L2:L2 * (1 + I)].astype(jnp.int32) - 1
        pairs = cbuf[L2 * (1 + I):].reshape(L2 * I, 2)
        iu = (pairs[:, 0].astype(jnp.int32)
              | (pairs[:, 1].astype(jnp.int32) << 8))
        evbuf = jnp.concatenate(
            [ret.reshape(G, EB),
             isl.reshape(G, EB * I),
             iu.reshape(G, EB * I)], axis=1)[:, None, :]
        return kern(evbuf, auxbuf)

    return jax.jit(fn)


def pack_aux(a1t: np.ndarray, a2t: np.ndarray, t0t: np.ndarray,
             UP: int) -> np.ndarray:
    """[U] uop tables (wgl_seg._pack_uop_tables) -> u32[1, 3*UP+16]."""
    U = a1t.shape[0]
    aux = np.zeros((1, 3 * UP + 16), np.uint32)
    aux[0, :U] = a1t
    aux[0, UP:UP + U] = a2t
    aux[0, 2 * UP:2 * UP + U] = t0t.astype(np.uint32)
    for b in range(16):
        aux[0, 3 * UP + b] = _INTRA[b] if b < 5 else _FULL
    return aux


def _pad_u(u: int) -> int:
    b = 8
    while b < u:
        b *= 2
    return b


def dispatch_tables(ret_t, islot_t, iuop_t, a1t, a2t, t0t,
                    R: int, Sn: int, stats=None):
    """Asynchronously dispatch the deep kernel on pre-packed
    register-delta tables; returns the UN-FETCHED i32[1, 2] device
    verdict (alive, first-dead-row | -1).  On the tunneled chip a
    result fetch costs a fixed round trip that bounds any single-shot
    check from below (bench.py's north-star decomposition), so
    steady-state callers dispatch many histories back-to-back and
    fetch once — the same pipelined formulation wgl_seg.check_pipeline
    uses."""
    import jax

    backend = jax.default_backend()
    if backend not in ("tpu", "cpu"):
        raise BackendUnavailable(
            f"no deep-kernel lowering for {backend}", backend=backend)
    I = islot_t.shape[2]
    UP = _pad_u(a1t.shape[0])
    cbuf, G = pack_events_compact(ret_t, islot_t, iuop_t)
    auxbuf = pack_aux(a1t, a2t, t0t, UP)
    if stats is not None:           # measured wire traffic (telemetry)
        stats["wire_bytes"] = (stats.get("wire_bytes", 0)
                               + cbuf.nbytes + auxbuf.nbytes)
    # past R_BASE the plane word-splits into P base-sized sub-planes
    # (ISSUE 10) — same kernel, factored mask axis
    P = planner.deep_split_planes(R)
    Wd = max(1, (1 << R) // 32 // P)
    kern = planner.compiled(
        "wgl_deep", (G, I, Wd, _snp(Sn), R, UP, P, backend),
        _build_c, G, I, Wd, _snp(Sn), R, UP, P,
        interpret=(backend == "cpu"))
    return kern(cbuf, auxbuf), G


def check_tables(ret_t, islot_t, iuop_t, a1t, a2t, t0t,
                 R: int, Sn: int) -> dict[str, Any]:
    """Run the deep kernel on pre-packed register-delta tables and
    fetch the verdict.  Returns {"valid?": bool, "failed_row":
    int | None, ...}; failed_row indexes ret_t's rows (callers map it
    to the returning op)."""
    t1 = time.monotonic()
    dev, G = dispatch_tables(ret_t, islot_t, iuop_t, a1t, a2t, t0t,
                             R, Sn)
    out = np.asarray(dev)
    alive = bool(out[0, 0])
    res = {"valid?": alive,
           "failed_row": None if alive else int(out[0, 1]),
           "time_kernel_s": time.monotonic() - t1,
           "grid": G}
    P = planner.deep_split_planes(R)
    if P > 1:
        res["deep_variant"] = "word-split"
        res["shards"] = P
    return res


def map_witness(ret_t, fk, ops, failed_row):
    """Map a kernel-reported failing event row to the failing call's
    INVOKE op — the witness the oracle names (differentially pinned).
    Returns (op, op_index, return_position) or None when the scan
    carried no positions (pure-Python crash scans).  The ONE
    definition, shared by wgl_seg._check_deep and check_pipeline so
    the padded-row -> return-ordinal -> op arithmetic cannot drift."""
    if failed_row is None or fk.positions is None \
            or not len(fk.positions):
        return None
    ordinal = int((ret_t[:failed_row + 1, 0] >= 0).sum()) - 1
    if not (0 <= ordinal < len(fk.positions)):
        return None
    pos = int(fk.positions[ordinal])
    p = ops[pos].process
    inv = pos
    while inv >= 0 and not (ops[inv].process == p
                            and ops[inv].type == "invoke"):
        inv -= 1
    op = ops[max(inv, 0)]
    return op, (op.index if op.index is not None else max(inv, 0)), pos


def check_pipeline(model, histories, *, max_open_bits=None,
                   max_states: int = 64, stats=None,
                   mesh=None) -> list:
    """Steady-state deep-overlap checking: scan + pack every history on
    host, dispatch ALL kernels asynchronously, stack the [1, 2]
    verdicts ON DEVICE and fetch them in ONE round trip — the tunnel's
    fixed D2H latency bounds any single-shot check from below
    (bench.py's north-star decomposition), and this amortizes it over
    the batch exactly like wgl_seg.check_pipeline does for the shallow
    regime.  Verdict-identical to wgl_seg.check per history
    (differential battery).

    R <= planner.DEEP_R_BASE rides the classic resident plane; past it
    (to deep_r_max's single-device boundary) the SAME kernel runs with
    the plane word-split into base-sized sub-planes, so R = 15/16
    histories stay on-device instead of degrading to the serial chain
    (ISSUE 10).  `max_open_bits` defaults to that boundary.

    Histories OUTSIDE the kernel's scope (R past the boundary, crashed
    scans, undecomposable growth) do not poison the batch: with a
    `mesh`, stragglers within the hypercube envelope
    (R <= deep_r_max(backend, D)) verdict on the mask-sharded mesh
    engine first; past every device tier they ride wgl_seg.check's own
    fallback chain after the in-scope verdicts are fetched — so a
    mixed-depth batch (e.g. one R = 18 history among R <= 16 ones)
    still returns one correct verdict per history.  A device OOM on
    one history's dispatch demotes THAT history to the straggler chain
    (counted, never a poisoned batch or a silent wrong verdict).

    `stats`, when given a dict, receives the per-stage host-time
    decomposition (scan / tables / pack / dispatch / fetch / assemble
    seconds), mirroring wgl_seg.check_pipeline's."""
    import jax

    from jepsen_tpu import errors as errors_mod
    from jepsen_tpu.ops import wgl_seg

    spec = model.device_spec()
    if spec is None:
        raise BackendUnavailable(f"model {model!r} has no device spec")
    stats = {} if stats is None else stats   # always collected now
    _mt, _acc = wgl_seg._stats_clock(stats)
    backend = jax.default_backend()
    n_mesh = int(np.prod(mesh.devices.shape)) if mesh is not None else 1
    if max_open_bits is None:
        # scan up to everything ANY device tier can take; the serial
        # chain owns whatever scans out past that
        max_open_bits = planner.deep_r_max(backend, n_mesh)
    pend = []
    strag = []
    oom_demoted = 0
    results: list = [None] * len(histories)
    # shared interning across the batch: state enumeration, the
    # decomposition, and the uop tables are (re)built only when a
    # history grows the alphabet — not once per history
    seen: dict = {}
    rows: list = []
    U_at = -1
    Sn = 0
    tables = None            # (a1t, a2t, t0t)
    init = np.asarray(spec.encode(model), np.int32)
    for i, h in enumerate(histories):
        ops = h.ops
        t0 = _mt()
        fk = wgl_seg._scan_history(h, ops, spec, seen, rows,
                                   max_open_bits, want_snaps=False)
        t0 = _acc("scan", t0)
        if not fk:
            strag.append(i)
            continue
        R = int(fk.max_open)
        if len(rows) != U_at:
            uops = np.asarray(rows, np.int32).reshape(len(rows), 4)
            try:
                states, legal, next_state = wgl_seg._enumerate_states(
                    spec, init, uops, max_states)
            except wgl_seg.Unsupported:
                # the alphabet (and with it the state space) only
                # grows: everything from here on is a straggler —
                # already-dispatched in-scope verdicts stay valid
                from jepsen_tpu import telemetry as telemetry_mod
                telemetry_mod.count_fallback("wgl_deep_pipeline",
                                             "state-space")
                strag.extend(range(i, len(histories)))
                break
            Sn = states.shape[0]
            dw, cw, t0c = wgl_seg._decompose(legal, next_state)
            if dw is None:
                # undecomposable models only grow less decomposable
                strag.extend(range(i, len(histories)))
                break
            tables = wgl_seg._pack_uop_tables(legal, next_state,
                                              dw, cw, t0c)
            U_at = len(rows)
        t0 = _acc("tables", t0)
        if not supported(R, Sn, len(rows), True, backend):
            strag.append(i)   # e.g. R past deep_r_max: straggler tiers
            continue
        I = min(2, R) if R else 1
        if fk.deltas is not None:
            ret_t, islot_t, iuop_t, _ = wgl_seg._pack_regs_single(
                fk, [fk.n_rets], R, len(rows), I)
        else:
            ret_t, islot_t, iuop_t, _ = wgl_seg._pack_regs(
                [(0, fk)], 1, R, len(rows), I)
        a1t, a2t, t0t = tables
        t0 = _acc("pack", t0)
        try:
            dev, G = dispatch_tables(ret_t, islot_t, iuop_t, a1t, a2t,
                                     t0t, R, Sn, stats=stats)
        except Exception as e:       # noqa: BLE001 - classified below
            if not errors_mod.is_oom(e):
                raise
            # a sub-plane stack this device cannot hold degrades THIS
            # history to the straggler chain — counted, never a
            # poisoned batch (ISSUE 10: no silent wrong verdict)
            oom_demoted += 1
            strag.append(i)
            _acc("dispatch", t0)
            continue
        _acc("dispatch", t0)
        pend.append((dev, i, fk, ret_t, ops, R, Sn, G))

    if pend:
        t0 = _mt()
        stacked = wgl_seg._build_stack(len(pend))(
            *[d for d, *_ in pend])
        outs = np.asarray(stacked)                    # ONE fetch
        t0 = _acc("fetch", t0)
        for j, (dev, i, fk, ret_t, ops, R, Sn_i, G) in enumerate(pend):
            alive = bool(outs[j, 0, 0])
            res = {"valid?": alive, "op_count": fk.n_calls,
                   "backend": backend, "engine": "wgl_deep",
                   "max_open": R, "states": Sn_i, "pipelined": True}
            P_i = planner.deep_split_planes(R)
            if P_i > 1:
                res["deep_variant"] = "word-split"
                res["shards"] = P_i
            if not alive:
                res["anomaly"] = "nonlinearizable"
                w = map_witness(ret_t, fk, ops, int(outs[j, 0, 1]))
                if w is not None:
                    res["op"] = w[0].to_dict()
                    res["op_index"] = w[1]
            results[i] = res
        _acc("assemble", t0)
    # in-scope verdicts carry the deep pipeline's plan + stage
    # decomposition BEFORE the stragglers run, so the serial chain's
    # verdicts keep their own engines' records
    from jepsen_tpu import telemetry as telemetry_mod
    R_pend = max(p[5] for p in pend) if pend else 0
    pipe_plan = planner.plan_engines(
        planner.Shape(kind="deep-pipeline", R=R_pend,
                      Sn=Sn or None, U=len(rows) or None,
                      decomposed=True, batch=len(histories),
                      mesh=n_mesh if mesh is not None else None,
                      max_states=max_states,
                      max_open_bits=max_open_bits),
        backend=backend)
    telemetry_mod.attach_dispatch(
        results,
        pipe_plan.record(engine="wgl_deep",
                         R=R_pend or None,
                         batch=len(histories),
                         stragglers=len(strag) or None,
                         oom_demoted=oom_demoted or None),
        stages=stats)
    if oom_demoted:
        try:
            telemetry_mod.REGISTRY.counter(
                "jepsen_deep_oom_demotions_total").inc(oom_demoted)
        except Exception:       # noqa: BLE001 - telemetry is advisory
            pass
    for i in strag:
        if mesh is not None:
            # straggler tier 1 (ISSUE 10): the hypercube mask shard —
            # R past one device's stack but within 14 + log2(D)
            try:
                results[i] = check_hypercube(
                    model, [histories[i]], mesh,
                    max_states=max_states)[0]
                continue
            except CheckError:
                # out of the mesh envelope too: serial
                telemetry_mod.count_fallback("wgl_deep_hc",
                                             "mesh-envelope")
        try:
            results[i] = wgl_seg.check(model, histories[i],
                                       max_states=max_states,
                                       max_open_bits=max_open_bits)
            continue
        except wgl_seg.Unsupported:
            # beyond every batched gate (R past deep_r_max): the
            # serial frontier engine has no overlap-depth limit
            telemetry_mod.count_fallback("wgl_deep", "beyond-gates")
            why = ("deep straggler beyond every batched gate "
                   "(serial frontier engine)")
        except Exception as e:   # noqa: BLE001 - OOM-only degradation
            if not errors_mod.is_oom(e):
                raise
            # the single-history retry OOM'd again (wgl_seg routed it
            # back onto the deep kernel): the serial chain is the
            # total fallback, not a re-raise
            why = ("deep straggler after device OOM "
                   "(serial frontier engine)")
        from jepsen_tpu.ops import wgl
        results[i] = wgl.check(model, histories[i])
        telemetry_mod.attach_dispatch(
            [results[i]],
            telemetry_mod.dispatch_record(
                results[i].get("engine", "wgl"), why=why,
                fallback_chain=["wgl_cpu"], batch=1))
    return results


def check_mesh(model, histories, mesh, *, mesh_axis: str = "hists",
               max_open_bits=None,
               max_states: int = 64) -> list:
    """Deep-overlap scale-out over a jax.sharding.Mesh — TWO layouts
    behind one entry point (ISSUE 10):

      * histories within one device's plane stack (R <= the
        single-device deep_r_max, word-split included): one history
        per device (SURVEY.md §2.5), the embarrassingly parallel
        layout — every history's packed event buffer is padded to one
        common grid shape, stacked on a leading axis sharded over
        `mesh_axis`, and shard_map runs the kernel once per device
        with NO collectives (verdicts are independent; the [D, 2]
        output gathers on fetch).  Grid-padding rows are ret = -1 /
        islot = -1 no-op rows — exact, as in the pipelined path.
      * histories DEEPER than one device's stack (R up to
        14 + log2(D)): the batch routes to `check_hypercube`, which
        mask-shards each history's 2^R configuration plane across the
        whole mesh (any batch length; histories run one at a time,
        each using every device).

    Verdict-identical to check_pipeline per history; histories must
    all be in deep scope (callers route stragglers through
    check_pipeline instead)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec

    from jepsen_tpu.ops import shard_map_compat, wgl_seg

    spec = model.device_spec()
    if spec is None:
        raise BackendUnavailable(f"model {model!r} has no device spec")
    backend = jax.default_backend()
    n_dev = int(np.prod(mesh.devices.shape))
    if max_open_bits is None:
        max_open_bits = planner.deep_r_max(backend, n_dev)
    r_dev = planner.deep_r_max(backend, 1)
    # Cheap pre-scan for the routing depth (the real scan below shares
    # interning); prep.max_open is exact and costs one host pass.
    from jepsen_tpu.ops import prep as prep_mod
    try:
        R_probe = max(prep_mod.prepare(h).max_open for h in histories)
    except Exception:            # noqa: BLE001 - scan decides below
        R_probe = 0
    if R_probe > r_dev:
        return check_hypercube(model, histories, mesh,
                               max_states=max_states,
                               max_open_bits=max_open_bits)
    if len(histories) != n_dev:
        raise CheckError(f"one history per device: got "
                         f"{len(histories)} histories, {n_dev} devices",
                         batch_size=len(histories), backend=backend)
    seen: dict = {}
    rows: list = []
    init = np.asarray(spec.encode(model), np.int32)
    fks = []
    for d, h in enumerate(histories):
        fk = wgl_seg._scan_history(h, h.ops, spec, seen, rows,
                                   max_open_bits, want_snaps=False)
        if not fk:
            raise CheckError("history out of deep-kernel scope (scan)",
                             history_index=d, backend=backend)
        fks.append(fk)
    uops = np.asarray(rows, np.int32).reshape(len(rows), 4)
    states, legal, next_state = wgl_seg._enumerate_states(
        spec, init, uops, max_states)
    Sn = states.shape[0]
    dw, cw, t0c = wgl_seg._decompose(legal, next_state)
    if dw is None:
        raise CheckError("model not decomposable", backend=backend)
    a1t, a2t, t0t = wgl_seg._pack_uop_tables(legal, next_state,
                                             dw, cw, t0c)
    R = max(int(fk.max_open) for fk in fks)
    if not supported(R, Sn, len(rows), True, backend):
        raise CheckError(
            f"batch out of deep-kernel scope (R={R}, Sn={Sn})",
            backend=backend)
    I = min(2, R) if R else 1
    UP = _pad_u(a1t.shape[0])
    auxbuf = pack_aux(a1t, a2t, t0t, UP)
    tabs, rets = [], []
    for fk in fks:
        if fk.deltas is not None:
            ret_t, islot_t, iuop_t, _ = wgl_seg._pack_regs_single(
                fk, [fk.n_rets], R, len(rows), I)
        else:
            ret_t, islot_t, iuop_t, _ = wgl_seg._pack_regs(
                [(0, fk)], 1, R, len(rows), I)
        tabs.append((ret_t, islot_t, iuop_t))
        rets.append(ret_t)
    # one common grid size, then the COMPACT wire form per history
    # (sentinel rows are exact no-ops) — the mesh path ships the same
    # ~3.6x-smaller buffers as the pipelined path
    G_max = max(_pad_g((rt.shape[0] + EB - 1) // EB)
                for rt, _, _ in tabs)
    cbufs = [pack_events_compact(rt, it, ut, g_min=G_max)[0]
             for rt, it, ut in tabs]
    ev_all = np.stack(cbufs)                     # [D, nbytes] u8
    P = planner.deep_split_planes(R)
    Wd = max(1, (1 << R) // 32 // P)
    kern = _build_c(G_max, I, Wd, _snp(Sn), R, UP, P,
                    interpret=(backend == "cpu"))
    pspec = PartitionSpec(mesh_axis)
    _body = lambda ev, aux: kern(ev[0], aux)[None]  # noqa: E731
    # pallas_call's out_shape carries no varying-mesh-axes info and the
    # per-device program is trivially independent (no collectives), so
    # the vma/rep check must be skipped rather than threaded through
    # the kernel builder — shard_map_compat degrades through the
    # version-sensitive kwarg spellings (ADVICE r5).
    fn = shard_map_compat(_body, mesh=mesh,
                          in_specs=(pspec, PartitionSpec()),
                          out_specs=pspec)
    ev_sharded = jax.device_put(
        ev_all, NamedSharding(mesh, pspec))
    outs = np.asarray(fn(ev_sharded, jnp.asarray(auxbuf)))  # [D, 1, 2]
    results = []
    for d, fk in enumerate(fks):
        alive = bool(outs[d, 0, 0])
        res = {"valid?": alive, "op_count": fk.n_calls,
               "backend": backend, "engine": "wgl_deep",
               "max_open": int(fk.max_open), "states": int(Sn),
               "sharded": True}
        if P > 1:
            res["deep_variant"] = "word-split"
            res["shards"] = P
        if not alive:
            res["anomaly"] = "nonlinearizable"
            w = map_witness(rets[d], fk, histories[d].ops,
                            int(outs[d, 0, 1]))
            if w is not None:
                res["op"] = w[0].to_dict()
                res["op_index"] = w[1]
        results.append(res)
    from jepsen_tpu import telemetry as telemetry_mod
    mesh_plan = planner.plan_engines(
        planner.Shape(kind="deep-mesh", R=R, Sn=int(Sn),
                      U=len(rows), decomposed=True,
                      batch=len(histories), mesh=n_dev,
                      max_states=max_states),
        backend=backend)
    telemetry_mod.attach_dispatch(
        results,
        mesh_plan.record(
            engine="wgl_deep",
            R=R, batch=len(histories),
            mesh=dict(zip(mesh.axis_names, mesh.devices.shape))))
    return results


# ---------------------------------------------------------------------------
# Hypercube mask shard (ISSUE 10): one history's 2^R configuration
# plane partitioned across the device mesh by its TOP mask bits.
# ---------------------------------------------------------------------------

def _pad_events_flat(ret_t: np.ndarray, islot_t: np.ndarray,
                     iuop_t: np.ndarray):
    """Register-delta tables -> the flat int32 event arrays the
    hypercube engine walks (ret[L2], islot[L2, I], iuop[L2, I]),
    64-padded with ret = -1 / islot = -1 no-op rows (exact, as in
    pack_events)."""
    Lp = ret_t.shape[0]
    I = islot_t.shape[2]
    L2 = max(64, ((Lp + 63) // 64) * 64)
    ret = np.full(L2, -1, np.int32)
    ret[:Lp] = ret_t[:, 0]
    islot = np.full((L2, I), -1, np.int32)
    islot[:Lp] = islot_t[:, 0, :]
    iuop = np.zeros((L2, I), np.int32)
    iuop[:Lp] = np.maximum(iuop_t[:, 0, :].astype(np.int32), 0)
    return ret, islot, iuop, L2


def _build_hc(L2: int, I: int, Wdl: int, SnP: int, R: int, UP: int,
              devs: tuple, mesh_axis: str):
    """The hypercube-sharded deep engine: the SAME just-in-time
    linearization walk as `_build`, expressed as an XLA program under
    `shard_map` so the 2^R mask plane can span the mesh.  Device d
    holds full-plane words [d*Wdl, (d+1)*Wdl) — i.e. the top log2(D)
    mask bits ARE the device index.  Slot-bit geography per device:
    bits < 5 intra-word, [5, 5+log2(Wdl)) local word shifts, and
    [5+log2(Wdl), R) — the device bits — one deterministic pairwise
    `ppermute` with the hypercube partner d XOR 2^k per event round
    (shard_map_compat.hypercube_exchange).  The closure while_loop
    early-exits on the mesh-wide frontier counts (psum — every trip
    decision is uniform across devices, so the collectives inside the
    loop always rendezvous), exactly as `elle_mesh` detects its
    fixpoint.

    Trade disclosed in docs/deep-engine.md: events step at the XLA
    level (no Pallas megakernel fusion), so per-event overhead is
    higher than the resident-plane kernel — this variant exists for
    the R that does NOT FIT one device, not to race it.

    kern(ret i32[L2], islot i32[L2, I], iuop i32[L2, I],
         a1 u32[UP], a2 u32[UP], t0 i32[UP]) -> i32[D, 3]
    (alive, first-dead-row | -1, pairwise exchanges carried out)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec

    from jepsen_tpu.ops.shard_map_compat import (hypercube_exchange,
                                                 shard_map_compat)

    D = len(devs)
    SB = D.bit_length() - 1          # device (high) mask bits
    LW = Wdl.bit_length() - 1        # log2 words per device
    SUB0 = 5 + LW                    # first device bit
    assert (Wdl * D) << 5 == (1 << R), (Wdl, D, R)
    u32 = jnp.uint32
    FULL = np.uint32(_FULL)
    intra_np = np.array(list(_INTRA) + [_FULL], np.uint32)

    def body(ret, islot, iuop, a1, a2, t0):
        d = jax.lax.axis_index(mesh_axis).astype(jnp.int32)
        intra_tab = jnp.asarray(intra_np)
        s_iota = jax.lax.broadcasted_iota(jnp.int32, (SnP, Wdl), 0)
        l_iota = jax.lax.broadcasted_iota(jnp.int32, (SnP, Wdl), 1)
        w_iota = d * Wdl + l_iota    # FULL-plane word index

        def msk(c):
            return jnp.where(c, jnp.asarray(FULL, u32), jnp.uint32(0))

        def popsum(x):
            return jax.lax.population_count(x).astype(jnp.int32).sum()

        def gsum(x):
            return jax.lax.psum(x, mesh_axis)

        def lackpat(b):
            if b < 5:
                return jnp.full((SnP, Wdl), np.uint32(_INTRA[b]), u32)
            return msk(((w_iota >> (b - 5)) & 1) == 0)

        def shift_set(x, b):
            """Pre-masked to bit-b-clear configs -> mask|bit.  Device
            bits leave via the pairwise exchange (the sender masked
            the other side to zero, so the receive IS the move)."""
            if b < 5:
                return x << (1 << b)
            dd = 1 << (b - 5)
            if dd < Wdl:
                return jnp.concatenate(
                    [jnp.zeros((SnP, dd), u32), x[:, :Wdl - dd]],
                    axis=1)
            return hypercube_exchange(x, mesh_axis, b - SUB0, D)

        def shift_unset(x, b):
            if b < 5:
                return x >> (1 << b)
            dd = 1 << (b - 5)
            if dd < Wdl:
                return jnp.concatenate(
                    [x[:, dd:], jnp.zeros((SnP, dd), u32)], axis=1)
            return hypercube_exchange(x, mesh_axis, b - SUB0, D)

        def or_rows(x):
            sh = 1
            while sh < SnP:
                x = x | jnp.roll(x, sh, axis=0)
                sh *= 2
            return x

        def slot_pattern(sl):
            """Lacks-bit-sl for a DYNAMIC (traced) slot index."""
            ipat = intra_tab[jnp.minimum(jnp.maximum(sl, 0), 5)]
            sh = jnp.maximum(sl - 5, 0)
            wsel = (sl < 5) | (((w_iota >> sh) & 1) == 0)
            return jnp.where(wsel, ipat, jnp.uint32(0))

        fr0 = jnp.where((w_iota == 0) & (s_iota == 0),
                        jnp.uint32(1), jnp.uint32(0))

        def event(r, st):
            fr, a1r, a2r, t0r, openr, f0, f1, ex = st
            alive = f0 == 0
            # --- register the row's new invokes (lazy-retirement
            # merge normalizes the slot's meaningless bit to 0; for a
            # device bit that is one pairwise exchange) ---------------
            for i in range(I):
                sl = islot[r, i]
                do = alive & (sl >= 0)
                slc = jnp.maximum(sl, 0)
                u = iuop[r, i]
                a1r = a1r.at[slc].set(jnp.where(do, a1[u], a1r[slc]))
                a2r = a2r.at[slc].set(jnp.where(do, a2[u], a2r[slc]))
                t0r = t0r.at[slc].set(jnp.where(do, t0[u], t0r[slc]))
                openr = openr.at[slc].set(
                    jnp.where(do, 1, openr[slc]))
                lp = slot_pattern(sl)
                low = fr & lp
                high = fr & ~lp
                m = jnp.where(
                    do & (sl < 5),
                    low | (high >> (jnp.uint32(1)
                                    << jnp.minimum(slc, 4)
                                    .astype(u32))), fr)
                for b in range(5, SUB0):
                    m = jnp.where(do & (sl == b),
                                  low | shift_unset(high, b), m)
                for b in range(SUB0, R):
                    hit = do & (sl == b)
                    # the exchange itself runs unconditionally (every
                    # device must rendezvous); non-matching slots send
                    # zeros and discard the result
                    merged = hypercube_exchange(
                        jnp.where(hit, high, jnp.uint32(0)),
                        mesh_axis, b - SUB0, D)
                    ex = ex + jnp.where(hit, 1, 0)
                    m = jnp.where(hit, low | merged, m)
                fr = m

            # --- the row's return: closure to fixpoint + prune -------
            rs = ret[r]
            rsc = jnp.maximum(rs, 0)
            do_ret = alive & (rs >= 0)
            ltpv = slot_pattern(rsc)
            a1t_ = a1r[rsc]
            a2t_ = a2r[rsc]
            dselt = msk(((a1t_ >> s_iota.astype(u32))
                         & jnp.uint32(1)) == 1)
            lt = fr & ltpv
            n_lt = gsum(popsum(lt))
            n_ill = gsum(popsum(lt & ~dselt))
            fast = (a2t_ == jnp.uint32(0)) & (n_ill == 0)
            do_slow = do_ret & jnp.logical_not(fast)

            def expand(frv, exv):
                """One Gauss-Seidel closure round (the _build
                expand_round, device bits exchanged)."""
                for b in range(R):
                    opn = openr[b] == 1
                    f0v = frv
                    src = (f0v & ltpv) & lackpat(b)
                    a1b = a1r[b]
                    a2b = a2r[b]
                    dsel = msk(((a1b >> s_iota.astype(u32))
                                & jnp.uint32(1)) == 1)
                    moved = src & dsel
                    csel = msk(((a2b >> s_iota.astype(u32))
                                & jnp.uint32(1)) == 1)
                    red = or_rows(src & csel)
                    moved = moved | (red & msk(s_iota == t0r[b]))
                    if b >= SUB0:
                        contrib = hypercube_exchange(
                            jnp.where(opn, moved, jnp.uint32(0)),
                            mesh_axis, b - SUB0, D)
                        exv = exv + jnp.where(opn, 1, 0)
                        frv = frv | contrib
                    else:
                        frv = jnp.where(opn,
                                        f0v | shift_set(moved, b), frv)
                return frv, exv

            def cond(c):
                _, prog, _, lack, _ = c
                return prog & (lack > 0)

            def round_(c):
                frv, _, prev, _, exv = c
                frv, exv = expand(frv, exv)
                cnt = gsum(popsum(frv))
                lack = gsum(popsum(frv & ltpv))
                return frv, cnt > prev, cnt, lack, exv

            frv, _, cnt, lack, ex = jax.lax.while_loop(
                cond, round_,
                (fr, do_slow, jnp.int32(-1), n_lt, ex))
            # prune configs that never linearized rs (bit stays set —
            # lazy retirement); a fast (pure, everywhere-legal) return
            # is the identity on the plane, exactly as in _build
            fr = jnp.where(do_slow, frv & ~ltpv, frv)
            dead = do_slow & (cnt >= 0) & (cnt == lack)
            f1 = jnp.where((f0 == 0) & dead, r, f1)
            f0 = jnp.where(dead, 1, f0)
            openr = openr.at[rsc].set(
                jnp.where(do_ret, 0, openr[rsc]))
            return fr, a1r, a2r, t0r, openr, f0, f1, ex

        st = jax.lax.fori_loop(
            0, L2, event,
            (fr0, jnp.zeros(R, u32), jnp.zeros(R, u32),
             jnp.zeros(R, jnp.int32), jnp.zeros(R, jnp.int32),
             jnp.int32(0), jnp.int32(-1), jnp.int32(0)))
        f0, f1, ex = st[5], st[6], st[7]
        return jnp.stack([1 - f0, f1, ex])[None]

    mesh = Mesh(np.array(list(devs)), (mesh_axis,))
    rep = PartitionSpec()
    fn = shard_map_compat(body, mesh=mesh, in_specs=(rep,) * 6,
                          out_specs=PartitionSpec(mesh_axis))
    return jax.jit(fn)


def check_hypercube(model, histories, mesh, *,
                    mesh_axis: str = "cfg",
                    max_states: int = 64,
                    max_open_bits=None) -> list:
    """Verdict histories whose 2^R configuration plane exceeds one
    device's stack by mask-sharding it across `mesh`: the top log2(D)
    mask bits become the device index (D a power of two).  Each
    history runs as ONE sharded program over the whole mesh (histories
    at this depth are individually the bottleneck; the batch axis is a
    host loop).  Verdicts and witnesses are bit-identical to the
    serial-chain oracle (differential battery); `exchange_rounds` on
    each verdict counts the pairwise hypercube exchanges that carried
    data — the wire bill of the top-bit transitions."""
    import jax

    from jepsen_tpu.ops import wgl_seg

    spec = model.device_spec()
    if spec is None:
        raise BackendUnavailable(f"model {model!r} has no device spec")
    backend = jax.default_backend()
    if backend not in ("tpu", "cpu"):
        raise BackendUnavailable(
            f"no deep-kernel lowering for {backend}", backend=backend)
    devs = list(mesh.devices.reshape(-1))
    D = len(devs)
    if D < 2 or (D & (D - 1)):
        raise CheckError(
            f"hypercube mask shard needs a power-of-2 device count "
            f">= 2, got {D}", backend=backend)
    rmax = planner.deep_r_max(backend, D)
    if max_open_bits is None:
        max_open_bits = rmax
    seen: dict = {}
    rows: list = []
    init = np.asarray(spec.encode(model), np.int32)
    fks = []
    for d, h in enumerate(histories):
        fk = wgl_seg._scan_history(h, h.ops, spec, seen, rows,
                                   max_open_bits, want_snaps=False)
        if not fk:
            raise CheckError("history out of deep-kernel scope (scan)",
                             history_index=d, backend=backend)
        fks.append(fk)
    uops = np.asarray(rows, np.int32).reshape(len(rows), 4)
    try:
        states, legal, next_state = wgl_seg._enumerate_states(
            spec, init, uops, max_states)
    except wgl_seg.Unsupported as e:
        raise CheckError(str(e), backend=backend) from e
    Sn = states.shape[0]
    dw, cw, t0c = wgl_seg._decompose(legal, next_state)
    if dw is None:
        raise CheckError("model not decomposable", backend=backend)
    a1t, a2t, t0t = wgl_seg._pack_uop_tables(legal, next_state,
                                             dw, cw, t0c)
    R = max(int(fk.max_open) for fk in fks)
    if not supported(R, Sn, len(rows), True, backend, n_devices=D):
        raise CheckError(
            f"batch out of the hypercube deep envelope "
            f"(R={R}, Sn={Sn}, D={D})", backend=backend)
    if (1 << R) < 32 * D:
        raise CheckError(
            f"R={R} too shallow for a {D}-device mask shard "
            f"(need 2^R >= 32*D words)", backend=backend)
    Wdl = (1 << R) // 32 // D
    I = min(2, R) if R else 1
    UP = _pad_u(a1t.shape[0])
    U = a1t.shape[0]
    a1p = np.zeros(UP, np.uint32)
    a1p[:U] = a1t
    a2p = np.zeros(UP, np.uint32)
    a2p[:U] = a2t
    t0p = np.zeros(UP, np.int32)
    t0p[:U] = t0t
    from jepsen_tpu import telemetry as telemetry_mod
    hc_plan = planner.plan_engines(
        planner.Shape(kind="deep-mesh", R=R, Sn=int(Sn), U=len(rows),
                      decomposed=True, batch=len(histories), mesh=D,
                      max_states=max_states),
        backend=backend)
    if hc_plan.engine != "wgl_deep_hc":
        # hypercube forced BELOW the single-device boundary (caller
        # intent — differential batteries, explicit mesh routing): the
        # record names what actually ran, not the auto route
        hc_plan = hc_plan.refine(
            engine="wgl_deep_hc", deep_variant="hypercube", shards=D,
            exchange_rounds=D.bit_length() - 1,
            why=(f"hypercube mask shard forced over {D} devices "
                 "(caller intent; R within the single-device "
                 "envelope)"))
    results = []
    for hidx, fk in enumerate(fks):
        if fk.deltas is not None:
            ret_t, islot_t, iuop_t, _ = wgl_seg._pack_regs_single(
                fk, [fk.n_rets], R, len(rows), I)
        else:
            ret_t, islot_t, iuop_t, _ = wgl_seg._pack_regs(
                [(0, fk)], 1, R, len(rows), I)
        ret, islot, iuop, L2 = _pad_events_flat(ret_t, islot_t,
                                                iuop_t)
        t1 = time.monotonic()
        kern = planner.compiled(
            "wgl_deep_hc",
            (L2, I, Wdl, _snp(Sn), R, UP, tuple(devs)),
            _build_hc, L2, I, Wdl, _snp(Sn), R, UP,
            tuple(devs), mesh_axis)
        out = np.asarray(kern(ret, islot, iuop, a1p, a2p, t0p))
        alive = bool(out[0, 0])
        res = {"valid?": alive, "op_count": fk.n_calls,
               "backend": backend, "engine": "wgl_deep",
               "max_open": int(fk.max_open), "states": int(Sn),
               "sharded": True, "deep_variant": "hypercube",
               "shards": D, "exchange_rounds": int(out[0, 2]),
               "time_kernel_s": time.monotonic() - t1}
        if not alive:
            res["anomaly"] = "nonlinearizable"
            w = map_witness(ret_t, fk, histories[hidx].ops,
                            int(out[0, 1]))
            if w is not None:
                res["op"] = w[0].to_dict()
                res["op_index"] = w[1]
        results.append(res)
    telemetry_mod.attach_dispatch(
        results,
        hc_plan.record(
            engine="wgl_deep", R=R, batch=len(histories),
            deep_variant="hypercube", shards=D,
            mesh=dict(zip(mesh.axis_names, mesh.devices.shape))))
    return results
