"""Batched WGL: linearizability of MANY independent histories in one
device program.

The reference checks independent keys with a bounded thread pool
(`jepsen/src/jepsen/independent.clj:247-298` + checker.clj:104), each key
an isolated JVM search.  Here every per-key history is packed into a
columnar batch and the whole check is `vmap` of the frontier kernel over
the key axis — one XLA program for a million-op multi-key history
(SURVEY.md §2.4, BASELINE config 3).  The key axis shards over a TPU
mesh with `jax.sharding` (keys are embarrassingly parallel; no
collectives needed beyond the final gather).

Unlike ops/wgl.py's adaptive single-history kernel (tiered closure
pools, pure-op fast path — both built on `lax.cond`, which `vmap` would
turn into run-both-branches), this kernel uses one fixed frontier size.
Per-key register histories are short and narrow, so a small frontier
almost always suffices; lanes that overflow AND look invalid escalate
host-side to the adaptive kernel.
"""

from __future__ import annotations

import functools
from typing import Any, Optional, Sequence

import numpy as np

from jepsen_tpu.errors import BackendUnavailable
from jepsen_tpu.ops import frontier
from jepsen_tpu.ops.prep import prepare
from jepsen_tpu.ops.wgl import WGLPlan, _bucket, plan

@functools.lru_cache(maxsize=32)
def _build_batch_kernel(step_fn, F: int, C: int, W: int, S: int):
    import jax
    import jax.numpy as jnp

    Wd = max((W + 31) // 32, 1)
    u32 = jnp.uint32

    has_bit, set_bit, clear_bit = frontier.make_bit_ops(Wd)
    dedupe_compact = frontier.make_dedupe_compact(Wd, S)

    def kernel(ret_call, ret_slot, cand_call, cand_slot, fv, av, bv, okv,
               init_state, n_events):
        masks0 = jnp.zeros((F, Wd), u32)
        states0 = jnp.zeros((F, S), jnp.int32).at[0].set(init_state)
        valid0 = jnp.zeros(F, bool).at[0].set(True)

        def ev_cond(carry):
            r, _, _, _, dead, _ = carry
            return (r < n_events) & ~dead

        def ev_body(carry):
            r, masks, states, valid, dead, overflow = carry
            tslot = ret_slot[r]
            cc = cand_call[r]
            cs = cand_slot[r]
            jc = jnp.clip(cc, 0, None)
            cf, ca, cb, cok = fv[jc], av[jc], bv[jc], okv[jc]
            open_c = cc >= 0

            def cl_cond(c):
                m, s, v, ovf, rounds, progressed, _ = c
                lacks = v & ~has_bit(m, jnp.broadcast_to(tslot, (F,)))
                return jnp.any(lacks) & (rounds < C) & progressed & ~ovf

            def cl_body(c):
                m, s, v, ovf, rounds, _, prev_count = c
                lacks = v & ~has_bit(m, jnp.broadcast_to(tslot, (F,)))

                def per_config(mask, state, lack):
                    def per_cand(slot, f_, a_, b_, ok_, is_open):
                        st2, legal = step_fn(state, f_, a_, b_, ok_)
                        not_lin = ~has_bit(mask[None, :], slot[None])[0]
                        okc = lack & is_open & not_lin & legal
                        m2 = set_bit(mask[None, :], slot[None])[0]
                        return m2, st2, okc
                    return jax.vmap(per_cand)(cs, cf, ca, cb, cok, open_c)

                chm, chs, chv = jax.vmap(per_config)(m, s, lacks)
                pool_m = jnp.concatenate([m, chm.reshape(F * C, Wd)])
                pool_s = jnp.concatenate([s, chs.reshape(F * C, S)])
                pool_v = jnp.concatenate([v, chv.reshape(F * C)])
                nm, ns, nv, o2, count = dedupe_compact(
                    pool_m, pool_s, pool_v, F)
                return (nm, ns, nv, ovf | o2, rounds + 1,
                        count > prev_count, count)

            masks, states, valid, ovf, _, _, _ = jax.lax.while_loop(
                cl_cond, cl_body,
                (masks, states, valid, jnp.bool_(False), jnp.int32(0),
                 jnp.bool_(True), jnp.int32(-1)))

            # prune configs that never linearized the returning call,
            # then retire its slot
            sat = has_bit(masks, jnp.broadcast_to(tslot, (F,)))
            valid = valid & sat
            masks = clear_bit(masks, jnp.broadcast_to(tslot, (F,)))
            dead = ~jnp.any(valid)
            return r + 1, masks, states, valid, dead, overflow | ovf

        r, masks, states, valid, dead, overflow = jax.lax.while_loop(
            ev_cond, ev_body,
            (jnp.int32(0), masks0, states0, valid0, jnp.bool_(False),
             jnp.bool_(False)))
        return {"ok": ~dead, "failed_event": jnp.where(dead, r - 1, -1),
                "overflow": overflow, "frontier": jnp.sum(valid)}

    return jax.jit(jax.vmap(kernel))


def _pad_plan(pl: WGLPlan, R: int, C: int, N: int) -> WGLPlan:
    """Pad a plan's arrays to batch-wide shapes: R events, C candidates,
    N calls."""

    def pad2(x, rows, cols, fill):
        out = np.full((rows, cols), fill, x.dtype)
        out[:x.shape[0], :x.shape[1]] = x
        return out

    def pad1(x, rows, fill):
        out = np.full(rows, fill, x.dtype)
        out[:x.shape[0]] = x
        return out

    return WGLPlan(
        pad1(pl.ret_call, R, -1), pad1(pl.ret_slot, R, 0),
        pad2(pl.cand_call, R, C, -1), pad2(pl.cand_slot, R, C, 0),
        pad1(pl.f, N, 0), pad1(pl.a, N, 0), pad1(pl.b, N, 0),
        pad1(pl.a_ok, N, False), pl.init_state,
        pl.n_calls, pl.n_events, pl.max_open)


def check_many(model, histories: Sequence, *,
               frontier_size: int = 256,
               mesh=None,
               escalate: bool = True,
               stats: Optional[dict] = None) -> list[dict[str, Any]]:
    """Check linearizability of many independent histories in one
    batched device call.  Returns one knossos-shaped result map per
    history (same keys as ops.wgl.check).

    mesh: optional jax.sharding.Mesh; the key axis is sharded over its
    first axis (pure data parallelism — each device checks its shard of
    keys).

    `stats` (always collected; pass a dict to read it back) receives
    the per-stage host-time decomposition — plan / pack / dispatch /
    fetch / assemble seconds — mirroring wgl_seg.check_pipeline's
    protocol, and every verdict carries a dispatch record
    (jepsen_tpu.telemetry)."""
    import jax

    from jepsen_tpu.ops.wgl_seg import _stats_clock

    spec = model.device_spec()
    if spec is None:
        raise BackendUnavailable(f"model {model!r} has no device spec")
    stats = {} if stats is None else stats
    _mt, _acc = _stats_clock(stats)
    t0 = _mt()

    preps = [h if hasattr(h, "calls") else prepare(h) for h in histories]
    results: list[Optional[dict]] = [None] * len(preps)
    lanes = []  # (index, plan)
    for i, prep in enumerate(preps):
        if not prep.calls:
            results[i] = {"valid?": True, "op_count": 0}
            continue
        lanes.append((i, plan(prep, spec, model)))
    t0 = _acc("plan", t0)
    if not lanes:
        return [r for r in results]

    R = _bucket(max(pl.ret_call.shape[0] for _, pl in lanes))
    C = _bucket(max(pl.cand_call.shape[1] for _, pl in lanes), 4)
    N = _bucket(max(pl.n_calls for _, pl in lanes))
    S = lanes[0][1].init_state.shape[0]
    W = max(C, _bucket(max(pl.max_open for _, pl in lanes), 4))

    padded = [_pad_plan(pl, R, C, N) for _, pl in lanes]
    K = len(padded)
    # Pad the key axis to a multiple of the mesh size so it shards evenly.
    K_pad = K
    if mesh is not None:
        d = int(np.prod(list(mesh.shape.values())))
        K_pad = ((K + d - 1) // d) * d
    while len(padded) < K_pad:
        padded.append(padded[0])  # duplicate lane; result ignored

    def stack(attr):
        return np.stack([getattr(p, attr) for p in padded])

    args = [stack("ret_call"), stack("ret_slot"), stack("cand_call"),
            stack("cand_slot"), stack("f"), stack("a"), stack("b"),
            stack("a_ok"), stack("init_state"),
            np.asarray([p.n_events for p in padded], np.int32)]

    stats["wire_bytes"] = (stats.get("wire_bytes", 0)
                           + sum(a.nbytes for a in args))
    t0 = _acc("pack", t0)
    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec
        axis = mesh.axis_names[0]
        sharding = NamedSharding(mesh, PartitionSpec(axis))
        args = [jax.device_put(a, sharding) for a in args]

    kern = _build_batch_kernel(spec.step, int(frontier_size), int(C),
                               int(W), int(S))
    dev = kern(*args)
    t0 = _acc("dispatch", t0)
    out = jax.device_get(dev)
    t0 = _acc("fetch", t0)

    escalated: list = []
    for lane_idx, (i, pl) in enumerate(lanes):
        ok = bool(out["ok"][lane_idx])
        overflow = bool(out["overflow"][lane_idx])
        if ok or not overflow:
            r: dict[str, Any] = {"valid?": ok, "op_count": pl.n_calls,
                                 "frontier_size": frontier_size,
                                 "final_frontier": int(
                                     out["frontier"][lane_idx])}
            if not ok:
                ev = int(out["failed_event"][lane_idx])
                cid = int(pl.ret_call[ev]) if ev >= 0 else -1
                calls = preps[i].calls
                if 0 <= cid < len(calls):
                    r["op"] = calls[cid].op.to_dict()
                    r["op_index"] = calls[cid].op.index
                r["anomaly"] = "nonlinearizable"
            results[i] = r
        elif escalate:
            from jepsen_tpu.ops import wgl
            results[i] = wgl.check(model, preps[i])
            results[i].setdefault("engine", "wgl")
            escalated.append(i)
        else:
            results[i] = {"valid?": "unknown", "cause": "frontier-overflow",
                          "op_count": pl.n_calls}
    _acc("assemble", t0)
    # dispatch records (telemetry): batched lanes vs escalated lanes,
    # both rendering the planner-emitted plan (ops.planner)
    from jepsen_tpu import telemetry as telemetry_mod
    from jepsen_tpu.ops import planner
    mesh_desc = (dict(zip(mesh.axis_names, mesh.devices.shape))
                 if mesh is not None else None)
    batch_plan = planner.plan_engines(
        planner.Shape(kind="batch-many", batch=len(histories),
                      mesh=None if mesh is None else int(
                          np.prod(list(mesh.shape.values()))))).refine(
        why="vmap-over-keys frontier kernel "
            f"(frontier_size={int(frontier_size)})",
        bucket=("wgl_batch", int(frontier_size), int(R), int(C),
                int(W), int(S)))
    batched_rs = [r for i, r in enumerate(results)
                  if isinstance(r, dict) and i not in set(escalated)]
    telemetry_mod.attach_dispatch(
        batched_rs,
        batch_plan.record(engine="wgl_batch", batch=len(histories),
                          mesh=mesh_desc),
        stages=stats)
    for i in escalated:
        telemetry_mod.attach_dispatch(
            [results[i]],
            batch_plan.refine(
                why="frontier overflow on an invalid-looking lane; "
                    "escalated to the adaptive serial kernel").record(
                engine=results[i].get("engine", "wgl"), batch=1))
    return [r for r in results]
