"""Batched WGL linearizability search on TPU — the centerpiece kernel.

The reference delegates linearizability checking to knossos
(`jepsen/src/jepsen/checker.clj:141-145`), a JVM depth-first search whose
cost is "exponential in the number of concurrent operations"
(`doc/tutorial/06-refining.md:7-10`) and which routinely needs a 32 GB
heap (`jepsen/project.clj:30`).  Here the same search is a *breadth-first
frontier* evolved by vectorized kernels:

  configuration = (bitmask over open-call slots, model state int32[S])
  frontier      = fixed-capacity arrays   masks u32[F, Wd], states i32[F, S]

The search walks *return events* in history order (just-in-time
linearization, equivalent to knossos :linear / Lowe's algorithm).  At the
return of call `i`, configurations that have not yet linearized `i` are
expanded by linearizing any currently-open call; expansion repeats (at
most `C` rounds — each round linearizes one more op) until every
surviving configuration contains `i`; configurations that cannot are
pruned.  All expansion, exact dedupe (lexicographic sort over mask+state
words — no hashing, no false merges), and compaction happen on device
with static shapes, so the whole history check is ONE compiled XLA
program (`lax.while_loop` over events).

Per-event cost is adaptive:

  * fast path (no sort): if the returning op is directly legal and
    state-preserving on every configuration still lacking it, the event
    is a pure filter — sound because any closure path that linearizes
    other pending ops first can be *deferred* to a later forcing event
    and reproduces the same (mask, state) configs;
  * tiered closure: otherwise the closure runs in the smallest pool tier
    that fits the live config count, escalating tiers on overflow inside
    the event, so small frontiers sort hundreds — not tens of thousands
    — of rows.

Bitmask slots: a call occupies a slot only while *open* (invoked, return
event not yet processed).  Once its return is processed every surviving
configuration has it linearized, so its bit carries no information and
the slot is recycled.  Crashed (:info) calls never return and hold their
slot forever — the mask width is exactly `max_open` from prep.py, which
is the reference's "a couple crashed processes can make the difference
between seconds and days" cost model (`doc/tutorial/06-refining.md:12-19`)
made explicit.

Capacity policy: a fixed frontier can overflow (the search is NP-hard;
worst case n!).  Overflow never corrupts results — it sets a flag, and:
  * a *valid* verdict is always trustworthy (surviving configs are real
    linearizations);
  * an *invalid* verdict with overflow is reported `unknown`, and the
    caller escalates to a larger frontier (check() retries through
    `frontier_sizes`).
"""

from __future__ import annotations

import dataclasses
import functools
import time
from typing import Any, Optional, Sequence

import numpy as np

from jepsen_tpu.models import DeviceSpec
from jepsen_tpu.ops import frontier
from jepsen_tpu.ops.prep import PreparedHistory, prepare

# ---------------------------------------------------------------------------
# Host-side planning: events -> dense per-return-event candidate tables
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class WGLPlan:
    """Static arrays consumed by the kernel.  R return events, C max
    candidates per event, W mask bits (= max simultaneously-open calls),
    S model-state words."""

    ret_call: np.ndarray     # int32 [R]   returning call id (-1 = padding)
    ret_slot: np.ndarray     # int32 [R]
    cand_call: np.ndarray    # int32 [R, C] open-call ids (-1 = none)
    cand_slot: np.ndarray    # int32 [R, C]
    f: np.ndarray            # int32 [n_calls]
    a: np.ndarray            # int32 [n_calls]
    b: np.ndarray            # int32 [n_calls]
    a_ok: np.ndarray         # bool  [n_calls]
    init_state: np.ndarray   # int32 [S]
    n_calls: int
    n_events: int            # real (unpadded) return events
    max_open: int
    # crashed calls' permanent slots, grouped by identical op encoding
    # (interchangeable tokens), each group in invoke order
    crash_groups: tuple = ()


def _generic_encode_op(op, f_codes) -> tuple[int, int, int, bool]:
    """Default op -> (f, a, b, a_ok) encoding: int values in slot a,
    [a, b] pairs across both, None/unencodable marked not-ok (matches
    the read-with-unknown-value rule in models._register_step)."""
    fc = f_codes.get(op.f, -1)
    v = op.value
    if isinstance(v, bool):
        return fc, int(v), 0, True
    if isinstance(v, int):
        return fc, v, 0, True
    if (isinstance(v, (list, tuple)) and len(v) == 2
            and all(isinstance(x, int) and not isinstance(x, bool) for x in v)):
        return fc, v[0], v[1], True
    return fc, 0, 0, False


def plan(prep: PreparedHistory, spec: DeviceSpec, model,
         pad_events_to: Optional[int] = None,
         pad_cands_to: Optional[int] = None) -> WGLPlan:
    calls = prep.calls
    n = len(calls)

    f = np.zeros(n, np.int32)
    a = np.zeros(n, np.int32)
    b = np.zeros(n, np.int32)
    a_ok = np.zeros(n, bool)
    encode_op = getattr(spec, "encode_op", None) or \
        (lambda op: _generic_encode_op(op, spec.f_codes))
    for c in calls:
        fc, av, bv, okv = encode_op(c.op)
        if fc < 0:
            raise ValueError(f"model has no f-code for {c.op.f!r}")
        if not (-2 ** 31 <= av < 2 ** 31 and -2 ** 31 <= bv < 2 ** 31):
            raise ValueError(
                f"op value {c.op.value!r} exceeds the device kernel's "
                f"int32 range; use ops.wgl_cpu.check for this history")
        f[c.id], a[c.id], b[c.id], a_ok[c.id] = fc, av, bv, okv

    # Slot assignment + per-return-event open sets.  Crashed calls get
    # DEDICATED slots above the normal range (remapped below, like
    # wgl_seg._fast_scan's rn+j pseudo-slots): slot index <-> crashed
    # call identity must be STATIC across the whole history for the
    # kernel's crash-bit pruning — a crashed call on a recycled slot
    # would alias the normal calls that held the slot earlier.
    free: list[int] = []
    next_slot = 0
    n_crashed = 0
    slot_of: dict[int, int] = {}
    open_calls: list[int] = []
    rets: list[tuple[int, int, list[int]]] = []
    for _, kind, cid in prep.events:
        if kind == 0:
            if calls[cid].is_crashed:
                slot_of[cid] = -2 - n_crashed    # placeholder
                n_crashed += 1
            else:
                s = free.pop() if free else next_slot
                if s == next_slot:
                    next_slot += 1
                slot_of[cid] = s
            open_calls.append(cid)
        else:
            rets.append((cid, slot_of[cid], list(open_calls)))
            open_calls.remove(cid)
            free.append(slot_of[cid])
    if n_crashed:
        rn = next_slot
        slot_of = {cid: (s if s >= 0 else rn + (-2 - s))
                   for cid, s in slot_of.items()}
        rets = [(cid, s if s >= 0 else rn + (-2 - s), cands)
                for cid, s, cands in rets]

    # Group crashed calls by identical op encoding: same transition
    # function makes them interchangeable consumption tokens, and
    # grouping them (in invoke order) lets the kernel canonicalize +
    # dominance-prune the crashed-bit combinatorics that otherwise
    # explode exactly like knossos ("a couple crashed processes ...
    # seconds and days", doc/tutorial/06-refining.md:12-19).
    groups: dict = {}
    for c in calls:
        if c.is_crashed:
            groups.setdefault(
                (int(f[c.id]), int(a[c.id]), int(b[c.id]),
                 bool(a_ok[c.id])), []).append(slot_of[c.id])
    crash_groups = tuple(tuple(g) for g in groups.values())

    R = len(rets)
    C = max((len(cands) for _, _, cands in rets), default=1)
    C = max(C, 1)
    if pad_cands_to is not None:
        C = max(C, pad_cands_to)
    Rp = max(R, 1)
    if pad_events_to is not None:
        Rp = max(Rp, pad_events_to)

    ret_call = np.full(Rp, -1, np.int32)
    ret_slot = np.zeros(Rp, np.int32)
    cand_call = np.full((Rp, C), -1, np.int32)
    cand_slot = np.zeros((Rp, C), np.int32)
    for r, (cid, slot, cands) in enumerate(rets):
        ret_call[r] = cid
        ret_slot[r] = slot
        for k, j in enumerate(cands):
            cand_call[r, k] = j
            cand_slot[r, k] = slot_of[j]

    return WGLPlan(ret_call, ret_slot, cand_call, cand_slot,
                   f, a, b, a_ok, np.asarray(spec.encode(model), np.int32),
                   n_calls=n, n_events=R,
                   max_open=max(next_slot + n_crashed, 1),
                   crash_groups=crash_groups)


# ---------------------------------------------------------------------------
# Device kernel
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=64)
def _build_kernel(step_fn, pure_fn, F: int, C: int, W: int, S: int,
                  crash_sizes: tuple | None = None):
    """Compile the frontier search for static shapes.  step_fn must be a
    hashable (module-level or cached) pure function.

    With `crash_groups` (crashed calls' permanent slots grouped by
    identical op encoding, each group in invoke order), the closure
    additionally prunes the crashed-consumption combinatorics — the
    regime where knossos's config set multiplies per crashed op:

      * fungibility canonicalization: same-encoding crashed calls are
        interchangeable tokens, so each group's consumed subset remaps
        to the earliest-invoked prefix (bits are only ever set on
        already-invoked slots, so the prefix is always invoked — the
        exchange swaps a consumed token for an earlier-invoked one,
        which was available whenever the later one was);
      * dominance: a config that consumed a PROPER SUPERSET of crashed
        tokens while agreeing on model state and open-call bits is
        redundant — every completion available to it is available to
        the subset config (crashed tokens carry no obligation).

    Both preserve exact verdicts.  They run inside every closure round
    (the explosion is intra-event), which breaks the count-growth
    termination test — so crash-mode rounds instead stop at an exact
    content fixpoint: dedupe+compaction order output deterministically,
    and a pruned set equal to the previous round's can never change
    again (a dominated config's children are dominated by its
    dominator's children, which expand from the same set).

    `crash_sizes` is None for crash-free histories and a tuple of the
    multi-slot group sizes otherwise — the ONLY crash data in the
    compile cache key.  The actual slot masks/LUTs arrive as runtime
    device arrays (extra kernel args), so every crash-bearing history
    with the same shape signature shares one compiled kernel instead
    of recompiling per history.  Dominance is skipped in escalation
    tiers above _DOM_TIER_CAP: its (P, P) relation matrices are
    quadratic in the pool (4.3 GB at P=65536), and skipping a prune is
    always exact — worst case the big tier overflows and reports
    unknown, as before."""
    import jax
    import jax.numpy as jnp

    Wd = max((W + 31) // 32, 1)
    u32 = jnp.uint32
    # Closure pool tiers: smallest tier that fits the live config count
    # runs first; overflow escalates within the event.
    TIERS = [t for t in (64, 512) if t < F] + [F]

    has_bit, set_bit, clear_bit = frontier.make_bit_ops(Wd)
    dedupe_compact = frontier.make_dedupe_compact(Wd, S)

    crash_mode = crash_sizes is not None
    # LUT row offsets per multi-slot group (static: derived from sizes)
    _lut_off = []
    off = 0
    for size in (crash_sizes or ()):
        _lut_off.append(off)
        off += size + 1

    def canonicalize(masks, gws, luts):
        """Remap each crash group's consumed bits to its invoke-order
        prefix: count the group's set bits, clear them, OR in the
        prefix of that size (two table ops per group).  Bits are only
        ever set on already-invoked slots, so the prefix is always
        invoked.  gws u32[G, Wd]; luts u32[sum(sizes+1), Wd]."""
        for gi, size in enumerate(crash_sizes):
            gw = gws[gi]
            lut = luts[_lut_off[gi]:_lut_off[gi] + size + 1]
            cnt = jax.lax.population_count(masks & gw).sum(
                axis=-1).astype(jnp.int32)
            masks = (masks & ~gw) | lut[cnt]
        return masks

    def dominate(masks, states, valid, cw):
        """Invalidate configs whose crashed-consumption set is a proper
        superset of another config with equal state and open bits.
        cw u32[Wd]: all crashed slots' word mask."""
        crash = masks & cw
        normal = masks & ~cw
        P = masks.shape[0]
        eq = valid[:, None] & valid[None, :]
        for w in range(Wd):
            eq &= normal[:, None, w] == normal[None, :, w]
        for si in range(S):
            eq &= states[:, None, si] == states[None, :, si]
        subset = jnp.ones((P, P), bool)
        proper = jnp.zeros((P, P), bool)
        for w in range(Wd):
            subset &= (crash[:, None, w] & ~crash[None, :, w]) == 0
            proper |= crash[:, None, w] != crash[None, :, w]
        dominated = (eq & subset & proper).any(axis=0)
        return valid & ~dominated

    def compact(masks, states, valid):
        """Re-pack valid configs to the front (cheap: no sort)."""
        keep = valid
        pos = jnp.cumsum(keep) - 1
        count = pos[-1] + 1
        pos = jnp.where(keep, pos, F + 1)
        out_masks = jnp.zeros((F, Wd), u32).at[pos].set(masks, mode="drop")
        out_states = jnp.zeros((F, S), jnp.int32).at[pos].set(
            states, mode="drop")
        out_valid = jnp.arange(F) < count
        return out_masks, out_states, out_valid

    def step_call(states, call, fv, av, bv, okv):
        """Apply call's op to a batch of states.  states i32[..., S]."""
        j = jnp.clip(call, 0, None)
        flat = states.reshape(-1, S)
        st2, legal = jax.vmap(
            lambda st: step_fn(st, fv[j], av[j], bv[j], okv[j]))(flat)
        return (st2.reshape(states.shape),
                legal.reshape(states.shape[:-1]))

    _DOM_TIER_CAP = 4096

    def closure_tier(Fb: int, masks, states, valid, tslot,
                     cc, cs, cf, ca, cb, cok, cwords=None, gws=None,
                     luts=None):
        """Run the closure in a pool of Fb*(C+1); configs live in the
        first Fb rows (caller guarantees count <= Fb).  Returns
        full-F arrays + overflow flag."""
        bm, bs, bv = masks[:Fb], states[:Fb], valid[:Fb]
        open_c = cc >= 0

        def ex_cond(c):
            bm, bs, bv, ovf, rounds, progressed, _ = c
            lacks = bv & ~has_bit(bm, jnp.broadcast_to(tslot, (Fb,)))
            return jnp.any(lacks) & (rounds < C) & progressed & ~ovf

        def ex_body(c):
            bm, bs, bv, ovf, rounds, _, prev_count = c
            lacks = bv & ~has_bit(bm, jnp.broadcast_to(tslot, (Fb,)))

            def per_config(mask, state, lack):
                def per_cand(slot, f_, a_, b_, ok_, is_open):
                    st2, legal = step_fn(state, f_, a_, b_, ok_)
                    not_lin = ~has_bit(mask[None, :], slot[None])[0]
                    okc = lack & is_open & not_lin & legal
                    m2 = set_bit(mask[None, :], slot[None])[0]
                    return m2, st2, okc
                return jax.vmap(per_cand)(cs, cf, ca, cb, cok, open_c)

            chm, chs, chv = jax.vmap(per_config)(bm, bs, lacks)
            pool_m = jnp.concatenate([bm, chm.reshape(Fb * C, Wd)])
            pool_s = jnp.concatenate([bs, chs.reshape(Fb * C, S)])
            pool_v = jnp.concatenate([bv, chv.reshape(Fb * C)])
            if crash_mode and crash_sizes:
                pool_m = jnp.where(pool_v[:, None],
                                   canonicalize(pool_m, gws, luts),
                                   pool_m)
            nm, ns, nv, o2, count = dedupe_compact(
                pool_m, pool_s, pool_v, Fb)
            if crash_mode:
                # In-round pruning breaks the count-growth test below;
                # stop at the exact content fixpoint instead (see the
                # builder docstring for why a stable pruned set can
                # never change again).  Re-pack after dominance so the
                # comparison sees canonical content — stale rows left
                # in dominance holes would read as change every round
                # and burn the full rounds cap.
                nv2 = dominate(nm, ns, nv, cwords) \
                    if Fb <= _DOM_TIER_CAP else nv
                pos = jnp.where(nv2, jnp.cumsum(nv2) - 1, Fb + 1)
                nm = jnp.zeros_like(nm).at[pos].set(nm, mode="drop")
                ns = jnp.zeros_like(ns).at[pos].set(ns, mode="drop")
                nv = jnp.arange(Fb) < jnp.sum(nv2)
                progressed = (jnp.any(nm != bm) | jnp.any(ns != bs)
                              | jnp.any(nv != bv))
            else:
                # Parents are all retained in the pool, so "a new config
                # appeared" is exactly "the DEDUPED count grew vs the
                # previous round's deduped count" — the loop must stop
                # on saturation even while some configs still lack the
                # target (they are pruned afterwards).  Comparing
                # against a raw sum(valid) would be wrong: the frontier
                # entering an event may hold duplicates (configs that
                # differed only in the just-retired slot bit), so round
                # 1 always runs (prev_count starts at -1) and later
                # rounds compare distinct-to-distinct.
                progressed = count > prev_count
            return (nm, ns, nv, ovf | o2, rounds + 1,
                    progressed, count)

        bm, bs, bv, ovf, _, _, _ = jax.lax.while_loop(
            ex_cond, ex_body,
            (bm, bs, bv, jnp.bool_(False), jnp.int32(0), jnp.bool_(True),
             jnp.int32(-1)))

        if Fb == F:
            return bm, bs, bv, ovf
        pm = jnp.zeros((F, Wd), u32).at[:Fb].set(bm)
        ps = jnp.zeros((F, S), jnp.int32).at[:Fb].set(bs)
        pv = jnp.zeros(F, bool).at[:Fb].set(bv)
        return pm, ps, pv, ovf

    def kernel(ret_call, ret_slot, cand_call, cand_slot, fv, av, bv, okv,
               r0, masks0, states0, valid0, n_events, stop_r,
               *crash_args):
        """Walk events r0..min(n_events, stop_r).  The frontier enters
        and leaves as explicit args so check() can CHUNK the walk into
        bounded device programs — a single program spanning tens of
        thousands of events runs long enough to trip device-runtime
        watchdogs on tunneled chips."""
        cwords = gws = luts = None
        if crash_mode:
            cwords, gws, luts = crash_args

        def ev_cond(carry):
            r, _, _, _, dead, _ = carry
            return (r < n_events) & (r < stop_r) & ~dead

        def ev_body(carry):
            r, masks, states, valid, dead, overflow = carry
            tslot = ret_slot[r]
            tcall = ret_call[r]
            cc = cand_call[r]
            cs = cand_slot[r]
            jc = jnp.clip(cc, 0, None)
            cf, ca, cb, cok = fv[jc], av[jc], bv[jc], okv[jc]

            # ---- fast path: the returning op is *pure* (never changes
            # state, e.g. a read) and directly legal on every config
            # still lacking it.  Sound because a pure op's closure
            # variants (linearize pending ops first) produce the same
            # (mask, state) configs as deferring those pending ops to a
            # later forcing event; purity must hold for ALL states (a
            # write that happens to rewrite the current value does NOT
            # qualify — its closure variants diverge). ----
            has = has_bit(masks, jnp.broadcast_to(tslot, (F,)))
            lacking = valid & ~has
            if pure_fn is not None:
                jt = jnp.clip(tcall, 0, None)
                is_pure = pure_fn(fv[jt], av[jt], bv[jt], okv[jt])
                _, legal = step_call(states, tcall, fv, av, bv, okv)
                fast_ok = is_pure & jnp.all(~lacking | legal)
            else:
                fast_ok = jnp.bool_(False)

            def fast(_):
                # every lacking config linearizes the op in place; masks
                # are unchanged after the retire-clear below.
                return masks, states, valid, jnp.bool_(False)

            def slow(_):
                count = jnp.sum(valid)
                # Flattened escalation chain: each tier is traced exactly
                # once (a recursive cond-nest would trace the largest
                # tier 2^(n-1) times).  A tier runs iff no smaller tier
                # succeeded and it can hold the current config count;
                # overflow falls through to the next tier, which reruns
                # the closure from the same event-start frontier.
                out = (masks, states, valid, jnp.bool_(False))
                settled = jnp.bool_(False)
                for i, Fb in enumerate(TIERS):
                    is_last = i == len(TIERS) - 1
                    should = ~settled & ((count <= Fb) | is_last)
                    res = jax.lax.cond(
                        should,
                        functools.partial(
                            lambda Fb, _: closure_tier(
                                Fb, masks, states, valid, tslot,
                                cc, cs, cf, ca, cb, cok,
                                cwords, gws, luts), Fb),
                        lambda _: out, operand=None)
                    accept = should & (~res[3] | is_last)
                    out = tuple(
                        jnp.where(accept, n, o) for n, o in zip(res, out))
                    settled = settled | accept
                m, s, v, ovf = out
                # prune configs that never linearized the returning call
                sat = has_bit(m, jnp.broadcast_to(tslot, (F,)))
                v = v & sat
                m, s, v = compact(m, s, v)
                return m, s, v, ovf

            masks, states, valid, ovf = jax.lax.cond(
                fast_ok, fast, slow, operand=None)
            # retire the returning call's slot
            masks = clear_bit(masks, jnp.broadcast_to(tslot, (F,)))
            dead = ~jnp.any(valid)
            return r + 1, masks, states, valid, dead, overflow | ovf

        r, masks, states, valid, dead, overflow = jax.lax.while_loop(
            ev_cond, ev_body,
            (r0, masks0, states0, valid0, jnp.bool_(False),
             jnp.bool_(False)))
        return {"ok": ~dead, "failed_event": jnp.where(dead, r - 1, -1),
                "overflow": overflow, "frontier": jnp.sum(valid),
                "r": r, "final_masks": masks,
                "final_states": states, "final_valid": valid}

    return jax.jit(kernel)


@functools.lru_cache(maxsize=32)
def _init_frontier_fn(F: int, Wd: int, S: int):
    """Jitted initial-frontier builder: only the S-element init state
    crosses the link; the F-row zero arrays materialize on device."""
    import jax
    import jax.numpy as jnp

    def init(init_state):
        masks = jnp.zeros((F, Wd), jnp.uint32)
        states = jnp.zeros((F, S), jnp.int32).at[0].set(init_state)
        valid = jnp.zeros(F, bool).at[0].set(True)
        return masks, states, valid

    return jax.jit(init)


def init_frontier(F: int, W: int, S: int, init_state):
    """(masks0, states0, valid0) for a frontier of F rows over W mask
    bits — the ONE definition of the frontier layout, shared by check()
    and the driver graft entry."""
    Wd = max((int(W) + 31) // 32, 1)
    return _init_frontier_fn(int(F), Wd, int(S))(
        np.asarray(init_state, np.int32))


def _bucket(x: int, minimum: int = 1) -> int:
    b = minimum
    while b < x:
        b *= 2
    return b


def check(model, history, *,
          frontier_sizes: Sequence[int] = (1024, 8192, 65536),
          pad: bool = True,
          events_per_call: int = 2048) -> dict[str, Any]:
    """Check linearizability of `history` against `model` on the default
    JAX backend, walking events in device programs of at most
    `events_per_call` events (one unbounded program trips tunneled-chip
    watchdogs).  Returns a knossos-shaped analysis map (same keys as
    ops.wgl_cpu.check) plus timing info."""
    import jax

    if events_per_call < 1:
        raise ValueError("events_per_call must be >= 1")

    spec = model.device_spec()
    if spec is None:
        raise ValueError(
            f"model {model!r} has no device spec; use ops.wgl_cpu.check")

    t0 = time.monotonic()
    prep = history if isinstance(history, PreparedHistory) else prepare(history)
    backend_name = jax.default_backend()
    if not prep.calls:
        return {"valid?": True, "op_count": 0, "backend": backend_name}

    # Bucket trace-shapes so repeated checks reuse compiled kernels.
    n_events = sum(1 for _, kind, _ in prep.events if kind == 1)
    pl = plan(prep, spec, model,
              pad_events_to=_bucket(n_events) if pad else None,
              pad_cands_to=_bucket(prep.max_open, 4) if pad else None)
    C = pl.cand_call.shape[1]
    # slots range over [0, max_open); crashed calls' dedicated slots can
    # exceed the concurrent-candidate count C.  Bucketed so same-shaped
    # histories share compiled kernels.
    W = _bucket(max(C, pl.max_open), 4) if pad else max(C, pl.max_open)
    S = pl.init_state.shape[0]
    t_plan = time.monotonic() - t0

    # Crash data splits into a static shape key (multi-group sizes) and
    # runtime device arrays, so same-shaped crash histories share one
    # compiled kernel (see _build_kernel docstring).
    crash_sizes = None
    crash_args: tuple = ()
    if pl.crash_groups:
        Wd = max((int(W) + 31) // 32, 1)
        multi = sorted((g for g in pl.crash_groups if len(g) >= 2),
                       key=len, reverse=True)
        # Bucket each group size (pow2) and pad the group COUNT so the
        # static key collapses to a few shapes; padded rows/size-0
        # groups are inert (cnt never exceeds the real bit count).
        G_pad = _bucket(max(len(multi), 1))
        crash_sizes = tuple(_bucket(len(g)) for g in multi) \
            + (0,) * (G_pad - len(multi))
        cw = np.zeros(Wd, np.uint32)
        for g in pl.crash_groups:
            for slot in g:
                cw[slot // 32] |= np.uint32(1) << (slot % 32)
        gws = np.zeros((G_pad, Wd), np.uint32)
        luts = np.zeros((max(sum(z + 1 for z in crash_sizes), 1), Wd),
                        np.uint32)
        off = 0
        for gi, g in enumerate(multi):
            for i, slot in enumerate(g):
                gws[gi, slot // 32] |= np.uint32(1) << (slot % 32)
                luts[off + i + 1] = luts[off + i]
                luts[off + i + 1, slot // 32] |= \
                    np.uint32(1) << (slot % 32)
            for i in range(len(g), crash_sizes[gi]):
                luts[off + i + 1] = luts[off + i]
            off += crash_sizes[gi] + 1
        crash_args = (cw, gws, luts)

    # Pad the per-call op tables too: every input shape must bucket or
    # the jit re-traces per distinct n_calls.
    fv, av, bv, okv = pl.f, pl.a, pl.b, pl.a_ok
    if pad:
        Np = _bucket(pl.n_calls)
        if Np != len(fv):
            fv = np.concatenate([fv, np.zeros(Np - len(fv), np.int32)])
            av = np.concatenate([av, np.zeros(Np - len(av), np.int32)])
            bv = np.concatenate([bv, np.zeros(Np - len(bv), np.int32)])
            okv = np.concatenate([okv, np.zeros(Np - len(okv), bool)])

    for F in frontier_sizes:
        if F < 1:
            continue
        kern = _build_kernel(spec.step, spec.pure, int(F), int(C), int(W),
                             int(S), crash_sizes)
        masks0, states0, valid0 = init_frontier(F, W, S, pl.init_state)
        t1 = time.monotonic()
        # Chunked walk: each device program covers at most
        # events_per_call events, with the frontier carried across —
        # one program spanning a whole long history runs long enough
        # to trip device-runtime watchdogs on tunneled chips.
        r = 0
        overflow = False
        while True:
            out = kern(pl.ret_call, pl.ret_slot, pl.cand_call,
                       pl.cand_slot, fv, av, bv, okv,
                       np.int32(r), masks0, states0, valid0,
                       np.int32(pl.n_events),
                       np.int32(r + events_per_call), *crash_args)
            ok = bool(out["ok"])
            overflow = overflow or bool(out["overflow"])
            r = int(out["r"])
            if not ok or r >= pl.n_events:
                break
            masks0, states0, valid0 = (out["final_masks"],
                                       out["final_states"],
                                       out["final_valid"])
        t_kernel = time.monotonic() - t1
        if ok or not overflow:
            result: dict[str, Any] = {
                "valid?": ok,
                "op_count": pl.n_calls,
                "backend": backend_name,
                "frontier_size": F,
                "final_frontier": int(out["frontier"]),
                "time_plan_s": t_plan,
                "time_kernel_s": t_kernel,
            }
            if not ok:
                ev = int(out["failed_event"])
                cid = int(pl.ret_call[ev]) if ev >= 0 else -1
                if 0 <= cid < len(prep.calls):
                    call = prep.calls[cid]
                    result["op"] = call.op.to_dict()
                    result["op_index"] = call.op.index
                result["anomaly"] = "nonlinearizable"
            return result
    return {"valid?": "unknown", "cause": "frontier-overflow",
            "op_count": pl.n_calls, "backend": backend_name,
            "frontier_size": frontier_sizes[-1]}
