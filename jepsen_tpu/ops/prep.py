"""History -> call-record preprocessing shared by the CPU oracle and the
TPU WGL kernel.

Semantics (knossos parity, see `doc/tutorial/06-refining.md:7-22`):
  * invoke/completion pairs are matched per process;
  * :fail completions mean the op never happened — the pair is dropped
    entirely (it must never be linearized);
  * :ok completions close the op; reads take their observed value from
    the completion (invoke carries None);
  * :info completions (and invokes that never complete) crash the op: it
    remains concurrent with *everything after it* and may be linearized
    at any later point, or never.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from jepsen_tpu.history import History, Op

INF = 2 ** 62


@dataclasses.dataclass
class Call:
    """One logical operation: an invocation plus its (possible) completion."""

    id: int                 # dense call id, in invocation order
    process: int
    inv: int                # index of invocation event in the filtered history
    ret: int                # index of ok-completion event, or INF if crashed
    op: Op                  # invocation op with resolved value
    completion: Optional[Op]

    @property
    def is_crashed(self):
        return self.ret >= INF


@dataclasses.dataclass
class PreparedHistory:
    calls: list[Call]
    # events: (event_index, kind, call_id); kind 0=invoke 1=return.
    events: list[tuple[int, int, int]]
    max_open: int           # max simultaneously-open calls = mask width bound
    skipped: int            # ops dropped (fail pairs, nemesis, unpaired)


def prepare(history, client_only: bool = True) -> PreparedHistory:
    h = history if isinstance(history, History) else History(history)
    calls: list[Call] = []
    events: list[tuple[int, int, int]] = []
    skipped = 0

    # Filter to client ops ONCE (this function is the host-side hot
    # path for 1M-op histories; the old two-pass per-op type checks
    # dominated multi-key bench wall time).
    if client_only:
        flt = []
        append = flt.append
        for pos, o in enumerate(h.ops):
            p = o.process
            if type(p) is int and p >= 0:
                append((pos, o))
            else:
                skipped += 1
    else:
        flt = list(enumerate(h.ops))

    # First pass: pair ops and decide each invocation's fate.
    open_by_process: dict = {}
    fate: dict[int, tuple[str, Optional[Op]]] = {}  # pos -> (fate, completion)
    for pos, o in flt:
        t = o.type
        if t == "invoke":
            if o.process in open_by_process:
                raise ValueError(f"process {o.process} double-invoked at {pos}")
            open_by_process[o.process] = pos
        else:
            inv_pos = open_by_process.pop(o.process, None)
            if inv_pos is None:
                # Completion without invocation (e.g. history truncation):
                # treat like the reference does — ignore.
                skipped += 1
                continue
            fate[inv_pos] = (t, o)
    for inv_pos in open_by_process.values():
        fate[inv_pos] = ("info", None)  # never completed => crashed

    # Second pass: build calls + events, excluding fail pairs.
    open_count = 0
    max_open = 0
    open_call: dict = {}  # process -> call id of its currently-open call
    no_fate = ("info", None)
    for pos, o in flt:
        t = o.type
        if t == "invoke":
            kind, completion = fate.get(pos, no_fate)
            if kind == "fail":
                skipped += 2
                continue
            cid = len(calls)
            open_call[o.process] = cid
            value = o.value
            if completion is not None and completion.type == "ok" \
                    and value is None:
                value = completion.value
            inv_ev = len(events)
            # copy only when the resolved value differs (reads) — the
            # per-op assoc was the other prep hot spot
            inv_op = o if value is o.value else o.assoc(value=value)
            calls.append(Call(cid, o.process, inv_ev, INF,
                              inv_op, completion))
            events.append((inv_ev, 0, cid))
            open_count += 1
            if open_count > max_open:
                max_open = open_count
        elif t == "ok":
            cid = open_call.pop(o.process, None)
            if cid is None:
                continue
            ev = len(events)
            calls[cid].ret = ev
            events.append((ev, 1, cid))
            open_count -= 1
        elif t == "info":
            # Crashed: the process moves on but the call stays open for
            # linearization purposes forever (its slot is never freed).
            open_call.pop(o.process, None)

    return PreparedHistory(calls, events, max_open, skipped)
