"""Segment-parallel linearizability check — the TPU-shaped fast path.

The serial frontier kernel (`ops.wgl`) walks return events one at a time
inside a `lax.while_loop`; its wall-clock is bounded by *serial depth*
(~one loop iteration per return event), which no accelerator can hide.
This module removes that bound for the common case — crash-free
histories over models with a small enumerable state space (registers,
mutexes: exactly the models behind `checker/linearizable` register
workloads, `tests/linearizable_register.clj:33`, `etcd.clj:157`) — by
reformulating the check as three data-parallel stages:

1. **Enumerate** the model's reachable states `Q` (|Q| = Sn) by closing
   the initial state under every distinct op in the history, and tabulate
   the transition relation `next[u, s] -> s'`, `legal[u, s]` for the U
   distinct ops.

2. **Cut** the history at *quiescent points* — moments with zero open
   calls — into K segments.  Linearizability is compositional across
   such cuts: every call is invoked and returned within one segment, so
   the only information flowing across a cut is the model state.  Each
   segment therefore defines a boolean *transfer matrix*
   `T_k[s0, s1] = "state s1 reachable at the cut after segment k, having
   entered with state s0"`.  All K×Sn transfer rows are computed **in
   parallel** (`vmap` over segments × start states): per (segment,
   start), the frontier is not a sorted list of configurations but a
   dense boolean tensor `fr[mask, state]` over (open-call bitmask ×
   model state) — per-event expansion, dedupe, pruning and slot
   retirement are O(2^R × Sn) masked gathers and tiny matmuls with *no
   sorting*.  Serial depth drops from #events to #events / K.

3. **Compose** the K transfer matrices left-to-right (K boolean
   matvecs): the history is linearizable iff a state survives all cuts.

Semantics are just-in-time linearization (Lowe / knossos :linear), same
as `ops.wgl`:

  * at the return of call `t`, the frontier is closed under linearizing
    any currently-open calls (to fixpoint — exact, monotone), then
    pruned to configurations containing `t`, then `t`'s slot is retired;
  * closure only at return events is complete: a window only closes at
    a return, so any linearization between returns can be deferred to
    the closure of the next return event.

Crashed (`:info`) calls — the reference's worst cost driver ("a couple
crashed processes can make the difference between seconds and days",
`doc/tutorial/06-refining.md:12-19`, `doc/tutorial/07-parameters.md:150-152`)
— are handled in three exact tiers (see _check_crashed_fast): inert
crashed calls (identity + always-legal, e.g. reads) are dropped
outright; up to `_MAX_CRASHED` remaining crashed calls ride the kernel
as permanent mask slots with a `J = Sn * 2^nc` entry-configuration axis
(cuts count open NORMAL calls only — "quiescent modulo crashed");
beyond the bound, a valid verdict on the crash-stripped history proves
validity at full speed (crashed calls carry no obligation).  Only the
residual case — many effect-bearing crashes on a history the stripped
pass cannot prove valid — falls back to the serial engines.

Scope guard: models whose state space does not close within
`max_states` (and the residual crash case above) raise `Unsupported`,
and callers fall back to `ops.wgl` / `ops.wgl_cpu`.

Verdict trust: both verdicts are exact (no frontier capacity exists to
overflow — the bitmap covers the whole configuration space).  On
invalid, the failing op is localized by re-running the CPU oracle on
the prefix through the first dead segment.
"""

from __future__ import annotations

import functools
import os
import threading
import time
from typing import Any, Optional

import numpy as np

from jepsen_tpu.history import History, PackedHistory
from jepsen_tpu.ops.prep import PreparedHistory, prepare
from jepsen_tpu.ops.frontier import (make_plane_ops as _bit_ops,
                                     reshape_shift as _reshape_shift)

# Host-side planning (scanning, segmentation, slot assignment, state
# enumeration, decomposition) and the engine-routing decision live in
# ops.planner (ISSUE 8); every name is re-exported here for the
# long-standing `wgl_seg.<name>` callers and the differential
# batteries.  This module keeps the device kernels and entry points.
from jepsen_tpu.ops import planner
from jepsen_tpu.ops.planner import (  # noqa: F401 - re-exports
    _MAX_CRASHED, SegPlan, Unsupported, _FastKey, _RegsLayout,
    _StreamKey, _assign_slots, _cols_args, _compact_many_block,
    _compose_transfer, _decompose, _encode_calls, _enumerate_states,
    _expand_fn, _fast_scan, _fastkey_from_native, _fill_block_stream,
    _fk_arrays, _native_scan, _native_scan_cols, _native_scan_streams,
    _next_pow2, _pack_cand_tables, _pack_regs, _pack_regs_single,
    _pack_uop_tables, _pad_len, _regs_eligible, _regs_fill,
    _regs_fill_compact, _scan_history, _segment_ends,
    _segments_from_fk, _split_crashed, plan)


# ---------------------------------------------------------------------------
# Device kernel — bit-packed mask axis
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=32)
def _build_kernel_bits(K: int, L: int, C: int, Wd: int, Sn: int, R: int,
                       decomposed: bool, J: int, rounds: int = 0,
                       unroll: int = 1):
    """Bit-packed variant of the frontier kernel: the 2^R mask axis
    lives in the BITS of `Wd = max(1, 2^R/32)` uint32 words, so the
    frontier is `fr[Wd, Sn, J, K]` uint32 — 16-32x smaller than the
    dense 0/1 tensor, and every mask operation is a constant-pattern
    bitwise op:

      * configs lacking slot b (b<5):   x & _INTRA[b]
      * linearize slot b (set bit):     (x & _INTRA[b]) << 2^b
      * retire slot b (prune+clear):    (x & ~_INTRA[b]) >> 2^b
      * slots b>=5 shift whole words along the word axis instead.

    State transitions use the diagonal + rank-1 decomposition when
    available (any Sn), else an unrolled s->t select-OR (Sn <= 16);
    callers fall back to the dense bf16 kernel otherwise.

    `rounds > 0` replaces the dynamic closure `while_loop` with exactly
    `rounds` statically-unrolled expansion rounds.  `rounds = R` is
    EXACT: a closure sequence linearizes each open call at most once
    (its slot bit is set and never cleared until retirement), at most R
    calls are open, and round k unions in every config reachable by <= k
    linearizations — so the fixpoint is reached by round R.  Removing
    the data-dependent loop lets XLA fuse the whole event step and
    pipeline the scan (`unroll` events per loop iteration), which on a
    latency-bound chip beats early exit: the dynamic loop's per-round
    popcount condition costs more than the 2-3 "wasted" rounds."""
    import jax
    import jax.numpy as jnp

    u32 = jnp.uint32
    lacking, set_slot, retire_slot, sel32 = _bit_ops(Wd, R)

    def popcount(x):
        return jax.lax.population_count(x).astype(jnp.int32).sum()

    def kern(ret_slot, cand_slot, cand_aux1, cand_aux2, cand_t0):
        # fr[w, s, j, k]; bit i of word w = mask index w*32+i.
        # Decomposed: aux1/aux2 = uint32 per-candidate state-bitmasks of
        # the diag/const weights (bit s set iff weight[s]); no device
        # gathers — all tables are host-precomputed per event.
        # Non-decomposed: aux1/aux2 = uint32 bitmasks of legality and a
        # packed next-state nibble table (4 bits per state, Sn <= 8) —
        # callers gate accordingly.
        if J == Sn:
            fr0 = jnp.zeros((Wd, Sn, J, K), u32).at[0].set(
                (jnp.eye(Sn, dtype=u32)[:, :, None]
                 * jnp.ones((1, 1, K), u32)))
        else:
            fr0 = jnp.zeros((Wd, Sn, J, K), u32).at[0, 0, 0, :].set(1)

        s_iota = jnp.arange(Sn, dtype=jnp.int32)

        def event(fr, ev):
            # Tables travel host->device in the narrowest dtype that fits
            # (int8 slots, uint8/16/32 bitmasks — the device tunnel's
            # bandwidth, not compute, bounds large batches); upcast the
            # per-event slices here.
            rs, cslot, aux1, aux2, ct0 = ev           # [K], then [K,C]x4
            rs = rs.astype(jnp.int32)
            cslot = cslot.astype(jnp.int32)
            aux1 = aux1.astype(u32)
            aux2 = aux2.astype(u32)
            ct0 = ct0.astype(jnp.int32)

            def expand_candidate(fr, c):
                """All legal single-linearizations of candidate c."""
                slot_kc = cslot[:, c]                  # [K]
                # contrib: configs lacking c's slot (select static slot
                # variant per segment/key)
                contrib = jnp.zeros_like(fr)
                for b in range(R):
                    contrib = contrib | (
                        lacking(fr, b) & sel32(slot_kc == b))
                # state transition s -> t
                if decomposed:
                    # [Sn, K] selects from per-candidate bitmasks
                    dsel = sel32(((aux1[:, c][None, :]
                                   >> s_iota[:, None]) & 1) == 1)
                    moved = contrib & dsel[None, :, None, :]  # identity
                    csel = sel32(((aux2[:, c][None, :]
                                   >> s_iota[:, None]) & 1) == 1)
                    red = contrib & csel[None, :, None, :]
                    # OR over s, place at t0
                    red = jax.lax.reduce(
                        red, np.uint32(0), jax.lax.bitwise_or, (1,))
                    at_t0 = sel32(s_iota[:, None] == ct0[None, :, c])
                    moved = moved | (red[:, None, :, :]
                                     & at_t0[None, :, None, :])
                else:
                    lsel = sel32(((aux1[:, c][None, :]
                                   >> s_iota[:, None]) & 1) == 1)
                    nxt = (aux2[:, c][None, :]
                           >> (4 * s_iota[:, None])) & 15   # [Sn, K]
                    moved = jnp.zeros_like(fr)
                    for s in range(Sn):
                        src = contrib[:, s] & lsel[None, s, None, :]
                        for t in range(Sn):
                            m_t = src & sel32(nxt[s] == t)[None, None, :]
                            moved = moved.at[:, t].set(moved[:, t] | m_t)
                # set the slot bit
                out = jnp.zeros_like(fr)
                for b in range(R):
                    out = out | (set_slot(moved, b) & sel32(slot_kc == b))
                return out

            # lacking-target pattern (zero for pad rows -> no rounds)
            def lack_target(fr):
                lt = jnp.zeros_like(fr)
                for b in range(R):
                    lt = lt | (lacking(fr, b) & sel32(rs == b))
                return lt & sel32(rs >= 0)[None, None, None, :]

            def one_round(fr):
                add = jnp.zeros_like(fr)
                for c in range(C):
                    add = add | expand_candidate(fr, c)
                return fr | add

            if rounds > 0:
                for _ in range(rounds):
                    fr = one_round(fr)
            else:
                def round_(carry):
                    fr, _, prev = carry
                    fr2 = one_round(fr)
                    cnt = popcount(fr2)
                    return (fr2,
                            (cnt > prev) & (popcount(lack_target(fr2)) > 0),
                            cnt)

                fr, _, _ = jax.lax.while_loop(
                    lambda cy: cy[1], round_,
                    (fr, popcount(lack_target(fr)) > 0, jnp.int32(-1)))

            # prune + retire the returning slot
            cleared = jnp.zeros_like(fr)
            for b in range(R):
                cleared = cleared | (retire_slot(fr, b) & sel32(rs == b))
            fr = jnp.where((rs >= 0)[None, None, None, :], cleared, fr)
            return fr, None

        fr, _ = jax.lax.scan(
            event, fr0, (ret_slot, cand_slot, cand_aux1, cand_aux2, cand_t0),
            unroll=unroll)
        # mask 0 = bit 0 of word 0
        return (fr[0] & 1).transpose(2, 1, 0)          # [K, J, Sn]

    return jax.jit(kern)


@functools.lru_cache(maxsize=32)
def _build_kernel_regs(K: int, L: int, I: int, Wd: int, Sn: int, R: int,
                       decomposed: bool, rounds: int, unroll: int,
                       J: int = 1, nc: int = 0, rn: int = 0,
                       compose: bool = False, crash_closure: bool = False,
                       death_row: bool = False, sn_words: int = 1):
    """Register-delta variant of the bit-packed batch kernel (J=1 for
    independent whole histories; J=Sn computes per-segment transfer
    matrices for the single-history path, one lane per segment).

    The candidate-table kernel ships the FULL open-call set per return
    ([L, K, C] x 4 tables, ~23 MB for the 1M-op bench) even though the
    open set changes by ~one call per return; on a tunneled chip the
    host->device transfer, not compute, bounds throughput (measured
    ~0.45 s transfer vs ~0.12 s compute).  Here the device maintains the
    open set itself in per-slot registers (aux words [R, K] carried
    through the scan), and the host ships only the NEWLY-INVOKED calls
    per return row — at most I per row, with bursts spilling into
    virtual rows (ret_slot = -1: closure still runs there — a monotone
    union of configs the return row reaches anyway — but nothing is
    pruned or retired).  Transfer drops to [L', K] x (1 + 2I) bytes
    (~5.5 MB for the same bench).

    A second win falls out: candidates are now indexed BY slot, so the
    closure's per-candidate 2R slot-select masks disappear — slot b's
    expansion uses its static bit patterns directly.

    Closure semantics and the rounds=R exactness argument are identical
    to _build_kernel_bits (see its docstring); this builder only
    supports fixed rounds (callers gate R <= 6 to the candidate-table
    dynamic loop).  Transition tables are [U]-indexed on device (tiny
    per-step gathers) in the same decomposed / nibble forms.

    With `sn_words = W > 1` (the crash-relaxed tier's wide-state lift,
    VERDICT r3 #5): every per-state bitmask — the decomposed aux
    tables/registers, the epsilon-closure rows, the death_row seed —
    becomes W uint32 words, supporting Sn <= 32*W states; state row s
    reads word s // 32, bit s % 32.  W = 1 keeps the legacy
    single-word shapes bit-for-bit.

    With `death_row` (J = 1, one extra runtime arg `seed_mask`
    u32[W]):
    the frontier is seeded with the SET of states in seed_mask at mask
    0 (the composed verdict's reachable-entry mask) and the scan
    additionally reports the first row index at which the frontier
    empties (-1 = survives) — the per-return death localization the
    crash-relaxed refutation tier uses to name an exact witness op
    without any oracle.

    With `nc > 0` (crashed-call support, J = Sn * 2^nc): crashed calls
    hold permanent slots rn..rn+nc-1 — registered like invokes, never
    retired, free to linearize at any return's closure or never.  Lane
    entry/exit configurations become (crashed-linearized-mask x state)
    pairs: fr0 seeds one entry config per J index (j = cm * Sn + s,
    mask = cm << rn), and the output reads the 2^nc crashed-mask planes
    at zero normal bits, giving [K, J, 2^nc * Sn] transfer matrices.
    This removes the reference's worst scaling cliff — knossos treats a
    crashed op as concurrent with the entire rest of the history
    (doc/tutorial/06-refining.md:12-19); here it costs 2^nc extra
    frontier width instead of exponential search."""
    import jax
    import jax.numpy as jnp

    u32 = jnp.uint32
    lacking, set_slot, retire_slot, sel32 = _bit_ops(Wd, R)
    b_iota = np.arange(R, dtype=np.int32)[:, None]          # [R, 1]

    def kern(ret_slot, inv_slot, inv_uop, aux1_tab, aux2_tab, t0_tab,
             *closure_args):
        # ret_slot [L, K] i8; inv_slot/inv_uop [L, K, I] i8/i16;
        # aux1_tab/aux2_tab [U] u32, t0_tab [U] i32.  With
        # crash_closure: closure_args = (crow i32 [L, K] row index,
        # ctab u32 [nC, Sn]) — per-state next-masks, reflexively and
        # transitively closed ON HOST, applied between expansion rounds
        # (see _relaxed_refute for the exactness argument).
        seed_mask = None
        if death_row:
            *closure_args, seed_mask = closure_args
        if crash_closure:
            crow_all, ctab = closure_args

            def close_states(fr, nm):
                # nm [K, Sn, W] u32: bit t%32 of word t//32 of
                # nm[k, s] = s->t allowed.  One gather + shift builds
                # the full [K, s2, t] allow tensor and a single
                # OR-reduction contracts the source-state axis — O(1)
                # HLO ops instead of the Sn^2 unrolled select-ORs that
                # made wide-state (Sn ~ 40) compiles take minutes.
                t_i = np.arange(Sn)
                words = nm[:, :, t_i // 32]          # [K, s2, t]
                sel = sel32(((words >> jnp.asarray(
                    t_i % 32, jnp.uint32)) & 1) == 1)
                contrib = (fr[:, :, None, :, :]
                           & sel.transpose(1, 2, 0)[None, :, :, None, :])
                return jax.lax.reduce(contrib, np.uint32(0),
                                      jax.lax.bitwise_or, (1,))
        if J > 1:
            # one lane per (segment, entry config): j = cm * Sn + s with
            # mask cm << rn (cm = 0 when nc = 0, reducing to the eye)
            fr0_np = np.zeros((Wd, 32, Sn, J), np.uint32)
            for cm in range(1 << nc):
                m0 = cm << rn
                for s in range(Sn):
                    fr0_np[m0 // 32, m0 % 32, s, cm * Sn + s] = 1
            fr0_np = (fr0_np << np.arange(32, dtype=np.uint32)
                      [None, :, None, None]).sum(1, dtype=np.uint32)
            fr0 = jnp.asarray(fr0_np)[..., None] * jnp.ones((K,), u32)
        elif death_row:
            # seed the single lane with every state in seed_mask
            # (u32[W]) at mask index 0 (bit 0 of word 0)
            si = np.arange(Sn)
            sm = jnp.asarray(seed_mask, u32)
            sb = ((sm[si // 32] >> jnp.asarray(si % 32, u32))
                  & 1).astype(u32)
            fr0 = jnp.zeros((Wd, Sn, 1, K), u32).at[0, :, 0, :].set(
                sb[:, None] * jnp.ones((K,), u32))
        else:
            fr0 = jnp.zeros((Wd, Sn, 1, K), u32).at[0, 0, 0, :].set(1)
        aw = (R, K) if sn_words == 1 else (R, K, sn_words)
        reg0 = (jnp.zeros(aw, u32), jnp.zeros(aw, u32),
                jnp.zeros((R, K), jnp.int32), jnp.zeros((R, K), bool))
        s_iota = jnp.arange(Sn, dtype=jnp.int32)

        def event(carry, ev):
            if death_row:
                fr, a1r, a2r, t0r, openr, row, dead = carry
            else:
                fr, a1r, a2r, t0r, openr = carry
            if crash_closure:
                rs, isl, iu, cr = ev
                nm = ctab[cr.astype(jnp.int32)]           # [K, Sn]
            else:
                rs, isl, iu = ev
            rs = rs.astype(jnp.int32)
            isl = isl.astype(jnp.int32)
            iu = iu.astype(jnp.int32)

            # --- register the row's new invokes -----------------------
            for i in range(I):
                u = iu[:, i]
                uc = jnp.clip(u, 0, None)
                m = (u >= 0)[None, :] & (isl[:, i][None, :] == b_iota)
                ma = m if sn_words == 1 else m[..., None]
                a1r = jnp.where(ma, aux1_tab[uc][None], a1r)
                a2r = jnp.where(ma, aux2_tab[uc][None], a2r)
                t0r = jnp.where(m, t0_tab[uc][None, :], t0r)
                openr = openr | m
            if crash_closure:
                # jumps BEFORE any linearization at this return
                fr = close_states(fr, nm)

            # --- closure: rounds x per-slot expansion -----------------
            for _ in range(rounds):
                add = jnp.zeros_like(fr)
                for b in range(R):
                    contrib = (lacking(fr, b)
                               & sel32(openr[b])[None, None, None, :])
                    if decomposed:
                        if sn_words == 1:
                            a1b = a1r[b][None, :]        # [1, K]
                            a2b = a2r[b][None, :]
                            sh = s_iota[:, None]
                        else:
                            # state row s reads word s//32, bit s%32
                            si = np.arange(Sn)
                            a1b = a1r[b].T[si // 32]     # [Sn, K]
                            a2b = a2r[b].T[si // 32]
                            sh = jnp.asarray(si % 32)[:, None]
                        dsel = sel32(((a1b >> sh) & 1) == 1)
                        moved = contrib & dsel[None, :, None, :]
                        csel = sel32(((a2b >> sh) & 1) == 1)
                        red = jax.lax.reduce(
                            contrib & csel[None, :, None, :],
                            np.uint32(0), jax.lax.bitwise_or, (1,))
                        at_t0 = sel32(s_iota[:, None] == t0r[b][None, :])
                        moved = moved | (red[:, None, :, :]
                                         & at_t0[None, :, None, :])
                    else:
                        lsel = sel32(((a1r[b][None, :]
                                       >> s_iota[:, None]) & 1) == 1)
                        nxt = (a2r[b][None, :]
                               >> (4 * s_iota[:, None])) & 15   # [Sn, K]
                        moved = jnp.zeros_like(fr)
                        for s in range(Sn):
                            src = contrib[:, s] & lsel[None, s, None, :]
                            for t in range(Sn):
                                m_t = src & sel32(nxt[s] == t)[None, None, :]
                                moved = moved.at[:, t].set(moved[:, t] | m_t)
                    add = add | set_slot(moved, b)
                fr = fr | add
                if crash_closure:
                    # jumps between consecutive linearizations
                    fr = close_states(fr, nm)

            # --- prune + retire the returning slot --------------------
            cleared = jnp.zeros_like(fr)
            for b in range(R):
                cleared = cleared | (retire_slot(fr, b) & sel32(rs == b))
            fr = jnp.where((rs >= 0)[None, None, None, :], cleared, fr)
            openr = openr & ~(rs[None, :] == b_iota)
            if death_row:
                alive = jax.lax.population_count(fr).astype(
                    jnp.int32).sum()
                dead = jnp.where((dead < 0) & (alive == 0), row, dead)
                return (fr, a1r, a2r, t0r, openr, row + 1, dead), None
            return (fr, a1r, a2r, t0r, openr), None

        xs = (ret_slot, inv_slot, inv_uop)
        if crash_closure:
            xs = xs + (closure_args[0],)
        carry0 = (fr0,) + reg0
        if death_row:
            carry0 = carry0 + (jnp.int32(0), jnp.int32(-1))
        (fr, *rest), _ = jax.lax.scan(event, carry0, xs,
                                      unroll=unroll)
        if death_row:
            return rest[-1]
        if nc == 0:
            out = (fr[0] & 1).transpose(2, 1, 0)       # [K, J, Sn]
        else:
            # read the 2^nc crashed-mask planes at zero normal bits
            planes = []
            for cm in range(1 << nc):
                m = cm << rn
                planes.append((fr[m // 32] >> np.uint32(m % 32)) & 1)
            outp = jnp.stack(planes)                   # [2^nc, Sn, J, K]
            out = outp.transpose(3, 2, 0, 1).reshape(
                K, J, (1 << nc) * Sn)                  # j' = cm*Sn + s
        if not compose:
            return out
        # On-device composition (single-history path): prefix products
        # of the per-segment transfer matrices via an associative scan
        # — log2(K) levels of batched [J, J] matmuls on the MXU —
        # instead of downloading [K, J, J] matrices over the tunnel and
        # composing on host.  The verdict comes back as SIX int32 words
        # (valid, first-dead-segment, 128-bit entry-config mask of the
        # dead segment): one fixed-latency fetch.  Exactness: boolean
        # matrix product is associative; `alive` is monotone (the empty
        # state set is absorbing), so sum(alive) IS the first dead
        # index; the entry mask = reachable configs at the cut BEFORE
        # the dead segment, which witness localization replays from.
        Tm = out.astype(jnp.float32)                   # [K, J, J]
        P = jax.lax.associative_scan(
            lambda a, b: (jnp.einsum("kij,kjl->kil", a, b) > 0)
            .astype(jnp.float32), Tm, axis=0)
        alive = (P[:, 0, :] > 0).any(axis=1)           # entry config 0
        valid = alive[-1]
        dead = jnp.where(valid, jnp.int32(-1),
                         jnp.sum(alive.astype(jnp.int32)))
        Jw = out.shape[1]
        reach = P[jnp.clip(dead - 1, 0, K - 1), 0, :] > 0   # [J]
        entry0 = jnp.zeros((Jw,), bool).at[0].set(True)
        entry = jnp.where(valid, False,
                          jnp.where(dead > 0, reach, entry0))
        em = [jnp.uint32(0)] * 4
        for j in range(min(Jw, 128)):
            em[j // 32] = em[j // 32] | (
                entry[j].astype(jnp.uint32) << np.uint32(j % 32))
        return jnp.stack(
            [valid.astype(jnp.int32), dead]
            + [jax.lax.bitcast_convert_type(w, jnp.int32) for w in em])

    return jax.jit(kern)


def _unpack_transfer_bufs(buf8, buf32, B: int, L: int, K: int, I: int,
                          U: int, wide_uop: bool):
    """Device-side unpack of the two transfer buffers into the six
    kernel tables (shared by the single-history and grouped builders —
    the buffer layout and the little-endian int16 reassembly live
    ONLY here).  buf8 holds B consecutive per-history blocks, each
    ret[L,K] i8 ++ islot[L,K,I] i8 ++ iuop[L,K,I] i8|i16; with B > 1
    the histories concatenate on the lane axis (ret [L, B*K], ...).
    buf32 = a1[U] ++ a2[U] ++ t0[U]."""
    import jax
    import jax.numpy as jnp

    n_ret = L * K
    n_islot = L * K * I
    n_iuop = L * K * I * (2 if wide_uop else 1)
    per = n_ret + n_islot + n_iuop
    blocks = buf8.reshape(B, per)

    def lanes(x):                    # [B, L, ...] -> [L, B*K, ...]
        x = jnp.moveaxis(x, 0, 1)
        return x.reshape((L, B * K) + x.shape[3:])

    ret = lanes(jax.lax.bitcast_convert_type(
        blocks[:, :n_ret], jnp.int8).reshape(B, L, K))
    islot = lanes(jax.lax.bitcast_convert_type(
        blocks[:, n_ret:n_ret + n_islot], jnp.int8).reshape(B, L, K, I))
    raw = blocks[:, n_ret + n_islot:per]
    if wide_uop:                     # little-endian int16 from 2 bytes
        pairs = raw.reshape(B, L, K, I, 2)
        lo = pairs[..., 0].astype(jnp.int32)
        hi = jax.lax.bitcast_convert_type(
            pairs[..., 1], jnp.int8).astype(jnp.int32)
        iuop = lanes(lo | (hi << 8))
    else:
        iuop = lanes(jax.lax.bitcast_convert_type(
            raw, jnp.int8).reshape(B, L, K, I))
    a1 = buf32[:U]
    a2 = buf32[U:2 * U]
    t0 = jax.lax.bitcast_convert_type(buf32[2 * U:3 * U], jnp.int32)
    return ret, islot, iuop, a1, a2, t0


@functools.lru_cache(maxsize=16)
def _build_kernel_regs_relaxed(K: int, L: int, I: int, Wd: int,
                               Sn: int, R: int, decomposed: bool,
                               rounds: int, unroll: int, U: int,
                               wide_uop: bool, nC: int,
                               sn_words: int = 1):
    """Packed composed kernel under RELAXED crash semantics: crashed
    ops are position-dependent epsilon-transitions whose reflexive-
    transitive closures ride as a [nC, Sn] uint32 table (appended to
    buf32); each event row carries an i16 index into it (appended to
    buf8).  nC is bucket-padded by the caller so shapes recompile
    rarely.  Output = the same int32[6] composed verdict."""
    import jax
    import jax.numpy as jnp

    kern = _build_kernel_regs(K, L, I, Wd, Sn, R, decomposed,
                              rounds=rounds, unroll=unroll, J=Sn,
                              nc=0, rn=0, compose=True,
                              crash_closure=True, sn_words=sn_words)
    n_crow = L * K * 2               # i16
    W = sn_words

    def fn(buf8, buf32):
        base = len(buf8) - n_crow
        if W == 1:
            tabs = _unpack_transfer_bufs(buf8[:base], buf32[:3 * U],
                                         1, L, K, I, U, wide_uop)
        else:
            # wide-state aux layout: a1[U,W] ++ a2[U,W] ++ t0[U]
            na = U * W
            t8 = _unpack_transfer_bufs(
                buf8[:base],
                jnp.zeros(3 * U, jnp.uint32), 1, L, K, I, U, wide_uop)
            tabs = t8[:3] + (
                buf32[:na].reshape(U, W),
                buf32[na:2 * na].reshape(U, W),
                jax.lax.bitcast_convert_type(
                    buf32[2 * na:2 * na + U], jnp.int32))
        pairs = buf8[base:].reshape(L, K, 2)
        lo = pairs[..., 0].astype(jnp.int32)
        hi = jax.lax.bitcast_convert_type(
            pairs[..., 1], jnp.int8).astype(jnp.int32)
        crow = lo | (hi << 8)
        aux_n = 3 * U if W == 1 else 2 * U * W + U
        ctab = buf32[aux_n:].reshape(nC, Sn, W)
        return kern(*tabs, crow, ctab)

    return jax.jit(fn)


@functools.lru_cache(maxsize=32)
def _build_kernel_regs_packed(K: int, L: int, I: int, Wd: int, Sn: int,
                              R: int, decomposed: bool, rounds: int,
                              unroll: int, J: int, nc: int, rn: int,
                              U: int, wide_uop: bool):
    """Packed-transfer wrapper around the composed register kernel: the
    six host tables travel as TWO buffers (one uint8 for the [L, K(, I)]
    event tables, one uint32 for the [U] transition tables) instead of
    six separate device_puts — on the tunneled chip each transfer pays
    a fixed latency that dominated the old 6-put plan.  Unpacking is
    free on device (bitcasts + reshapes fused into the kernel)."""
    import jax

    kern = _build_kernel_regs(K, L, I, Wd, Sn, R, decomposed,
                              rounds=rounds, unroll=unroll, J=J, nc=nc,
                              rn=rn, compose=True)

    def fn(buf8, buf32):
        return kern(*_unpack_transfer_bufs(buf8, buf32, 1, L, K, I, U,
                                           wide_uop))

    return jax.jit(fn)




@functools.lru_cache(maxsize=32)
def _build_kernel_regs_group_c(B: int, K: int, L: int, Wd: int,
                               Sn: int, R: int, decomposed: bool,
                               rounds: int, unroll: int, U: int,
                               Rp: int):
    """Grouped composed kernel over the COMPACT wire format (I = 1):
    B histories' blocks travel as ONE uint8 buffer, each carrying the
    segment-major row STREAMS of _regs_fill_compact instead of padded
    [L, K] tables; the padded tables are rebuilt on device with one
    masked gather per table (table[l, k] = stream[cum[k] + l] where
    l < rows_k, sentinel otherwise) — a few fused [L, K] gathers, free
    next to the event scan, while the tunnel carries ~10x fewer bytes
    than padded tables would (the wire bounds the easy regime).  The
    per-segment transfer matrices are composed per history by a
    batched associative scan; output is int32 [B, 6] (valid,
    first-dead, 128-bit entry mask)."""
    import jax
    import jax.numpy as jnp

    J = Sn
    ub = 1 if U <= 255 else 2
    per = Rp * (1 + ub) + 4 * (K + 1)
    kern = _build_kernel_regs(B * K, L, 1, Wd, Sn, R, decomposed,
                              rounds=rounds, unroll=unroll, J=J,
                              nc=0, rn=0, compose=False)
    l_iota = np.arange(L, dtype=np.int32)[:, None]      # [L, 1]

    def fn(buf8, buf32):
        blocks = buf8.reshape(B, per)
        cum = jax.lax.bitcast_convert_type(
            blocks[:, Rp * (1 + ub):].reshape(B, K + 1, 4),
            jnp.int32)                                   # [B, K+1]
        start = cum[:, :K]                               # [B, K]
        nrows = cum[:, 1:] - start                       # [B, K]
        idx = jnp.clip(start[:, None, :] + l_iota[None], 0, Rp - 1)
        live = l_iota[None] < nrows[:, None, :]          # [B, L, K]
        b_ix = jnp.arange(B)[:, None, None]
        rows8 = jnp.where(live, blocks[:, :Rp][b_ix, idx],
                          jnp.uint8(0)).astype(jnp.int32)
        ret = (rows8 & 15) - 1
        islot = (rows8 >> 4) - 1
        if ub == 1:
            iu = blocks[:, Rp:2 * Rp].astype(jnp.int32)
        else:
            pairs = blocks[:, Rp:3 * Rp].reshape(B, Rp, 2)
            iu = (pairs[..., 0].astype(jnp.int32)
                  | (pairs[..., 1].astype(jnp.int32) << 8))
        iuop = jnp.where(live, iu[b_ix, idx], jnp.int32(0))
        # liveness rides islot's -1 sentinel (the kernel registers a
        # slot only where islot == b), so iuop needs no sentinel

        def lanes(x):                    # [B, L, K] -> [L, B*K, 1]
            return jnp.moveaxis(x, 0, 1).reshape(L, B * K, 1)

        a1 = buf32[:U]
        a2 = buf32[U:2 * U]
        t0 = jax.lax.bitcast_convert_type(buf32[2 * U:3 * U], jnp.int32)
        out = kern(lanes(ret)[..., 0], lanes(islot), lanes(iuop),
                   a1, a2, t0)                           # [B*K, J, J]
        Tm = out.reshape(B, K, J, J).astype(jnp.float32)
        P = jax.lax.associative_scan(
            lambda a, b: (jnp.einsum("bkij,bkjl->bkil", a, b) > 0)
            .astype(jnp.float32), Tm, axis=1)
        alive = (P[:, :, 0, :] > 0).any(axis=-1)     # [B, K]
        valid = alive[:, -1]
        dead = jnp.where(valid, jnp.int32(-1),
                         jnp.sum(alive.astype(jnp.int32), axis=1))
        idx2 = jnp.clip(dead - 1, 0, K - 1)          # [B]
        reach = P[jnp.arange(B), idx2, 0, :] > 0     # [B, J]
        entry0 = jnp.zeros((B, J), bool).at[:, 0].set(True)
        entry = jnp.where(valid[:, None], False,
                          jnp.where((dead > 0)[:, None], reach, entry0))
        em = jnp.zeros((B, 4), jnp.uint32)
        for j in range(min(J, 128)):
            em = em.at[:, j // 32].set(
                em[:, j // 32]
                | (entry[:, j].astype(jnp.uint32) << np.uint32(j % 32)))
        return jnp.concatenate(
            [valid.astype(jnp.int32)[:, None], dead[:, None],
             jax.lax.bitcast_convert_type(em, jnp.int32)], axis=1)

    return jax.jit(fn)


@functools.lru_cache(maxsize=32)
def _build_kernel_regs_many_c(K: int, L: int, Wd: int, Sn: int, R: int,
                              decomposed: bool, rounds: int,
                              unroll: int, U: int, Rp: int,
                              donate: bool = False):
    """Compact-wire twin of check_many's J=1 register kernel (I = 1):
    the whole key batch travels as ONE uint8 buffer of key-major row
    streams (rows u8[Rp]: ret+1 | (islot+1)<<4; iuop u8|u16[Rp]; cum
    i32[K+1]) and the padded [L, K] tables are rebuilt on device by
    masked gathers — the multi-key bench's padded tables were ~3x the
    stream bytes, and on the tunneled chip the wire bounds the batch
    wall (BENCH_r05 wire model, docs/environments.md).  Output
    [K, 1, Sn] like the padded form.

    `donate=True` donates the per-chunk event buffer (arg 0) to the
    executable so the double-buffered executor's chunk k buffer is
    reclaimed as chunk k+1 transfers — every dispatch re-packs a fresh
    host buffer, so an OOM retry never touches a consumed donation.
    (Callers gate it off the 'cpu' backend, where XLA ignores donation
    with a warning.)"""
    import jax
    import jax.numpy as jnp

    kern = _build_kernel_regs(K, L, 1, Wd, Sn, R, decomposed,
                              rounds=rounds, unroll=unroll, J=1,
                              nc=0, rn=0, compose=False)
    ub = 1 if U <= 255 else 2
    l_iota = np.arange(L, dtype=np.int32)[:, None]      # [L, 1]

    def fn(buf8, buf32):
        cum = jax.lax.bitcast_convert_type(
            buf8[Rp * (1 + ub):].reshape(K + 1, 4), jnp.int32)
        start = cum[:K]
        nrows = cum[1:] - start
        idx = jnp.clip(start[None, :] + l_iota, 0, Rp - 1)  # [L, K]
        live = l_iota < nrows[None, :]
        rows8 = jnp.where(live, buf8[:Rp][idx],
                          jnp.uint8(0)).astype(jnp.int32)
        ret = (rows8 & 15) - 1
        islot = ((rows8 >> 4) - 1)[:, :, None]
        if ub == 1:
            iu_s = buf8[Rp:2 * Rp].astype(jnp.int32)
        else:
            pairs = buf8[Rp:3 * Rp].reshape(Rp, 2)
            iu_s = (pairs[:, 0].astype(jnp.int32)
                    | (pairs[:, 1].astype(jnp.int32) << 8))
        iuop = jnp.where(live, iu_s[idx], jnp.int32(0))[:, :, None]
        a1 = buf32[:U]
        a2 = buf32[U:2 * U]
        t0 = jax.lax.bitcast_convert_type(buf32[2 * U:3 * U],
                                          jnp.int32)
        return kern(ret, islot, iuop, a1, a2, t0)

    return jax.jit(fn, donate_argnums=(0,)) if donate else jax.jit(fn)




# ---------------------------------------------------------------------------
# Device kernel — dense bf16 (fallback for huge non-decomposable models)
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=32)
def _build_kernel(K: int, L: int, C: int, M: int, Sn: int, R: int,
                  decomposed: bool = False, J: Optional[int] = None):
    """Transfer-matrix kernel: [K, Sn, Sn] from padded segment tables.

    Manually batched for TPU vector units — no nested vmap:

      * the frontier is ONE tensor `fr[M, Sn, J, K]` over (open-call
        bitmask, model state, start state, segment), with the largest
        axis (segments) trailing so elementwise work vectorizes across
        the 128-lane VPU;
      * the dynamic mask-bit shifts (linearize-candidate, retire-slot)
        are decomposed into R statically-unrolled reshape shifts
        selected per segment/candidate — no device gathers;
      * closure uses Lowe's early-stop rule: expand only while some
        configuration still lacks the returning call AND the frontier
        grew; exact at quiescent cuts because every call's own return
        forces its linearization decision within the segment.

    All ops are 0/1 floats (union = saturating add, intersect =
    multiply); the state transition is a (k,c)-batched one-hot matmul.
    """
    import jax
    import jax.numpy as jnp

    # J = Sn computes full transfer matrices (segment rows of one long
    # history); J = 1 tracks only the model's initial state (independent
    # whole histories, one per row — the multi-key batch mode).
    if J is None:
        J = Sn
    Mhalf = [(M >> (b + 1), 1 << b) for b in range(R)]  # (hi, lo) per bit

    def shift_set_bit(x, b):
        """x[..., m, s, j, k] -> y where y[m | 1<<b] = x[m], y[m w/o bit]=0."""
        return _reshape_shift(x, *Mhalf[b], set_bit=True)

    def shift_clear_bit(x, b):
        """x -> y where y[m w/o bit] = x[m | 1<<b], y[m with bit] = 0."""
        return _reshape_shift(x, *Mhalf[b], set_bit=False)

    bf16 = jnp.bfloat16  # 0/1 indicator tensors and small-int sums only

    def kern(ret_slot, cand_slot, cand_uop, legal, next_state,
             diag_w, const_w, const_t0):
        # ret_slot [L, K]; cand_slot/cand_uop [L, K, C];
        # legal [U, Sn] bool; next_state [U, Sn] i32;
        # diag_w/const_w f32 [U, Sn], const_t0 i32 [U] (decomposed only)
        legal_t = legal.astype(bf16)
        if decomposed:
            diag_t = diag_w.astype(bf16)
            cw_t = const_w.astype(bf16)
            onehot0_t = jax.nn.one_hot(const_t0, Sn, dtype=bf16)  # [U, Sn]
        else:
            trans_t = (jax.nn.one_hot(next_state, Sn, dtype=bf16)
                       * legal_t[..., None])                 # [U, Sn, Sn]

        # fr[m, s, j, k]: start state j reaches (mask m, state s) in seg k
        if J == Sn:
            eye = jnp.eye(Sn, dtype=bf16)
            fr0 = jnp.zeros((M, Sn, J, K), bf16).at[0].set(
                eye[:, :, None] * jnp.ones((1, 1, K), bf16))
        else:
            # single start: the model's initial state (index 0 by
            # construction — _enumerate_states interns it first)
            fr0 = jnp.zeros((M, Sn, J, K), bf16).at[0, 0, 0, :].set(1)

        def event(fr, ev):
            rs, cslot, cuop = ev                             # [K], [K,C], [K,C]
            ju = jnp.clip(cuop, 0, None)
            live = (cuop >= 0).astype(bf16)                  # [K, C]
            legal_c = legal_t[ju] * live[..., None]          # [K, C, Sn]

            miota = jnp.arange(M, dtype=jnp.int32)
            bitc = jnp.int32(1) << jnp.clip(cslot, 0, None)  # [K, C]
            # lacks[c, m, k]: mask m lacks candidate c's slot (seg k)
            lacks = ((miota[None, :, None] & bitc.T[:, None, :]) == 0
                     ).astype(bf16)                          # [C, M, K]

            bt = jnp.int32(1) << jnp.clip(rs, 0, None)       # [K]
            # live target only for real events: pad rows do zero rounds
            lack_t = (((miota[:, None] & bt[None, :]) == 0) &
                      (rs >= 0)[None, :]).astype(jnp.float32)  # [M, K]

            def lacking_any(fr):
                return (fr.astype(jnp.float32).sum(axis=(1, 2))
                        * lack_t).sum()

            def round_(carry):
                fr, _, prev = carry
                # contrib[c, m, s, j, k] — legality folded into the
                # transition weights below
                contrib = fr[None] * lacks[:, :, None, None, :]
                if decomposed:
                    # moved = diag part + rank-1 part (all transitions
                    # with a changed state target one state t0 per op)
                    a = (diag_t[ju] * live[..., None]).transpose(1, 2, 0)
                    b_ = (cw_t[ju] * live[..., None]).transpose(1, 2, 0)
                    o0 = onehot0_t[ju].transpose(1, 2, 0)    # [C, Sn, K]
                    diag_part = contrib * a[:, None, :, None, :]
                    red = (contrib * b_[:, None, :, None, :]).sum(axis=2)
                    const_part = (red[:, :, None, :, :]
                                  * o0[:, None, :, None, :])
                    moved = diag_part + const_part           # [C,M,Sn,J,K]
                else:
                    contrib = contrib * legal_c.transpose(1, 2, 0)[
                        :, None, :, None, :]
                    trans_c = trans_t[ju]                    # [K, C, Sn, Sn]
                    if Sn <= 16:
                        # Unrolled select-add stays in the elementwise
                        # pipeline — the batched-einsum form forces large
                        # transposes every closure round.
                        cols = []
                        for t in range(Sn):
                            acc_t = None
                            for s in range(Sn):
                                w = trans_c[:, :, s, t].T[:, None, None, :]
                                term = contrib[:, :, s] * w  # [C, M, J, K]
                                acc_t = term if acc_t is None else acc_t + term
                            cols.append(acc_t)
                        moved = jnp.stack(cols, axis=2)      # [C,M,Sn,J,K]
                    else:
                        moved = jnp.einsum("cmsjk,kcst->cmtjk",
                                           contrib, trans_c)
                # Set candidate c's bit.  Shifts are linear, so select the
                # candidates for each bit FIRST (sum over c), then do one
                # static shift per bit.
                add = jnp.zeros_like(fr)
                for b in range(R):
                    sel = (cslot == b).astype(bf16)          # [K, C]
                    moved_b = (moved
                               * sel.T[:, None, None, None, :]).sum(0)
                    add = add + shift_set_bit(moved_b, b)
                fr2 = jnp.minimum(fr + add, jnp.asarray(1, bf16))
                cnt = fr2.astype(jnp.float32).sum()
                return fr2, (cnt > prev) & (lacking_any(fr2) > 0), cnt

            fr, _, _ = jax.lax.while_loop(
                lambda c: c[1], round_,
                (fr, lacking_any(fr) > 0, jnp.float32(-1.0)))

            # prune configs that never linearized the returning call and
            # retire its slot: keep only has-bit rows, moved to the
            # cleared index (shift_clear_bit does both at once)
            cleared = jnp.zeros_like(fr)
            for b in range(R):
                sel = (rs == b).astype(bf16)                 # [K]
                cleared = cleared + shift_clear_bit(fr, b) * sel
            fr = jnp.where((rs >= 0)[None, None, None, :], cleared, fr)
            return fr, None

        fr, _ = jax.lax.scan(event, fr0, (ret_slot, cand_slot, cand_uop))
        # At a quiescent cut every slot is retired: only mask 0 is live.
        return fr[0].transpose(2, 1, 0)                      # [K, J, Sn]

    return jax.jit(kern)


def _dispatch_kernel(K, L, C, M, Sn, R, J, ret_t, cslot_t, cuop_t,
                     legal, next_state, diag_w, const_w, const_t0):
    """Pick the kernel flavour — uint32 bitmap (decomposable or tiny
    state spaces) vs dense bf16 — build it, and assemble its argument
    list.  Shared by check() and check_many() so the gating and the
    argument plumbing cannot diverge.  Returns (kern, args, n_sharded):
    args[0] is [L, K], args[1:n_sharded] are [L, K, C] (key axis
    shardable over a mesh); the rest are replicated tables."""
    decomposed = diag_w is not None
    use_bits = (decomposed and Sn <= 32) or (not decomposed and Sn <= 8)
    if use_bits:
        # Fixed-round unrolled closure + scan pipelining by default (see
        # _build_kernel_bits: rounds=R is exact); JEPSEN_TPU_DYN_ROUNDS=1
        # restores the dynamic while_loop, JEPSEN_TPU_SCAN_UNROLL tunes
        # the events-per-loop-iteration pipelining.  Deep-concurrency
        # batches (R beyond typical workload concurrency) keep the
        # dynamic loop: the static body is O(R * C * R) full-tensor ops
        # per round x R rounds x unroll, which at R near max_open_bits
        # compiles huge HLO and wastes rounds the early exit would skip.
        # (JEPSEN_TPU_FORCE_STATIC=1 overrides the R guard explicitly;
        # the unroll knob never does.)
        if (os.environ.get("JEPSEN_TPU_DYN_ROUNDS") == "1"
                or (R > 6
                    and os.environ.get("JEPSEN_TPU_FORCE_STATIC") != "1")):
            rounds, unroll = 0, 1
        else:
            rounds = int(R)
            unroll = int(os.environ.get("JEPSEN_TPU_SCAN_UNROLL", "4"))
        kern = _build_kernel_bits(K, int(L), int(C), max(1, M // 32),
                                  int(Sn), int(R), decomposed, J=J,
                                  rounds=rounds, unroll=unroll)
        aux1, aux2, t0c = _pack_cand_tables(
            cuop_t, legal, next_state, diag_w, const_w, const_t0)
        return kern, [ret_t.astype(np.int8), cslot_t.astype(np.int8),
                      aux1, aux2, t0c], 5
    kern = _build_kernel(K, int(L), int(C), int(M), int(Sn), int(R),
                         decomposed, J=J)
    U = legal.shape[0]
    dummy2 = np.zeros((U, Sn), np.float32)
    dummy1 = np.zeros(U, np.int32)
    return kern, [ret_t, cslot_t, cuop_t, legal, next_state,
                  diag_w if decomposed else dummy2,
                  const_w if decomposed else dummy2,
                  const_t0 if decomposed else dummy1], 3


def _shard_args(mesh, mesh_axis: str, args: list, n_sharded: int):
    """Shard _dispatch_kernel's argument list over the mesh: args[0] is
    [L, K], args[1:n_sharded] are [L, K, C] (K = lane axis), the rest
    replicated tables.  One definition so the layout contract cannot
    diverge between check() and check_many()."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    shard_k = NamedSharding(mesh, P(None, mesh_axis))
    shard_kc = NamedSharding(mesh, P(None, mesh_axis, None))
    repl = NamedSharding(mesh, P())
    shardings = ([shard_k] + [shard_kc] * (n_sharded - 1)
                 + [repl] * (len(args) - n_sharded))
    return [jax.device_put(a, sh) for a, sh in zip(args, shardings)]


# ---------------------------------------------------------------------------
# Public API
# ---------------------------------------------------------------------------

def _run_seg_regs(seg_fk: list, K: int, R: int, U: int, Sn: int, M: int,
                  legal, next_state, diag_w, const_w, const_t0,
                  mesh, mesh_axis, nc: int = 0, rn: int = 0,
                  compose: bool = True, tables=None):
    """Run the register-delta kernel over per-segment lanes with
    J = Sn * 2^nc entry configurations (nc = crashed-call count).
    Returns (T, t_kernel, sharded, dead_segment) — shared by the
    plan()-based and fast-scan single-history paths.

    Unsharded with compose=True (the default), the per-segment transfer
    matrices are composed ON DEVICE and only (valid, first-dead) come
    back — T is None and dead_segment is set (-1 = valid).  Sharded
    runs keep the host composition (T comes back, dead_segment None):
    every device computes its segment slice and only the [K, J, J]
    matrices cross the ICI/host boundary.  (Multi-history pipelining
    does not come through here — check_pipeline has its own grouped
    dispatch.)"""
    sharded = False
    K_run = K
    if mesh is not None and mesh_axis is not None:
        # pad the segment axis up to a mesh-size multiple: all-padding
        # lanes (ret -1, no invokes) are identity transfer matrices
        m = int(mesh.shape[mesh_axis])
        K_run = ((K + m - 1) // m) * m
        sharded = True
    I = min(2, R) if R else 1
    decomposed = diag_w is not None
    # timer covers host packing too, matching the candidate-table path
    # (whose _dispatch_kernel packing sits inside the timed window) so
    # the two flavours report comparable time_kernel_s
    t1 = time.monotonic()
    if tables is not None and not sharded and K_run == K:
        ret_t, islot_t, iuop_t, Lp = tables
    else:
        ret_t, islot_t, iuop_t, Lp = _pack_regs(
            [(k, fk) for k, fk in enumerate(seg_fk)], K_run, R, int(U), I)
    a1t, a2t, t0t = _pack_uop_tables(
        legal, next_state, diag_w, const_w, const_t0)
    unroll = int(os.environ.get("JEPSEN_TPU_SCAN_UNROLL", "4"))
    if not sharded and compose:
        out = _dispatch_regs_packed(ret_t, islot_t, iuop_t, a1t, a2t,
                                    t0t, M, Sn, R, decomposed, nc, rn,
                                    unroll)
        vd = np.asarray(out)         # [6]: valid, dead, entry mask x4
        return None, time.monotonic() - t1, False, vd
    kern = _build_kernel_regs(K_run, int(Lp), I, max(1, M // 32),
                              int(Sn), R, decomposed,
                              rounds=R, unroll=unroll,
                              J=int(Sn) << nc, nc=nc, rn=rn)
    args = [ret_t, islot_t, iuop_t, a1t, a2t, t0t]
    if sharded:
        args = _shard_args(mesh, mesh_axis, args, 3)
    T = np.asarray(kern(*args))[:K] > 0.5                    # [K, J, J]
    return T, time.monotonic() - t1, sharded, None


def _dispatch_regs_packed(ret_t, islot_t, iuop_t, a1t, a2t, t0t,
                          M: int, Sn: int, R: int, decomposed: bool,
                          nc: int, rn: int, unroll: int):
    """Pack the six host tables into two transfer buffers and dispatch
    the composed register kernel asynchronously; returns the un-fetched
    int32[6] (valid, first-dead-segment, 128-bit entry-config mask)
    device value."""
    Lp, K_run = ret_t.shape
    I = islot_t.shape[2]
    wide = iuop_t.dtype == np.int16
    buf8 = np.concatenate([ret_t.view(np.uint8).ravel(),
                           islot_t.view(np.uint8).ravel(),
                           iuop_t.view(np.uint8).ravel()])
    buf32 = np.concatenate([a1t, a2t, t0t.view(np.uint32)])
    fn = _build_kernel_regs_packed(
        int(K_run), int(Lp), I, max(1, M // 32), int(Sn), R, decomposed,
        R, unroll, int(Sn) << nc, nc, rn, int(a1t.shape[0]), wide)
    return fn(buf8, buf32)


@functools.lru_cache(maxsize=8)
def _build_stack(n: int):
    import jax
    import jax.numpy as jnp
    return jax.jit(lambda *xs: jnp.stack(xs))


def _localize_segment(model, spec, ops, fk, seg_ends, dead: int,
                      mask_words, states) -> Optional[dict]:
    """Exact witness localization confined to the DEAD segment: replay
    only that segment's ops through the CPU oracle, once per reachable
    entry state (the device's composed verdict carries the entry-config
    mask).  The quiescent-cut composition argument makes this exact:
    configs before the cut are summarized entirely by the reachable
    state set, so the first op at which EVERY entry-state replay has
    died is the global witness (the union config set empties there).
    Returns the oracle result of the last-surviving replay (its op /
    op_index / final-paths ARE the analysis artifacts), or None when
    out of scope (no positions, no decode, crashed-path J-configs) —
    callers fall back to the whole-prefix oracle."""
    if fk.positions is None or getattr(spec, "decode", None) is None:
        return None
    from jepsen_tpu.ops import wgl_cpu

    end_ret = int(seg_ends[dead]) - 1
    start_pos = (int(fk.positions[int(seg_ends[dead - 1]) - 1]) + 1
                 if dead > 0 else 0)
    end_pos = int(fk.positions[end_ret])
    # Quiescent cuts count OK-open calls only, so FAIL pairs may
    # straddle either boundary; an unpaired half inside the slice
    # would read to the oracle as a crashed (maybe-linearizable) call
    # and could shift the witness.  A failed call is never linearized,
    # so dropping the stray halves is exact.
    seg_ops = []
    open_at: dict = {}               # process -> seg_ops index of its
    for o in ops[start_pos:end_pos + 1]:    # currently-open invoke
        p = o.process
        if type(p) is int and p >= 0:
            if o.type == "invoke":
                open_at[p] = len(seg_ops)
            elif p not in open_at:
                continue             # completion of a pre-slice invoke
            else:
                del open_at[p]
        seg_ops.append(o)
    if open_at:                      # invokes completing post-slice:
        drop = set(open_at.values())  # drop exactly those invokes
        seg_ops = [o for i, o in enumerate(seg_ops) if i not in drop]
    Sn = states.shape[0]
    entry = [j for j in range(Sn)
             if (int(mask_words[j // 32]) >> (j % 32)) & 1]
    if not entry:
        return None
    # ONE union walk seeded with every reachable entry state: its
    # witness (the first return at which the union config set empties)
    # is the whole-history witness by construction — separate
    # per-entry-state replays would die at different RETURN events and
    # picking among them by op_index (an INVOKE index) is wrong.
    o = wgl_cpu.check(None, History(seg_ops),
                      initial_models=[spec.decode(states[j])
                                      for j in entry])
    if o.get("valid?") is not False:
        return None              # disagreement with the device verdict
    return o




def _relaxed_refute(model, spec, history, ops, drop, crashed,
                    crash_uop, inert, seen, rows, states, legal,
                    next_state, *, max_open_bits,
                    target_returns_per_segment, backend_name,
                    localize, t0):
    """Tier 4 — SOUND REFUTATION under relaxed crash semantics.

    Over-approximate every crashed call as an unlimited-use epsilon
    transition available from its invoke position onward: any true
    linearization uses each crashed op at most once at some point
    after its invoke, and each such use is one allowed jump — so the
    relaxed config set contains the true one at every index, and
    RELAXED-INVALID implies truly invalid.  (Relaxed-valid proves
    nothing; callers fall through to the exact serial engines.)

    This closes the reference's worst asymmetry: knossos's cost
    explodes with crashed-op count precisely when refuting
    (doc/tutorial/06-refining.md:12-19), while here availability is a
    FUNCTION OF POSITION, not config state — availability only grows,
    so the host precomputes one reflexive-transitive closure matrix
    per crash-prefix and the kernel applies the row's closure between
    expansion rounds.  Cost: +Sn^2 selects per round, zero extra
    config width, any number of crashes.

    Witness: the composed verdict localizes the dead segment; its last
    return's original index is reported as `witness_bound_index` (the
    true witness is at or before it — the relaxed config set dies no
    earlier than the true one).  With localize=True a capped oracle
    attempt upgrades the bound to the exact op when it finishes."""
    Sn = states.shape[0]
    if Sn > 64:
        return None                  # closure masks cap at two words
    W = 1 if Sn <= 32 else 2         # uint32 words per state bitmask
    eff = [(ip, u) for (ip, cp, o), ine, u in
           zip(crashed, inert, crash_uop) if not ine]
    if any(u < 0 for _, u in eff):
        return None                  # unencodable crashed op
    if not eff:
        return None                  # nothing non-inert: not our tier
    if len(eff) > 32767:
        return None                  # crow rides as int16

    # Stripped history as columns (cheap when the run journaled them)
    packed = history.packed_columns() if isinstance(history, History) \
        else None
    keep = np.nonzero(~drop)[0]
    if packed is not None and packed.vkind is not None:
        stripped_pk = PackedHistory(
            packed.index[keep], packed.process[keep],
            packed.type[keep], packed.f[keep], packed.value[keep],
            packed.value_ok[keep], packed.time[keep],
            dict(packed.f_codes), vkind=packed.vkind[keep])
    else:
        from jepsen_tpu.history import pack_history
        stripped_pk = pack_history(
            History([ops[i] for i in keep]))
    U0 = len(rows)
    fk = _native_scan_cols(stripped_pk, spec, seen, rows,
                           max_open_bits)
    if not fk or fk.n_calls == 0 or fk.deltas is None \
            or len(rows) != U0:
        return None
    R = int(fk.max_open)
    diag_w, const_w, const_t0 = _decompose(legal, next_state)
    # one shared gate, width-aware: the wide (W=2) lift is decomposed-
    # only, so the nibble form never widens
    if not _regs_eligible(R, U0, Sn, diag_w is not None,
                          sn_cap=32 * W) \
            or (W > 1 and diag_w is None):
        return None
    cuts = np.asarray(fk.cuts, np.int32)
    if len(cuts) != fk.n_rets or cuts[-1] != 1:
        return None
    seg_ends = _segment_ends(cuts, target_returns_per_segment)
    I = min(2, R) if R else 1
    lay = _RegsLayout(fk, seg_ends, I)
    Lp = _pad_len(lay.lp_min)
    K = lay.k
    ret_t, islot_t, iuop_t = _regs_fill(lay, Lp, K, U0, I)

    # Availability: #effective crashes invoked before each return's
    # ORIGINAL position -> index into the prefix-closure table.
    crash_pos = np.asarray([ip for ip, _ in eff], np.int64)
    orig_ret_pos = keep[np.asarray(fk.positions, np.int64)]
    crow_ret = np.searchsorted(crash_pos, orig_ret_pos,
                               side="left").astype(np.int16)
    crow_t = np.zeros((Lp, K), np.int16)
    crow_t[lay.rho, lay.ret_key] = crow_ret

    # Prefix reflexive-transitive closures (numpy boolean matmuls).
    nC = len(eff) + 1
    C = np.eye(Sn, dtype=bool)
    ctab_rows = [C]
    for _, u in eff:
        rel = np.zeros((Sn, Sn), bool)
        lg = legal[u].astype(bool)
        rel[np.arange(Sn)[lg], next_state[u][lg]] = True
        C = C | rel
        while True:
            C2 = C | (C @ C)
            if (C2 == C).all():
                break
            C = C2
        ctab_rows.append(C)
    nC_pad = _pad_len(nC)
    ctab = np.zeros((nC_pad, Sn, W), np.uint32)

    def _rows_to_words(M):
        out = np.zeros((Sn, W), np.uint32)
        for sw in range(W):
            lo, hi = sw * 32, min((sw + 1) * 32, Sn)
            pw = (1 << np.arange(hi - lo, dtype=np.uint64)) \
                .astype(np.uint64)
            out[:, sw] = (M[:, lo:hi].astype(np.uint64)
                          * pw).sum(1).astype(np.uint32)
        return out

    ctab[:] = _rows_to_words(np.eye(Sn, dtype=bool))  # padding: identity
    for c, M in enumerate(ctab_rows):
        ctab[c] = _rows_to_words(M)

    a1t, a2t, t0t = _pack_uop_tables(
        legal, next_state, diag_w, const_w, const_t0, sn_words=W)
    # unroll=1: the closure adds Sn^2 selects per round and the scan
    # body would otherwise blow up XLA compile time; the refutation
    # path runs once per suspect history, not in the steady-state loop
    unroll = 1
    wide = iuop_t.dtype == np.int16
    buf8 = np.concatenate([ret_t.view(np.uint8).ravel(),
                           islot_t.view(np.uint8).ravel(),
                           iuop_t.view(np.uint8).ravel(),
                           crow_t.view(np.uint8).ravel()])
    buf32 = np.concatenate([a1t.ravel(), a2t.ravel(),
                            t0t.view(np.uint32), ctab.ravel()])
    fn = _build_kernel_regs_relaxed(
        K, int(Lp), I, max(1, (1 << R) // 32), int(Sn), R,
        diag_w is not None, R, unroll, U0, wide, int(nC_pad),
        sn_words=W)
    vd = np.asarray(fn(buf8, buf32))
    if int(vd[0]) == 1:
        return None                  # relaxed-valid: proves nothing
    dead = int(vd[1])
    seg_lo = int(seg_ends[dead - 1]) if dead > 0 else 0
    # Exact relaxed-death localization, NO oracle (VERDICT r3 #3):
    # re-run the relaxed kernel over the dead segment ALONE (its table
    # columns already exist), seeded with the composed verdict's
    # reachable entry-state mask, tracking the first row at which the
    # frontier empties.  Exactness of the point: the relaxed config
    # set OVER-approximates the true one at every index, so the return
    # it names is the first op at which even the relaxation is
    # impossible — the true witness is at or before it, and on
    # violations that are not themselves crash-explainable (e.g. a
    # value no write OR crashed write ever carried) the two coincide
    # (differentially asserted).
    bound_pos = int(orig_ret_pos[int(seg_ends[dead]) - 1])
    loc_kern = _build_kernel_regs(
        1, int(Lp), I, max(1, (1 << R) // 32), int(Sn), R,
        diag_w is not None, rounds=R, unroll=1, J=1,
        compose=False, crash_closure=True, death_row=True,
        sn_words=W)
    seed = (vd[2:2 + max(W, 2)].astype(np.int64)
            & 0xFFFFFFFF).astype(np.uint32)[:W] \
        if W > 1 else np.asarray(
            [np.int64(vd[2]) & 0xFFFFFFFF], np.uint32)
    drow = int(np.asarray(loc_kern(
        ret_t[:, dead:dead + 1], islot_t[:, dead:dead + 1],
        iuop_t[:, dead:dead + 1], a1t, a2t, t0t,
        crow_t[:, dead:dead + 1], ctab, seed)))
    if drow >= 0:
        local = int((ret_t[:drow + 1, dead] >= 0).sum()) - 1
        g = seg_lo + local
        if 0 <= g < len(orig_ret_pos):
            bound_pos = int(orig_ret_pos[g])
    p = ops[bound_pos].process
    inv = bound_pos
    while inv >= 0 and not (ops[inv].process == p
                            and ops[inv].type == "invoke"):
        inv -= 1
    bound_op = ops[max(inv, 0)]
    bound_idx = (bound_op.index if bound_op.index is not None
                 else max(inv, 0))
    result: dict[str, Any] = {
        "valid?": False,
        "op_count": fk.n_calls + len(crashed),
        "backend": backend_name,
        "engine": "wgl_seg",
        "anomaly": "nonlinearizable",
        "refutation": "crash-relaxed",
        "crashed": len(crashed),
        "dead_segment": dead,
        "op": bound_op.to_dict(),
        "op_index": bound_idx,
        "witness": "relaxed-exact" if drow >= 0 else "segment-bound",
        "witness_bound_index": bound_idx,
    }
    if localize:
        # the capped oracle now only upgrades ARTIFACTS (final-paths /
        # configs) and, when it finishes, the true minimal witness —
        # the exact relaxed-death op above is always reportable
        from jepsen_tpu.ops import wgl_cpu
        oracle = wgl_cpu.check(model, history, time_limit=15,
                               max_configs=500_000)
        if oracle.get("valid?") is False:
            for key in ("op", "op_index", "final-paths", "configs"):
                if key in oracle:
                    result[key] = oracle[key]
    return result


def _check_crashed_fast(model, spec, history, *, max_states,
                        max_open_bits, target_returns_per_segment,
                        localize, mesh, mesh_axis, backend_name, t0):
    """Crash-bearing histories on the segment-parallel engine, in three
    exact tiers (a crashed call may be linearized at any point after
    its invoke, or never — `doc/tutorial/06-refining.md:12-19`):

      1. *Inert-crash dropping.*  A crashed call whose op is identity
         and always-legal on every reachable state (e.g. a read: its
         result is unknown, so it constrains nothing) can be removed
         outright — linearizing it changes no configuration, and no
         witness is obliged to linearize it.  Exact in both directions.
      2. *Bounded crash kernel.*  If <= _MAX_CRASHED non-inert crashed
         calls remain, the register-delta kernel carries them as
         permanent mask slots (J = Sn * 2^nc entry configurations; see
         _build_kernel_regs).  Exact.
      3. *Crash-stripped validity proof.*  Beyond the bound, check the
         history with ALL crashed calls removed: crashed calls carry no
         obligation, so a linearization that never linearizes one is a
         linearization of the full history — stripped-valid => valid,
         at full engine speed for ANY number of crashes.  A stripped-
         invalid verdict proves nothing (a crashed write may need to
         take effect), so it returns None and callers fall back to the
         serial engines, which handle crashes exactly.
    """
    from jepsen_tpu.ops.wgl import _generic_encode_op

    ops = history.ops if isinstance(history, History) else \
        History(history).ops
    split = _split_crashed(ops)
    if split is None:
        return None
    drop, crashed = split
    if not crashed:
        return None              # scan failed for a non-crash reason

    stripped = [o for pos, o in enumerate(ops) if not drop[pos]]
    seen: dict = {}
    rows: list = []
    fk = _native_scan(stripped, spec, seen, rows, max_open_bits)
    if fk is False:
        fk = _fast_scan(stripped, spec, seen, rows, max_open_bits)
    if fk is None:
        return None              # stripped key still out of scope

    # Intern the crashed ops alongside the stripped key's ops so the
    # state space closes over BOTH, then classify inertness.
    crash_uop = []
    INT32 = 2 ** 31
    for _, _, o in crashed:
        fc, av, bv, okv = _generic_encode_op(o, spec.f_codes)
        if fc < 0 or not (-INT32 <= av < INT32 and -INT32 <= bv < INT32):
            crash_uop.append(-1)     # unencodable: never inert
            continue
        key = (fc, av, bv, okv)
        u = seen.get(key)
        if u is None:
            u = seen[key] = len(rows)
            rows.append(key)
        crash_uop.append(u)
    uops = np.asarray(rows, np.int32).reshape(len(rows), 4)
    init = np.asarray(spec.encode(model), np.int32)
    try:
        states, legal, next_state = _enumerate_states(
            spec, init, uops, max_states)
    except Unsupported:
        from jepsen_tpu import telemetry as telemetry_mod
        telemetry_mod.count_fallback("wgl_seg_crash_fast",
                                     "state-space")
        return None
    eye = np.arange(legal.shape[1])
    inert = [u >= 0 and bool(legal[u].all())
             and bool((next_state[u] == eye).all())
             for u in crash_uop]

    n_inert = sum(inert)
    if len(crashed) - n_inert <= _MAX_CRASHED:
        # Exact: drop only the inert crashed calls; the bounded kernel
        # carries the rest.
        if n_inert:
            red_drop = np.zeros(len(ops), bool)
            for (ip, cp, _), isin in zip(crashed, inert):
                if isin:
                    red_drop[ip] = True
                    if cp >= 0:
                        red_drop[cp] = True
            reduced = [o for pos, o in enumerate(ops)
                       if not red_drop[pos]]
        else:
            reduced = ops
        res = _check_fast(
            model, spec, History(reduced), max_states=max_states,
            max_open_bits=max_open_bits,
            target_returns_per_segment=target_returns_per_segment,
            localize=localize, mesh=mesh, mesh_axis=mesh_axis,
            backend_name=backend_name, t0=t0,
            max_crashed=_MAX_CRASHED, escalate=False)
        if res is not None:
            if n_inert:
                res["crashed_dropped"] = n_inert
            return res
        # tier 2 ineligible (e.g. Sn << nc too wide): fall through to
        # the stripped validity proof rather than straight to serial.

    # Beyond the bounded kernel's reach: a valid verdict on the fully-
    # stripped history is a valid verdict on the original.
    res = _check_fast(
        model, spec, History(stripped), max_states=max_states,
        max_open_bits=max_open_bits,
        target_returns_per_segment=target_returns_per_segment,
        localize=False, mesh=mesh, mesh_axis=mesh_axis,
        backend_name=backend_name, t0=t0, escalate=False)
    if res is None:
        # outside the register-delta gate (e.g. concurrency > 8): the
        # stripped twin has no crashes, so the full check() chain (the
        # candidate-table kernel) can still prove it — no recursion
        # hazard, _check_crashed_fast bails on crash-free input.
        try:
            res = check(model, History(stripped), max_states=max_states,
                        max_open_bits=max_open_bits,
                        target_returns_per_segment=
                        target_returns_per_segment,
                        localize=False, mesh=mesh, mesh_axis=mesh_axis)
        except Unsupported:
            from jepsen_tpu import telemetry as telemetry_mod
            telemetry_mod.count_fallback("wgl_seg_crash_fast",
                                         "stripped-chain")
            res = None
    if res is not None and res.get("valid?") is True:
        res["crashed_ignored"] = len(crashed)
        return res

    # Tier 4: the stripped history could NOT be proven valid — attempt
    # a sound refutation under relaxed crash semantics (any number of
    # crashes; see _relaxed_refute).  Inconclusive -> None (serial
    # engines take over, exactly as before).
    return _relaxed_refute(
        model, spec, history, ops, drop, crashed, crash_uop, inert,
        seen, rows, states, legal, next_state,
        max_open_bits=max_open_bits,
        target_returns_per_segment=target_returns_per_segment,
        backend_name=backend_name, localize=localize, t0=t0)


def _check_deep(model, ops, fk, legal, next_state,
                diag_w, const_w, const_t0, *, R, Sn, nc, localize,
                backend_name, t0):
    """Deep-overlap single history on the ops.wgl_deep Pallas
    megakernel (R > the register-delta gate, up to the word-split
    boundary planner.deep_r_max(backend, 1);
    crashed calls ride as permanent slots — no J-axis width limit).
    Returns a knossos-shaped result, or None when out of scope
    (callers fall through to the serial engines)."""
    from jepsen_tpu.ops import wgl_deep

    if diag_w is None or not wgl_deep.supported(
            R, Sn, legal.shape[0], True, backend_name):
        return None
    I = min(2, R) if R else 1
    if fk.deltas is not None:
        # columnar scan: the delta stream feeds the layout directly
        ret_t, islot_t, iuop_t, Lp = _pack_regs_single(
            fk, [fk.n_rets], R, int(legal.shape[0]), I)
    else:
        # crash-tolerant Python scan: snapshot-diff packer
        ret_t, islot_t, iuop_t, Lp = _pack_regs(
            [(0, fk)], 1, R, int(legal.shape[0]), I)
    a1t, a2t, t0t = _pack_uop_tables(
        legal, next_state, diag_w, const_w, const_t0)
    t_plan = time.monotonic() - t0
    res = wgl_deep.check_tables(ret_t, islot_t, iuop_t, a1t, a2t, t0t,
                                R, Sn)
    result: dict[str, Any] = {
        "valid?": res["valid?"],
        "op_count": fk.n_calls,
        "backend": backend_name,
        "engine": "wgl_deep",
        "max_open": R,
        "states": Sn,
        "time_plan_s": t_plan,
        "time_kernel_s": res["time_kernel_s"],
    }
    for key in ("deep_variant", "shards"):   # word-split provenance
        if key in res:
            result[key] = res[key]
    if nc:
        result["crashed"] = nc
    if res["valid?"]:
        return result
    result["anomaly"] = "nonlinearizable"
    # Exact witness: the kernel reports the failing event row;
    # wgl_deep.map_witness turns it into the failing call's invoke op
    # (the same witness the oracle names, differentially pinned)
    w = wgl_deep.map_witness(ret_t, fk, ops, res["failed_row"])
    pos = None
    if w is not None:
        result["op"] = w[0].to_dict()
        result["op_index"] = w[1]
        pos = w[2]
    if localize:
        # artifacts (final-paths/configs) via a CAPPED oracle on the
        # prefix through the witness: the deep regime is exactly where
        # an uncapped oracle can spin, and the verdict + witness above
        # are already exact without it
        from jepsen_tpu.ops import wgl_cpu
        prefix = ops if pos is None else ops[:pos + 1]
        oracle = wgl_cpu.check(model, History(list(prefix)),
                               time_limit=15, max_configs=500_000)
        if oracle.get("valid?") is False:
            for key in ("final-paths", "configs"):
                if key in oracle:
                    result[key] = oracle[key]
            if "op_index" not in result:
                for key in ("op", "op_index"):
                    if key in oracle:
                        result[key] = oracle[key]
    return result


def _check_fast(model, spec, history, *, max_states, max_open_bits,
                target_returns_per_segment, localize, mesh, mesh_axis,
                backend_name, t0, max_crashed: int = 0,
                escalate: bool = True):
    """Single-history fast path: one fused host scan (the native C
    scanner when available) straight into per-segment register-delta
    lanes — no per-op Python objects.  Crash-bearing histories escalate
    to _check_crashed_fast (inert dropping / bounded kernel / stripped
    validity proof).  Returns None when out of scope so check() takes
    the plan() route, which raises the descriptive Unsupported."""
    seen: dict = {}
    rows: list = []
    ops = history.ops if isinstance(history, History) else \
        History(history).ops
    fk = _scan_history(history, ops, spec, seen, rows, max_open_bits,
                       want_snaps=(mesh is not None))
    if fk is None and max_crashed:
        # crash-tolerant scan (Python twin; permanent high slots)
        fk = _fast_scan(history, spec, seen, rows, max_open_bits,
                        max_crashed=max_crashed)
    if fk is None:
        if escalate:
            return _check_crashed_fast(
                model, spec, history, max_states=max_states,
                max_open_bits=max_open_bits,
                target_returns_per_segment=target_returns_per_segment,
                localize=localize, mesh=mesh, mesh_axis=mesh_axis,
                backend_name=backend_name, t0=t0)
        return None
    if fk.n_calls == 0:
        return {"valid?": True, "op_count": 0, "backend": backend_name,
                "engine": "wgl_seg"}
    nc = int(fk.nc)
    rn = int(fk.rn) if fk.rn is not None else int(fk.max_open)
    uops = np.asarray(rows, np.int32).reshape(len(rows), 4)
    init = np.asarray(spec.encode(model), np.int32)
    try:
        states, legal, next_state = _enumerate_states(
            spec, init, uops, max_states)
    except Unsupported:
        from jepsen_tpu import telemetry as telemetry_mod
        telemetry_mod.count_fallback("wgl_seg_regs", "state-space")
        return None
    Sn = states.shape[0]
    R = rn + nc if nc else int(fk.max_open)
    diag_w, const_w, const_t0 = _decompose(legal, next_state)
    # THE routing decision (ops.planner): register-delta segment kernel
    # vs deep-overlap Pallas megakernel (crashed calls are permanent
    # slots there; a crash set too wide for the J = Sn * 2^nc entry
    # axis diverts the same way) vs the candidate-table plan() route
    # (None).  The JEPSEN_TPU_NO_REGS / JEPSEN_TPU_DYN_ROUNDS escape
    # hatches keep their documented meaning (the candidate-table path)
    # via the planner's prune table.
    route = planner.plan_engines(
        planner.Shape(kind="linear", R=rn if nc else int(fk.max_open),
                      crashes=nc, Sn=int(Sn), U=int(legal.shape[0]),
                      decomposed=diag_w is not None,
                      n_ops=int(fk.n_calls),
                      mesh=None if mesh is None else int(
                          np.prod(mesh.devices.shape)),
                      max_states=max_states,
                      max_open_bits=max_open_bits),
        backend=backend_name)
    if route.engine != "wgl_seg_regs":
        if route.engine in ("wgl_deep", "wgl_deep_split") \
                and mesh is None:
            r = _check_deep(
                model, ops, fk, legal, next_state,
                diag_w, const_w, const_t0, R=R, Sn=Sn, nc=nc,
                localize=localize, backend_name=backend_name, t0=t0)
            if isinstance(r, dict):
                r["_plan"] = route
            return r
        return None

    # segment at quiescent cuts, >= target returns per segment
    cuts = np.asarray(fk.cuts, np.int32)
    if len(cuts) != fk.n_rets or not fk.n_rets or cuts[-1] != 1:
        return None                  # defensive: malformed cut stream
    seg_ends = _segment_ends(cuts, target_returns_per_segment)
    K = len(seg_ends)
    tables = seg_fk = None
    if fk.deltas is not None and nc == 0 and mesh is None:
        # columnar scan supplied the invoke-delta stream: pack tables
        # directly, skipping the per-segment slicing round trip
        tables = _pack_regs_single(fk, seg_ends, R,
                                   int(legal.shape[0]),
                                   min(2, R) if R else 1)
    else:
        seg_fk = _segments_from_fk(fk, R, seg_ends)
    t_plan = time.monotonic() - t0

    T, t_kernel, sharded, verdict = _run_seg_regs(
        seg_fk, K, R, legal.shape[0], Sn, 1 << R, legal, next_state,
        diag_w, const_w, const_t0, mesh, mesh_axis, nc=nc, rn=rn,
        tables=tables)
    entry_mask = None
    if verdict is None:
        dead_segment = _compose_transfer(T, Sn << nc)
    else:
        dead_segment = int(verdict[1])
        entry_mask = verdict[2:6]

    result: dict[str, Any] = {
        "valid?": dead_segment < 0,
        "op_count": fk.n_calls,
        "backend": backend_name,
        "engine": "wgl_seg",
        "segments": K,
        "states": Sn,
        "sharded": sharded,
        "time_plan_s": t_plan,
        "time_kernel_s": t_kernel,
        "_plan": route.refine(
            bucket=("wgl_seg_regs", R, int(Sn), int(legal.shape[0]),
                    K)),
    }
    if nc:
        result["crashed"] = nc
    if dead_segment >= 0:
        result["anomaly"] = "nonlinearizable"
        result["dead_segment"] = dead_segment
        if localize:
            oracle = None
            if entry_mask is not None and nc == 0:
                # segment-local replay from the device's entry mask —
                # O(segment) instead of O(prefix-through-witness)
                oracle = _localize_segment(model, spec, ops, fk,
                                           seg_ends, dead_segment,
                                           entry_mask, states)
            if oracle is None:
                # fallback: whole-history oracle (terminates at the
                # first non-linearizable op)
                from jepsen_tpu.ops import wgl_cpu
                oracle = wgl_cpu.check(model, history)
            for key in ("op", "op_index", "final-paths", "configs"):
                if key in oracle:
                    result[key] = oracle[key]
    return result


def check(model, history, *, max_states: int = 64, max_open_bits: int = 10,
          target_returns_per_segment: int = 256,
          localize: bool = True, mesh=None,
          mesh_axis: Optional[str] = None) -> dict[str, Any]:
    """_check_impl plus the inspectable dispatch record every verdict
    carries (jepsen_tpu.telemetry): which engine produced it, why, the
    fallback chain below it, and the env knobs in effect — so
    `results.json` explains its own dispatch instead of requiring the
    reader to re-derive eight modules' worth of gating."""
    from jepsen_tpu import telemetry as telemetry_mod
    r = _check_impl(model, history, max_states=max_states,
                    max_open_bits=max_open_bits,
                    target_returns_per_segment=target_returns_per_segment,
                    localize=localize, mesh=mesh, mesh_axis=mesh_axis)
    if isinstance(r, dict):
        # the fast path stashed the planner-emitted Plan; the crash
        # tiers and the plan() route synthesize one so EVERY verdict
        # renders a plan (why + fallbacks + bucket) verbatim
        pl = r.pop("_plan", None)
        if pl is None and "dispatch" not in r:
            # crash tiers / the plan() route: re-derive the plan from
            # what the verdict discloses (same pure function, so the
            # env-knob prunes render here too), keeping the tier's own
            # why when it named one
            pl = planner.plan_engines(
                planner.Shape(
                    kind="linear",
                    R=int(r.get("max_open") or 0),
                    crashes=int(r.get("crashed")
                                or r.get("crashed_ignored") or 0),
                    Sn=r.get("states"),
                    max_states=max_states,
                    max_open_bits=max_open_bits),
                backend=r.get("backend"))
            tier_why = r.get("refutation") or r.get("crash_tier")
            if tier_why:
                pl = pl.refine(why=str(tier_why))
        if "dispatch" not in r:
            telemetry_mod.attach_dispatch(
                [r],
                pl.record(
                    engine=r.get("engine", "wgl_seg"),
                    R=r.get("max_open"),
                    crashes=r.get("crashed_ignored"),
                    batch=1,
                    mesh=(getattr(mesh, "shape", None)
                          if mesh is not None else None)),
                stages={"plan": r.get("time_plan_s"),
                        "kernel": r.get("time_kernel_s")})
    return r


def _check_impl(model, history, *, max_states: int = 64,
                max_open_bits: int = 10,
                target_returns_per_segment: int = 256,
                localize: bool = True, mesh=None,
                mesh_axis: Optional[str] = None) -> dict[str, Any]:
    """Segment-parallel linearizability check.  Returns a knossos-shaped
    analysis map (same keys as ops.wgl.check).  Crashed (:info) calls
    are handled exactly (inert dropping / bounded crash kernel /
    stripped validity proof — see _check_crashed_fast).  Raises
    Unsupported when the history/model falls outside this engine's
    scope (large state spaces, deep concurrency, residual many-crash
    histories) — callers fall back to ops.wgl.check / ops.wgl_cpu.check.

    With `mesh`/`mesh_axis`, ONE history's segment axis is sharded over
    the devices (SURVEY.md §5 long-context: "sharding the DFS/BFS
    frontier of a single long history across devices") — every device
    computes transfer matrices for its slice of the segments, and only
    the [K, Sn, Sn] matrices come back for the host composition."""
    import jax

    spec = model.device_spec()
    if spec is None:
        raise Unsupported(f"model {model!r} has no device spec")

    t0 = time.monotonic()
    backend_name = jax.default_backend()
    if (not isinstance(history, PreparedHistory)
            and getattr(spec, "encode_op", None) is None):
        fast = _check_fast(
            model, spec, history, max_states=max_states,
            max_open_bits=max_open_bits,
            target_returns_per_segment=target_returns_per_segment,
            localize=localize, mesh=mesh, mesh_axis=mesh_axis,
            backend_name=backend_name, t0=t0)
        if fast is not None:
            return fast
    prep = history if isinstance(history, PreparedHistory) else prepare(history)
    if not prep.calls:
        return {"valid?": True, "op_count": 0, "backend": backend_name,
                "engine": "wgl_seg"}

    pl = plan(prep, spec, model, max_states=max_states,
              max_open_bits=max_open_bits,
              target_returns_per_segment=target_returns_per_segment)
    K, L = pl.ret_slot.shape
    C = pl.cand_slot.shape[2]
    Sn = pl.states.shape[0]
    M = 1 << pl.max_open
    t_plan = time.monotonic() - t0

    # Register-delta kernel for segments (one lane per segment, J=Sn
    # entry states) under the same gate as the batch path; the
    # candidate-table kernel is the fallback.
    R = int(pl.max_open)
    decomposed = pl.diag_w is not None
    U = pl.legal.shape[0]
    dead_segment = None
    if pl.seg_fk is not None and _regs_eligible(R, U, Sn, decomposed):
        T, t_kernel, sharded, verdict = _run_seg_regs(
            pl.seg_fk, K, R, U, Sn, M, pl.legal, pl.next_state,
            pl.diag_w, pl.const_w, pl.const_t0, mesh, mesh_axis)
        if verdict is not None:
            dead_segment = int(verdict[1])
    else:
        sharded = False
        K_run = K
        if mesh is not None and mesh_axis is not None:
            # pad the segment axis up to a mesh-size multiple — the plan
            # does NOT guarantee divisibility, and all-padding segments
            # (ret -1, no candidates) are identity transfer matrices
            m = int(mesh.shape[mesh_axis])
            K_run = ((K + m - 1) // m) * m
            sharded = True
        ret_slot, cand_slot, cand_uop = \
            pl.ret_slot, pl.cand_slot, pl.cand_uop
        if K_run != K:
            ret_slot = np.concatenate(
                [ret_slot, np.full((K_run - K, L), -1, np.int32)])
            cand_slot = np.concatenate(
                [cand_slot, np.zeros((K_run - K, L, C), np.int32)])
            cand_uop = np.concatenate(
                [cand_uop, np.full((K_run - K, L, C), -1, np.int32)])
        ret_t = np.ascontiguousarray(ret_slot.T)             # [L, K]
        cslot_t = np.ascontiguousarray(cand_slot.transpose(1, 0, 2))
        cuop_t = np.ascontiguousarray(cand_uop.transpose(1, 0, 2))
        t1 = time.monotonic()
        kern, args, n_sharded = _dispatch_kernel(
            K_run, int(L), int(C), int(M), int(Sn), R,
            int(Sn), ret_t, cslot_t, cuop_t, pl.legal, pl.next_state,
            pl.diag_w, pl.const_w, pl.const_t0)
        if sharded:
            args = _shard_args(mesh, mesh_axis, args, n_sharded)
        T = np.asarray(kern(*args))[:K] > 0.5                # [K, Sn, Sn]
        t_kernel = time.monotonic() - t1

    if dead_segment is None:
        dead_segment = _compose_transfer(T, Sn)

    result: dict[str, Any] = {
        "valid?": dead_segment < 0,
        "op_count": pl.n_calls,
        "backend": backend_name,
        "engine": "wgl_seg",
        "segments": K,
        "states": Sn,
        "sharded": sharded,
        "time_plan_s": t_plan,
        "time_kernel_s": t_kernel,
    }
    if dead_segment >= 0:
        result["anomaly"] = "nonlinearizable"
        result["dead_segment"] = dead_segment
        if localize and not isinstance(history, PreparedHistory):
            # Exact failing op: CPU oracle on the prefix through the
            # first dead segment (bounded: verdict is known invalid).
            from jepsen_tpu.history import History
            from jepsen_tpu.ops import wgl_cpu
            end_call = int(pl.seg_end_call[dead_segment])
            if 0 <= end_call < len(prep.calls):
                last = prep.calls[end_call]
                cutoff = (last.completion.index
                          if last.completion is not None else last.op.index)
                prefix = History(
                    [o for o in history if o.index <= cutoff])
                oracle = wgl_cpu.check(model, prefix)
                for key in ("op", "op_index", "final-paths", "configs"):
                    if key in oracle:
                        result[key] = oracle[key]
    return result


def _stats_clock(stats: Optional[dict]):
    """(now_fn, acc_fn) pair for the pipelines' per-stage host-time
    decomposition — ONE definition so wgl_seg's and wgl_deep's stage
    protocols cannot drift.  acc(key, t0) adds now-t0 to stats[key]
    (no-op when stats is None) and returns the new t0."""
    mt = time.monotonic

    def acc(key, t0):
        if stats is not None:
            stats[key] = stats.get(key, 0.0) + (mt() - t0)
        return mt()

    return mt, acc


_fill_pool_lock = threading.Lock()
_fill_pool_inst = None


def _fill_pool():
    """Module-level lazy ThreadPoolExecutor for pipeline layout/fill —
    created once under a lock (two threads entering check_pipeline
    concurrently must not race a lazy attribute and leak a pool) and
    reused for the process lifetime."""
    global _fill_pool_inst
    if _fill_pool_inst is None:
        with _fill_pool_lock:
            if _fill_pool_inst is None:
                import concurrent.futures as _cf
                _fill_pool_inst = _cf.ThreadPoolExecutor(4)
    return _fill_pool_inst


def check_pipeline(model, histories, *, max_states: int = 64,
                   max_open_bits: int = 10,
                   target_returns_per_segment: int = 256,
                   localize: bool = True,
                   stats: Optional[dict] = None) -> list:
    """Steady-state checking of MANY long histories, fully STREAMED:
    histories are scanned, segmented, packed, and dispatched in groups
    of G, and every host-side stage of group g+1 runs while the device
    executes group g (dispatch is asynchronous); ALL verdicts are
    stacked on device and fetched in ONE round trip — amortizing the
    tunnel's fixed D2H latency over the batch, which bounds any
    single-shot check from below (see bench.py's north-star
    decomposition).

    The group kernel runs SPECULATIVE closure rounds (default 2): the
    exact fixpoint needs rounds=R, but fewer rounds only
    under-approximate the per-segment transfer matrices (strictly
    fewer truly-reachable configs survive), so a surviving composed
    verdict is an exact VALID; a speculative death is re-checked at
    full rounds via check() — valid workloads never pay the rerun.
    Verdict-identical to check() per history either way.

    Compiled-shape control: the kernel is keyed on (R, Sn, U, Lp, K);
    a later group that grows any of them (new op values enlarging the
    state space, deeper concurrency, longer segments) rebuilds the
    kernel for SUBSEQUENT groups only — already-dispatched verdicts
    stay valid, since a group's tables are self-consistent with the
    kernel that ran them.  Same-shaped steady-state batches (the
    reference's `analyze` re-check loop, cli.clj:366-397) compile
    exactly once.

    `stats`, when given a dict, receives the per-stage host-time
    decomposition in seconds (cumulative over the whole call): scan,
    segment, layout, tables (state enumeration + uop packing + kernel
    build), fill, dispatch (the async kernel calls), fetch (the single
    stacked D2H — on the tunneled chip this also absorbs whatever
    transfer/execution hasn't finished in the background), assemble —
    so bench regressions are attributable to a stage instead of a
    wall-clock blur (VERDICT r4 #1)."""
    import jax

    spec = model.device_spec()
    if spec is None:
        raise Unsupported(f"model {model!r} has no device spec")
    # stage timings are ALWAYS collected now (the dict costs a handful
    # of monotonic() reads per group): every pipelined verdict carries
    # its stage decomposition + dispatch record (telemetry, ISSUE 4)
    stats = {} if stats is None else stats
    _mt, _acc = _stats_clock(stats)
    backend_name = jax.default_backend()
    n = len(histories)
    results: list = [None] * n
    seen: dict = {}
    rows: list = []
    strag: list = []
    G = max(1, min(int(os.environ.get("JEPSEN_TPU_PIPE_GROUP", "4")),
                   len(histories) or 1))
    spec_rounds_env = max(1, int(os.environ.get(
        "JEPSEN_TPU_SPEC_ROUNDS", "2")))
    unroll = int(os.environ.get("JEPSEN_TPU_SCAN_UNROLL", "4"))
    init = np.asarray(spec.encode(model), np.int32)

    # streaming state: rebuilt only when the alphabet/shape grows
    U_at = -1           # len(rows) the tables were built for
    Sn = 0
    states = legal = next_state = None
    diag_w = const_w = const_t0 = None
    buf32 = None
    R_cur = 0
    Lp_c = K_c = Rp_c = 0
    fn = None
    spec_rounds = 1
    dispatched: list = []    # (device_out, [history indices])
    metas: dict = {}         # i -> (fk, seg_ends, k_segments)

    def refresh_tables():
        nonlocal U_at, Sn, states, legal, next_state, diag_w, \
            const_w, const_t0, buf32, fn
        uops = np.asarray(rows, np.int32).reshape(len(rows), 4)
        states, legal, next_state = _enumerate_states(
            spec, init, uops, max_states)
        Sn_new = states.shape[0]
        diag_w, const_w, const_t0 = _decompose(legal, next_state)
        a1t, a2t, t0t = _pack_uop_tables(
            legal, next_state, diag_w, const_w, const_t0)
        buf32 = np.concatenate([a1t, a2t, t0t.view(np.uint32)])
        U_at = len(rows)
        # ANY table growth invalidates the compiled kernel: it slices
        # buf32 at static U offsets and fixes the iuop width, so a
        # stale kernel over a grown buf32 would read garbage tables
        fn = None
        return Sn_new

    pos = 0
    while pos < n:
        grp: list = []
        while pos < n and len(grp) < G:
            i = pos
            pos += 1
            h = histories[i]
            if isinstance(h, PreparedHistory):
                strag.append(i)
                continue
            t0 = _mt()
            # fast path: ONE C pass from packed columns to the wire
            # layout (scan + segmentation + row streams fused)
            sk = _native_scan_streams(
                h.packed_columns() if isinstance(h, History) else None,
                spec, seen, rows, max_open_bits,
                target_returns_per_segment)
            if sk is not None and sk is not False:
                t0 = _acc("scan", t0)
                if sk.n_calls == 0:
                    results[i] = {"valid?": True, "op_count": 0,
                                  "backend": backend_name,
                                  "engine": "wgl_seg"}
                    continue
                grp.append((i, sk, sk.seg_ends, sk))
                continue
            ops = h.ops if isinstance(h, History) else History(h).ops
            fk = _scan_history(h, ops, spec, seen, rows,
                               max_open_bits, want_snaps=False)
            t0 = _acc("scan", t0)
            if fk is None:
                strag.append(i)
                continue
            if fk.n_calls == 0:
                results[i] = {"valid?": True, "op_count": 0,
                              "backend": backend_name,
                              "engine": "wgl_seg"}
                continue
            cuts = np.asarray(fk.cuts, np.int32)
            if len(cuts) != fk.n_rets or not fk.n_rets \
                    or cuts[-1] != 1 or fk.deltas is None:
                strag.append(i)
                continue
            seg_ends = _segment_ends(cuts, target_returns_per_segment)
            t0 = _acc("segment", t0)
            lay = _RegsLayout(fk, seg_ends, 1)
            _acc("layout", t0)
            grp.append((i, fk, seg_ends, lay))
        if not grp:
            continue

        # (re)build tables/kernel if this group grew anything
        t0 = _mt()
        if len(rows) != U_at:
            try:
                Sn = refresh_tables()
            except Unsupported:
                # state space outgrew max_states: this group (and any
                # later one — the alphabet only grows) goes through
                # check()'s own fallback chain
                from jepsen_tpu import telemetry as telemetry_mod
                telemetry_mod.count_fallback("wgl_seg_pipeline",
                                             "state-space")
                strag.extend(i for i, *_ in grp)
                continue
        R_g = max(fk.max_open for _, fk, _, _ in grp)
        U = int(legal.shape[0])
        if not _regs_eligible(max(R_g, R_cur), U, Sn,
                              diag_w is not None):
            # this group falls off the batched engine (deep overlap /
            # undecomposable growth): send it through check(), which
            # owns the full fallback chain, and keep streaming
            strag.extend(i for i, *_ in grp)
            continue
        grow = False
        for _, fk, seg_ends, filler in grp:
            if isinstance(filler, _StreamKey):
                lp, k, rp = filler.lp_min, filler.k, filler.rtot
            else:
                lp, k = filler.lp_min, filler.k
                rp = int(filler.rows_per_key.sum()) if k else 0
            if lp > Lp_c or k > K_c or rp > Rp_c:
                grow = True
                Lp_c = max(Lp_c, lp)
                K_c = max(K_c, k)
                Rp_c = max(Rp_c, rp)
        if R_g > R_cur:
            R_cur = R_g
            fn = None
        if grow:
            Lp_c = _pad_len(Lp_c)
            K_c = ((K_c + 63) // 64) * 64
            Rp_c = ((Rp_c + 8191) // 8192) * 8192
            fn = None
        if fn is None:
            spec_rounds = min(R_cur, spec_rounds_env)
            fn = planner.compiled(
                "wgl_seg_pipeline",
                (G, K_c, Lp_c, R_cur, int(Sn), U, Rp_c, spec_rounds,
                 unroll, diag_w is not None),
                _build_kernel_regs_group_c,
                G, K_c, Lp_c, max(1, (1 << R_cur) // 32), int(Sn),
                R_cur, diag_w is not None, spec_rounds, unroll, U,
                Rp_c)
        t0 = _acc("tables", t0)

        def _layout_fill(args):
            i, fk, seg_ends, filler = args
            if isinstance(filler, _StreamKey):
                return i, filler.k, _fill_block_stream(
                    filler, Rp_c, K_c, U)
            buf, _ = _regs_fill_compact(filler, Rp_c, K_c, U)
            return i, filler.k, buf

        # layout+fill are numpy-bound (GIL-releasing): a small pool
        # packs the group's histories in parallel while the device
        # executes the previous group
        if len(grp) > 1:
            filled = list(_fill_pool().map(_layout_fill, grp))
        else:
            filled = [_layout_fill(grp[0])]
        blocks = []
        for (i, fk, seg_ends, lay), (i2, k_segs, buf) in zip(grp, filled):
            assert i == i2
            metas[i] = (fk, seg_ends, k_segs)
            blocks.append(buf)
        while len(blocks) < G:        # short tail group: padding lane
            blocks.append(blocks[0])  # (extra verdicts discarded)
        t0 = _acc("fill", t0)
        payload = np.concatenate(blocks)
        # measured wire traffic: the compact event blocks + the uop
        # tables shipped with every group (bench.py reports MB/s over
        # the dispatch+fetch window from this)
        stats["wire_bytes"] = (stats.get("wire_bytes", 0)
                               + payload.nbytes + buf32.nbytes)
        dispatched.append(
            (fn(payload, buf32),
             [i for i, *_ in grp], spec_rounds, R_cur, Sn, states))
        _acc("dispatch", t0)

    if dispatched:
        t0 = _mt()
        stacked = _build_stack(len(dispatched))(
            *[d for d, *_ in dispatched])
        vds = np.asarray(stacked)                 # ONE fetch
        t0 = _acc("fetch", t0)
        for g, (_, idxs, sr, R_g_disp, Sn_g, states_g) \
                in enumerate(dispatched):
            vd = vds[g].reshape(-1, 6)
            for j, i in enumerate(idxs):
                valid = bool(vd[j, 0])
                fk, seg_ends_i, k_segs = metas[i]
                if not valid and sr < R_g_disp:
                    # speculative death is inconclusive: exact re-run
                    # (rare on valid workloads; carries the witness)
                    res = check(model, histories[i],
                                max_states=max_states,
                                max_open_bits=max_open_bits,
                                target_returns_per_segment=
                                target_returns_per_segment,
                                localize=localize)
                    res["pipelined"] = True
                    res["speculation"] = "exact-rerun"
                    results[i] = res
                    continue
                res = {"valid?": valid, "op_count": fk.n_calls,
                       "backend": backend_name, "engine": "wgl_seg",
                       "segments": k_segs, "states": int(Sn_g),
                       "pipelined": True}
                if not valid:
                    res["anomaly"] = "nonlinearizable"
                    res["dead_segment"] = int(vd[j, 1])
                    if localize:
                        hi = histories[i]
                        h_ops = hi.ops if isinstance(hi, History) \
                            else History(hi).ops
                        oracle = _localize_segment(
                            model, spec, h_ops, fk, seg_ends_i,
                            int(vd[j, 1]), vd[j, 2:6], states_g)
                        if oracle is None:
                            from jepsen_tpu.ops import wgl_cpu
                            oracle = wgl_cpu.check(model, histories[i])
                        for key in ("op", "op_index", "final-paths",
                                    "configs"):
                            if key in oracle:
                                res[key] = oracle[key]
                results[i] = res
        _acc("assemble", t0)
    # pipelined verdicts carry the pipeline's plan + stage
    # decomposition; stragglers (checked below through check()'s own
    # chain) carry the plan check() attaches for the engine that
    # actually produced them
    from jepsen_tpu import telemetry as telemetry_mod
    pipe_plan = planner.plan_engines(
        planner.Shape(kind="linear-pipeline", R=R_cur, Sn=Sn or None,
                      U=len(rows) or None, decomposed=True, batch=n,
                      max_states=max_states,
                      max_open_bits=max_open_bits),
        backend=backend_name).refine(
        bucket=("wgl_seg_pipeline", R_cur, int(Sn), G, Lp_c, K_c,
                Rp_c))
    telemetry_mod.attach_dispatch(
        results,
        pipe_plan.record(engine="wgl_seg",
                         R=R_cur or None, batch=n,
                         stragglers=len(strag) or None),
        stages=stats)
    for i in strag:
        results[i] = check(model, histories[i], max_states=max_states,
                           max_open_bits=max_open_bits,
                           target_returns_per_segment=
                           target_returns_per_segment,
                           localize=localize)
    return results


def _run_segmented(batch, legal, next_state, diag_w, const_w, const_t0,
                   Sn: int, R: int, M: int, C: int):
    """The segmented batch engine: each key's event stream is cut at
    its quiescent points (the single-history engine's trick, applied
    across the whole batch), segments become kernel lanes bucketed by
    length, each lane yields a [Sn, Sn] transfer matrix (J=Sn), and
    per-key verdicts come from composing each key's chain on host.

    Serial depth per kernel drops from max-returns-per-KEY (~hundreds)
    to the bucket's returns-per-SEGMENT (~8-32), with lanes multiplying
    accordingly — the same wall-clock trade the module docstring
    describes for one history, at batch scale.

    Returns (ok_by_batch_index bool[Kk], t_kernel_s)."""
    Kk = len(batch)

    # --- flatten all keys' returns with global segment ids ------------
    rs_parts, cnt_parts, cs_parts, cu_parts = [], [], [], []
    seg_of_ret_parts, rank_parts = [], []
    seg_sizes_parts = []
    seg_base = 0
    key_nseg = np.zeros(Kk, np.int64)
    for bi, (_, fk) in enumerate(batch):
        rs, counts, cs, cu = _fk_arrays(fk)
        nr = len(rs)
        cuts = np.asarray(fk.cuts, np.int32)
        # a crash-free complete history always ends quiescent, but be
        # safe: treat a non-quiescent tail as a final segment
        if nr and (len(cuts) != nr or cuts[-1] != 1):
            cuts = np.copy(cuts) if len(cuts) == nr else \
                np.zeros(nr, np.int32)
            cuts[-1] = 1
        seg_end = np.nonzero(cuts)[0]                    # inclusive
        sizes = np.diff(np.concatenate([[-1], seg_end]))
        nseg = len(seg_end)
        starts = np.concatenate([[0], seg_end[:-1] + 1])
        seg_of_ret = np.repeat(np.arange(nseg), sizes) + seg_base
        rank = np.arange(nr) - np.repeat(starts, sizes)
        rs_parts.append(rs)
        cnt_parts.append(counts)
        cs_parts.append(cs)
        cu_parts.append(cu)
        seg_of_ret_parts.append(seg_of_ret)
        rank_parts.append(rank)
        seg_sizes_parts.append(sizes)
        key_nseg[bi] = nseg
        seg_base += nseg

    rs_all = np.concatenate(rs_parts)
    cnt_all = np.concatenate(cnt_parts)
    cs_all = np.concatenate(cs_parts)
    cu_all = np.concatenate(cu_parts)
    seg_of_ret = np.concatenate(seg_of_ret_parts)
    rank_all = np.concatenate(rank_parts)
    seg_sizes = np.concatenate(seg_sizes_parts)
    n_seg = seg_base

    # candidate rows -> their return's segment/rank
    ends = np.cumsum(cnt_all)
    ret_of_cand = np.repeat(np.arange(len(rs_all)), cnt_all)
    j_of_cand = np.arange(ends[-1] if len(ends) else 0) - \
        np.repeat(ends - cnt_all, cnt_all)

    # --- bucket segments by size (pow2 floors at 8) --------------------
    Lb_of_seg = np.maximum(
        8, 1 << np.ceil(np.log2(np.maximum(seg_sizes, 1))).astype(int))
    t_kernel = 0.0
    S_max = int(key_nseg.max()) if Kk else 0
    # Ragged storage: one [Sn, Sn] matrix per segment — memory bounded
    # by TOTAL segments, not Kk x the single deepest key.  Segments
    # were appended key-by-key in order, so key bi's s-th segment lives
    # at key_off[bi] + s.
    T_all = np.empty((n_seg, Sn, Sn), bool)
    key_off = np.concatenate([[0], np.cumsum(key_nseg)[:-1]])

    for Lb in sorted(set(Lb_of_seg.tolist())):
        in_b = Lb_of_seg == Lb
        seg_ids = np.nonzero(in_b)[0]
        lanes = len(seg_ids)
        lane_of_seg = np.full(n_seg, -1, np.int64)
        lane_of_seg[seg_ids] = np.arange(lanes)
        # round lanes up through power-of-two tiers to bound the set of
        # compiled kernel shapes
        Kp = max(128, _next_pow2(lanes))

        ret_in = in_b[seg_of_ret]
        ret_slot = np.full((Kp, Lb), -1, np.int32)
        ret_slot[lane_of_seg[seg_of_ret[ret_in]],
                 rank_all[ret_in]] = rs_all[ret_in]
        cand_slot = np.zeros((Kp, Lb, C), np.int32)
        cand_uop = np.full((Kp, Lb, C), -1, np.int32)
        if len(cu_all):
            cand_in = ret_in[ret_of_cand]
            seg_c = seg_of_ret[ret_of_cand[cand_in]]
            cand_slot[lane_of_seg[seg_c],
                      rank_all[ret_of_cand[cand_in]],
                      j_of_cand[cand_in]] = cs_all[cand_in]
            cand_uop[lane_of_seg[seg_c],
                     rank_all[ret_of_cand[cand_in]],
                     j_of_cand[cand_in]] = cu_all[cand_in]

        ret_t = np.ascontiguousarray(ret_slot.T)
        cslot_t = np.ascontiguousarray(cand_slot.transpose(1, 0, 2))
        cuop_t = np.ascontiguousarray(cand_uop.transpose(1, 0, 2))
        kern, args, _ = _dispatch_kernel(
            Kp, int(Lb), int(C), int(M), int(Sn), int(R), int(Sn),
            ret_t, cslot_t, cuop_t, legal, next_state,
            diag_w, const_w, const_t0)
        t1 = time.monotonic()
        T = np.asarray(kern(*args)) > 0.5              # [Kp, Sn, Sn]
        t_kernel += time.monotonic() - t1
        T_all[seg_ids] = T[:lanes]

    # --- compose each key's chain (entry state = enumeration index 0) -
    v = np.zeros((Kk, Sn), bool)
    v[:, 0] = True
    for s in range(S_max):
        act = np.nonzero(key_nseg > s)[0]
        Ts = T_all[key_off[act] + s]                   # [A, Sn, Sn]
        v[act] = (v[act][:, :, None] & Ts).any(axis=1)
    return v.any(axis=1), t_kernel


def _emit_batch_result(results, i, fk, ok: bool, backend_name: str,
                       engine: str, t_kernel: float, model,
                       histories, localize: bool) -> None:
    """Per-key result dict + invalid-key localization via the CPU
    oracle — shared by the segmented and single-lane batch paths."""
    results[i] = {
        "valid?": ok,
        "op_count": fk.n_calls,
        "backend": backend_name,
        "engine": engine,
        "time_kernel_s": t_kernel,
    }
    if not ok:
        results[i]["anomaly"] = "nonlinearizable"
        if localize and not isinstance(histories[i], PreparedHistory):
            from jepsen_tpu.ops import wgl_cpu
            oracle = wgl_cpu.check(model, histories[i])
            for key in ("op", "op_index", "final-paths", "configs"):
                if key in oracle:
                    results[i][key] = oracle[key]


# ---------------------------------------------------------------------------
# Multi-key batch mode (jepsen.independent on device)
# ---------------------------------------------------------------------------

def _overlap_chunk() -> int:
    """Keys per double-buffered dispatch chunk (0 disables chunking:
    one monolithic pack + dispatch, the pre-overlap behavior)."""
    return int(os.environ.get("JEPSEN_TPU_OVERLAP_CHUNK", "1024"))


def _run_many_overlapped(batch, R: int, U: int, Sn: int, M: int,
                         decomposed: bool, unroll: int,
                         buf32: np.ndarray, stats: dict, _acc_s,
                         backend_name: str):
    """check_many's compact register-delta path through the async
    double-buffered executor (ops.runner.overlap): the key batch is cut
    into chunks; chunk k+1's host packing (_pack_regs +
    _compact_many_block — the dominant host cost on the 3400-key bench
    row) runs while the device executes chunk k's kernel (JAX dispatch
    is asynchronous), and ALL chunk verdicts are stacked on device and
    fetched in ONE round trip.  Per-chunk event buffers are donated to
    the executable off-CPU (fresh host buffer per dispatch, so an OOM
    retry never touches a consumed donation).  Chunks share one padded
    lane count, and Lp/Rp bucket at 64/8192 granularity, so a uniform
    batch reuses ONE compiled executable (planner.compiled counts the
    hits).  Verdict-identical to the monolithic dispatch: keys are
    independent and chunking only partitions the lane axis
    (differentially pinned in tests/test_planner.py).

    Returns (ok bool[len(batch)], kernel+fetch seconds)."""
    from jepsen_tpu.ops import runner as runner_mod

    chunk = _overlap_chunk()
    if chunk <= 0 or len(batch) <= chunk:
        chunks = [batch]
    else:
        chunks = [batch[k:k + chunk]
                  for k in range(0, len(batch), chunk)]
    Kp = max(128, ((min(len(batch), chunk or len(batch)) + 127)
                   // 128) * 128)
    donate = backend_name not in ("cpu", "unknown") \
        and os.environ.get("JEPSEN_TPU_NO_DONATE") != "1"
    n_native = [0]

    def pack(ch):
        import jax

        t0 = time.monotonic()
        # Native parallel ingest (ISSUE 9): GIL-released work-stealing
        # snapshot-delta pack straight into one arena, bit-identical
        # to the numpy packers below (the permanent differential twin
        # and total fallback — any native error degrades here, never a
        # silent wrong pack; planner counts both outcomes).
        nat = planner._native_pack_compact(ch, Kp, int(R), int(U))
        if nat is not None:
            buf8, Rp, Lp = nat
            n_native[0] += 1
        else:
            ret_t, islot_t, iuop_t, Lp = _pack_regs(ch, Kp, R, U, 1)
            buf8, Rp = _compact_many_block(ret_t, islot_t, iuop_t,
                                           Kp, U)
        _acc_s("pack", t0)
        stats["wire_bytes"] = (stats.get("wire_bytes", 0)
                               + buf8.nbytes + buf32.nbytes)
        if donate:
            # start the H2D transfer of the arena now, while the NEXT
            # chunk packs — the executable then consumes (and donates)
            # an already-on-device buffer instead of paying transfer
            # inside its own dispatch
            buf8 = jax.device_put(buf8)
        return buf8, int(Lp), Rp

    def dispatch(payload):
        import jax

        buf8, Lp, Rp = payload
        # AOT: jit(...).lower(...).compile() inside planner.compiled,
        # so the XLA compile is timed and charged to
        # cache_stats()['compile_s'] (and lands in the persistent
        # plan cache) instead of hiding in the first device call
        kern = planner.compiled(
            "wgl_seg_batch_regs",
            (Kp, Lp, R, Sn, U, Rp, unroll, decomposed, donate),
            _build_kernel_regs_many_c,
            Kp, Lp, max(1, M // 32), Sn, R, decomposed, R, unroll,
            U, Rp, donate,
            lower_args=(jax.ShapeDtypeStruct(buf8.shape, buf8.dtype),
                        jax.ShapeDtypeStruct(buf32.shape,
                                             buf32.dtype)))
        return kern(buf8, buf32)        # async device call

    t1 = time.monotonic()
    outs = runner_mod.overlap(chunks, pack, dispatch, depth=2)
    if len(outs) == 1:
        T = np.asarray(outs[0])                      # [Kp, 1, Sn]
        ok = (T[:, 0, :] > 0.5).any(axis=1)[:len(batch)]
    else:
        stacked = _build_stack(len(outs))(*outs)     # ONE fetch
        T = np.asarray(stacked)                      # [G, Kp, 1, Sn]
        ok_all = (T[:, :, 0, :] > 0.5).any(axis=2)   # [G, Kp]
        ok = np.concatenate(
            [ok_all[g][:len(ch)] for g, ch in enumerate(chunks)])
    t_kernel = time.monotonic() - t1
    stats["kernel"] = stats.get("kernel", 0.0) + t_kernel
    stats["overlap_chunks"] = len(chunks)
    # which ingest backend actually packed (vs the plan's intent):
    # popped into the dispatch RECORD by check_many — "mixed" means a
    # native error degraded some chunks to the Python twin
    stats["pack_backend"] = (
        "native" if n_native[0] == len(chunks)
        else "mixed" if n_native[0] else "python")
    stats["pack_threads"] = planner.pack_threads_effective()
    return ok, t_kernel


def check_many(model, histories, *, max_states: int = 64,
               max_open_bits: int = 10, localize: bool = True,
               mesh=None, mesh_axis: Optional[str] = None,
               fallback=None) -> list:
    """Check many INDEPENDENT histories in one device program — the
    `jepsen.independent` key-sharded workload (`independent.clj:247-298`
    runs a bounded-pmap over per-key subhistories; here every key is one
    row of the batched bitmap kernel, J=1 start state).  Short per-key
    histories are the reference's own scaling recipe ("linearizability
    ... requires we verify only short histories", independent.clj:2-7).

    Keys outside this engine's scope (crashed ops, big state spaces) are
    checked by `fallback(model, prep) -> dict` (default: the serial
    device kernel via ops.wgl, then ops.wgl_cpu on no-device models).

    With `mesh`/`mesh_axis`, the key axis is sharded over the mesh
    (pure data parallelism over ICI; SURVEY.md §2.5).
    """
    import jax

    spec = model.device_spec()
    if spec is None:
        raise Unsupported(f"model {model!r} has no device spec")

    t0 = time.monotonic()
    backend_name = jax.default_backend()
    results: list = [None] * len(histories)
    stats: dict = {}            # per-stage host seconds (telemetry)
    _mt_s, _acc_s = _stats_clock(stats)
    ts = _mt_s()

    # Partition keys: batchable vs fallback — one fused host pass per
    # key (no per-op objects).  With the native ingest layer and >= 2
    # threads, the whole batch's columnar scans run on the
    # work-stealing pool first (GIL released); keys it couldn't take
    # (no packed columns) and out-of-scope keys ride the serial
    # ladder below, with identical interning order either way.
    seen: dict = {}
    rows: list = []
    batch: list = []        # (key index, _FastKey)
    fall: list = []
    stripped_note: dict = {}  # key idx -> crash count (stripped twin batched)
    native_ok = getattr(spec, "encode_op", None) is None
    pre = planner._scan_cols_many(histories, spec, seen, rows,
                                  max_open_bits)
    for i, h in enumerate(histories):
        if isinstance(h, PreparedHistory):
            fall.append(i)  # pre-prepped callers take the slow path
            continue
        ops = h.ops if isinstance(h, History) else History(h).ops
        if pre is not None and pre.get(i) is not None:
            fk = pre[i]
        else:
            # includes keys the batch scan judged out of scope: the
            # serial ladder's object-scan retry can still recover
            # regimes outside the COLUMNAR scope (e.g. out-of-int32
            # client ids), exactly as before
            fk = _scan_history(h, ops, spec, seen, rows, max_open_bits)
        if fk is None:
            # Crashed keys ride the batch as their crash-stripped twin:
            # stripped-valid => valid (a crashed call carries no
            # obligation, so a linearization that never linearizes one
            # is a linearization of the full key).  Keys the stripped
            # pass cannot prove valid are re-checked exactly afterwards
            # (bounded crash kernel via check(), then serial fallback).
            split = _split_crashed(ops)
            if split is not None and split[1]:
                drop, crashed = split
                stripped = [o for pos, o in enumerate(ops)
                            if not drop[pos]]
                sfk = _native_scan(stripped, spec, seen, rows,
                                   max_open_bits) if native_ok else False
                if sfk is False:
                    sfk = _fast_scan(stripped, spec, seen, rows,
                                     max_open_bits)
                if sfk is not None and sfk.n_calls:
                    stripped_note[i] = len(crashed)
                    batch.append((i, sfk))
                    continue
                if sfk is not None:
                    # every client call crashed: trivially linearizable
                    # (linearize none of them)
                    results[i] = {"valid?": True, "op_count": 0,
                                  "backend": backend_name,
                                  "engine": "wgl_seg_batch",
                                  "crashed_ignored": len(crashed)}
                    continue
            fall.append(i)
        elif fk.n_calls == 0:
            results[i] = {"valid?": True, "op_count": 0,
                          "backend": backend_name,
                          "engine": "wgl_seg_batch"}
        else:
            batch.append((i, fk))
    ts = _acc_s("scan", ts)

    if batch:
        uops = np.asarray(rows, np.int32).reshape(len(rows), 4)
        init = np.asarray(spec.encode(model), np.int32)
        try:
            states, legal, next_state = _enumerate_states(
                spec, init, uops, max_states)
        except Unsupported:
            from jepsen_tpu import telemetry as telemetry_mod
            telemetry_mod.count_fallback("wgl_seg_batch",
                                         "state-space")
            fall.extend(i for i, _ in batch)
            batch = []
        ts = _acc_s("tables", ts)

    R_batch = None
    route = None
    if batch:
        Sn = states.shape[0]
        R = max(fk.max_open for _, fk in batch)
        R_batch = int(R)
        M = 1 << R
        # C needs no pow2 pad — a return's candidate set is the open
        # calls, <= R.
        L = _pad_len(max(fk.n_rets for _, fk in batch))
        C = int(R)
        diag_w, const_w, const_t0 = _decompose(legal, next_state)

        # THE routing decision (ops.planner): register-delta compact
        # lanes vs candidate-table lanes vs the opt-in segmented
        # engine (JEPSEN_TPU_SEGMENT=1 prunes the single-lane layouts
        # so the segmented tier surfaces — measured on a v5e-1 it
        # LOSES to them at both bench shapes, 2.0s vs 0.83s kernel at
        # 300-op keys, because the J=Sn entry-state axis multiplies
        # total work ~Sn x; kept verdict-identical as the scaling path
        # for workloads whose per-key depth actually binds).
        route = planner.plan_engines(
            planner.Shape(kind="linear-many", R=int(R), Sn=int(Sn),
                          U=len(rows), decomposed=diag_w is not None,
                          batch=len(batch),
                          mesh=None if mesh is None else int(
                              np.prod(mesh.devices.shape)),
                          max_states=max_states,
                          max_open_bits=max_open_bits),
            backend=backend_name)

        if route.engine == "wgl_seg_batch_seg":
            ok_b, t_kernel = _run_segmented(
                batch, legal, next_state, diag_w, const_w, const_t0,
                int(Sn), int(R), int(M), int(C))
            for bi, (i, fk) in enumerate(batch):
                _emit_batch_result(results, i, fk, bool(ok_b[bi]),
                                   backend_name, "wgl_seg_batch",
                                   t_kernel, model, histories,
                                   localize and i not in stripped_note)
            batch = []

    if batch:
        # Pad the key axis for lane alignment (and even mesh sharding).
        Kk = len(batch)
        mult = 128
        if mesh is not None and mesh_axis is not None:
            mult = int(np.lcm(mult, mesh.shape[mesh_axis]))
        Kp = max(mult, ((Kk + mult - 1) // mult) * mult)

        decomposed = diag_w is not None
        U = legal.shape[0]

        # Register-delta path (default): ship only per-return invoke
        # deltas and let the device maintain the open set — see
        # _build_kernel_regs and the shared _regs_eligible gate
        # (planner._linear_candidates routes on exactly that gate).
        if route.engine == "wgl_seg_batch_regs":
            unroll = int(os.environ.get("JEPSEN_TPU_SCAN_UNROLL", "4"))
            a1t, a2t, t0t = _pack_uop_tables(
                legal, next_state, diag_w, const_w, const_t0)
            if mesh is None:
                # compact wire (I = 1): key-major row streams, tables
                # rebuilt on device — ~3x fewer bytes than the padded
                # tables, and the tunnel wire bounds this batch's
                # wall.  Large batches run through the async
                # double-buffered executor (ops.runner.overlap): host
                # packing of chunk k+1 overlaps device compute of
                # chunk k, all verdicts fetched ONCE at the end.
                buf32 = np.concatenate(
                    [a1t, a2t, t0t.view(np.uint32)])
                ok_k, t_kernel = _run_many_overlapped(
                    batch, int(R), int(U), int(Sn), int(M),
                    decomposed, unroll, buf32, stats, _acc_s,
                    backend_name)
                ts = _mt_s()
            else:
                I = min(2, int(R))
                ret_t, islot_t, iuop_t, Lp = _pack_regs(
                    batch, Kp, int(R), int(U), I)
                kern = planner.compiled(
                    "wgl_seg_batch_regs",
                    (Kp, int(Lp), I, int(R), int(Sn), int(U),
                     unroll, decomposed, "mesh"),
                    _build_kernel_regs,
                    Kp, int(Lp), I, max(1, M // 32),
                    int(Sn), int(R), decomposed,
                    rounds=int(R), unroll=unroll)
                args = _shard_args(
                    mesh, mesh_axis,
                    [ret_t, islot_t, iuop_t, a1t, a2t, t0t], 3)
                ts = _acc_s("fill", ts)
                stats["wire_bytes"] = (stats.get("wire_bytes", 0)
                                       + sum(a.nbytes for a in args
                                             if hasattr(a, "nbytes")))
                t1 = time.monotonic()
                T = np.asarray(kern(*args))              # [Kp, 1, Sn]
                t_kernel = time.monotonic() - t1
                stats["kernel"] = stats.get("kernel", 0.0) + t_kernel
                ts = _mt_s()
                ok_k = (T[:, 0, :] > 0.5).any(axis=1)
            engine_name = "wgl_seg_batch_regs"
            for kk, (i, fk) in enumerate(batch):
                _emit_batch_result(results, i, fk, bool(ok_k[kk]),
                                   backend_name, engine_name, t_kernel,
                                   model, histories,
                                   localize and i not in stripped_note)
            batch = []

    if batch:
        ret_slot = np.full((Kp, L), -1, np.int32)
        cand_slot = np.zeros((Kp, L, C), np.int32)
        cand_uop = np.full((Kp, L, C), -1, np.int32)
        for kk, (_, fk) in enumerate(batch):
            if fk.arrays is not None:
                # native form: vectorized scatter from the flat arrays
                rs, counts, cs, cu = fk.arrays
                nr = len(rs)
                ret_slot[kk, :nr] = rs
                if len(cs):
                    ends = np.cumsum(counts)
                    r_idx = np.repeat(np.arange(nr), counts)
                    j_idx = (np.arange(ends[-1])
                             - np.repeat(ends - counts, counts))
                    cand_slot[kk, r_idx, j_idx] = cs
                    cand_uop[kk, r_idx, j_idx] = cu
                continue
            for r, (slot, cands) in enumerate(fk.rets):
                ret_slot[kk, r] = slot
                for j, (s2, u2) in enumerate(cands):
                    cand_slot[kk, r, j] = s2
                    cand_uop[kk, r, j] = u2

        ret_t = np.ascontiguousarray(ret_slot.T)             # [L, K]
        cslot_t = np.ascontiguousarray(cand_slot.transpose(1, 0, 2))
        cuop_t = np.ascontiguousarray(cand_uop.transpose(1, 0, 2))

        # (A Pallas megakernel variant of this scan was carried through
        # round 2 behind JEPSEN_TPU_PALLAS=1; it never beat XLA's
        # fusion of the same bitmap algebra on any measured shape
        # (~25% slower at its best) and was removed in round 3 —
        # hand-scheduling what the compiler already fuses well bought
        # nothing but maintenance surface.)
        engine_name = "wgl_seg_batch"
        kern, args, kc_shaped = _dispatch_kernel(
            Kp, int(L), int(C), int(M), int(Sn), int(R), 1,
            ret_t, cslot_t, cuop_t, legal, next_state,
            diag_w, const_w, const_t0)
        if mesh is not None and mesh_axis is not None:
            args = _shard_args(mesh, mesh_axis, args, kc_shaped)

        ts = _acc_s("fill", ts)
        t1 = time.monotonic()
        T = np.asarray(kern(*args))                      # [Kp, 1, Sn]
        t_kernel = time.monotonic() - t1
        stats["kernel"] = stats.get("kernel", 0.0) + t_kernel
        ok_k = (T[:, 0, :] > 0.5).any(axis=1)
        for kk, (i, fk) in enumerate(batch):
            _emit_batch_result(results, i, fk, bool(ok_k[kk]),
                               backend_name, engine_name, t_kernel,
                               model, histories,
                               localize and i not in stripped_note)

    if stripped_note:
        # Crash-bearing keys: a valid verdict on the stripped twin IS
        # the verdict; anything else gets the exact single-key chain
        # (inert dropping + bounded crash kernel), then the serial
        # fallback below.  Keys already routed to `fall` (e.g. the
        # whole batch bailed on state enumeration) are left to it.
        in_fall = set(fall)
        for i, nc in stripped_note.items():
            if i in in_fall:
                continue
            r = results[i]
            if r is not None and r.get("valid?") is True:
                r["crashed_ignored"] = nc
                continue
            try:
                results[i] = check(model, histories[i],
                                   max_states=max_states,
                                   max_open_bits=max_open_bits,
                                   localize=localize)
            except Unsupported:
                from jepsen_tpu import telemetry as telemetry_mod
                telemetry_mod.count_fallback("wgl_seg_batch",
                                             "per-key-chain")
                results[i] = None
                fall.append(i)

    if fall:
        if fallback is None:
            from jepsen_tpu.ops import wgl, wgl_cpu

            def fallback(m, h):
                try:
                    return wgl.check(m, h)
                except ValueError:
                    # Outside the serial device kernel's scope too
                    # (e.g. values that don't encode to int32) — the
                    # exact CPU oracle handles anything.
                    return wgl_cpu.check(m, h)
        for i in fall:
            h = histories[i]
            p = h if isinstance(h, PreparedHistory) else prepare(h)
            results[i] = fallback(model, p)
            results[i].setdefault("engine", "fallback")

    t_total = time.monotonic() - t0
    for r in results:
        if r is not None and "time_total_s" not in r:
            r["time_total_s"] = t_total
    # Dispatch records, grouped by the engine that actually produced
    # each verdict (batched kernel lanes, exact single-key crash
    # chains, serial fallbacks): one shared plan-rendered record per
    # engine, so the attribution costs dict references, not
    # per-verdict env scans.
    from jepsen_tpu import telemetry as telemetry_mod
    by_engine: dict = {}
    for r in results:
        if isinstance(r, dict) and "dispatch" not in r:
            by_engine.setdefault(r.get("engine", "wgl_seg_batch"),
                                 []).append(r)
    n_crash = sum(stripped_note.values()) if stripped_note else None
    if route is None:
        route = planner.plan_engines(
            planner.Shape(kind="linear-many", R=0,
                          batch=len(histories),
                          max_states=max_states,
                          max_open_bits=max_open_bits),
            backend=backend_name)
    # the ingest backend that ACTUALLY packed (may differ from the
    # plan's pack_backend when a native error degraded mid-batch) —
    # strings ride the record, not the numeric stage decomposition
    pack_used = stats.pop("pack_backend", None)
    pack_nt = stats.pop("pack_threads", None)
    for eng, rs in by_engine.items():
        telemetry_mod.attach_dispatch(
            rs,
            route.record(
                engine=eng,
                R=R_batch, crashes=n_crash, batch=len(histories),
                mesh=(getattr(mesh, "shape", None)
                      if mesh is not None else None),
                pack_backend=pack_used, pack_threads=pack_nt),
            stages=stats)
    return results
