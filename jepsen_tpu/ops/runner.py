"""Resilient checker runtime: fault-tolerant execution around the
batch entry points (`wgl_seg.check_pipeline` / `check_many`,
`wgl_deep.check_pipeline` / `check_mesh` / `check_hypercube`,
`wgl_batch.check_many`).

The deep engines' sub-plane stacks (ISSUE 10) compose with the OOM
machinery on two axes: a batch-level OOM (e.g. the stacked verdict
fetch of many word-split histories) bisects the HISTORY axis here,
down to one history per dispatch; a single history whose stack still
does not fit is demoted by `wgl_deep.check_pipeline` itself onto its
straggler chain (hypercube mesh when available, then the serial
engines) — counted in `jepsen_deep_oom_demotions_total`, never a
silent wrong verdict.

Long device-bound verification runs over large multi-history batches
fail the way inference stacks fail, not the way unit tests fail: one
`RESOURCE_EXHAUSTED` on a big batch, one hung compile, or one corrupted
history in ten thousand must not abort the run and discard every
completed verdict.  `ResilientRunner.check` gives every batch entry
point the same robustness contract:

  * **OOM-adaptive batch splitting** — a device OOM
    (`errors.is_oom`) bisects the batch and retries the halves with
    exponential backoff + deterministic jitter, down to per-history
    granularity; a single history that still OOMs after `max_retries`
    is quarantined with a structured verdict instead of raising.
  * **Poison isolation** — a non-OOM engine failure on a multi-history
    batch also bisects (no backoff: the failure is deterministic), so
    one corrupt history costs one quarantine verdict, not the batch.
  * **Deadline budget with graceful degradation** — when the device
    path exceeds `deadline_s`, every remaining history degrades to the
    capped CPU oracle (`wgl_cpu.check(time_limit=...)`), each verdict
    tagged with the backend that produced it and
    `fallback: "deadline"`.
  * **Resumable verdict checkpoints** — with `checkpoint_dir`, each
    completed per-history verdict is appended (fsynced) to
    `<dir>/verdicts.jsonl` via `jepsen_tpu.store` as it lands; a killed
    run resumes by re-checking only histories without a
    digest-matching checkpoint record.

Error classification lives in `jepsen_tpu.errors` (CheckError ->
DeviceOOM / DeadlineExceeded / BackendUnavailable / CorruptHistory);
`BackendUnavailable` (no DeviceSpec, no kernel lowering) short-circuits
the whole remaining batch to the CPU oracle rather than bisecting —
halving a batch cannot conjure a device.

`clock` / `sleep` are injectable so the fault-injection tests drive
deadlines and observe backoff without wall-clock waits.
"""

from __future__ import annotations

import logging
import time
import zlib
from typing import Any, Callable, Optional, Sequence

from jepsen_tpu import errors as errors_mod
from jepsen_tpu import store
from jepsen_tpu.errors import (BackendUnavailable, CheckError,
                               CorruptHistory, DeviceOOM)

log = logging.getLogger("jepsen")

_UNSET = object()


def overlap(items, pack: Callable, dispatch: Callable, *,
            depth: int = 2) -> list:
    """Async double-buffered executor (ISSUE 8): for each item, run
    `pack(item)` on the host and hand the payload to `dispatch`, an
    asynchronous device call returning device buffers.  Because JAX
    dispatch returns before the device finishes, packing item k+1
    overlaps device compute of item k; `depth` bounds how far the host
    may run ahead (older dispatches are blocked on past the window, so
    in-flight device memory stays at ~depth payloads instead of the
    whole batch).  The caller stacks the returned device outputs and
    fetches once — the one-round-trip discipline every pipeline here
    uses.

    Exceptions propagate exactly as a serial loop's would (an OOM
    raised at dispatch or at the deferred block surfaces to the
    caller), so a ResilientRunner wrapping an overlapped engine keeps
    its full bisection/quarantine semantics — including with donated
    input buffers, since every dispatch packs a fresh host payload
    (test_planner.py pins the OOM-mid-pipeline case)."""
    import collections

    pending: collections.deque = collections.deque()
    outs: list = []
    for it in items:
        payload = pack(it)
        out = dispatch(payload)
        outs.append(out)
        pending.append(out)
        if len(pending) > max(1, depth):
            old = pending.popleft()
            block = getattr(old, "block_until_ready", None)
            if block is not None:
                block()
    return outs


def _resolve_engine(engine) -> Callable:
    """Engine name -> batch callable `(model, histories, **kw) -> list`.
    A callable passes through (the fault-injection tests hand in
    wrapped/synthetic engines)."""
    if callable(engine):
        return engine
    from jepsen_tpu.ops import wgl_batch, wgl_deep, wgl_seg
    table = {
        "auto": wgl_seg.check_pipeline,
        "seg_pipeline": wgl_seg.check_pipeline,
        "seg_many": wgl_seg.check_many,
        "deep_pipeline": wgl_deep.check_pipeline,
        "deep_mesh": wgl_deep.check_mesh,
        "deep_hc": wgl_deep.check_hypercube,
        "batch_many": wgl_batch.check_many,
    }
    try:
        return table[engine]
    except KeyError:
        raise ValueError(f"unknown runner engine {engine!r}; one of "
                         f"{sorted(table)} or a callable") from None


def history_digest(h) -> str:
    """Cheap positional fingerprint of a history, used to key verdict
    checkpoints: resume only trusts a stored verdict whose digest
    matches the history at the same batch index, so reordered or
    edited batches re-check rather than mis-attribute."""
    ops = getattr(h, "ops", None)
    if ops is None:
        ops = getattr(h, "calls", None)
    if ops is None:
        try:
            ops = list(h)
        except TypeError:
            ops = [repr(h)]
    c = zlib.crc32(str(len(ops)).encode())
    for o in ops:
        key = (getattr(o, "index", None), getattr(o, "process", None),
               getattr(o, "type", None), getattr(o, "f", None),
               getattr(o, "value", None))
        c = zlib.crc32(repr(key).encode(), c)
    return f"{c:08x}"


class ResilientRunner:
    """Fault-tolerant wrapper around one batch checking engine.

    engine: an engine name ("auto"/"seg_pipeline"/"seg_many"/
        "deep_pipeline"/"deep_mesh"/"batch_many") or a callable
        `(model, histories, **engine_kwargs) -> list of verdict dicts`.
    engine_kwargs: passed through to the engine on every dispatch.
    max_retries: OOM retries per single history before quarantine.
    deadline_s: wall-clock budget; past it, remaining histories degrade
        to the capped CPU oracle.
    checkpoint_dir: directory for `verdicts.jsonl` (see module doc).
    max_group: largest batch dispatched at once — bounds both the OOM
        blast radius and the checkpoint granularity (verdicts land
        after each group).
    backoff_base_s / backoff_cap_s / jitter_seed: retry backoff shape;
        jitter is DETERMINISTIC in (jitter_seed, history index,
        attempt) so failures replay identically.
    cpu_slice_floor_s: minimum per-history time_limit handed to the
        CPU oracle on deadline fallback, so a blown budget still makes
        bounded forward progress instead of checking nothing.
    cpu_fallback: the per-history degradation target
        `(model, history, time_limit=None) -> verdict dict`; defaults
        to the wgl_cpu oracle.  Engines whose "histories" are not
        History objects (the live checker's window lanes) supply their
        own host-path callable here and keep the full deadline /
        backend-unavailable semantics.
    clock / sleep: injectable for tests.
    """

    def __init__(self, *, engine="auto",
                 engine_kwargs: Optional[dict] = None,
                 max_retries: int = 2,
                 deadline_s: Optional[float] = None,
                 checkpoint_dir: Optional[str] = None,
                 max_group: int = 32,
                 backoff_base_s: float = 0.05,
                 backoff_cap_s: float = 2.0,
                 jitter_seed: int = 0,
                 cpu_slice_floor_s: float = 2.0,
                 cpu_fallback: Optional[Callable] = None,
                 clock: Callable[[], float] = time.monotonic,
                 sleep: Callable[[float], None] = time.sleep):
        self.engine = engine
        self.engine_kwargs = dict(engine_kwargs or {})
        self.max_retries = max_retries
        self.deadline_s = deadline_s
        self.checkpoint_dir = checkpoint_dir
        self.max_group = max(1, int(max_group))
        self.backoff_base_s = backoff_base_s
        self.backoff_cap_s = backoff_cap_s
        self.jitter_seed = jitter_seed
        self.cpu_slice_floor_s = cpu_slice_floor_s
        self.cpu_fallback = cpu_fallback
        self.clock = clock
        self.sleep = sleep

    # -- backoff ------------------------------------------------------------

    def _jitter(self, key: int, attempt: int) -> float:
        """Deterministic jitter fraction in [0, 1): crc32 of the
        (seed, history key, attempt) triple — stable across processes
        (unlike hash()) so a failure replays with identical timing."""
        h = zlib.crc32(f"{self.jitter_seed}:{key}:{attempt}".encode())
        return (h % 1024) / 1024.0

    def backoff_s(self, key: int, attempt: int) -> float:
        """Exponential backoff with deterministic jitter, capped."""
        base = min(self.backoff_base_s * (2 ** max(attempt - 1, 0)),
                   self.backoff_cap_s)
        return base * (0.5 + self._jitter(key, attempt))

    # -- verdict shaping ----------------------------------------------------

    @staticmethod
    def _backend() -> str:
        try:
            import jax
            return jax.default_backend()
        except Exception:           # noqa: BLE001 - tagging must not raise
            return "unknown"

    @staticmethod
    def _quarantine(err: CheckError, i: int, seed=None) -> dict:
        """The structured verdict a poisoned history gets instead of
        aborting the batch.  'unknown' merges through the checker
        validity lattice without masking real invalids."""
        v: dict = {"valid?": "unknown", "quarantined": True,
                   "history_index": i}
        if seed is not None:
            v["seed"] = seed
        v.update(err.to_dict())
        return v

    # -- the runner ---------------------------------------------------------

    def check(self, model, histories: Sequence, *,
              deadline_s=_UNSET, max_retries=_UNSET,
              checkpoint_dir=_UNSET,
              seeds: Optional[Sequence[Any]] = None) -> list:
        """Check `histories` through the configured engine with OOM
        bisection, retry/quarantine, deadline-bounded CPU fallback, and
        checkpoint/resume.  Always returns one verdict dict per
        history, in order; never raises for a per-history failure."""
        if deadline_s is _UNSET:
            deadline_s = self.deadline_s
        if max_retries is _UNSET:
            max_retries = self.max_retries
        if checkpoint_dir is _UNSET:
            checkpoint_dir = self.checkpoint_dir
        engine_fn = _resolve_engine(self.engine)
        n = len(histories)
        results: list = [None] * n
        backend = self._backend()

        def seed_of(i):
            return seeds[i] if seeds is not None and i < len(seeds) \
                else None

        # -- resume --------------------------------------------------------
        ckpt_file = None
        digests: Optional[list] = None
        if checkpoint_dir:
            ckpt_file = store.checkpoint_path(checkpoint_dir)
            digests = [history_digest(h) for h in histories]
            for rec in store.read_checkpoint(ckpt_file):
                i = rec.get("i")
                if (isinstance(i, int) and 0 <= i < n
                        and results[i] is None
                        and rec.get("digest") == digests[i]
                        and isinstance(rec.get("verdict"), dict)):
                    v = dict(rec["verdict"])
                    v["resumed"] = True
                    results[i] = v

        def record(i: int) -> None:
            if ckpt_file is not None:
                store.append_checkpoint(
                    ckpt_file, {"i": i, "digest": digests[i],
                                "verdict": results[i]})

        pending = [i for i in range(n) if results[i] is None]
        start = self.clock()
        # Resilience accounting (telemetry, ISSUE 4): how much work the
        # runner did beyond one clean dispatch — counted in the global
        # registry and journaled into the active run's event log, and
        # echoed onto the verdicts the runner itself produced.
        counts = {"oom_bisections": 0, "poison_bisections": 0,
                  "retries": 0, "quarantines": 0, "cpu_fallbacks": 0}

        def remaining() -> Optional[float]:
            return None if deadline_s is None \
                else deadline_s - (self.clock() - start)

        # LIFO work stack of (indices, attempt); seeded with groups of
        # <= max_group in order, so verdicts (and checkpoints) land
        # roughly front-to-back.
        stack: list = []
        for k in range(0, len(pending), self.max_group):
            stack.append((pending[k:k + self.max_group], 0))
        stack.reverse()

        cpu_rest: list = []          # indices degrading to the oracle
        fallback_cause: Optional[str] = None

        while stack:
            rem = remaining()
            if rem is not None and rem <= 0:
                for idxs, _ in stack:
                    cpu_rest.extend(idxs)
                stack = []
                fallback_cause = "deadline"
                log.warning("runner deadline (%ss) exceeded with %d "
                            "histories left; degrading to CPU oracle",
                            deadline_s, len(cpu_rest))
                break
            idxs, attempt = stack.pop()
            if attempt:
                self.sleep(self.backoff_s(idxs[0], attempt))
            try:
                rs = engine_fn(model, [histories[i] for i in idxs],
                               **self.engine_kwargs)
            except Exception as e:   # noqa: BLE001 - classified below
                err = errors_mod.classify(
                    e, backend=backend, batch_size=len(idxs),
                    history_index=idxs[0] if len(idxs) == 1 else None,
                    seed=seed_of(idxs[0]) if len(idxs) == 1 else None)
                if isinstance(err, BackendUnavailable):
                    # No device path at all: bisection cannot help;
                    # everything still queued degrades to the oracle.
                    cpu_rest.extend(idxs)
                    for rest_idxs, _ in stack:
                        cpu_rest.extend(rest_idxs)
                    stack = []
                    fallback_cause = "backend-unavailable"
                    log.info("device path unavailable (%s); checking "
                             "%d histories on the CPU oracle",
                             err, len(cpu_rest))
                    break
                if len(idxs) > 1:
                    # Bisect to isolate; only OOM escalates the attempt
                    # counter (and with it the backoff) — a
                    # deterministic poison gains nothing from waiting.
                    counts["oom_bisections" if isinstance(err, DeviceOOM)
                           else "poison_bisections"] += 1
                    mid = len(idxs) // 2
                    nxt = attempt + 1 if isinstance(err, DeviceOOM) \
                        else attempt
                    log.warning("batch of %d failed (%s: %s); "
                                "bisecting", len(idxs),
                                type(err).__name__, err)
                    stack.append((idxs[mid:], nxt))
                    stack.append((idxs[:mid], nxt))
                    continue
                i = idxs[0]
                if isinstance(err, DeviceOOM) and attempt < max_retries:
                    counts["retries"] += 1
                    stack.append((idxs, attempt + 1))
                    continue
                log.warning("quarantining history %d after %d "
                            "attempt(s): %s: %s", i, attempt + 1,
                            type(err).__name__, err)
                counts["quarantines"] += 1
                results[i] = self._quarantine(err, i, seed_of(i))
                record(i)
                continue
            for i, r in zip(idxs, rs):
                if r is None:
                    results[i] = self._quarantine(
                        CorruptHistory("engine returned no verdict",
                                       history_index=i,
                                       backend=backend),
                        i, seed_of(i))
                else:
                    r = dict(r)
                    r.setdefault("backend", backend)
                    if attempt:
                        r["runner_attempts"] = attempt + 1
                    results[i] = r
                record(i)

        # -- CPU degradation ----------------------------------------------
        if cpu_rest:
            fb = self.cpu_fallback
            if fb is None:
                from jepsen_tpu.ops import wgl_cpu
                fb = wgl_cpu.check
                fb_engine = "wgl_cpu"
            else:
                fb_engine = getattr(fb, "__name__", "cpu-fallback")
            rem = remaining()
            slice_s = None
            if deadline_s is not None:
                # split what's left of the budget evenly, floored so a
                # blown budget still makes bounded progress per history
                slice_s = max(self.cpu_slice_floor_s,
                              max(rem or 0.0, 0.0) / len(cpu_rest))
            for i in cpu_rest:
                try:
                    r = dict(fb(model, histories[i],
                                time_limit=slice_s))
                    r["backend"] = "cpu"
                    r.setdefault("engine", fb_engine)
                    if fallback_cause:
                        r["fallback"] = fallback_cause
                    results[i] = r
                except Exception as e:  # noqa: BLE001 - quarantine
                    err = errors_mod.classify(
                        e, history_index=i, seed=seed_of(i),
                        backend="cpu", batch_size=1)
                    counts["quarantines"] += 1
                    results[i] = self._quarantine(err, i, seed_of(i))
                record(i)
            counts["cpu_fallbacks"] = len(cpu_rest)

        # -- telemetry ------------------------------------------------------
        self._account(results, counts, fallback_cause, n)
        return results

    def _account(self, results, counts: dict, fallback_cause, n) -> None:
        """Record resilience counters + attach dispatch records to the
        verdicts the runner itself produced (quarantines, CPU
        degradations); engine-produced verdicts already carry theirs.
        Never raises — accounting must not undo a survived batch."""
        try:
            from jepsen_tpu import telemetry as telemetry_mod
            for k, v in counts.items():
                if v:
                    telemetry_mod.REGISTRY.counter(
                        f"jepsen_runner_{k}_total").inc(v)
            if any(counts.values()):
                telemetry_mod.emit("runner", **counts)
            by_kind: dict = {}
            for r in results:
                if isinstance(r, dict) and "dispatch" not in r:
                    kind = ("quarantine" if r.get("quarantined")
                            else r.get("engine", "wgl_cpu"))
                    by_kind.setdefault(kind, []).append(r)
            engine_name = self.engine if isinstance(self.engine, str) \
                else getattr(self.engine, "__name__", "custom")
            fb_name = getattr(self.cpu_fallback, "__name__", "wgl_cpu") \
                if self.cpu_fallback is not None else "wgl_cpu"
            from jepsen_tpu.ops import planner
            for kind, rs in by_kind.items():
                pl = planner.runner_plan(
                    engine_name, fb_name,
                    why=(fallback_cause
                         or ("quarantined after retries/bisection"
                             if kind == "quarantine"
                             else "resilient-runner degradation")))
                telemetry_mod.attach_dispatch(
                    rs, pl.record(engine=kind, batch=n, **counts))
        except Exception:   # noqa: BLE001
            log.debug("runner telemetry accounting failed",
                      exc_info=True)


def check(model, histories: Sequence, *, engine="auto",
          engine_kwargs: Optional[dict] = None,
          deadline_s: Optional[float] = None, max_retries: int = 2,
          checkpoint_dir: Optional[str] = None,
          seeds: Optional[Sequence[Any]] = None, **runner_kw) -> list:
    """One-shot convenience: `runner.check(model, histories, ...)`
    without holding a ResilientRunner."""
    return ResilientRunner(
        engine=engine, engine_kwargs=engine_kwargs, **runner_kw,
    ).check(model, histories, deadline_s=deadline_s,
            max_retries=max_retries, checkpoint_dir=checkpoint_dir,
            seeds=seeds)
