"""Cycle / SCC kernels on device — dependency-graph analysis as matmul.

The reference detects serializability anomalies by order/graph reasoning
on the host (`jepsen/src/jepsen/tests/long_fork.clj:216-271`, the
cockroach `monotonic` checker, `jepsen/src/jepsen/tests/adya.clj`), and
its checker complexity notes single out graph search as a scaling wall.
Here the dependency graph of a transaction history becomes a boolean
adjacency matrix, and reachability / strongly-connected components are
computed by **iterated boolean matrix squaring** — ⌈log2 n⌉ matmuls that
XLA tiles straight onto the MXU (BASELINE.json config 4).

    closure:  R ← R ∨ R·R            (log-squaring transitive closure)
    on-cycle: diag(R⁺)               (node reaches itself in ≥1 step)
    SCC:      label i = min { j : R⁺[i,j] ∧ R⁺[j,i] }  (∨ i itself)

Matrices are padded to 128×128 tiles so the matmuls land on the systolic
array at full utilisation; 0/1 values make bf16×bf16→f32 accumulation
exact, so `> 0` thresholds are safe.

Host-side helpers recover one *explicit* cycle path per SCC for error
reporting, walking the closure greedily — O(cycle length) host work only
after the device has proved a cycle exists.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import numpy as np

_TILE = 128


def _pad_to_tile(n: int) -> int:
    return max(_TILE, _TILE * math.ceil(n / _TILE))


@functools.cache
def _kernels(n_pad: int):
    import jax
    import jax.numpy as jnp

    # After k squarings R covers paths of length ≤ 2^k; n_pad-1 hops max.
    steps = max(1, math.ceil(math.log2(max(n_pad - 1, 2))))

    def _closure(adj):
        def body(_, r):
            rf = r.astype(jnp.bfloat16)     # 0/1 exact in bf16 x bf16
            return r | (jnp.dot(rf, rf,
                                preferred_element_type=jnp.float32) > 0.5)

        return jax.lax.fori_loop(0, steps, body, adj)

    # The closure matrix leaves the device BIT-PACKED: device-to-host
    # over a tunneled chip runs ~13 MB/s, so the 4 MB bool matrix at
    # n=2048 cost 3x the matmuls; n^2/8 bytes cuts that 8x.
    @jax.jit
    def closure(adj):
        return jnp.packbits(_closure(adj), axis=1)

    @jax.jit
    def scc(adj):
        r = _closure(adj)
        idx = jnp.arange(n_pad)
        both = (r & r.T) | (idx[:, None] == idx[None, :])
        labels = jnp.min(jnp.where(both, idx[None, :], n_pad), axis=1)
        return labels, jnp.diagonal(r), jnp.packbits(r, axis=1)

    return {"closure": closure, "scc": scc}


def _unpack(packed: np.ndarray, n: int) -> np.ndarray:
    """Host-side inverse of the device packbits: bool [n, n]."""
    return np.unpackbits(np.asarray(packed), axis=1,
                         count=packed.shape[0])[:n, :n].astype(bool)


def _pad(adj: np.ndarray) -> np.ndarray:
    n = adj.shape[0]
    n_pad = _pad_to_tile(n)
    out = np.zeros((n_pad, n_pad), bool)
    out[:n, :n] = np.asarray(adj, bool)
    return out


def transitive_closure(adj: np.ndarray) -> np.ndarray:
    """R⁺ (paths of length ≥ 1) of a boolean adjacency matrix."""
    n = adj.shape[0]
    if n == 0:
        return np.zeros((0, 0), bool)
    k = _kernels(_pad_to_tile(n))["closure"]
    return _unpack(k(_pad(adj)), n)


def scc(adj: np.ndarray):
    """(labels, on_cycle, closure): SCC label per node (min node index of
    its component), mask of nodes on some ≥1-length cycle, and R⁺."""
    n = adj.shape[0]
    if n == 0:
        return (np.zeros(0, np.int64), np.zeros(0, bool),
                np.zeros((0, 0), bool))
    import jax

    k = _kernels(_pad_to_tile(n))["scc"]
    # one pipelined D2H for all three outputs: each separate fetch pays
    # ~90 ms round-trip latency on a tunneled chip
    labels, diag, r = jax.device_get(k(_pad(adj)))
    return labels[:n], diag[:n], _unpack(r, n)


def find_cycle(adj: np.ndarray,
               closure: Optional[np.ndarray] = None) -> Optional[list]:
    """One explicit cycle [v0, v1, …, v0] if the graph has any, else
    None.  BFS from the lowest-indexed on-cycle node back to itself
    (shortest such loop; parent pointers guarantee termination)."""
    adj = np.asarray(adj, bool)
    n = adj.shape[0]
    if n == 0:
        return None
    if closure is None:
        closure = transitive_closure(adj)
    diag = np.diagonal(closure)
    if not diag.any():
        return None
    start = int(np.argmax(diag))
    if adj[start, start]:
        return [start, start]
    parent = {}
    frontier = [start]
    while frontier:
        nxt = []
        for u in frontier:
            for v in map(int, np.nonzero(adj[u])[0]):
                if v == start:
                    path = [u]
                    while path[-1] != start:
                        path.append(parent[path[-1]])
                    path.reverse()
                    path.append(start)
                    return path
                if v not in parent:
                    parent[v] = u
                    nxt.append(v)
        frontier = nxt
    return None


def cycles_by_component(adj: np.ndarray) -> list:
    """One explicit cycle per non-trivial SCC (for reporting every
    independent anomaly, not just the first)."""
    adj = np.asarray(adj, bool)
    labels, on_cycle, closure = scc(adj)
    out = []
    for comp in np.unique(labels[on_cycle]):
        members = np.nonzero(labels == comp)[0]
        sub = adj[np.ix_(members, members)]
        cyc = find_cycle(sub, closure[np.ix_(members, members)])
        if cyc is not None:
            out.append([int(members[i]) for i in cyc])
    return out


def reachability_from(adj: np.ndarray, sources: np.ndarray) -> np.ndarray:
    """Boolean reachability of every node from a set of sources in one
    closure pass — the building block for monotonicity / precedes
    queries."""
    closure = transitive_closure(adj)
    src = np.asarray(sources, bool)
    return src @ closure | src
