"""Pallas TPU megakernel for the batched bitmap frontier scan.

The XLA kernel (ops/wgl_seg._build_kernel_bits) runs the L-event scan
as a lax.scan whose carry round-trips memory and whose per-event
while_loop dispatches as separate fusions.  This Pallas variant keeps
the frontier in VMEM **scratch that persists across grid steps**: the
grid is (L,) — one step per event, the ENTIRE key axis in lanes (the
event axis is inherently serial, so all parallelism comes from K) —
and the pipeline streams each event's tables into VMEM while the
previous event computes.  Scratch is [SN_PAD, K] uint32 (~2 MB at the
K <= 2^16 cap).

Scope (the multi-key batch hot path, exactly the bench shape): J=1
start state, R <= 5 open slots (the 2^R mask axis fits ONE uint32
word), decomposed transitions, Sn <= 8 states.  Everything else takes
the XLA kernel; verdicts are bit-identical (differential tests).

Host->device transfer stays at the XLA path's narrow-table budget: the
four per-candidate tables (diag bitmask, const bitmask, const target,
slot) pack into ONE uint32 word per (event, candidate, key):

    bits 0-7   aux1  (diagonal state bitmask)
    bits 8-15  aux2  (rank-1 state bitmask)
    bits 16-19 t0    (rank-1 target state)
    bits 20-23 slot  (candidate's open slot)
"""

from __future__ import annotations

import functools

import numpy as np

# Intra-word "lacks bit b" patterns (wgl_seg._INTRA)
_INTRA = (0x55555555, 0x33333333, 0x0F0F0F0F, 0x00FF00FF, 0x0000FFFF)

SN_PAD = 8        # frontier sublane padding
KT_MAX = 1 << 16  # beyond this the frontier would stress VMEM


def supported(Wd: int, Sn: int, J: int, decomposed: bool,
              L: int, C: int, K: int) -> bool:
    return (Wd == 1 and J == 1 and decomposed and Sn <= SN_PAD
            and C <= SN_PAD and K % 128 == 0 and K <= KT_MAX)


def pack_tables(cslot_t: np.ndarray, aux1: np.ndarray,
                aux2: np.ndarray, t0c: np.ndarray) -> np.ndarray:
    """[L, K, C] narrow tables -> [L, C, K] uint32 packed words."""
    w = (aux1.astype(np.uint32)
         | (aux2.astype(np.uint32) << 8)
         | ((t0c.astype(np.uint32) & 0xF) << 16)
         | ((cslot_t.astype(np.uint32) & 0xF) << 20))
    return np.ascontiguousarray(w.transpose(0, 2, 1))


@functools.lru_cache(maxsize=16)
def build(K: int, L: int, C: int, Sn: int, R: int,
          interpret: bool = False):
    """kern(rs_i32 [L, 1, K], packed_u32 [L, C, K]) -> [SN_PAD, K]
    uint32 with fr & 1 — whether mask-0 survives at each state."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    u32 = jnp.uint32
    FULL = np.uint32(0xFFFFFFFF)
    # one grid step per event, the whole key axis in lanes: the L axis
    # is inherently serial, so all parallelism must come from K
    KT = K

    def popcount_sum(x):
        return jax.lax.population_count(x).astype(jnp.int32).sum()

    def sel32(cond):
        return jnp.where(cond, jnp.asarray(FULL, u32),
                         jnp.asarray(np.uint32(0), u32))

    def kernel(rs_ref, packed_ref, out_ref, fr_ref):
        l = pl.program_id(0)
        s_iota = jax.lax.broadcasted_iota(jnp.int32, (SN_PAD, KT), 0)

        @pl.when(l == 0)
        def _init():
            # J=1: only mask 0 (bit 0 of the word) at start state 0
            fr_ref[:, :] = jnp.where(
                s_iota == 0, jnp.asarray(np.uint32(1), u32),
                jnp.asarray(np.uint32(0), u32))

        rs = rs_ref[0, 0, :]                               # [KT] i32
        packed = packed_ref[0]                             # [C, KT] u32
        aux1 = packed & np.uint32(0xFF)
        aux2 = (packed >> 8) & np.uint32(0xFF)
        ct0 = ((packed >> 16) & np.uint32(0xF)).astype(jnp.int32)
        cslot = ((packed >> 20) & np.uint32(0xF)).astype(jnp.int32)

        def lacking(fr, b):
            return fr & np.uint32(_INTRA[b])

        def set_slot(fr, b):
            return (fr & np.uint32(_INTRA[b])) << (1 << b)

        def retire_slot(fr, b):
            return (fr & np.uint32(~np.uint32(_INTRA[b]))) >> (1 << b)

        def expand_candidate(fr, c):
            slot_kc = cslot[c, :]                          # [KT]
            contrib = jnp.zeros_like(fr)
            for b in range(R):
                contrib = contrib | (
                    lacking(fr, b) & sel32(slot_kc == b)[None, :])
            # decomposed transition: the diagonal part stays put; the
            # rank-1 part ORs over source states onto row t0
            dsel = sel32(((aux1[c, :][None, :].astype(jnp.int32)
                           >> s_iota) & 1) == 1)
            moved = contrib & dsel
            csel = sel32(((aux2[c, :][None, :].astype(jnp.int32)
                           >> s_iota) & 1) == 1)
            red = contrib & csel
            red_or = jnp.zeros((KT,), u32)
            for s in range(Sn):
                red_or = red_or | red[s, :]
            at_t0 = sel32(s_iota == ct0[c, :][None, :])
            moved = moved | (red_or[None, :] & at_t0)
            out = jnp.zeros_like(fr)
            for b in range(R):
                out = out | (set_slot(moved, b)
                             & sel32(slot_kc == b)[None, :])
            return out

        def lack_target(fr):
            lt = jnp.zeros_like(fr)
            for b in range(R):
                lt = lt | (lacking(fr, b) & sel32(rs == b)[None, :])
            return lt & sel32(rs >= 0)[None, :]

        def round_(carry):
            fr, _, prev = carry
            add = jnp.zeros_like(fr)
            for c in range(C):
                add = add | expand_candidate(fr, c)
            fr2 = fr | add
            cnt = popcount_sum(fr2)
            return (fr2,
                    (cnt > prev) & (popcount_sum(lack_target(fr2)) > 0),
                    cnt)

        fr = fr_ref[:, :]
        fr, _, _ = jax.lax.while_loop(
            lambda cy: cy[1], round_,
            (fr, popcount_sum(lack_target(fr)) > 0, jnp.int32(-1)))

        cleared = jnp.zeros_like(fr)
        for b in range(R):
            cleared = cleared | (retire_slot(fr, b)
                                 & sel32(rs == b)[None, :])
        fr = jnp.where((rs >= 0)[None, :], cleared, fr)
        fr_ref[:, :] = fr

        @pl.when(l == L - 1)
        def _finish():
            out_ref[:, :] = fr_ref[:, :] & np.uint32(1)

    def kern(rs_i32, packed_u32):
        import jax

        return pl.pallas_call(
            kernel,
            grid=(L,),
            in_specs=[
                # sublane dims must divide 8 or equal the array dim —
                # hence the size-1 middle axis on rs
                pl.BlockSpec((1, 1, KT), lambda l: (l, 0, 0)),
                pl.BlockSpec((1, C, KT), lambda l: (l, 0, 0)),
            ],
            out_specs=pl.BlockSpec((SN_PAD, KT), lambda l: (0, 0)),
            out_shape=jax.ShapeDtypeStruct((SN_PAD, K), np.uint32),
            scratch_shapes=[pltpu.VMEM((SN_PAD, KT), np.uint32)],
            compiler_params=pltpu.CompilerParams(
                dimension_semantics=("arbitrary",)),
            interpret=interpret,
        )(rs_i32, packed_u32)

    import jax
    return jax.jit(kern)


def run_packed(ret_t: np.ndarray, packed: np.ndarray, K: int, L: int,
               C: int, Sn: int, R: int):
    """Run on pre-packed tables (see pack_tables); returns [K, 1, Sn]
    bool like the XLA kernel's thresholded output.  The interpreter is
    used ONLY on CPU (the test backend) — on any other non-TPU backend
    (e.g. GPU) this raises so callers fall back to the fast XLA
    kernel instead of silently interpreting."""
    import jax

    backend = jax.default_backend()
    if backend not in ("tpu", "cpu"):
        raise RuntimeError(f"no pallas lowering for {backend}")
    kern = build(K, L, C, Sn, R, interpret=(backend == "cpu"))
    rs = np.ascontiguousarray(ret_t.astype(np.int32)[:, None, :])
    out = np.asarray(kern(rs, packed))                # [SN_PAD, K]
    return (out.T[:, None, :Sn] > 0)                  # [K, 1, Sn]


def run(ret_t: np.ndarray, cslot_t: np.ndarray, aux1: np.ndarray,
        aux2: np.ndarray, t0c: np.ndarray, K: int, L: int, C: int,
        Sn: int, R: int):
    """Adapt the XLA bits-kernel argument layout ([L, K] + [L, K, C])
    to the packed Pallas layout and run."""
    return run_packed(ret_t, pack_tables(cslot_t, aux1, aux2, t0c),
                      K, L, C, Sn, R)
