"""Device kernels (JAX/TPU) and their CPU oracles.

  prep.py       shared history -> call-record preprocessing
  wgl_cpu.py    CPU just-in-time-linearization oracle (knossos-equivalent)
  wgl.py        batched frontier WGL search on TPU — the centerpiece
  fold.py       masked segmented reductions for O(n) checkers
  cycle.py      dependency-graph reachability / SCC via bool matmul
  elle_graph.py typed-cycle (Adya) classification, dense vmap engine
  elle_mesh.py  bit-packed + mesh-sharded Elle closure engine
  planner.py    THE engine-routing decision (shape -> terminating
                engine chain, rendered into every dispatch record),
                the persistent compiled-plan cache, and the host-side
                planning/packing section (scanners, segmentation,
                state enumeration, table packers)
  runner.py     resilient execution layer around the batch entry points
                (OOM bisection, deadline-bounded CPU fallback,
                retry/quarantine, resumable verdict checkpoints) +
                the async double-buffered executor (`overlap`)
"""


def shard_map_compat(body, *, mesh, in_specs, out_specs):
    """`jax.shard_map` across the JAX-version drift this repo has to
    survive (ADVICE r5): the export moved out of `jax.experimental`,
    and the "skip the replication check" kwarg is spelled `check_vma`
    on newer releases, `check_rep` on 0.4.x (where the default check
    also has no rule for several primitives we shard).  Degrade through
    the spellings on unknown-kwarg TypeError instead of raising; a
    total miss is a BackendUnavailable, not a crash.

    The check must be *skipped*, not satisfied: our sharded bodies are
    per-device-independent (or use explicit collectives), and e.g.
    pallas_call carries no varying-mesh-axes info for the checker to
    consume.
    """
    import jax

    from jepsen_tpu.errors import BackendUnavailable
    try:
        shard_map = jax.shard_map
    except AttributeError:        # pre-export-move JAX releases
        from jax.experimental.shard_map import shard_map

    specs = dict(mesh=mesh, in_specs=in_specs, out_specs=out_specs)
    for kwarg in ({"check_vma": False}, {"check_rep": False}, {}):
        try:
            return shard_map(body, **specs,
                             **kwarg)  # type: ignore[call-arg]
        except TypeError:
            continue
    raise BackendUnavailable(
        "jax.shard_map rejected every known kwarg spelling",
        backend=jax.default_backend())
