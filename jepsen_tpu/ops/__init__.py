"""Device kernels (JAX/TPU) and their CPU oracles.

  prep.py     shared history -> call-record preprocessing
  wgl_cpu.py  CPU just-in-time-linearization oracle (knossos-equivalent)
  wgl.py      batched frontier WGL search on TPU — the centerpiece
  fold.py     masked segmented reductions for O(n) checkers
  cycle.py    dependency-graph reachability / SCC via bool matmul
  runner.py   resilient execution layer around the batch entry points
              (OOM bisection, deadline-bounded CPU fallback,
              retry/quarantine, resumable verdict checkpoints)
"""
