"""Device kernels (JAX/TPU) and their CPU oracles.

  prep.py       shared history -> call-record preprocessing
  wgl_cpu.py    CPU just-in-time-linearization oracle (knossos-equivalent)
  wgl.py        batched frontier WGL search on TPU — the centerpiece
  fold.py       masked segmented reductions for O(n) checkers
  cycle.py      dependency-graph reachability / SCC via bool matmul
  elle_graph.py typed-cycle (Adya) classification, dense vmap engine
  elle_mesh.py  bit-packed + mesh-sharded Elle closure engine
  planner.py    THE engine-routing decision (shape -> terminating
                engine chain, rendered into every dispatch record),
                the persistent compiled-plan cache, and the host-side
                planning/packing section (scanners, segmentation,
                state enumeration, table packers)
  runner.py     resilient execution layer around the batch entry points
                (OOM bisection, deadline-bounded CPU fallback,
                retry/quarantine, resumable verdict checkpoints) +
                the async double-buffered executor (`overlap`)
  shard_map_compat.py
                the shard_map kwarg-drift shim + the mesh-collective
                helpers (frontier all-gather, exact monotone early
                exit, hypercube pairwise exchange) shared by elle_mesh
                and wgl_deep's mask shard
"""

# Long-standing callers import the shim AS `ops.shard_map_compat` (a
# callable); the helpers grew into a module of the same name (ISSUE 10
# satellite).  This re-export keeps the package attribute bound to the
# FUNCTION — identity-pinned by tests/test_elle_mesh.py — while the
# sibling helpers are reachable via the module in sys.modules
# (`from jepsen_tpu.ops.shard_map_compat import hypercube_exchange`).
from jepsen_tpu.ops.shard_map_compat import shard_map_compat  # noqa: F401,E501
