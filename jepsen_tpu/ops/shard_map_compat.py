"""Mesh-collective helpers shared by every shard_map engine.

Two engines shard a monotone-frontier fixpoint over a device mesh —
`ops.elle_mesh` (packed Adya closure, row-sharded) and
`ops.wgl_deep.check_hypercube` (configuration-mask shard, ISSUE 10) —
and both need the same three pieces of glue:

  * `shard_map_compat` — `jax.shard_map` across the JAX-version drift
    this repo has to survive (export location + the replication-check
    kwarg spelling);
  * `all_gather_frontier` — the per-round frontier all-gather (tiled,
    so a row-shard gathers to the full operand every device's local
    product needs);
  * `frontier_settled` — the exact device-side early-exit test: the
    closure state is monotone, so a round that changed nothing on ANY
    device (psum of the per-device change flags is zero) IS the
    fixpoint.

The deep hypercube shard adds `hypercube_exchange`: with the top
log2(D) mask bits mapped onto the device axis, a transition that flips
high bit k is a deterministic pairwise `ppermute` with the partner
`d XOR 2^k` — one exchange per high slot per event round, no
all-to-all.  Extracted here (ISSUE 10 satellite) so the kwarg-drift
handling and the frontier early-exit idiom exist ONCE; `ops/__init__`
re-exports `shard_map_compat` for the long-standing callers
(identity-pinned by tests/test_elle_mesh.py)."""

from __future__ import annotations


def shard_map_compat(body, *, mesh, in_specs, out_specs):
    """`jax.shard_map` across the JAX-version drift this repo has to
    survive (ADVICE r5): the export moved out of `jax.experimental`,
    and the "skip the replication check" kwarg is spelled `check_vma`
    on newer releases, `check_rep` on 0.4.x (where the default check
    also has no rule for several primitives we shard).  Degrade through
    the spellings on unknown-kwarg TypeError instead of raising; a
    total miss is a BackendUnavailable, not a crash.

    The check must be *skipped*, not satisfied: our sharded bodies are
    per-device-independent (or use explicit collectives), and e.g.
    pallas_call carries no varying-mesh-axes info for the checker to
    consume.
    """
    import jax

    from jepsen_tpu.errors import BackendUnavailable
    try:
        shard_map = jax.shard_map
    except AttributeError:        # pre-export-move JAX releases
        from jax.experimental.shard_map import shard_map

    specs = dict(mesh=mesh, in_specs=in_specs, out_specs=out_specs)
    for kwarg in ({"check_vma": False}, {"check_rep": False}, {}):
        try:
            return shard_map(body, **specs,
                             **kwarg)  # type: ignore[call-arg]
        except TypeError:
            continue
    raise BackendUnavailable(
        "jax.shard_map rejected every known kwarg spelling",
        backend=jax.default_backend())


def all_gather_frontier(x, axis: str):
    """Gather a sharded frontier operand to its full extent along
    `axis` (tiled: shards concatenate, no new leading axis) — the
    per-round right-operand gather of every sharded closure here."""
    import jax

    return jax.lax.all_gather(x, axis, tiled=True)


def frontier_settled(changed, axis: str):
    """Exact mesh-wide fixpoint test for a MONOTONE frontier: True when
    no device changed anything this round (psum of the boolean change
    flags is zero).  Monotonicity is what makes this exact — an
    unchanged round can never be followed by a changing one."""
    import jax
    import jax.numpy as jnp

    return jax.lax.psum(changed.astype(jnp.int32), axis) == 0


def hypercube_exchange(x, axis: str, bit: int, n_devices: int):
    """One deterministic pairwise exchange on the hypercube: every
    device swaps `x` with its partner `d XOR 2^bit` along `axis`
    (a single ppermute — the full pairing permutation is its own
    inverse).  Callers pre-mask `x` to the sending side, so the value
    received on the non-sending side is exactly the moved data and the
    sending side receives zeros."""
    import jax

    pairs = [(d, d ^ (1 << bit)) for d in range(int(n_devices))]
    return jax.lax.ppermute(x, axis, perm=pairs)
