"""Typed-cycle classification on device — Elle's DSG phase as batched
boolean matmuls.

`ops/cycle.py` proves *a* cycle exists; isolation classification needs
to know which **edge-type combination** closes one (Adya):

    G0        cycle of ww edges only
    G1c       cycle of ww ∪ wr containing ≥ 1 wr
    G-single  cycle containing exactly one rw (anti-dependency)
    G2-item   cycle containing ≥ 2 rw

Each history arrives as a stack of boolean adjacency planes
(`elle.infer.PLANES`: ww, wr, rw, po, rt) and the whole batch runs as
ONE device program (same batching discipline as `wgl_batch`): planes
pad to 128-aligned tiles so the log-squaring matmuls land on the MXU
at full utilisation, and `vmap` carries the history axis.

The classification trick — *masked closures*: each class is decided by
whether some defining edge (a, b) has a return path b ⇒ a through a
restricted plane union:

    G0        (a,b) ∈ ww,  b ⇒ a via ww ∪ O          (O = po/rt planes)
    G1c       (a,b) ∈ wr,  b ⇒ a via ww ∪ wr ∪ O
    G-single  (a,b) ∈ rw,  b ⇒ a via ww ∪ wr ∪ O     (zero further rw)
    G2-item   (a,b) ∈ rw,  b ⇒ a via the full plane **using ≥ 1 rw**,
              and (a,b) closes NO zero-rw return (priority: an edge
              already explained as G-single cannot define a G2 —
              closures count walks, and a single-rw cycle walked twice
              would otherwise masquerade as a ≥2-rw cycle)

The ≥1-rw reachability is a two-plane closure: carry (P0, P1) =
(paths with zero rw, paths with ≥ one rw) and square the pair —
P1 ← P1 ∨ P0·P1 ∨ P1·P0 ∨ P1·P1.  The device returns only per-class
flags and ONE defining edge per class (argmax over the mask), so the
D2H transfer is O(B), not O(B·n²); the host then walks one explicit
cycle witness per anomaly over the sparse planes it already holds.

`classify_host` is the independent naive oracle (numpy closures +
BFS) used by the differential battery and as the no-device fallback
(`engine=elle-host`).
"""

from __future__ import annotations

import math
import time
from typing import Optional, Sequence

import numpy as np

from jepsen_tpu.elle.infer import PLANES

_TILE = 128

ANOMALY_CLASSES = ("G0", "G1c", "G-single", "G2-item")


def _pad_to_tile(n: int) -> int:
    return max(_TILE, _TILE * math.ceil(n / _TILE))


# Compiled-kernel cache, one entry per 128-aligned tile size — explicit
# (not functools.cache) so the shape-bucket accounting below can tell a
# warm bucket from a fresh compile.
_KERNEL_CACHE: dict = {}
_BUCKET_STATS = {"hits": 0, "misses": 0}


def kernel_cache_stats() -> dict:
    return dict(_BUCKET_STATS)


def clear_kernel_cache() -> None:
    _KERNEL_CACHE.clear()
    _BUCKET_STATS.update(hits=0, misses=0)


def _kernels(n_pad: int):
    """Bucketed kernel lookup: one compiled program per tile size,
    hit/miss counted into telemetry (`jepsen_elle_bucket_total`)."""
    hit = n_pad in _KERNEL_CACHE
    if hit:
        _BUCKET_STATS["hits"] += 1
    else:
        _KERNEL_CACHE[n_pad] = _build_kernels(n_pad)
        _BUCKET_STATS["misses"] += 1
    try:
        from jepsen_tpu import telemetry
        telemetry.REGISTRY.counter(
            "jepsen_elle_bucket_total",
            result="hit" if hit else "miss").inc()
    except Exception:           # noqa: BLE001 - telemetry is advisory
        pass
    return _KERNEL_CACHE[n_pad]


def _build_kernels(n_pad: int):
    import jax
    import jax.numpy as jnp

    steps = max(1, math.ceil(math.log2(max(n_pad - 1, 2))))

    def _sq(a, b):
        # 0/1 exact in bf16 x bf16 -> f32 accumulation on the MXU
        return (jnp.dot(a.astype(jnp.bfloat16), b.astype(jnp.bfloat16),
                        preferred_element_type=jnp.float32) > 0.5)

    def _closure(adj):
        def body(_, r):
            return r | _sq(r, r)
        return jax.lax.fori_loop(0, steps, body, adj)

    def _pair_closure(a, r):
        """(reach with 0 rw, reach with ≥1 rw) over plane a ∪ r where
        only r-edges count as rw.  P0 seeds with identity so length-0
        prefixes/suffixes compose."""
        eye = jnp.eye(n_pad, dtype=bool)

        def body(_, c):
            p0, p1 = c
            n0 = p0 | _sq(p0, p0)
            n1 = p1 | _sq(p0, p1) | _sq(p1, p0) | _sq(p1, p1)
            return n0, n1

        return jax.lax.fori_loop(0, steps, body, (a | eye, r))

    def _pick(mask):
        """(found?, a, b) for one edge of a boolean [n, n] mask."""
        flat = jnp.argmax(mask)
        return mask.reshape(-1)[flat], flat // n_pad, flat % n_pad

    def one(planes):
        ww, wr, rw, po, rt = (planes[i] for i in range(len(PLANES)))
        order = po | rt
        c_ww = _closure(ww | order)
        c_wwr = _closure(ww | wr | order)
        _, p1 = _pair_closure(ww | wr | order, rw)
        # Priority masking (the "which combination first closes a
        # cycle" rule): the pair closure counts WALKS, so a G-single
        # cycle traversed twice would read as a ≥2-rw cycle — an rw
        # edge that already closes with zero further rw (G-single)
        # therefore cannot define a G2-item.
        masks = {
            "G0": ww & c_ww.T,
            "G1c": wr & c_wwr.T,
            "G-single": rw & c_wwr.T,
            "G2-item": rw & p1.T & ~c_wwr.T,
        }
        flags, edges = [], []
        for cls in ANOMALY_CLASSES:
            found, a, b = _pick(masks[cls])
            flags.append(found)
            edges.append(jnp.stack([a, b]))
        return jnp.stack(flags), jnp.stack(edges).astype(jnp.int32)

    return jax.jit(jax.vmap(one))


def _pad_stack(stacks: Sequence[np.ndarray], n_pad: int) -> np.ndarray:
    out = np.zeros((len(stacks), len(PLANES), n_pad, n_pad), bool)
    for i, s in enumerate(stacks):
        n = s.shape[-1]
        out[i, :, :n, :n] = s
    return out


def classify_batch(stacks: Sequence[np.ndarray],
                   include_order: bool = True) -> list:
    """Classify MANY histories, one device program per SHAPE BUCKET.

    stacks: one [len(PLANES), n, n] bool array per history (n may
    differ).  Histories group by their own 128-aligned tile size —
    a stray 10k-txn history costs its 1k-txn batchmates nothing (the
    old behavior padded the whole batch to the largest tile, a 100x
    cost amplifier); each bucket's compiled kernel is cached, with
    hit/miss counts in `jepsen_elle_bucket_total`.
    include_order: include the po/rt planes in every combination
    (strict/strong-session variants); when False they are zeroed.

    Returns one dict per history (input order preserved):
      {"anomalies": {cls: (a, b) defining edge}, "n": n, "n_pad": int}
    """
    if not stacks:
        return []
    import jax

    buckets: dict = {}
    for i, s in enumerate(stacks):
        buckets.setdefault(_pad_to_tile(s.shape[-1]), []).append(i)
    out: list = [None] * len(stacks)
    for n_pad in sorted(buckets):
        idxs = buckets[n_pad]
        batch = _pad_stack([stacks[i] for i in idxs], n_pad)
        if not include_order:
            batch[:, 3:, :, :] = False
        flags, edges = jax.device_get(_kernels(n_pad)(batch))
        for j, i in enumerate(idxs):
            found = {cls: (int(edges[j, c, 0]), int(edges[j, c, 1]))
                     for c, cls in enumerate(ANOMALY_CLASSES)
                     if bool(flags[j, c])}
            out[i] = {"anomalies": found, "n": stacks[i].shape[-1],
                      "n_pad": n_pad}
    return out


# ---------------------------------------------------------------------------
# Host oracle — independent formulation (numpy closure + BFS), the
# differential-test baseline and the no-device fallback engine.
# ---------------------------------------------------------------------------

def _mm(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    # f32, not uint8: path counts overflow a byte past n=255 and can
    # wrap to exactly 0, silently erasing reachability
    return a.astype(np.float32) @ b.astype(np.float32) > 0


def closure_reference(stack: np.ndarray,
                      include_order: bool = True) -> tuple:
    """Cold pair-closure triple (cww, p0, p1) of one dense
    [len(PLANES), n, n] bool stack, computed to the unconditional
    fixpoint with the mesh kernel's exact update rule — the oracle the
    incremental tier's warm-started closures are pinned against
    (tests/test_live_txn.py): a warm closure over any covered-removal
    history must equal this, square for square."""
    ww, wr, rw, po, rt = (np.asarray(stack[i], bool)
                          for i in range(len(PLANES)))
    n = ww.shape[-1]
    order = (po | rt) if include_order else np.zeros_like(ww)
    eye = np.eye(n, dtype=bool)
    cww = ww | order
    p0 = ww | wr | order | eye
    p1 = rw.copy()
    while True:
        q = p0 | p1
        cww2 = cww | _mm(cww, cww)
        p0n = p0 | _mm(p0, p0)
        p1n = p1 | _mm(q, p1) | _mm(p1, q)
        if (np.array_equal(cww2, cww) and np.array_equal(p0n, p0)
                and np.array_equal(p1n, p1)):
            return cww, p0, p1
        cww, p0, p1 = cww2, p0n, p1n


class _HostDeadline(Exception):
    pass


def classify_host(stack: np.ndarray, include_order: bool = True,
                  deadline_s: Optional[float] = None) -> dict:
    """Naive host classification of ONE history's plane stack —
    same output row shape as classify_batch.

    deadline_s caps the wall clock: the O(n^3 log n) numpy closure is
    an accidental multi-minute hang when reached as a fallback at
    sharded sizes, so past the budget it returns an honest `unknown`
    degradation row ({"unknown": True, "degraded": "host-deadline"})
    instead of either finishing hours later or silently passing."""
    t0 = time.monotonic()

    def tick():
        if (deadline_s is not None
                and time.monotonic() - t0 > deadline_s):
            raise _HostDeadline

    ww, wr, rw, po, rt = (stack[i] for i in range(len(PLANES)))
    n = ww.shape[-1]
    if n == 0:
        return {"anomalies": {}, "n": 0, "n_pad": 0}
    order = (po | rt) if include_order else np.zeros_like(ww)
    steps = max(1, math.ceil(math.log2(max(n - 1, 2))))
    try:
        tick()
        c_ww = ww | order
        for _ in range(steps):
            c_ww = c_ww | _mm(c_ww, c_ww)
            tick()
        c_wwr = ww | wr | order
        for _ in range(steps):
            c_wwr = c_wwr | _mm(c_wwr, c_wwr)
            tick()
        # ≥1-rw reachability via the same pair recurrence
        p0 = (ww | wr | order) | np.eye(n, dtype=bool)
        p1 = rw.copy()
        for _ in range(steps):
            n0 = p0 | _mm(p0, p0)
            n1 = p1 | _mm(p0, p1) | _mm(p1, p0) | _mm(p1, p1)
            p0, p1 = n0, n1
            tick()
    except _HostDeadline:
        return {"anomalies": {}, "n": n, "n_pad": n, "unknown": True,
                "degraded": "host-deadline", "deadline_s": deadline_s,
                "elapsed_s": round(time.monotonic() - t0, 3)}
    masks = {"G0": ww & c_ww.T, "G1c": wr & c_wwr.T,
             "G-single": rw & c_wwr.T,
             "G2-item": rw & p1.T & ~c_wwr.T}
    found = {}
    for cls, m in masks.items():
        if m.any():
            a, b = np.unravel_index(int(np.argmax(m)), m.shape)
            found[cls] = (int(a), int(b))
    return {"anomalies": found, "n": n, "n_pad": n}


# ---------------------------------------------------------------------------
# Witness recovery — host walk, O(cycle) after the device proved it
# ---------------------------------------------------------------------------

def _bfs_path(adj: np.ndarray, src: int, dst: int) -> Optional[list]:
    """Shortest path src -> dst (length ≥ 1) over a boolean adjacency
    matrix, or None."""
    parent = {src: None}
    frontier = [src]
    while frontier:
        nxt = []
        for u in frontier:
            for v in map(int, np.nonzero(adj[u])[0]):
                if v == dst:
                    path = [v, u]
                    while parent[path[-1]] is not None:
                        path.append(parent[path[-1]])
                    path.reverse()
                    return path
                if v not in parent:
                    parent[v] = u
                    nxt.append(v)
        frontier = nxt
    return None


def _bfs_path_with_rw(base: np.ndarray, rw: np.ndarray,
                      src: int, dst: int) -> Optional[list]:
    """Path src -> dst over base ∪ rw that uses ≥ 1 rw edge: BFS over
    the (node, seen-rw) product graph."""
    full = base | rw
    start = (src, False)
    parent: dict = {start: None}
    frontier = [start]
    while frontier:
        nxt = []
        for u, seen in frontier:
            for v in map(int, np.nonzero(full[u])[0]):
                s2 = seen or bool(rw[u, v])
                if v == dst and s2:
                    path = [(v, s2), (u, seen)]
                    while parent[path[-1]] is not None:
                        path.append(parent[path[-1]])
                    path.reverse()
                    return [p for p, _ in path]
                if (v, s2) not in parent:
                    parent[(v, s2)] = (u, seen)
                    nxt.append((v, s2))
        frontier = nxt
    return None


def find_witness(stack: np.ndarray, cls: str, edge,
                 include_order: bool = True) -> Optional[list]:
    """One explicit cycle [a, b, ..., a] for a device-found anomaly:
    the defining edge (a, b) plus the restricted return path b ⇒ a.
    G-single's return path must avoid rw; G2-item's must include one."""
    ww, wr, rw, po, rt = (stack[i] for i in range(len(PLANES)))
    order = (po | rt) if include_order else np.zeros_like(ww)
    a, b = int(edge[0]), int(edge[1])
    if cls == "G0":
        back = _bfs_path(ww | order, b, a)
    elif cls in ("G1c", "G-single"):
        back = _bfs_path(ww | wr | order, b, a)
    elif cls == "G2-item":
        back = _bfs_path_with_rw(ww | wr | order, rw, b, a)
    else:
        raise ValueError(f"unknown anomaly class {cls!r}")
    if back is None:
        return None
    return [a] + back
