"""CPU linearizability oracle: just-in-time linearization with memoization.

This is the knossos-equivalent reference implementation (the reference
delegates to knossos.linear / knossos.wgl at
`jepsen/src/jepsen/checker.clj:141-145`).  It exists for three reasons:

  1. differential testing of the TPU kernel (same history => same verdict);
  2. the fallback path for rich host-side models with no DeviceSpec;
  3. the "CPU knossos" baseline that bench.py measures speedups against.

Algorithm (Lowe-style JIT linearization, equivalent to knossos :linear):
walk history events in order keeping a set of *configurations*
(frozenset-of-linearized-open-calls, model).  When a call returns, expand
each configuration by linearizing pending calls until every surviving
configuration contains the returning call; configurations that cannot are
pruned.  If the set empties, the history is not linearizable and the
current op is the witness.  Crashed (:info) calls stay pending forever and
may be linearized at any later point or never
(`doc/tutorial/06-refining.md:12-19`).
"""

from __future__ import annotations

import time
from typing import Any, Optional

from jepsen_tpu.models import is_inconsistent
from jepsen_tpu.ops.prep import PreparedHistory, prepare


def check(model, history, *,
          max_configs: int = 1_000_000,
          time_limit: Optional[float] = None,
          cancel=None, initial_models=None) -> dict[str, Any]:
    """cancel: optional threading.Event — when set, the walk stops and
    returns {'valid?': 'cancelled'} (competition-mode loser).

    initial_models: optional list of models to seed the config set with
    INSTEAD of `model` — the segment-local witness replay passes every
    reachable entry state of the dead segment here, so the walk IS the
    union of the per-entry-state searches and its witness (first return
    at which the union empties) matches the whole-history oracle's by
    quiescent-cut compositionality.

    Returns a knossos-shaped analysis map:
    {'valid?': True|False|'unknown', 'op_count', 'configs', 'final_model'?,
     'op'? (witness), 'anomaly'?}."""
    t0 = time.monotonic()
    prep = history if isinstance(history, PreparedHistory) else prepare(history)
    calls = prep.calls

    configs: set[tuple[frozenset, Any]] = {
        (frozenset(), m)
        for m in (initial_models if initial_models is not None
                  else [model])}
    pending: set[int] = set()

    events_done = 0
    for ev, kind, cid in prep.events:
        events_done += 1
        if kind == 0:
            pending.add(cid)
            continue

        # Return of call `cid`: close configurations over one-step
        # linearizations of pending calls until all contain cid.
        done: set[tuple[frozenset, Any]] = set()
        frontier = configs
        seen = set(configs)
        while frontier:
            if cancel is not None and cancel.is_set():
                # competition mode lost the race: stop burning CPU
                return {"valid?": "cancelled", "op_count": len(calls)}
            if time_limit is not None and time.monotonic() - t0 > time_limit:
                return {"valid?": "unknown", "cause": "timeout",
                        "op_count": len(calls),
                        "events_done": events_done,
                        "events_total": len(prep.events)}
            nxt: set[tuple[frozenset, Any]] = set()
            for mask, m in frontier:
                if cid in mask:
                    done.add((mask, m))
                    continue
                for j in pending:
                    if j in mask:
                        continue
                    m2 = m.step(calls[j].op)
                    if is_inconsistent(m2):
                        continue
                    c2 = (mask | {j}, m2)
                    if c2 not in seen:
                        seen.add(c2)
                        nxt.add(c2)
            if len(seen) > max_configs:
                return {"valid?": "unknown", "cause": "config-explosion",
                        "op_count": len(calls), "configs": len(seen),
                        "events_done": events_done,
                        "events_total": len(prep.events)}
            frontier = nxt

        call = calls[cid]
        if not done:
            return {"valid?": False,
                    "op": call.op.to_dict(),
                    "op_index": call.op.index,
                    "op_count": len(calls),
                    "anomaly": "nonlinearizable",
                    "configs": _render_configs(configs, calls),
                    "final-paths": _final_paths(configs, calls, cid,
                                                pending)}
        # cid's slot retires: drop it from masks (it is now linearized in
        # every surviving configuration, so the bit carries no information).
        pending.discard(cid)
        configs = {(mask - {cid}, m) for mask, m in done}

    return {"valid?": True, "op_count": len(calls),
            "configs": _render_configs(configs, calls, limit=10)}


def _final_paths(configs, calls, failing_cid: int, pending,
                 limit: int = 10):
    """Why each surviving configuration could not linearize the failing
    call: for every config (truncated to `limit`, the reference's own
    cap — knossos final-paths 'can take *hours*' to write,
    checker.clj:155-158), the one-step expansion attempts from it and
    the inconsistency each produced."""
    from jepsen_tpu.models import is_inconsistent

    paths = []
    for mask, m in list(configs)[:limit]:
        attempts = []
        for j in sorted(pending):
            if j in mask:
                continue
            m2 = m.step(calls[j].op)
            attempts.append({
                "op": calls[j].op.to_dict(),
                "result": (m2.msg if is_inconsistent(m2) else repr(m2)),
                "inconsistent": is_inconsistent(m2),
            })
        paths.append({
            "model": m,
            "pending-linearized": sorted(
                calls[c].op.index for c in mask
                if calls[c].op.index is not None),
            "attempts": attempts,
        })
    return paths


def _render_configs(configs, calls, limit: int = 10):
    """Human-readable configurations, truncated like the reference
    (checker.clj:155-158: writing them all 'can take *hours*')."""
    out = []
    for mask, m in list(configs)[:limit]:
        out.append({"model": m,
                    "pending-linearized": sorted(
                        calls[c].op.index for c in mask
                        if calls[c].op.index is not None)})
    return out
