"""Masked segmented folds on device — the O(n) checker kernels.

The reference's commutative checkers (`set`, `total-queue`, `unique-ids`,
`counter` — `jepsen/src/jepsen/checker.clj:182-233,569-755`) are O(n)
folds over histories.  On TPU these become sort-based set algebra over
packed int64 columns: membership, multiset difference/intersection, and
duplicate detection all reduce to one `sort` plus vectorized compares,
which XLA maps onto the VPU with no host round-trips.

Every kernel here is shape-polymorphic via jit caching and takes plain
int64 arrays (produced by `history.pack()` / the checkers' column
extraction).  Checkers fall back to pure-Python multisets when values
aren't integers; these kernels are the large-history fast path.
"""

from __future__ import annotations

import functools

import numpy as np


@functools.cache
def _kernels():
    import jax
    import jax.numpy as jnp

    def member_counts(xs, ys):
        """For each x in xs: multiplicity of x in ys.  Only the sorted
        VALUES of ys are needed — jnp.sort beats argsort + gather."""
        ys_s = jnp.sort(ys)
        lo = jnp.searchsorted(ys_s, xs, side="left")
        hi = jnp.searchsorted(ys_s, xs, side="right")
        return hi - lo

    def member(xs, ys):
        """Membership x in ys — one binary search + a gather-compare,
        half the cost of the two-sided count (the set checker only needs
        masks, never multiplicities)."""
        n = ys.shape[0]
        if n == 0:
            return jnp.zeros(xs.shape, bool)
        ys_s = jnp.sort(ys)
        lo = jnp.searchsorted(ys_s, xs, side="left")
        return (ys_s[jnp.clip(lo, 0, n - 1)] == xs) & (lo < n)

    @jax.jit
    def set_kernel(attempts, adds, final_read):
        """The `set` checker's algebra (checker.clj:182-233) in one fused
        program.  attempts/adds: values of invoked / ok'd :add ops;
        final_read: elements of the last ok :read.  Returns boolean masks
        over the inputs (host side maps them back to elements)."""
        read_attempted = member(final_read, attempts)
        # ok = final_read ∩ attempts ; unexpected = final_read \ attempts
        ok_mask = read_attempted
        unexpected_mask = ~read_attempted
        # lost = adds \ final_read
        lost_mask = ~member(adds, final_read)
        # recovered = ok \ adds
        recovered_mask = ok_mask & ~member(final_read, adds)
        return ok_mask, unexpected_mask, lost_mask, recovered_mask

    @jax.jit
    def dup_kernel(xs):
        """Duplicate detection: for each x, count>1?  Returns (multiplicity
        per element, duplicate mask)."""
        counts = member_counts(xs, xs)
        return counts, counts > 1

    @jax.jit
    def multiset_minus_mask(xs, ys):
        """Multiset difference xs ∸ ys as a keep-mask over xs: the k-th
        occurrence (in sorted order) of value v in xs survives iff
        k >= count(v in ys)."""
        order = jnp.argsort(xs, stable=True)
        s = xs[order]
        n = s.shape[0]
        idx = jnp.arange(n)
        first = jnp.searchsorted(s, s, side="left")
        occurrence = idx - first  # 0-based occurrence number within its run
        cut = member_counts(s, ys)
        keep_sorted = occurrence >= cut
        keep = jnp.zeros(n, bool).at[order].set(keep_sorted)
        return keep

    @jax.jit
    def counter_bounds(is_inv_add, is_ok_add, values):
        """Prefix lower/upper counter bounds after each event
        (checker.clj:678-755): an attempted decrement / ok'd increment
        moves `lower`; an attempted increment / ok'd decrement moves
        `upper`."""
        v = values
        dl = jnp.where(is_inv_add & (v < 0), v, 0) + \
            jnp.where(is_ok_add & (v > 0), v, 0)
        du = jnp.where(is_inv_add & (v > 0), v, 0) + \
            jnp.where(is_ok_add & (v < 0), v, 0)
        return jnp.cumsum(dl), jnp.cumsum(du)

    return {
        "set": set_kernel,
        "dups": dup_kernel,
        "multiset_minus_mask": multiset_minus_mask,
        "counter_bounds": counter_bounds,
    }


def _i64(xs) -> np.ndarray:
    if isinstance(xs, np.ndarray):
        # no list() round-trip: boxing 1M elements costs more than the
        # kernel itself
        return xs.astype(np.int64, copy=False).reshape(-1)
    return np.asarray(list(xs), np.int64).reshape(-1)


_I32_MIN, _I32_MAX = -2 ** 31, 2 ** 31 - 1


def _narrow(*arrs: np.ndarray):
    """Cast a group of int64 arrays to int32 when every value fits —
    halves host->device transfer and runs the TPU sorts on the native
    32-bit lanes.  The group narrows together so cross-array compares
    (searchsorted) keep one dtype."""
    for a in arrs:
        if len(a) and (a.min() < _I32_MIN or a.max() > _I32_MAX):
            return arrs
    return tuple(a.astype(np.int32) for a in arrs)


def all_ints(xs) -> bool:
    return all(isinstance(x, int) and not isinstance(x, bool) for x in xs)


def _get(out):
    """One pipelined device-to-host fetch for a tuple of outputs — each
    separate np.asarray pays a full round-trip on a tunneled chip."""
    import jax

    return jax.device_get(out)


def set_masks(attempts, adds, final_read):
    """Device-evaluated masks for the set checker; see set_kernel."""
    k = _kernels()["set"]
    return tuple(_get(k(*_narrow(_i64(attempts), _i64(adds),
                                 _i64(final_read)))))


def duplicate_counts(xs):
    k = _kernels()["dups"]
    counts, mask = _get(k(*_narrow(_i64(xs))))
    return counts, mask


def multiset_minus_mask(xs, ys):
    k = _kernels()["multiset_minus_mask"]
    return np.asarray(k(*_narrow(_i64(xs), _i64(ys))))


def counter_bounds(is_inv_add, is_ok_add, values):
    k = _kernels()["counter_bounds"]
    lo, hi = _get(k(np.asarray(is_inv_add, bool),
                    np.asarray(is_ok_add, bool), _i64(values)))
    return lo, hi
