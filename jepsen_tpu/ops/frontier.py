"""Shared frontier-kernel primitives for the WGL device engines.

These encode the exactness-critical parts of the frontier search — the
no-false-merge dedupe argument and the bitmask slot algebra — used by
`ops.wgl` (adaptive single-history kernel), `ops.wgl_batch` (vmapped
multi-key kernel) and `ops.wgl_seg` (segment-parallel bitmap kernel).
One definition each: a subtle soundness bug in a hand-synced copy is
exactly how a checker starts lying, so the copies were consolidated
here (the differential test matrix in tests/test_wgl_*.py holds all
three engines verdict-identical to the CPU oracle).

Two families:

  * row-frontier ops (`make_bit_ops`, `make_dedupe_compact`): a config
    is one row (mask u32[Wd], state i32[S]); dedupe is a full-content
    lexicographic sort — never a hash, so distinct configurations are
    never merged;
  * plane-frontier ops (`make_plane_ops`): the wgl_seg dense bitmap
    layout, frontier bool[2^R x Sn] bit-packed into u32 words along a
    [Wd, 32-lane, ...] axis — slot operations are word shuffles with
    static bit patterns.
"""

from __future__ import annotations

import numpy as np

_SENTINEL = np.uint32(0xFFFFFFFF)


def make_bit_ops(Wd: int):
    """(has_bit, set_bit, clear_bit) over mask rows u32[..., Wd].
    `slot` broadcasts to masks.shape[:-1]."""
    import jax.numpy as jnp

    u32 = jnp.uint32

    def slot_word_bit(slot):
        return slot // 32, (u32(1) << (slot % 32).astype(jnp.uint32))

    def has_bit(masks, slot):
        w, bit = slot_word_bit(slot)
        word = jnp.take_along_axis(
            masks, jnp.broadcast_to(w[..., None], masks.shape[:-1] + (1,)),
            axis=-1)[..., 0]
        return (word & bit) != 0

    def set_bit(masks, slot):
        w, bit = slot_word_bit(slot)
        word_idx = jnp.arange(Wd)
        shape = masks.shape[:-1] + (Wd,)
        return jnp.where(
            jnp.broadcast_to(word_idx, shape) == w[..., None],
            masks | bit[..., None], masks)

    def clear_bit(masks, slot):
        w, bit = slot_word_bit(slot)
        word_idx = jnp.arange(Wd)
        shape = masks.shape[:-1] + (Wd,)
        return jnp.where(
            jnp.broadcast_to(word_idx, shape) == w[..., None],
            masks & ~bit[..., None], masks)

    return has_bit, set_bit, clear_bit


def make_dedupe_compact(Wd: int, S: int):
    """Exact dedupe + compaction of a pool of configs down to out_rows.
    masks u32[P, Wd], states i32[P, S], valid bool[P].  Exactness
    matters: dedupe compares full (mask, state) content — never a hash —
    so distinct configurations are never merged.  Returns
    (masks, states, valid, overflowed, distinct_count)."""
    import jax
    import jax.numpy as jnp

    u32 = jnp.uint32

    def dedupe_compact(masks, states, valid, out_rows: int):
        P = masks.shape[0]
        st_keys = jax.lax.bitcast_convert_type(states, u32) \
            ^ u32(0x80000000)
        sent = ~valid
        keys = [jnp.where(sent, u32(1), u32(0))]
        for wi in range(Wd):
            keys.append(jnp.where(sent, _SENTINEL, masks[:, wi]))
        for si in range(S):
            keys.append(jnp.where(sent, _SENTINEL, st_keys[:, si]))
        # lexsort: last key is primary -> reverse so keys[0] is primary.
        perm = jnp.lexsort(tuple(reversed(keys)))
        s_masks = masks[perm]
        s_states = states[perm]
        s_valid = valid[perm]
        content = [k[perm] for k in keys[1:]]
        eq_prev = jnp.ones(s_valid.shape, bool)
        for col in content:
            eq_prev &= col == jnp.roll(col, 1)
        eq_prev = eq_prev.at[0].set(False)
        keep = s_valid & ~eq_prev
        pos = jnp.cumsum(keep) - 1
        count = pos[-1] + 1
        pos = jnp.where(keep, pos, P + 1)
        out_masks = jnp.zeros((out_rows, Wd), u32).at[pos].set(
            s_masks, mode="drop")
        out_states = jnp.zeros((out_rows, S), jnp.int32).at[pos].set(
            s_states, mode="drop")
        out_valid = jnp.arange(out_rows) < jnp.minimum(count, out_rows)
        return out_masks, out_states, out_valid, count > out_rows, count

    return dedupe_compact


def reshape_shift(x, hi: int, lo: int, set_bit: bool):
    """Move frontier content across one bit of the axis at position -4
    by reshaping it to (hi, 2, lo): set_bit moves the bit-clear half to
    the bit-set half (linearize), else the reverse (prune + retire).
    Shared by the dense kernel (mask axis) and the bit-packed kernel
    (word axis)."""
    import jax.numpy as jnp

    xs = x.reshape(x.shape[:-4] + (hi, 2, lo) + x.shape[-3:])
    if set_bit:
        half = xs[..., :, 0:1, :, :, :, :]
        y = jnp.concatenate([jnp.zeros_like(half), half], axis=-5)
    else:
        half = xs[..., :, 1:2, :, :, :, :]
        y = jnp.concatenate([half, jnp.zeros_like(half)], axis=-5)
    return y.reshape(x.shape)


# Intra-word "lacks bit b" patterns: bit i is set iff mask-index i has
# bit b clear (i & (1<<b) == 0).
_INTRA = (0x55555555, 0x33333333, 0x0F0F0F0F, 0x00FF00FF, 0x0000FFFF)


def make_plane_ops(Wd: int, R: int):
    """The frontier bit algebra shared by the wgl_seg bit-packed
    kernels: slot bits 0-4 live within each uint32 word
    (constant-pattern masks and shifts), slots >= 5 shift whole words
    along the word axis.  Returns (lacking, set_slot, retire_slot,
    sel32) closures over frontier tensors shaped [Wd, Sn, J, K]."""
    import jax.numpy as jnp

    FULL = np.uint32(0xFFFFFFFF)
    Whalf = [(Wd >> (b + 1), 1 << b) for b in range(max(R - 5, 0))]
    word_iota = np.arange(Wd, dtype=np.int32)

    def word_lack(b):
        """uint32 [Wd] mask: FULL where word index lacks bit b-5."""
        return jnp.asarray(
            np.where((word_iota >> (b - 5)) & 1 == 0, FULL, 0),
            jnp.uint32)

    def lacking(x, b):
        """Configs in x whose mask lacks slot b."""
        if b < 5:
            return x & np.uint32(_INTRA[b])
        return x & word_lack(b)[:, None, None, None]

    def set_slot(x, b):
        """Linearize slot b: configs lacking it move to mask|bit."""
        if b < 5:
            return (x & np.uint32(_INTRA[b])) << (1 << b)
        return reshape_shift(x & word_lack(b)[:, None, None, None],
                             *Whalf[b - 5], set_bit=True)

    def retire_slot(x, b):
        """Prune configs lacking slot b, clear the bit on the rest."""
        if b < 5:
            return (x & np.uint32(~np.uint32(_INTRA[b]))) >> (1 << b)
        keep = x & (~word_lack(b))[:, None, None, None]
        return reshape_shift(keep, *Whalf[b - 5], set_bit=False)

    def sel32(cond):
        """bool -> uint32 FULL/0 select mask."""
        return jnp.where(cond, jnp.asarray(FULL),
                         jnp.asarray(np.uint32(0)))

    return lacking, set_slot, retire_slot, sel32
