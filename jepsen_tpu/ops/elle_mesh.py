"""Mesh-sharded, bit-packed Elle closure engine — million-transaction
isolation certificates.

`ops/elle_graph.py` decides the Adya classes with dense bf16 plane
stacks vmapped on ONE device: the O(n^3 log n) closure and a single
device's HBM cap histories at ~1k-10k txns.  This module removes both
caps, with the same masked-closure semantics (differentially pinned):

**Bit-packed uint32 planes.**  A boolean plane row packs 32 columns
per word (`bit b of word w  <->  column w*32 + b`), so a resident
plane costs n^2/8 bytes — 8x below the dense bool stack and 32x below
the bf16 matmul operands the dense path materializes.  Plane unions
(ww|wr|order...) are single bitwise ORs on the packed words.  The
closure matmuls stay 0/1-exact MXU work: each blocked product unpacks
only the (block x block) tiles in flight to bf16, accumulates f32
counts (exact to 2^24 > any path count we admit), thresholds, and
repacks — HBM residency never sees a dense plane.

**Mesh sharding.**  Packed planes shard by ROWS over the device mesh
(`PartitionSpec("rows")` via the same shard_map kwarg-drift shim
`wgl_deep.check_mesh` uses).  One log-squaring round all-gathers the
frontier operands (every device needs all rows of the RIGHT operand;
its own row shard of the LEFT stays local), then runs the blocked
products on the local shard: compute n^3/D per device, wire 3 packed
planes per round.

**Device-side early exit.**  The closure state is monotone, so the
fixpoint is detected exactly: a round that changes nothing anywhere
(psum over the mesh) ends the `while_loop`.  Clean histories with
short dependency diameters settle in ~log2(diameter) rounds instead
of the full log2(n) schedule; `rounds` is reported per history for
telemetry and the bench's early-exit accounting.

One pair-closure carries everything the four class masks need:

    cww       closure of ww|order                 (G0)
    p0        reflexive closure of ww|wr|order    (zero-rw paths;
              off-diagonal it IS c_wwr, and defining edges are never
              diagonal)                            (G1c, G-single)
    p1        >=1-rw paths over ww|wr|order|rw    (G2-item, priority-
              masked by ~p0.T exactly as the dense engine)

    round:  cww <- cww | cww.cww
            p0  <- p0  | p0.p0
            p1  <- p1  | q.p1 | p1.q      (q = p0|p1: 3 products
                                           instead of the naive 4)

Host-side companions (numpy over the same packed layout, no dense
materialization):

  * `find_witness_packed` — level-BFS cycle recovery for device-found
    anomalies (product-graph BFS for G2's >=1-rw constraint);
  * `classify_host_packed` — the sharded-scale differential oracle:
    SCC (iterative Tarjan) decides G0/G1c exactly in O(V+E); rw edges
    probe G-single/G2 per edge (SCC pre-filter, then BFS), with a
    DISCLOSED probe cap and deadline — on exceeding either it returns
    an honest `unknown` degradation row, never a silent pass.

`checker/elle.py` runs this as the `elle-mesh` tier of its
ResilientRunner chain (elle-mesh -> elle-device -> elle-host).
"""

from __future__ import annotations

import math
import os
import time
from typing import Optional, Sequence

import numpy as np

from jepsen_tpu.elle.infer import PLANES

_TILE = 128
_BITS32 = np.arange(32, dtype=np.uint32)

ANOMALY_CLASSES = ("G0", "G1c", "G-single", "G2-item")


# ---------------------------------------------------------------------------
# Packed layout (host side, numpy)
# ---------------------------------------------------------------------------

def mesh_tile(n_dev: int) -> int:
    """Row-count granularity a D-device mesh needs: rows split evenly
    AND every shard offset lands on a word boundary (the transpose
    step slices whole words)."""
    return int(np.lcm(_TILE, 32 * max(1, int(n_dev))))

def pad_for_mesh(n: int, n_dev: int = 1) -> int:
    t = mesh_tile(n_dev)
    return max(t, t * math.ceil(n / t))

def plane_nbytes(n: int, packed: bool = True) -> int:
    """Resident bytes for one n x n boolean plane (the memory math
    docs/elle.md quotes)."""
    return (n * n) // 8 if packed else n * n

def pack_bits(dense) -> np.ndarray:
    """bool [..., n] -> uint32 [..., ceil32(n)] (bit b of word w is
    column w*32+b)."""
    dense = np.asarray(dense, bool)
    n = dense.shape[-1]
    w = math.ceil(n / 32)
    if n % 32:
        pad = np.zeros(dense.shape[:-1] + (w * 32 - n,), bool)
        dense = np.concatenate([dense, pad], axis=-1)
    bits = dense.reshape(dense.shape[:-1] + (w, 32)).astype(np.uint32)
    return (bits << _BITS32).sum(axis=-1, dtype=np.uint32)

def unpack_bits(packed, n: int) -> np.ndarray:
    """uint32 [..., W] -> bool [..., n]."""
    packed = np.asarray(packed, np.uint32)
    bits = (packed[..., None] >> _BITS32) & np.uint32(1)
    return bits.reshape(packed.shape[:-1] + (-1,))[..., :n].astype(bool)

def pack_planes(stack, n_pad: Optional[int] = None,
                n_dev: int = 1) -> np.ndarray:
    """Dense [P, n, n] bool plane stack -> packed uint32
    [P, n_pad, n_pad/32] padded for an n_dev-row mesh."""
    stack = np.asarray(stack, bool)
    p, n, _ = stack.shape
    if n_pad is None:
        n_pad = pad_for_mesh(n, n_dev)
    out = np.zeros((p, n_pad, n_pad // 32), np.uint32)
    if n:
        out[:, :n, :math.ceil(n / 32)] = pack_bits(stack)
    return out

def set_bits(plane: np.ndarray, src, dst) -> None:
    """Sparse edge insertion into one packed plane [n_pad, W]:
    plane[src, dst//32] |= 1 << (dst%32) (the bench's 100k/1M
    generators and elle/infer's plane construction build packed planes
    without a dense detour).  Rides the native ingest layer's batch
    word-OR (packext.or_words, GIL released) when available; the numpy
    fallback is the raveled-index form of np.bitwise_or.at — one flat
    word index per edge instead of a 2-d fancy tuple, measurably
    faster and pinned bit-identical to the per-edge loop by
    tests/test_packext.py."""
    src = np.asarray(src, np.int64)
    dst = np.asarray(dst, np.int64)
    if not len(src):
        return
    W = plane.shape[-1]
    masks = (np.uint32(1) << (dst & 31).astype(np.uint32))
    if plane.flags.c_contiguous:
        words = src * np.int64(W) + (dst >> 5)
        mod = _packext()
        if mod is not None:
            mod.or_words(plane, np.ascontiguousarray(words),
                         np.ascontiguousarray(masks))
            return
        np.bitwise_or.at(plane.reshape(-1), words, masks)
        return
    np.bitwise_or.at(plane, (src, dst >> 5), masks)


def clear_bits(plane: np.ndarray, src, dst) -> None:
    """Sparse edge RETRACTION from one packed plane — the inverse of
    `set_bits`, for the incremental tier's covered-removal deltas
    (elle/infer.IncrementalInference): plane[src, dst//32] &=
    ~(1 << (dst%32)).  Pure numpy (bitwise_and.at over raveled word
    indices, the same flat-index trick set_bits' fallback uses);
    retractions are orders of magnitude rarer than insertions, so the
    native OR path has no AND twin."""
    src = np.asarray(src, np.int64)
    dst = np.asarray(dst, np.int64)
    if not len(src):
        return
    W = plane.shape[-1]
    masks = ~(np.uint32(1) << (dst & 31).astype(np.uint32))
    if plane.flags.c_contiguous:
        words = src * np.int64(W) + (dst >> 5)
        np.bitwise_and.at(plane.reshape(-1), words, masks)
        return
    np.bitwise_and.at(plane, (src, dst >> 5), masks)


def grow_packed(packed: np.ndarray, n_pad: int) -> np.ndarray:
    """Re-pad a packed plane stack [..., rows, W] to a larger n_pad
    (row AND word growth — the packed layout is word-aligned, so the
    old words copy verbatim into the top-left corner)."""
    old_rows, old_w = packed.shape[-2], packed.shape[-1]
    if n_pad < old_rows:
        raise ValueError(f"cannot shrink packed planes "
                         f"{old_rows} -> {n_pad}")
    out = np.zeros(packed.shape[:-2] + (n_pad, n_pad // 32),
                   np.uint32)
    out[..., :old_rows, :old_w] = packed
    return out


def _packext():
    """The native ingest extension, honoring the pack-threads knob
    (JEPSEN_TPU_PACK_THREADS=0 pins the pure-numpy twins)."""
    from jepsen_tpu import native
    from jepsen_tpu.ops import planner
    if planner.pack_threads_effective() <= 0:
        return None
    return native.packext()

def _get_bit(row: np.ndarray, j: int) -> bool:
    return bool((row[j // 32] >> np.uint32(j % 32)) & np.uint32(1))

def _row_indices(row: np.ndarray, n: int) -> np.ndarray:
    """Set bit positions (< n) of one packed row [W]."""
    nz = np.nonzero(row)[0]
    if not len(nz):
        return np.empty(0, np.int64)
    bits = (row[nz, None] >> _BITS32) & np.uint32(1)
    words, pos = np.nonzero(bits)
    idx = nz[words] * 32 + pos
    return idx[idx < n]


# ---------------------------------------------------------------------------
# Device kernel
# ---------------------------------------------------------------------------

_PLAN_CACHE: dict = {}
_PLAN_STATS = {"hits": 0, "misses": 0}

def plan_cache_stats() -> dict:
    return dict(_PLAN_STATS)

def clear_plan_cache() -> None:
    _PLAN_CACHE.clear()
    _PLAN_STATS.update(hits=0, misses=0)

def _block_for(n_pad: int) -> int:
    """Largest tile (bits) that divides n_pad — bounds the dense
    in-flight unpacked tiles.  JEPSEN_TPU_ELLE_BLOCK caps it."""
    cap = int(os.environ.get("JEPSEN_TPU_ELLE_BLOCK", 2048))
    for b in (2048, 1024, 512, 256, 128):
        if b <= cap and n_pad % b == 0:
            return b
    return _TILE

def _device_fns(n_pad: int, block: int):
    """(unpack, pack, pmm) closures for one (n_pad, block) shape."""
    import jax
    import jax.numpy as jnp

    wb = block // 32
    w = n_pad // 32
    nk = n_pad // block
    shifts = jnp.arange(32, dtype=jnp.uint32)

    def unpack(words):
        # uint32 [r, v] -> bf16 [r, v*32]
        r, v = words.shape
        bits = (words[:, :, None] >> shifts) & jnp.uint32(1)
        return bits.reshape(r, v * 32).astype(jnp.bfloat16)

    def pack(bits):
        # bool/0-1 [r, c] (c % 32 == 0) -> uint32 [r, c//32]
        r, c = bits.shape
        b = bits.reshape(r, c // 32, 32).astype(jnp.uint32)
        return jnp.sum(b << shifts, axis=-1, dtype=jnp.uint32)

    def pmm(a, b):
        """Packed boolean product: a [r, W] (columns packed) @
        b [n_pad, W] (columns packed) -> [r, W].  Blocked so only
        (r x block) + (block x block) dense bf16 tiles exist at once;
        f32 accumulation keeps the 0/1 product exact."""
        r = a.shape[0]

        def jbody(j, out):
            def kbody(k, acc):
                at = unpack(jax.lax.dynamic_slice(
                    a, (0, k * wb), (r, wb)))
                bt = unpack(jax.lax.dynamic_slice(
                    b, (k * block, j * wb), (block, wb)))
                return acc + jnp.dot(
                    at, bt, preferred_element_type=jnp.float32)
            acc = jax.lax.fori_loop(
                0, nk, kbody, jnp.zeros((r, block), jnp.float32))
            return jax.lax.dynamic_update_slice(
                out, pack(acc > 0.5), (0, j * wb))

        return jax.lax.fori_loop(
            0, nk, jbody, jnp.zeros((r, w), jnp.uint32))

    return unpack, pack, pmm

def _build_kernel(n_pad: int, devs: tuple, block: int,
                  warm: bool = False):
    """One compiled shard_map program: packed pair closure with early
    exit + class masks + per-device defining-edge picks.

    With `warm` (the incremental tier, ISSUE 18) the program takes the
    previous closure triple (cww, p0, p1) as three extra row-sharded
    operands seeding the while_loop, and returns the settled triple
    alongside the verdict.  The state is monotone, so the same
    early-exit psum that proves cold convergence proves warm
    convergence — a delta that extends the frontier by a short path
    settles in ~log2(delta diameter) rounds, not log2(n)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec

    from jepsen_tpu.ops.shard_map_compat import (all_gather_frontier,
                                                 frontier_settled,
                                                 shard_map_compat)

    n_dev = len(devs)
    m = n_pad // n_dev
    w = n_pad // 32
    wm = m // 32
    steps = max(1, math.ceil(math.log2(max(n_pad - 1, 2))))
    unpack, pack, pmm = _device_fns(n_pad, block)
    shifts = jnp.arange(32, dtype=jnp.uint32)
    nk = n_pad // block
    wb = block // 32

    def tpose(full, a0):
        """Packed transpose restricted to this shard's rows:
        out[a, bit b] = full[b, a0 + a]."""
        def bbody(k, out):
            blk = jax.lax.dynamic_slice(
                full, (k * block, a0 // 32), (block, wm))
            bits = ((blk[:, :, None] >> shifts) & jnp.uint32(1)
                    ).reshape(block, m)
            return jax.lax.dynamic_update_slice(
                out, pack(bits.T), (0, k * wb))
        return jax.lax.fori_loop(
            0, nk, bbody, jnp.zeros((m, w), jnp.uint32))

    def pick(mask, a0):
        """(found, a, b) — lowest (a, b) row-major, matching the dense
        engine's argmax pick so cross-engine edges compare equal."""
        row_any = (mask != 0).any(axis=1)
        found = row_any.any()
        al = jnp.argmax(row_any)
        rowm = mask[al]
        wi = jnp.argmax(rowm != 0)
        word = rowm[wi]
        bit = jnp.argmax(((word >> shifts) & jnp.uint32(1)) > 0)
        return (found, (a0 + al).astype(jnp.int32),
                (wi * 32 + bit).astype(jnp.int32))

    def body(ww, wr, rw, od, *seed):
        idx = jax.lax.axis_index("rows")
        a0 = idx * m
        rows_idx = a0 + jnp.arange(m)
        eye = jnp.zeros((m, w), jnp.uint32).at[
            jnp.arange(m), rows_idx // 32].set(
            jnp.uint32(1) << (rows_idx % 32).astype(jnp.uint32))
        base = ww | wr | od

        def gather(x):
            return all_gather_frontier(x, "rows")

        def cond(st):
            _, _, _, rounds, done = st
            return (~done) & (rounds < steps)

        def round_(st):
            cww, p0, p1, rounds, _ = st
            cww_f, p0_f, p1_f = gather(cww), gather(p0), gather(p1)
            q, q_f = p0 | p1, p0_f | p1_f
            cww2 = cww | pmm(cww, cww_f)
            p0n = p0 | pmm(p0, p0_f)
            p1n = p1 | pmm(q, p1_f) | pmm(p1, q_f)
            ch = (jnp.any(cww2 != cww) | jnp.any(p0n != p0)
                  | jnp.any(p1n != p1))
            done = frontier_settled(ch, "rows")
            return cww2, p0n, p1n, rounds + 1, done

        init = (ww | od, base | eye, rw)
        if warm:
            # OR the previous closure under the fresh direct planes:
            # the union's closure equals the exact closure as long as
            # every retraction since the last cold rebuild was covered
            # (elle/infer.IncrementalInference's rebuild contract)
            init = (init[0] | seed[0], init[1] | seed[1],
                    init[2] | seed[2])
        cww, p0, p1, rounds, _ = jax.lax.while_loop(
            cond, round_, init + (jnp.int32(0), jnp.bool_(False)))

        t_cww = tpose(gather(cww), a0)
        t_p0 = tpose(gather(p0), a0)
        t_p1 = tpose(gather(p1), a0)
        masks = (ww & t_cww,               # G0
                 wr & t_p0,               # G1c   (planes have no
                 rw & t_p0,               # G-single  diagonal, so
                 rw & t_p1 & ~t_p0)       # G2-item   p0's eye is inert)
        flags, edges = [], []
        for mk in masks:
            f, a, b = pick(mk, a0)
            flags.append(f)
            edges.append(jnp.stack([a, b]))
        out = (jnp.stack(flags)[None], jnp.stack(edges)[None],
               rounds.reshape(1))
        if warm:
            out += (cww, p0, p1)
        return out

    mesh = Mesh(np.array(list(devs)), ("rows",))
    spec = PartitionSpec("rows")
    fn = shard_map_compat(
        body, mesh=mesh, in_specs=(spec,) * (7 if warm else 4),
        out_specs=(spec, spec, spec) + ((spec,) * 3 if warm else ()))
    return jax.jit(fn), mesh

def _kernel(n_pad: int, devs: tuple, warm: bool = False):
    """Compiled-plan cache over (n_pad, devices, block, warm) shape
    buckets, hit/miss counted (the mesh-path analogue of the dense
    engine's kernel-bucket counters)."""
    block = _block_for(n_pad)
    key = (n_pad, devs, block, "warm") if warm \
        else (n_pad, devs, block)
    hit = key in _PLAN_CACHE
    if hit:
        _PLAN_STATS["hits"] += 1
    else:
        _PLAN_CACHE[key] = _build_kernel(n_pad, devs, block,
                                         warm=warm)
        _PLAN_STATS["misses"] += 1
    try:
        from jepsen_tpu import telemetry
        telemetry.REGISTRY.counter(
            "jepsen_elle_mesh_plan_total",
            result="hit" if hit else "miss").inc()
    except Exception:           # noqa: BLE001 - telemetry is advisory
        pass
    return _PLAN_CACHE[key]


# ---------------------------------------------------------------------------
# Entry points
# ---------------------------------------------------------------------------

def _devices(devices=None, max_devices: Optional[int] = None) -> list:
    import jax
    devs = list(devices) if devices is not None else list(jax.devices())
    if max_devices:
        devs = devs[:max_devices]
    if not devs:
        from jepsen_tpu.errors import BackendUnavailable
        raise BackendUnavailable("no jax devices for the elle mesh",
                                 backend="none")
    return devs

def classify_packed(packed_stacks: Sequence[np.ndarray],
                    ns: Sequence[int],
                    include_order: bool = True,
                    devices=None,
                    max_devices: Optional[int] = None) -> list:
    """Classify histories whose planes are ALREADY bit-packed
    ([len(PLANES), n_pad, n_pad/32] uint32 each, `pack_planes` /
    `set_bits` layout, n_pad a multiple of `mesh_tile(D)`).

    Each history runs as one sharded device program over the row axis
    of the mesh (histories at mesh scale are individually huge; the
    batch axis is a host loop).  Returns one row per history:
    {"anomalies": {cls: (a, b)}, "n", "n_pad", "rounds", "shards"}.
    """
    import jax

    devs = _devices(devices, max_devices)
    out = []
    for packed, n in zip(packed_stacks, ns):
        packed = np.asarray(packed, np.uint32)
        n_pad = packed.shape[-2]
        n_dev = len(devs)
        if n_pad % mesh_tile(n_dev):
            raise ValueError(
                f"n_pad={n_pad} not a multiple of mesh_tile({n_dev})="
                f"{mesh_tile(n_dev)}; pad with pad_for_mesh")
        fn, mesh = _kernel(n_pad, tuple(devs))
        from jax.sharding import NamedSharding, PartitionSpec
        sh = NamedSharding(mesh, PartitionSpec("rows"))
        ww, wr, rw = (jax.device_put(packed[i], sh) for i in range(3))
        if include_order:
            od = jax.device_put(packed[3] | packed[4], sh)
        else:
            od = jax.device_put(np.zeros_like(packed[0]), sh)
        flags, edges, rounds = (np.asarray(x)
                                for x in fn(ww, wr, rw, od))
        found: dict = {}
        for c, cls in enumerate(ANOMALY_CLASSES):
            hits = np.nonzero(flags[:, c])[0]
            if len(hits):
                d = int(hits[0])    # lowest device = lowest row block
                found[cls] = (int(edges[d, c, 0]), int(edges[d, c, 1]))
        out.append({"anomalies": found, "n": int(n), "n_pad": n_pad,
                    "rounds": int(rounds[0]), "shards": n_dev})
    return out

CLOSURE_PLANES = 3                     # (cww, p0, p1)


def empty_closure(n_pad: int) -> np.ndarray:
    """A cold closure seed: the warm entry points treat all-zeros as
    'start from the direct planes alone'."""
    return np.zeros((CLOSURE_PLANES, n_pad, n_pad // 32), np.uint32)


def classify_packed_warm(packed_stack: np.ndarray, n: int,
                         closure: Optional[np.ndarray] = None,
                         include_order: bool = True,
                         devices=None,
                         max_devices: Optional[int] = None) -> tuple:
    """Incremental classify on the device mesh: one history's packed
    planes plus the PREVIOUS settled closure triple ([3, n_pad, W]
    uint32, or None for a cold start).  The while_loop seeds from the
    old closure OR'd under the current direct planes, so the delta's
    frontier-product rounds are all that run (monotone state — the
    early-exit psum proves convergence exactly as in the cold path).
    Returns (row, closure) where `row` matches `classify_packed` rows
    and `closure` is the settled triple to seed the next window."""
    import jax

    devs = _devices(devices, max_devices)
    packed = np.asarray(packed_stack, np.uint32)
    n_pad = packed.shape[-2]
    n_dev = len(devs)
    if n_pad % mesh_tile(n_dev):
        raise ValueError(
            f"n_pad={n_pad} not a multiple of mesh_tile({n_dev})="
            f"{mesh_tile(n_dev)}; pad with pad_for_mesh")
    if closure is None:
        closure = empty_closure(n_pad)
    closure = np.asarray(closure, np.uint32)
    if closure.shape[-2] != n_pad:
        closure = grow_packed(closure, n_pad)
    fn, mesh = _kernel(n_pad, tuple(devs), warm=True)
    from jax.sharding import NamedSharding, PartitionSpec
    sh = NamedSharding(mesh, PartitionSpec("rows"))
    ww, wr, rw = (jax.device_put(packed[i], sh) for i in range(3))
    if include_order:
        od = jax.device_put(packed[3] | packed[4], sh)
    else:
        od = jax.device_put(np.zeros_like(packed[0]), sh)
    c0, q0, r0 = (jax.device_put(closure[i], sh) for i in range(3))
    flags, edges, rounds, cww, p0, p1 = fn(ww, wr, rw, od, c0, q0, r0)
    flags, edges, rounds = (np.asarray(x)
                            for x in (flags, edges, rounds))
    found: dict = {}
    for c, cls in enumerate(ANOMALY_CLASSES):
        hits = np.nonzero(flags[:, c])[0]
        if len(hits):
            d = int(hits[0])
            found[cls] = (int(edges[d, c, 0]), int(edges[d, c, 1]))
    row = {"anomalies": found, "n": int(n), "n_pad": n_pad,
           "rounds": int(rounds[0]), "shards": n_dev}
    out_closure = np.stack([np.asarray(cww), np.asarray(p0),
                            np.asarray(p1)]).astype(np.uint32)
    return row, out_closure


def classify_host_warm(packed_stack: np.ndarray, n: int,
                       closure: Optional[np.ndarray] = None,
                       include_order: bool = True) -> tuple:
    """Numpy twin of `classify_packed_warm` — same update rule, same
    early exit, same masks, same lowest-row-major defining-edge pick,
    so verdicts and closures interchange with the device path
    bit-for-bit (the live txn tenants' default engine; dense float32
    matmuls are exact 0/1 counts below 2^24)."""
    packed = np.asarray(packed_stack, np.uint32)
    n_pad = packed.shape[-2]
    if n_pad == 0:
        return ({"anomalies": {}, "n": 0, "n_pad": 0, "rounds": 0,
                 "shards": 0}, empty_closure(0))
    dense = [unpack_bits(packed[i], n_pad) for i in range(len(PLANES))]
    ww, wr, rw = dense[:3]
    od = (dense[3] | dense[4]) if include_order \
        else np.zeros_like(ww)
    base = ww | wr | od
    eye = np.eye(n_pad, dtype=bool)
    cww = ww | od
    p0 = base | eye
    p1 = rw.copy()
    if closure is not None and closure.shape[-2]:
        closure = np.asarray(closure, np.uint32)
        if closure.shape[-2] != n_pad:
            closure = grow_packed(closure, n_pad)
        cww |= unpack_bits(closure[0], n_pad)
        p0 |= unpack_bits(closure[1], n_pad)
        p1 |= unpack_bits(closure[2], n_pad)

    def bmm(a, b):
        return (a.astype(np.float32) @ b.astype(np.float32)) > 0.5

    steps = max(1, math.ceil(math.log2(max(n_pad - 1, 2))))
    rounds = 0
    done = False
    while not done and rounds < steps:
        q = p0 | p1
        cww2 = cww | bmm(cww, cww)
        p0n = p0 | bmm(p0, p0)
        p1n = p1 | bmm(q, p1) | bmm(p1, q)
        done = (np.array_equal(cww2, cww) and np.array_equal(p0n, p0)
                and np.array_equal(p1n, p1))
        cww, p0, p1 = cww2, p0n, p1n
        rounds += 1
    masks = (ww & cww.T, wr & p0.T, rw & p0.T, rw & p1.T & ~p0.T)
    found: dict = {}
    for cls, mk in zip(ANOMALY_CLASSES, masks):
        if mk.any():
            a, b = np.unravel_index(int(np.argmax(mk)), mk.shape)
            found[cls] = (int(a), int(b))
    row = {"anomalies": found, "n": int(n), "n_pad": n_pad,
           "rounds": rounds, "shards": 0}
    out_closure = np.stack([pack_bits(cww), pack_bits(p0),
                            pack_bits(p1)]).astype(np.uint32)
    return row, out_closure


def classify_mesh(stacks: Sequence[np.ndarray],
                  include_order: bool = True,
                  devices=None,
                  max_devices: Optional[int] = None,
                  inferences=None) -> list:
    """Dense-stack front door (the checker's path): packs each
    [len(PLANES), n, n] bool stack and classifies on the row-sharded
    mesh.  Output rows match `elle_graph.classify_batch` plus
    `rounds`/`shards`.

    With `inferences` (the elle/infer.Inference objects the stacks
    came from), the packed planes are built by sparse word-insertion
    from the inference edge lists (Inference.packed_stacked — the
    native ingest layer's or_words fast path) instead of re-packing
    the dense stacks; equal bytes either way, pinned by
    tests/test_packext.py."""
    devs = _devices(devices, max_devices)
    if inferences is not None:
        packed = [inf.packed_stacked(n_dev=len(devs))
                  for inf in inferences]
    else:
        packed = [pack_planes(s, n_dev=len(devs)) for s in stacks]
    return classify_packed(packed, [s.shape[-1] for s in stacks],
                           include_order=include_order, devices=devs)

def packed_product(a_dense, b_dense) -> np.ndarray:
    """Test pin: the device packed boolean product of two dense bool
    matrices, returned dense (must equal `(a @ b) > 0`)."""
    import jax

    a = np.asarray(a_dense, bool)
    n = a.shape[0]
    n_pad = pad_for_mesh(n, 1)
    ap = pack_planes(a[None])[0]
    bp = pack_planes(np.asarray(b_dense, bool)[None])[0]
    _, _, pmm = _device_fns(n_pad, _block_for(n_pad))
    out = np.asarray(jax.jit(pmm)(ap, bp))
    return unpack_bits(out, n)[:n]


# ---------------------------------------------------------------------------
# Witness recovery over packed planes — level-BFS, no dense planes
# ---------------------------------------------------------------------------

def _frontier_nodes(frontier: np.ndarray, n: int) -> np.ndarray:
    return _row_indices(frontier, n)

def _succ_or(adj: np.ndarray, nodes: np.ndarray) -> np.ndarray:
    if not len(nodes):
        return np.zeros(adj.shape[1], np.uint32)
    return np.bitwise_or.reduce(adj[nodes], axis=0)

def _bfs_path_packed(adj: np.ndarray, src: int, dst: int,
                     n: int) -> Optional[list]:
    """Shortest path src -> dst (length >= 1) over one packed
    adjacency [n_pad, W], or None.  Frontiers are packed bitsets; the
    expansion is one OR-reduction over the frontier's rows."""
    w = adj.shape[1]
    visited = np.zeros(w, np.uint32)
    frontier = np.zeros(w, np.uint32)
    frontier[src // 32] = np.uint32(1) << np.uint32(src % 32)
    visited |= frontier
    levels = []
    while frontier.any():
        nodes = _frontier_nodes(frontier, n)
        levels.append(nodes)
        nxt = _succ_or(adj, nodes)
        if _get_bit(nxt, dst):
            path = [dst]
            cur = dst
            for lv in reversed(levels):
                pred = lv[((adj[lv, cur // 32]
                            >> np.uint32(cur % 32)) & 1).astype(bool)]
                cur = int(pred[0])
                path.append(cur)
            path.reverse()
            return path
        nxt &= ~visited
        visited |= nxt
        frontier = nxt
    return None

def _bfs_path_with_rw_packed(base: np.ndarray, rw: np.ndarray,
                             src: int, dst: int,
                             n: int) -> Optional[list]:
    """Path src -> dst over base|rw using >= 1 rw edge: level-BFS over
    the (node, seen-rw) product graph with packed frontiers."""
    full = base | rw
    w = base.shape[1]
    f0 = np.zeros(w, np.uint32)
    f0[src // 32] = np.uint32(1) << np.uint32(src % 32)
    f1 = np.zeros(w, np.uint32)
    v0, v1 = f0.copy(), np.zeros(w, np.uint32)
    levels = []                      # (nodes0, nodes1) per level
    while f0.any() or f1.any():
        n0 = _frontier_nodes(f0, n)
        n1 = _frontier_nodes(f1, n)
        levels.append((n0, n1))
        nxt1 = _succ_or(full, n1) | _succ_or(rw, n0)
        if _get_bit(nxt1, dst):
            # walk back through the product graph
            path, cur, seen = [dst], dst, True
            for lv0, lv1 in reversed(levels):
                if seen:
                    p1 = lv1[((full[lv1, cur // 32]
                               >> np.uint32(cur % 32)) & 1
                              ).astype(bool)] if len(lv1) else lv1
                    if len(p1):
                        cur = int(p1[0])            # stay in seen-rw
                    else:
                        p0 = lv0[((rw[lv0, cur // 32]
                                   >> np.uint32(cur % 32)) & 1
                                  ).astype(bool)]
                        cur, seen = int(p0[0]), False
                else:
                    p0 = lv0[((base[lv0, cur // 32]
                               >> np.uint32(cur % 32)) & 1
                              ).astype(bool)]
                    cur = int(p0[0])
                path.append(cur)
            path.reverse()
            return path
        nxt0 = _succ_or(base, n0)
        nxt0 &= ~v0
        nxt1 &= ~v1
        v0 |= nxt0
        v1 |= nxt1
        f0, f1 = nxt0, nxt1
    return None

def find_witness_packed(packed_stack: np.ndarray, cls: str, edge,
                        n: int, include_order: bool = True
                        ) -> Optional[list]:
    """One explicit cycle [a, b, ..., a] for a mesh-found anomaly —
    the packed-layout twin of `elle_graph.find_witness`."""
    ww, wr, rw, po, rt = (np.asarray(packed_stack[i], np.uint32)
                          for i in range(len(PLANES)))
    order = (po | rt) if include_order else np.zeros_like(ww)
    a, b = int(edge[0]), int(edge[1])
    if cls == "G0":
        back = _bfs_path_packed(ww | order, b, a, n)
    elif cls in ("G1c", "G-single"):
        back = _bfs_path_packed(ww | wr | order, b, a, n)
    elif cls == "G2-item":
        back = _bfs_path_with_rw_packed(ww | wr | order, rw, b, a, n)
    else:
        raise ValueError(f"unknown anomaly class {cls!r}")
    if back is None:
        return None
    return [a] + back


# ---------------------------------------------------------------------------
# Sparse host oracle — SCC + bounded per-edge probes, honest caps
# ---------------------------------------------------------------------------

def _sccs(adj: np.ndarray, n: int) -> np.ndarray:
    """Strongly-connected components of one packed adjacency (Tarjan,
    iterative).  Returns comp id per node; comp ids are arbitrary."""
    UNSET = -1
    index = np.full(n, UNSET, np.int64)
    low = np.zeros(n, np.int64)
    comp = np.full(n, UNSET, np.int64)
    on_stack = np.zeros(n, bool)
    succ_cache: dict = {}

    def succ(u):
        s = succ_cache.get(u)
        if s is None:
            s = _row_indices(adj[u], n)
            succ_cache[u] = s
        return s

    counter = 0
    n_comp = 0
    tstack: list = []
    for root in range(n):
        if index[root] != UNSET:
            continue
        work = [(root, 0)]
        while work:
            u, pi = work[-1]
            if pi == 0:
                index[u] = low[u] = counter
                counter += 1
                tstack.append(u)
                on_stack[u] = True
            advanced = False
            su = succ(u)
            while pi < len(su):
                v = int(su[pi])
                pi += 1
                if index[v] == UNSET:
                    work[-1] = (u, pi)
                    work.append((v, 0))
                    advanced = True
                    break
                if on_stack[v]:
                    low[u] = min(low[u], index[v])
            if advanced:
                continue
            work.pop()
            if low[u] == index[u]:
                while True:
                    v = tstack.pop()
                    on_stack[v] = False
                    comp[v] = n_comp
                    if v == u:
                        break
                n_comp += 1
            if work:
                pu = work[-1][0]
                low[pu] = min(low[pu], low[u])
    return comp

def _edges_of(plane: np.ndarray, n: int):
    for u in range(n):
        for v in _row_indices(plane[u], n):
            yield u, int(v)

def classify_host_packed(packed_stack: np.ndarray, n: int,
                         include_order: bool = True,
                         deadline_s: Optional[float] = None,
                         max_rw_probe: int = 4096) -> dict:
    """Sparse host oracle over packed planes: exact G0/G1c via SCC in
    O(V+E); G-single/G2 via bounded per-rw-edge probes (SCC
    pre-filter, then packed BFS).  Never lies about its bounds: a
    blown `deadline_s` or rw probe cap yields an `unknown` degradation
    row with the cap disclosed (no-silent-caps)."""
    t0 = time.monotonic()

    def over_deadline() -> bool:
        return (deadline_s is not None
                and time.monotonic() - t0 > deadline_s)

    def degrade(reason: str, **extra) -> dict:
        row = {"anomalies": {}, "n": n,
               "n_pad": int(packed_stack.shape[-2]),
               "unknown": True, "degraded": reason,
               "elapsed_s": round(time.monotonic() - t0, 3)}
        if deadline_s is not None:
            row["deadline_s"] = deadline_s
        row.update(extra)
        return row

    ww, wr, rw, po, rt = (np.asarray(packed_stack[i], np.uint32)
                          for i in range(len(PLANES)))
    order = (po | rt) if include_order else np.zeros_like(ww)
    base = ww | wr | order
    found: dict = {}
    if n == 0:
        return {"anomalies": {}, "n": 0, "n_pad": 0}

    comp_ww = _sccs(ww | order, n)
    if over_deadline():
        return degrade("host-deadline", stage="scc-ww")
    for u, v in _edges_of(ww, n):
        if comp_ww[u] == comp_ww[v]:
            found["G0"] = (u, v)
            break
    comp = _sccs(base, n)
    if over_deadline():
        return degrade("host-deadline", stage="scc-base")
    for u, v in _edges_of(wr, n):
        if comp[u] == comp[v]:
            found["G1c"] = (u, v)
            break

    # rw probes: a zero-rw return (base path b=>a) is G-single; only a
    # >=1-rw return WITHOUT a zero-rw one defines G2 (the dense
    # engine's priority mask).  Same-SCC is a free G-single certificate
    # (edge a->b is in neither graph, so reachability may hold across
    # comps too — those pay a BFS each, hence the disclosed cap).
    probed = 0
    capped = False
    want = {"G-single", "G2-item"} - set(found)
    for a, b in _edges_of(rw, n):
        if not want:
            break
        if over_deadline():
            return degrade("host-deadline", stage="rw-probe",
                           rw_probed=probed, partial=dict(
                               (k, list(v)) for k, v in found.items()))
        if probed >= max_rw_probe:
            capped = True
            break
        probed += 1
        if "G-single" in want and comp[a] == comp[b]:
            found["G-single"] = (a, b)
            want.discard("G-single")
            continue
        zero_rw = (comp[a] == comp[b]
                   or _bfs_path_packed(base, b, a, n) is not None)
        if zero_rw:
            if "G-single" in want:
                found["G-single"] = (a, b)
                want.discard("G-single")
            continue
        if ("G2-item" in want and _bfs_path_with_rw_packed(
                base, rw, b, a, n) is not None):
            found["G2-item"] = (a, b)
            want.discard("G2-item")

    if capped and want:
        # classes still open when the cap hit: the verdict would be a
        # silent pass — degrade honestly instead
        return degrade("rw-probe-cap", rw_probed=probed,
                       max_rw_probe=max_rw_probe,
                       partial={k: list(v) for k, v in found.items()})
    return {"anomalies": found, "n": n,
            "n_pad": int(packed_stack.shape[-2]), "rw_probed": probed}
