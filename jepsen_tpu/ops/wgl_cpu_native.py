"""Native (C) CPU linearizability oracle — the second baseline.

Same Lowe-style just-in-time linearization as ops.wgl_cpu (the
knossos-equivalent reference implementation), with the hot loop in C
over the integer uop tables the device kernels use.  bench.py reports
device speedups against BOTH oracles so the ratios carry no hidden
interpreter constant (the reference runs knossos on a 32 GB JVM,
jepsen/project.clj:30; this native oracle bounds any
"Python-was-just-slow" objection from below).

Scope: models with a DeviceSpec and no custom encode_op, histories
with <= 64 simultaneously pending (open + crashed) calls and <= 2^31
enumerated states.  Everything else falls back to the Python oracle —
check() is verdict-identical to wgl_cpu.check on the shared domain
(differential tests enforce it).
"""

from __future__ import annotations

from typing import Any, Optional

import numpy as np

from jepsen_tpu.ops.prep import PreparedHistory, prepare

_I32 = 2 ** 31


def check(model, history, *,
          max_configs: int = 1_000_000,
          time_limit: Optional[float] = None,
          cancel=None) -> dict[str, Any]:
    """Drop-in for wgl_cpu.check; falls back to it outside the native
    scope (no spec / custom encoding / deep pending / cancel racing —
    the C loop cannot observe a threading.Event mid-walk)."""
    from jepsen_tpu import native
    from jepsen_tpu.ops import wgl_cpu

    mod = native.wgloracle()
    spec = model.device_spec()
    if (mod is None or cancel is not None or spec is None
            or getattr(spec, "encode_op", None) is not None):
        return wgl_cpu.check(model, history, max_configs=max_configs,
                             time_limit=time_limit, cancel=cancel)
    from jepsen_tpu.ops.wgl import _generic_encode_op
    from jepsen_tpu.ops.wgl_seg import Unsupported, _enumerate_states

    seen: dict = {}
    rows: list = []
    calls = None
    prep = None
    ev_kind = ev_cid = call_uop_b = None
    n_calls = 0
    n_events = 0
    # Fast ingest: event streams built in C straight from the
    # history's columns (the same courtesy the device path gets from
    # the journal) — the Python prepare() walk only runs when no
    # columns exist or the columnar ingest is out of scope.
    packed = (history.packed_columns()
              if hasattr(history, "packed_columns") else None)
    if packed is not None and getattr(packed, "vkind", None) is not None \
            and hasattr(mod, "prep_cols"):
        fmap = _spec_fmap(packed, spec)
        out = mod.prep_cols(
            np.ascontiguousarray(packed.process, np.int32),
            np.ascontiguousarray(packed.type, np.uint8),
            np.ascontiguousarray(fmap),
            np.ascontiguousarray(packed.value[:, 0].astype(np.int32)),
            np.ascontiguousarray(packed.value[:, 1].astype(np.int32)),
            np.ascontiguousarray(packed.vkind, np.uint8),
            seen, rows)
        if out is not None:
            n_calls, ev_kind, ev_cid, call_uop_b, _ = out
            n_events = len(ev_kind)
    if ev_kind is None:
        prep = history if isinstance(history, PreparedHistory) \
            else prepare(history)
        calls = prep.calls
        if not calls:
            return {"valid?": True, "op_count": 0, "configs": []}
        call_uop = np.empty(len(calls), np.int32)
        for c in calls:
            fc, av, bv, okv = _generic_encode_op(c.op, spec.f_codes)
            if fc < 0 or not (-_I32 <= av < _I32
                              and -_I32 <= bv < _I32):
                return wgl_cpu.check(model, history,
                                     max_configs=max_configs,
                                     time_limit=time_limit)
            key = (fc, av, bv, okv)
            u = seen.get(key)
            if u is None:
                u = seen[key] = len(rows)
                rows.append(key)
            call_uop[c.id] = u
        ev_kind = np.asarray([k for _, k, _ in prep.events],
                             np.uint8).tobytes()
        ev_cid = np.asarray([c for _, _, c in prep.events],
                            np.int32).tobytes()
        call_uop_b = call_uop.tobytes()
        n_calls = len(calls)
        n_events = len(prep.events)
    if n_calls == 0:
        return {"valid?": True, "op_count": 0, "configs": []}
    uops = np.asarray(rows, np.int32).reshape(len(rows), 4)
    init = np.asarray(spec.encode(model), np.int32)
    try:
        states, legal, next_state = _enumerate_states(
            spec, init, uops, 4096)
    except Unsupported:
        from jepsen_tpu import telemetry
        telemetry.count_fallback("wgl_cpu_native", "state-space")
        return wgl_cpu.check(model, history, max_configs=max_configs,
                             time_limit=time_limit)
    Sn = states.shape[0]

    code, events_done, fail_event, fail_cid, n_seen, surv, pend = \
        mod.run(ev_kind, ev_cid, call_uop_b,
                np.ascontiguousarray(legal, np.uint8).tobytes(),
                np.ascontiguousarray(next_state, np.uint32).tobytes(),
                int(Sn), 0,
                int(max_configs),
                float(time_limit * 1000) if time_limit else 0.0)

    if code == 4:                    # > 64 pending: Python fallback
        return wgl_cpu.check(model, history, max_configs=max_configs,
                             time_limit=time_limit)
    if code == 3:
        return {"valid?": "unknown", "cause": "timeout",
                "op_count": n_calls, "events_done": events_done,
                "events_total": n_events}
    if code == 2:
        return {"valid?": "unknown", "cause": "config-explosion",
                "op_count": n_calls, "configs": n_seen,
                "events_done": events_done,
                "events_total": n_events}

    if code == 0 and calls is None:
        # call records only needed for rendering (witness op, config
        # decode) — built lazily on the rare invalid verdict; call ids
        # align with the columnar ingest (both number ok+crashed
        # invokes densely in stream order, fail pairs dropped).
        prep = prepare(history)
        calls = prep.calls

    def decode_configs():
        out = []
        sv = np.frombuffer(surv or b"", np.uint64).reshape(-1, 2)
        pc = np.frombuffer(pend, np.int32)
        for mask, st in sv[:10]:
            lin = []
            for b in range(64):
                if (int(mask) >> b) & 1 and pc[b] >= 0:
                    if calls is not None:
                        idx = calls[pc[b]].op.index
                        if idx is not None:
                            lin.append(idx)
                    else:            # valid fast path: raw call ids
                        lin.append(int(pc[b]))
            m = (spec.decode(states[int(st)])
                 if getattr(spec, "decode", None) else
                 {"state": states[int(st)].tolist()})
            out.append({"model": m, "pending-linearized": sorted(lin)})
        return out

    if code == 0:
        call = calls[fail_cid]
        return {"valid?": False,
                "op": call.op.to_dict(),
                "op_index": call.op.index,
                "op_count": n_calls,
                "anomaly": "nonlinearizable",
                "configs": decode_configs(),
                "engine": "wgl_cpu_native"}
    return {"valid?": True, "op_count": n_calls,
            "configs": decode_configs(),
            "engine": "wgl_cpu_native"}


def _spec_fmap(packed, spec):
    """Per-op spec f-codes from the packed history's f-id column."""
    nf = len(packed.f_codes)
    fcol = packed.f
    if nf == 0:
        return np.full(len(fcol), -1, np.int32)
    f2spec = np.full(nf, -1, np.int32)
    for tag, hid in packed.f_codes.items():
        code = spec.f_codes.get(tag)
        if code is not None:
            f2spec[hid] = code
    return np.where((fcol >= 0) & (fcol < nf),
                    f2spec[np.clip(fcol, 0, nf - 1)],
                    np.int32(-1)).astype(np.int32, copy=False)
